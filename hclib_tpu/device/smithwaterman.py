"""Smith-Waterman wavefront inside the megakernel.

Tile tasks on the same 2D DDF grid as the host model (reference:
test/smithwaterman/smith_waterman.cpp:77-180), with the tile computation
re-designed for the VPU instead of translated from the scalar DP loop:

- Rows are processed top to bottom; the row recurrence's left-to-right
  dependency H[i,j] = max(0, cand[i,j], H[i,j-1] - G) is solved *exactly* as
  a max-plus prefix scan: H = max(0, cummax(cand + j*G) - j*G), where the
  0-truncation can be applied once at the end because a truncation point
  only ever contributes negative values downstream. cummax is 7 log-step
  shift+max ops over the 128 lanes.
- Inter-tile boundaries travel through dedicated HBM buffers (bottom row,
  right column, corner per tile) instead of overlapping tile reads, keeping
  every DMA aligned. The right column and the per-row left boundary live in
  SMEM so the row loop can read/write per-row scalars without dynamic lane
  indexing in VMEM.

The global best score accumulates in ivalues[0].
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.smithwaterman import GAP, MATCH, MISMATCH
from .descriptor import TaskGraphBuilder
from .megakernel import KernelContext, Megakernel

__all__ = ["device_sw", "make_sw_megakernel"]

T = 128
TILE_FN = 0
NEG = -(1 << 30)  # plain int: a jnp constant here would be captured by the trace


def _cummax_lanes(x):
    """Inclusive running max along the 128 lanes of a (1, T) vector."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    for sh in (1, 2, 4, 8, 16, 32, 64):
        shifted = pltpu.roll(x, sh, axis=1)
        shifted = jnp.where(lane >= sh, shifted, NEG)
        x = jnp.maximum(x, shifted)
    return x


def _sw_tile_kernel(ctx: KernelContext) -> None:
    ti, tj = ctx.arg(0), ctx.arg(1)
    aseq, bseq = ctx.data["aseq"], ctx.data["bseq"]
    bot, right = ctx.data["bot"], ctx.data["right"]
    htiles = ctx.data["htiles"]
    vh = ctx.scratch["vh"]  # (T, T) VMEM: this tile's H
    vtop = ctx.scratch["vtop"]  # (1, T) VMEM: incoming top boundary
    vb = ctx.scratch["vb"]  # (1, T) VMEM: b chars for this column tile
    a_sm = ctx.scratch["a_sm"]  # (1, T) SMEM: a chars (per-row scalars)
    left_sm = ctx.scratch["left_sm"]  # (1, T) SMEM: incoming left boundary
    rout_sm = ctx.scratch["rout_sm"]  # (1, T) SMEM: outgoing right column
    corner_sm = ctx.scratch["corner_sm"]  # (1, T) SMEM; corner at lane T-1
    sems = ctx.scratch["sems"]

    def dma(src, dst, s):
        cp = pltpu.make_async_copy(src, dst, s)
        cp.start()
        cp.wait()

    dma(aseq.at[ti], a_sm, sems.at[0])
    dma(bseq.at[tj], vb, sems.at[1])

    @pl.when(ti > 0)
    def _():
        dma(bot.at[ti - 1, tj], vtop, sems.at[0])

    @pl.when(ti == 0)
    def _():
        vtop[:] = jnp.zeros((1, T), jnp.int32)

    @pl.when(tj > 0)
    def _():
        dma(right.at[ti, tj - 1], left_sm, sems.at[1])

    @pl.when(tj == 0)
    def _():
        # SMEM only takes scalar stores - zero it with a scalar loop.
        def z(i, _):
            left_sm[0, i] = 0
            return 0

        jax.lax.fori_loop(0, T, z, 0)

    # The diagonal corner H[(ti-1,tj-1)][T-1,T-1] is lane T-1 of that
    # tile's right column - no separate (1,1) buffer (DMA lane alignment).
    @pl.when((ti > 0) & (tj > 0))
    def _():
        dma(right.at[ti - 1, tj - 1], corner_sm, sems.at[2])

    @pl.when((ti == 0) | (tj == 0))
    def _():
        corner_sm[0, T - 1] = 0

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    bvec = vb[:]

    def row(i, hprev):
        ai = a_sm[0, i]
        # H[i-1, j0-1]: the left boundary one row up (corner for row 0).
        im1 = jnp.maximum(i - 1, 0)
        prev_left = jnp.where(i == 0, corner_sm[0, T - 1], left_sm[0, im1])
        sub = jnp.where(bvec == ai, jnp.int32(MATCH), jnp.int32(MISMATCH))
        diag = pltpu.roll(hprev, 1, axis=1)
        diag = jnp.where(lane == 0, prev_left, diag)
        cand = jnp.maximum(diag + sub, hprev - GAP)
        # This row's left boundary enters as an extra candidate at lane 0.
        cand = jnp.maximum(
            cand, jnp.where(lane == 0, left_sm[0, i] - GAP, NEG)
        )
        scan = _cummax_lanes(cand + lane * GAP) - lane * GAP
        hrow = jnp.maximum(scan, 0)
        vh[pl.ds(i, 1), :] = hrow
        rout_sm[0, i] = hrow[0, T - 1]
        return hrow

    hlast = jax.lax.fori_loop(0, T, row, vtop[:])

    # Publish boundaries + tile, update the global best score.
    vtop[:] = hlast
    dma(vtop, bot.at[ti, tj], sems.at[0])
    dma(rout_sm, right.at[ti, tj], sems.at[1])
    dma(vh, htiles.at[ti, tj], sems.at[3])
    tile_max = jnp.max(vh[:])
    best = ctx.value(0)
    ctx.set_value(0, jnp.maximum(best, tile_max))


def make_sw_megakernel(nt_i: int, nt_j: int, interpret: Optional[bool] = None) -> Megakernel:
    i32 = jnp.int32
    return Megakernel(
        kernels=[("sw_tile", _sw_tile_kernel)],
        data_specs={
            "aseq": jax.ShapeDtypeStruct((nt_i, 1, T), i32),
            "bseq": jax.ShapeDtypeStruct((nt_j, 1, T), i32),
            "bot": jax.ShapeDtypeStruct((nt_i, nt_j, 1, T), i32),
            "right": jax.ShapeDtypeStruct((nt_i, nt_j, 1, T), i32),
            "htiles": jax.ShapeDtypeStruct((nt_i, nt_j, T, T), i32),
        },
        scratch_specs={
            "vh": pltpu.VMEM((T, T), i32),
            "vtop": pltpu.VMEM((1, T), i32),
            "vb": pltpu.VMEM((1, T), i32),
            "a_sm": pltpu.SMEM((1, T), i32),
            "left_sm": pltpu.SMEM((1, T), i32),
            "rout_sm": pltpu.SMEM((1, T), i32),
            "corner_sm": pltpu.SMEM((1, T), i32),
            "sems": pltpu.SemaphoreType.DMA((4,)),
        },
        capacity=max(64, nt_i * nt_j),
        num_values=8,
        succ_capacity=max(64, 3 * nt_i * nt_j),
        interpret=interpret,
    )


def device_sw(
    a: np.ndarray,
    b: np.ndarray,
    interpret: Optional[bool] = None,
    mk: Optional[Megakernel] = None,
) -> Tuple[int, np.ndarray, dict]:
    """Run tiled SW on-device; returns (best_score, H[1:, 1:], info).

    Sequence lengths must be multiples of the 128 tile edge.
    """
    n, m = len(a), len(b)
    if n % T or m % T:
        raise ValueError(f"sequence lengths must be multiples of {T}")
    nt_i, nt_j = n // T, m // T
    if mk is None:
        mk = make_sw_megakernel(nt_i, nt_j, interpret)
    builder = TaskGraphBuilder()
    ids = {}
    for ti in range(nt_i):
        for tj in range(nt_j):
            deps = [
                ids[key]
                for key in ((ti - 1, tj), (ti, tj - 1), (ti - 1, tj - 1))
                if key in ids
            ]
            ids[(ti, tj)] = builder.add(TILE_FN, args=[ti, tj], deps=deps)
    i32 = np.int32
    data = {
        "aseq": np.asarray(a, i32).reshape(nt_i, 1, T),
        "bseq": np.asarray(b, i32).reshape(nt_j, 1, T),
        "bot": np.zeros((nt_i, nt_j, 1, T), i32),
        "right": np.zeros((nt_i, nt_j, 1, T), i32),
        "htiles": np.zeros((nt_i, nt_j, T, T), i32),
    }
    t0 = time.perf_counter()
    ivalues, out, info = mk.run(builder, data=data)
    dt = time.perf_counter() - t0
    h = np.asarray(out["htiles"]).swapaxes(1, 2).reshape(n, m)
    info = dict(info)
    info["seconds"] = dt
    info["cells_per_sec"] = n * m / dt
    return int(ivalues[0]), h, info
