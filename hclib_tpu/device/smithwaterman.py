"""Smith-Waterman wavefront inside the megakernel.

Tile tasks on the same 2D DDF grid as the host model (reference:
test/smithwaterman/smith_waterman.cpp:77-180), with the tile computation
re-designed for the VPU instead of translated from the scalar DP loop:

- Rows are processed top to bottom; the row recurrence's left-to-right
  dependency H[i,j] = max(0, cand[i,j], H[i,j-1] - G) is solved *exactly* as
  a max-plus prefix scan: H = max(0, cummax(cand + j*G) - j*G), where the
  0-truncation can be applied once at the end because a truncation point
  only ever contributes negative values downstream. cummax is 7 log-step
  shift+max ops over the 128 lanes.
- Inter-tile boundaries travel through dedicated HBM buffers (bottom row,
  right column, corner per tile) instead of overlapping tile reads, keeping
  every DMA aligned. The right column and the per-row left boundary live in
  SMEM so the row loop can read/write per-row scalars without dynamic lane
  indexing in VMEM.

The global best score accumulates in ivalues[0].
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.smithwaterman import GAP, MATCH, MISMATCH
from .descriptor import TaskGraphBuilder
from .megakernel import KernelContext, Megakernel

__all__ = [
    "device_sw", "make_sw_megakernel", "device_sw_wave",
    "make_sw_wave_megakernel", "build_sw_wave_graph", "sw_wave_buffers",
]

T = 128
TILE_FN = 0
NEG = -(1 << 30)  # plain int: a jnp constant here would be captured by the trace


def _cummax_lanes(x):
    """Inclusive running max along the 128 lanes of an (R, T) plane (each
    sublane row scans independently)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    for sh in (1, 2, 4, 8, 16, 32, 64):
        shifted = pltpu.roll(x, sh, axis=1)
        shifted = jnp.where(lane >= sh, shifted, NEG)
        x = jnp.maximum(x, shifted)
    return x


def _sw_tile_kernel(ctx: KernelContext, with_h: bool = True) -> None:
    ti, tj = ctx.arg(0), ctx.arg(1)
    aseq, bseq = ctx.data["aseq"], ctx.data["bseq"]
    bot, right = ctx.data["bot"], ctx.data["right"]
    htiles = ctx.data["htiles"] if with_h else None
    vh = ctx.scratch["vh"] if with_h else None  # (T, T) VMEM: this tile's H
    vtop = ctx.scratch["vtop"]  # (1, T) VMEM: incoming top boundary
    vb = ctx.scratch["vb"]  # (1, T) VMEM: b chars for this column tile
    a_sm = ctx.scratch["a_sm"]  # (1, T) SMEM: a chars (per-row scalars)
    left_sm = ctx.scratch["left_sm"]  # (1, T) SMEM: incoming left boundary
    rout_sm = ctx.scratch["rout_sm"]  # (1, T) SMEM: outgoing right column
    corner_sm = ctx.scratch["corner_sm"]  # (1, T) SMEM; corner at lane T-1
    sems = ctx.scratch["sems"]

    def dma(src, dst, s):
        cp = pltpu.make_async_copy(src, dst, s)
        cp.start()
        cp.wait()

    dma(aseq.at[ti], a_sm, sems.at[0])
    dma(bseq.at[tj], vb, sems.at[1])

    @pl.when(ti > 0)
    def _():
        dma(bot.at[ti - 1, tj], vtop, sems.at[0])

    @pl.when(ti == 0)
    def _():
        vtop[:] = jnp.zeros((1, T), jnp.int32)

    @pl.when(tj > 0)
    def _():
        dma(right.at[ti, tj - 1], left_sm, sems.at[1])

    @pl.when(tj == 0)
    def _():
        # SMEM only takes scalar stores - zero it with a scalar loop.
        def z(i, _):
            left_sm[0, i] = 0
            return 0

        jax.lax.fori_loop(0, T, z, 0)

    # The diagonal corner H[(ti-1,tj-1)][T-1,T-1] is lane T-1 of that
    # tile's right column - no separate (1,1) buffer (DMA lane alignment).
    @pl.when((ti > 0) & (tj > 0))
    def _():
        dma(right.at[ti - 1, tj - 1], corner_sm, sems.at[2])

    @pl.when((ti == 0) | (tj == 0))
    def _():
        corner_sm[0, T - 1] = 0

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    bvec = vb[:]

    def row(i, carry):
        hprev = carry[0]
        ai = a_sm[0, i]
        # H[i-1, j0-1]: the left boundary one row up (corner for row 0).
        im1 = jnp.maximum(i - 1, 0)
        prev_left = jnp.where(i == 0, corner_sm[0, T - 1], left_sm[0, im1])
        sub = jnp.where(bvec == ai, jnp.int32(MATCH), jnp.int32(MISMATCH))
        diag = pltpu.roll(hprev, 1, axis=1)
        diag = jnp.where(lane == 0, prev_left, diag)
        cand = jnp.maximum(diag + sub, hprev - GAP)
        # This row's left boundary enters as an extra candidate at lane 0.
        cand = jnp.maximum(
            cand, jnp.where(lane == 0, left_sm[0, i] - GAP, NEG)
        )
        scan = _cummax_lanes(cand + lane * GAP) - lane * GAP
        hrow = jnp.maximum(scan, 0)
        if with_h:
            vh[pl.ds(i, 1), :] = hrow
        rout_sm[0, i] = hrow[0, T - 1]
        return hrow, jnp.maximum(carry[1], hrow)

    hlast, hmax = jax.lax.fori_loop(
        0, T, lambda i, c: row(i, c), (vtop[:], jnp.zeros((1, T), jnp.int32))
    )

    # Publish boundaries + tile, update the global best score.
    vtop[:] = hlast
    dma(vtop, bot.at[ti, tj], sems.at[0])
    dma(rout_sm, right.at[ti, tj], sems.at[1])
    if with_h:
        dma(vh, htiles.at[ti, tj], sems.at[3])
    tile_max = jnp.max(hmax)
    best = ctx.value(0)
    ctx.set_value(0, jnp.maximum(best, tile_max))


WAVE_R = 8  # tiles batched per wave task (VPU sublanes)
WAVE_FN = 0


def _sw_wave_kernel(ctx: KernelContext, with_h: bool = True) -> None:
    """A *wave task*: up to WAVE_R tiles of one anti-diagonal processed as
    stacked (R, T) VPU planes - the dep-bearing wavefront riding the
    megakernel's batch-dispatch idea (VERDICT r3 #4's alternative
    criterion). Where the tile kernel sweeps one (1, T) row per VPU step,
    this sweeps the SAME row index of R tiles at once: sub/diag/cummax all
    become (R, T) plane ops, so the vector unit runs ~R tiles for one
    tile's instruction count. Dependencies stay REAL: wave chunks are
    descriptor tasks whose dep counters encode the anti-diagonal order
    (chunk of wave w waits on every chunk of wave w-1), exactly the
    reference's wavefront DAG (test/smithwaterman/smith_waterman.cpp:
    77-180) regrouped for the hardware.

    args: [w, lo, count] - tiles (ti, w - ti) for ti in [lo, lo+count).
    """
    w, lo, count = ctx.arg(0), ctx.arg(1), ctx.arg(2)
    aseq, bseq = ctx.data["aseq"], ctx.data["bseq"]
    bot, right = ctx.data["bot"], ctx.data["right"]
    htiles = ctx.data["htiles"] if with_h else None
    R = WAVE_R
    va = ctx.scratch["va"]  # (R, T) a chars per slot
    vb = ctx.scratch["vb"]  # (R, T) b chars per slot
    vtop = ctx.scratch["vtop"]  # (R, T) incoming top boundaries
    vleft = ctx.scratch["vleft"]  # (R, T) incoming left boundaries
    vcorn = ctx.scratch["vcorn"]  # (R, T) incoming corner rows
    vh = ctx.scratch["vwh"] if with_h else None  # (R, T, T) the R tiles' H
    sems = ctx.scratch["sems"]

    def dma(src, dst, s):
        cp = pltpu.make_async_copy(src, dst, s)
        cp.start()
        cp.wait()

    zrow = jnp.zeros((1, T), jnp.int32)
    for s in range(R):  # static slots
        ti = lo + s
        tj = w - ti
        live = s < count

        @pl.when(live)
        def _(s=s, ti=ti, tj=tj):
            dma(aseq.at[ti], va.at[pl.ds(s, 1)], sems.at[0])
            dma(bseq.at[tj], vb.at[pl.ds(s, 1)], sems.at[1])

            @pl.when(ti > 0)
            def _():
                dma(bot.at[ti - 1, tj], vtop.at[pl.ds(s, 1)], sems.at[2])

            @pl.when(ti == 0)
            def _():
                vtop[pl.ds(s, 1), :] = zrow

            @pl.when(tj > 0)
            def _():
                dma(right.at[ti, tj - 1], vleft.at[pl.ds(s, 1)], sems.at[3])

            @pl.when(tj == 0)
            def _():
                vleft[pl.ds(s, 1), :] = zrow

            @pl.when((ti > 0) & (tj > 0))
            def _():
                dma(
                    right.at[ti - 1, tj - 1], vcorn.at[pl.ds(s, 1)],
                    sems.at[0],
                )

            @pl.when((ti == 0) | (tj == 0))
            def _():
                vcorn[pl.ds(s, 1), :] = zrow

        @pl.when(jnp.logical_not(live))
        def _(s=s):
            # Dead slots sweep zeros (harmless, keeps the planes uniform).
            vtop[pl.ds(s, 1), :] = zrow
            vleft[pl.ds(s, 1), :] = zrow
            vcorn[pl.ds(s, 1), :] = zrow
            va[pl.ds(s, 1), :] = zrow
            vb[pl.ds(s, 1), :] = zrow - 1  # never matches a real char

    lane = jax.lax.broadcasted_iota(jnp.int32, (R, T), 1)
    bplane = vb[:]
    aplane = va[:]
    leftp = vleft[:]
    corner = vcorn[:][:, T - 1 :]  # (R, 1)

    def col(plane, i):
        """Column i of an (R, T) plane as (R, 1): mask + lane-reduce
        (Mosaic has no dynamic_slice on values; this is 2 plane ops)."""
        return jnp.sum(
            jnp.where(lane == i, plane, 0), axis=1, keepdims=True
        )

    def row(i, carry):
        hprev, rout, _mpl = carry
        achar = col(aplane, i)
        prev_left = jnp.where(i == 0, corner, col(leftp, i - 1))
        this_left = col(leftp, i)
        sub = jnp.where(
            bplane == achar, jnp.int32(MATCH), jnp.int32(MISMATCH)
        )
        diag = pltpu.roll(hprev, 1, axis=1)
        diag = jnp.where(lane == 0, prev_left, diag)
        cand = jnp.maximum(diag + sub, hprev - GAP)
        cand = jnp.maximum(cand, jnp.where(lane == 0, this_left - GAP, NEG))
        scan = _cummax_lanes(cand + lane * GAP) - lane * GAP
        hrow = jnp.maximum(scan, 0)
        if with_h:
            vh[:, pl.ds(i, 1), :] = hrow[:, None, :]
        # Accumulate the right column (lane T-1 of each row) into column i
        # of rout - pure plane ops, no scalar extracts in the hot loop.
        rcol = hrow[:, T - 1 :]
        rout = jnp.where(lane == i, rcol, rout)
        mplane = jnp.maximum(carry[2], hrow)
        return hrow, rout, mplane

    zero_rt = jnp.zeros((R, T), jnp.int32)
    hlast, rout, mplane = jax.lax.fori_loop(
        0, T, row, (vtop[:], zero_rt, zero_rt)
    )
    vtop[:] = hlast  # reuse as staging for the bottom-row stores
    vleft[:] = rout  # staging for the right-column stores
    vcorn[:] = mplane  # staging: per-slot running max planes

    for s in range(R):
        ti = lo + s
        tj = w - ti

        @pl.when(s < count)
        def _(s=s, ti=ti, tj=tj):
            dma(vtop.at[pl.ds(s, 1)], bot.at[ti, tj], sems.at[0])
            dma(vleft.at[pl.ds(s, 1)], right.at[ti, tj], sems.at[1])
            if with_h:
                dma(vh.at[s], htiles.at[ti, tj], sems.at[2])
            m = jnp.max(vcorn[s])
            ctx.set_value(0, jnp.maximum(ctx.value(0), m))

    # Each wave task accounts for `count` tiles (itself + count-1 extra),
    # so 'executed' counts tiles across tiers, as the vector tier does.
    ctx.add_executed(count - 1)


def make_sw_wave_megakernel(
    nt_i: int, nt_j: int, interpret: Optional[bool] = None,
    with_h: bool = True,
) -> Megakernel:
    import functools as _ft

    i32 = jnp.int32
    nwaves = nt_i + nt_j - 1
    chunks = [
        -(-min(w + 1, nt_i, nt_j, nt_i + nt_j - 1 - w) // WAVE_R)
        for w in range(nwaves)
    ]
    ntasks = sum(chunks)
    # Exact CSR demand: every wave-w chunk lists ALL wave-(w+1) chunks as
    # successors (2 ride inline, the rest spill to CSR) - quadratic in
    # chunks-per-diagonal, so a heuristic multiple of ntasks under-counts
    # on large grids.
    csr_words = sum(
        chunks[w] * max(0, chunks[w + 1] - 2) for w in range(nwaves - 1)
    )
    data_specs = {
        "aseq": jax.ShapeDtypeStruct((nt_i, 1, T), i32),
        "bseq": jax.ShapeDtypeStruct((nt_j, 1, T), i32),
        "bot": jax.ShapeDtypeStruct((nt_i, nt_j, 1, T), i32),
        "right": jax.ShapeDtypeStruct((nt_i, nt_j, 1, T), i32),
    }
    scratch = {
        "va": pltpu.VMEM((WAVE_R, T), i32),
        "vb": pltpu.VMEM((WAVE_R, T), i32),
        "vtop": pltpu.VMEM((WAVE_R, T), i32),
        "vleft": pltpu.VMEM((WAVE_R, T), i32),
        "vcorn": pltpu.VMEM((WAVE_R, T), i32),
        "sems": pltpu.SemaphoreType.DMA((4,)),
    }
    if with_h:
        data_specs["htiles"] = jax.ShapeDtypeStruct((nt_i, nt_j, T, T), i32)
        scratch["vwh"] = pltpu.VMEM((WAVE_R, T, T), i32)
    return Megakernel(
        kernels=[("sw_wave", _ft.partial(_sw_wave_kernel, with_h=with_h))],
        data_specs=data_specs,
        scratch_specs=scratch,
        capacity=max(64, ntasks),
        num_values=8,
        succ_capacity=max(64, csr_words),
        interpret=interpret,
    )


def build_sw_wave_graph(nt_i: int, nt_j: int) -> TaskGraphBuilder:
    """Wave-chunk task DAG: up to WAVE_R tiles of one anti-diagonal per
    task, consecutive anti-diagonals chained by dependencies (shared by
    device_sw_wave and the bench so both stage the SAME graph)."""
    builder = TaskGraphBuilder()
    prev_wave: list = []
    for w in range(nt_i + nt_j - 1):
        lo = max(0, w - (nt_j - 1))
        hi = min(nt_i - 1, w)
        this_wave = []
        for base in range(lo, hi + 1, WAVE_R):
            cnt = min(WAVE_R, hi + 1 - base)
            this_wave.append(
                builder.add(WAVE_FN, args=[w, base, cnt], deps=prev_wave)
            )
        prev_wave = this_wave
    return builder


def sw_wave_buffers(a: np.ndarray, b: np.ndarray) -> dict:
    """Host data buffers for the wave engine (without the optional H
    matrix): sequences in row-tile layout + the boundary channels."""
    n, m = len(a), len(b)
    nt_i, nt_j = n // T, m // T
    i32 = np.int32
    return {
        "aseq": np.asarray(a, i32).reshape(nt_i, 1, T),
        "bseq": np.asarray(b, i32).reshape(nt_j, 1, T),
        "bot": np.zeros((nt_i, nt_j, 1, T), i32),
        "right": np.zeros((nt_i, nt_j, 1, T), i32),
    }


def device_sw_wave(
    a: np.ndarray,
    b: np.ndarray,
    interpret: Optional[bool] = None,
    mk: Optional[Megakernel] = None,
    with_h: bool = True,
) -> Tuple[int, Optional[np.ndarray], dict]:
    """Tiled SW where each task is a WAVE CHUNK (up to WAVE_R tiles of one
    anti-diagonal batched over VPU sublanes); dependencies chain
    anti-diagonals. Same results as device_sw, ~WAVE_R x the vector-unit
    utilization once diagonals are wide."""
    n, m = len(a), len(b)
    if n % T or m % T:
        raise ValueError(f"sequence lengths must be multiples of {T}")
    nt_i, nt_j = n // T, m // T
    if mk is None:
        mk = make_sw_wave_megakernel(nt_i, nt_j, interpret, with_h=with_h)
    builder = build_sw_wave_graph(nt_i, nt_j)
    i32 = np.int32
    data = sw_wave_buffers(a, b)
    if "htiles" in mk.data_specs:
        data["htiles"] = np.zeros((nt_i, nt_j, T, T), i32)
    t0 = time.perf_counter()
    ivalues, out, info = mk.run(builder, data=data)
    dt = time.perf_counter() - t0
    h = (
        np.asarray(out["htiles"]).swapaxes(1, 2).reshape(n, m)
        if "htiles" in out
        else None
    )
    info = dict(info)
    info["seconds"] = dt
    info["cells_per_sec"] = n * m / dt
    return int(ivalues[0]), h, info


def make_sw_megakernel(
    nt_i: int, nt_j: int, interpret: Optional[bool] = None,
    with_h: bool = True,
) -> Megakernel:
    import functools as _ft

    i32 = jnp.int32
    data_specs = {
        "aseq": jax.ShapeDtypeStruct((nt_i, 1, T), i32),
        "bseq": jax.ShapeDtypeStruct((nt_j, 1, T), i32),
        "bot": jax.ShapeDtypeStruct((nt_i, nt_j, 1, T), i32),
        "right": jax.ShapeDtypeStruct((nt_i, nt_j, 1, T), i32),
    }
    scratch = {
        "vtop": pltpu.VMEM((1, T), i32),
        "vb": pltpu.VMEM((1, T), i32),
        "a_sm": pltpu.SMEM((1, T), i32),
        "left_sm": pltpu.SMEM((1, T), i32),
        "rout_sm": pltpu.SMEM((1, T), i32),
        "corner_sm": pltpu.SMEM((1, T), i32),
        "sems": pltpu.SemaphoreType.DMA((4,)),
    }
    if with_h:
        data_specs["htiles"] = jax.ShapeDtypeStruct((nt_i, nt_j, T, T), i32)
        scratch["vh"] = pltpu.VMEM((T, T), i32)
    return Megakernel(
        kernels=[("sw_tile", _ft.partial(_sw_tile_kernel, with_h=with_h))],
        data_specs=data_specs,
        scratch_specs=scratch,
        capacity=max(64, nt_i * nt_j),
        num_values=8,
        succ_capacity=max(64, 3 * nt_i * nt_j),
        interpret=interpret,
    )


def device_sw(
    a: np.ndarray,
    b: np.ndarray,
    interpret: Optional[bool] = None,
    mk: Optional[Megakernel] = None,
    with_h: bool = True,
) -> Tuple[int, Optional[np.ndarray], dict]:
    """Run tiled SW on-device; returns (best_score, H[1:, 1:], info).

    Sequence lengths must be multiples of the 128 tile edge.
    """
    n, m = len(a), len(b)
    if n % T or m % T:
        raise ValueError(f"sequence lengths must be multiples of {T}")
    nt_i, nt_j = n // T, m // T
    if mk is None:
        mk = make_sw_megakernel(nt_i, nt_j, interpret, with_h=with_h)
    builder = TaskGraphBuilder()
    ids = {}
    for ti in range(nt_i):
        for tj in range(nt_j):
            deps = [
                ids[key]
                for key in ((ti - 1, tj), (ti, tj - 1), (ti - 1, tj - 1))
                if key in ids
            ]
            ids[(ti, tj)] = builder.add(TILE_FN, args=[ti, tj], deps=deps)
    i32 = np.int32
    data = {
        "aseq": np.asarray(a, i32).reshape(nt_i, 1, T),
        "bseq": np.asarray(b, i32).reshape(nt_j, 1, T),
        "bot": np.zeros((nt_i, nt_j, 1, T), i32),
        "right": np.zeros((nt_i, nt_j, 1, T), i32),
    }
    if "htiles" in mk.data_specs:
        data["htiles"] = np.zeros((nt_i, nt_j, T, T), i32)
    t0 = time.perf_counter()
    ivalues, out, info = mk.run(builder, data=data)
    dt = time.perf_counter() - t0
    h = (
        np.asarray(out["htiles"]).swapaxes(1, 2).reshape(n, m)
        if "htiles" in out
        else None
    )
    info = dict(info)
    info["seconds"] = dt
    info["cells_per_sec"] = n * m / dt
    return int(ivalues[0]), h, info
