"""The unified resident kernel: ONE per-device scheduler composing work
stealing, one-sided PGAS, active messages, remote atomics/locks, and host
injection - with **general migration of dependency-bearing tasks**.

This is the device-side analogue of the reference's module architecture,
where every module adds locales to a SINGLE scheduler instead of spawning a
private runtime (/root/reference/inc/hclib-module.h:79-97,
src/hclib-runtime.c:294-317). Round 3 shipped three disjoint wrappers
around ``Megakernel`` (ici_steal / pgas_kernel / inject); this module is
their composition: one kernel per device that steals, puts, AMs, waits,
and polls an injection ring in the same round loop. The older wrappers
remain as narrower configurations (see their module docstrings).

**General task migration** (the round-3 gap: only successor-free
whitelisted rows could move). The reference thief takes ANY task out of a
victim's deque - finish scopes, dependency edges, continuations included
(/root/reference/src/hclib-deque.c:75-106, src/hclib-locality-graph.c:
843-888) - because shared memory makes its pointers location-transparent.
On a TPU mesh the links are device-local row/slot indices, so migration is
re-designed as a **home-link protocol**:

- Exporting a ready row WITH successor links keeps the row at home as a
  *proxy* (off the ready ring, still pending, links intact) and ships a
  copy whose F_HOME/F_HROW words name the proxy.
- The copy executes on the thief like any local task; continuations
  spawned there inherit the home-link (``take_continuation`` moves
  F_HOME/F_HROW with the successor words).
- Whoever ends the chain forwards its out-slot value home in a
  **remote-completion active message**; the home device writes the value
  into the proxy's out slot and completes the proxy - firing the real
  successor edges exactly as if the task had run at home.
- Copies migrate ONCE: re-exporting a homed copy would leave an extra
  proxy row on every intermediate device until the completion chain
  unwinds (measured as task-table exhaustion under churny windows), so
  copies are steal-ineligible; load still spreads through the fresh
  tasks migrated work spawns on the thief.
- A migrated kernel's *value-slot arguments* (args that index the local
  ivalues buffer, declared per kernel id in ``migratable_fns``) are
  dereferenced at export - they are final, the row was ready - and
  rehydrated into thief-local slots at install (the closure-capture of
  the reference's AM lambda serialization, modules/openshmem-am).
- Copies write results into a reserved per-row region at the top of the
  value buffer ([num_values - capacity, num_values)), sized/validated at
  run(): the slot is written by the chain-ending task and read by its
  completion hook in the same scheduler step, so the serial per-device
  scheduler makes slot reuse race-free by construction.

**Remote atomics and locks** (round-3 gap #3, matching the reference
SHMEM layer's AMO + promise-chained locks,
/root/reference/modules/openshmem/src/hclib_openshmem.cpp:572-600,
124-134): owner-computes via *builtin* active messages, dispatched by
negative F_FN ids at drain time. The owner applies fetch-add /
compare-swap on its own value slots - the per-device scheduler is serial,
so owner-side application IS the atomicity - and replies with another AM
that deposits the old value and dep-decrements the caller's parked
continuation row. Locks keep a FIFO of (device, row) waiters in the
owner's value slots; RC_GRANT releases the next waiter's row - the
device translation of the reference chaining lock requests through
promises.

**Termination and flow control.** Counting protocol as in
device/pgas_kernel.py (Mattern-style: exit when global pending == 0,
outboxes and injection rings empty, and messages sent == received), but
the per-round stat exchange is re-designed for pod scale (round-3 weak
item #8): instead of ring-allreducing an O(ndev^2) send matrix, each
round runs log2(ndev) paired XOR hops that (1) recursive-double the five
scalar sums, and (2) route the per-destination send counts with the
hypercube XOR all-to-all (slot p of device v ends holding the count from
source v^p) - payload O(ndev + ndev*nchan) words per hop, O(ndev log
ndev) per round. The same hops carry the backlog-equalizing steal
exchange of device/ici_steal.py, so termination, stealing, and message
accounting ride one credited lockstep schedule.

Arrival correctness: every (source, channel) pair has its OWN DMA
semaphore (``am_sems[src]``, ``chan_sems[src, chan]``), and receivers
wait exactly the announced per-source count before reading - closing a
latent aliasing hazard in the shared-semaphore drain of the round-3 PGAS
kernel, where an early next-round arrival from a fast device could
satisfy a wait for a slower device's still-in-flight message.

Meshes: 1D, 2D, or 3D (v4/v5p slices are 3D tori), power-of-two per axis
(TPU slices are pof2 per axis); multi-axis hops decompose into per-axis
transfers exactly as in ici_steal (row-major flattening, low XOR bits =
minor axis, so each hypercube hop flips exactly one mesh coordinate).
Tested on 8-device 1D, 4x2, and 2x2x2 interpret meshes (including under
the Mosaic race detector) and compiled/run on the real 1-device TPU
(self-loop AMs, atomics, locks).

**Placement seeding (forasync device tier, ISSUE 9).** The per-device
ready rings this runner stages are seeded by whatever the caller put in
its builders - ``device.forasync_tier.place_tiles`` maps a tile loop's
flat tiles onto the roster through a JSON placement descriptor or dist
func (runtime/locality.py), so data-driven placement works here exactly
as on the sharded runner (tests/test_forasync_device.py's resident
seeding test). The XOR-hop exchange partner sequence is graph-ordered
too (the PR 9 residual, closed by ISSUE 10): ``run(hop_order=)`` takes
a permutation of the XOR partner deltas - ``runtime.locality.
xor_hop_order`` / ``MeshPlacement.xor_hop_order()`` derive it
near-neighbors-first from the machine graph's ICI distances, like the
sharded runner's ``steal_hop_order`` - validated, compile-cache-keyed,
and graph-absent behavior (bit-position order, minor axis first)
unchanged. Order is free because the fold's per-dimension exchanges
commute; coverage is not, so partial hop lists are refused.
"""

from __future__ import annotations

import functools
import time
import types
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..jaxcompat import shard_map
from .descriptor import (
    DESC_WORDS,
    F_A0,
    F_CSR_N,
    F_CSR_OFF,
    F_DEP,
    F_FN,
    F_HOME,
    F_HROW,
    F_OUT,
    F_SUCC0,
    F_SUCC1,
    F_VMASK,
    NO_TASK,
    NUM_ARGS,
    RING_ROW,
    TEN_EXPIRED,
    TEN_ID,
    TaskGraphBuilder,
)
from ..runtime.resilience import DeviceFaultPlan, StallError
from .tenants import (
    TC_CONSUMED,
    TC_DROPPED,
    TC_EXPIRED,
    TC_INSTALLED,
    TC_PAUSE,
    TC_TAIL,
    TC_WEIGHT,
    build_row,
)
from .megakernel import (
    fault_mix,
    interpret_mode,
    C_EXECUTED,
    LS_WORDS,
    OVF_LOCKQ,
    OVF_OUTBOX,
    OVF_WAITS,
    C_HEAD,
    C_OVERFLOW,
    C_PENDING,
    C_ROUNDS,
    C_TAIL,
    C_VBASE,
    Megakernel,
    TS_WORDS,
    VBLOCK,
)
from .tracebuf import (
    CR_DROPPED,
    CR_DUPED,
    CR_REGENERATED,
    FLT_DEAD_QUARANTINE,
    FLT_DELAY,
    NullTracer,
    TR_ABORT,
    TR_CKPT,
    TR_CREDIT,
    TR_FAULT,
    TR_INJECT,
    TR_QUIESCE,
    TR_TENANT,
    TR_XFER,
    Tracer,
    trace_info,
)

__all__ = [
    "ResidentKernel",
    "decode_fault_stats",
    "pack_inject_rows",
    "RC_COMPLETE",
    "RC_FADD",
    "RC_FADD_R",
    "RC_CSWAP",
    "RC_REPLY",
    "RC_LOCK",
    "RC_UNLOCK",
    "RC_GRANT",
    "lock_block_slots",
]

# Builtin active-message ids (negative F_FN values, dispatched at drain
# time by the receiving scheduler - they never occupy a task row).
RC_COMPLETE = -2  # [proxy_row, value]: forward a migrated task's result home
RC_FADD = -3      # [slot, delta]: fire-and-forget remote fetch-add
RC_FADD_R = -4    # [slot, delta, src, row, rslot]: fetch-add, reply old value
RC_CSWAP = -5     # [slot, expected, new, src, row, rslot]: compare-swap
RC_REPLY = -6     # [row, value, rslot]: deposit value, dep-decrement row
RC_LOCK = -7      # [lbase, src, row, qcap]: acquire or enqueue
RC_UNLOCK = -8    # [lbase, qcap]: release / grant next waiter
RC_GRANT = -9     # [row]: lock granted - dep-decrement the parked row

AMROW = 128  # padded AM wire row (SMEM DMA minor dim wants 128-word units)
# RING_ROW (the padded injection-ring row, 256 words) now lives in
# descriptor.py beside the TEN_* transport-metadata words it carries;
# imported above and re-exported here for existing callers.


def pack_inject_rows(rows: Sequence, R: int, dev: int = 0):
    """Pack one device's ``inject_rows`` specs into its ``(R, RING_ROW)``
    ring image: tuples ``(fn, args[, out[, tenant_lane]])`` or prebuilt
    RING_ROW numpy rows (``tenants.build_row`` + a TEN_ID stamp - the
    transport metadata rides the row, so tenant identity survives the
    checkpoint residue export and reshard's round-robin re-deal).
    Returns ``(ring, n)``."""
    ring = np.zeros((R, RING_ROW), np.int32)
    if len(rows) > R:
        raise ValueError(f"device {dev}: injection ring overflow")
    for i, spec in enumerate(rows):
        if isinstance(spec, np.ndarray):
            ring[i] = np.asarray(spec, np.int32).reshape(RING_ROW)
            continue
        fn, args = spec[0], spec[1]
        out = spec[2] if len(spec) > 2 else 0
        ring[i] = build_row(fn, args, out)
        if len(spec) > 3:
            ring[i, TEN_ID] = int(spec[3])
    return ring, len(rows)


def lock_block_slots(qcap: int) -> int:
    """Value slots a lock block occupies: [held, qlen, head, (dev,row)*qcap].
    Host presets the block to zero at ``lbase`` on the owner device."""
    return 3 + 2 * int(qcap)


# Per-device fault/abort stats row (an extra SMEM output of every run; the
# device-side fault trace - byte-reproducible from a DeviceFaultPlan seed).
FS_DROPPED = 0      # credits I (as granter) dropped
FS_REGEN = 1        # starved-channel waits I skipped (credit regeneration)
FS_DUPED = 2        # duplicate credits I signalled
FS_DELAYED = 3      # hops where my export quota was zeroed (delay fault)
FS_DEAD_ROUND = 4   # round I first quarantined a dead peer (-1: none)
FS_QMASK = 5        # bitmask of peers I consider dead
FS_REHOMED = 6      # rows I exported while dead (queue re-homing)
FS_ABORT_ROUND = 7  # round the folded abort word was observed (-1: none)
FS_STARVED = 8      # ((hop << 8) | granter) + 1 of my starved channel
FS_HB = 9           # my final heartbeat
FS_QUIESCE_ROUND = 10  # round the folded quiesce word was observed (-1)
FS_TEN_EXPIRED = 11 # tenant-tagged ring rows I dropped expired (the
                    # mesh half of deadline admission: the host marks
                    # TEN_EXPIRED on published rows, the poll skips them)
FS_WORDS = 16


def decode_fault_stats(row) -> Dict[str, Any]:
    """Human shape of one device's FS_* stats row."""
    row = [int(x) for x in row]
    st = row[FS_STARVED]
    return {
        "credits_dropped": row[FS_DROPPED],
        "credits_regenerated": row[FS_REGEN],
        "credits_duplicated": row[FS_DUPED],
        "xfers_delayed": row[FS_DELAYED],
        "dead_detected_round": row[FS_DEAD_ROUND],
        "quarantined": [d for d in range(31) if (row[FS_QMASK] >> d) & 1],
        "rehomed_rows": row[FS_REHOMED],
        "abort_round": row[FS_ABORT_ROUND],
        "starved_channel": (
            None if st == 0
            else {"hop": (st - 1) >> 8, "granter": (st - 1) & 0xFF}
        ),
        "heartbeat": row[FS_HB],
        "quiesce_round": row[FS_QUIESCE_ROUND],
        "tenant_expired": row[FS_TEN_EXPIRED],
    }


class ResidentKernel:
    """One resident scheduler per device of a 1D/2D/3D pof2 mesh, composing
    stealing + PGAS + AM/atomics/locks + injection (see module docstring).

    ``migratable_fns``: iterable of kernel-table ids eligible to migrate
    (dependency-bearing rows included, via the home-link protocol), or a
    dict ``{fn_id: (value_arg_index, ...)}`` naming which arg words of
    that kernel are value-slot references to dereference at export.
    ``channels``: as PGASMegakernel - ``{name: (data_buffer, rows)}``.
    ``inject=True`` adds a per-device host injection ring (rows published
    before entry are discovered by the in-kernel poll).

    ``tenants=`` (mesh-wide tenancy, device/tenants.py; needs
    ``inject=True``): an int N, a sequence of TenantSpec/str/dict lane
    specs, None for the ``HCLIB_TPU_MESH_TENANTS`` env spelling, False
    to force off. With lanes enabled every device's injection ring is
    partitioned into per-tenant regions with a per-device ``tctl[T, 8]``
    control block (host-published per entry, echoed back), and the
    in-kernel poll becomes the same weighted-round-robin lane scan the
    single-device stream compiles - at most ``weight`` rows per lane
    per poll, start lane rotating per round, installs bounded by live
    scheduler headroom, host-marked-expired rows dropped counted.
    Admission routes through a :class:`MeshTenantTable`
    (``run(tenant_table=...)``). A ``tenants=None`` build (no env)
    compiles ZERO new device words - no extra inputs, outputs, or
    branches - bit-identical to the pre-tenancy mesh kernel.

    **Device resilience** (ISSUE 2): every run polls a host-writable abort
    word (HBM, one per device) inside the round loop and folds it into the
    termination collective, so ``run(abort=...)`` stops a running mesh
    within one round in lockstep (``info['aborted']``, per-device abort
    round in ``info['fault_stats']``). ``fault_plan`` (a seeded
    ``DeviceFaultPlan``) compiles deterministic fault injection INTO the
    kernel - dropped/duplicated steal credits with timeout + regeneration,
    delayed transfers, and a dead chip with heartbeat detection,
    quarantine, and task re-homing; see the class docstring in
    runtime/resilience.py. Zero-cost when None.
    """

    def __init__(
        self,
        mk: Megakernel,
        mesh: Mesh,
        *,
        steal: bool = True,
        migratable_fns: Union[Iterable[int], Dict[int, Sequence[int]]] = (),
        homed: bool = True,
        channels: Optional[Dict[str, Tuple[str, int]]] = None,
        inject: bool = False,
        window: int = 8,
        scan: Optional[int] = None,
        am_window: int = 8,
        outbox: int = 256,
        max_waits: int = 64,
        ring_capacity: int = 256,
        proxy_cap: Optional[int] = None,
        fault_plan: Optional[DeviceFaultPlan] = None,
        tenants=None,
    ) -> None:
        if len(mesh.axis_names) not in (1, 2, 3):
            raise ValueError(
                "ResidentKernel wants a 1D/2D/3D mesh (TPU slices are at "
                "most 3D tori)"
            )
        dims = tuple(int(d) for d in mesh.devices.shape)
        for d in dims:
            if d & (d - 1):
                raise ValueError(
                    f"mesh axes must be power-of-two, got {dims} (non-pof2 "
                    "1D meshes: use ICIStealMegakernel / PGASMegakernel)"
                )
        if am_window < 2:
            raise ValueError("am_window must be >= 2")
        self.mk = mk
        self.mesh = mesh
        self.axes = tuple(mesh.axis_names)
        self.dims = dims
        self.ndev = int(np.prod(dims))
        self.nh = self.ndev.bit_length() - 1  # log2 hops (0 for 1 device)
        self.steal = bool(steal)
        # homed=False restricts migration to the round-3 semantics (only
        # link-free rows move, whole; no proxies, no result forwarding, no
        # value-slot reservation) - the configuration the legacy
        # ICIStealMegakernel wrapper delegates to.
        self.homed = bool(homed)
        if isinstance(migratable_fns, dict):
            self.migratable: Dict[int, Tuple[int, ...]] = {
                int(f): tuple(int(i) for i in v)
                for f, v in migratable_fns.items()
            }
        else:
            self.migratable = {int(f): () for f in migratable_fns}
        if self.migratable and self.homed:
            # The scheduler must maintain descriptor home-link words on
            # spawn/continuation transfer (plain megakernels skip these
            # scalar writes - see Megakernel.tracks_home).
            mk.tracks_home = True
        # A claimed kernel id outside the table would silently never
        # migrate (the whitelist is a per-kind mask) - refuse
        # unconditionally, verifier on or off.
        bad = [f for f in self.migratable
               if not 0 <= f < len(mk.kernel_names)]
        if bad:
            raise ValueError(
                f"migratable_fns {sorted(bad)} outside the kernel "
                f"table (0..{len(mk.kernel_names) - 1})"
            )
        for f, vargs in self.migratable.items():
            if len(vargs) > VBLOCK:
                raise ValueError(
                    f"kernel {f}: at most {VBLOCK} value args (rehydration "
                    "uses the row's own value block)"
                )
            if vargs and not mk.uses_row_values:
                raise ValueError(
                    "value-arg rehydration needs uses_row_values=True "
                    "(arriving rows rehydrate into their own row block)"
                )
        self.channels: List[Tuple[str, int]] = []
        self.chan_id: Dict[str, int] = {}
        for cname, (bname, rows) in (channels or {}).items():
            if bname not in mk.data_specs:
                raise ValueError(
                    f"channel {cname!r}: no data buffer {bname!r}"
                )
            if rows < 1 or rows > mk.data_specs[bname].shape[0]:
                raise ValueError(f"channel {cname!r}: bad row count {rows}")
            self.chan_id[cname] = len(self.channels)
            self.channels.append((bname, int(rows)))
        self.nchan = max(1, len(self.channels))
        self.inject = bool(inject)
        self.window = int(window)
        self.scan = int(scan) if scan is not None else 2 * self.window
        self.am_window = int(am_window)
        self.outbox = int(outbox)
        self.max_waits = int(max_waits)
        self.ring_capacity = -(-int(ring_capacity) // 8) * 8
        # Mesh-wide tenancy (device/tenants.py): per-tenant ring regions
        # + a per-device tctl WRR control block. Off (the default, and
        # the env-less default) compiles ZERO new device words.
        from .tenants import normalize_mesh_tenants

        specs = normalize_mesh_tenants(tenants)
        if specs is not None and not self.inject:
            raise ValueError(
                "tenants= partitions the injection ring into per-tenant "
                "regions: needs inject=True"
            )
        self.tenant_specs = specs
        self.T = 0 if specs is None else len(specs)
        if self.T:
            self.region_rows = -(-self.ring_capacity // (8 * self.T)) * 8
            # The ring is exactly the concatenation of the lane regions.
            self.ring_capacity = self.T * self.region_rows
        else:
            self.region_rows = 0
        # Outstanding-proxy budget: a homed export pins a proxy row until
        # the migrated SUBTREE completes remotely (its continuation chain
        # sends the completion), so unthrottled migration of dep-bearing
        # work can pin O(migrations) rows for O(subtree) time - measured
        # as task-table exhaustion. Above this many live proxies a device
        # stops exporting dep-bearing rows (link-free rows still move);
        # local execution continues, so this throttles, never deadlocks.
        self.proxy_cap = (
            int(proxy_cap) if proxy_cap is not None
            else max(8, mk.capacity // 4)
        )
        # Migration result slots: one per descriptor row, at the top of the
        # value buffer. The chain-ending task writes its result there and
        # its completion hook reads it in the same scheduler step, so the
        # serial scheduler makes reuse race-free (module docstring).
        self.rbase = (
            mk.num_values - mk.capacity
            if (self.migratable and self.homed)
            else mk.num_values
        )
        if self.rbase <= 0:
            raise ValueError(
                "migration needs num_values > capacity (one result slot "
                "per row is reserved at the top of the value buffer)"
            )
        # Compiled-in fault injection (None = no fault code emitted).
        self.plan = (
            fault_plan
            if fault_plan is not None and fault_plan.enabled()
            else None
        )
        if self.plan is not None:
            if not self.steal:
                raise ValueError(
                    "DeviceFaultPlan faults target the steal exchange "
                    "(credits, dead-chip re-homing): needs steal=True"
                )
            if self.ndev > 31:
                raise ValueError(
                    f"DeviceFaultPlan supports at most 31 devices (the "
                    f"quarantine bitmask is one int32 stats word), got "
                    f"{self.ndev}"
                )
            if self.plan.dead_device is not None and not (
                0 <= self.plan.dead_device < self.ndev
            ):
                raise ValueError(
                    f"dead_device {self.plan.dead_device} out of range for "
                    f"a {self.ndev}-device mesh"
                )
        # Stat-vector layout (exchanged every hop). Words [0, SX_AM) are
        # recursive-doubling SUMS; [SX_AM, S_BL) route by the hypercube
        # XOR all-to-all (slot p ends holding source me^p's count);
        # [S_BL] is the sender's CURRENT backlog, read raw per hop.
        # SF_ABORT/SF_WEDGE fold the per-device abort word and the
        # starved-channel wedge flag, so a local abort (or an unrecoverable
        # dropped credit) exits the WHOLE mesh in lockstep one fold later -
        # a divergent exit would strand partners in the paired exchanges.
        # SF_QUIESCE (checkpoint builds only - the word costs an exchanged
        # stat slot, so a non-checkpoint build compiles none of it) folds
        # the host quiesce word the same way: on observing it every device
        # stops popping (sched quantum -> 0) but KEEPS the exchange rounds
        # - outboxes drain, in-flight AMs land, sent == recv - and the
        # mesh exits in lockstep with nothing on the wire, every device's
        # live scheduler state in its aliased outputs (the clean-cut
        # property a checkpoint needs that an abort does not provide).
        self.SF_PEND = 0
        self.SF_RECV = 1
        self.SF_OUTB = 2
        self.SF_SENT = 3
        self.SF_INJ = 4
        self.SF_ABORT = 5
        self.SF_WEDGE = 6
        self.checkpoint = bool(mk.checkpoint)
        if self.checkpoint:
            self.SF_QUIESCE = 7
            self.SX_AM = 8
        else:
            self.SF_QUIESCE = None
            self.SX_AM = 7
        self.SX_DATA = self.SX_AM + self.ndev
        nxt = self.SX_DATA + self.ndev * self.nchan
        if self.plan is not None:
            # Heartbeat section (dead-chip detection): routed by the same
            # XOR all-to-all - slot p of device v ends holding v^p's
            # heartbeat, so every device observes every peer every round.
            self.SX_HB = nxt
            nxt += self.ndev
        self.S_BL = nxt
        self.S = self.S_BL + 1
        self._jitted: Dict[Any, Any] = {}
        self._pc_stats: Optional[Dict[str, Any]] = None

    def _cache_variant(self, key) -> tuple:
        """Everything this runner compiles into the program beyond the
        Megakernel's own content: the program-cache variant key
        (runtime/progcache.py). ``key`` is the per-run (quantum,
        max_rounds, hop_bits) tuple the L1 dict uses."""
        from ..runtime.progcache import mesh_key

        return (
            "resident", mesh_key(self.mesh), self.steal, self.homed,
            tuple(sorted(self.migratable.items())),
            tuple(self.channels), self.inject, self.window, self.scan,
            self.am_window, self.outbox, self.max_waits,
            self.ring_capacity, self.T, self.region_rows,
            self.proxy_cap, self.plan, self.checkpoint,
        ) + tuple(key)

    def program_cached(
        self, quantum: int = 64, max_rounds: int = 1 << 14,
        hop_order=None,
    ) -> bool:
        """True when the compiled program for a ``run()`` with these
        parameters is already warm - in this instance's own jit table
        or the process-wide program cache (so a resize onto a shape
        ANY kernel of this process ever built reports hot). The read
        ``Autoscaler`` records as ``ScaleEvent.cache_hit``."""
        key = (quantum, max_rounds, self._hop_bits(hop_order))
        if key in self._jitted:
            return True
        from ..runtime.progcache import probe

        return probe(self.mk, self._cache_variant(key))

    # -- mesh addressing (as ici_steal) --

    def _flat_me(self):
        # Row-major flattening over the mesh axes; with pof2 dims the XOR
        # hop bits partition per axis (minor axis = low bits), so every
        # hypercube hop flips exactly one mesh coordinate - the same
        # decomposition for 1D, 2D, and 3D tori.
        f = jax.lax.axis_index(self.axes[0])
        for ax, d in zip(self.axes[1:], self.dims[1:]):
            f = f * d + jax.lax.axis_index(ax)
        return f

    def _did(self, flat):
        if len(self.axes) == 1:
            return flat
        coords = []
        rem = flat
        for d in self.dims[:0:-1]:
            coords.append(rem % d)
            rem = rem // d
        coords.append(rem)
        return tuple(reversed(coords))

    @property
    def _did_type(self):
        return (
            pltpu.DeviceIdType.LOGICAL
            if len(self.axes) == 1
            else pltpu.DeviceIdType.MESH
        )

    # -- the kernel --

    def _hop_bits(self, hop_order) -> Tuple[int, ...]:
        """Normalize a ``hop_order`` (XOR partner deltas, e.g. from
        ``runtime.locality.xor_hop_order`` / a placement descriptor's
        ``xor_hop_order()``) into the bit-index sequence the exchange
        loop iterates. None = the default bit-position order (minor axis
        first) - graph-absent behavior unchanged. The fold needs every
        hypercube dimension each round (recursive-doubling sums and the
        XOR all-to-all are products of commuting per-dimension
        exchanges, so ORDER is free but coverage is not): anything short
        of a full permutation of the power-of-two deltas is refused."""
        if hop_order is None:
            return tuple(range(self.nh))
        deltas = [int(d) for d in hop_order]
        if sorted(deltas) != [1 << k for k in range(self.nh)]:
            raise ValueError(
                f"hop_order must be a permutation of the XOR deltas "
                f"{[1 << k for k in range(self.nh)]} (every hypercube "
                f"dimension exactly once), got {deltas}"
            )
        return tuple(d.bit_length() - 1 for d in deltas)

    def _kernel(
        self, quantum: int, max_rounds: int, trace, hop_bits, *refs
    ) -> None:
        # ``trace`` is captured at _build time (pallas traces lazily;
        # reading mk.trace here could disagree with the built out tree).
        mk = self.mk
        ndata = len(mk.data_specs)
        nbatch = len(mk.batch_specs)
        ntrace = 1 if trace is not None else 0
        nten = 1 if self.T else 0
        # + abort word (last); tenant builds add the per-device tctl
        # block between ictl and it.
        n_in = 7 + ndata + (2 if self.inject else 0) + nten
        in_refs = refs[:n_in]
        # + (tenant builds) the tctl echo after the ctl echo, + (batch-
        # routed builds) the per-device tstats row, + fstats, then
        # (checkpoint builds only) the exported wait table - the lifted
        # scratch limit: quiesce with pending host-declared waits now
        # exports them instead of refusing - then the optional
        # flight-recorder ring (always last).
        n_out = (
            5 + ndata + (1 if self.inject else 0) + nten
            + (1 if nbatch else 0)
            + (1 if self.checkpoint else 0) + ntrace
        )
        out_refs = refs[n_in : n_in + n_out]
        rest = refs[n_in + n_out :]
        nscratch = len(mk.scratch_specs)
        scratch_refs = rest[:nscratch]
        tail = list(rest[nscratch:])

        def take(n):
            head, tail[:n] = tail[:n], []
            return head

        nckpt = 1 if self.checkpoint else 0
        nh = self.nh
        (free, vfree, candbuf, sendbuf, statacc, statsnd) = take(6)
        statrcv = take(nh)
        inboxes = take(nh) if self.steal else []
        (
            outq_tgt, outq_desc, obctl, ambuf, inbox, am_sent, am_recv,
            sent_round, data_sent, chan_recv, chan_tot, pstate, wait_tab,
        ) = take(13)
        if self.inject:
            ctlbuf, rowbuf = take(2)
        (ssems, rsems, csems, am_sems, chan_sems) = take(5)
        if self.inject:
            (isem,) = take(1)
        (abuf, asem) = take(2)  # abort-word staging + its DMA semaphore
        if nbatch:
            # Batched same-kind dispatch tier (ISSUE 7): the per-kind lane
            # scratch, re-entrant across sched() entries by the spill
            # discipline - every sched exit (quantum, quiesce hold)
            # spills unrun lane entries to the ready ring's cold end, so
            # the steal export scan, queue re-homing, and checkpoint
            # export below only ever see ring rows.
            (lanes, lstate) = take(2)
        else:
            lanes = lstate = None
        plan = self.plan
        if plan is not None:
            # Fault-layer state (per steal channel k / per peer device):
            # pair_down[k] = last round of the current starvation window,
            # owed[k] = dropped credits not yet compensated by a skipped
            # wait, cbal[k] = live credit balance (signals in - waits
            # done; the exit drain consumes exactly this), hb_seen/
            # hb_round/deadmask = heartbeat detection + quarantine.
            (pair_down, owed, cbal, hb_seen, hb_round, deadmask) = take(6)
        assert not tail, f"{len(tail)} unconsumed scratch refs"

        tasks_in, succ, ready_in, counts_in, ivalues_in = in_refs[:5]
        waits_in = in_refs[5 + ndata]
        if self.inject:
            iring, ictl = in_refs[6 + ndata], in_refs[7 + ndata]
        tctl_in = in_refs[8 + ndata] if nten else None
        abort_in = in_refs[n_in - 1]
        tasks, ready, counts, ivalues = out_refs[:4]
        data = dict(zip(mk.data_specs.keys(), out_refs[4 : 4 + ndata]))
        if self.inject:
            ctl_out = out_refs[4 + ndata]
        # Tenant lane cursors + cumulative counters: host-seeded per
        # entry, mutated in place by the WRR poll, echoed back at exit
        # (right after the ctl echo).
        tctl_out = out_refs[5 + ndata] if nten else None
        # Per-device batched-tier counters (appended after the ctl/tctl
        # echoes): decoded host-side into info['tiers'][d], the mesh
        # occupancy the perf guard and the lane-firing-policy detector
        # watch.
        tstats = (
            out_refs[4 + ndata + (1 if self.inject else 0) + nten]
            if nbatch else None
        )
        fstats = out_refs[n_out - 1 - ntrace - nckpt]
        waits_out = out_refs[n_out - 1 - ntrace] if self.checkpoint else None
        tr = (
            Tracer(out_refs[n_out - 1], trace.capacity)
            if ntrace
            else NullTracer()
        )
        scratch = dict(zip(mk.scratch_specs.keys(), scratch_refs))

        ndev = self.ndev
        nchan = self.nchan
        AMW = self.am_window
        OUTQ = self.outbox
        MAXW = self.max_waits
        W = self.window
        SCAN = self.scan
        cap = mk.capacity
        RBASE = self.rbase
        SF_PEND, SF_RECV, SF_OUTB, SF_SENT, SF_INJ = (
            self.SF_PEND, self.SF_RECV, self.SF_OUTB, self.SF_SENT,
            self.SF_INJ,
        )
        SF_ABORT, SF_WEDGE = self.SF_ABORT, self.SF_WEDGE
        SF_QUIESCE, ckpt = self.SF_QUIESCE, self.checkpoint
        SX_AM, SX_DATA, S_BL, S = self.SX_AM, self.SX_DATA, self.S_BL, self.S
        did_type = self._did_type
        me = self._flat_me()

        # pstate slots
        PS_RECV, PS_NWAIT, PS_SENT, PS_PROXIES = 0, 1, 2, 3
        PS_HB, PS_WEDGE, PS_QUIESCE = 4, 5, 6

        # ---- compiled-in fault predicates (None plan emits nothing) ----

        if plan is not None:
            def _pred(site, millis, exact, r, k, g):
                """Does fault ``site`` fire at (round r, hop k, granter g)?
                Pure in (seed, site, r, k, g): every device of the
                lockstep mesh computes the identical answer, for any
                (k, g) - injector, victim, and bystanders agree."""
                p = jnp.bool_(False)
                if millis > 0:
                    p = fault_mix(plan.seed, site, r, k, g) < millis
                for (rr, kk, gg) in exact:
                    if kk == k:
                        p = p | ((r == jnp.int32(rr)) & (g == jnp.int32(gg)))
                return p

            def pred_drop(r, k, g):
                return _pred(0, plan.drop_millis, plan.drop_credit_at,
                             r, k, g)

            def pred_dup(r, k, g):
                return _pred(1, plan.dup_millis, plan.dup_credit_at,
                             r, k, g)

            def pred_delay(r, k, g):
                return _pred(2, plan.delay_millis, (), r, k, g)

            def is_dead(r):
                """Is THIS device the plan's dead chip at round r?"""
                if plan.dead_device is None:
                    return jnp.bool_(False)
                return (me == jnp.int32(plan.dead_device)) & (
                    r >= jnp.int32(plan.dead_round)
                )

            if plan.drops_credits() and plan.credit_timeout == 0:
                # Regeneration disabled: ANY drop wedges the mesh. Every
                # device evaluates the all-pairs drop schedule, so all
                # skip the row exchanges of the following round in
                # lockstep (a starved writer must never reach its wait)
                # and exit together at the next fold.
                def any_drop(r):
                    p = jnp.bool_(False)
                    for k in range(nh):
                        for g in range(ndev):
                            p = p | pred_drop(r, k, jnp.int32(g))
                    return p

        # ---- outbox / active messages ----

        def op_am(dev, fn, args: Sequence = (), out=0) -> None:
            """Queue a descriptor (or builtin op, fn < 0) for device
            ``dev``'s scheduler; the round loop launches it under the
            per-target inbox window."""
            if len(args) > NUM_ARGS:
                raise ValueError(f"at most {NUM_ARGS} args per AM")
            h = obctl[1]
            ok = h - obctl[0] < OUTQ
            slot = h % OUTQ

            @pl.when(ok)
            def _():
                outq_tgt[slot] = dev
                outq_desc[slot, F_FN] = jnp.int32(fn)
                outq_desc[slot, F_DEP] = 0
                outq_desc[slot, F_SUCC0] = jnp.int32(NO_TASK)
                outq_desc[slot, F_SUCC1] = jnp.int32(NO_TASK)
                outq_desc[slot, F_CSR_OFF] = 0
                outq_desc[slot, F_CSR_N] = 0
                for i in range(NUM_ARGS):
                    outq_desc[slot, F_A0 + i] = (
                        jnp.int32(args[i]) if i < len(args) else 0
                    )
                outq_desc[slot, F_OUT] = jnp.int32(out)
                outq_desc[slot, F_HOME] = jnp.int32(NO_TASK)
                outq_desc[slot, F_HROW] = 0
                outq_desc[slot, F_VMASK] = 0
                obctl[1] = h + 1

            @pl.when(jnp.logical_not(ok))
            def _():
                counts[C_OVERFLOW] = counts[C_OVERFLOW] | OVF_OUTBOX

        def op_put(dev, chan: int, dst_row, src_row) -> None:
            """One-sided channel write (SHMEM put): local completion on
            return; target-side arrival is what wait_until observes."""
            if not isinstance(chan, int):
                raise TypeError("chan must be a static channel id")
            if not (0 <= chan < len(self.channels)):
                raise ValueError(
                    f"channel id {chan} not configured (have "
                    f"{len(self.channels)}): a kernel using ctx.pgas.put "
                    "needs its channel declared in ResidentKernel(channels=)"
                )
            bname, rows = self.channels[chan]
            buf = data[bname]
            rdma = pltpu.make_async_remote_copy(
                src_ref=buf.at[pl.ds(src_row, rows)],
                dst_ref=buf.at[pl.ds(dst_row, rows)],
                send_sem=ssems.at[2],
                # Per-(source, channel) arrival semaphore: slot [me, chan]
                # on the TARGET (symmetric allocation).
                recv_sem=chan_sems.at[me, chan],
                device_id=self._did(dev),
                device_id_type=did_type,
            )
            rdma.start()
            rdma.wait_send()
            data_sent[dev, chan] = data_sent[dev, chan] + 1
            pstate[PS_SENT] = pstate[PS_SENT] + 1

        def op_wait_until(chan, need, row) -> None:
            n = pstate[PS_NWAIT]
            ok = n < MAXW
            nc = jnp.minimum(n, MAXW - 1)

            @pl.when(ok)
            def _():
                wait_tab[nc, 0] = chan
                wait_tab[nc, 1] = need
                wait_tab[nc, 2] = row
                pstate[PS_NWAIT] = n + 1

            @pl.when(jnp.logical_not(ok))
            def _():
                counts[C_OVERFLOW] = counts[C_OVERFLOW] | OVF_WAITS

        def op_count(chan: int):
            return chan_tot[chan]

        def op_fadd(dev, slot, delta) -> None:
            """Fire-and-forget remote fetch-add (owner-computes)."""
            op_am(dev, RC_FADD, (slot, delta))

        def op_fadd_get(dev, slot, delta, row, rslot) -> None:
            """Fetch-add whose OLD value lands in local slot ``rslot`` and
            dep-decrements parked row ``row`` (spawn it with an extra
            dep)."""
            op_am(dev, RC_FADD_R, (slot, delta, me, row, rslot))

        def op_cswap(dev, slot, expected, new, row, rslot) -> None:
            """Remote compare-swap; old value replies to (row, rslot)."""
            # me is the wire's src word: the owner replies to it. Dropping
            # it shifted every later arg (the reply went to device=row,
            # row=rslot, slot=garbage) - caught by the volume stress test.
            op_am(dev, RC_CSWAP, (slot, expected, new, me, row, rslot))

        def op_lock(dev, lbase, row, qcap: int) -> None:
            """Acquire the lock block at ``lbase`` on ``dev``; parked row
            ``row`` (one extra dep) is dep-decremented when granted."""
            op_am(dev, RC_LOCK, (lbase, me, row, qcap))

        def op_unlock(dev, lbase, qcap: int) -> None:
            op_am(dev, RC_UNLOCK, (lbase, qcap))

        def ctx_hook(ctx) -> None:
            ctx.pgas = types.SimpleNamespace(
                put=op_put, am=op_am, wait_until=op_wait_until,
                count=op_count, fadd=op_fadd, fadd_get=op_fadd_get,
                cswap=op_cswap, lock=op_lock, unlock=op_unlock,
                me=me, ndev=ndev, nchan=len(self.channels),
            )

        def complete_hook(idx) -> None:
            """Migrated chains forward their result to the home proxy on
            completion (module docstring: the home-link protocol)."""

            @pl.when(tasks[idx, F_HOME] >= 0)
            def _():
                op_am(
                    tasks[idx, F_HOME],
                    RC_COMPLETE,
                    (tasks[idx, F_HROW], ivalues[tasks[idx, F_OUT]]),
                )

        core = mk._make_core(
            succ, tasks, ready, counts, ivalues, data, scratch, free, vfree,
            tasks_in, ready_in, counts_in, ivalues_in, True, ctx_hook,
            complete_hook if (self.migratable and self.homed) else None,
            value_limit=RBASE,
            lanes=lanes, lstate=lstate, tstats=tstats,
            tracer=tr if tr.enabled else None,
        )

        def dep_dec(row) -> None:
            d = tasks[row, F_DEP] - 1
            tasks[row, F_DEP] = d

            @pl.when(d == 0)
            def _():
                core.push_ready(row)

        # ---- stage ----

        def stage_resident() -> None:
            def z(i, _):
                am_sent[i] = 0
                am_recv[i] = 0
                sent_round[i] = 0
                for c in range(nchan):
                    data_sent[i, c] = 0
                    chan_recv[i, c] = 0
                return 0

            jax.lax.fori_loop(0, ndev, z, 0)
            for c in range(nchan):
                chan_tot[c] = 0
            for i in range(8):
                pstate[i] = 0
            for i in range(FS_WORDS):
                fstats[i] = 0
            fstats[FS_DEAD_ROUND] = -1
            fstats[FS_ABORT_ROUND] = -1
            fstats[FS_QUIESCE_ROUND] = -1
            if plan is not None:
                for k in range(nh):
                    pair_down[k] = -1
                    owed[k] = 0
                    cbal[k] = 0

                def zf(i, _):
                    hb_seen[i] = 0
                    hb_round[i] = 0
                    deadmask[i] = 0
                    return 0

                jax.lax.fori_loop(0, ndev, zf, 0)
            pstate[PS_NWAIT] = waits_in[0, 0]
            obctl[0] = 0
            obctl[1] = 0

            def cw(i, _):
                for w in range(3):
                    wait_tab[i, w] = waits_in[1 + i, w]
                return 0

            jax.lax.fori_loop(0, waits_in[0, 0], cw, 0)

        # ---- import fixups (stolen rows, AM task rows) ----

        has_vargs = any(v for v in self.migratable.values())

        def install_fixed(read_word):
            """Adopt an external row, then apply migration fixups: homed
            rows get a local result slot; dereferenced value args
            rehydrate into the row's own value block."""
            row = core.install_descriptor(read_word)

            @pl.when(tasks[row, F_HOME] >= 0)
            def _():
                tasks[row, F_OUT] = jnp.int32(RBASE) + row

            if has_vargs:
                mask = tasks[row, F_VMASK]
                base = counts[C_VBASE] + row * VBLOCK
                jj = jnp.int32(0)
                for i in range(NUM_ARGS):
                    bit = (mask >> i) & 1

                    @pl.when(bit == 1)
                    def _(i=i, jj=jj):
                        ivalues[base + jj] = tasks[row, F_A0 + i]
                        tasks[row, F_A0 + i] = base + jj

                    jj = jj + bit
                # Cleared HERE (not in spawn): wire copies are the only
                # writers of F_VMASK, so the import path owns its reset.
                tasks[row, F_VMASK] = 0
            return row

        # ---- steal export / import (general migration) ----

        wl = sorted(self.migratable)

        def homed_elig_of(cand):
            """Rows migrate as homed copies when they carry successor
            links or write a DYNAMIC value slot (>= the symmetric host
            region): a dynamic out address is only valid on its home
            device, so the result must forward home rather than land at
            the same index on the thief (where it could alias a live
            block). (Rows that are already migrated copies never reach
            this classification - elig_of's migrate-once term excludes
            F_HOME >= 0 rows from export entirely.)"""
            return (
                (tasks[cand, F_SUCC0] != NO_TASK)
                | (tasks[cand, F_SUCC1] != NO_TASK)
                | (tasks[cand, F_CSR_N] > 0)
                | (tasks[cand, F_OUT] >= counts[C_VBASE])
            )

        def elig_of(cand, allow_homed):
            """``allow_homed`` is SNAPSHOTTED once per export scan: the
            proxy counter moves while classify takes rows, and an
            eligibility that flipped mid-scan would ship fewer rows than
            the announced count (stale sendbuf entries on the wire)."""
            d_fn = tasks[cand, F_FN]
            ok = jnp.bool_(False)
            for f in wl:
                ok = ok | (d_fn == f)
            # Migrate-once: a row that is already a migrated copy (carries
            # a home-link) never re-exports. Re-stealing would work
            # protocol-wise (completions chain through intermediate
            # proxies), but every extra hop leaves ANOTHER proxy row alive
            # until the completion propagates back - measured as
            # task-table exhaustion when churny windows bounce tasks
            # between devices. Bounding chains at length 1 keeps proxy
            # liveness = in-flight migrations, and thieves still rebalance
            # through the fresh tasks migrated work spawns locally.
            ok = ok & (tasks[cand, F_HOME] < 0)
            if not self.homed:
                # Round-3 semantics: only link-free rows may move.
                ok = ok & jnp.logical_not(homed_elig_of(cand))
            else:
                # Proxy budget: dep-bearing rows stop exporting while too
                # many migrated subtrees are outstanding (see proxy_cap).
                ok = ok & (jnp.logical_not(homed_elig_of(cand)) | allow_homed)
            return ok

        def export(quota):
            """Move up to ``quota`` eligible ready rows into sendbuf.
            Rows with successor links (or an existing home-link) export as
            homed copies and leave a proxy; link-free rows move whole."""
            head = counts[C_HEAD]
            backlog = counts[C_TAIL] - head
            Sn = jnp.minimum(backlog, SCAN)

            def copy_cand(j, _):
                candbuf[j] = ready[(head + j) % cap]
                return 0

            jax.lax.fori_loop(0, Sn, copy_cand, 0)

            allow_homed = pstate[PS_PROXIES] < self.proxy_cap

            def count_elig(j, n):
                return n + elig_of(candbuf[j], allow_homed).astype(jnp.int32)

            nelig = jax.lax.fori_loop(0, Sn, count_elig, jnp.int32(0))
            nsend = jnp.minimum(quota, nelig)

            def homed_of(cand):
                if not self.homed:
                    return jnp.bool_(False)  # eligibility already excluded
                return homed_elig_of(cand)

            def classify(j, carry):
                se, kp, nw = carry
                cand = candbuf[j]
                tk = elig_of(cand, allow_homed) & (se < nsend)

                @pl.when(tk)
                def _():
                    for w in range(DESC_WORDS):
                        sendbuf[se, w] = tasks[cand, w]
                    # The wire's value-mask is OWNED BY EXPORT, never
                    # copied from the row: spawn leaves F_VMASK unwritten
                    # (a dead word locally), so a recycled/bump row holds
                    # garbage there - and a garbage mask would make the
                    # importer rehydrate ALL SIX args of the copy,
                    # corrupting its descriptor (observed: FIB(4) arriving
                    # as FIB(<block address>), spawning unbounded trees).
                    sendbuf[se, F_VMASK] = 0
                    links = homed_of(cand)

                    @pl.when(links)
                    def _():
                        # Homed copy: links stay on the proxy; the copy
                        # names us as home. (Copies themselves never
                        # re-export - migrate-once in elig_of - so every
                        # home-link points at the row's origin device.)
                        sendbuf[se, F_SUCC0] = jnp.int32(NO_TASK)
                        sendbuf[se, F_SUCC1] = jnp.int32(NO_TASK)
                        sendbuf[se, F_CSR_OFF] = 0
                        sendbuf[se, F_CSR_N] = 0
                        sendbuf[se, F_HOME] = me
                        sendbuf[se, F_HROW] = cand
                        pstate[PS_PROXIES] = pstate[PS_PROXIES] + 1

                    @pl.when(jnp.logical_not(links))
                    def _():
                        # Whole-row migration: the task now lives on the
                        # target; tombstone + free the home row.
                        tasks[cand, F_DEP] = -1
                        nf = free[0] + 1
                        free[0] = nf
                        free[nf] = cand

                    # Dereference declared value-slot args (final: the
                    # row was ready, all predecessors completed).
                    for f, vargs in self.migratable.items():
                        if not vargs:
                            continue
                        m = 0
                        for i in vargs:
                            m |= 1 << i

                        @pl.when(tasks[cand, F_FN] == f)
                        def _(f=f, vargs=vargs, m=m):
                            for i in vargs:
                                sendbuf[se, F_A0 + i] = ivalues[
                                    tasks[cand, F_A0 + i]
                                ]
                            sendbuf[se, F_VMASK] = m

                @pl.when(jnp.logical_not(tk))
                def _():
                    ready[(head + nsend + kp) % cap] = cand

                # Safe to re-evaluate after the mutation above: homed
                # export leaves tasks[cand] untouched, and whole-row
                # export only tombstones F_DEP, which homed_of never reads.
                whole = tk & jnp.logical_not(homed_of(cand))
                return (
                    se + tk.astype(jnp.int32),
                    kp + (1 - tk.astype(jnp.int32)),
                    nw + whole.astype(jnp.int32),
                )

            _, _, nwhole = jax.lax.fori_loop(
                0, Sn, classify, (jnp.int32(0), jnp.int32(0), jnp.int32(0))
            )
            counts[C_HEAD] = head + nsend
            # Homed exports stay pending at home (the proxy); only
            # whole-row exports hand their pending count to the thief.
            counts[C_PENDING] = counts[C_PENDING] - nwhole
            return nsend

        def import_rows(box):
            n = box[W, 0]

            def one(i, _):
                install_fixed(lambda w: box[i, w])
                return 0

            jax.lax.fori_loop(0, n, one, 0)

        # ---- AM drain machinery ----

        def drain_outbox() -> None:
            """Launch queued AMs under the per-target inbox window (FIFO;
            a capped head entry stalls until next round, preserving
            per-target order)."""

            def zz(i, _):
                sent_round[i] = 0
                return 0

            jax.lax.fori_loop(0, ndev, zz, 0)

            def cond(h):
                more = h < obctl[1]
                t = outq_tgt[h % OUTQ]
                return more & (
                    sent_round[jnp.where(more, t, 0)] < AMW // 2
                )

            def body(h):
                slot_q = h % OUTQ
                t = outq_tgt[slot_q]
                slot = am_sent[t] % AMW
                for w in range(DESC_WORDS):
                    ambuf[w] = outq_desc[slot_q, w]
                rdma = pltpu.make_async_remote_copy(
                    src_ref=ambuf,
                    dst_ref=inbox.at[me, slot],
                    send_sem=ssems.at[3],
                    # Slot [me] on the TARGET: per-source arrivals.
                    recv_sem=am_sems.at[me],
                    device_id=self._did(t),
                    device_id_type=did_type,
                )
                rdma.start()
                rdma.wait_send()
                am_sent[t] = am_sent[t] + 1
                sent_round[t] = sent_round[t] + 1
                pstate[PS_SENT] = pstate[PS_SENT] + 1
                return h + 1

            obctl[0] = jax.lax.while_loop(cond, body, obctl[0])

        def handle_am(s, slot) -> None:
            """Dispatch one landed AM: builtin ops (fn < 0) run inline at
            the receiving scheduler; task descriptors install."""
            fn = inbox[s, slot, F_FN]

            def a(i):
                return inbox[s, slot, F_A0 + i]

            @pl.when(fn >= 0)
            def _():
                install_fixed(lambda w: inbox[s, slot, w])

            @pl.when(fn == RC_COMPLETE)
            def _():
                hrow = a(0)
                ivalues[tasks[hrow, F_OUT]] = a(1)
                core.complete(hrow)
                # The execution was already counted on the thief.
                counts[C_EXECUTED] = counts[C_EXECUTED] - 1
                pstate[PS_PROXIES] = pstate[PS_PROXIES] - 1

            @pl.when(fn == RC_FADD)
            def _():
                ivalues[a(0)] = ivalues[a(0)] + a(1)

            @pl.when(fn == RC_FADD_R)
            def _():
                old = ivalues[a(0)]
                ivalues[a(0)] = old + a(1)
                op_am(a(2), RC_REPLY, (a(3), old, a(4)))

            @pl.when(fn == RC_CSWAP)
            def _():
                old = ivalues[a(0)]
                ivalues[a(0)] = jnp.where(old == a(1), a(2), old)
                op_am(a(3), RC_REPLY, (a(4), old, a(5)))

            @pl.when(fn == RC_REPLY)
            def _():
                ivalues[a(2)] = a(1)
                dep_dec(a(0))

            @pl.when(fn == RC_LOCK)
            def _():
                lbase, src, row, qcap = a(0), a(1), a(2), a(3)
                held = ivalues[lbase]

                @pl.when(held == 0)
                def _():
                    ivalues[lbase] = 1
                    op_am(src, RC_GRANT, (row,))

                @pl.when(held != 0)
                def _():
                    qlen = ivalues[lbase + 1]
                    head_q = ivalues[lbase + 2]
                    okq = qlen < qcap
                    pos = lbase + 3 + 2 * ((head_q + qlen) % qcap)

                    @pl.when(okq)
                    def _():
                        ivalues[pos] = src
                        ivalues[pos + 1] = row
                        ivalues[lbase + 1] = qlen + 1

                    @pl.when(jnp.logical_not(okq))
                    def _():
                        counts[C_OVERFLOW] = counts[C_OVERFLOW] | OVF_LOCKQ

            @pl.when(fn == RC_UNLOCK)
            def _():
                lbase, qcap = a(0), a(1)
                qlen = ivalues[lbase + 1]

                @pl.when(qlen == 0)
                def _():
                    ivalues[lbase] = 0

                @pl.when(qlen > 0)
                def _():
                    head_q = ivalues[lbase + 2]
                    pos = lbase + 3 + 2 * (head_q % qcap)
                    ivalues[lbase + 2] = (head_q + 1) % qcap
                    ivalues[lbase + 1] = qlen - 1
                    # Lock stays held; hand it to the next waiter.
                    op_am(ivalues[pos], RC_GRANT, (ivalues[pos + 1],))

            @pl.when(fn == RC_GRANT)
            def _():
                dep_dec(a(0))

        def drain_receives() -> None:
            """Consume exactly the per-source arrivals the fold announced:
            wait each (source, channel) semaphore down by its announced
            delta BEFORE reading - payloads are never observed partially
            written, and a fast device's next-round message can never
            satisfy a wait for a slower source (per-source semaphores)."""
            me_did = self._did(me)
            for c, (bname, rows) in enumerate(self.channels):
                buf = data[bname]
                for p in range(ndev):
                    src = me ^ p
                    expected = statacc[SX_DATA + p * nchan + c]
                    delta = expected - chan_recv[src, c]
                    waiter = pltpu.make_async_remote_copy(
                        src_ref=buf.at[pl.ds(0, rows)],
                        dst_ref=buf.at[pl.ds(0, rows)],
                        send_sem=ssems.at[2],
                        recv_sem=chan_sems.at[src, c],
                        device_id=me_did,
                        device_id_type=did_type,
                    )

                    def one(i, _):
                        waiter.wait_recv()
                        return 0

                    jax.lax.fori_loop(0, delta, one, 0)
                    chan_recv[src, c] = expected
                    chan_tot[c] = chan_tot[c] + delta
                    pstate[PS_RECV] = pstate[PS_RECV] + delta

            for p in range(ndev):
                src = me ^ p
                expected = statacc[SX_AM + p]
                base = am_recv[src]
                delta = expected - base
                waiter = pltpu.make_async_remote_copy(
                    src_ref=inbox.at[0, 0],
                    dst_ref=inbox.at[0, 0],
                    send_sem=ssems.at[3],
                    recv_sem=am_sems.at[src],
                    device_id=me_did,
                    device_id_type=did_type,
                )

                def wait_one(i, _):
                    waiter.wait_recv()
                    return 0

                jax.lax.fori_loop(0, delta, wait_one, 0)

                def install_one(i, _):
                    handle_am(src, (base + i) % AMW)
                    return 0

                jax.lax.fori_loop(0, delta, install_one, 0)
                am_recv[src] = expected
                pstate[PS_RECV] = pstate[PS_RECV] + delta

        def scan_waits() -> None:
            n = pstate[PS_NWAIT]

            def one(i, kept):
                ch = wait_tab[i, 0]
                need = wait_tab[i, 1]
                row = wait_tab[i, 2]
                fire = chan_tot[ch] >= need

                @pl.when(fire)
                def _():
                    dep_dec(row)

                @pl.when(jnp.logical_not(fire))
                def _():
                    wait_tab[kept, 0] = ch
                    wait_tab[kept, 1] = need
                    wait_tab[kept, 2] = row

                return kept + jnp.where(fire, 0, 1)

            pstate[PS_NWAIT] = jax.lax.fori_loop(0, n, one, jnp.int32(0))

        # ---- injection ring poll (as device/inject.py) ----

        if self.inject:

            def poll(consumed, quiescing=None):
                cp = pltpu.make_async_copy(ictl, ctlbuf, isem.at[0])
                cp.start()
                cp.wait()
                tl = ctlbuf[0]
                if quiescing is not None:
                    # Quiescing round: consume nothing (tl clamps to the
                    # cursor, the chunk loop is immediately done) - the
                    # unread rows are the exported ring residue.
                    tl = jnp.where(quiescing, jnp.minimum(tl, consumed), tl)

                def chunk(c):
                    base = (c // 8) * 8
                    rp = pltpu.make_async_copy(
                        iring.at[pl.ds(base, 8)], rowbuf, isem.at[1]
                    )
                    rp.start()
                    rp.wait()
                    n = jnp.minimum(tl - c, 8 - (c - base))

                    def ins(i, _):
                        # Tenant deadline admission, mesh half: the host
                        # marks TEN_EXPIRED on a published row whose
                        # admission deadline lapsed; the poll drops it
                        # (counted, TR_TENANT names the lane) instead of
                        # installing stale work.
                        slot = c - base + i
                        expired = rowbuf[slot, TEN_EXPIRED] != 0

                        @pl.when(jnp.logical_not(expired))
                        def _():
                            install_fixed(lambda w: rowbuf[slot, w])

                        @pl.when(expired)
                        def _():
                            fstats[FS_TEN_EXPIRED] = (
                                fstats[FS_TEN_EXPIRED] + 1
                            )
                            tr.emit(
                                TR_TENANT, tr.now(),
                                rowbuf[slot, TEN_ID] << 16, 1,
                            )

                        return 0

                    jax.lax.fori_loop(0, n, ins, 0)
                    return c + n

                return jax.lax.while_loop(lambda c: c < tl, chunk, consumed)

        if self.inject and nten:
            T, region = self.T, self.region_rows

            def tpoll(r, quiescing):
                """Mesh half of the tenant front door: the same WRR
                lane scan the single-device stream compiles
                (device/inject.py ``tpoll``), over THIS device's ring
                regions. Per lane visit it installs at most ``weight``
                rows, never more than the scheduler's live
                ``headroom()`` (a full task table turns into ring
                backpressure the host reads off the cursor echo), drops
                rows the host marked expired (counted: FS_TEN_EXPIRED +
                the tctl echo + a TR_TENANT record), and sweeps paused
                lanes. Quiescing rounds freeze the scan entirely -
                published rows stay put and export as the checkpoint's
                per-lane residue."""
                newly = jnp.int32(0)
                for k in range(T):
                    lane = jax.lax.rem(r + k, T)
                    tail = tctl_out[lane, TC_TAIL]
                    cons = tctl_out[lane, TC_CONSUMED]
                    paused = tctl_out[lane, TC_PAUSE] != 0
                    avail = tail - cons
                    weight = tctl_out[lane, TC_WEIGHT]
                    take = jnp.where(
                        paused | quiescing,
                        0,
                        jnp.minimum(
                            jnp.minimum(weight, avail), core.headroom()
                        ),
                    )
                    target = cons + take

                    def chunk(carry, lane=lane, target=target):
                        c, inst, exp = carry
                        base = (c // 8) * 8
                        rp = pltpu.make_async_copy(
                            iring.at[pl.ds(lane * region + base, 8)],
                            rowbuf, isem.at[1],
                        )
                        rp.start()
                        rp.wait()
                        n = jnp.minimum(target - c, 8 - (c - base))

                        def ins(i, ie, c=c, base=base):
                            inst0, exp0 = ie
                            slot = c - base + i
                            expired = rowbuf[slot, TEN_EXPIRED] != 0

                            @pl.when(jnp.logical_not(expired))
                            def _():
                                install_fixed(lambda w: rowbuf[slot, w])

                            one = jnp.int32(1)
                            return (
                                inst0 + jnp.where(expired, 0, one),
                                exp0 + jnp.where(expired, one, 0),
                            )

                        inst, exp = jax.lax.fori_loop(
                            0, n, ins, (inst, exp)
                        )
                        return c + n, inst, exp

                    c, inst, exp = jax.lax.while_loop(
                        lambda cr, target=target: cr[0] < target,
                        chunk,
                        (cons, jnp.int32(0), jnp.int32(0)),
                    )
                    sweep = paused & jnp.logical_not(quiescing)
                    tctl_out[lane, TC_CONSUMED] = jnp.where(
                        sweep, tail, c
                    )
                    tctl_out[lane, TC_DROPPED] = (
                        tctl_out[lane, TC_DROPPED]
                        + jnp.where(sweep, avail, 0)
                    )
                    tctl_out[lane, TC_INSTALLED] = (
                        tctl_out[lane, TC_INSTALLED] + inst
                    )
                    tctl_out[lane, TC_EXPIRED] = (
                        tctl_out[lane, TC_EXPIRED] + exp
                    )
                    fstats[FS_TEN_EXPIRED] = fstats[FS_TEN_EXPIRED] + exp

                    @pl.when((inst > 0) | (exp > 0))
                    def _(lane=lane, inst=inst, exp=exp):
                        tr.emit(
                            TR_TENANT, tr.now(), (lane << 16) | inst, exp
                        )

                    newly = newly + inst
                return newly

            def lane_backlog():
                b = jnp.int32(0)
                for i in range(T):
                    b = b + (
                        tctl_out[i, TC_TAIL] - tctl_out[i, TC_CONSUMED]
                    )
                return b

        # ---- the fold + steal hops ----

        def fold_and_steal(r, inj_backlog, am_dead, local_abort,
                           local_quiesce):
            statacc[SF_PEND] = counts[C_PENDING]
            statacc[SF_RECV] = pstate[PS_RECV]
            statacc[SF_OUTB] = obctl[1] - obctl[0]
            statacc[SF_SENT] = pstate[PS_SENT]
            statacc[SF_INJ] = inj_backlog
            statacc[SF_ABORT] = local_abort.astype(jnp.int32)
            statacc[SF_WEDGE] = pstate[PS_WEDGE]
            if ckpt:
                statacc[SF_QUIESCE] = local_quiesce.astype(jnp.int32)

            def f1(p, _):
                statacc[SX_AM + p] = am_sent[me ^ p]
                for c in range(nchan):
                    statacc[SX_DATA + p * nchan + c] = data_sent[me ^ p, c]
                if plan is not None:
                    statacc[self.SX_HB + p] = pstate[PS_HB]
                return 0

            jax.lax.fori_loop(0, ndev, f1, 0)

            # Exchange order: ``hop_bits`` (default 0..nh-1, minor axis
            # first; a locality graph reorders it near-neighbors-first
            # via run(hop_order=)). Per-hop state (semaphores, inboxes,
            # credit balances, fault predicates) stays indexed by the
            # PHYSICAL bit k, so both endpoints of a pair - and the
            # seeded fault schedule - agree regardless of scan order.
            for k in hop_bits:
                partner = me ^ (1 << k)
                pdev = self._did(partner)

                def cpy(i, _):
                    statsnd[i] = statacc[i]
                    return 0

                jax.lax.fori_loop(0, S, cpy, 0)
                statsnd[S_BL] = counts[C_TAIL] - counts[C_HEAD]

                @pl.when(r > 0)
                def _(k=k):
                    pltpu.semaphore_wait(csems.at[2 * k], 1)

                rdma = pltpu.make_async_remote_copy(
                    src_ref=statsnd, dst_ref=statrcv[k],
                    send_sem=ssems.at[0], recv_sem=rsems.at[2 * k],
                    device_id=pdev, device_id_type=did_type,
                )
                rdma.start()
                rdma.wait()
                for i in range(SX_AM):  # the scalar sums (incl abort/wedge)
                    statacc[i] = statacc[i] + statrcv[k][i]

                def mrg(p, _, k=k):
                    swap = ((p >> k) & 1) == 1

                    @pl.when(swap)
                    def _():
                        statacc[SX_AM + p] = statrcv[k][SX_AM + p]
                        for c in range(nchan):
                            statacc[SX_DATA + p * nchan + c] = statrcv[k][
                                SX_DATA + p * nchan + c
                            ]
                        if plan is not None:
                            statacc[self.SX_HB + p] = statrcv[k][
                                self.SX_HB + p
                            ]

                    return 0

                jax.lax.fori_loop(0, ndev, mrg, 0)
                peer_b = statrcv[k][S_BL]
                pltpu.semaphore_signal(
                    csems.at[2 * k], inc=1, device_id=pdev,
                    device_id_type=did_type,
                )
                if self.steal:
                    myb = counts[C_TAIL] - counts[C_HEAD]
                    # DEMAND-DRIVEN (the reference steals when a worker
                    # runs dry, src/hclib-runtime.c:646-694): export only
                    # to a STARVING partner (ready backlog under one
                    # quantum). Continuous backlog equalization measured
                    # pathological on recursive graphs: ready counts don't
                    # reflect subtree sizes, so busy-busy pairs ping-pong
                    # "surplus" forever, and every bounced dep-bearing row
                    # pins a proxy until its subtree completes remotely -
                    # the table fills with proxies instead of work.
                    starving = peer_b < jnp.int32(min(quantum, W))
                    quota = jnp.where(
                        starving, jnp.clip((myb - peer_b + 1) // 2, 0, W), 0
                    )
                    if plan is None:
                        sendbuf[W, 0] = 0

                        @pl.when(quota > 0)
                        def _():
                            sendbuf[W, 0] = export(quota)

                        @pl.when(sendbuf[W, 0] > 0)
                        def _(partner=partner):
                            tr.emit(
                                TR_XFER, tr.now(), partner, sendbuf[W, 0]
                            )

                        @pl.when(r > 0)
                        def _(k=k):
                            pltpu.semaphore_wait(csems.at[2 * k + 1], 1)

                        rdma2 = pltpu.make_async_remote_copy(
                            src_ref=sendbuf, dst_ref=inboxes[k],
                            send_sem=ssems.at[1], recv_sem=rsems.at[2 * k + 1],
                            device_id=pdev, device_id_type=did_type,
                        )
                        rdma2.start()
                        rdma2.wait()
                        import_rows(inboxes[k])
                        pltpu.semaphore_signal(
                            csems.at[2 * k + 1], inc=1, device_id=pdev,
                            device_id_type=did_type,
                        )
                    else:
                        # ---- faulty row exchange. Granter ids are
                        # ABSOLUTE device ids, so both endpoints (and any
                        # bystander) evaluate identical predicates: my
                        # partner grants my channel's credits, I grant
                        # theirs.
                        drop_mine = pred_drop(r, k, partner)
                        drop_theirs = pred_drop(r, k, me)
                        dup_mine = jnp.logical_not(drop_mine) & pred_dup(
                            r, k, partner
                        )
                        dup_theirs = jnp.logical_not(drop_theirs) & pred_dup(
                            r, k, me
                        )
                        delay_me = pred_delay(r, k, me)
                        # A starvation window downs the PAIR's hop-k row
                        # exchange (both sides skip: the paired DMA needs
                        # both writers) - the visible cost of credit
                        # detection latency. A global wedge (timeout 0)
                        # downs every exchange until the lockstep exit.
                        down = (r <= pair_down[k]) | (
                            pstate[PS_WEDGE] != 0
                        )
                        quota = jnp.where(delay_me, 0, quota)
                        if plan.dead_device is not None:
                            # Quarantine: no work to a dead partner; the
                            # dead chip itself re-homes its whole backlog
                            # regardless of demand.
                            quota = jnp.where(
                                deadmask[partner] != 0, 0, quota
                            )
                            quota = jnp.where(
                                am_dead, jnp.clip(myb, 0, W), quota
                            )

                        @pl.when(jnp.logical_not(down))
                        def _(k=k, quota=quota, partner=partner, pdev=pdev,
                              drop_mine=drop_mine, drop_theirs=drop_theirs,
                              dup_mine=dup_mine, dup_theirs=dup_theirs,
                              delay_me=delay_me):
                            fstats[FS_DELAYED] = fstats[
                                FS_DELAYED
                            ] + delay_me.astype(jnp.int32)

                            @pl.when(delay_me)
                            def _(k=k):
                                tr.emit(
                                    TR_FAULT, tr.now(), FLT_DELAY, k
                                )

                            sendbuf[W, 0] = 0

                            @pl.when(quota > 0)
                            def _():
                                sendbuf[W, 0] = export(quota)

                            @pl.when(sendbuf[W, 0] > 0)
                            def _(partner=partner):
                                tr.emit(
                                    TR_XFER, tr.now(), partner,
                                    sendbuf[W, 0],
                                )

                            if plan.dead_device is not None:
                                fstats[FS_REHOMED] = fstats[
                                    FS_REHOMED
                                ] + jnp.where(am_dead, sendbuf[W, 0], 0)
                            # Credit wait, with REGENERATION: one wait is
                            # skipped per owed (dropped) credit. Safe: the
                            # partner consumed our inbox before dropping
                            # its signal, so the write below cannot
                            # overwrite an unconsumed transfer.
                            skip = owed[k] > 0

                            @pl.when((r > 0) & jnp.logical_not(skip))
                            def _(k=k):
                                pltpu.semaphore_wait(csems.at[2 * k + 1], 1)
                                cbal[k] = cbal[k] - 1

                            @pl.when((r > 0) & skip)
                            def _(k=k, partner=partner):
                                owed[k] = owed[k] - 1
                                fstats[FS_REGEN] = fstats[FS_REGEN] + 1
                                tr.emit(
                                    TR_CREDIT, tr.now(),
                                    (jnp.int32(k) << 8) | partner,
                                    CR_REGENERATED,
                                )

                            rdma2 = pltpu.make_async_remote_copy(
                                src_ref=sendbuf, dst_ref=inboxes[k],
                                send_sem=ssems.at[1],
                                recv_sem=rsems.at[2 * k + 1],
                                device_id=pdev, device_id_type=did_type,
                            )
                            rdma2.start()
                            rdma2.wait()
                            import_rows(inboxes[k])

                            # FAULT SITE: the credit I owe my partner
                            # after consuming its transfer.
                            @pl.when(jnp.logical_not(drop_theirs))
                            def _(k=k):
                                pltpu.semaphore_signal(
                                    csems.at[2 * k + 1], inc=1,
                                    device_id=pdev, device_id_type=did_type,
                                )

                            @pl.when(dup_theirs)
                            def _(k=k, partner=partner):
                                pltpu.semaphore_signal(
                                    csems.at[2 * k + 1], inc=1,
                                    device_id=pdev, device_id_type=did_type,
                                )
                                fstats[FS_DUPED] = fstats[FS_DUPED] + 1
                                tr.emit(
                                    TR_CREDIT, tr.now(),
                                    (jnp.int32(k) << 8) | partner, CR_DUPED,
                                )

                            @pl.when(drop_theirs)
                            def _(k=k, partner=partner):
                                fstats[FS_DROPPED] = fstats[FS_DROPPED] + 1
                                tr.emit(
                                    TR_CREDIT, tr.now(),
                                    (jnp.int32(k) << 8) | partner,
                                    CR_DROPPED,
                                )

                            # Deterministic mirror of the partner's signal
                            # decisions: the live balance the exit drain
                            # consumes (signals in - waits done).
                            cbal[k] = cbal[k] + jnp.where(
                                drop_mine, 0, 1 + dup_mine.astype(jnp.int32)
                            )

                            @pl.when(drop_mine)
                            def _(k=k, partner=partner):
                                owed[k] = owed[k] + 1
                                if plan.credit_timeout == 0:
                                    st = (jnp.int32(k << 8) | partner) + 1
                                    fstats[FS_STARVED] = jnp.where(
                                        fstats[FS_STARVED] == 0, st,
                                        fstats[FS_STARVED],
                                    )

                            if plan.credit_timeout > 0:

                                @pl.when(drop_mine | drop_theirs)
                                def _(k=k):
                                    pair_down[k] = r + jnp.int32(
                                        plan.credit_timeout
                                    )

            if plan is not None and plan.dead_device is not None:
                # Heartbeat detection (GENUINE, not oracle-driven: it
                # observes only the folded heartbeat words): quarantine
                # any peer whose heartbeat has not advanced for
                # heartbeat_timeout rounds. Quarantined ids leave the
                # eligibility side of the steal exchange next round.
                def det(p, _):
                    src = me ^ p
                    hb = statacc[self.SX_HB + p]
                    changed = hb != hb_seen[src]
                    hb_seen[src] = hb
                    hb_round[src] = jnp.where(changed, r, hb_round[src])
                    stale = (
                        r - hb_round[src]
                        >= jnp.int32(plan.heartbeat_timeout)
                    ) & (src != me)
                    newly = stale & (deadmask[src] == 0)

                    @pl.when(newly)
                    def _():
                        deadmask[src] = 1
                        fstats[FS_QMASK] = fstats[FS_QMASK] | (
                            jnp.int32(1) << src
                        )
                        fstats[FS_DEAD_ROUND] = jnp.where(
                            fstats[FS_DEAD_ROUND] < 0, r,
                            fstats[FS_DEAD_ROUND],
                        )
                        tr.emit(
                            TR_FAULT, tr.now(), FLT_DEAD_QUARANTINE, src
                        )

                    return 0

                jax.lax.fori_loop(0, ndev, det, 0)

        # ---- the round loop ----

        core.stage()
        stage_resident()
        if nten:
            # Lane cursors + cumulative counters: host-seeded per entry,
            # mutated in place by the WRR poll, echoed back at exit.
            for i in range(self.T):
                for w in range(8):
                    tctl_out[i, w] = tctl_in[i, w]
        if self.inject:
            cp0 = pltpu.make_async_copy(ictl, ctlbuf, isem.at[0])
            cp0.start()
            cp0.wait()
            consumed0 = ctlbuf[2]
        else:
            consumed0 = jnp.int32(0)

        def cond(carry):
            r, done, consumed = carry
            return jnp.logical_not(done) & (r < max_rounds)

        def body(carry):
            r, done, consumed = carry
            # Dead chip: the scalar-core scheduler is wedged (fuel 0, no
            # heartbeat tick) but the wire - exchanges, drains, re-homing
            # exports - stays up, like a real chip whose ICI router
            # outlives its core.
            am_dead = is_dead(r) if plan is not None else jnp.bool_(False)
            # Host abort word: re-read from HBM every round (BEFORE the
            # sched/poll so the quiesce flag can gate both), folded into
            # the termination collective below so the whole mesh exits in
            # lockstep within one fold of the write landing.
            cpa = pltpu.make_async_copy(abort_in, abuf, asem.at[0])
            cpa.start()
            cpa.wait()
            local_abort = abuf[0] != 0
            # Quiesce word rides the same per-device HBM row (word [1],
            # threshold in [2]): every device compares the same r, so the
            # flag is lockstep-consistent without waiting for the fold.
            if ckpt:
                local_quiesce = (abuf[1] != 0) & (r >= abuf[2])
            else:
                local_quiesce = jnp.bool_(False)
            # Quiesce drain rounds: from the threshold round on, stop
            # popping (fuel 0 - the round boundary the export contract
            # promises) but keep the exchange machinery live until the
            # wire is empty; heartbeats keep ticking so the drain cannot
            # be mistaken for a dead chip.
            #
            # Batched-tier residue is handled INSIDE this sched call, the
            # same way SF_INJ residue is handled by the poll below: every
            # sched() exit - a drained quantum AND the fuel-0 hold rounds
            # - retires any in-flight operand prefetch through the PR 3
            # ``drain`` callback and spills unrun lane entries back to
            # the ready ring's cold end. So by the time the fold, the
            # steal export scan, or the settled exit below run, no
            # prefetch DMA is outstanding and no descriptor is
            # lane-resident: the checkpoint cut only ever sees ring rows
            # and a quiet local DMA engine (prefetches are device-local,
            # so they never gate the sent == recv wire settle).
            hold = am_dead
            if ckpt:
                hold = hold | local_quiesce | (pstate[PS_QUIESCE] != 0)
            core.sched(jnp.where(hold, 0, quantum))
            pstate[PS_HB] = pstate[PS_HB] + jnp.where(am_dead, 0, 1)
            if self.inject:
                # Quiescing also stops RING consumption: published-but-
                # unconsumed rows stay put and export as the checkpoint's
                # ring residue (with the consumed cursor), instead of
                # being installed into the cut - the poll is the consumer
                # half of the cursor contract the bundle preserves.
                if ckpt:
                    quiescing = (
                        local_quiesce | (pstate[PS_QUIESCE] != 0)
                    )
                else:
                    quiescing = jnp.bool_(False)
                if nten:
                    # Tenant lanes: rows come off the per-lane regions
                    # through the WRR poll; cursors live in the tctl
                    # echo, not the loop carry.
                    newly = tpoll(r, quiescing)

                    @pl.when(newly > 0)
                    def _():
                        tr.emit(TR_INJECT, tr.now(), newly)

                    inj_backlog = lane_backlog()
                else:
                    c_new = poll(consumed, quiescing)

                    @pl.when(c_new > consumed)
                    def _():
                        tr.emit(TR_INJECT, tr.now(), c_new - consumed)

                    consumed = c_new
                    inj_backlog = ctlbuf[0] - consumed
            else:
                inj_backlog = jnp.int32(0)
            drain_outbox()
            fold_and_steal(r, inj_backlog, am_dead, local_abort,
                           local_quiesce)
            aborted = statacc[SF_ABORT] > 0

            @pl.when(aborted & (fstats[FS_ABORT_ROUND] < 0))
            def _():
                tr.emit(TR_ABORT, tr.now(), r)

            fstats[FS_ABORT_ROUND] = jnp.where(
                aborted & (fstats[FS_ABORT_ROUND] < 0), r,
                fstats[FS_ABORT_ROUND],
            )
            wire_idle = (
                (statacc[SF_OUTB] == 0)
                & (statacc[SF_INJ] == 0)
                & (statacc[SF_SENT] == statacc[SF_RECV])
            )
            settled = jnp.bool_(False)
            if ckpt:
                quiescing = statacc[SF_QUIESCE] > 0

                @pl.when(quiescing & (fstats[FS_QUIESCE_ROUND] < 0))
                def _():
                    fstats[FS_QUIESCE_ROUND] = r
                    tr.emit(TR_QUIESCE, tr.now(), r)

                pstate[PS_QUIESCE] = pstate[PS_QUIESCE] | quiescing.astype(
                    jnp.int32
                )
                # Lockstep clean-cut exit: quiesced AND the wire is empty
                # (pending work intentionally remains - that is the
                # checkpoint). Unconsumed INJECT rows also remain, by
                # design: the poll stopped consuming at the quiesce, so
                # the ring residue + cursor export with the state rather
                # than gating the exit (SF_INJ is a normal-termination
                # condition only).
                settled = quiescing & (
                    (statacc[SF_OUTB] == 0)
                    & (statacc[SF_SENT] == statacc[SF_RECV])
                )
            done = (
                ((statacc[SF_PEND] == 0) & wire_idle)
                | aborted | (statacc[SF_WEDGE] > 0) | settled
            )
            if plan is not None and (
                plan.drops_credits() and plan.credit_timeout == 0
            ):
                # Unrecoverable drop anywhere this round: every device
                # raises the wedge flag for the next fold and skips all
                # row exchanges meanwhile (a starved writer must never
                # reach its wait).
                pstate[PS_WEDGE] = pstate[PS_WEDGE] | any_drop(r).astype(
                    jnp.int32
                )
            # Unconditional: on the done round every delta is zero; on a
            # max_rounds cutoff this consumes every announced arrival.
            drain_receives()
            scan_waits()
            return r + 1, done, consumed

        r, done, consumed = jax.lax.while_loop(
            cond, body, (jnp.int32(0), jnp.bool_(False), consumed0)
        )
        counts[C_ROUNDS] = r
        if ckpt:
            # State-export record (the checkpoint bracket's device half).
            @pl.when(pstate[PS_QUIESCE] != 0)
            def _():
                tr.emit(
                    TR_CKPT, tr.now(), counts[C_PENDING],
                    counts[C_TAIL] - counts[C_HEAD],
                )

            # Export the live wait table (the lifted kernel-scratch
            # limit): pending waits leave with their needs REBASED to
            # arrivals-since-entry (need - chan_tot), so a resume that
            # restages with fresh channel counters fires them at exactly
            # the same residual arrival count. Rows beyond the count are
            # zeroed - the exported array must be a pure function of the
            # run, not of stale SMEM (bundle sha256 determinism).
            for i in range(MAXW + 1):
                for w in range(3):
                    waits_out[i, w] = 0
            waits_out[0, 0] = pstate[PS_NWAIT]

            def wexp(i, _):
                ch = wait_tab[i, 0]
                waits_out[1 + i, 0] = ch
                waits_out[1 + i, 1] = wait_tab[i, 1] - chan_tot[ch]
                waits_out[1 + i, 2] = wait_tab[i, 2]
                return 0

            jax.lax.fori_loop(0, pstate[PS_NWAIT], wexp, 0)
        if self.inject:
            ctl_out[0] = ctlbuf[0]
            ctl_out[1] = ctlbuf[1]
            ctl_out[2] = consumed
            for i in range(3, 8):
                ctl_out[i] = 0
        if plan is not None:
            fstats[FS_HB] = pstate[PS_HB]
        # Credit drain: every executed round ran every hop, and the first
        # send of each credited channel never waited - exactly one
        # outstanding credit per used channel once any round ran. Under a
        # fault plan the row channels drain their TRACKED balance instead
        # (signals received minus waits done): drops, dups, regeneration,
        # and down rounds all move it, and it must reach zero here or the
        # kernel cannot exit - the protocol's own conservation check.
        for k in range(2 * nh):
            if not self.steal and k % 2 == 1:
                continue
            if plan is not None and k % 2 == 1:

                def one(i, _, k=k):
                    pltpu.semaphore_wait(csems.at[k], 1)
                    return 0

                jax.lax.fori_loop(0, cbal[k // 2], one, 0)
                continue

            @pl.when(r >= 1)
            def _(k=k):
                pltpu.semaphore_wait(csems.at[k], 1)

    # -- host entry --

    def _build(self, quantum: int, max_rounds: int, hop_bits=None):
        mk = self.mk
        ndata = len(mk.data_specs)
        ndev, nchan, nh = self.ndev, self.nchan, self.nh
        W = self.window
        smem = functools.partial(pl.BlockSpec, memory_space=pltpu.SMEM)
        anyspace = functools.partial(pl.BlockSpec, memory_space=pl.ANY)
        nten = 1 if self.T else 0
        in_specs = [smem()] * 5 + [anyspace()] * ndata + [smem()]
        if self.inject:
            in_specs += [anyspace(), anyspace()]  # iring, ictl (HBM)
        if nten:
            in_specs += [smem()]  # per-device tctl block (tiny)
        in_specs += [anyspace()]  # abort word (HBM: re-read every round)
        out_specs = [smem()] * 4 + [anyspace()] * ndata
        data_shapes = [
            jax.ShapeDtypeStruct(s.shape, s.dtype)
            for s in mk.data_specs.values()
        ]
        out_shape = [
            jax.ShapeDtypeStruct((mk.capacity, DESC_WORDS), jnp.int32),
            jax.ShapeDtypeStruct((mk.capacity,), jnp.int32),
            jax.ShapeDtypeStruct((8,), jnp.int32),
            jax.ShapeDtypeStruct((mk.num_values,), jnp.int32),
        ] + data_shapes
        if self.inject:
            out_specs.append(smem())
            out_shape.append(jax.ShapeDtypeStruct((8,), jnp.int32))
        if nten:
            # The tctl echo (lane cursors + cumulative counters), right
            # after the ctl echo.
            out_specs.append(smem())
            out_shape.append(
                jax.ShapeDtypeStruct((self.T, 8), jnp.int32)
            )
        if mk.batch_specs:
            # Batched-tier counters (TS_* words) per device, appended
            # after the ctl echo: decoded into info['tiers'][d].
            out_specs.append(smem())
            out_shape.append(jax.ShapeDtypeStruct((TS_WORDS,), jnp.int32))
        # Per-device fault/abort stats (FS_* words), then (checkpoint
        # builds) the exported wait table, then the optional flight-
        # recorder ring - appended outputs, existing indices intact.
        out_specs.append(smem())
        out_shape.append(jax.ShapeDtypeStruct((FS_WORDS,), jnp.int32))
        if self.checkpoint:
            out_specs.append(smem())
            out_shape.append(
                jax.ShapeDtypeStruct((self.max_waits + 1, 3), jnp.int32)
            )
        if mk.trace is not None:
            out_specs.append(smem())
            out_shape.append(mk.trace.out_shape())
        aliases = {0: 0, 2: 1, 3: 2, 4: 3}
        for i in range(ndata):
            aliases[5 + i] = 4 + i
        scratch = list(mk.scratch_specs.values()) + [
            pltpu.SMEM((mk.capacity + 1,), jnp.int32),  # free
            pltpu.SMEM((mk.num_values // VBLOCK + 1,), jnp.int32),  # vfree
            pltpu.SMEM((self.scan,), jnp.int32),  # candbuf
            pltpu.SMEM((W + 1, DESC_WORDS), jnp.int32),  # sendbuf
            pltpu.SMEM((self.S,), jnp.int32),  # statacc
            pltpu.SMEM((self.S,), jnp.int32),  # statsnd
        ]
        scratch += [pltpu.SMEM((self.S,), jnp.int32) for _ in range(nh)]
        if self.steal:
            scratch += [
                pltpu.SMEM((W + 1, DESC_WORDS), jnp.int32)
                for _ in range(nh)
            ]
        scratch += [
            pltpu.SMEM((self.outbox,), jnp.int32),  # outq_tgt
            pltpu.SMEM((self.outbox, DESC_WORDS), jnp.int32),  # outq_desc
            pltpu.SMEM((2,), jnp.int32),  # obctl
            pltpu.SMEM((AMROW,), jnp.int32),  # ambuf
            pltpu.SMEM((ndev, self.am_window, AMROW), jnp.int32),  # inbox
            pltpu.SMEM((ndev,), jnp.int32),  # am_sent
            pltpu.SMEM((ndev,), jnp.int32),  # am_recv
            pltpu.SMEM((ndev,), jnp.int32),  # sent_round
            pltpu.SMEM((ndev, nchan), jnp.int32),  # data_sent
            pltpu.SMEM((ndev, nchan), jnp.int32),  # chan_recv
            pltpu.SMEM((nchan,), jnp.int32),  # chan_tot
            pltpu.SMEM((8,), jnp.int32),  # pstate
            pltpu.SMEM((self.max_waits, 3), jnp.int32),  # wait_tab
        ]
        if self.inject:
            scratch += [
                pltpu.SMEM((8,), jnp.int32),  # ctlbuf
                pltpu.SMEM((8, RING_ROW), jnp.int32),  # rowbuf
            ]
        scratch += [
            pltpu.SemaphoreType.DMA((4,)),  # ssems: stat,row,put,am sends
            pltpu.SemaphoreType.DMA((max(1, 2 * nh),)),  # rsems (per hop)
            pltpu.SemaphoreType.REGULAR((max(1, 2 * nh),)),  # csems
            pltpu.SemaphoreType.DMA((ndev,)),  # am_sems (per source)
            pltpu.SemaphoreType.DMA((ndev, nchan)),  # chan_sems
        ]
        if self.inject:
            scratch += [pltpu.SemaphoreType.DMA((2,))]  # isem
        scratch += [
            pltpu.SMEM((8,), jnp.int32),  # abuf (abort-word staging)
            pltpu.SemaphoreType.DMA((1,)),  # asem
        ]
        if mk.batch_specs:
            # Batched dispatch tier lane scratch (lanes + lane state);
            # re-entrant across sched() entries via the spill discipline.
            nb = mk.lane_scratch_rows  # kinds x priority buckets
            scratch += [
                pltpu.SMEM((nb, mk.capacity), jnp.int32),  # lanes
                pltpu.SMEM((nb, LS_WORDS), jnp.int32),  # lstate
            ]
        if self.plan is not None:
            nhk = max(1, nh)
            scratch += [
                pltpu.SMEM((nhk,), jnp.int32),  # pair_down
                pltpu.SMEM((nhk,), jnp.int32),  # owed
                pltpu.SMEM((nhk,), jnp.int32),  # cbal
                pltpu.SMEM((ndev,), jnp.int32),  # hb_seen
                pltpu.SMEM((ndev,), jnp.int32),  # hb_round
                pltpu.SMEM((ndev,), jnp.int32),  # deadmask
            ]
        if hop_bits is None:
            hop_bits = tuple(range(nh))
        kern = pl.pallas_call(
            functools.partial(
                self._kernel, quantum, max_rounds, mk.trace, hop_bits
            ),
            out_shape=tuple(out_shape),
            in_specs=in_specs,
            out_specs=tuple(out_specs),
            scratch_shapes=scratch,
            input_output_aliases=aliases,
            interpret=interpret_mode() if mk.interpret else False,
        )
        axes = self.axes

        ckpt = self.checkpoint

        def step(tasks, succ, ring, counts, iv, *rest):
            data_in = rest[:ndata]
            waits = rest[ndata]
            extra = rest[ndata + 1 :]
            outs = kern(
                tasks[0], succ[0], ring[0], counts[0], iv[0],
                *[d[0] for d in data_in], waits[0],
                *[x[0] for x in extra],
            )
            counts_o, iv_o = outs[2], outs[3]
            data_o = outs[4 : 4 + ndata]
            ntrace = 1 if self.mk.trace is not None else 0
            nckpt = 1 if ckpt else 0
            nbatch = 1 if self.mk.batch_specs else 0
            # Per-device batched-tier counters (appended after the ctl/
            # tctl echoes, before fstats): surfaced so info['tiers'][d]
            # reads mesh occupancy exactly like the single-device decode.
            tstats_o = (
                [outs[4 + ndata + (1 if self.inject else 0) + nten]]
                if nbatch else []
            )
            # The tctl echo rides out beside fstats on every tenant run
            # (the host table absorbs it after each entry).
            tctl_o = [outs[5 + ndata]] if nten else []
            fstats_o = outs[-1 - ntrace - nckpt]
            tail_o = ([outs[-1]] if ntrace else [])
            # Checkpoint builds export the mutated task table + ready
            # ring too - the per-device scheduler snapshot restore()
            # relaunches from (dropped by non-checkpoint builds, whose
            # positional consumers predate them) - plus the wait table
            # and (inject runs) the ctl echo carrying the inject-ring
            # consumed cursor, the two lifted coverage limits.
            state_o = [outs[0], outs[1], outs[-1 - ntrace]] if ckpt else []
            if ckpt and self.inject:
                state_o.append(outs[4 + ndata])
            gcounts = jax.lax.psum(counts_o, axes)
            return (
                counts_o[None],
                iv_o[None],
                gcounts[None],
                *[d[None] for d in data_o],
                *[t[None] for t in tstats_o],
                *[t[None] for t in tctl_o],
                fstats_o[None],
                *[s[None] for s in state_o],
                *[t[None] for t in tail_o],
            )

        nin = 7 + ndata + (2 if self.inject else 0) + nten
        # fstats (and the tstats / tctl echo / trace ring / checkpoint
        # state outputs, when built in) are per-device outputs too:
        # out_specs must cover them or shard_map rejects the pytree at
        # trace time.
        nout = (
            4 + ndata + (1 if self.mk.batch_specs else 0) + nten
            + (1 if self.mk.trace is not None else 0)
            + ((3 + (1 if self.inject else 0)) if ckpt else 0)
        )
        f = shard_map(
            step,
            mesh=self.mesh,
            in_specs=(P(axes),) * nin,
            out_specs=(P(axes),) * nout,
            check_vma=False,
        )
        return jax.jit(f)

    def run(
        self,
        builders: Optional[Sequence[TaskGraphBuilder]] = None,
        data: Optional[Dict[str, np.ndarray]] = None,
        ivalues: Optional[np.ndarray] = None,
        waits: Optional[Sequence[Sequence[Tuple[int, int, int]]]] = None,
        inject_rows: Optional[Sequence[Sequence[Tuple]]] = None,
        quantum: int = 64,
        max_rounds: int = 1 << 14,
        abort=None,
        quiesce=None,
        resume_state: Optional[Dict[str, Any]] = None,
        hop_order: Optional[Sequence[int]] = None,
        tenant_table=None,
    ):
        """Execute all partitions fully on-device.

        ``waits[d]``: host-declared wait-sets (chan_id, need, task_index),
        as PGASMegakernel. ``inject_rows[d]``: descriptor tuples
        ``(fn, args[, out[, tenant_lane]])`` - or prebuilt RING_ROW
        numpy rows (``tenants.build_row``) - published on device d's
        injection ring before entry (requires ``inject=True``); the
        in-kernel poll discovers and installs them mid-run, dropping
        rows whose ``TEN_EXPIRED`` word the host set (counted in
        ``fault_stats['tenant_expired']``, TR_TENANT traced). Returns
        (ivalues[ndev, V], data, info).

        ``abort``: the host abort word - truthy (or a per-device sequence
        of flags) makes every round loop observe the abort inside one
        round and the mesh exit in lockstep with ``info['aborted']``
        (pending work abandoned, no stall raise). The kernels re-read the
        word from HBM every round, which is what a host with in-place
        device-buffer write access would need to stop a mesh mid-run;
        through this driver the word is uploaded at entry.
        ``info['fault_stats']`` carries
        each device's FS_* trace (abort round, credits dropped/regenerated/
        duplicated, quarantine mask, re-homed rows, heartbeat).

        Checkpoint (``mk`` built with ``checkpoint=True``): ``quiesce``
        is the host quiesce word - truthy stops the mesh at its next
        round boundary, an int k at round >= k (the deterministic
        checkpoint-at-round-k spelling). Unlike abort, the exit is a
        clean cut: every device stops popping but the exchange rounds
        keep draining until outboxes are empty and sent == recv, then the
        mesh exits in lockstep with ``info['quiesced']=True`` and
        ``info['state']`` (the stacked per-device snapshot;
        ``run(resume_state=...)`` relaunches mid-graph, and
        ``runtime.checkpoint`` serializes / re-homes it onto a different
        mesh size). Pending host-declared ``waits`` survive the cut: the
        kernel exports its live wait table at exit (needs rebased to
        arrivals-since-entry), and ``resume_state`` restages it, so
        parked wait rows re-arm exactly. An injecting mesh exports its
        ring residue + consumed cursor the same way (``state['ring_rows']``
        / ``state['ictl']``), so a mid-stream quiesce loses nothing.

        Mesh tenancy (``tenants=`` at construction): ``tenant_table`` is
        the :class:`~hclib_tpu.device.tenants.MeshTenantTable` fronting
        this mesh - it pumps every device's lane regions + tctl block
        before entry and absorbs the echo after; rows enter ONLY through
        its ``submit`` routing (``inject_rows`` is refused). The echo
        rides out as ``info['tenant_ctl']`` and aggregate stats as
        ``info['tenants']``; a quiesced run's state carries the
        per-device tenant-tagged residue + aggregate tctl/tstats blocks
        (``tenant_table.export_state``), which a resume - on ANY mesh
        size, through ``CheckpointBundle.reshard`` - feeds back via
        ``run(resume_state=..., tenant_table=fresh_table)``.
        """
        from .sharded import execute_partitions

        mk = self.mk
        ndev = self.ndev
        if (builders is None) == (resume_state is None):
            raise ValueError(
                "run() wants exactly one of builders= or resume_state="
            )
        if quiesce is False:  # falsy boolean plumbing = off (see
            quiesce = None    # Megakernel.quiesce_words)
        if quiesce is not None and not self.checkpoint:
            raise ValueError(
                "quiesce= needs Megakernel(checkpoint=True): the quiesce "
                "word is compiled into the round loop only then"
            )
        if resume_state is not None:
            if waits or inject_rows:
                raise ValueError(
                    "resume_state= cannot be combined with waits/"
                    "inject_rows: the snapshot already carries every "
                    "pending row (incl. its wait table and inject-ring "
                    "residue)"
                )
            if data is not None or ivalues is not None:
                raise ValueError(
                    "resume_state= carries its own data/ivalues"
                )
            data = dict(resume_state.get("data") or {})
        waits = list(waits or [])
        if len(waits) < ndev:
            waits = waits + [[] for _ in range(ndev - len(waits))]
        if resume_state is not None and "waits" in resume_state:
            # Restage the exported wait table (needs already rebased to
            # arrivals-since-entry by the kernel's exit export; the
            # parked rows keep their dep bump in the snapshot, so no
            # bump_waits pass runs on resume).
            waits_arr = np.asarray(
                resume_state["waits"], np.int32
            ).reshape(-1, self.max_waits + 1, 3)
            if waits_arr.shape[0] != ndev:
                raise ValueError(
                    f"resume_state wait table covers "
                    f"{waits_arr.shape[0]} devices, this mesh has {ndev}"
                )
        else:
            waits_arr = np.zeros((ndev, self.max_waits + 1, 3), np.int32)
            for d, wlist in enumerate(waits):
                if len(wlist) > self.max_waits:
                    raise ValueError(f"device {d}: too many waits")
                waits_arr[d, 0, 0] = len(wlist)
                for i, (ch, need, row) in enumerate(wlist):
                    if not (0 <= ch < len(self.channels)):
                        raise ValueError(f"bad channel id {ch}")
                    if not (0 <= row < builders[d].num_tasks):
                        raise ValueError(
                            f"device {d}: wait names task {row} out of "
                            "range"
                        )
                    waits_arr[d, 1 + i] = (ch, need, row)
        if tenant_table is not None and not self.T:
            raise ValueError(
                "tenant_table= needs a tenant-enabled mesh: build the "
                "ResidentKernel with tenants= (or set "
                "HCLIB_TPU_MESH_TENANTS)"
            )
        extra: List[np.ndarray] = [waits_arr]
        if self.inject:
            R = self.ring_capacity
            iring = np.zeros((ndev, R, RING_ROW), np.int32)
            ictl = np.zeros((ndev, 8), np.int32)
            if self.T:
                # Mesh tenancy: rows enter through the MeshTenantTable's
                # routed admission only - the table pumps each device's
                # lane regions and builds the stacked tctl block this
                # entry uploads; the plain linear tail is unused.
                if inject_rows:
                    raise ValueError(
                        "a tenant-enabled mesh admits rows through its "
                        "MeshTenantTable (run(tenant_table=...)), not "
                        "inject_rows="
                    )
                if tenant_table is not None and (
                    len(tenant_table) != self.T
                    or tenant_table.ndev != ndev
                    or tenant_table.region_rows != self.region_rows
                ):
                    raise ValueError(
                        f"tenant_table shape mismatch: table has "
                        f"{len(tenant_table)} lanes x "
                        f"{tenant_table.ndev} devices x "
                        f"{tenant_table.region_rows} region rows; this "
                        f"mesh wants {self.T} x {ndev} x "
                        f"{self.region_rows}"
                    )
                if resume_state is not None and "tctl" in resume_state:
                    if tenant_table is None:
                        raise ValueError(
                            "resume state carries per-tenant lane "
                            "blocks (tctl/tstats): pass a fresh "
                            "tenant_table= so residue re-deals into "
                            "its lanes instead of being dropped"
                        )
                    tenant_table.resume_from(resume_state)
                elif resume_state is not None:
                    rr = resume_state.get("ring_rows")
                    rc = resume_state.get("ictl")
                    if (
                        rr is not None and rc is not None
                        and int(np.asarray(rc)[:, 0].sum()) > 0
                    ):
                        # A tenancy-off snapshot's residue has no lane
                        # identity: republishing it here would misfile
                        # every row, silently dropping it would lose
                        # tasks - refuse, like the mirror guard below.
                        raise ValueError(
                            "resume state carries untagged inject-ring "
                            "residue but no per-tenant lane blocks: it "
                            "was exported from a tenancy-off mesh and "
                            "cannot resume on a tenant-enabled one"
                        )
                ictl[:, 1] = 1  # closed: single-entry run drains fully
                if tenant_table is not None:
                    tctl_np = tenant_table.pump(iring)
                else:
                    tctl_np = np.zeros((ndev, self.T, 8), np.int32)
            elif resume_state is not None:
                if "tctl" in resume_state:
                    # Mirror of the tenant-resume guard: silently
                    # stripping every row's tenant identity would break
                    # the conservation contract.
                    raise ValueError(
                        "resume state carries per-tenant lane blocks "
                        "(tctl/tstats): it was exported from a "
                        "tenant-enabled mesh and cannot resume on a "
                        "tenancy-off one"
                    )
                # Re-publish the inject-ring residue (rows that were on
                # the ring but unconsumed at quiesce): packed from slot
                # 0 with a reset consumed cursor, so the in-kernel poll
                # discovers exactly the rows the cut left behind - the
                # cursor survives the checkpoint (and any reshard).
                rr = resume_state.get("ring_rows")
                rc = resume_state.get("ictl")
                if rr is not None and rc is not None:
                    rr = np.asarray(rr, np.int32)
                    rc = np.asarray(rc, np.int32)
                    if rr.shape[0] != ndev:
                        raise ValueError(
                            f"resume_state inject ring covers "
                            f"{rr.shape[0]} devices, this mesh has {ndev}"
                        )
                    for d in range(ndev):
                        n = int(rc[d, 0])
                        if n > R:
                            raise ValueError(
                                f"device {d}: {n} residue ring rows "
                                f"exceed ring_capacity {R}"
                            )
                        iring[d, :n] = rr[d, :n]
                        ictl[d, 0] = n
                        ictl[d, 1] = 1  # single-entry run drains fully
            else:
                for d, rows in enumerate(inject_rows or []):
                    iring[d], n = pack_inject_rows(rows, R, dev=d)
                    ictl[d, 0] = n
                    ictl[d, 1] = 1  # closed: single-entry run drains fully
            extra += [iring, ictl]
            if self.T:
                extra += [tctl_np]
        elif inject_rows:
            raise ValueError("inject_rows requires inject=True")
        from .sharded import abort_words

        abort_arr = abort_words(abort, ndev)
        abort_requested = bool(abort_arr[:, 0].any())
        quiesce_requested = quiesce is not None
        if quiesce_requested:
            # Quiesce word rides words [1] (flag) and [2] (round
            # threshold) of the same per-device HBM row the abort word
            # occupies - one ctl row per device, re-read every round.
            abort_arr[:, 1] = 1
            abort_arr[:, 2] = 0 if quiesce is True else int(quiesce)
        extra += [abort_arr]

        def bump_waits(tasks, succ, ring, counts):
            # Symmetric-heap layout: host value slots occupy the SAME range
            # on every device (the region below value_alloc), so a
            # whole-row-migrated task's host-slot F_OUT means the same
            # address everywhere and no device's dynamic row blocks overlap
            # another's host slots.
            va = max(int(counts[d][4]) for d in range(ndev))
            for d in range(ndev):
                counts[d][4] = va
            if self.migratable and self.homed:
                # The migration result-slot region [rbase, num_values)
                # must sit above every device's host value range and row
                # blocks, or homed copies' results would alias live slots.
                blocks = VBLOCK * mk.capacity if mk.uses_row_values else 0
                for d in range(ndev):
                    need = int(counts[d][4]) + blocks  # C_VALLOC
                    if need > self.rbase:
                        raise ValueError(
                            f"device {d}: value region [0, {need}) overlaps "
                            f"the migration result slots at [{self.rbase}, "
                            f"{mk.num_values}); grow num_values by at least "
                            f"{need - self.rbase}"
                        )
            for d, wlist in enumerate(waits):
                for (_, _, row) in wlist:
                    tasks[d, row, F_DEP] += 1
                bumped = {row for (_, _, row) in wlist}
                if not bumped:
                    continue
                old_n = counts[d][C_TAIL]
                keep = [x for x in ring[d][:old_n] if x not in bumped]
                ring[d][: len(keep)] = keep
                counts[d][C_TAIL] = len(keep)

        # hop_order (locality.xor_hop_order / a placement descriptor's
        # xor_hop_order()): reorders the paired XOR exchange scan
        # near-neighbors-first - validated to a full delta permutation
        # by _hop_bits, and part of the compile cache key (the loop is
        # unrolled into the kernel).
        hop_bits = self._hop_bits(hop_order)
        key = (quantum, max_rounds, hop_bits)
        first_build = key not in self._jitted
        if first_build:
            from ..runtime.progcache import shared_build

            self._jitted[key], self._pc_stats = shared_build(
                mk, self._cache_variant(key),
                lambda: self._build(quantum, max_rounds, hop_bits),
            )
        t0_ns = time.monotonic_ns()
        iv_o, data_o, info = execute_partitions(
            mk, self.mesh, ndev, self._jitted[key], builders, data, ivalues,
            with_rounds=True,
            mutate=bump_waits if resume_state is None else None,
            extra_inputs=extra, state=resume_state,
            keep_inputs=self.checkpoint,
        )
        t1_ns = time.monotonic_ns()
        if (
            first_build and self._pc_stats is not None
            and not self._pc_stats["hit"]
        ):
            # jax.jit is lazy: a cache MISS pays trace/lower/compile
            # inside this first entry (the Megakernel._execute
            # discipline), so fold the first wall into build_s before
            # it is reported.
            self._pc_stats["build_s"] += (t1_ns - t0_ns) / 1e9
        if self._pc_stats is not None:
            info["program_cache"] = dict(self._pc_stats)
        info["rounds"] = info.pop("steal_rounds")
        inputs = info.pop("inputs", None)
        tail = info.pop("extra_outputs")
        if mk.trace is not None:
            trows = tail[-1]
            info["trace"] = trace_info(
                [trows[d] for d in range(ndev)], t0_ns, t1_ns,
                mk.trace.capacity,
            )
            tail = tail[:-1]
        if self.checkpoint:
            if self.inject:
                ictl_rows = tail[-1]
                tail = tail[:-1]
            waits_rows = tail[-1]
            tasks_rows, ready_rows = tail[-3], tail[-2]
            tail = tail[:-3]
        frows = tail[-1]
        fs = [decode_fault_stats(frows[d]) for d in range(ndev)]
        info["fault_stats"] = fs
        info["aborted"] = any(f["abort_round"] >= 0 for f in fs)
        if self.T:
            # The stacked tctl echo (lane cursors + cumulative install/
            # expire/sweep counters): fold it back into the front door
            # so consume-cursor advances free in-flight budget and the
            # aggregate stats refresh.
            tctl_echo = np.asarray(tail[-2]).reshape(ndev, self.T, 8)
            info["tenant_ctl"] = tctl_echo
            if tenant_table is not None:
                tenant_table.absorb(tctl_echo)
                info["tenants"] = tenant_table.stats()
        if mk.batch_specs:
            # Per-device batched-tier occupancy (counters accumulate over
            # the whole resident entry): the mesh lane-firing-policy
            # signal the perf guard and MetricsRegistry gauges watch.
            trows = tail[-2 - (1 if self.T else 0)]
            info["tiers"] = [
                mk.decode_tier_stats(trows[d]) for d in range(ndev)
            ]
        if self.checkpoint:
            info["quiesced"] = any(f["quiesce_round"] >= 0 for f in fs)
            if self.inject:
                info["inject_ctl"] = np.asarray(ictl_rows)
            if info["quiesced"]:
                # The stacked per-device snapshot run(resume_state=)
                # relaunches from; runtime/checkpoint.py serializes it
                # and re-homes it onto a different mesh size. The wait
                # table (needs rebased at export) and the inject-ring
                # residue + cursor ride along - the two coverage limits
                # PR 6 lifted - so a mid-stream, waits-pending mesh
                # quiesces, migrates, and resumes without loss.
                info["state"] = {
                    "tasks": np.asarray(tasks_rows),
                    "succ": np.asarray(inputs["succ"]),
                    "ready": np.asarray(ready_rows),
                    "counts": np.asarray(info["per_device_counts"]),
                    "ivalues": np.asarray(iv_o),
                    "data": {k: np.asarray(v) for k, v in data_o.items()},
                    "waits": np.asarray(waits_rows),
                }
                if self.inject and self.T:
                    # Tenant mesh: the front door exports the per-lane
                    # residue (deadline-stamped, tenant-tagged) plus the
                    # aggregate tctl/tstats blocks. Without a table
                    # nothing was ever published (inject_rows is
                    # refused), so the state carries no tenant blocks
                    # and resumes table-less.
                    if tenant_table is not None:
                        info["state"].update(
                            tenant_table.export_state(iring)
                        )
                elif self.inject:
                    ic = np.asarray(ictl_rows)
                    rr = np.zeros(
                        (ndev, self.ring_capacity, RING_ROW), np.int32
                    )
                    nictl = np.zeros((ndev, 8), np.int32)
                    for d in range(ndev):
                        tl, cl, cons = (
                            int(ic[d, 0]), int(ic[d, 1]), int(ic[d, 2])
                        )
                        res = iring[d, cons:tl]
                        rr[d, : len(res)] = res
                        nictl[d, 0] = len(res)
                        nictl[d, 1] = cl
                    info["state"]["ring_rows"] = rr
                    info["state"]["ictl"] = nictl
        if info["overflow"]:
            from .megakernel import decode_overflow

            masks = [int(c[C_OVERFLOW]) for c in info["per_device_counts"]]
            agg = 0
            for m in masks:
                agg |= m
            raise RuntimeError(
                f"resident kernel overflow: {decode_overflow(agg)} "
                f"exhausted (per-device masks {masks}). Note: homed "
                "migration keeps a PROXY row at home until the remote "
                "completion lands, so the table must hold live + "
                "in-flight-proxy rows - raise capacity, shrink the steal "
                "window, or raise am_window to drain completions faster"
            )
        starved = [(d, f["starved_channel"]) for d, f in enumerate(fs)
                   if f["starved_channel"] is not None]
        if starved and info["pending"] != 0:
            d, ch = starved[0]
            raise StallError(
                f"ici steal credit starved: device {d}'s hop-{ch['hop']} "
                f"channel lost a flow-control credit from granter device "
                f"{ch['granter']} with regeneration disabled "
                f"(credit_timeout=0); mesh exited in lockstep with "
                f"{info['pending']} pending",
                stats=info,
            )
        if info["pending"] != 0 and not (
            abort_requested or info["aborted"] or info.get("quiesced")
        ):
            suspects = sorted({
                p for f in fs for p in f["quarantined"]
            })
            suspect = (
                f" suspect chip: device {suspects[0]} (quarantined by "
                f"heartbeat timeout; its unmigratable work cannot re-home)."
                if suspects else ""
            )
            raise StallError(
                f"resident kernel stalled: {info['pending']} pending after "
                f"{info['executed']} executed ({info['rounds']} rounds) - "
                "a wait/lock whose release never comes, or max_rounds too "
                f"small.{suspect}",
                stats=info,
            )
        return iv_o, data_o, info
