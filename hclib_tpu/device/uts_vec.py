"""Vectorized UTS: data-parallel tree search on the VPU.

The TPU-first re-design of UTS (reference workload: test/uts): instead of
one task per node (scalar megakernel) or one pthread per worker (C++ core),
1024 SIMD lanes each run an independent DFS over their own subtrees, with
every per-node operation vectorized across the (8, 128) VPU shape:

- SHA-1 (the UTS splittable RNG) is computed for all lanes' current children
  simultaneously - ~1.3k u32 plane-ops per step hash up to 1024 nodes.
- Each lane's DFS stack is a set of (state, next-child, count, depth) planes
  indexed by a per-lane stack pointer; stack reads/writes are select loops
  over the (small, static) stack height - no gathers, no dynamic indexing.
- Child counts are *exact*: the host binary-searches (in f64, matching the
  scalar implementations bit-for-bit) the integer thresholds t_k = min{r :
  floor(log(1-r/2^31)/log(1-p)) >= k}, and the device counts children as
  #(r >= t_k) with pure int32 compares. Leaf children are counted without
  being pushed (80% of canonical-tree nodes are leaves).
- The host seeds the lanes by BFS-ing the tree top (hashlib) to >= the
  requested root count, then deals shuffled subtree roots round-robin.

Supports the GEO/FIXED shape (all canonical T1/T1L/T1XL/T3 trees); the
depth-varying shapes would need per-depth threshold tables.

This is pure JAX (jnp + while_loop) - XLA maps it onto the VPU without a
hand-written kernel; it also runs on the CPU backend for tests.
"""

from __future__ import annotations

import functools
import math
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.uts import FIXED, UTSParams, num_children, root_state, spawn_state

__all__ = ["uts_vec", "child_thresholds"]

LANES = (8, 128)
NLANES = LANES[0] * LANES[1]
MAX_CHILDREN = 100


def child_thresholds(b0: float) -> np.ndarray:
    """Integer thresholds for the geometric child count at branching b0:
    count(r) = #{k : r >= t_k}. Exact w.r.t. the f64 scalar formula."""
    p = 1.0 / (1.0 + b0)
    logq = math.log(1.0 - p)

    def count_of(r: int) -> int:
        u = r / 2147483648.0
        if u >= 1.0:
            return MAX_CHILDREN
        return min(MAX_CHILDREN, int(math.floor(math.log(1.0 - u) / logq)))

    ts: List[int] = []
    rmax = (1 << 31) - 1
    for k in range(1, MAX_CHILDREN + 1):
        if count_of(rmax) < k:
            break  # k unreachable for any r
        lo, hi = 0, rmax  # invariant: count(hi) >= k
        while lo < hi:
            mid = (lo + hi) // 2
            if count_of(mid) >= k:
                hi = mid
            else:
                lo = mid + 1
        ts.append(lo)
    return np.asarray(ts, dtype=np.int32)


def _rotl(x, s: int):
    return (x << jnp.uint32(s)) | (x >> jnp.uint32(32 - s))


def _sha1_block(w16: List):
    """SHA-1 compression of one 16-word block, vectorized over planes."""
    K = (0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xCA62C1D6)
    H = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0)
    w = list(w16)
    a = jnp.full(LANES, H[0], jnp.uint32)
    b = jnp.full(LANES, H[1], jnp.uint32)
    c = jnp.full(LANES, H[2], jnp.uint32)
    d = jnp.full(LANES, H[3], jnp.uint32)
    e = jnp.full(LANES, H[4], jnp.uint32)
    for i in range(80):
        if i >= 16:
            nw = _rotl(w[(i - 3) % 16] ^ w[(i - 8) % 16] ^ w[(i - 14) % 16]
                       ^ w[i % 16], 1)
            w[i % 16] = nw
        wi = w[i % 16]
        if i < 20:
            f = (b & c) | (~b & d)
            k = K[0]
        elif i < 40:
            f = b ^ c ^ d
            k = K[1]
        elif i < 60:
            f = (b & c) | (b & d) | (c & d)
            k = K[2]
        else:
            f = b ^ c ^ d
            k = K[3]
        tmp = _rotl(a, 5) + f + e + jnp.uint32(k) + wi
        e, d, c, b, a = d, c, _rotl(b, 30), a, tmp
    return (
        a + jnp.uint32(H[0]),
        b + jnp.uint32(H[1]),
        c + jnp.uint32(H[2]),
        d + jnp.uint32(H[3]),
        e + jnp.uint32(H[4]),
    )


def _sha1_child(state5, child_idx):
    """SHA1(parent_state(20B) || BE32(child)) for 24-byte messages."""
    zero = jnp.zeros(LANES, jnp.uint32)
    w16 = [
        state5[0], state5[1], state5[2], state5[3], state5[4],
        child_idx.astype(jnp.uint32),
        jnp.full(LANES, 0x80000000, jnp.uint32),
        zero, zero, zero, zero, zero, zero, zero, zero,
        jnp.full(LANES, 24 * 8, jnp.uint32),
    ]
    return _sha1_block(w16)


def _level_select(stack, sp):
    """Read a per-lane level from a tuple-of-planes stack via selects.

    The stack is a Python tuple (one plane per level), NOT a stacked array:
    functional updates then leave untouched levels as the same arrays, so
    XLA's while-loop carry aliasing avoids whole-stack copies (a stacked
    (S, ...) array with .at[].set() costs a full copy per write and made the
    DFS step ~300x slower than its op count).
    """
    out = jnp.zeros_like(stack[0])
    for L, plane in enumerate(stack):
        out = jnp.where(sp == L, plane, out)
    return out


def _level_store(stack, sp, value, mask):
    """Write value at per-lane level sp where mask; returns a new tuple."""
    return tuple(
        jnp.where(mask & (sp == L), value, plane)
        for L, plane in enumerate(stack)
    )


@functools.partial(
    jax.jit,
    static_argnames=("stack_size", "gen_mx", "thresholds", "max_steps"),
)
def _uts_dfs(
    stack_state,  # (S, 5, 8, 128) u32
    stack_child,  # (S, 8, 128) i32
    stack_count,  # (S, 8, 128) i32
    stack_depth,  # (S, 8, 128) i32
    sp0,  # (8, 128) i32; -1 = done
    stack_size: int,
    gen_mx: int,
    thresholds: tuple,  # static ints: compiled as immediates, not memory reads
    max_steps: int,
):
    nthresh = len(thresholds)
    S = stack_size
    # Unstack into tuples of planes (see _level_select for why).
    st = tuple(
        tuple(stack_state[L, i] for i in range(5)) for L in range(S)
    )
    ch = tuple(stack_child[L] for L in range(S))
    cn = tuple(stack_count[L] for L in range(S))
    dp = tuple(stack_depth[L] for L in range(S))

    def count_children(r, depth):
        cnt = jnp.zeros(LANES, jnp.int32)
        for k in range(nthresh):
            cnt = cnt + (r >= jnp.int32(thresholds[k])).astype(jnp.int32)
        return jnp.where(depth < gen_mx, cnt, 0)

    def cond(carry):
        sp, nodes, leaves, maxd, st, ch, cn, dp, steps = carry
        return jnp.any(sp >= 0) & (steps < max_steps)

    def body(carry):
        sp, nodes, leaves, maxd, st, ch, cn, dp, steps = carry
        active = sp >= 0
        # Top frame.
        child = _level_select(ch, sp)
        count = _level_select(cn, sp)
        depth = _level_select(dp, sp)
        state = [
            _level_select(tuple(st[L][i] for L in range(S)), sp)
            for i in range(5)
        ]
        expand = active & (child < count)
        # Hash the next child for every lane (masked lanes pay, SIMD-style).
        cstate = _sha1_child(state, child)
        r = (cstate[4] & jnp.uint32(0x7FFFFFFF)).astype(jnp.int32)
        cdepth = depth + 1
        ccount = count_children(r, cdepth)
        is_leaf = ccount == 0
        nodes = nodes + expand.astype(jnp.int32)
        leaves = leaves + (expand & is_leaf).astype(jnp.int32)
        maxd = jnp.maximum(maxd, jnp.where(expand, cdepth, 0))
        # Parent consumed one child.
        ch = _level_store(ch, sp, child + 1, expand)
        # Push non-leaf children.
        push = expand & ~is_leaf
        spp = sp + 1
        st = tuple(
            tuple(
                jnp.where(push & (spp == L), cstate[i], st[L][i])
                for i in range(5)
            )
            for L in range(S)
        )
        ch = _level_store(ch, spp, jnp.zeros(LANES, jnp.int32), push)
        cn = _level_store(cn, spp, ccount, push)
        dp = _level_store(dp, spp, cdepth, push)
        # Pop exhausted frames; advance pushed frames.
        sp = jnp.where(push, spp, jnp.where(active & ~expand, sp - 1, sp))
        return sp, nodes, leaves, maxd, st, ch, cn, dp, steps + 1

    zeros = jnp.zeros(LANES, jnp.int32)
    carry = (sp0, zeros, zeros, zeros, st, ch, cn, dp, jnp.int32(0))
    sp, nodes, leaves, maxd, *_rest, steps = jax.lax.while_loop(cond, body, carry)
    # int32 totals: fine up to 2^31 device-side nodes (T1L is 102M; the 4.2B
    # T1XXL tree would need per-lane int64 counters or periodic draining).
    return (
        jnp.sum(nodes),
        jnp.sum(leaves),
        jnp.max(maxd),
        steps,
        jnp.any(sp >= 0),
    )


def uts_vec(
    params: UTSParams,
    target_roots: int = 4 * NLANES,
    max_steps: Optional[int] = None,
    device=None,
) -> dict:
    """Run UTS with the vectorized DFS engine; returns counts + timing info.

    The host BFS-expands the tree top until >= target_roots frontier nodes
    (counting that part itself), then the device traverses the subtrees.
    """
    if params.shape != FIXED:
        raise NotImplementedError("uts_vec supports the GEO/FIXED shape")
    # Host BFS seed.
    host_nodes = host_leaves = 0
    host_maxd = 0
    frontier: List[Tuple[bytes, int]] = [(root_state(params.root_seed), 0)]
    while frontier and len(frontier) < target_roots:
        nxt: List[Tuple[bytes, int]] = []
        for state, depth in frontier:
            host_nodes += 1
            host_maxd = max(host_maxd, depth)
            nc = num_children(params, state, depth)
            if nc == 0:
                host_leaves += 1
                continue
            for i in range(nc):
                nxt.append((spawn_state(state, i), depth + 1))
        frontier = nxt
    result = {
        "host_seed_nodes": host_nodes,
        "roots": len(frontier),
    }
    if not frontier:
        result.update(
            nodes=host_nodes, leaves=host_leaves, max_depth=host_maxd, steps=0
        )
        return result
    d0 = frontier[0][1]
    # Roots count as nodes; leaf roots as leaves (the device counts children
    # at expansion time, so roots must be accounted here).
    thresholds = child_thresholds(params.b0)
    root_counts = []
    for state, depth in frontier:
        host_nodes += 1
        host_maxd = max(host_maxd, depth)
        c = num_children(params, state, depth)
        root_counts.append(c)
        if c == 0:
            host_leaves += 1
    rng = np.random.default_rng(0)
    order = rng.permutation(len(frontier))
    rpl = (len(frontier) + NLANES - 1) // NLANES
    S = rpl + (params.gen_mx - d0) + 1
    st = np.zeros((S, 5) + LANES, np.uint32)
    ch = np.zeros((S,) + LANES, np.int32)
    cn = np.zeros((S,) + LANES, np.int32)
    dp = np.zeros((S,) + LANES, np.int32)
    for slot, j in enumerate(order):
        state, _ = frontier[j]
        level, lane = divmod(slot, NLANES)
        r, c = divmod(lane, LANES[1])
        words = np.frombuffer(state, dtype=">u4").astype(np.uint32)
        st[level, :, r, c] = words
        cn[level, r, c] = root_counts[j]
        dp[level, r, c] = d0
    # Lanes with fewer roots: the unused bottom frames have count 0 and pop
    # straight through.
    sp0 = np.full(LANES, rpl - 1, np.int32)
    if max_steps is None:
        max_steps = 1 << 31 - 1
    import time

    args = (
        jnp.asarray(st), jnp.asarray(ch), jnp.asarray(cn), jnp.asarray(dp),
        jnp.asarray(sp0),
    )
    kw = dict(
        stack_size=S, gen_mx=params.gen_mx,
        thresholds=tuple(int(t) for t in thresholds),
        max_steps=max_steps,
    )
    if device is not None:
        args = tuple(jax.device_put(a, device) for a in args)
    nodes, leaves, maxd, steps, unfinished = _uts_dfs(*args, **kw)
    t0 = time.perf_counter()
    nodes, leaves, maxd, steps, unfinished = _uts_dfs(*args, **kw)
    dev_nodes = int(nodes)
    dt = time.perf_counter() - t0
    if bool(unfinished):
        raise RuntimeError(f"uts_vec ran out of steps ({max_steps})")
    result.update(
        nodes=host_nodes + dev_nodes,
        leaves=host_leaves + int(leaves),
        max_depth=max(host_maxd, int(maxd)),
        steps=int(steps),
        device_nodes=dev_nodes,
        device_seconds=dt,
        nodes_per_sec=dev_nodes / dt if dt > 0 else float("inf"),
    )
    return result


if __name__ == "__main__":  # pragma: no cover
    import sys

    from ..models.uts import T1, T1L, T3

    name = sys.argv[1] if len(sys.argv) > 1 else "T3"
    params = {"T1": T1, "T1L": T1L, "T3": T3}[name]
    print(uts_vec(params))
