"""Vectorized UTS: data-parallel tree search on the VPU.

The TPU-first re-design of UTS (reference workload: test/uts): instead of
one task per node (scalar megakernel) or one pthread per worker (C++ core),
thousands of SIMD lanes each run an independent DFS, with every per-node
operation vectorized across (rows, 128) VPU planes:

- SHA-1 (the UTS splittable RNG) is computed for all lanes' current children
  simultaneously - ~1.3k u32 plane-ops per step hash one child per lane.
- Each lane's DFS stack is a set of (state, next-child, count, depth) planes
  indexed by a per-lane stack pointer; stack reads/writes are select loops
  over the (small, static) stack height - no gathers, no dynamic indexing.
  Tail-call scheduling (a frame expanding its last non-leaf child is
  *replaced* by that child; a last leaf child pops immediately) keeps every
  stack frame expandable, so every active step performs an expansion - the
  classic DFS pop-the-exhausted-frame steps, ~20% of all steps on canonical
  trees, are eliminated.
- **Dynamic load balancing via a shared root queue**: the host seeds a flat
  array of subtree roots (all at one BFS depth d0); every step, lanes whose
  stack emptied claim the next unclaimed roots with a prefix-sum over the
  done mask + a gather from the root arrays. Imbalance is therefore bounded
  by the size of a single subtree instead of the sum of a lane's static
  deal - this is the work-stealing idea of the reference scheduler
  (src/hclib-deque.c) recast as a data-parallel claim, and it is what makes
  lane efficiency scale.
- Child counts are *exact*: the host binary-searches (in f64, matching the
  scalar implementations bit-for-bit) the integer thresholds t_k = min{r :
  floor(log(1-r/2^31)/log(1-p)) >= k}, and the device counts children as
  #(r >= t_k) with pure int32 compares. Leaf children are counted without
  being pushed (80% of canonical-tree nodes are leaves).
- The host BFS seed is itself vectorized: the same SHA-1 block function runs
  on numpy arrays over whole frontier levels, so seeding hundreds of
  thousands of subtree roots costs well under a second.

Supports every GEO shape: FIXED (canonical T1/T1L/T1XL/T3) on the
depth-independent threshold fast path, LINEAR/CYCLIC (canonical T5/T2) and
EXPDEC via exact per-depth threshold tables (one row of integer thresholds
per depth from the f64 shape function, -1 padded; the device gathers its
row by depth and counts with pure int32 compares).

This is pure JAX (jnp + while_loop) - XLA maps it onto the VPU without a
hand-written kernel; it also runs on the CPU backend for tests.
"""

from __future__ import annotations

import functools
import math
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.uts import CYCLIC, FIXED, LINEAR, UTSParams, _branching
from ..ops.sha1 import sha1_block as _sha1_block, sha1_child as _sha1_child

__all__ = [
    "uts_vec", "child_thresholds", "child_threshold_table", "depth_cap",
    "inrow_threshold_table", "padded_threshold_table", "MAX_CHILDREN",
    "PAD_QUANTUM",
    "LANES", "NLANES", "make_count_children", "make_dfs_step",
    "make_refill",
]

LANES = (8, 128)
NLANES = LANES[0] * LANES[1]
MAX_CHILDREN = 100
# Root arrays are padded to a multiple of this (shared by both engines):
# trees with different root counts land on one padded shape and so share
# one compiled engine (the real count travels as a runtime scalar). A
# multiple of uts_pallas.ALIGN (1024) so the pallas row-block DMA windows
# stay aligned.
PAD_QUANTUM = 4096


def _thresholds_for_b(b_i: float) -> List[int]:
    """Integer thresholds for the geometric child count at branching b_i:
    count(r) = #{k : r >= t_k}. Exact w.r.t. the f64 scalar formula."""
    if b_i <= 0.0:
        return []
    p = 1.0 / (1.0 + b_i)
    logq = math.log(1.0 - p)

    def count_of(r: int) -> int:
        u = r / 2147483648.0
        if u >= 1.0:
            return MAX_CHILDREN
        return min(MAX_CHILDREN, int(math.floor(math.log(1.0 - u) / logq)))

    ts: List[int] = []
    rmax = (1 << 31) - 1
    for k in range(1, MAX_CHILDREN + 1):
        if count_of(rmax) < k:
            break  # k unreachable for any r
        lo, hi = 0, rmax  # invariant: count(hi) >= k
        while lo < hi:
            mid = (lo + hi) // 2
            if count_of(mid) >= k:
                hi = mid
            else:
                lo = mid + 1
        ts.append(lo)
    return ts


def child_thresholds(b0: float) -> np.ndarray:
    """Depth-independent thresholds (the GEO/FIXED fast path)."""
    return np.asarray(_thresholds_for_b(b0), dtype=np.int32)


def depth_cap(params: UTSParams) -> Optional[int]:
    """Smallest depth bound that covers every node the shape can produce
    (node depths strictly below the returned value), or None when the
    shape is unbounded (EXPDEC: b_i decays but never reaches 0, so a cap
    must be chosen by the caller and validated against the observed max
    depth)."""
    if params.shape == FIXED:
        return params.gen_mx + 1
    if params.shape == LINEAR:
        return params.gen_mx + 1  # b_i <= 0 at depth >= gen_mx
    if params.shape == CYCLIC:
        return 5 * params.gen_mx + 2  # b_i = 0 beyond 5*gen_mx
    return None


def child_threshold_table(params: UTSParams, max_depth: int) -> np.ndarray:
    """Per-depth threshold table for the depth-varying shapes
    (reference: the b_i shape functions, test/uts/uts.c:171-221): row d
    holds the thresholds for a node AT depth d, -1 padding marks child
    ordinals unreachable at that depth. Rows cover d in [0, max_depth]."""
    rows = [
        _thresholds_for_b(_branching(params, d))
        for d in range(max_depth + 1)
    ]
    K = max((len(r) for r in rows), default=0) or 1
    table = np.full((max_depth + 1, K), -1, dtype=np.int32)
    for d, r in enumerate(rows):
        table[d, : len(r)] = r
    return table


def _level_select(stack, sp):
    """Read a per-lane level from a tuple-of-planes stack via selects.

    The stack is a Python tuple (one plane per level), NOT a stacked array:
    functional updates then leave untouched levels as the same arrays, so
    XLA's while-loop carry aliasing avoids whole-stack copies (a stacked
    (S, ...) array with .at[].set() costs a full copy per write and made the
    DFS step ~300x slower than its op count).
    """
    out = jnp.zeros_like(stack[0])
    for L, plane in enumerate(stack):
        out = jnp.where(sp == L, plane, out)
    return out


def _level_store(stack, sp, value, mask):
    """Write value at per-lane level sp where mask; returns a new tuple."""
    return tuple(
        jnp.where(mask & (sp == L), value, plane)
        for L, plane in enumerate(stack)
    )


def inrow_threshold_table(thresholds: tuple, cols: int) -> np.ndarray:
    """Transpose a per-depth threshold table to the in-row-gather layout:
    one ``cols``-wide row per child ordinal, -1 padded, so a per-lane
    (depth -> threshold) lookup is a same-shape ``take_along_axis``. The
    fused Pallas engine passes this as a kernel input (Mosaic kernels
    cannot capture array constants)."""
    tab_np = np.asarray(thresholds, dtype=np.int32)  # (D+1, K)
    D = tab_np.shape[0] - 1
    if D + 1 >= cols:
        # STRICTLY below cols: count_children_inrow clips depth to
        # cols - 1 and relies on that column being -1 padding, so an
        # over-deep lane counts 0 children (a full table would put live
        # thresholds there and expand a phantom subtree to max_steps).
        raise NotImplementedError(
            f"in-row table gather needs depth cap + 1 < {cols} "
            f"lane columns, got {D + 1}"
        )
    padded = np.full((tab_np.shape[1], cols), -1, np.int32)
    padded[:, : D + 1] = tab_np.T
    return padded


def make_count_children(
    thresholds, gen_mx, lanes: tuple, inrow_table=None, table=None
):
    """Exact geometric child count. ``thresholds`` is either a flat tuple
    (depth-independent FIXED shape, guarded by the runtime ``gen_mx``
    scalar) or None: the per-depth threshold table then arrives as a
    RUNTIME array - ``table`` ((D+1, K), -1 padded; the count is a row
    gather by each lane's depth) or ``inrow_table`` ((K, cols) laid out by
    inrow_threshold_table): the Mosaic-compatible formulation for the
    fused Pallas engine, where the per-lane (depth -> threshold) lookup
    becomes a same-shape ``take_along_axis`` per child ordinal - the only
    gather form Mosaic supports. Same integer thresholds, bit-identical
    counts either way - and because the table VALUES are inputs, trees
    whose padded table SHAPES match share one compiled engine (the
    per-shape XLA/Mosaic compile is ~1 min; the suite pads all
    depth-varying trees to a common shape, see padded_threshold_table)."""
    if thresholds is None:
        if inrow_table is not None:
            K = inrow_table.shape[0]
            cols = lanes[1]

            def count_children_inrow(r, depth):
                # Depths beyond the real table rows hit the -1 column
                # padding (inrow tables are padded to the full row width),
                # so the count is exactly 0 there - no explicit guard.
                dclip = jnp.clip(depth, 0, cols - 1)
                cnt = jnp.zeros(lanes, jnp.int32)
                for k in range(K):
                    row = jnp.broadcast_to(inrow_table[k], lanes)
                    t = jnp.take_along_axis(row, dclip, axis=1)
                    cnt = cnt + ((t >= 0) & (r >= t)).astype(jnp.int32)
                return cnt

            return count_children_inrow
        D = table.shape[0] - 1

        def count_children_rows(r, depth):
            rows = jnp.take(table, jnp.clip(depth, 0, D), axis=0)
            cnt = jnp.sum(
                (rows >= 0) & (r[..., None] >= rows), axis=-1
            ).astype(jnp.int32)
            # Beyond the table the count is 0, NOT the last row's (which
            # may be supercritical when a depth_bound truncates a live
            # region): the traversal then terminates and the caller's
            # maxd >= cap validation fails loudly instead of the kernel
            # grinding a phantom infinite subtree to max_steps.
            return jnp.where(depth <= D, cnt, 0)

        return count_children_rows

    def count_children(r, depth):
        cnt = jnp.zeros(lanes, jnp.int32)
        for k in range(len(thresholds)):
            cnt = cnt + (r >= jnp.int32(thresholds[k])).astype(jnp.int32)
        return jnp.where(depth < gen_mx, cnt, 0)

    return count_children


def make_dfs_step(
    S: int, lanes: tuple, thresholds, gen_mx,
    inrow_table=None, table=None,
):
    """One vectorized DFS expansion step over all lanes (the hot loop body,
    shared by the XLA engine here and the fused Pallas engine in
    uts_pallas.py). Signature:
    (sp, nodes, leaves, maxd, st, ch, cn, dp) -> same tuple."""
    count_children = make_count_children(
        thresholds, gen_mx, lanes, inrow_table, table
    )

    def step(sp, nodes, leaves, maxd, st, ch, cn, dp):
        active = sp >= 0
        child = _level_select(ch, sp)
        count = _level_select(cn, sp)
        depth = _level_select(dp, sp)
        state = [
            _level_select(tuple(st[L][i] for L in range(S)), sp)
            for i in range(5)
        ]
        expand = active & (child < count)
        cstate = _sha1_child(state, child, jnp)
        r = (cstate[4] & jnp.uint32(0x7FFFFFFF)).astype(jnp.int32)
        cdepth = depth + 1
        ccount = count_children(r, cdepth)
        is_leaf = ccount == 0
        nodes = nodes + expand.astype(jnp.int32)
        leaves = leaves + (expand & is_leaf).astype(jnp.int32)
        maxd = jnp.maximum(maxd, jnp.where(expand, cdepth, 0))
        # Tail-call scheduling keeps every stack frame expandable (child <
        # count), so every active step performs an expansion - no steps are
        # wasted popping exhausted frames:
        #  - last+leaf child: frame is done, pop now.
        #  - last+non-leaf child: child *replaces* the parent frame (tail
        #    call) - exhausted parents are never buried on the stack.
        #  - otherwise: bump the cursor; push non-leaf children.
        last = expand & (child + 1 >= count)
        push = expand & ~is_leaf & ~last
        tail = expand & ~is_leaf & last
        pop = (expand & is_leaf & last) | (active & ~expand)
        ch = _level_store(ch, sp, child + 1, expand & ~last)
        # One store pass for both push (at sp+1) and tail-replace (at sp).
        spp = sp + 1
        lvl = jnp.where(push, spp, sp)
        newf = push | tail
        st = tuple(
            tuple(
                jnp.where(newf & (lvl == L), cstate[i], st[L][i])
                for i in range(5)
            )
            for L in range(S)
        )
        ch = _level_store(ch, lvl, jnp.zeros(lanes, jnp.int32), newf)
        cn = _level_store(cn, lvl, ccount, newf)
        dp = _level_store(dp, lvl, cdepth, newf)
        sp = jnp.where(push, spp, jnp.where(pop, sp - 1, sp))
        return sp, nodes, leaves, maxd, st, ch, cn, dp

    return step


def apply_claim(claim, rst, rcn, d0, sp, st0, ch0, cn0, dp0):
    """Install gathered roots into level 0 of claiming lanes (the shared
    tail of every refill implementation)."""
    st0 = tuple(jnp.where(claim, rst[i], st0[i]) for i in range(5))
    ch0 = jnp.where(claim, 0, ch0)
    cn0 = jnp.where(claim, rcn, cn0)
    dp0 = jnp.where(claim, d0, dp0)
    sp = jnp.where(claim, 0, sp)
    return sp, st0, ch0, cn0, dp0


def make_refill(lanes: tuple, d0: int):
    """Shared-root-queue claim: starved lanes (sp < 0) take the next
    contiguous unclaimed roots via prefix-sum rank + windowed gather.
    Returns refill(roots_state, roots_count, R, sp, next_root, st0, ch0,
    cn0, dp0) -> (sp, next_root, st0, ch0, cn0, dp0)."""
    nlanes = lanes[0] * lanes[1]

    def refill(roots_state, roots_count, R, sp, next_root, st0, ch0, cn0,
               dp0):
        done = sp < 0
        rank = jnp.cumsum(done.reshape(-1).astype(jnp.int32)).reshape(lanes)
        avail = R - next_root
        claim = done & (rank <= avail)
        # Claims are contiguous [next_root, next_root + nclaim): slice an
        # nlanes-wide window once, then gather within it - a gather over a
        # small VMEM-resident window instead of the whole HBM root array.
        win = [
            jax.lax.dynamic_slice(roots_state[i], (next_root,), (nlanes,))
            for i in range(5)
        ]
        wcn = jax.lax.dynamic_slice(roots_count, (next_root,), (nlanes,))
        idx = jnp.clip(rank - 1, 0, nlanes - 1)
        rst = [jnp.take(win[i], idx, axis=0) for i in range(5)]
        rcn = jnp.take(wcn, idx, axis=0)
        sp, st0, ch0, cn0, dp0 = apply_claim(
            claim, rst, rcn, d0, sp, st0, ch0, cn0, dp0
        )
        next_root = next_root + jnp.minimum(
            jnp.sum(done.astype(jnp.int32)), avail
        )
        return sp, next_root, st0, ch0, cn0, dp0

    return refill


def make_traversal(
    S: int,
    lanes: tuple,
    thresholds,
    gen_mx,
    min_idle: int,
    max_steps: int,
    refill,
    R,
    inrow_table=None,
    table=None,
):
    """The complete traversal driver shared by both engines: outer loop =
    refill + refill-free inner expansion loop until `min_idle` lanes are
    starved (or nothing is left to claim). ``refill(sp, next_root, st0,
    ch0, cn0, dp0)`` is the only engine-specific part (XLA gather here vs
    in-kernel DMA + matmul gather in uts_pallas). Returns run() ->
    (sp, next_root, nodes, leaves, maxd, steps)."""
    step = make_dfs_step(S, lanes, thresholds, gen_mx, inrow_table, table)

    def inner_cond(carry):
        sp, nodes, leaves, maxd, st, ch, cn, dp, steps, avail = carry
        active = jnp.any(sp >= 0)
        ndone = jnp.sum((sp < 0).astype(jnp.int32))
        # Keep expanding while work remains and either too few lanes are
        # idle to justify a refill, or there is nothing left to claim.
        return (
            active
            & ((ndone < min_idle) | (avail <= 0))
            & (steps < max_steps)
        )

    def inner_body(carry):
        sp, nodes, leaves, maxd, st, ch, cn, dp, steps, avail = carry
        sp, nodes, leaves, maxd, st, ch, cn, dp = step(
            sp, nodes, leaves, maxd, st, ch, cn, dp
        )
        return sp, nodes, leaves, maxd, st, ch, cn, dp, steps + 1, avail

    def outer_cond(carry):
        sp, next_root, nodes, leaves, maxd, st, ch, cn, dp, steps = carry
        return (jnp.any(sp >= 0) | (next_root < R)) & (steps < max_steps)

    def outer_body(carry):
        sp, next_root, nodes, leaves, maxd, st, ch, cn, dp, steps = carry
        sp, next_root, st0, ch0, cn0, dp0 = refill(
            sp, next_root, st[0], ch[0], cn[0], dp[0]
        )
        st = (st0,) + st[1:]
        ch = (ch0,) + ch[1:]
        cn = (cn0,) + cn[1:]
        dp = (dp0,) + dp[1:]
        inner = (
            sp, nodes, leaves, maxd, st, ch, cn, dp, steps, R - next_root,
        )
        (
            sp, nodes, leaves, maxd, st, ch, cn, dp, steps, _,
        ) = jax.lax.while_loop(inner_cond, inner_body, inner)
        return sp, next_root, nodes, leaves, maxd, st, ch, cn, dp, steps

    def run():
        zeros = jnp.zeros(lanes, jnp.int32)
        uzeros = jnp.zeros(lanes, jnp.uint32)
        st0 = tuple(tuple(uzeros for _ in range(5)) for _ in range(S))
        ch0 = tuple(zeros for _ in range(S))
        cn0 = tuple(zeros for _ in range(S))
        dp0 = tuple(zeros for _ in range(S))
        carry = (
            jnp.full(lanes, -1, jnp.int32), jnp.int32(0), zeros, zeros,
            zeros, st0, ch0, cn0, dp0, jnp.int32(0),
        )
        (sp, next_root, nodes, leaves, maxd, *_rest, steps) = (
            jax.lax.while_loop(outer_cond, outer_body, carry)
        )
        return sp, next_root, nodes, leaves, maxd, steps

    return run


def resolve_timing_reps(timing_reps, on_tpu: bool) -> int:
    """Default timing policy shared by both engines: best-of-3 same-args
    executions on a real TPU (a single timed execution right after staging
    reads transient allocator/transfer stalls on the tunnel-attached chip
    as phantom 4-6x throttling), one execution elsewhere (CPU/interpret
    runs are deterministic, and correctness callers only need counts)."""
    if timing_reps is not None:
        return max(1, int(timing_reps))
    return 3 if on_tpu else 1


def _timed_best(run, reps: int):
    """Warm once, then return (outputs, dev_nodes, best_dt) over ``reps``
    timed executions of ``run`` (same compiled kernel, same staged args;
    the per-run D2H node-plane sum is the only reliable sync through the
    tunnel and is deliberately inside the timed region for both engines)."""
    outs = run()
    # Synchronize the warm execution (dispatch is async; its tail would
    # otherwise bleed into rep 1's t0 and bias the single-rep rate slow).
    _ = int(np.asarray(outs[0]).sum(dtype=np.int64))
    dt = None
    dev_nodes = 0
    for _ in range(reps):
        t0 = time.perf_counter()
        outs = run()
        dev_nodes = int(np.asarray(outs[0]).sum(dtype=np.int64))
        d = time.perf_counter() - t0
        dt = d if dt is None else min(dt, d)
    return outs, dev_nodes, dt


def padded_threshold_table(
    params: UTSParams,
    cap: int,
    max_rows: Optional[int] = None,
    min_cols: Optional[int] = None,
) -> np.ndarray:
    """child_threshold_table padded to a COMMON shape: rows (depths) up to
    a multiple of 16, columns (child ordinals) to the next multiple of 16
    (capped at MAX_CHILDREN), -1 filled. The table values are runtime
    inputs to both engines, so every depth-varying tree whose padded shape
    matches shares ONE compiled engine (per stack height) instead of
    paying the ~1 min XLA/Mosaic compile per tree - padding costs a few
    dead compares per step (the per-step table cost scales with the COLUMN
    count, so quantized widths keep small-ordinal trees cheap while trees
    in one width class still share a compile).

    ``max_rows`` (uts_pallas passes its lane-column limit) caps the row
    round-up when the quantized height would cross a consumer's bound but
    the real cap still fits - so a cap of, say, 120 under a 127-row bound
    rides at the bound (rows = max_rows, here 127) instead of failing at
    the quantized 128. ``min_cols`` widens the
    ordinal padding (capped at MAX_CHILDREN) so callers can opt INTO a
    shared width class across trees whose natural widths differ - the
    test suite pads every depth-varying tree to one (rows, cols) class
    and so pays ONE engine trace instead of one per tree; perf callers
    omit it and keep the tightest class."""
    t = child_threshold_table(params, cap)
    rows = -(-(cap + 1) // 16) * 16
    if max_rows is not None and rows > max_rows >= cap + 1:
        rows = max_rows
    cols = min(MAX_CHILDREN, -(-t.shape[1] // 16) * 16)
    if min_cols is not None:
        cols = min(MAX_CHILDREN, max(cols, int(min_cols)))
    out = np.full((rows, max(cols, t.shape[1])), -1, np.int32)
    out[: t.shape[0], : t.shape[1]] = t
    return out


@functools.partial(
    jax.jit,
    static_argnames=(
        "stack_size", "thresholds", "max_steps", "lanes", "min_idle_div",
    ),
)
def _uts_dfs(
    roots_state,  # (5, P) u32 - subtree roots, all at BFS depth d0
    roots_count,  # (P,) i32 - exact child counts (all >= 1)
    tab,  # (D+1, K) i32 runtime threshold table ((1, 1) dummy for FIXED)
    gen_mx,  # () i32 - FIXED-shape depth guard (unused on the table path)
    d0,  # () i32 - BFS depth of the roots
    nroots,  # () i32 - REAL root count R (arrays are padded to a common
    # quantum P >= R + nlanes so different trees share one compile AND the
    # refill window dynamic_slice is always in bounds)
    stack_size: int,
    thresholds,  # static ints (FIXED fast path) or None (runtime table)
    max_steps: int,
    lanes: tuple,
    min_idle_div: int = 8,
):
    S = stack_size
    nlanes = lanes[0] * lanes[1]
    R = nroots

    # Refill threshold: the gather+cumsum claim is much more expensive than
    # one SHA-1 step, so the hot expansion loop runs refill-free (inner
    # while) until this many lanes are idle; the outer loop then claims
    # roots for all of them at once. Imbalance cost is bounded by
    # min_idle/nlanes per refill round; refill wall cost by R/min_idle
    # rounds - min_idle_div trades the two.
    refill_min_idle = max(64, nlanes // min_idle_div)

    refill_fn = make_refill(lanes, d0)

    def refill(sp, next_root, st0, ch0, cn0, dp0):
        return refill_fn(
            roots_state, roots_count, R, sp, next_root, st0, ch0, cn0, dp0
        )

    run = make_traversal(
        S, lanes, thresholds, gen_mx, refill_min_idle, max_steps, refill, R,
        table=tab if thresholds is None else None,
    )
    sp, next_root, nodes, leaves, maxd, steps = run()
    return (
        # Per-lane planes, not totals: totals are summed on the host in
        # int64 so trees beyond 2^31 total nodes (T1XXL's 4.23B) count
        # correctly while per-lane counters stay comfortably in int32.
        nodes,
        leaves,
        maxd,
        steps,
        jnp.any(sp >= 0) | (next_root < R),
    )


def _host_seed(params: UTSParams, target_roots: int):
    """Vectorized BFS of the tree top with numpy SHA-1 over whole levels.

    Returns (host_nodes, host_leaves, host_maxd, d0, roots_state (5,R) u32,
    roots_count (R,) i32). Roots all sit at depth d0 and have count >= 1;
    leaf frontier nodes are counted host-side.
    """
    def counts_of(state5, depth: int) -> np.ndarray:
        # Per-level thresholds from the depth's branching factor: one code
        # path covers FIXED and every depth-varying shape exactly.
        ts = np.asarray(
            _thresholds_for_b(_branching(params, depth)), np.int32
        )
        if ts.size == 0:
            return np.zeros(state5[0].shape, np.int32)
        r = (state5[4] & np.uint32(0x7FFFFFFF)).astype(np.int32)
        return (r[:, None] >= ts[None, :]).sum(axis=1, dtype=np.int32)

    # Root state: SHA1(16 zero bytes || BE32(seed)) per the UTS spec
    # (models/uts.py root_state).
    seed_words = [np.zeros(1, np.uint32) for _ in range(4)]
    seed_words.append(np.full(1, params.root_seed, np.uint32))
    w16 = seed_words + [
        np.full(1, 0x80000000, np.uint32),
        *[np.zeros(1, np.uint32) for _ in range(9)],
        np.full(1, 20 * 8, np.uint32),
    ]
    state5 = list(_sha1_block(w16, np))

    host_nodes = 0
    host_leaves = 0
    host_maxd = 0
    depth = 0
    while True:
        n = state5[0].shape[0]
        counts = counts_of(state5, depth)
        host_nodes += n
        host_maxd = max(host_maxd, depth) if n else host_maxd
        nonleaf = counts > 0
        host_leaves += int((~nonleaf).sum())
        total = int(counts.sum())
        if total == 0:
            return host_nodes, host_leaves, host_maxd, depth, None, None
        if n >= target_roots:
            # Hand the non-leaf frontier to the device. Frontier leaves were
            # already counted above; roots themselves were counted as nodes.
            # LPT order: biggest child counts first, so the large subtrees
            # are claimed (and balanced over lanes) early and the drain tail
            # is short - classic longest-processing-time scheduling. Totals
            # are order-independent; only steps/lane-efficiency change.
            rs = [s[nonleaf] for s in state5]
            rc = counts[nonleaf]
            order = np.argsort(-rc, kind="stable")
            rs = [s[order] for s in rs]
            rc = rc[order]
            return (
                host_nodes, host_leaves, host_maxd, depth,
                np.stack(rs).astype(np.uint32), rc.astype(np.int32),
            )
        # Expand the whole level at once.
        parent = np.repeat(np.arange(n), counts)
        starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
        rank = (np.arange(total) - starts[parent]).astype(np.uint32)
        state5 = list(
            _sha1_child([s[parent] for s in state5], rank, np)
        )
        depth += 1


def uts_vec(
    params: UTSParams,
    target_roots: int = 16 * NLANES,
    max_steps: Optional[int] = None,
    device=None,
    lanes: Tuple[int, int] = LANES,
    min_idle_div: int = 8,
    depth_bound: Optional[int] = None,
    stack_pad: Optional[int] = None,
    timing_reps: Optional[int] = None,
    table_cols: Optional[int] = None,
) -> dict:
    """Run UTS with the vectorized DFS engine; returns counts + timing info.

    The host BFS-expands the tree top until >= target_roots frontier nodes
    (counting that part itself), then the device traverses the subtrees,
    lanes claiming roots from the shared queue as they drain.

    All GEO shapes are supported: FIXED uses the depth-independent
    threshold fast path; LINEAR/CYCLIC get exact per-depth threshold
    tables with a shape-derived depth cap; EXPDEC (whose branching decays
    but never reaches zero) uses ``depth_bound`` (default 8*gen_mx) and
    the run fails loudly if the tree actually reaches the bound."""
    import time

    t_seed = time.perf_counter()
    host_nodes, host_leaves, host_maxd, d0, roots_state, roots_count = (
        _host_seed(params, target_roots)
    )
    seed_seconds = time.perf_counter() - t_seed
    result = {
        "host_seed_nodes": host_nodes,
        "roots": 0 if roots_count is None else int(roots_count.shape[0]),
        "seed_seconds": seed_seconds,
    }
    if roots_count is None:
        result.update(
            nodes=host_nodes, leaves=host_leaves, max_depth=host_maxd, steps=0
        )
        return result
    if max_steps is None:
        max_steps = (1 << 31) - 1
    # Pad to PAD_QUANTUM (>= R + nlanes): the refill window dynamic_slice
    # never runs off the end, and trees with different root counts land
    # on the SAME padded shape, sharing one compiled engine.
    nlanes = lanes[0] * lanes[1]
    R = int(roots_count.shape[0])
    padn = -(-(R + nlanes) // PAD_QUANTUM) * PAD_QUANTUM
    pstate = np.zeros((5, padn), np.uint32)
    pstate[:, :R] = roots_state
    pcount = np.zeros(padn, np.int32)
    pcount[:R] = roots_count
    args = (jnp.asarray(pstate), jnp.asarray(pcount))
    derived = depth_cap(params)
    if derived is None:  # EXPDEC: caller-chosen bound, validated below
        cap = depth_bound if depth_bound is not None else 8 * params.gen_mx
        bounded = True
    elif depth_bound is not None and depth_bound < derived:
        # An explicit bound below the shape's own cap shrinks the stack
        # for known-shallow trees - and gets the same loud validation.
        cap = depth_bound
        bounded = True
    else:
        cap = derived
        bounded = False
    if params.shape == FIXED and not bounded:
        thr = tuple(int(t) for t in child_thresholds(params.b0))
        stack_size = max(1, params.gen_mx - d0)
        tabnp = np.zeros((1, 1), np.int32)  # unused dummy input
    else:
        # Runtime-table path: values are an input, so all trees with the
        # same padded table shape + stack height share one compile.
        thr = None
        # table_cols (like stack_pad) opts into a shared width class.
        tabnp = padded_threshold_table(params, cap, min_cols=table_cols)
        # Pushed frames hold non-leaf nodes only; for shapes whose cap is
        # exact the deepest non-leaf sits at cap-2, so the tight height is
        # cap-1-d0 (every extra level costs select/store work per step).
        stack_size = max(
            1, (cap - d0) if bounded else (cap - 1 - d0)
        )
    if stack_pad is not None:
        # Opt-in: pad the stack so differently-shaped trees share one
        # compiled engine (taller stacks cost select/store work per step,
        # so the perf path keeps the tight height).
        stack_size = max(stack_size, int(stack_pad))
    args = args + (
        jnp.asarray(tabnp), jnp.int32(params.gen_mx), jnp.int32(d0),
        jnp.int32(R),
    )
    kw = dict(
        stack_size=stack_size,
        thresholds=thr,
        max_steps=max_steps,
        lanes=tuple(lanes),
        min_idle_div=min_idle_div,
    )
    if device is not None:
        args = tuple(jax.device_put(a, device) for a in args)
    on_tpu = (
        device.platform == "tpu" if device is not None
        else jax.default_backend() == "tpu"
    )
    (nodes, leaves, maxd, steps, unfinished), dev_nodes, dt = _timed_best(
        lambda: _uts_dfs(*args, **kw),
        resolve_timing_reps(timing_reps, on_tpu),
    )
    if bool(unfinished):
        raise RuntimeError(f"uts_vec ran out of steps ({max_steps})")
    if bounded and int(np.asarray(maxd).max()) >= cap:
        raise RuntimeError(
            f"tree reached the depth bound ({cap}): counts beyond it are "
            "truncated - rerun with a larger depth_bound"
        )
    nlanes = lanes[0] * lanes[1]
    result.update(
        nodes=host_nodes + dev_nodes,
        leaves=host_leaves + int(np.asarray(leaves).sum(dtype=np.int64)),
        max_depth=max(host_maxd, int(np.asarray(maxd).max())),
        steps=int(steps),
        device_nodes=dev_nodes,
        device_seconds=dt,
        nodes_per_sec=dev_nodes / dt if dt > 0 else float("inf"),
        lane_efficiency=dev_nodes / (int(steps) * nlanes) if steps else 0.0,
    )
    return result


if __name__ == "__main__":  # pragma: no cover
    import sys

    from ..models.uts import T1, T1L, T3

    name = sys.argv[1] if len(sys.argv) > 1 else "T3"
    params = {"T1": T1, "T1L": T1L, "T3": T3}[name]
    print(uts_vec(params))
