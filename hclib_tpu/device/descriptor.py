"""Task-descriptor ABI and host-side task-graph builder.

A task is a fixed row of 16 int32 words - the device replacement for the
reference's heap task struct + promise waiter lists (inc/hclib-task.h:32-44,
inc/hclib-promise.h:76-90). Dependencies are inverted relative to the
reference: instead of tasks registering on promises, each task carries a
*dependency counter* and every task lists its *successors*; completing a task
decrements each successor's counter and pushes those that reach zero onto the
ready ring. (The reference's one-at-a-time registration walk exists to avoid
locks on the waiter list; on-device, the scheduler loop is single-threaded
per core, so plain counters are the natural design.)

Word layout (all int32):

    0  F_FN       kernel-table index (what to run)
    1  F_DEP      remaining unsatisfied dependencies (runnable at 0)
    2  F_SUCC0    inline successor task index, or NO_TASK
    3  F_SUCC1    inline successor task index, or NO_TASK
    4  F_CSR_OFF  offset into the successor-CSR array (extra successors)
    5  F_CSR_N    number of CSR successors
    6..11 F_A0+i  six argument words (meaning defined by the kernel)
    12 F_OUT      output value slot (index into the int32 value buffer)
    13 F_HOME     home device (flat mesh index) of a migrated task, or -1.
                  A row with F_HOME >= 0 is a *traveling copy*: a proxy row
                  F_HROW still exists on device F_HOME holding the real
                  successor links, and completing this copy forwards its
                  out-slot value home via a remote-completion active message
                  (device/resident.py) - the TPU re-design of the reference
                  thief taking dependency-bearing tasks out of a victim's
                  deque (src/hclib-deque.c:75-106), where shared memory made
                  links location-transparent.
    14 F_HROW     proxy row index on device F_HOME (valid iff F_HOME >= 0)
    15 F_VMASK    bitmask of arg words carrying *dereferenced values* (a
                  migrated task's value-slot args are resolved at export and
                  rehydrated into local slots at install)

Static DAGs (Cholesky, Smith-Waterman) are built host-side with
``TaskGraphBuilder``; dynamic tasks (fib, UTS) are allocated on-device by
kernels via ``KernelContext.spawn``.

Injection-ring row extension (multi-tenant ingress, device/tenants.py):
ring rows are padded to 256 words (``RING_ROW``, device/inject.py) so any
row offset DMA-aligns, and the pad words directly above the descriptor
ABI carry *transport metadata* the scheduler never copies
(``install_descriptor`` reads exactly ``DESC_WORDS`` words):

    16 TEN_ID      tenant lane index of an injected row (0 = default lane)
    17 TEN_EXPIRED nonzero = the row's admission deadline passed while it
                   sat on the ring; the in-kernel tenant poll drops it
                   (counted, a ``TenantExpired`` record) instead of
                   installing it
    18 TEN_DEADLINE_MS  the row's REMAINING admission-deadline budget in
                   milliseconds (0 = no deadline), stamped by the host at
                   checkpoint export and re-armed against the resuming
                   clock - deadlines survive a cut as remaining budget,
                   never as stale wall-clock instants. Host-only: the
                   device poll never reads it.
    19 TEN_TOKEN   submit token of a tracked request (0 = fire-and-forget
                   row, no completion published). Stamped at admission by
                   the tenant front door when egress is enabled
                   (device/egress.py): the egress-enabled inject poll
                   records it per installed row and the retirement-time
                   mailbox publish carries it back to the host, where it
                   keys the ``Future`` ledger (``FutureTable``).
    20 TEN_ADMIT_ROUND  admit-round stamp of the request, in the stream's
                   cumulative scheduler-round timebase (device/telemetry
                   .py): the host pump stamps the round gauge it last saw
                   echoed (``TenantTable.set_admit_round``), the
                   telemetry-enabled install path copies it into the
                   per-row stamp table, and retirement folds
                   ``retire - admit`` into the on-device latency
                   histogram. 0 = unstamped (telemetry off, or the
                   stream's first entry). A nonzero stamp is PRESERVED by
                   the pump on re-publication, so residue re-published
                   after a checkpoint cut keeps its original admission
                   round (the round gauge itself rides the echoed
                   telemetry block across the cut).

Because the words ride the row itself, tenant identity - a residue
row's remaining deadline budget, and its submit token - survives every
path a row can travel: checkpoint residue export, ``reshard``'s
round-robin re-deal, and resume re-publication (which is what lets
futures re-attach across a cut via their resume tokens).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = [
    "DESC_WORDS",
    "NO_TASK",
    "F_FN",
    "F_DEP",
    "F_SUCC0",
    "F_SUCC1",
    "F_CSR_OFF",
    "F_CSR_N",
    "F_A0",
    "F_OUT",
    "F_HOME",
    "F_HROW",
    "F_VMASK",
    "RING_ROW",
    "TEN_ID",
    "TEN_EXPIRED",
    "TEN_DEADLINE_MS",
    "TEN_TOKEN",
    "TEN_ADMIT_ROUND",
    "TaskGraphBuilder",
]

DESC_WORDS = 16
NO_TASK = -1

F_FN = 0
F_DEP = 1
F_SUCC0 = 2
F_SUCC1 = 3
F_CSR_OFF = 4
F_CSR_N = 5
F_A0 = 6  # args occupy words 6..11
F_OUT = 12
F_HOME = 13
F_HROW = 14
F_VMASK = 15
NUM_ARGS = 6

# Injection-ring row width: descriptors padded to 1024 B so any row
# offset is a legal dynamic DMA offset (Mosaic wants coarse alignment).
# Canonical home of the constant device/inject.py and device/resident.py
# share (both re-export it for their callers).
RING_ROW = 256

# Ring-row transport metadata (words beyond DESC_WORDS; see module
# docstring). Valid only on RING_ROW-padded injection rows - task-table
# rows are DESC_WORDS wide and never carry them.
TEN_ID = 16
TEN_EXPIRED = 17
TEN_DEADLINE_MS = 18
TEN_TOKEN = 19
TEN_ADMIT_ROUND = 20


class TaskGraphBuilder:
    """Builds the host-side arrays for a static task DAG.

    ``add(fn, args, deps=[...])`` returns the new task's index; ``deps`` are
    indices of tasks that must complete first (the builder fills dep counters
    and successor lists - inline first, CSR overflow after).
    """

    def __init__(self) -> None:
        self._rows: List[List[int]] = []
        self._succs: List[List[int]] = []  # successor indices per task
        self._reserved_values = 0

    def add(
        self,
        fn: int,
        args: Sequence[int] = (),
        deps: Sequence[int] = (),
        out: int = 0,
    ) -> int:
        if len(args) > NUM_ARGS:
            raise ValueError(f"at most {NUM_ARGS} args per task, got {len(args)}")
        idx = len(self._rows)
        row = [0] * DESC_WORDS
        row[F_FN] = int(fn)
        row[F_DEP] = len(deps)
        row[F_SUCC0] = NO_TASK
        row[F_SUCC1] = NO_TASK
        row[F_HOME] = NO_TASK  # local task (no migration home-link)
        for i, a in enumerate(args):
            row[F_A0 + i] = int(a)
        row[F_OUT] = int(out)
        self._rows.append(row)
        self._succs.append([])
        for d in deps:
            self._succs[d].append(idx)
        return idx

    @property
    def num_tasks(self) -> int:
        return len(self._rows)

    def reserve_values(self, n: int) -> None:
        """Declare slots [0, n) as host-owned: they are staged into the
        kernel (even if preset to zero) and the device allocator/row blocks
        start above them. Out slots already reserve themselves; use this for
        input-only or deliberately-zero slots."""
        self._reserved_values = max(self._reserved_values, int(n))

    def finalize(self, capacity: Optional[int] = None, succ_capacity: Optional[int] = None):
        """Returns (tasks, succ_csr, ready, counts0) numpy arrays sized to
        ``capacity`` tasks (extra rows are free slots for on-device spawns).

        counts0 = [head, tail, alloc, pending, value_alloc, 0, 0, 0].
        """
        n = len(self._rows)
        capacity = capacity or max(64, n)
        if n > capacity:
            raise ValueError(f"{n} tasks exceed capacity {capacity}")
        tasks = np.zeros((capacity, DESC_WORDS), dtype=np.int32)
        csr: List[int] = []
        for idx, row in enumerate(self._rows):
            succ = self._succs[idx]
            r = list(row)
            if len(succ) > 0:
                r[F_SUCC0] = succ[0]
            if len(succ) > 1:
                r[F_SUCC1] = succ[1]
            extra = succ[2:]
            r[F_CSR_OFF] = len(csr)
            r[F_CSR_N] = len(extra)
            csr.extend(extra)
            tasks[idx] = r
        succ_capacity = succ_capacity or max(64, len(csr))
        if len(csr) > succ_capacity:
            raise ValueError("successor CSR overflow")
        succ_arr = np.full(succ_capacity, NO_TASK, dtype=np.int32)
        if csr:
            succ_arr[: len(csr)] = csr
        # Ready ring: initially-runnable tasks in index order.
        ready0 = [i for i, row in enumerate(self._rows) if row[F_DEP] == 0]
        ring = np.full(capacity, NO_TASK, dtype=np.int32)
        ring[: len(ready0)] = ready0
        counts = np.zeros(8, dtype=np.int32)
        counts[0] = 0  # head
        counts[1] = len(ready0)  # tail
        counts[2] = n  # alloc cursor (next free descriptor row)
        counts[3] = n  # pending (tasks not yet executed)
        # Start on-device value allocation past every host-assigned out slot
        # (and any reserve_values declaration) so alloc_values/row blocks
        # never alias a host slot.
        counts[4] = max(
            1 + max((row[F_OUT] for row in self._rows), default=-1),
            self._reserved_values,
        )
        return tasks, succ_arr, ring, counts
