"""Host -> resident-kernel task injection: streaming graphs over an HBM ring.

The reference can hand new work to a running runtime from outside: an AM
handler materializes a task on a remote PE mid-execution
(modules/openshmem-am/src/hclib_openshmem-am.cpp:64-123), and hclib_async
may be called while workers run. The megakernel's task table, by contrast,
was sealed at launch. This module adds the missing channel: an **injection
ring** in HBM that the scheduler polls *from inside the kernel*:

- ring[R, 256] int32: descriptor rows padded to 1024 B so any row offset is
  a legal dynamic DMA offset (Mosaic wants coarse alignment); row words
  0..15 are the standard descriptor ABI (device/descriptor.py).
- ctl[8] int32: [0]=tail (total rows ever appended), [1]=close flag,
  [2]=device-consumed cursor (echoed back), [3]=host abort word - polled
  by the kernel INSIDE its round loop, [4] echoes the round the abort
  was observed, [5]=host quiesce word + [6]=its executed-count threshold
  (checkpoint builds only, see ``quiesce()``; the output's [5] echoes
  the round the quiesce was observed, -1 = never). This driver uploads
  a fresh ctl copy per entry, so an abort
  lands at the next ENTRY boundary and the in-kernel poll then bounds the
  final entry to about one round; the per-round ctl re-read is the device
  half a zero-copy pinned-host producer would need for true mid-quantum
  aborts (same status as the ring's pinned-production mode above).
- Write ordering (the fence contract): the producer writes descriptor rows
  FIRST, then bumps tail - release semantics. The kernel reads tail, then
  DMAs only rows below it - acquire semantics; a row is never read before
  the tail that published it.
- The kernel interleaves scheduler quanta with ring polls, installing new
  rows through the same row-allocation path spawns use, and reports its
  consumed count back through the aliased ctl output.

Multi-tenant mode (``tenants=``, device/tenants.py): the ring is
partitioned into per-tenant contiguous regions, each with its own
tail/consumed cursors in a per-tenant ``tctl[T, 8]`` control block, and
the in-kernel poll becomes a **weighted round-robin** over the lanes -
at most ``weight`` rows per lane per poll, start lane rotating every
round, rows host-marked expired dropped with a counted TR_TENANT record,
and total installs bounded by the scheduler's live ``headroom()`` so a
full task table turns into ring backpressure instead of an overflow.
Admission (quotas, token buckets, deadlines, poison quarantine) is the
host half, in device/tenants.py; ``submit()`` below is its entry point.
A ``tenants=None`` build compiles none of this - no extra inputs,
outputs, or scratch - and is bit-identical to the single-firehose path
(the perf_regression ``ingress-overhead`` guard pins it).

Execution model: ``StreamingMegakernel.run_stream`` re-enters the kernel in
bounded quanta; each entry drains everything available (including rows that
appear mid-entry: the poll runs between quanta INSIDE the kernel) and
returns when there is nothing left and the stream is not yet closed. Host
threads may call ``inject()`` at any time; ``close()`` lets the final entry
drain and exit. On a directly-attached TPU VM the same ring layout admits
zero-copy pinned-host production (host writes rows then tail over PCIe;
the in-kernel poll is the consumer side already); through a tunnel-attached
chip (this dev environment) physical concurrent writes are not reachable,
so delivery lands at entry boundaries while the in-kernel poll/drain path
is exercised by pre-published rows discovered mid-entry
(tests/test_inject.py).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..runtime import resilience
from ..runtime.resilience import CancelledError, StallError
from ..runtime.clockprobe import EpochBracket
from ..runtime.env import env_bool
from .descriptor import (
    DESC_WORDS,
    F_FN,
    F_OUT,
    NO_TASK,
    RING_ROW,
    TEN_ADMIT_ROUND,
    TEN_EXPIRED,
    TEN_ID,
    TEN_TOKEN,
    TaskGraphBuilder,
)
from .egress import (
    EC_CONSUMED,
    EC_INFLIGHT,
    EC_PARK_COUNT,
    EC_PARK_HEAD,
    EC_PARKED,
    EC_WRITE,
    EGR_FN,
    EGR_OK,
    EGR_SLOT,
    EGR_STATUS,
    EGR_T_ADMIT,
    EGR_T_SPANS,
    EGR_TEN,
    EGR_TOKEN,
    EGR_VALUE,
    EGR_WORDS,
    TOKEN_LIMIT,
    EgressProtocolError,
)
from .megakernel import (
    C_EXECUTED,
    C_HEAD,
    C_OVERFLOW,
    C_PENDING,
    C_TAIL,
    C_VALLOC,
    Megakernel,
)
from .telemetry import (
    LAT_ADMIT,
    LAT_BUCKETS,
    LAT_FIRE,
    LAT_INSTALL,
    LAT_WORDS,
    TG_BACKLOG,
    TG_ENTRIES,
    TG_INSTALLS,
    TG_PARKED,
    TG_RETIRES,
    TG_ROUNDS,
    unpack_spans,
)
from .tenants import (
    TC_CONSUMED,
    TC_DROPPED,
    TC_EXPIRED,
    TC_INSTALLED,
    TC_PAUSE,
    TC_TAIL,
    TC_WEIGHT,
    Admission,
    TenantTable,
    build_row,
    normalize_tenants,
)
from .tracebuf import (
    NullTracer,
    TR_ABORT,
    TR_CKPT,
    TR_EGRESS,
    TR_INJECT,
    TR_LATENCY,
    TR_QUIESCE,
    TR_TENANT,
    Tracer,
    trace_info,
)

__all__ = ["StreamingMegakernel", "RING_ROW"]


class StreamingMegakernel:
    """Megakernel + injection ring: a resident scheduler whose task supply
    is open-ended (the streaming/AM substrate).

    Relationship to the unified runner: ``ResidentKernel(inject=True)``
    subsumes this capability on device meshes (injection composes there
    with stealing and PGAS in one kernel, and ``dryrun_multichip``
    exercises exactly that). This class remains the single-device,
    no-mesh specialization whose host loop supports LIVE re-entrant
    production (inject()/close() from any thread between entries).

    ``mk`` supplies kernels/capacities; the injection ring holds
    ``ring_capacity`` rows. The ring is a linear (non-wrapping) append log
    per stream: capacity bounds TOTAL injected tasks per run_stream (keeps
    the producer/consumer index algebra trivial; streams needing more roll
    over to a fresh run_stream).

    ``tenants=`` (the multi-tenant front door, device/tenants.py): an int
    N, a sequence of TenantSpec/str/dict lane specs, or a prebuilt
    TenantTable (deterministic-clock tests build their own). None reads
    the ``HCLIB_TPU_TENANTS*`` env spelling; False forces single-firehose
    mode regardless of env. With lanes enabled the ring splits into
    per-tenant regions of ``ring_capacity // N`` rows (rounded up to
    8-row DMA chunks), producers go through ``submit()`` for a typed
    ``Admission`` verdict, and the in-kernel poll runs weighted
    round-robin over the lanes.
    """

    def __init__(self, mk: Megakernel, ring_capacity: int = 1024,
                 tenants=None, telemetry=None) -> None:
        self.mk = mk
        # Rounded up to a whole 8-row chunk: the kernel fetches the ring in
        # 8-row DMAs, and the final chunk must not run off the array.
        self.ring_capacity = -(-int(ring_capacity) // 8) * 8
        if isinstance(tenants, TenantTable):
            self.tenants: Optional[TenantTable] = tenants
        else:
            specs = normalize_tenants(tenants)
            if specs is None:
                self.tenants = None
            else:
                region = -(-self.ring_capacity // (8 * len(specs))) * 8
                self.tenants = TenantTable(specs, region)
        if self.tenants is not None:
            # The ring is exactly the concatenation of the lane regions.
            self.ring_capacity = (
                len(self.tenants) * self.tenants.region_rows
            )
        # Completion-mailbox egress (device/egress.py): compiled into
        # the kernel only when the tenant table is egress-enabled - a
        # mailbox ring + park buffer + ectl cursor block + per-task-row
        # token table ride as four extra SMEM in/out pairs, retirements
        # publish EGR rows through the complete_hook seam, and the
        # driver drains both regions (resolving futures) after every
        # entry. Egress-off builds compile ZERO of it - no extra
        # operands, no extra words - and stay bit-identical to the
        # pre-egress kernel (tests/test_serving.py pins the lowered
        # text).
        self._egress = (
            self.tenants.egress if self.tenants is not None else None
        )
        # Live telemetry plane (ISSUE 19, device/telemetry.py):
        # per-row lifecycle stamps + per-tenant on-device latency
        # histograms + a live-gauge row, riding two extra host-seeded/
        # echoed SMEM pairs (the ctl-echo discipline) so the host can
        # scrape them MID-STREAM (telemetry_snapshot / TelemetryPoller).
        # Requires an egress-enabled tenant stream: the latency fold
        # runs at the egress publish hook, keyed by the retiring row's
        # tenant. None reads HCLIB_TPU_TELEMETRY; False forces off.
        # Off compiles ZERO of it - no extra operands, no hooks - and
        # stays bit-identical to the pre-telemetry kernel
        # (tests/test_telemetry.py pins the lowered text).
        if telemetry is None:
            telemetry = env_bool("HCLIB_TPU_TELEMETRY")
        self.telemetry = bool(telemetry)
        if self.telemetry and self._egress is None:
            raise ValueError(
                "telemetry needs an egress-enabled tenant stream (the "
                "latency histograms are per-tenant and fold at the "
                "egress publish hook): build with tenants= plus an "
                "EgressSpec, or set HCLIB_TPU_EGRESS_DEPTH"
            )
        # Last entry's echoed telemetry block + conversion state, under
        # self._lock (written by the driver thread, read by pollers).
        self._tele_seq = 0
        self._tele_snapshot: Optional[Dict[str, Any]] = None
        self._spans: Dict[int, Tuple[int, int, int]] = {}
        self._jitted: Dict[Any, Any] = {}
        self._pc_stats: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()
        self._pending_rows: List[np.ndarray] = []
        self._closed = False
        # Distinguishes a quiesce-induced close (undone by a same-object
        # resume) from an explicit close()/abort() (sticky).
        self._closed_by_quiesce = False
        self._abort_reason: Optional[str] = None
        self._abort_t: Optional[float] = None
        # Checkpoint quiesce (mk must be built with checkpoint=True):
        # requested threshold + the wall clock of the request, for the
        # quiesce-latency stat.
        self._quiesce_after: Optional[int] = None
        self._quiesce_t: Optional[float] = None
        # Abort-latency accounting (surfaced by stats_dict): filled by the
        # run_stream driver when the abort entry returns.
        self._stats: Dict[str, Any] = {
            "aborts": 0,
            "abort_reason": None,
            "abort_observed_round": None,
            "abort_latency_s": None,
            "abort_drain_executed": None,
            # Preempt-storm accounting (ISSUE 6): how many quiesce cuts
            # this stream object has taken and resumed through, so a
            # storm soak can assert every injected preemption actually
            # cut (and the MetricsRegistry can rate() the churn).
            "quiesces": 0,
            "resumes": 0,
            "last_quiesce_latency_s": None,
        }

    # ---- lifecycle (resilience: the ring must never stay open) ----

    def __enter__(self) -> "StreamingMegakernel":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        # Guarantee close() even when the producer body raised: an open
        # ring would leave run_stream (on any thread) re-entering forever
        # waiting for a close that never comes.
        self.close()
        return False

    def abort(self, reason: str = "aborted") -> None:
        """Host-side abort: stop accepting injections and stop the running
        stream. At its next entry boundary the driving run_stream
        publishes the ctl abort word and runs ONE final kernel entry - the
        round loop polls the word and exits within a bounded number of
        inner iterations, remaining rows dropped - then raises
        ``CancelledError``. Abort latency (wall time, observed round,
        tasks drained after the abort) is surfaced by ``stats_dict()``.
        (The in-kernel per-round poll is what a zero-copy pinned-host
        producer would need to land an abort mid-entry; this driver's
        per-entry ctl upload bounds latency at one entry + one round.)"""
        with self._lock:
            if self._abort_reason is None:
                self._abort_reason = str(reason)
                self._abort_t = time.monotonic()
            self._closed = True
            self._closed_by_quiesce = False

    def quiesce(self, after_executed: int = 0) -> None:
        """Host-side checkpoint request (``mk`` must be built with
        ``checkpoint=True``): at its next entry boundary the driving
        run_stream publishes the ctl quiesce word; the kernel observes it
        inside its round loop - once at least ``after_executed`` tasks
        have run (0: immediately; a positive k is the deterministic
        checkpoint-at-k spelling) - stops popping at that round boundary,
        and exits with its live scheduler state. run_stream then returns
        with ``info['quiesced']=True`` and ``info['state']`` (feed it to
        ``runtime.checkpoint.snapshot_stream`` / ``run_stream(
        resume_state=...)``), the ring closed so producers fail fast -
        preemption semantics: checkpoint, then stop."""
        if not self.mk.checkpoint:
            raise ValueError(
                "quiesce() needs Megakernel(checkpoint=True): the quiesce "
                "word is compiled into the round loop only then"
            )
        with self._lock:
            if self._quiesce_after is None:
                self._quiesce_after = max(0, int(after_executed))
                self._quiesce_t = time.monotonic()

    def stats_dict(self) -> dict:
        """Resilience counters for this stream (abort latency included).
        With tenant lanes enabled the snapshot folds in the per-tenant
        admission counters (``tenants.<id>.backlog/accepted/rejected``
        ...), so a StallError carrying these stats names the tenant that
        wedged the stream, not just "the stream"."""
        with self._lock:
            d = dict(self._stats)
        if self.tenants is not None:
            d["tenants"] = self.tenants.stats()
            if self.tenants.futures is not None:
                d["egress"] = self.tenants.futures.stats_dict()
        return d

    # ---- producer side (host; any thread) ----

    def inject(
        self,
        fn: int,
        args: Sequence[int] = (),
        out: int = 0,
        dep_count: int = 0,
        succ0: int = NO_TASK,
        succ1: int = NO_TASK,
    ) -> None:
        """Queue one descriptor for the stream (thread-safe; rows reach the
        device ring at the next entry boundary, or immediately on attached
        hosts writing the pinned ring directly). On a tenant-enabled
        stream this is sugar for ``submit()`` on the first (default)
        lane, raising if that lane rejects - quota-aware producers call
        ``submit`` directly and handle the Admission verdict."""
        if dep_count != 0:
            # A dependent injected row would wait on predecessors, but the
            # host has no way to wire successor edges INTO a row whose
            # device id is unknown until installation - nothing could ever
            # decrement it. (Successor edges OUT of injected rows, succ0/1
            # naming static-graph rows, are fine.)
            raise ValueError("injected tasks must have dep_count == 0")
        if self.tenants is not None:
            adm = self.submit(
                self.tenants.ids[0], fn, args=args, out=out,
                succ0=succ0, succ1=succ1,
            )
            if not adm:
                raise RuntimeError(
                    f"inject rejected by tenant lane "
                    f"{self.tenants.ids[0]!r}: {adm.reason}"
                )
            return
        row = build_row(fn, args, out, succ0, succ1)
        with self._lock:
            if self._closed:
                reason = self._abort_reason
                raise RuntimeError(
                    "stream closed" + (f" ({reason})" if reason else "")
                )
            self._pending_rows.append(row)

    def submit(
        self,
        tenant,
        fn: int,
        args: Sequence[int] = (),
        out: int = 0,
        succ0: int = NO_TASK,
        succ1: int = NO_TASK,
        deadline_s: Optional[float] = None,
        cancel_scope=None,
        wait: bool = False,
        wait_timeout_s: float = 30.0,
    ) -> Admission:
        """Admit one task into a tenant lane (thread-safe; needs a
        tenant-enabled stream). Returns the typed ``Admission`` verdict:
        ACCEPTED (inside the lane's in-flight budget; publishes at the
        next entry), QUEUED (over budget, host backlog has room), or
        REJECTED(reason) - the explicit backpressure signal.

        ``deadline_s``/``cancel_scope`` feed deadline-aware admission
        (device/tenants.py): explicit deadline wins, else the scope
        chain's nearest ``CancelScope.set_deadline``, else the lane's
        default. Expired-at-admission rejects on the spot; later
        expiries drop lazily (host pump or device poll, counted).

        ``wait=True`` converts the *transient* rejections - "rate" (the
        token bucket refills) and "backlog" (the pump drains the host
        queue) - into a blocking wait with bounded exponential backoff,
        up to ``wait_timeout_s`` or the submission's own deadline.
        Terminal rejections (ring budget, quarantine, cancellation,
        expiry, closed stream) return immediately either way."""
        if self.tenants is None:
            raise ValueError(
                "submit() needs tenant lanes: build the stream with "
                "tenants= (or set HCLIB_TPU_TENANTS)"
            )
        table = self.tenants
        table._lane(tenant)  # unknown tenants raise KeyError up front
        row = build_row(fn, args, out, succ0, succ1)
        deadline_at = table.resolve_deadline(
            tenant, deadline_s, cancel_scope
        )
        with self._lock:
            closed = self._closed
        if closed:
            return table.record_reject(tenant, "closed")
        if not wait:
            return table.admit(tenant, row, deadline_at, cancel_scope)
        # The timeout is a WALL-clock bound: an injected table clock
        # (deterministic tests) governs admission/deadline semantics but
        # must not be able to make "bounded wait" unbounded - a frozen
        # fake clock would otherwise never reach t_end while time.sleep
        # burns real time forever.
        t_end = time.monotonic() + float(wait_timeout_s)
        backoff = 0.0005
        while True:
            adm = table.admit(
                tenant, row, deadline_at, cancel_scope,
                record_reject=False,
            )
            if adm:
                return adm
            if adm.reason not in ("rate", "backlog"):
                return table.record_reject(tenant, adm.reason)
            if deadline_at is not None and table.clock() >= deadline_at:
                return table.record_reject(tenant, "expired")
            if time.monotonic() >= t_end:
                return table.record_reject(tenant, adm.reason)
            with self._lock:
                if self._closed:
                    return table.record_reject(tenant, "closed")
            time.sleep(backoff)
            backoff = min(backoff * 2, 0.05)
        assert False, "unreachable"

    def close(self) -> None:
        """No more injections: the stream drains and run_stream returns."""
        with self._lock:
            self._closed = True
            self._closed_by_quiesce = False

    # ---- kernel ----

    def _kernel(self, quantum: int, max_rounds: int, trace, *refs) -> None:
        # ``trace`` captured at _build time (pallas traces lazily; see
        # Megakernel._kernel).
        mk = self.mk
        ndata = len(mk.data_specs)
        ntrace = 1 if trace is not None else 0
        nten = 1 if self.tenants is not None else 0
        negr = 1 if (nten and self._egress is not None) else 0
        ntele = 1 if self.telemetry else 0
        depth = self._egress.depth if negr else 0
        park_cap = depth  # bounds tokened in-flight work (credit gate)
        # + ring, ctl (+ tctl, tenant lanes) (+ egr/park/ectl/etok,
        # egress) (+ tele/tlat, telemetry)
        n_in = 7 + ndata + nten + 4 * negr + 2 * ntele
        in_refs = refs[:n_in]
        # + ctl out (+ tctl echo) (+ egress echoes) (+ telemetry echoes)
        n_out = 5 + ndata + ntrace + nten + 4 * negr + 2 * ntele
        out_refs = refs[n_in : n_in + n_out]
        rest = refs[n_in + n_out :]
        nscratch = len(mk.scratch_specs)
        scratch_refs = rest[:nscratch]
        free, vfree, ctlbuf, rowbuf, isem = rest[nscratch:]
        tasks_in, succ, ready_in, counts_in, ivalues_in = in_refs[:5]
        ring, ctl_in = in_refs[5], in_refs[6]
        tctl_in = in_refs[7 + ndata] if nten else None
        if negr:
            egr_in, park_in, ectl_in, etok_in = in_refs[
                8 + ndata : 12 + ndata
            ]
        if ntele:
            tele_in, tlat_in = in_refs[12 + ndata : 14 + ndata]
        tasks, ready, counts, ivalues = out_refs[:4]
        ctl_out = out_refs[4]
        data = dict(zip(mk.data_specs.keys(), out_refs[5 : 5 + ndata]))
        tr = (
            Tracer(out_refs[5 + ndata], trace.capacity)
            if ntrace
            else NullTracer()
        )
        tctl_out = out_refs[5 + ndata + ntrace] if nten else None
        if negr:
            egr_out, park_out, ectl_out, etok_out = out_refs[
                6 + ndata + ntrace : 10 + ndata + ntrace
            ]
        if ntele:
            tele_out, tlat_out = out_refs[
                10 + ndata + ntrace : 12 + ndata + ntrace
            ]
        scratch = dict(zip(mk.scratch_specs.keys(), scratch_refs))

        def egress_complete(idx):
            """Completion-mailbox publish, run at task retirement (the
            complete_hook seam fires FIRST inside complete(), while the
            row's words are intact). Tokened rows (etok != 0) publish an
            EGR row into the mailbox; a full mailbox PARKS the row in
            the park ring instead - counted (EC_PARKED cumulative,
            EC_PARK_COUNT current), traced as TR_EGRESS, never dropped,
            never an OVF abort. The install-side credit gate bounds
            parked + in-flight below park_cap, so the park append here
            cannot overflow by construction. egress_reference is the
            executable spec - change one, change both."""
            packed = etok_out[idx]

            @pl.when(packed != 0)
            def _():
                token = jax.lax.rem(packed, jnp.int32(TOKEN_LIMIT))
                ten = packed // jnp.int32(TOKEN_LIMIT)
                slot = tasks[idx, F_OUT]
                write = ectl_out[EC_WRITE]
                room = depth - (write - ectl_out[EC_CONSUMED])
                if ntele:
                    # Lifecycle span (telemetry builds only): retire
                    # round == fire round (dispatch and completion are
                    # atomic within one inner round), so the EGR span
                    # word packs only (fire - install, install - admit)
                    # and the fold below uses the live round gauge as
                    # the retire stamp. unpack_spans / bucket_of /
                    # hist_fold_reference (device/telemetry.py) are the
                    # host spec of these three computations.
                    now = tele_out[0, TG_ROUNDS]
                    admit = tlat_out[idx, LAT_ADMIT]
                    spans = (
                        jnp.clip(
                            now - tlat_out[idx, LAT_INSTALL], 0, 0xFFFF
                        ) << 16
                    ) | jnp.clip(
                        tlat_out[idx, LAT_INSTALL] - admit, 0, 0xFFFF
                    )

                @pl.when(room > 0)
                def _():
                    s = jax.lax.rem(write, depth)
                    egr_out[s, EGR_STATUS] = jnp.int32(EGR_OK)
                    egr_out[s, EGR_TOKEN] = token
                    egr_out[s, EGR_TEN] = ten
                    egr_out[s, EGR_FN] = tasks[idx, F_FN]
                    egr_out[s, EGR_SLOT] = slot
                    egr_out[s, EGR_VALUE] = ivalues[slot]
                    if ntele:
                        egr_out[s, EGR_T_ADMIT] = admit
                        egr_out[s, EGR_T_SPANS] = spans
                    ectl_out[EC_WRITE] = write + 1

                @pl.when(room <= 0)
                def _():
                    n = ectl_out[EC_PARK_COUNT]
                    p = jax.lax.rem(
                        ectl_out[EC_PARK_HEAD] + n, park_cap
                    )
                    park_out[p, EGR_STATUS] = jnp.int32(EGR_OK)
                    park_out[p, EGR_TOKEN] = token
                    park_out[p, EGR_TEN] = ten
                    park_out[p, EGR_FN] = tasks[idx, F_FN]
                    park_out[p, EGR_SLOT] = slot
                    park_out[p, EGR_VALUE] = ivalues[slot]
                    if ntele:
                        park_out[p, EGR_T_ADMIT] = admit
                        park_out[p, EGR_T_SPANS] = spans
                    ectl_out[EC_PARK_COUNT] = n + 1
                    ectl_out[EC_PARKED] = ectl_out[EC_PARKED] + 1
                    tr.emit(TR_EGRESS, tr.now(), token, n + 1)

                if ntele:
                    # Histogram fold: log2 bucket of (retire - admit),
                    # branch-free (b = sum of threshold crossings; the
                    # last bucket is the counted overflow bucket). One
                    # event, two views: the per-tenant counter bump the
                    # poller scrapes, and the TR_LATENCY trace record.
                    d = jnp.maximum(now - admit, 0)
                    b = jnp.int32(0)
                    for k in range(1, LAT_BUCKETS):
                        b = b + (d >= (1 << k)).astype(jnp.int32)
                    tele_out[1 + ten, b] = tele_out[1 + ten, b] + 1
                    tele_out[0, TG_RETIRES] = (
                        tele_out[0, TG_RETIRES] + 1
                    )
                    tr.emit(TR_LATENCY, tr.now(), (ten << 16) | b, d)
                etok_out[idx] = jnp.int32(0)
                ectl_out[EC_INFLIGHT] = ectl_out[EC_INFLIGHT] - 1

        def tele_fire(idx):
            """Telemetry fire stamp (the _make_core fire_hook seam):
            runs at every dispatch site before the task body, so the
            egress fold inside complete_hook sees it."""
            tlat_out[idx, LAT_FIRE] = tele_out[0, TG_ROUNDS]

        def tele_round():
            """Telemetry round tick (the _make_core round_hook seam):
            advances the cumulative round gauge - the stream's
            timebase - and refreshes the point-in-time gauges."""
            tele_out[0, TG_ROUNDS] = tele_out[0, TG_ROUNDS] + 1
            tele_out[0, TG_BACKLOG] = counts[C_TAIL] - counts[C_HEAD]
            tele_out[0, TG_PARKED] = ectl_out[EC_PARK_COUNT]

        core = mk._make_core(
            succ, tasks, ready, counts, ivalues, data, scratch, free, vfree,
            tasks_in, ready_in, counts_in, ivalues_in, True,
            tracer=tr if tr.enabled else None,
            complete_hook=egress_complete if negr else None,
            fire_hook=tele_fire if ntele else None,
            round_hook=tele_round if ntele else None,
        )
        cap = mk.capacity

        core.stage()

        def install(row_slot) -> None:
            idx = core.install_descriptor(lambda w: rowbuf[row_slot, w])
            if ntele:
                # Lifecycle stamps: the ring row's host-stamped admit
                # round rides into the per-row table (0 = unstamped),
                # the install round is the live gauge, and installs
                # count - tracked and untracked alike.
                tlat_out[idx, LAT_ADMIT] = rowbuf[
                    row_slot, TEN_ADMIT_ROUND
                ]
                tlat_out[idx, LAT_INSTALL] = tele_out[0, TG_ROUNDS]
                tele_out[0, TG_INSTALLS] = tele_out[0, TG_INSTALLS] + 1
            if negr:
                # Stamp the submit token (packed token | tenant << 24)
                # onto the allocated task-table row so retirement knows
                # where to publish; count it in-flight for the credit
                # gate.
                token = rowbuf[row_slot, TEN_TOKEN]

                @pl.when(token != 0)
                def _():
                    etok_out[idx] = token + (
                        rowbuf[row_slot, TEN_ID] * jnp.int32(TOKEN_LIMIT)
                    )
                    ectl_out[EC_INFLIGHT] = ectl_out[EC_INFLIGHT] + 1

        def poll(consumed):
            """Acquire-read the ring: ctl first (tail publishes rows), then
            the rows below tail, fetched in 8-row chunks (Mosaic dynamic
            slices along the sublane-tiled dim must be 8-aligned).
            Returns (consumed', close_flag)."""
            cp = pltpu.make_async_copy(ctl_in, ctlbuf, isem.at[0])
            cp.start()
            cp.wait()
            tail = ctlbuf[0]
            close = ctlbuf[1]

            def chunk(c):
                base = (c // 8) * 8
                rp = pltpu.make_async_copy(
                    ring.at[pl.ds(base, 8)], rowbuf, isem.at[1]
                )
                rp.start()
                rp.wait()
                n = jnp.minimum(tail - c, 8 - (c - base))

                def ins(i, _):
                    install(c - base + i)
                    return 0

                jax.lax.fori_loop(0, n, ins, 0)
                return c + n

            consumed = jax.lax.while_loop(
                lambda c: c < tail, chunk, consumed
            )
            return consumed, close

        T = len(self.tenants) if nten else 0
        region = self.tenants.region_rows if nten else 0

        def tpoll(r):
            """The tenant-lane poll: weighted round-robin over the lane
            regions, start lane rotating with the round index. Per lane
            visit it installs at most ``weight`` rows, never more than
            the scheduler's live ``headroom()`` (a full task table turns
            into ring backpressure the host reads off the cursor echo,
            not an OVF_ROWS abort), drops rows the host marked expired
            (counted, a TR_TENANT record), and sweeps paused lanes -
            quarantine/cancel drains their published residue without
            installing. Cursors and cumulative counters live in the
            tctl echo (host-seeded, so they survive entries). Returns
            rows installed this poll. The global ctl acquire DMA
            (close/abort/quiesce words) stays with the caller.

            This scan IS the mesh-tenancy poll too: ``ResidentKernel``
            (tenants=) compiles the same semantics per device against
            its per-device tctl block (plus a quiesce freeze), and
            ``tenants.wrr_poll_reference`` is the shared executable
            spec both are tested against - change one, change all
            three."""
            newly = jnp.int32(0)
            for k in range(T):
                lane = jax.lax.rem(r + k, T)
                tail = tctl_out[lane, TC_TAIL]
                cons = tctl_out[lane, TC_CONSUMED]
                paused = tctl_out[lane, TC_PAUSE] != 0
                avail = tail - cons
                weight = tctl_out[lane, TC_WEIGHT]
                take = jnp.where(
                    paused,
                    0,
                    jnp.minimum(
                        jnp.minimum(weight, avail), core.headroom()
                    ),
                )
                if negr:
                    # Egress credit gate: tokened rows currently parked
                    # or in-flight never exceed park_cap, so a retiring
                    # row ALWAYS has a mailbox slot or a park slot - a
                    # full mailbox is ring backpressure (rows wait on
                    # their lanes, cursors stop advancing), never loss.
                    take = jnp.minimum(
                        take,
                        jnp.maximum(
                            jnp.int32(park_cap)
                            - ectl_out[EC_PARK_COUNT]
                            - ectl_out[EC_INFLIGHT],
                            0,
                        ),
                    )
                target = cons + take

                def chunk(carry, lane=lane, target=target):
                    c, inst, exp = carry
                    base = (c // 8) * 8
                    rp = pltpu.make_async_copy(
                        ring.at[pl.ds(lane * region + base, 8)], rowbuf,
                        isem.at[1],
                    )
                    rp.start()
                    rp.wait()
                    n = jnp.minimum(target - c, 8 - (c - base))

                    def ins(i, ie, c=c, base=base):
                        inst0, exp0 = ie
                        slot = c - base + i
                        expired = rowbuf[slot, TEN_EXPIRED] != 0

                        @pl.when(jnp.logical_not(expired))
                        def _():
                            install(slot)

                        one = jnp.int32(1)
                        return (
                            inst0 + jnp.where(expired, 0, one),
                            exp0 + jnp.where(expired, one, 0),
                        )

                    inst, exp = jax.lax.fori_loop(0, n, ins, (inst, exp))
                    return c + n, inst, exp

                c, inst, exp = jax.lax.while_loop(
                    lambda cr, target=target: cr[0] < target,
                    chunk,
                    (cons, jnp.int32(0), jnp.int32(0)),
                )
                tctl_out[lane, TC_CONSUMED] = jnp.where(paused, tail, c)
                tctl_out[lane, TC_DROPPED] = (
                    tctl_out[lane, TC_DROPPED]
                    + jnp.where(paused, avail, 0)
                )
                tctl_out[lane, TC_INSTALLED] = (
                    tctl_out[lane, TC_INSTALLED] + inst
                )
                tctl_out[lane, TC_EXPIRED] = (
                    tctl_out[lane, TC_EXPIRED] + exp
                )

                @pl.when((inst > 0) | (exp > 0))
                def _(lane=lane, inst=inst, exp=exp):
                    tr.emit(
                        TR_TENANT, tr.now(), (lane << 16) | inst, exp
                    )

                newly = newly + inst
            return newly

        def lanes_drained():
            d = jnp.bool_(True)
            for i in range(T):
                d = d & (
                    tctl_out[i, TC_CONSUMED] == tctl_out[i, TC_TAIL]
                )
            return d

        ckpt = mk.checkpoint

        def cond(carry):
            r, consumed, done, abr, qr = carry
            return jnp.logical_not(done) & (r < max_rounds)

        def body(carry):
            r, consumed, _, abr, qr = carry
            core.sched(quantum)
            if nten:
                # Tenant lanes: the global ctl acquire DMA still lands
                # every round (abort/close/quiesce words), but rows come
                # off the per-lane regions through the WRR poll; lane
                # cursors live in the tctl echo, not the loop carry.
                cp = pltpu.make_async_copy(ctl_in, ctlbuf, isem.at[0])
                cp.start()
                cp.wait()
                newly = tpoll(r)

                @pl.when(newly > 0)
                def _():
                    tr.emit(TR_INJECT, tr.now(), newly)

            else:
                c0 = consumed
                consumed, close = poll(consumed)

                @pl.when(consumed > c0)
                def _():
                    tr.emit(TR_INJECT, tr.now(), consumed - c0)

            # Host abort word (ctl[3]): re-read by the same acquire DMA as
            # the ring tail, so the abort lands INSIDE the round loop - a
            # running stream stops within one quantum + poll of the write,
            # pending work and unconsumed rows abandoned where they stand.
            aborted = ctlbuf[3] != 0

            @pl.when(aborted & (abr < 0))
            def _():
                tr.emit(TR_ABORT, tr.now(), r)

            abr = jnp.where(aborted & (abr < 0), r, abr)
            # Host quiesce word (ctl[5], checkpoint builds only; same
            # acquire DMA): observed once the cumulative executed count
            # passes ctl[6], the round loop stops popping at this round
            # boundary and exits WITH its state - unlike abort, nothing
            # is abandoned (pending rows, unconsumed ring rows, and the
            # consumed cursor all survive into the exported snapshot).
            if ckpt:
                qz = (ctlbuf[5] != 0) & (counts[C_EXECUTED] >= ctlbuf[6])

                @pl.when(qz & (qr < 0))
                def _():
                    tr.emit(TR_QUIESCE, tr.now(), r)

                qr = jnp.where(qz & (qr < 0), r, qr)
            else:
                qz = jnp.bool_(False)
            # Nothing runnable and nothing new: exit. The host re-enters
            # while the stream is open; a closed, drained stream is final.
            idle = counts[C_PENDING] == 0
            drained = lanes_drained() if nten else (consumed == ctlbuf[0])
            done = (idle & drained) | aborted | qz
            return r + 1, consumed, done, abr, qr

        if nten:
            # Lane cursors + cumulative counters: host-seeded per entry,
            # mutated in place by the WRR poll, echoed back at exit.
            for i in range(T):
                for w in range(8):
                    tctl_out[i, w] = tctl_in[i, w]
        if negr:
            # Mailbox/park/token staging: host-seeded per entry (the
            # tctl pattern - no aliasing), mutated in place by the
            # publish path, echoed back at exit for the host drain.
            for w in range(8):
                ectl_out[w] = ectl_in[w]

            def _cp_egr(i, _):
                for w in range(EGR_WORDS):
                    egr_out[i, w] = egr_in[i, w]
                return 0

            jax.lax.fori_loop(0, depth, _cp_egr, 0)

            def _cp_park(i, _):
                for w in range(EGR_WORDS):
                    park_out[i, w] = park_in[i, w]
                return 0

            jax.lax.fori_loop(0, park_cap, _cp_park, 0)

            def _cp_tok(i, _):
                etok_out[i] = etok_in[i]
                return 0

            jax.lax.fori_loop(0, cap, _cp_tok, 0)

            # Entry-start parked retry: the host consumed between
            # entries, so mailbox room may have opened - move parked
            # rows (FIFO, off EC_PARK_HEAD) into the mailbox while room
            # lasts. flush_parked_reference is the executable spec.
            def _flush(i, _):
                cnt = ectl_out[EC_PARK_COUNT]
                room = depth - (
                    ectl_out[EC_WRITE] - ectl_out[EC_CONSUMED]
                )

                @pl.when((cnt > 0) & (room > 0))
                def _():
                    h = ectl_out[EC_PARK_HEAD]
                    s = jax.lax.rem(ectl_out[EC_WRITE], depth)
                    for w in range(EGR_WORDS):
                        egr_out[s, w] = park_out[h, w]
                    for w in range(EGR_WORDS):
                        park_out[h, w] = jnp.int32(0)
                    ectl_out[EC_PARK_HEAD] = jax.lax.rem(
                        h + 1, park_cap
                    )
                    ectl_out[EC_PARK_COUNT] = cnt - 1
                    ectl_out[EC_WRITE] = ectl_out[EC_WRITE] + 1
                return 0

            jax.lax.fori_loop(0, park_cap, _flush, 0)
        if ntele:
            # Telemetry echo staging (the tctl pattern): host-seeded
            # per entry, mutated by the hooks and the egress fold,
            # echoed back at exit - the block the mid-run poller and
            # the checkpoint cut both read.
            def _cp_tele(i, _):
                for w in range(LAT_BUCKETS):
                    tele_out[i, w] = tele_in[i, w]
                return 0

            jax.lax.fori_loop(0, 1 + T, _cp_tele, 0)

            def _cp_tlat(i, _):
                for w in range(LAT_WORDS):
                    tlat_out[i, w] = tlat_in[i, w]
                return 0

            jax.lax.fori_loop(0, cap, _cp_tlat, 0)
        # Initial ctl fetch: the consumed cursor (slot 2) persists across
        # entries through the host-echoed ctl.
        cp0 = pltpu.make_async_copy(ctl_in, ctlbuf, isem.at[0])
        cp0.start()
        cp0.wait()
        _, consumed, _, abr, qr = jax.lax.while_loop(
            cond, body, (jnp.int32(0), ctlbuf[2], jnp.bool_(False),
                         jnp.int32(-1), jnp.int32(-1))
        )
        # Report progress: consumed count rides the aliased ctl output
        # (slot 2); tail/close/abort echo through; slot 4 reports the round
        # the abort word was first observed, slot 5 the round the quiesce
        # word was (-1: never).
        ctl_out[0] = ctlbuf[0]
        ctl_out[1] = ctlbuf[1]
        ctl_out[2] = consumed
        ctl_out[3] = ctlbuf[3]
        ctl_out[4] = abr
        ctl_out[5] = qr if ckpt else 0
        for i in range(6, 8):
            ctl_out[i] = 0
        if ckpt:
            @pl.when(qr >= 0)
            def _():
                tr.emit(
                    TR_CKPT, tr.now(), counts[C_PENDING],
                    ctlbuf[0] - consumed,
                )

    def _build(self, quantum: int, max_rounds: int):
        mk = self.mk
        ndata = len(mk.data_specs)
        smem = functools.partial(pl.BlockSpec, memory_space=pltpu.SMEM)
        anyspace = functools.partial(pl.BlockSpec, memory_space=pl.ANY)
        # ring AND ctl live in ANY (HBM): the kernel re-reads them by DMA
        # on every poll - the consumer side of the pinned-host production
        # path - instead of snapshotting them into SMEM at entry. The
        # tenant tctl block (host-published per entry, tiny) rides SMEM;
        # a tenants=None build compiles none of it.
        nten = 1 if self.tenants is not None else 0
        negr = 1 if (nten and self._egress is not None) else 0
        ntele = 1 if self.telemetry else 0
        depth = self._egress.depth if negr else 0
        T = len(self.tenants) if nten else 0
        in_specs = (
            [smem()] * 5 + [anyspace(), anyspace()] + [anyspace()] * ndata
            + [smem()] * nten + [smem()] * (4 * negr)
            + [smem()] * (2 * ntele)
        )
        data_shapes = [
            jax.ShapeDtypeStruct(s.shape, s.dtype)
            for s in mk.data_specs.values()
        ]
        ntrace = 1 if mk.trace is not None else 0
        out_shape = tuple(
            [
                jax.ShapeDtypeStruct((mk.capacity, DESC_WORDS), jnp.int32),
                jax.ShapeDtypeStruct((mk.capacity,), jnp.int32),
                jax.ShapeDtypeStruct((8,), jnp.int32),
                jax.ShapeDtypeStruct((mk.num_values,), jnp.int32),
                jax.ShapeDtypeStruct((8,), jnp.int32),  # ctl out
            ]
            + data_shapes
            + ([mk.trace.out_shape()] if ntrace else [])
            + ([jax.ShapeDtypeStruct((T, 8), jnp.int32)] if nten else [])
            + ([
                # mailbox ring, park ring, ectl cursor block, per-row
                # token table - host-seeded, echoed (the tctl pattern).
                jax.ShapeDtypeStruct((depth, EGR_WORDS), jnp.int32),
                jax.ShapeDtypeStruct((depth, EGR_WORDS), jnp.int32),
                jax.ShapeDtypeStruct((8,), jnp.int32),
                jax.ShapeDtypeStruct((mk.capacity,), jnp.int32),
            ] if negr else [])
            + ([
                # Telemetry: gauge row + per-tenant histograms, and the
                # per-row lifecycle stamp table - host-seeded, echoed
                # (the tctl pattern; device/telemetry.py).
                jax.ShapeDtypeStruct((1 + T, LAT_BUCKETS), jnp.int32),
                jax.ShapeDtypeStruct((mk.capacity, LAT_WORDS), jnp.int32),
            ] if ntele else [])
        )
        out_specs = tuple(
            [smem()] * 4 + [smem()] + [anyspace()] * ndata
            + [smem()] * ntrace + [smem()] * nten + [smem()] * (4 * negr)
            + [smem()] * (2 * ntele)
        )
        aliases = {0: 0, 2: 1, 3: 2, 4: 3}
        for i in range(ndata):
            aliases[7 + i] = 5 + i
        from .megakernel import VBLOCK

        return jax.jit(pl.pallas_call(
            functools.partial(self._kernel, quantum, max_rounds, mk.trace),
            out_shape=out_shape,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=list(mk.scratch_specs.values())
            + [
                pltpu.SMEM((mk.capacity + 1,), jnp.int32),
                pltpu.SMEM((mk.num_values // VBLOCK + 1,), jnp.int32),
                pltpu.SMEM((8,), jnp.int32),  # ctl staging
                pltpu.SMEM((8, RING_ROW), jnp.int32),  # row staging (8-row chunks)
                pltpu.SemaphoreType.DMA((2,)),
            ],
            input_output_aliases=aliases,
            interpret=mk.interpret,
        ))

    # ---- the stream driver ----

    def run_stream(
        self,
        builder: Optional[TaskGraphBuilder] = None,
        ivalues: Optional[np.ndarray] = None,
        data: Optional[Dict[str, Any]] = None,
        quantum: int = 1 << 10,
        max_rounds: int = 64,
        poll_interval_s: float = 0.001,
        deadline_s: Optional[float] = None,
        cancel_scope=None,
        resume_state: Optional[Dict[str, Any]] = None,
    ) -> Tuple[np.ndarray, dict]:
        """Run the stream to completion: entries re-enter the resident
        scheduler while the host (any thread) injects; returns after
        close() once everything drained. Returns (ivalues, info).

        Resilience: ``deadline_s`` bounds the whole stream - past it the
        ring is closed and a structured ``StallError`` raises instead of
        re-entering forever (e.g. a producer that never calls close()).
        ``abort()`` from any thread stops the stream mid-quantum via the
        ctl abort word (see ``abort``) and raises ``CancelledError``;
        ``cancel_scope`` ties the stream to a host finish scope - the
        scope cancelling (e.g. root-finish cancellation, the watchdog's
        last rung) aborts the stream the same way, through a registered
        abort hook, so a device stream never outlives its cancelled scope.
        ANY exception escaping this driver closes the ring, so concurrent
        producers fail fast on their next inject() instead of queueing
        rows nobody will ever drain.

        Checkpoint/restore (``mk`` built with ``checkpoint=True``):
        ``quiesce()`` from any thread stops the stream at its next round
        boundary WITH its state - run_stream returns (ivalues, info) where
        ``info['quiesced']=True`` and ``info['state']`` is the resumable
        snapshot (tables, values, unconsumed ring rows). A later
        ``run_stream(resume_state=...)`` - on this object or a freshly
        built equivalent one - re-publishes the residue and continues the
        stream mid-graph (``builder`` and ``resume_state`` are mutually
        exclusive)."""
        if (builder is None) == (resume_state is None):
            raise ValueError(
                "run_stream wants exactly one of builder= (a fresh "
                "stream) or resume_state= (a checkpointed one)"
            )
        if resume_state is not None and (
            data is not None or ivalues is not None
        ):
            raise ValueError(
                "resume_state= carries its own data/ivalues; passing "
                "them too would be silently ignored"
            )
        unregister = None
        if cancel_scope is not None:
            # Register-then-replay (the one implementation, in
            # runtime/resilience.py): a cancel() racing this registration
            # still aborts the stream.
            unregister = resilience.bind_abort_to_scope(
                self.abort, cancel_scope
            )
        try:
            return self._run_stream(
                builder, ivalues, data, quantum, max_rounds,
                poll_interval_s, deadline_s, resume_state,
            )
        except BaseException:
            with self._lock:
                self._closed = True
            raise
        finally:
            if unregister is not None:
                unregister()

    @staticmethod
    def _drain_egress(table, egr, park, ectl, spans=None) -> int:
        """Consume the completion mailbox AND the park ring at an entry
        boundary (this driver IS the poller), resolving each row's
        future exactly once. Mutates the arrays in place: consumed
        mailbox slots re-zero and EC_CONSUMED catches up to EC_WRITE;
        parked rows resolve directly (they never occupied a mailbox
        slot) and the park ring empties. Draining both regions here is
        what makes a full mailbox unable to wedge quiesce or the
        drained exit. ``spans`` (telemetry builds): a dict collecting
        ``token -> (admit, install, fire)`` absolute rounds decoded off
        the EGR span words. Returns rows consumed."""
        futures = table.futures

        def _one(row):
            if spans is not None:
                spans[int(row[EGR_TOKEN])] = unpack_spans(
                    row[EGR_T_ADMIT], row[EGR_T_SPANS]
                )[:3]
            futures.resolve(int(row[EGR_TOKEN]), int(row[EGR_VALUE]))
            row[:] = 0

        depth = egr.shape[0]
        n = 0
        consumed = int(ectl[EC_CONSUMED])
        while consumed < int(ectl[EC_WRITE]):
            row = egr[consumed % depth]
            if int(row[EGR_STATUS]) != EGR_OK:
                raise EgressProtocolError(
                    f"mailbox slot {consumed % depth} consumed twice or "
                    f"never published (status {int(row[EGR_STATUS])})"
                )
            _one(row)
            consumed += 1
            n += 1
        ectl[EC_CONSUMED] = consumed
        head, cnt = int(ectl[EC_PARK_HEAD]), int(ectl[EC_PARK_COUNT])
        cap = park.shape[0]
        for k in range(cnt):
            row = park[(head + k) % cap]
            if int(row[EGR_STATUS]) != EGR_OK:
                raise EgressProtocolError(
                    f"park slot {(head + k) % cap} empty but counted "
                    f"(status {int(row[EGR_STATUS])})"
                )
            _one(row)
            n += 1
        ectl[EC_PARK_HEAD] = 0
        ectl[EC_PARK_COUNT] = 0
        return n

    # ---- live telemetry (ISSUE 19) ----

    def telemetry_snapshot(self) -> Optional[Dict[str, Any]]:
        """Thread-safe copy of the LAST entry's echoed telemetry block
        (None before the first telemetry entry completes): ``seq``
        (monotone snapshot counter), ``tele`` (the (1+T, LAT_BUCKETS)
        gauge+histogram block), ``rounds``/``entries`` (cumulative),
        and ``ns_per_round`` (rounds->wall conversion from the entry
        epoch brackets; None until a bracket with round progress
        lands). This is the :class:`~..device.telemetry.TelemetryPoller`
        source - call it from any thread while the stream runs."""
        with self._lock:
            if self._tele_snapshot is None:
                return None
            snap = dict(self._tele_snapshot)
        snap["tele"] = np.array(snap["tele"])
        return snap

    def telemetry_spans(self) -> Dict[int, Tuple[int, int, int]]:
        """``token -> (admit, install, fire)`` absolute rounds for every
        retirement drained so far (telemetry builds; retire == fire).
        tools/timeline.py joins these with Future submit/done wall
        stamps into Perfetto flow events."""
        with self._lock:
            return dict(self._spans)

    @staticmethod
    def _adopt_etok(table, etok, tasks) -> None:
        """Re-adopt installed-but-unretired submit tokens off a resumed
        snapshot's etok table: each packed word (token | tenant << 24)
        re-enters the futures ledger so the resumed stream's
        retirements resolve - and preempted clients reattach - instead
        of raising on an unknown token."""
        tasks = np.asarray(tasks)
        for idx in np.flatnonzero(etok):
            packed = int(etok[idx])
            table.futures.adopt_row_token(
                packed % TOKEN_LIMIT,
                table.ids[packed // TOKEN_LIMIT],
                int(tasks[idx, F_FN]),
                int(tasks[idx, F_OUT]),
            )

    def _run_stream(
        self, builder, ivalues, data, quantum, max_rounds,
        poll_interval_s, deadline_s, resume_state=None,
    ) -> Tuple[np.ndarray, dict]:
        deadline = (
            None if deadline_s is None else time.monotonic() + deadline_s
        )
        mk = self.mk
        table = self.tenants
        ring = np.zeros((self.ring_capacity, RING_ROW), np.int32)
        ctl = np.zeros(8, np.int32)  # [tail, close, consumed, abort, ...]
        egspec = self._egress if table is not None else None
        if egspec is not None:
            # Completion-mailbox host halves: mailbox + park rings,
            # cursor block, per-task-row token table. Host-seeded every
            # entry, mutated by the kernel's publish path, drained (and
            # futures resolved) right after every entry - so quiesce and
            # the drained exit always run against an EMPTY mailbox and
            # an empty park ring: a slow poller cannot wedge either.
            depth = egspec.depth
            egr_np = np.zeros((depth, EGR_WORDS), np.int32)
            park_np = np.zeros((depth, EGR_WORDS), np.int32)
            ectl_np = np.zeros(8, np.int32)
            etok_np = np.zeros(mk.capacity, np.int32)
        if self.telemetry:
            # Telemetry host halves: the gauge+histogram block and the
            # per-row stamp table (host-seeded every entry, mutated by
            # the kernel, snapshotted after) plus the epoch bracket
            # that converts cumulative rounds to wall time.
            tele_np = np.zeros((1 + len(table), LAT_BUCKETS), np.int32)
            tlat_np = np.zeros((mk.capacity, LAT_WORDS), np.int32)
            bracket = EpochBracket()
            prev_rounds = 0
            if resume_state is None:
                with self._lock:
                    self._spans = {}
                    self._tele_snapshot = None
                    self._tele_seq = 0
        injected = 0
        if resume_state is not None:
            # Same-object resume must behave like a fresh stream: clear
            # the quiesce request and undo the QUIESCE-induced close (the
            # snapshot already captured everything producers queued). An
            # explicit close()/abort() stays sticky - drain-and-exit
            # semantics survive the resume.
            with self._lock:
                self._quiesce_after = None
                self._quiesce_t = None
                self._stats["resumes"] += 1
                if self._closed_by_quiesce:
                    self._closed = False
                    self._closed_by_quiesce = False
            st = resume_state
            succ = np.asarray(st["succ"])
            state = [
                np.asarray(st["tasks"]), np.asarray(st["ready"]),
                np.asarray(st["counts"]), np.asarray(st["ivalues"]),
            ]
            data = dict(st.get("data") or {})
            # Residue: rows published-but-unconsumed at quiesce (plus any
            # host-queued rows the snapshot captured) re-publish from ring
            # slot 0 with a reset consumed cursor - installed rows already
            # live in the task table. Tenant-tagged residue instead
            # re-enters its lanes' host backlogs (counters restored from
            # the snapshot's tctl/tstats blocks) and the next pump
            # re-publishes it per region - per-tenant counts conserved.
            if table is not None:
                table.resume_from(st)
                if egspec is not None:
                    et = np.asarray(
                        st.get("etok", np.zeros(mk.capacity, np.int32)),
                        np.int32,
                    ).reshape(-1)
                    if et.shape[0] != mk.capacity:
                        raise ValueError(
                            f"resume etok table has {et.shape[0]} rows; "
                            f"this kernel's task table has {mk.capacity}"
                        )
                    etok_np = et.copy()
                    self._adopt_etok(table, etok_np, state[0])
                    # The cut exported no ectl block (the mailbox and
                    # park ring drained before export) - but the
                    # adopted tokens ARE in flight, and the install
                    # credit gate reads EC_INFLIGHT. Seeding it zero
                    # would let each adopted retirement drive it
                    # negative, inflating the gate until the park ring
                    # overwraps its counted rows.
                    ectl_np[EC_INFLIGHT] = int(np.count_nonzero(etok_np))
                if self.telemetry:
                    # The telemetry block rides the cut: the round
                    # gauge is cumulative, so resumed rows' measured
                    # latencies span the preemption. Absent keys mean
                    # the snapshot came from a telemetry-off stream -
                    # start the plane fresh from zero.
                    for name, cur in (
                        ("tele", tele_np), ("tlat", tlat_np),
                    ):
                        blk = st.get(name)
                        if blk is None:
                            continue
                        blk = np.asarray(blk, np.int32)
                        if blk.shape != cur.shape:
                            raise ValueError(
                                f"resume {name} block has shape "
                                f"{blk.shape}; this stream expects "
                                f"{cur.shape}"
                            )
                        cur[:] = blk
                    prev_rounds = int(tele_np[0, TG_ROUNDS])
            elif "tctl" in st or "tstats" in st:
                # The mirror of TenantTable.resume_from's guard: a
                # tenant-tagged snapshot resumed on a plain stream would
                # silently strip every row's tenant identity (and its
                # counters) instead of conserving them.
                raise ValueError(
                    "resume state carries per-tenant lane blocks "
                    "(tctl/tstats): it was exported from a tenant-enabled "
                    "stream and cannot resume on a plain one"
                )
            else:
                residue = np.asarray(
                    st.get("ring_rows",
                           np.zeros((0, RING_ROW), np.int32))
                ).reshape(-1, RING_ROW)
                if len(residue) > self.ring_capacity:
                    raise ValueError(
                        f"resume residue ({len(residue)} rows) exceeds "
                        f"this stream's ring_capacity "
                        f"{self.ring_capacity}"
                    )
                ring[: len(residue)] = residue
                injected = len(residue)
        else:
            tasks, succ, ring0, counts = builder.finalize(
                capacity=mk.capacity, succ_capacity=mk.succ_capacity
            )
            if ivalues is None:
                ivalues = np.zeros(mk.num_values, np.int32)
            else:
                counts = counts.copy()
                mk.widen_value_alloc(counts, ivalues)
            mk.check_row_values(int(counts[C_VALLOC]))
            data = dict(data or {})
            state = [tasks, ring0, counts, ivalues]
        if set(data.keys()) != set(mk.data_specs.keys()):
            raise ValueError("data buffers != declared data_specs")
        key = (quantum, max_rounds)
        if key not in self._jitted:
            from ..runtime.progcache import shared_build

            # Only facts the stream compiles into the program key the
            # variant: tenant count / region rows / egress ring depth.
            # WRR weights and rate limits ride tctl at runtime.
            variant = (
                "stream", self.ring_capacity,
                None if self.tenants is None
                else (len(self.tenants), self.tenants.region_rows),
                None if self._egress is None else self._egress.depth,
                bool(self.telemetry),
            ) + key
            self._jitted[key], self._pc_stats = shared_build(
                mk, variant, lambda: self._build(quantum, max_rounds),
            )
        jitted = self._jitted[key]

        data_np = [np.asarray(data[k]) for k in mk.data_specs.keys()]
        ndata = len(mk.data_specs)
        # Flight recorder: each entry resets the ring, so the LAST entry's
        # records surface in info - bracketed by THAT entry's own epoch
        # (a whole-stream bracket would stretch the final entry's rounds
        # across every earlier entry's wall time in the Perfetto view).
        trace_row = None
        entry_t0_ns = entry_t1_ns = time.monotonic_ns()
        while True:
            # Publish queued rows: rows first, then tail (release order;
            # over the tunnel both land before the next entry launches).
            with self._lock:
                rows, self._pending_rows = self._pending_rows, []
                closed = self._closed
                abort_reason = self._abort_reason
                quiesce_after = self._quiesce_after
            if abort_reason is not None:
                # Publish the ctl abort word and run ONE final entry: the
                # kernel polls the word inside its round loop and exits
                # within one quantum's worth of inner iterations, pending
                # work abandoned where it stands and queued rows dropped.
                # Then surface latency and raise. Tenant lanes get a
                # frozen all-paused tctl: nothing publishes, nothing
                # installs, remaining rows abandoned like the plain path.
                e0 = int(state[2][C_EXECUTED])
                ctl[0] = injected
                ctl[1] = 1
                ctl[3] = 1
                extra = []
                if table is not None:
                    frozen = np.zeros((len(table), 8), np.int32)
                    frozen[:, TC_PAUSE] = 1
                    extra = [jnp.asarray(frozen)]
                if egspec is not None:
                    extra += [
                        jnp.asarray(egr_np), jnp.asarray(park_np),
                        jnp.asarray(ectl_np), jnp.asarray(etok_np),
                    ]
                if self.telemetry:
                    extra += [
                        jnp.asarray(tele_np), jnp.asarray(tlat_np),
                    ]
                outs = jitted(
                    jnp.asarray(state[0]), jnp.asarray(succ),
                    jnp.asarray(state[1]), jnp.asarray(state[2]),
                    jnp.asarray(state[3]), jnp.asarray(ring),
                    jnp.asarray(ctl), *[jnp.asarray(d) for d in data_np],
                    *extra,
                )
                counts_ab = np.asarray(outs[2])
                ctl_ab = np.asarray(outs[4])
                if egspec is not None:
                    # Degradation ladder, abort rung: results that made
                    # it into the mailbox/park before the stop still
                    # resolve RESULT; every other outstanding future
                    # poisons - clients get a typed terminal state, not
                    # a hang.
                    nt_ab = 1 if mk.trace is not None else 0
                    base = 6 + len(mk.data_specs) + nt_ab
                    egr_np, park_np, ectl_np, etok_np = (
                        np.array(outs[base + i]) for i in range(4)
                    )
                    sp = {} if self.telemetry else None
                    self._drain_egress(
                        table, egr_np, park_np, ectl_np, spans=sp
                    )
                    if self.telemetry:
                        tele_np = np.array(outs[base + 4])
                        tlat_np = np.array(outs[base + 5])
                        with self._lock:
                            self._spans.update(sp)
                    table.futures.poison_all(
                        f"stream aborted: {abort_reason}"
                    )
                with self._lock:
                    t0 = self._abort_t
                    self._stats.update({
                        "aborts": self._stats["aborts"] + 1,
                        "abort_reason": abort_reason,
                        "abort_observed_round": int(ctl_ab[4]),
                        "abort_latency_s": (
                            None if t0 is None
                            else round(time.monotonic() - t0, 6)
                        ),
                        "abort_drain_executed": (
                            int(counts_ab[C_EXECUTED]) - e0
                        ),
                    })
                raise CancelledError(f"stream aborted: {abort_reason}")
            if deadline is not None and time.monotonic() >= deadline:
                raise StallError(
                    f"run_stream deadline of {deadline_s}s exceeded "
                    f"(injected={injected}, closed={closed})",
                    stats=self.stats_dict(),
                )
            for row in rows:
                if injected >= self.ring_capacity:
                    raise RuntimeError(
                        f"injection ring exhausted ({self.ring_capacity} "
                        "rows per stream)"
                    )
                ring[injected] = row
                injected += 1
            if table is not None:
                # Tenant lanes: the pump expires/publishes the host
                # backlogs into the per-lane ring regions and builds the
                # tctl block this entry uploads; the plain tail is unused.
                if self.telemetry:
                    # Admit-round feedback: rows published by THIS pump
                    # are stamped with the round gauge the last entry
                    # echoed - ring-wait time is inside the measured
                    # admission->retire span.
                    table.set_admit_round(int(tele_np[0, TG_ROUNDS]))
                tctl_np = table.pump(ring)
                injected = table.total_published()
                ctl[0] = 0
            ctl[1] = 1 if closed else 0
            if quiesce_after is not None:
                # Publish the quiesce word + threshold: the kernel
                # observes it inside its round loop once the executed
                # count passes the threshold and exits with its state.
                ctl[5] = 1
                ctl[6] = quiesce_after
            if table is None:
                ctl[0] = injected
            entry_t0_ns = time.monotonic_ns()
            outs = jitted(
                jnp.asarray(state[0]), jnp.asarray(succ),
                jnp.asarray(state[1]), jnp.asarray(state[2]),
                jnp.asarray(state[3]), jnp.asarray(ring),
                jnp.asarray(ctl), *[jnp.asarray(d) for d in data_np],
                *([jnp.asarray(tctl_np)] if table is not None else []),
                *([
                    jnp.asarray(egr_np), jnp.asarray(park_np),
                    jnp.asarray(ectl_np), jnp.asarray(etok_np),
                ] if egspec is not None else []),
                *([
                    jnp.asarray(tele_np), jnp.asarray(tlat_np),
                ] if self.telemetry else []),
            )
            state = [np.asarray(o) for o in outs[:4]]
            ctl_o = np.asarray(outs[4])
            data_np = [np.asarray(o) for o in outs[5 : 5 + ndata]]
            ntrace = 1 if mk.trace is not None else 0
            if mk.trace is not None:
                trace_row = np.asarray(outs[5 + ndata])
                entry_t1_ns = time.monotonic_ns()
            if table is not None:
                # Fold the lane-cursor echo back: consume cursors advance
                # (freeing in-flight budget), cumulative install/expire/
                # sweep counters refresh, admission latencies record.
                table.absorb(np.asarray(outs[5 + ndata + ntrace]))
            if egspec is not None:
                # Drain the mailbox AND the park ring at the entry
                # boundary, resolving futures - both always empty when
                # the loop reaches the quiesce/drained-exit checks
                # below, so a full mailbox can never wedge either.
                base = 6 + ndata + ntrace
                egr_np, park_np, ectl_np, etok_np = (
                    np.array(outs[base + i]) for i in range(4)
                )
                sp = {} if self.telemetry else None
                self._drain_egress(
                    table, egr_np, park_np, ectl_np, spans=sp
                )
                if sp:
                    with self._lock:
                        self._spans.update(sp)
            if self.telemetry:
                # Absorb the echoed histogram/gauge + stamp blocks and
                # publish a coherent snapshot for mid-run scrapers. The
                # epoch bracket pairs this entry's host wall clock with
                # the round-gauge delta so rounds convert to ns without
                # any on-device clock.
                tbase = 10 + ndata + ntrace
                tele_np = np.array(outs[tbase])
                tlat_np = np.array(outs[tbase + 1])
                tele_np[0, TG_ENTRIES] += 1
                t1_ns = time.monotonic_ns()
                rounds = int(tele_np[0, TG_ROUNDS])
                bracket.accumulate(
                    entry_t0_ns, t1_ns, rounds - prev_rounds
                )
                prev_rounds = rounds
                with self._lock:
                    self._tele_seq += 1
                    self._tele_snapshot = {
                        "seq": self._tele_seq,
                        "tele": tele_np.copy(),
                        "rounds": rounds,
                        "entries": int(tele_np[0, TG_ENTRIES]),
                        "ns_per_round": bracket.ns_per_round(),
                        "t0_ns": entry_t0_ns,
                        "t1_ns": t1_ns,
                    }
            counts_np = state[2]
            ctl[2] = ctl_o[2]  # device-consumed cursor persists
            if bool(counts_np[C_OVERFLOW]):
                raise RuntimeError("streaming megakernel overflow")
            observed_round = int(ctl_o[5]) if quiesce_after is not None else -1
            # A threshold the workload never reaches must not spin this
            # loop forever: once the stream is fully drained, the entry
            # boundary IS a round boundary - export host-side (observed
            # round -1) instead of waiting on a quiesce the kernel can
            # never observe.
            drained_cut = (
                quiesce_after is not None
                and int(counts_np[C_PENDING]) == 0
                and (
                    table.drained() if table is not None
                    else int(ctl_o[2]) == injected
                )
            )
            if observed_round >= 0 or drained_cut:
                # The quiesce point: export the live stream state and
                # stop. The ring closes (preemption semantics:
                # checkpoint, then stop) so concurrent producers fail
                # fast; rows they queued before the close ride along as
                # unpublished residue.
                consumed = int(ctl_o[2])
                with self._lock:
                    late, self._pending_rows = self._pending_rows, []
                    if not self._closed:
                        self._closed = True
                        self._closed_by_quiesce = True
                    t0 = self._quiesce_t
                    self._stats["quiesces"] += 1
                    self._stats["last_quiesce_latency_s"] = (
                        None if t0 is None
                        else round(time.monotonic() - t0, 6)
                    )
                info = {
                    "executed": int(counts_np[C_EXECUTED]),
                    "pending": int(counts_np[C_PENDING]),
                    "injected": injected,
                    "quiesced": True,
                    "quiesce_observed_round": observed_round,
                    "quiesce_latency_s": (
                        None if t0 is None
                        else round(time.monotonic() - t0, 6)
                    ),
                    "state": {
                        "tasks": state[0],
                        "succ": np.asarray(succ),
                        "ready": state[1],
                        "counts": state[2],
                        "ivalues": state[3],
                        "data": dict(zip(mk.data_specs.keys(), data_np)),
                    },
                }
                if self._pc_stats is not None:
                    info["program_cache"] = dict(self._pc_stats)
                if table is not None:
                    # Per-tenant residue (tenant-tagged rows) + the
                    # cumulative tctl/tstats counter blocks: resume_from
                    # re-seeds the lanes so per-tenant accepted/installed/
                    # expired counts are conserved exactly across the cut.
                    # (inject() on a tenant stream routes through
                    # submit(), so _pending_rows holds no untagged rows.)
                    assert not late, "tenant stream held untagged rows"
                    if egspec is not None:
                        # Installed-but-unretired tokens ride the cut
                        # (mailbox/park already drained above); their
                        # futures go PREEMPTED inside export_state and
                        # reattach via resume tokens after resume_from
                        # re-adopts this table.
                        info["state"]["etok"] = etok_np.copy()
                    if self.telemetry:
                        # Histogram/gauge + stamp blocks ride the cut so
                        # the resumed stream's round gauge and per-tenant
                        # latency totals stay cumulative across it.
                        info["state"]["tele"] = tele_np.copy()
                        info["state"]["tlat"] = tlat_np.copy()
                    info["state"].update(table.export_state(ring))
                else:
                    residue = (
                        list(ring[consumed:injected]) + list(late)
                    )
                    info["state"]["ring_rows"] = np.asarray(
                        residue, np.int32
                    ).reshape(-1, RING_ROW)
                if mk.trace is not None and trace_row is not None:
                    info["trace"] = trace_info(
                        [trace_row], entry_t0_ns, entry_t1_ns,
                        mk.trace.capacity,
                    )
                return state[3], info
            if (
                closed
                and int(counts_np[C_PENDING]) == 0
                and (
                    # Atomically drained-check AND close the front door:
                    # a submit racing this exit gets "closed", never an
                    # ACCEPTED row the returned stream will not run.
                    table.close_if_drained() if table is not None
                    else int(ctl_o[2]) == injected
                )
                and not self._pending_rows
            ):
                info = {
                    "executed": int(counts_np[C_EXECUTED]),
                    "pending": int(counts_np[C_PENDING]),
                    "injected": (
                        table.total_published() if table is not None
                        else injected
                    ),
                }
                if self._pc_stats is not None:
                    info["program_cache"] = dict(self._pc_stats)
                if table is not None:
                    info["tenants"] = table.stats()
                if self.telemetry:
                    info["telemetry"] = {
                        "tele": tele_np.copy(),
                        "ns_per_round": bracket.ns_per_round(),
                        "rounds": int(tele_np[0, TG_ROUNDS]),
                    }
                if mk.trace is not None and trace_row is not None:
                    info["trace"] = trace_info(
                        [trace_row], entry_t0_ns, entry_t1_ns,
                        mk.trace.capacity,
                    )
                return state[3], info
            time.sleep(poll_interval_s)
