"""One-sided device PGAS inside the resident kernel: put / active messages /
wait-until on *data*, between devices, without leaving the kernel.

This closes the gap between the descriptor-only ICI steal machinery
(device/ici_steal.py moves 16-word task rows) and the reference's SHMEM
layer, which does one-sided put/get/AMO/wait-until on *user data* in a
symmetric heap (/root/reference/modules/openshmem/src/hclib_openshmem.cpp:
136-760; wait-sets :755-920) and pushes lambdas at arbitrary PEs
(/root/reference/modules/openshmem-am/src/hclib_openshmem-am.cpp:64-123).
SURVEY §2.4 maps both to "TPU remote DMA between chips" - this module is
that mapping:

- **symmetric buffers**: the megakernel's ``data_specs`` buffers exist on
  every device of the mesh with identical shapes - a symmetric heap. A
  *channel* is a static contract (buffer name, row count) under which
  one-sided writes travel.
- **put**: ``ctx.pgas.put(dev, chan, dst_row, src_row)`` remote-DMAs rows
  of the channel's buffer from this device into ``dev``'s same-named
  buffer (``pltpu.make_async_remote_copy``), signalling the channel's
  arrival semaphore on the target. SHMEM-style contract: concurrent puts
  to one target must write disjoint regions.
- **active message**: ``ctx.pgas.am(dev, fn, args)`` queues a task
  descriptor for *that specific device's* resident scheduler - unlike the
  steal schedule, which only moves work to its round partner. ``get`` is
  its composition, exactly as in the reference's AM-over-SHMEM design: am
  a handler at the owner; the handler puts the data back on a reply
  channel the caller's consumer task waits on.
- **wait-until**: ``ctx.pgas.wait_until(chan, need, row)`` parks task
  ``row`` until ``need`` messages have *landed* on ``chan`` - the
  scheduler loop polls arrival counts each round and readies parked rows
  (the reference's wait-set poll task, hclib_openshmem.cpp:755-894, as
  part of the resident scheduler itself).

**The counting protocol** (how one-sided completes without a receiver-side
call site): senders count messages per (target, channel); each round, the
counts ride the termination ring-allreduce, so every device learns exactly
how many messages were directed at it; it then *consumes* exactly that many
arrival-semaphore signals via matching ``wait_recv`` descriptors (blocking,
but for messages already launched - never speculative). Data reads happen
only after the matching semaphore count is consumed, so no torn/partial
payload is ever observed, with zero non-blocking semaphore reads (Mosaic's
interpret mode has none). Termination is message-counting (Mattern-style):
exit when globally pending == 0, outboxes empty, and messages sent ==
messages received - so an in-flight message always blocks exit and every
semaphore is drained to zero at kernel exit.

AM flow control needs no credit round-trips: device s owns inbox row
``inbox[s, :]`` on every target (AMW slots, cycled). A receiver drains
*everything* the round-k snapshot announced during round k; ring-allreduce
completion of round k+1 implies every device finished that drain, so a
sender that launches at most AMW//2 AMs per target per round can never
overwrite an unconsumed slot. Queued-but-uncapped AMs wait in a local
outbox (the reference's pending-op list at the NIC locale,
modules/common/hclib-module-common.h:10-115), drained by the round loop.

Stat payload is O(ndev^2 + ndev*nchan) words per hop - fine for a pod
slice's worth of devices; past that the matrix wants the same hierarchical
split the locality graph gives steal paths.
"""

from __future__ import annotations

import functools
import types
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..jaxcompat import shard_map
from .descriptor import (
    DESC_WORDS,
    F_A0,
    F_CSR_N,
    F_CSR_OFF,
    F_DEP,
    F_FN,
    F_HOME,
    F_OUT,
    F_SUCC0,
    F_SUCC1,
    NO_TASK,
    TaskGraphBuilder,
)
from .megakernel import (
    interpret_mode,
    C_OVERFLOW,
    C_PENDING,
    C_ROUNDS,
    C_TAIL,
    LS_WORDS,
    Megakernel,
    VBLOCK,
)
from .tracebuf import (
    NullTracer,
    TR_ABORT,
    TR_XFER,
    Tracer,
    trace_info,
)

__all__ = ["PGASMegakernel"]

# pstate[] slots
PS_RECV = 0   # messages received (drained) on this device, all kinds
PS_NWAIT = 1  # live wait-table entries


class PGASMegakernel:
    """Per-device resident scheduler + one-sided PGAS over a 1D mesh.

    ``channels`` maps channel name -> (data buffer name, rows per message);
    every put on a channel moves exactly that many leading-axis rows (the
    static-shape contract that lets receivers consume arrival semaphores
    with matching descriptors). ``chan_id`` gives the table index kernels
    use. ``am_window`` is the per-(source, target) inbox depth; at most
    ``am_window // 2`` AMs per target leave the outbox per round.
    """

    def __init__(
        self,
        mk: Megakernel,
        mesh: Mesh,
        channels: Optional[Dict[str, Tuple[str, int]]] = None,
        am_window: int = 8,
        outbox: int = 64,
        max_waits: int = 64,
    ) -> None:
        if len(mesh.axis_names) != 1:
            raise ValueError("PGASMegakernel wants a 1D mesh")
        if am_window < 2:
            raise ValueError("am_window must be >= 2")
        self.mk = mk
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.ndev = int(np.prod(mesh.devices.shape))
        self.channels: List[Tuple[str, int]] = []
        self.chan_id: Dict[str, int] = {}
        for cname, (bname, rows) in (channels or {}).items():
            if bname not in mk.data_specs:
                raise ValueError(f"channel {cname!r}: no data buffer {bname!r}")
            if rows < 1 or rows > mk.data_specs[bname].shape[0]:
                raise ValueError(f"channel {cname!r}: bad row count {rows}")
            self.chan_id[cname] = len(self.channels)
            self.channels.append((bname, int(rows)))
        self.nchan = max(1, len(self.channels))
        self.am_window = int(am_window)
        self.outbox = int(outbox)
        self.max_waits = int(max_waits)
        # Power-of-two meshes delegate to the unified resident kernel
        # (device/resident.py) in its PGAS-only configuration, which also
        # upgrades the counting protocol: per-source arrival semaphores
        # (closing this module's shared-semaphore cross-round aliasing
        # exposure) and O(ndev log ndev) stat routing instead of the ring
        # allreduce of an O(ndev^2) matrix. This class remains the
        # non-pof2 fallback (and the named legacy API).
        self._resident = None
        if self.ndev & (self.ndev - 1) == 0:
            from .resident import ResidentKernel

            self._resident = ResidentKernel(
                mk, mesh, steal=False, channels=dict(channels or {}),
                am_window=self.am_window, outbox=self.outbox,
                max_waits=self.max_waits,
            )
        # Stat-vector layout (ring-allreduced every round; all entries
        # sum). Slot 3 folds the per-device abort word so a host abort
        # exits the whole ring in lockstep one round later.
        self.ST_AM = 4  # [src * ndev + dst] AM send counts
        self.ST_DATA = 4 + self.ndev * self.ndev  # [dst * nchan + chan]
        self.S = self.ST_DATA + self.ndev * self.nchan
        self._jitted: Dict[Any, Any] = {}
        self._pc_stats: Optional[Dict[str, Any]] = None

    # -- the kernel --

    def _kernel(self, quantum: int, max_rounds: int, trace, *refs) -> None:
        # ``trace`` captured at _build time (pallas traces lazily; see
        # Megakernel._kernel).
        mk = self.mk
        ndata = len(mk.data_specs)
        nbatch = 1 if mk.batch_specs else 0
        ntrace = 1 if trace is not None else 0
        n_in = 7 + ndata  # + waits_in + abort word (last)
        in_refs = refs[:n_in]
        out_refs = refs[n_in : n_in + 4 + ndata + nbatch + ntrace]
        rest = refs[n_in + 4 + ndata + nbatch + ntrace :]
        nscratch = len(mk.scratch_specs)
        scratch_refs = rest[:nscratch]
        stail = list(rest[nscratch:])
        (
            free, vfree,
            outq_tgt, outq_desc, ambuf, obctl, inbox, am_sent, am_recv, sent_round,
            data_sent, chan_recv, pstate, wait_tab,
            statsnd, statrcv, statacc, abuf,
            dsems, am_sem, chan_sems, csem, asem,
        ) = stail[:23]
        # Batched dispatch tier (ISSUE 7): lane scratch rides last; the
        # spill discipline empties it at every sched() exit, so the AM
        # drain and ring fold between rounds only ever see ring rows. The
        # length check keeps the positional bind loud: an edit to
        # _build's scratch list that forgets these indices must fail at
        # trace time, not scribble batch descriptors into a neighbor.
        assert len(stail) == 23 + 2 * nbatch, len(stail)
        lanes, lstate = (stail[23], stail[24]) if nbatch else (None, None)
        abort_in = in_refs[n_in - 1]
        tasks_in, succ, ready_in, counts_in, ivalues_in = in_refs[:5]
        waits_in = in_refs[5 + ndata]  # waits ride after the data inputs
        tasks, ready, counts, ivalues = out_refs[:4]
        data = dict(zip(mk.data_specs.keys(), out_refs[4 : 4 + ndata]))
        tstats = out_refs[4 + ndata] if nbatch else None
        tr = (
            Tracer(out_refs[4 + ndata + nbatch], trace.capacity)
            if ntrace
            else NullTracer()
        )
        scratch = dict(zip(mk.scratch_specs.keys(), scratch_refs))

        ndev = self.ndev
        nchan = self.nchan
        AMW = self.am_window
        OUTQ = self.outbox
        MAXW = self.max_waits
        ST_AM, ST_DATA, S = self.ST_AM, self.ST_DATA, self.S
        axis = self.axis

        me = jax.lax.axis_index(axis)
        right = (me + 1) % ndev
        left = (me + ndev - 1) % ndev

        # -- ops attached to every task's KernelContext (ctx.pgas.*) --

        def op_put(dev, chan: int, dst_row, src_row) -> None:
            """One-sided write of channel ``chan``'s row window from my
            buffer rows [src_row, +rows) into device ``dev``'s rows
            [dst_row, +rows). Local completion on return (send done);
            target-side arrival is what wait_until/count observe."""
            if not isinstance(chan, int):
                raise TypeError("chan must be a static channel id")
            bname, rows = self.channels[chan]
            buf = data[bname]
            rdma = pltpu.make_async_remote_copy(
                src_ref=buf.at[pl.ds(src_row, rows)],
                dst_ref=buf.at[pl.ds(dst_row, rows)],
                send_sem=dsems.at[2],
                recv_sem=chan_sems.at[chan],
                device_id=dev,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdma.wait_send()
            data_sent[dev, chan] = data_sent[dev, chan] + 1

        def op_am(dev, fn: int, args: Sequence = (), out=0) -> None:
            """Queue a task descriptor for device ``dev``'s scheduler (the
            reference's async_remote at a chosen PE). Non-blocking: the
            round loop launches it under the inbox-window cap; a full
            outbox sets the overflow flag (bounded, like every queue
            here)."""
            if len(args) > 6:
                raise ValueError(f"at most 6 args per AM, got {len(args)}")
            h = obctl[1]
            ok = h - obctl[0] < OUTQ
            slot = h % OUTQ

            @pl.when(ok)
            def _():
                outq_tgt[slot] = dev
                outq_desc[slot, F_FN] = jnp.int32(fn)
                outq_desc[slot, F_DEP] = 0
                outq_desc[slot, F_SUCC0] = jnp.int32(NO_TASK)
                outq_desc[slot, F_SUCC1] = jnp.int32(NO_TASK)
                outq_desc[slot, F_CSR_OFF] = 0
                outq_desc[slot, F_CSR_N] = 0
                for i in range(6):
                    outq_desc[slot, F_A0 + i] = (
                        jnp.int32(args[i]) if i < len(args) else 0
                    )
                outq_desc[slot, F_OUT] = jnp.int32(out)
                for w in range(F_OUT + 1, DESC_WORDS):
                    # F_HOME word: AM tasks are local to their target.
                    outq_desc[slot, w] = NO_TASK if w == F_HOME else 0
                obctl[1] = h + 1

            @pl.when(jnp.logical_not(ok))
            def _():
                counts[C_OVERFLOW] = 1

        def op_wait_until(chan, need, row) -> None:
            """Park descriptor ``row`` (spawned with an extra dep) until
            ``need`` messages have landed on ``chan``; the round loop
            readies it (the reference's wait-set enqueue,
            hclib_openshmem.cpp:895-920)."""
            n = pstate[PS_NWAIT]
            ok = n < MAXW
            nc = jnp.minimum(n, MAXW - 1)

            @pl.when(ok)
            def _():
                wait_tab[nc, 0] = chan
                wait_tab[nc, 1] = need
                wait_tab[nc, 2] = row
                pstate[PS_NWAIT] = n + 1

            @pl.when(jnp.logical_not(ok))
            def _():
                counts[C_OVERFLOW] = 1

        def op_count(chan: int):
            """Messages landed-and-consumed on ``chan`` at this device (the
            wait-until counter; monotone)."""
            return chan_recv[chan]

        def ctx_hook(ctx) -> None:
            ctx.pgas = types.SimpleNamespace(
                put=op_put, am=op_am, wait_until=op_wait_until,
                count=op_count, me=me, ndev=ndev,
                nchan=len(self.channels),
            )

        core = mk._make_core(
            succ, tasks, ready, counts, ivalues, data, scratch, free, vfree,
            tasks_in, ready_in, counts_in, ivalues_in, True, ctx_hook,
            lanes=lanes, lstate=lstate, tstats=tstats,
            tracer=tr if tr.enabled else None,
        )

        # -- round-loop phases --

        def stage_pgas() -> None:
            def z(i, _):
                am_sent[i] = 0
                am_recv[i] = 0
                for c in range(nchan):
                    data_sent[i, c] = 0
                return 0

            jax.lax.fori_loop(0, ndev, z, 0)
            for c in range(nchan):
                chan_recv[c] = 0
            pstate[PS_RECV] = 0
            pstate[PS_NWAIT] = waits_in[0, 0]
            obctl[0] = 0
            obctl[1] = 0

            def cw(i, _):
                for w in range(3):
                    wait_tab[i, w] = waits_in[1 + i, w]
                return 0

            jax.lax.fori_loop(0, waits_in[0, 0], cw, 0)

        def drain_outbox() -> None:
            """Launch queued AMs under the per-target window cap (FIFO:
            a capped head entry stalls the queue until next round, which
            preserves per-target order)."""

            def zz(i, _):
                sent_round[i] = 0
                return 0

            jax.lax.fori_loop(0, ndev, zz, 0)

            def cond(h):
                more = h < obctl[1]
                t = outq_tgt[h % OUTQ]
                return more & (sent_round[jnp.where(more, t, 0)] < AMW // 2)

            def body(h):
                slot_q = h % OUTQ
                t = outq_tgt[slot_q]
                slot = am_sent[t] % AMW
                # Stage into the 128-word-aligned comm row: Mosaic requires
                # SMEM DMA slices to be 128-word multiples in the minor
                # dim, so the wire unit is a padded row, not the bare
                # 16-word descriptor.
                for w in range(DESC_WORDS):
                    ambuf[w] = outq_desc[slot_q, w]
                rdma = pltpu.make_async_remote_copy(
                    src_ref=ambuf,
                    dst_ref=inbox.at[me, slot],
                    send_sem=dsems.at[3],
                    recv_sem=am_sem,
                    device_id=t,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                rdma.start()
                rdma.wait_send()
                am_sent[t] = am_sent[t] + 1
                sent_round[t] = sent_round[t] + 1
                return h + 1

            h0 = obctl[0]
            h = jax.lax.while_loop(cond, body, h0)
            obctl[0] = h

            @pl.when(h > h0)
            def _():
                # AM launches this round (wire traffic, all targets).
                tr.emit(TR_XFER, tr.now(), me, h - h0)

        def stat_allreduce(r):
            """Ring-allreduce of the S-word stat vector (pending, received,
            outbox backlog, AM send matrix, data send matrix). Same 1-deep
            credited channel as ici_steal's termination collective."""

            def zs(i, _):
                statsnd[i] = 0
                statacc[i] = 0
                return 0

            jax.lax.fori_loop(0, S, zs, 0)
            statsnd[0] = counts[C_PENDING]
            statsnd[1] = pstate[PS_RECV]
            statsnd[2] = obctl[1] - obctl[0]
            statsnd[3] = (abuf[0] != 0).astype(jnp.int32)

            def fill_am(t, _):
                statsnd[ST_AM + me * ndev + t] = am_sent[t]
                for c in range(nchan):
                    statsnd[ST_DATA + t * nchan + c] = data_sent[t, c]
                return 0

            jax.lax.fori_loop(0, ndev, fill_am, 0)

            def acc_local(i, _):
                statacc[i] = statsnd[i]
                return 0

            jax.lax.fori_loop(0, S, acc_local, 0)
            for k in range(ndev - 1):
                if k > 0:
                    pltpu.semaphore_wait(csem, 1)
                else:

                    @pl.when(r > 0)
                    def _():
                        pltpu.semaphore_wait(csem, 1)

                rdma = pltpu.make_async_remote_copy(
                    src_ref=statsnd,
                    dst_ref=statrcv,
                    send_sem=dsems.at[0],
                    recv_sem=dsems.at[1],
                    device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                rdma.start()
                rdma.wait()

                def fwd(i, _):
                    v = statrcv[i]
                    statsnd[i] = v
                    statacc[i] = statacc[i] + v
                    return 0

                jax.lax.fori_loop(0, S, fwd, 0)
                # statrcv consumed: free our left neighbor to overwrite it
                # with its next hop. Signal strictly AFTER the read above.
                pltpu.semaphore_signal(
                    csem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )

        def drain_receives() -> None:
            """Consume exactly the arrivals the snapshot announced for this
            device: per-channel data messages (matching-shape wait_recv on
            the channel semaphore), then per-source AM inbox slots in FIFO
            order. Reads happen only after the semaphore count is consumed,
            so payloads are never observed partially written."""
            for c, (bname, rows) in enumerate(self.channels):
                buf = data[bname]
                waiter = pltpu.make_async_remote_copy(
                    src_ref=buf.at[pl.ds(0, rows)],
                    dst_ref=buf.at[pl.ds(0, rows)],
                    send_sem=dsems.at[2],
                    recv_sem=chan_sems.at[c],
                    device_id=me,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                expected = statacc[ST_DATA + me * nchan + c]
                delta = expected - chan_recv[c]

                def one(i, _):
                    waiter.wait_recv()
                    return 0

                jax.lax.fori_loop(0, delta, one, 0)
                chan_recv[c] = expected
                pstate[PS_RECV] = pstate[PS_RECV] + delta

            am_waiter = pltpu.make_async_remote_copy(
                src_ref=inbox.at[0, 0],
                dst_ref=inbox.at[0, 0],
                send_sem=dsems.at[3],
                recv_sem=am_sem,
                device_id=me,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

            # The AM arrival semaphore is SHARED across sources, so a
            # per-source wait can be satisfied by another source's bytes
            # while the wanted slot is still in flight (a real race, caught
            # by the interpreter's randomized scheduling). Wait for the
            # TOTAL announced arrivals first - the count only reaches
            # total * |row| bytes once every message has fully landed - and
            # only then read any inbox slot. (sent_round doubles as the
            # per-source delta scratch; drain_outbox re-zeroes it.)
            def calc(s, tot):
                d = statacc[ST_AM + s * ndev + me] - am_recv[s]
                sent_round[s] = d
                return tot + d

            total = jax.lax.fori_loop(0, ndev, calc, jnp.int32(0))

            def wait_one(i, _):
                am_waiter.wait_recv()
                return 0

            jax.lax.fori_loop(0, total, wait_one, 0)

            def install_src(s, _):
                base = am_recv[s]
                delta = sent_round[s]

                def install_one(i, _):
                    slot = (base + i) % AMW
                    core.install_descriptor(lambda w: inbox[s, slot, w])
                    return 0

                jax.lax.fori_loop(0, delta, install_one, 0)
                am_recv[s] = base + delta
                pstate[PS_RECV] = pstate[PS_RECV] + delta
                return 0

            jax.lax.fori_loop(0, ndev, install_src, 0)

        def scan_waits() -> None:
            """Ready parked rows whose channel counters reached their
            threshold; compact survivors in place (the wait-set poll,
            hclib_openshmem.cpp:755-894)."""
            n = pstate[PS_NWAIT]

            def one(i, kept):
                ch = wait_tab[i, 0]
                need = wait_tab[i, 1]
                row = wait_tab[i, 2]
                fire = chan_recv[ch] >= need

                @pl.when(fire)
                def _():
                    d = tasks[row, F_DEP] - 1
                    tasks[row, F_DEP] = d

                    @pl.when(d == 0)
                    def _():
                        core.push_ready(row)

                @pl.when(jnp.logical_not(fire))
                def _():
                    wait_tab[kept, 0] = ch
                    wait_tab[kept, 1] = need
                    wait_tab[kept, 2] = row

                return kept + jnp.where(fire, 0, 1)

            pstate[PS_NWAIT] = jax.lax.fori_loop(0, n, one, jnp.int32(0))

        # -- the round loop --

        core.stage()
        stage_pgas()

        def cond(carry):
            r, done = carry
            return jnp.logical_not(done) & (r < max_rounds)

        def body(carry):
            r, done = carry
            core.sched(quantum)
            # Host abort word: re-read from HBM inside the round loop and
            # folded below, so an abort stops the mesh within one round.
            cpa = pltpu.make_async_copy(abort_in, abuf, asem.at[0])
            cpa.start()
            cpa.wait()
            drain_outbox()
            stat_allreduce(r)
            tot_sent = jax.lax.fori_loop(
                ST_AM, S, lambda i, a: a + statacc[i], jnp.int32(0)
            )
            done = (
                (statacc[0] == 0)
                & (statacc[2] == 0)
                & (tot_sent == statacc[1])
            ) | (statacc[3] > 0)

            @pl.when(statacc[3] > 0)
            def _():
                tr.emit(TR_ABORT, tr.now(), r)
            # Unconditional: on the done round every delta is zero, and on
            # a max_rounds cutoff this leaves no arrival semaphore
            # unconsumed for announced messages.
            drain_receives()
            scan_waits()
            return r + 1, done

        r, done = jax.lax.while_loop(
            cond, body, (jnp.int32(0), jnp.bool_(False))
        )
        counts[C_ROUNDS] = r
        # Ring-credit drain (mirror of ici_steal): the first stat hop of
        # the run never waited, so one credit is outstanding iff any ring
        # hop ran.
        if ndev > 1:

            @pl.when(r >= 1)
            def _():
                pltpu.semaphore_wait(csem, 1)

    # -- host entry --

    def _build(self, quantum: int, max_rounds: int):
        mk = self.mk
        ndata = len(mk.data_specs)
        nbatch = 1 if mk.batch_specs else 0
        ndev, nchan = self.ndev, self.nchan
        smem = functools.partial(pl.BlockSpec, memory_space=pltpu.SMEM)
        anyspace = functools.partial(pl.BlockSpec, memory_space=pl.ANY)
        ntrace = 1 if mk.trace is not None else 0
        in_specs = [smem()] * 5 + [anyspace()] * ndata + [smem()]
        in_specs += [anyspace()]  # abort word (HBM: re-read per round)
        out_specs = tuple(
            [smem()] * 4 + [anyspace()] * ndata
            + [smem()] * nbatch  # tstats (batch-routed builds)
            + [smem()] * ntrace
        )
        data_shapes = [
            jax.ShapeDtypeStruct(s.shape, s.dtype)
            for s in mk.data_specs.values()
        ]
        from .megakernel import TS_WORDS

        out_shape = tuple(
            [
                jax.ShapeDtypeStruct((mk.capacity, DESC_WORDS), jnp.int32),
                jax.ShapeDtypeStruct((mk.capacity,), jnp.int32),
                jax.ShapeDtypeStruct((8,), jnp.int32),
                jax.ShapeDtypeStruct((mk.num_values,), jnp.int32),
            ]
            + data_shapes
            + (
                [jax.ShapeDtypeStruct((TS_WORDS,), jnp.int32)]
                if nbatch else []
            )
            + ([mk.trace.out_shape()] if ntrace else [])
        )
        aliases = {0: 0, 2: 1, 3: 2, 4: 3}
        for i in range(ndata):
            aliases[5 + i] = 4 + i
        kern = pl.pallas_call(
            functools.partial(self._kernel, quantum, max_rounds, mk.trace),
            out_shape=out_shape,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=list(mk.scratch_specs.values())
            + [
                pltpu.SMEM((mk.capacity + 1,), jnp.int32),  # free
                pltpu.SMEM((mk.num_values // VBLOCK + 1,), jnp.int32),
                pltpu.SMEM((self.outbox,), jnp.int32),  # outq targets
                pltpu.SMEM((self.outbox, DESC_WORDS), jnp.int32),
                pltpu.SMEM((128,), jnp.int32),  # ambuf: padded wire row
                pltpu.SMEM((2,), jnp.int32),  # obctl head/tail
                pltpu.SMEM((ndev, self.am_window, 128), jnp.int32),
                pltpu.SMEM((ndev,), jnp.int32),  # am_sent
                pltpu.SMEM((ndev,), jnp.int32),  # am_recv
                pltpu.SMEM((ndev,), jnp.int32),  # sent_round
                pltpu.SMEM((ndev, nchan), jnp.int32),  # data_sent
                pltpu.SMEM((nchan,), jnp.int32),  # chan_recv
                pltpu.SMEM((8,), jnp.int32),  # pstate
                pltpu.SMEM((self.max_waits, 3), jnp.int32),
                pltpu.SMEM((self.S,), jnp.int32),  # statsnd
                pltpu.SMEM((self.S,), jnp.int32),  # statrcv
                pltpu.SMEM((self.S,), jnp.int32),  # statacc
                pltpu.SMEM((8,), jnp.int32),  # abuf (abort staging)
                pltpu.SemaphoreType.DMA((4,)),
                pltpu.SemaphoreType.DMA(()),  # am arrival
                pltpu.SemaphoreType.DMA((nchan,)),  # channel arrivals
                pltpu.SemaphoreType.REGULAR,  # ring credit
                pltpu.SemaphoreType.DMA((1,)),  # asem
            ]
            + (
                [
                    # Batched dispatch tier lane scratch (unpacked last;
                    # rows = kinds x priority buckets).
                    pltpu.SMEM(
                        (mk.lane_scratch_rows, mk.capacity), jnp.int32
                    ),
                    pltpu.SMEM((mk.lane_scratch_rows, LS_WORDS), jnp.int32),
                ]
                if mk.batch_specs
                else []
            ),
            input_output_aliases=aliases,
            interpret=interpret_mode() if mk.interpret else False,
        )

        def step(tasks, succ, ring, counts, iv, *data_and_waits):
            data_in = data_and_waits[:ndata]
            waits = data_and_waits[ndata]
            abort = data_and_waits[ndata + 1]
            outs = kern(
                tasks[0], succ[0], ring[0], counts[0], iv[0],
                *[d[0] for d in data_in], waits[0], abort[0],
            )
            tasks_o, ready_o, counts_o, iv_o = outs[:4]
            data_o = outs[4 : 4 + ndata]
            extra_o = outs[4 + ndata :]  # [tstats?, trace?]
            gcounts = jax.lax.psum(counts_o, self.axis)
            return (
                counts_o[None],
                iv_o[None],
                gcounts[None],
                *[d[None] for d in data_o],
                *[t[None] for t in extra_o],
            )

        nin = 7 + ndata
        f = shard_map(
            step,
            mesh=self.mesh,
            in_specs=(P(self.axis),) * nin,
            out_specs=(P(self.axis),) * (3 + ndata + nbatch + ntrace),
            check_vma=False,
        )
        return jax.jit(f)

    def run(
        self,
        builders: Sequence[TaskGraphBuilder],
        data: Optional[Dict[str, np.ndarray]] = None,
        ivalues: Optional[np.ndarray] = None,
        waits: Optional[Sequence[Sequence[Tuple[int, int, int]]]] = None,
        quantum: int = 64,
        max_rounds: int = 1 << 14,
        abort=None,
    ):
        """Execute all partitions fully on-device.

        ``waits[d]`` lists host-declared wait-sets for device d as
        (chan_id, need, task_index) - the named task gains one extra
        dependency satisfied when ``need`` messages have landed on the
        channel. Returns (ivalues[ndev, V], data, info); ``data`` values
        carry a leading device axis (per-device symmetric-heap instances).
        ``abort``: host abort word (truthy or per-device flags) - the
        round loops observe it within one round and the mesh exits in
        lockstep with ``info['aborted']`` instead of draining.
        """
        from .sharded import execute_partitions

        if self._resident is not None:
            return self._resident.run(
                builders, data=data, ivalues=ivalues, waits=waits,
                quantum=quantum, max_rounds=max_rounds, abort=abort,
            )
        mk = self.mk
        ndev = self.ndev
        waits = list(waits or [])
        if len(waits) < ndev:
            waits = waits + [[] for _ in range(ndev - len(waits))]
        waits_arr = np.zeros((ndev, self.max_waits + 1, 3), np.int32)
        for d, wl in enumerate(waits):
            if len(wl) > self.max_waits:
                raise ValueError(f"device {d}: too many waits ({len(wl)})")
            waits_arr[d, 0, 0] = len(wl)
            for i, (ch, need, row) in enumerate(wl):
                if not (0 <= ch < len(self.channels)):
                    raise ValueError(f"bad channel id {ch}")
                if not (0 <= row < builders[d].num_tasks):
                    raise ValueError(
                        f"device {d}: wait names task {row}, but the "
                        f"partition has {builders[d].num_tasks} tasks"
                    )
                waits_arr[d, 1 + i] = (ch, need, row)

        def bump_waits(tasks, succ, ring, counts):
            """Each parked task owes one extra dependency (satisfied by the
            wait-table when its channel count reaches `need`), and must not
            start on the ready ring."""
            for d, wl in enumerate(waits):
                for (_, _, row) in wl:
                    tasks[d, row, F_DEP] += 1
                bumped = {row for (_, _, row) in wl}
                if not bumped:
                    continue
                old_n = counts[d][C_TAIL]
                keep = [r for r in ring[d][:old_n] if r not in bumped]
                ring[d][: len(keep)] = keep
                counts[d][C_TAIL] = len(keep)

        key = (quantum, max_rounds)
        first_build = key not in self._jitted
        if first_build:
            from ..runtime.progcache import mesh_key, shared_build

            variant = (
                "pgas", mesh_key(self.mesh), tuple(self.channels),
                self.am_window, self.outbox, self.max_waits,
            ) + key
            self._jitted[key], self._pc_stats = shared_build(
                mk, variant,
                lambda: self._build(quantum, max_rounds),
            )
        from .sharded import abort_words

        abort_arr = abort_words(abort, ndev)
        import time as _time

        t0_ns = _time.monotonic_ns()
        iv_o, data_o, info = execute_partitions(
            mk, self.mesh, ndev, self._jitted[key], builders, data, ivalues,
            with_rounds=True, mutate=bump_waits,
            extra_inputs=[waits_arr, abort_arr],
        )
        t1_ns = _time.monotonic_ns()
        if (
            first_build and self._pc_stats is not None
            and not self._pc_stats["hit"]
        ):
            # jax.jit is lazy: a cache MISS pays trace/lower/compile
            # inside this first entry (the Megakernel._execute
            # discipline), so fold the first wall into build_s before
            # it is reported.
            self._pc_stats["build_s"] += (t1_ns - t0_ns) / 1e9
        if self._pc_stats is not None:
            info["program_cache"] = dict(self._pc_stats)
        info["rounds"] = info.pop("steal_rounds")
        tail = info.pop("extra_outputs", None)
        if mk.trace is not None and tail:
            info["trace"] = trace_info(
                [tail[-1][d] for d in range(ndev)], t0_ns, t1_ns,
                mk.trace.capacity,
            )
        if mk.batch_specs and tail:
            # Per-device batched-tier counters (tstats rides before the
            # trace ring in the appended outputs).
            trows = tail[0]
            info["tiers"] = [
                mk.decode_tier_stats(trows[d]) for d in range(ndev)
            ]
        info["aborted"] = bool(abort_arr[:, 0].any()) and info["pending"] != 0
        if info["overflow"]:
            raise RuntimeError(
                "pgas kernel overflow: task table, value slots, outbox, or "
                "wait table exceeded - raise the limits or coarsen"
            )
        if info["pending"] != 0 and not info["aborted"]:
            from ..runtime.resilience import StallError

            raise StallError(
                f"pgas kernel stalled: {info['pending']} pending after "
                f"{info['executed']} executed ({info['rounds']} rounds) - "
                "a wait-until whose messages never arrive, or max_rounds "
                "too small",
                stats=info,
            )
        return iv_o, data_o, info
