"""Dynamic graph service: mutable blocked-CSR adjacency + incremental
recompute, served multi-tenant (ISSUE 20).

The frontier tier (frontier.py) traverses a STATIC blocked-CSR
adjacency; a production graph service takes edge inserts while queries
run. The substrate already fits: an edge insert is just one more task
descriptor kind. This module adds

**The mutable adjacency.** ``DynGraph`` pre-allocates ``spare`` edge
blocks per vertex in HBM behind the static rows: vertex ``v``'s spares
occupy rows ``[spare_base + v*spare, spare_base + (v+1)*spare)`` of the
same ``indices``/``weights`` arrays the static tier DMAs. The layout is
a PURE FUNCTION of (v, ordinal) - no link table, no allocation order -
so a block id means the same thing on every mesh replica and a migrated
EXPAND stays physically meaningful wherever it lands. The per-vertex
append cursor is the vertex's own live block count (``vt[1]``) in the
SMEM vertex table: all blocks are full except the tail, so the splice
target and position derive from ``(deg, blk_count)`` alone.

**The UPDATE kind.** ``UPDATE(u, v, w, uid)`` splices edge ``u -> v``
into u's chain in-kernel: DMA the tail block row into VMEM, set the
next lane, DMA it back (read-modify-write), or - when the tail is full -
blind-write a freshly-built row into the next spare block (the append
cursor owns fresh rows uniquely, hclint's documented blind-overwrite
exemption). No CAS anywhere: updates to one vertex serialize through
the batch body's slot order and the monotone SMEM folds, and the
``uid``-indexed applied flag makes every splice idempotent - which is
what lets the mesh path BROADCAST the full update stream to every
device (UPDATE is non-migratable; only EXPANDs steal) and lets reshard
re-deliver residue safely. After the splice the body relaxes the new
edge with u's CURRENT label and spawns v's blocks only if it improved -
incremental recompute touches exactly the rows whose labels can move.

**Exactness.** BFS/SSSP labels are monotone min-folds, so the
incremental fixpoint is bit-identical to a from-scratch run on the
mutated graph - per-device label arrays are local caches combined by
elementwise min, and a replica that has not yet applied a splice reads
a CLAMPED live-edge count (``_eff_cnt``) so it never relaxes a
half-visible edge; its own eventual splice-relax covers the edge with
whatever label u has by then, and transitivity does the rest. PageRank
splices are mass-neutral (degree changes only steer FUTURE splits), so
total mass conserves exactly while the result is schedule-dependent -
the certificate claims conservation, not identity. The
``("dyngraph", kind, reps, buckets, updates)`` claim is certified by
analysis/model.py against permuted update/expand interleavings.

**Serving.** Queries are their own kind (``QUERY(v)`` publishes the
label through the descriptor's out slot, so egress mailboxes resolve
query futures at retirement), and on priority-bucketed builds updates
and queries route to DISTINCT priority classes: the ``update_priority``
knob (HCLIB_TPU_DYNGRAPH_UPDATE_PRIORITY) pins the UPDATE lane's
bucket while queries default to the lowest class.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..runtime.locality import MeshPlacement, resolve_placement
from .descriptor import TaskGraphBuilder
from .frontier import (
    EBLOCK,
    FR_EXPAND,
    INF,
    V_EDGES,
    V_RELAX,
    VT_BASE,
    FrontierKernel,
    Graph,
    _bucket_fn,
    _pr_seed_rank,
    bfs_kernel,
    default_delta,
    host_bfs,
    host_pagerank_push,
    host_sssp,
    pagerank_kernel,
    seed_frontier,
    sssp_kernel,
)
from .megakernel import BatchSpec, Megakernel, _batch_stub

__all__ = [
    "DG_UPDATE",
    "DG_QUERY",
    "V_UPDATES",
    "V_FREE",
    "V_DROPPED",
    "V_QUERIES",
    "DynGraph",
    "DynFrontierKernel",
    "SpliceKernel",
    "QueryKernel",
    "make_dyngraph_megakernel",
    "run_dyngraph",
    "reshard_dyngraph",
    "serve_dyngraph",
    "host_dyngraph",
    "host_incremental",
    "host_incremental_pagerank",
]

# Kernel-table ids: EXPAND keeps the frontier tier's fixed id 0 (so
# ``_spawn_blocks``-shaped spawns and ``migratable_fns=[FR_EXPAND]``
# carry over unchanged); the service kinds follow.
DG_UPDATE = 1
DG_QUERY = 2

# Value-slot counters beyond the frontier tier's pair (V_EDGES=0,
# V_RELAX=1): all combine across devices by sum except V_FREE, which is
# per-replica spare-block occupancy (identical on every replica once
# the same update set applied).
V_UPDATES = 2  # splices applied (idempotent: counted once per uid)
V_FREE = 3     # spare blocks in use (the global free-cursor ledger)
V_DROPPED = 4  # splices dropped on spare exhaustion (overflow-flagged)
V_QUERIES = 5  # QUERY descriptors served


def _env_spare_blocks() -> int:
    from ..runtime.env import env_int

    s = env_int("HCLIB_TPU_DYNGRAPH_SPARE_BLOCKS", 2)
    if s < 1:
        raise ValueError(
            f"HCLIB_TPU_DYNGRAPH_SPARE_BLOCKS={s} must be >= 1"
        )
    return int(s)


def _env_update_priority() -> int:
    from ..runtime.env import env_int

    return int(env_int("HCLIB_TPU_DYNGRAPH_UPDATE_PRIORITY", 0))


class DynGraph(Graph):
    """Blocked-CSR adjacency with per-vertex spare blocks and a
    registered update stream. The STATIC arrays (``deg``/``blk_count``/
    ``adj``/block prefixes) stay immutable host-side - updates ride as
    descriptors and mutate the DEVICE copy in-kernel; the host mirror
    (``updates``) feeds the twin, the certifier, and reshard's
    canonical-rebuild path."""

    def __init__(
        self,
        n: int,
        src: np.ndarray,
        dst: np.ndarray,
        weights: Optional[np.ndarray] = None,
        *,
        spare_blocks: Optional[int] = None,
        upd_cap: int = 256,
    ) -> None:
        super().__init__(n, src, dst, weights)
        spare = (
            _env_spare_blocks() if spare_blocks is None
            else int(spare_blocks)
        )
        if spare < 0:
            # 0 is a legal DEGENERATE config (every need-new splice
            # drops, overflow-flagged) - the drop-path test spelling;
            # the env knob keeps its >= 1 floor for real builds.
            raise ValueError(f"spare_blocks must be >= 0, got {spare}")
        self.spare = spare
        self.spare_base = self.nblocks  # static rows end here
        self.static_nblocks = self.nblocks
        self.nblocks = self.spare_base + self.n * spare
        self.indices = np.concatenate(
            [self.indices, np.full((self.n * spare, EBLOCK), -1, np.int32)]
        )
        self.weights = np.concatenate(
            [self.weights, np.zeros((self.n * spare, EBLOCK), np.int32)]
        )
        self.upd_cap = int(upd_cap)
        if self.upd_cap < 1:
            raise ValueError(f"upd_cap must be >= 1, got {upd_cap}")
        self.updates: List[Tuple[int, int, int]] = []

    # -- value-slot layout (counters | vt | static-counts | flags | state) --

    @property
    def bcs_base(self) -> int:
        """Immutable static block counts, one word per vertex: the
        boundary between static rows and spare ordinals that both the
        dyn spawner and the clamp read back after vt[1] mutates."""
        return VT_BASE + 3 * self.n

    @property
    def flag_base(self) -> int:
        """Applied-update flags, one word per uid (idempotence)."""
        return self.bcs_base + self.n

    @property
    def st_base(self) -> int:
        return self.flag_base + self.upd_cap

    def preset_values(self, num_values: int, state0: int) -> np.ndarray:
        iv = super().preset_values(num_values, state0)
        iv[self.bcs_base : self.bcs_base + self.n] = self.blk_count
        return iv

    # -- the update stream --

    def add_update(self, u: int, v: int, w: int = 1) -> int:
        """Register edge insert ``u -> v`` (weight ``w``); returns its
        uid (the applied-flag index every replica keys idempotence on)."""
        u, v, w = int(u), int(v), int(w)
        if not (0 <= u < self.n and 0 <= v < self.n):
            raise ValueError(
                f"update endpoints ({u}, {v}) out of range [0, {self.n})"
            )
        if w < 0:
            raise ValueError(f"update weight must be >= 0, got {w}")
        uid = len(self.updates)
        if uid >= self.upd_cap:
            raise ValueError(
                f"update stream exceeds upd_cap={self.upd_cap}: size the "
                "applied-flag region up (DynGraph(upd_cap=))"
            )
        self.updates.append((u, v, w))
        return uid

    def spare_needed(self) -> int:
        """Spare blocks the registered stream consumes (host mirror of
        the device free-cursor ledger; drops excluded)."""
        deg = self.deg.astype(np.int64).copy()
        bc = self.blk_count.astype(np.int64).copy()
        used = 0
        for u, _v, _w in self.updates:
            if deg[u] == bc[u] * EBLOCK:
                if bc[u] - int(self.blk_count[u]) >= self.spare:
                    continue  # dropped on-device, consumes nothing
                bc[u] += 1
                used += 1
            deg[u] += 1
        return used

    def mutated(self, count: Optional[int] = None) -> Graph:
        """The host twin's graph: static edges + the first ``count``
        updates (all by default), as a plain static ``Graph`` - the
        from-scratch reference arm the incremental fixpoint must match
        bit-for-bit (bfs/sssp) or conserve mass against (pagerank).
        Updates the device would DROP (spare exhaustion) are excluded,
        mirroring the in-kernel bounds check exactly."""
        ups = self.updates if count is None else self.updates[:count]
        deg = self.deg.astype(np.int64).copy()
        bc = self.blk_count.astype(np.int64).copy()
        kept: List[Tuple[int, int, int]] = []
        for u, v, w in ups:
            if deg[u] == bc[u] * EBLOCK:  # tail full: needs a new block
                if bc[u] - int(self.blk_count[u]) >= self.spare:
                    continue  # device drops it (overflow-flagged)
                bc[u] += 1
            deg[u] += 1
            kept.append((u, v, w))
        src0 = np.repeat(np.arange(self.n), self.deg)
        dst0 = (
            np.concatenate(self.adj) if self.m else np.zeros(0, np.int64)
        )
        w0 = (
            np.concatenate(self.adj_w) if self.m else np.zeros(0, np.int64)
        )
        src = np.concatenate([src0, np.asarray([u for u, _, _ in kept])])
        dst = np.concatenate([dst0, np.asarray([v for _, v, _ in kept])])
        ww = np.concatenate([w0, np.asarray([w for _, _, w in kept])])
        return Graph(self.n, src.astype(np.int64), dst.astype(np.int64),
                     ww.astype(np.int64))


# ---------------------------------------------------------- device tier


class DynFrontierKernel(FrontierKernel):
    """A frontier kernel bound to a mutable adjacency: EXPANDs clamp
    their live-edge count to the LOCAL vertex table (a replica that has
    not applied a splice yet must not read past its own live edges),
    and improving relaxes spawn through the two-range spare-aware
    spawner the factory injected."""

    def __init__(self, name, relax, weighted, state0,
                 graph: DynGraph) -> None:
        super().__init__(name, relax, weighted, state0)
        self.graph = graph

    def _eff_cnt(self, kctx, v, blk, cnt):
        g = self.graph
        vt = VT_BASE + 3 * v
        bs = kctx.ivalues[vt]
        deg = kctx.ivalues[vt + 2]
        bcs = kctx.ivalues[g.bcs_base + v]
        ordinal = jnp.where(
            blk >= jnp.int32(g.spare_base),
            bcs + (blk - jnp.int32(g.spare_base) - v * jnp.int32(g.spare)),
            blk - bs,
        )
        live = jnp.clip(deg - ordinal * EBLOCK, 0, EBLOCK)
        return jnp.minimum(cnt, live)


def _dyn_spawn(graph: DynGraph) -> Callable:
    """The spare-aware block spawner: static rows ``[bs, bs+min(bc,
    bcs))`` then spare ordinals ``[0, bc - min(bc, bcs))`` - two
    contiguous ranges, each block's live count derived from ``deg``
    exactly as the static spawner derives it."""
    spare_base, spare, bcs_base = (
        graph.spare_base, graph.spare, graph.bcs_base,
    )

    def spawn(kctx, u, carry) -> None:
        vt = VT_BASE + 3 * u
        bs = kctx.ivalues[vt]
        bc = kctx.ivalues[vt + 1]
        deg = kctx.ivalues[vt + 2]
        bcs = kctx.ivalues[bcs_base + u]
        ns = jnp.minimum(bc, bcs)

        def sp_static(i, _):
            cnt = jnp.clip(deg - i * EBLOCK, 0, EBLOCK)
            kctx.spawn(FR_EXPAND, [u, bs + i, carry, cnt], nargs=4)
            return 0

        jax.lax.fori_loop(0, ns, sp_static, 0)

        def sp_spare(j, _):
            i = bcs + j
            cnt = jnp.clip(deg - i * EBLOCK, 0, EBLOCK)
            kctx.spawn(
                FR_EXPAND,
                [u, jnp.int32(spare_base) + u * jnp.int32(spare) + j,
                 carry, cnt],
                nargs=4,
            )
            return 0

        jax.lax.fori_loop(0, bc - ns, sp_spare, 0)

    return spawn


def _dyn_frontier_kernel(kind: str, graph: DynGraph,
                         reps: int = 64) -> DynFrontierKernel:
    """The traversal family over a mutable adjacency: the SAME relax
    closures as the static tier (one relax trace = scalar/batched/mesh
    identity by construction), with the spare-aware spawner injected."""
    spawn = _dyn_spawn(graph)
    if kind == "bfs":
        base = bfs_kernel(spawn=spawn)
    elif kind == "sssp":
        base = sssp_kernel(spawn=spawn)
    elif kind == "pagerank":
        base = pagerank_kernel(reps=reps, spawn=spawn)
    else:
        raise ValueError(
            f"unknown dyngraph kind {kind!r} (bfs|sssp|pagerank)"
        )
    fk = DynFrontierKernel(
        base.name, base._relax, base.weighted, base.state0, graph
    )
    if kind == "pagerank":
        fk.reps = int(reps)
    return fk


class SpliceKernel:
    """The UPDATE kind: splice + incremental relax, both dispatch
    spellings off ONE ``_splice`` trace (the FrontierKernel pattern).

    Splice protocol (checked by hclint's ``check_splice``):
    - the tail append is a read-modify-write of the whole block row
      (HBM -> VMEM, set one lane, VMEM -> HBM), strictly ordered inside
      the slot so same-vertex updates in one batch serialize;
    - a FULL tail allocates the next spare ordinal and blind-writes a
      freshly built row - legal ONLY because the append cursor
      (``vt[1]``) owns fresh spare rows uniquely (the blind-overwrite
      exemption, rows >= spare_base);
    - no lane of a dyngraph build runs the cross-round prefetch (a
      prefetched slab could race the write-back of the same row).
    """

    def __init__(self, fk: DynFrontierKernel) -> None:
        self.fk = fk
        self.graph = fk.graph

    def scratch(self, slots: int) -> Dict[str, Any]:
        sc: Dict[str, Any] = {
            "dg_idx": pltpu.VMEM((slots, EBLOCK), jnp.int32),
            "dg_lsem": pltpu.SemaphoreType.DMA((slots,)),
        }
        if self.fk.weighted:
            sc["dg_wgt"] = pltpu.VMEM((slots, EBLOCK), jnp.int32)
        return sc

    def _splice(self, kctx, s: int, u, v, w, uid) -> None:
        g = self.graph
        vt = VT_BASE + 3 * u
        bs = kctx.ivalues[vt]
        bc = kctx.ivalues[vt + 1]
        deg = kctx.ivalues[vt + 2]
        bcs = kctx.ivalues[g.bcs_base + u]
        applied = kctx.ivalues[g.flag_base + uid]
        need_new = deg == bc * EBLOCK  # tail full (or no blocks yet)
        used = bc - bcs                # spare ordinals in use
        overflow = need_new & (used >= jnp.int32(g.spare))
        fresh = applied == 0
        kctx.flag_overflow(fresh & overflow)
        kctx.ivalues[V_DROPPED] = kctx.ivalues[V_DROPPED] + jnp.where(
            fresh & overflow, 1, 0
        )
        do = fresh & jnp.logical_not(overflow)
        nb = jnp.int32(g.spare_base) + u * jnp.int32(g.spare) + used
        # Tail row of the CURRENT chain (only read when ~need_new, where
        # bc >= 1): static row while the static tail has slack, else the
        # newest spare ordinal.
        tb_tail = jnp.where(bc <= bcs, bs + bc - 1, nb - 1)
        pos = deg - jnp.maximum(bc - 1, 0) * EBLOCK  # live edges in tail
        sem = kctx.scratch["dg_lsem"].at[s]
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, EBLOCK), 1)

        @pl.when(do & need_new)
        def _():
            # Blind-write the fresh spare row: build it whole in VMEM
            # (new edge in lane 0, the static fill elsewhere) and DMA it
            # out - no read, the append cursor owns row ``nb`` uniquely.
            kctx.scratch["dg_idx"][s : s + 1, :] = jnp.where(
                lane == 0, v, jnp.int32(-1)
            )
            cp = pltpu.make_async_copy(
                kctx.scratch["dg_idx"].at[s], kctx.data["indices"].at[nb],
                sem,
            )
            cp.start()
            if self.fk.weighted:
                kctx.scratch["dg_wgt"][s : s + 1, :] = jnp.where(
                    lane == 0, w, jnp.int32(0)
                )
                cpw = pltpu.make_async_copy(
                    kctx.scratch["dg_wgt"].at[s],
                    kctx.data["weights"].at[nb], sem,
                )
                cpw.start()
                cpw.wait()
            cp.wait()

        @pl.when(do & jnp.logical_not(need_new))
        def _():
            # Read-modify-write the tail row: the only writer of lanes
            # >= pos is this slot (earlier same-vertex slots already
            # folded their bumps into deg/bc before this read).
            cp = pltpu.make_async_copy(
                kctx.data["indices"].at[tb_tail],
                kctx.scratch["dg_idx"].at[s], sem,
            )
            cp.start()
            cp.wait()
            row = kctx.scratch["dg_idx"][s : s + 1, :]
            kctx.scratch["dg_idx"][s : s + 1, :] = jnp.where(
                lane == pos, v, row
            )
            cpo = pltpu.make_async_copy(
                kctx.scratch["dg_idx"].at[s],
                kctx.data["indices"].at[tb_tail], sem,
            )
            cpo.start()
            cpo.wait()
            if self.fk.weighted:
                cpw = pltpu.make_async_copy(
                    kctx.data["weights"].at[tb_tail],
                    kctx.scratch["dg_wgt"].at[s], sem,
                )
                cpw.start()
                cpw.wait()
                wrow = kctx.scratch["dg_wgt"][s : s + 1, :]
                kctx.scratch["dg_wgt"][s : s + 1, :] = jnp.where(
                    lane == pos, w, wrow
                )
                cpwo = pltpu.make_async_copy(
                    kctx.scratch["dg_wgt"].at[s],
                    kctx.data["weights"].at[tb_tail], sem,
                )
                cpwo.start()
                cpwo.wait()

        @pl.when(do)
        def _():
            # Fold the ledger bumps AFTER the block write retires, so a
            # concurrent reader that sees the new deg also sees the
            # edge (the monotone-fold ordering the protocol relies on).
            kctx.ivalues[vt + 1] = jnp.where(need_new, bc + 1, bc)
            kctx.ivalues[vt + 2] = deg + 1
            kctx.ivalues[g.flag_base + uid] = 1
            kctx.ivalues[V_FREE] = kctx.ivalues[V_FREE] + jnp.where(
                need_new, 1, 0
            )
            kctx.ivalues[V_UPDATES] = kctx.ivalues[V_UPDATES] + 1
            if self.fk.name != "fr_pagerank":
                # Incremental recompute: relax the ONE new edge with u's
                # current label - the same relax trace EXPAND runs, so
                # an improvement re-spawns v's blocks and nothing else.
                du = kctx.ivalues[self.fk.st_base + u]
                self.fk.relax(kctx, v, w, du)

    def scalar_kernel(self, ctx) -> None:
        u, v, w, uid = (ctx.arg(i) for i in range(4))
        self._splice(ctx, 0, u, v, w, uid)

    def batch_body(self, ctx) -> None:
        for b in range(ctx.width):
            @pl.when(ctx.live(b))
            def _(b=b):
                kctx = ctx.slot_ctx(b)
                self._splice(
                    kctx, b, ctx.arg(b, 0), ctx.arg(b, 1), ctx.arg(b, 2),
                    ctx.arg(b, 3),
                )


class QueryKernel:
    """The QUERY kind: publish vertex ``v``'s current label through the
    descriptor's out slot (egress mailboxes turn that into the query
    future's value at retirement). Mid-run queries read the TENTATIVE
    label - the serving semantic; post-drain queries read the exact
    fixpoint (what the bit-identity tests assert)."""

    def __init__(self, fk: DynFrontierKernel) -> None:
        self.fk = fk

    def _query(self, kctx, set_out) -> None:
        v = kctx.arg(0)
        kctx.ivalues[V_QUERIES] = kctx.ivalues[V_QUERIES] + 1
        set_out(kctx.ivalues[self.fk.st_base + v])

    def scalar_kernel(self, ctx) -> None:
        self._query(ctx, ctx.set_out)

    def batch_body(self, ctx) -> None:
        for b in range(ctx.width):
            @pl.when(ctx.live(b))
            def _(b=b):
                kctx = ctx.slot_ctx(b)
                self._query(kctx, kctx.set_out)


# ------------------------------------------------------------ megakernel


def make_dyngraph_megakernel(
    kind: str,
    graph: DynGraph,
    *,
    width: int = 8,
    capacity: int = 512,
    num_values: Optional[int] = None,
    interpret: Optional[bool] = None,
    trace=None,
    checkpoint: Optional[bool] = None,
    lane_max_age: Optional[int] = None,
    priority_buckets: Optional[int] = None,
    delta: Optional[int] = None,
    update_priority: Optional[int] = None,
    reps: int = 64,
) -> Megakernel:
    """Build the dynamic-graph service megakernel: the traversal's
    EXPAND lane plus the UPDATE (splice) and QUERY kinds. ``width=0``
    is the all-scalar bit-identity arm; ``width>0`` routes every kind
    through its own batch lane - all with the cross-round prefetch OFF
    (the splice protocol: a prefetched slab must never race a block
    write-back). ``priority_buckets=B`` maps updates and queries to
    distinct priority classes: UPDATEs pin to bucket
    ``update_priority`` (default 0 - inserts beat queries), QUERYs to
    the lowest class, EXPANDs keep the traversal's own bucket function."""
    if kind not in ("bfs", "sssp", "pagerank"):
        raise ValueError(
            f"unknown dyngraph kind {kind!r} (bfs|sssp|pagerank)"
        )
    if not isinstance(graph, DynGraph):
        raise TypeError(
            "make_dyngraph_megakernel needs a DynGraph (the static "
            "Graph has no spare rows to splice into)"
        )
    fk = _dyn_frontier_kernel(kind, graph, reps=reps)
    upd = SpliceKernel(fk)
    qk = QueryKernel(fk)
    if num_values is None:
        num_values = graph.num_value_slots + 16
    if priority_buckets is None:
        from ..runtime.env import env_int

        priority_buckets = env_int("HCLIB_TPU_PRIORITY_BUCKETS", None)
    priority_buckets = int(priority_buckets or 0)
    if priority_buckets and not width:
        raise ValueError(
            "priority_buckets needs the batched arm (width > 0): the "
            "bucket rings layer over the per-kind batch lanes"
        )
    if update_priority is None:
        update_priority = _env_update_priority()
    update_priority = int(update_priority)
    if priority_buckets:
        update_priority = max(0, min(update_priority,
                                     priority_buckets - 1))
    query_priority = max(0, priority_buckets - 1)
    if delta is None:
        delta = default_delta(graph)
    if width:
        kernels = [
            (fk.name, _batch_stub),
            ("dg_update", _batch_stub),
            ("dg_query", _batch_stub),
        ]
        up, qp = int(update_priority), int(query_priority)
        route = {
            fk.name: BatchSpec(
                fk.batch_body, width=width, prefetch=False,
                priority=_bucket_fn(fk.name, delta,
                                    getattr(fk, "reps", 64)),
            ),
            "dg_update": BatchSpec(
                upd.batch_body, width=width, prefetch=False,
                priority=lambda arg, up=up: jnp.int32(up),
            ),
            "dg_query": BatchSpec(
                qk.batch_body, width=width, prefetch=False,
                priority=lambda arg, qp=qp: jnp.int32(qp),
            ),
        }
        scratch = dict(fk.batch_scratch(width))
        scratch.update(upd.scratch(width))
        if lane_max_age is None:
            from ..runtime.env import env_set

            if env_set("HCLIB_TPU_LANE_MAX_AGE"):
                lane_max_age = None  # env wins, Megakernel resolves it
            elif priority_buckets:
                lane_max_age = 2 * capacity  # starvation backstop
            else:
                lane_max_age = 4 * width
    else:
        kernels = [
            (fk.name, fk.scalar_kernel),
            ("dg_update", upd.scalar_kernel),
            ("dg_query", qk.scalar_kernel),
        ]
        route = None
        scratch = dict(fk.scalar_scratch())
        scratch.update(upd.scratch(1))
        lane_max_age = 0 if lane_max_age is None else lane_max_age
    fk.st_base = graph.st_base
    mk = Megakernel(
        kernels=kernels,
        route=route,
        data_specs=fk.data_specs(graph),
        scratch_specs=scratch,
        capacity=capacity,
        num_values=num_values,
        succ_capacity=8,
        interpret=interpret,
        trace=trace,
        checkpoint=checkpoint,
        lane_max_age=lane_max_age,
        priority_buckets=priority_buckets,
    )
    mk._frontier_layout = (fk.name, graph.n, graph.nblocks, graph.st_base)
    # The dyngraph layout stamp: hclint's splice-protocol check, the
    # checkpoint snapshot path, and reshard's canonical rebuild all key
    # off it (plain ints, so it serializes into bundle meta verbatim).
    mk._dyngraph = {
        "kind": kind,
        "n": graph.n,
        "spare": graph.spare,
        "spare_base": graph.spare_base,
        "total_blocks": graph.nblocks,
        "bcs_base": graph.bcs_base,
        "flag_base": graph.flag_base,
        "upd_cap": graph.upd_cap,
        "st_base": graph.st_base,
        "weighted": bool(fk.weighted),
        "update_kind": DG_UPDATE,
        "query_kind": DG_QUERY,
        "update_priority": int(update_priority),
        "buckets": priority_buckets,
        "reps": int(getattr(fk, "reps", 0) or 0),
    }
    # Schedule-independence claim over the MUTATED fixpoint: updates
    # stamp in at run time (run_dyngraph), the tile-claim discipline -
    # an unbound claim certifies as "unbound" rather than lying.
    mk.si_claim = ("dyngraph", kind, getattr(fk, "reps", None),
                   priority_buckets, None)
    return mk


def _bind_updates(mk: Megakernel, graph: DynGraph) -> None:
    """Stamp the registered update stream into the si claim (the bound
    spelling certify_claim actually certifies) AND the layout stamp
    (checkpoint manifests carry it; reshard's canonical rebuild maps
    applied-flag uids back to their (u, v, w) endpoints through it)."""
    tag, kind, reps, buckets, _ = mk.si_claim
    mk.si_claim = (tag, kind, reps, buckets, tuple(graph.updates))
    mk._dyngraph["updates"] = [
        [int(u), int(v), int(w)] for u, v, w in graph.updates
    ]


# ------------------------------------------------------------ host twin


def host_dyngraph(
    kind: str,
    graph: DynGraph,
    src: int = 0,
    *,
    m0: int = 1 << 14,
    reps: int = 64,
) -> np.ndarray:
    """The from-scratch host reference ON THE MUTATED GRAPH - what the
    incremental device fixpoint must match bit-for-bit (bfs/sssp)."""
    g = graph.mutated()
    if kind == "bfs":
        return host_bfs(g, src)
    if kind == "sssp":
        return host_sssp(g, src)
    if kind == "pagerank":
        rank, _ = host_pagerank_push(g, m0=m0, reps=reps)
        return rank
    raise ValueError(f"unknown dyngraph kind {kind!r}")


def host_incremental(
    kind: str,
    graph: DynGraph,
    src: int = 0,
    *,
    order: Optional[Sequence[int]] = None,
) -> np.ndarray:
    """Pure-python incremental twin (bfs/sssp): apply seed expansion and
    the update stream as a SINGLE op pool processed in ``order`` (a
    permutation of the initial ops; spawned re-expansions append), each
    update splicing then relaxing with u's current label - exactly the
    device protocol. The certifier runs this under K permutations and
    asserts every fixpoint equals the from-scratch reference."""
    if kind not in ("bfs", "sssp"):
        raise ValueError(
            "host_incremental models the label-correcting kinds "
            f"(bfs|sssp), got {kind!r}"
        )
    n = graph.n
    adj: List[List[Tuple[int, int]]] = [
        [(int(t), int(w)) for t, w in zip(graph.adj[v], graph.adj_w[v])]
        for v in range(n)
    ]
    deg = graph.deg.astype(np.int64).copy()
    bc = graph.blk_count.astype(np.int64).copy()
    dist = np.full(n, INF, np.int64)
    dist[int(src)] = 0
    ops: List[Tuple] = [("expand", int(src))]
    ops += [("update", u, v, w) for (u, v, w) in graph.updates]
    if order is None:
        order = range(len(ops))
    pending: List[Tuple] = [ops[i] for i in order]
    if len(pending) != len(ops):
        raise ValueError("order must be a permutation of the op pool")

    def relax(u, v, w):
        nd = dist[u] + (1 if kind == "bfs" else w)
        if dist[u] < INF and nd < dist[v]:
            dist[v] = nd
            pending.append(("expand", v))

    while pending:
        op = pending.pop(0)
        if op[0] == "expand":
            v = op[1]
            for t, w in list(adj[v]):
                relax(v, t, w)
        else:
            _, u, v, w = op
            if deg[u] == bc[u] * EBLOCK:  # tail full
                if bc[u] - int(graph.blk_count[u]) >= graph.spare:
                    continue  # dropped, exactly as the device drops it
                bc[u] += 1
            deg[u] += 1
            adj[u].append((v, w))
            relax(u, v, w)
    return dist.astype(np.int32)


def host_incremental_pagerank(
    graph: DynGraph,
    *,
    m0: int = 1 << 14,
    reps: int = 64,
    order: Optional[Sequence[int]] = None,
) -> Tuple[np.ndarray, int]:
    """Pure-python incremental pagerank twin: deliveries and splices
    interleave in ``order``; splices are mass-neutral (degree steers
    only FUTURE splits), so ``rank.sum() == n * m0`` holds for EVERY
    order - the conservation certificate. Returns (rank, deliveries)."""
    from .frontier import _pr_split

    n = graph.n
    adj: List[List[int]] = [
        [int(t) for t in graph.adj[v]] for v in range(n)
    ]
    deg = graph.deg.astype(np.int64).copy()
    bc = graph.blk_count.astype(np.int64).copy()
    rank = np.zeros(n, np.int64)
    ops: List[Tuple] = []
    for v in range(n):
        d = int(deg[v])
        qc = _pr_split(m0, d)
        if m0 >= reps and qc > 0 and d > 0:
            rank[v] = m0 - d * qc
            for u in adj[v]:
                ops.append(("deliver", int(u), qc))
        else:
            rank[v] = m0
    ops += [("update", u, v, w) for (u, v, w) in graph.updates]
    if order is None:
        order = range(len(ops))
    pending: List[Tuple] = [ops[i] for i in order]
    if len(pending) != len(ops):
        raise ValueError("order must be a permutation of the op pool")
    deliveries = 0
    while pending:
        op = pending.pop(0)
        if op[0] == "update":
            _, u, v, w = op
            if deg[u] == bc[u] * EBLOCK:
                if bc[u] - int(graph.blk_count[u]) >= graph.spare:
                    continue
                bc[u] += 1
            deg[u] += 1
            adj[u].append(int(v))
            continue
        _, u, q = op
        deliveries += 1
        d = int(deg[u])
        qc = _pr_split(q, d)
        if q >= reps and qc > 0 and d > 0:
            rank[u] += q - d * qc
            for t in list(adj[u]):
                pending.append(("deliver", int(t), qc))
        else:
            rank[u] += q
    return rank, deliveries


# ---------------------------------------------------------------- runner


def _seed_builders(
    graph: DynGraph,
    kind: str,
    src: int,
    m0: int,
    reps: int,
    queries: Sequence[int],
    num_values: int,
    ndev: int,
    dev_of,
) -> Tuple[List[TaskGraphBuilder], List[int]]:
    """Per-device builders: traversal seeds dealt by placement, the
    update stream BROADCAST to every device (UPDATE is non-migratable
    and idempotent - every replica applies every splice), queries dealt
    round-robin with out slots above the state region."""
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for b in builders:
        b.reserve_values(graph.num_value_slots)
    seeds = seed_frontier(None, graph, kind, src=src, m0=m0, reps=reps)
    pcounts = [0] * ndev
    for i, args in enumerate(seeds):
        d = int(dev_of(i, max(1, len(seeds))))
        if not 0 <= d < ndev:
            raise ValueError(
                f"placement sent seed {i} to device {d} (mesh has {ndev})"
            )
        builders[d].add(FR_EXPAND, args=list(args))
        pcounts[d] += 1
    for uid, (u, v, w) in enumerate(graph.updates):
        for b in builders:
            b.add(DG_UPDATE, args=[u, v, w, uid])
    qbase = graph.st_base + graph.n
    for qi, v in enumerate(queries):
        slot = qbase + qi
        if slot >= num_values:
            raise ValueError(
                f"query {qi} wants out slot {slot} >= num_values "
                f"{num_values}: raise num_values"
            )
        builders[qi % ndev].add(DG_QUERY, args=[int(v)], out=slot)
    return builders, pcounts


def run_dyngraph(
    kind: str,
    graph: DynGraph,
    src: int = 0,
    *,
    updates: Optional[Sequence[Tuple[int, int, int]]] = None,
    queries: Sequence[int] = (),
    width: int = 8,
    m0: int = 1 << 14,
    reps: int = 64,
    capacity: int = 512,
    interpret: Optional[bool] = None,
    trace=None,
    fuel: Optional[int] = None,
    lane_max_age: Optional[int] = None,
    priority_buckets: Optional[int] = None,
    delta: Optional[int] = None,
    update_priority: Optional[int] = None,
    mk: Optional[Megakernel] = None,
    placement=None,
    mesh=None,
    quantum: int = 64,
    window: int = 16,
    hop_order=None,
) -> Tuple[np.ndarray, Dict]:
    """One concurrent traversal + update storm to the fixpoint.
    ``updates`` (``(u, v[, w])`` tuples) register on the graph and ride
    as UPDATE descriptors - on a mesh, broadcast to every device.
    Returns ``(result, info)``: the exact fixpoint ON THE MUTATED GRAPH
    (bit-identical to ``host_dyngraph`` for bfs/sssp; mass-conserving
    for pagerank), with ``info`` carrying ``edges``/``relaxations``
    plus ``updates_applied``/``spare_in_use``/``dropped``/``queries``
    and per-query out values (``query_values``; tentative when queries
    raced the traversal, exact once it drained first)."""
    for up in updates or ():
        if len(up) == 2:
            graph.add_update(up[0], up[1])
        else:
            graph.add_update(up[0], up[1], up[2])
    if mk is None:
        mk = make_dyngraph_megakernel(
            kind, graph, width=width, capacity=capacity,
            interpret=interpret, trace=trace, lane_max_age=lane_max_age,
            priority_buckets=priority_buckets, delta=delta,
            update_priority=update_priority, reps=reps,
        )
    else:
        dg = getattr(mk, "_dyngraph", None)
        if dg is None or dg["n"] != graph.n or dg["kind"] != kind or (
            dg["st_base"] != graph.st_base
        ):
            raise ValueError(
                "prebuilt megakernel is not bound to this dyngraph "
                f"layout (stamp {dg}): build one per (kind, graph) via "
                "make_dyngraph_megakernel"
            )
    _bind_updates(mk, graph)
    fk_state0 = INF if kind in ("bfs", "sssp") else 0
    st = graph.st_base
    iv = graph.preset_values(mk.num_values, fk_state0)
    if kind in ("bfs", "sssp"):
        iv[st + int(src)] = 0
    else:
        iv[st : st + graph.n] = _pr_seed_rank(graph, m0, reps).astype(
            np.int32
        )

    def finish(iv_rows, info):
        rows = np.asarray(iv_rows, np.int64)
        if rows.ndim == 1:
            rows = rows[None]
        states = rows[:, st : st + graph.n]
        if kind in ("bfs", "sssp"):
            result = states.min(axis=0).astype(np.int32)
        else:
            result = states.sum(axis=0) - (
                (rows.shape[0] - 1) * iv[st : st + graph.n].astype(np.int64)
            )
        flags = rows[:, graph.flag_base : graph.flag_base + graph.upd_cap]
        info["edges"] = int(rows[:, V_EDGES].sum())
        info["relaxations"] = int(rows[:, V_RELAX].sum())
        info["updates_applied"] = int((flags.max(axis=0) != 0).sum())
        info["spare_in_use"] = int(rows[:, V_FREE].max())
        info["dropped"] = int(rows[:, V_DROPPED].max())
        info["queries"] = int(rows[:, V_QUERIES].sum())
        qbase = st + graph.n
        info["query_values"] = [
            int(rows[qi % rows.shape[0], qbase + qi])
            for qi in range(len(queries))
        ]
        return result, info

    if placement is None:
        builders, _ = _seed_builders(
            graph, kind, src, m0, reps, queries, mk.num_values, 1,
            lambda i, tot: 0,
        )
        iv_o, _, info = mk.run(
            builders[0], data=dict(fk_data(graph, mk)), ivalues=iv,
            fuel=1 << 22 if fuel is None else fuel,
        )
        return finish(iv_o, info)

    if fuel is not None:
        raise ValueError(
            "fuel= applies to the single-device path only; bound a mesh "
            "run with quantum= instead"
        )
    p = resolve_placement(placement)
    from ..parallel.mesh import cpu_mesh

    if mesh is None:
        if not isinstance(p, MeshPlacement):
            raise ValueError(
                "a dist-func placement needs an explicit mesh= (a "
                "MeshPlacement knows its own device count)"
            )
        mesh = cpu_mesh(p.ndev, axis_name="q")
    ndev = int(np.prod(mesh.devices.shape))
    dev_of = p.device_of if isinstance(p, MeshPlacement) else (
        lambda i, tot: p(1, i, tot)
    )
    builders, pcounts = _seed_builders(
        graph, kind, src, m0, reps, queries, mk.num_values, ndev, dev_of
    )
    data = fk_data(graph, mk)
    stacked_iv = np.broadcast_to(iv, (ndev,) + iv.shape).copy()
    stacked = {
        k: np.broadcast_to(v, (ndev,) + v.shape).copy()
        for k, v in data.items()
    }
    from .sharded import ShardedMegakernel

    if hop_order is None and isinstance(p, MeshPlacement):
        hop_order = p.hop_order()
    smk = ShardedMegakernel(mk, mesh, migratable_fns=[FR_EXPAND])
    iv_o, _, info = smk.run(
        builders, data=stacked, ivalues=stacked_iv, steal=True,
        quantum=quantum, window=window, hop_order=hop_order,
    )
    info["placement_counts"] = pcounts
    info["hop_order"] = list(hop_order) if hop_order else None
    return finish(iv_o, info)


def fk_data(graph: DynGraph, mk: Megakernel) -> Dict[str, np.ndarray]:
    """The device data buffers (static rows + pristine spare rows)."""
    d = {"indices": graph.indices}
    if mk._dyngraph["weighted"]:
        d["weights"] = graph.weights
    return d


# -------------------------------------------------------- serving loop


def serve_dyngraph(
    kind: str,
    graph: DynGraph,
    src: int = 0,
    *,
    updates: Sequence[Tuple[int, ...]] = (),
    queries: Sequence[int] = (),
    update_tenant: str = "updates",
    query_tenant: str = "queries",
    width: int = 0,
    m0: int = 1 << 14,
    reps: int = 64,
    capacity: int = 512,
    interpret: Optional[bool] = None,
    trace=None,
    checkpoint: Optional[bool] = None,
    lane_max_age: Optional[int] = None,
    priority_buckets: Optional[int] = None,
    delta: Optional[int] = None,
    update_priority: Optional[int] = None,
    ring_capacity: int = 64,
    egress_depth: int = 64,
    quantum: int = 1 << 10,
    max_rounds: int = 256,
    result_timeout_s: float = 30.0,
) -> Tuple[np.ndarray, Dict]:
    """Serve one resident adjacency to concurrent tenants through the
    front door: an ``updates`` lane and a ``queries`` lane submit
    UPDATE/QUERY descriptors against the SAME running traversal, each
    submission returning a completion-mailbox future (``Admission.
    future``) that resolves to the retired row's out-slot value - a
    query future resolves to the label the service published (tentative
    while the traversal races, exact once it drained). The lanes are
    distinct WRR classes at the ring (TenantSpec weights); the DEVICE
    priority classes (``update_priority=`` over bucket rings) are the
    batched mesh arm's - the stream embedding is scalar-tier only.
    Returns ``(result, info)`` shaped like
    ``run_dyngraph`` plus ``info['query_results']`` (future-resolved
    values), ``info['serve_stats']`` (lane + egress ledgers, the
    conservation identity closed) and ``info['splice_trace']`` (one
    host TR_SPLICE record in the flight-recorder ABI)."""
    import time as _time

    from .egress import EgressSpec
    from .inject import StreamingMegakernel
    from .tenants import TenantSpec, TenantTable
    from .tracebuf import TR_SPLICE, host_trace_info

    if width:
        raise ValueError(
            "serve_dyngraph runs the scalar arm (width=0): the stream "
            "front door's core embedding carries no batch-lane scratch; "
            "bucketed/batched service rides the mesh path "
            "(run_dyngraph(placement=...))"
        )
    for up in updates or ():
        graph.add_update(*up)
    mk = make_dyngraph_megakernel(
        kind, graph, width=width, capacity=capacity,
        interpret=interpret, trace=trace, checkpoint=checkpoint,
        lane_max_age=lane_max_age, priority_buckets=priority_buckets,
        delta=delta, update_priority=update_priority, reps=reps,
    )
    _bind_updates(mk, graph)
    region = -(-int(ring_capacity) // 16) * 8  # two lanes over the ring
    table = TenantTable(
        [TenantSpec(update_tenant), TenantSpec(query_tenant)],
        max(8, region), egress=EgressSpec(depth=egress_depth),
    )
    sm = StreamingMegakernel(mk, ring_capacity=ring_capacity,
                             tenants=table)
    st = graph.st_base
    fk_state0 = INF if kind in ("bfs", "sssp") else 0
    iv = graph.preset_values(mk.num_values, fk_state0)
    if kind in ("bfs", "sssp"):
        iv[st + int(src)] = 0
    else:
        iv[st : st + graph.n] = _pr_seed_rank(graph, m0, reps).astype(
            np.int32
        )
    seed = TaskGraphBuilder()
    seed.reserve_values(graph.num_value_slots)
    for args in seed_frontier(None, graph, kind, src=src, m0=m0,
                              reps=reps):
        seed.add(FR_EXPAND, args=list(args))
    upd_futs = []
    for uid, (u, v, w) in enumerate(graph.updates):
        adm = sm.submit(update_tenant, DG_UPDATE, args=[u, v, w, uid])
        if not adm.accepted:
            raise RuntimeError(
                f"update lane rejected uid {uid}: {adm.reason!r}"
            )
        upd_futs.append(adm.future)
    qbase = st + graph.n
    q_futs = []
    for qi, v in enumerate(queries):
        slot = qbase + qi
        if slot >= mk.num_values:
            raise ValueError(
                f"query {qi} wants out slot {slot} >= num_values "
                f"{mk.num_values}: raise num_values"
            )
        adm = sm.submit(query_tenant, DG_QUERY, args=[int(v)], out=slot)
        if not adm.accepted:
            raise RuntimeError(
                f"query lane rejected query {qi}: {adm.reason!r}"
            )
        q_futs.append(adm.future)
    sm.close()
    t0 = _time.monotonic_ns()
    iv_o, info = sm.run_stream(
        seed, ivalues=iv, data=dict(fk_data(graph, mk)),
        quantum=quantum, max_rounds=max_rounds,
    )
    t1 = _time.monotonic_ns()
    rows = np.asarray(iv_o, np.int64)[None]
    if kind in ("bfs", "sssp"):
        result = rows[0, st : st + graph.n].astype(np.int32)
    else:
        result = rows[0, st : st + graph.n]
    flags = rows[0, graph.flag_base : graph.flag_base + graph.upd_cap]
    info["edges"] = int(rows[0, V_EDGES])
    info["relaxations"] = int(rows[0, V_RELAX])
    info["updates_applied"] = int((flags != 0).sum())
    info["spare_in_use"] = int(rows[0, V_FREE])
    info["dropped"] = int(rows[0, V_DROPPED])
    info["queries"] = int(rows[0, V_QUERIES])
    info["query_values"] = [
        int(rows[0, qbase + qi]) for qi in range(len(queries))
    ]
    info["update_futures"] = upd_futs
    info["query_futures"] = q_futs
    info["query_results"] = [
        int(f.result(timeout=result_timeout_s)) for f in q_futs
    ]
    for f in upd_futs:
        f.result(timeout=result_timeout_s)
    info["serve_stats"] = sm.stats_dict()
    applied, dropped = info["updates_applied"], info["dropped"]
    info["splice_trace"] = host_trace_info(
        [[TR_SPLICE, 0, (applied << 16) | dropped,
          info["spare_in_use"]]],
        t0, max(t1, t0 + 1),
    )
    return result, info


# ----------------------------------------------------- elastic reshard


def reshard_dyngraph(bundle, ndev_new: int):
    """Re-home a quiesced dyngraph bundle onto ``ndev_new`` devices -
    the mutated-adjacency arm of ``CheckpointBundle.reshard`` (which
    delegates here off ``meta['dyngraph']``).

    The generic reshard refuses per-device data buffers because no
    generic fold exists; a dyngraph bundle has exactly the fold the
    generic path lacks. Each device's adjacency is the static graph
    plus the subset of the (broadcast, idempotent) update stream that
    device has applied, appended at the tail of each endpoint's chain.
    So the merge rebuilds ONE canonical adjacency - static rows plus
    the union-applied updates spliced in uid order - and broadcasts it
    (with the matching vt / applied flags / free cursor) to every new
    device. Canonical uid order may permute edges WITHIN a vertex's
    appended tail relative to what some replica held; the fixpoint is
    adjacency-order-free (that is the certified claim), so results are
    unchanged. Labels min-fold (bfs/sssp; a pagerank mid-run reshard is
    refused - per-device rank shares have no device-count-free fold),
    accumulator counters sum-fold, and the conservation identity
    ``sum(deg) == m_static + |union-applied|`` is asserted, as is each
    old device's free-cursor ledger (``V_FREE``) against its own vt.

    Pending residue: EXPAND and QUERY rows deal round-robin (QUERY's
    dynamic out slot is safe precisely because the value region is
    broadcast-identical); pending UPDATE replicas dedupe by uid, drop
    the union-applied ones (their splice already rides the canonical
    arrays; re-delivery would be a no-op anyway), and BROADCAST to
    every new device - the mesh invariant "every replica sees every
    update" survives the resize."""
    from ..runtime.checkpoint import CheckpointBundle, CheckpointError
    from .descriptor import (
        DESC_WORDS, F_A0, F_CSR_N, F_DEP, F_FN, F_HOME, F_SUCC0,
        F_SUCC1, NO_TASK,
    )
    from .megakernel import C_ALLOC, C_EXECUTED, C_PENDING, C_VALLOC

    dg = dict(bundle.meta["dyngraph"])
    kind = dg["kind"]
    if kind == "pagerank":
        raise CheckpointError(
            "dyngraph reshard supports bfs/sssp only: pagerank's "
            "per-device rank shares combine by sum-minus-preset over "
            "the ORIGINAL device count, so no device-count-free fold "
            "exists mid-run - drain to the fixpoint and reseed instead"
        )
    n = int(dg["n"])
    spare = int(dg["spare"])
    spare_base = int(dg["spare_base"])
    bcs_base = int(dg["bcs_base"])
    flag_base = int(dg["flag_base"])
    upd_cap = int(dg["upd_cap"])
    st_base = int(dg["st_base"])
    updates = [tuple(int(x) for x in u) for u in (dg.get("updates") or ())]
    upd_kind = int(dg.get("update_kind", DG_UPDATE))
    q_kind = int(dg.get("query_kind", DG_QUERY))

    tasks = np.asarray(bundle.arrays["tasks"])
    counts = np.asarray(bundle.arrays["counts"])
    ivalues = np.asarray(bundle.arrays["ivalues"]).astype(np.int64)
    ndev, cap, _ = tasks.shape
    waits = bundle.arrays.get("waits")
    if waits is not None and int(np.asarray(waits)[:, 0, 0].sum()):
        raise CheckpointError(
            "dyngraph reshard cannot re-home parked waits (the service "
            "kinds never wait on-device); drain the wait table first"
        )
    if "ictl" in bundle.arrays and int(
        np.asarray(bundle.arrays["ictl"])[:, 0].sum()
    ):
        raise CheckpointError(
            "dyngraph reshard: inject-ring residue present - let the "
            "poll consume the ring (or close and drain) before a resize "
            "so every update/query is a scheduler row or a flag"
        )
    if int(ivalues[:, V_DROPPED].max()):
        raise CheckpointError(
            "dyngraph reshard: a replica dropped splices on spare "
            "exhaustion (V_DROPPED != 0) - the adjacency is no longer "
            "the registered stream's; rebuild with more spare blocks"
        )
    other = [
        k for k in bundle.arrays
        if k.startswith("data/") and k not in ("data/indices",
                                               "data/weights")
    ]
    if other:
        raise CheckpointError(
            f"dyngraph reshard: no fold for extra data buffers {other}"
        )
    ind = np.asarray(bundle.arrays["data/indices"]).astype(np.int32)
    weighted = bool(dg.get("weighted")) and "data/weights" in bundle.arrays
    wgt = (
        np.asarray(bundle.arrays["data/weights"]).astype(np.int32)
        if weighted else None
    )

    # ---- union-applied flags -> the canonical update subset ----
    flags = ivalues[:, flag_base : flag_base + upd_cap]
    union = flags.max(axis=0)
    if int(union[len(updates):].max(initial=0)):
        raise CheckpointError(
            "dyngraph reshard: applied flag set beyond the registered "
            f"update stream ({len(updates)} updates in the manifest) - "
            "the bundle and its meta disagree"
        )
    applied_uids = [u for u in range(len(updates)) if union[u]]

    # ---- per-device ledgers + the shared static skeleton ----
    vt = ivalues[:, VT_BASE : VT_BASE + 3 * n].reshape(ndev, n, 3)
    bcs = ivalues[0, bcs_base : bcs_base + n]
    bs = vt[0, :, 0]
    for d in range(1, ndev):
        if not np.array_equal(ivalues[d, bcs_base : bcs_base + n], bcs):
            raise CheckpointError(
                f"dyngraph reshard: device {d} static block counts "
                "diverged from device 0 (immutable region corrupt)"
            )
        if not np.array_equal(vt[d, :, 0], bs):
            raise CheckpointError(
                f"dyngraph reshard: device {d} block starts diverged "
                "(immutable region corrupt)"
            )
    per_dev_applied = np.zeros((ndev, n), np.int64)
    for d in range(ndev):
        for uid in range(len(updates)):
            if flags[d, uid]:
                per_dev_applied[d, updates[uid][0]] += 1
    deg0 = vt[0, :, 2] - per_dev_applied[0]
    for d in range(ndev):
        if not np.array_equal(vt[d, :, 2] - per_dev_applied[d], deg0):
            raise CheckpointError(
                f"dyngraph reshard: device {d} degrees minus its own "
                "applied splices disagree with the static degrees - "
                "edge-count conservation does not hold"
            )
        used_d = int((vt[d, :, 1] - bcs).sum())
        if used_d != int(ivalues[d, V_FREE]):
            raise CheckpointError(
                f"dyngraph reshard: device {d} free-cursor ledger "
                f"(V_FREE={int(ivalues[d, V_FREE])}) != its vt spare "
                f"occupancy ({used_d})"
            )
    if int(deg0.min(initial=0)) < 0:
        raise CheckpointError(
            "dyngraph reshard: negative static degree reconstructed - "
            "the applied flags and the vertex table disagree"
        )

    # ---- canonical rebuild: truncate device 0 to static, replay ----
    def _pos(u: int, p: int) -> Tuple[int, int]:
        blk = p // EBLOCK
        if blk < int(bcs[u]):
            return int(bs[u]) + blk, p % EBLOCK
        return spare_base + u * spare + (blk - int(bcs[u])), p % EBLOCK

    can_ind = ind[0].copy()
    can_wgt = wgt[0].copy() if weighted else None
    for u in range(n):
        for p in range(int(deg0[u]), int(vt[0, u, 2])):
            r, c = _pos(u, p)
            can_ind[r, c] = -1
            if weighted:
                can_wgt[r, c] = 0
    can_bc = bcs.copy()
    can_deg = deg0.copy()
    for uid in applied_uids:
        u, v, w = updates[uid]
        if can_deg[u] == can_bc[u] * EBLOCK:
            if can_bc[u] - bcs[u] >= spare:
                raise CheckpointError(
                    f"dyngraph reshard: replaying uid {uid} overflows "
                    f"vertex {u}'s spare region - a flag is set for a "
                    "splice the device could not have applied"
                )
            r, c = spare_base + u * spare + int(can_bc[u] - bcs[u]), 0
            can_ind[r, :] = -1
            if weighted:
                can_wgt[r, :] = 0
            can_bc[u] += 1
        else:
            r, c = _pos(u, int(can_deg[u]))
        can_ind[r, c] = v
        if weighted:
            can_wgt[r, c] = w
        can_deg[u] += 1
    m_static = int(deg0.sum())
    if int(can_deg.sum()) != m_static + len(applied_uids):
        raise CheckpointError(
            "dyngraph reshard edge-count conservation failed: "
            f"{int(can_deg.sum())} canonical edges != {m_static} static "
            f"+ {len(applied_uids)} union-applied"
        )

    # ---- residue scan: classify, dedupe, refuse links ----
    expand_rows: List[np.ndarray] = []
    query_rows: List[np.ndarray] = []
    upd_rows: Dict[int, np.ndarray] = {}
    for d in range(ndev):
        for i in range(int(counts[d, C_ALLOC])):
            row = tasks[d, i]
            if int(row[F_DEP]) == -1:
                continue  # tombstone
            if (
                int(row[F_DEP]) != 0
                or int(row[F_SUCC0]) != NO_TASK
                or int(row[F_SUCC1]) != NO_TASK
                or int(row[F_CSR_N]) != 0
                or int(row[F_HOME]) >= 0
            ):
                raise CheckpointError(
                    f"dyngraph reshard: device {d} row {i} is not "
                    "link-free; quiesce at a round boundary drains "
                    "dependent subgraphs first"
                )
            fn = int(row[F_FN])
            if fn == upd_kind:
                uid = int(row[F_A0 + 3])
                if not 0 <= uid < len(updates):
                    raise CheckpointError(
                        f"dyngraph reshard: pending UPDATE row carries "
                        f"uid {uid} outside the registered stream"
                    )
                if not union[uid]:
                    upd_rows.setdefault(uid, row.copy())
            elif fn == q_kind:
                query_rows.append(row.copy())
            else:
                expand_rows.append(row.copy())
    pend_upd = [upd_rows[k] for k in sorted(upd_rows)]

    # ---- deal + rebuild the scheduler arrays ----
    va = int(counts[:, C_VALLOC].max())
    V = ivalues.shape[1]
    tasks_new = np.zeros((ndev_new, cap, DESC_WORDS), np.int32)
    ready_new = np.full((ndev_new, cap), NO_TASK, np.int32)
    counts_new = np.zeros((ndev_new, 8), np.int32)
    parts: List[List[np.ndarray]] = [list(pend_upd)
                                     for _ in range(ndev_new)]
    for i, row in enumerate(expand_rows):
        parts[i % ndev_new].append(row)
    for i, row in enumerate(query_rows):
        parts[i % ndev_new].append(row)
    for j, p in enumerate(parts):
        if len(p) > cap:
            raise CheckpointError(
                f"dyngraph reshard {ndev} -> {ndev_new}: device {j} "
                f"would hold {len(p)} rows > capacity {cap} (updates "
                "broadcast to every device); scale in less aggressively "
                "or rebuild with a larger capacity"
            )
        for i, row in enumerate(p):
            tasks_new[j, i] = row
            ready_new[j, i] = i
        counts_new[j, 0] = 0
        counts_new[j, 1] = len(p)
        counts_new[j, C_ALLOC] = len(p)
        counts_new[j, C_PENDING] = len(p)
        counts_new[j, C_VALLOC] = va
    iv_new = np.zeros((ndev_new, V), np.int64)
    for d in range(ndev):
        j = d % ndev_new
        counts_new[j, C_EXECUTED] += int(counts[d, C_EXECUTED])
        for s in (V_EDGES, V_RELAX, V_QUERIES, 6, 7):
            iv_new[j, s] += ivalues[d, s]
    iv_new[:, V_UPDATES] = len(applied_uids)
    iv_new[:, V_FREE] = int((can_bc - bcs).sum())
    iv_new[:, V_DROPPED] = 0
    can_vt = vt[0].copy()
    can_vt[:, 1] = can_bc
    can_vt[:, 2] = can_deg
    iv_new[:, VT_BASE : VT_BASE + 3 * n] = can_vt.reshape(-1)
    iv_new[:, bcs_base : bcs_base + n] = bcs
    iv_new[:, flag_base : flag_base + upd_cap] = union
    iv_new[:, st_base : st_base + n] = (
        ivalues[:, st_base : st_base + n].min(axis=0)
    )
    if V > st_base + n:
        # Query out slots: written by at most one (owner) device, zero
        # elsewhere - elementwise max is the published value, broadcast
        # so pending QUERY rows may land anywhere.
        iv_new[:, st_base + n :] = ivalues[:, st_base + n :].max(axis=0)
    scap = np.asarray(bundle.arrays["succ"]).shape[1]
    arrays: Dict[str, np.ndarray] = {
        "tasks": tasks_new,
        "succ": np.full((ndev_new, scap), NO_TASK, np.int32),
        "ready": ready_new,
        "counts": counts_new,
        "ivalues": iv_new.astype(np.int32),
        "data/indices": np.broadcast_to(
            can_ind, (ndev_new,) + can_ind.shape
        ).copy(),
    }
    if weighted:
        arrays["data/weights"] = np.broadcast_to(
            can_wgt, (ndev_new,) + can_wgt.shape
        ).copy()
    if waits is not None:
        arrays["waits"] = np.zeros(
            (ndev_new,) + np.asarray(waits).shape[1:], np.int32
        )
    if "ring_rows" in bundle.arrays:
        rr = np.asarray(bundle.arrays["ring_rows"])
        ic = np.asarray(bundle.arrays["ictl"])
        arrays["ring_rows"] = np.zeros(
            (ndev_new,) + rr.shape[1:], np.int32
        )
        ic_new = np.zeros((ndev_new, 8), np.int32)
        ic_new[:, 1] = ic[:, 1].max() if ic.size else 0  # close flag
        arrays["ictl"] = ic_new
    for k in ("tctl", "tstats", "etok", "tele", "tlat"):
        if k in bundle.arrays:
            arrays[k] = np.asarray(bundle.arrays[k]).copy()
    meta = dict(bundle.meta)
    meta["ndev"] = int(ndev_new)
    meta["resharded_from"] = int(ndev)
    meta["dyngraph_reshard"] = {
        "union_applied": len(applied_uids),
        "pending_updates": len(pend_upd),
        "edges": int(can_deg.sum()),
        "m_static": m_static,
    }
    return CheckpointBundle("resident", meta, arrays)
