"""Fully-fused Pallas UTS: the entire tree traversal in ONE resident kernel.

The XLA engine (uts_vec.py) emits the DFS step as ~1.3k separate VPU ops;
unfused intermediates round-trip HBM, putting the measured per-step wall
(~85us at 8192 lanes) ~8x above the raw op cost. Splitting only the
expansion loop into a Pallas phase kernel got ~16us/step but left ~1ms of
XLA glue per refill round (gathers + layout conversions around the custom
call) - at 60-250 rounds per run that glue dominated. This engine therefore
runs EVERYTHING on-core in one kernel launch:

- the DFS traversal (uts_vec.make_traversal - the exact driver and step
  shared with the XLA engine) with all lane state (~2 MB at (64,128)
  lanes) living in VMEM/registers;
- the shared-root-queue refill, re-expressed in Mosaic-supported primitives:
  * flat cumsum over the starved mask -> two triangular MXU matmuls (exact:
    counts <= nlanes << 2^24 in f32);
  * the root-window DMA -> a 1024-aligned dynamic row-block copy from HBM
    (roots are laid out (rows, 128) host-side; the residual offset folds
    into the gather indices);
  * the monotone claim gather -> same-shape ``take_along_axis`` passes
    (Mosaic's only gather form): claim ranks are a prefix sum, so each
    output row's indices span <= 127 and touch <= 2 window rows - select
    those two rows (clipped row-gathers), roll each by the row's start
    offset, stitch, then one in-row gather finishes the job.

The reference's work-stealing scheduler loop (src/hclib-runtime.c:705-724)
maps to the megakernel (device/megakernel.py) for task graphs; this is the
same persistent-kernel idea specialized to the data-parallel engine - the
core never returns to XLA until the tree is fully counted.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from ..models.uts import FIXED, UTSParams
from .uts_vec import (
    LANES,
    PAD_QUANTUM,
    _host_seed,
    _timed_best,
    apply_claim,
    child_thresholds,
    depth_cap,
    inrow_threshold_table,
    make_traversal,
    padded_threshold_table,
    resolve_timing_reps,
)

__all__ = ["uts_pallas"]

ALIGN = 1024  # dynamic DMA offsets must be 1024-aligned (Mosaic tiling)


def _mm_cumsum(mask, lanes):
    """Inclusive prefix sum of a 0/1 mask over flat lane order via two
    triangular MXU matmuls (exact in f32 for counts < 2^24)."""
    rows, cols = lanes
    m = mask.astype(jnp.float32)
    Uc = (
        jax.lax.broadcasted_iota(jnp.int32, (cols, cols), 0)
        <= jax.lax.broadcasted_iota(jnp.int32, (cols, cols), 1)
    ).astype(jnp.float32)
    P = jnp.dot(m, Uc, preferred_element_type=jnp.float32)
    t = P[:, cols - 1 : cols]  # (rows, 1) row totals
    Ur = (
        jax.lax.broadcasted_iota(jnp.int32, (rows, rows), 0)
        < jax.lax.broadcasted_iota(jnp.int32, (rows, rows), 1)
    ).astype(jnp.float32)
    carry = jnp.dot(t.T, Ur, preferred_element_type=jnp.float32)  # (1, rows)
    return (P + carry.T).astype(jnp.int32)


def _row_select(win2d, a, lanes, winrows):
    """A[i, :] = win2d[a[i], :] for a (rows,)-vector of window-row indices.

    Mosaic's axis-0 dynamic gather is single-vreg-only, so this is a
    one-hot MXU matmul instead: onehot(a) (rows, winrows) @ win2d
    (winrows, cols). The MXU multiplies f32 inputs at bf16 precision
    (8-bit mantissa), so full 32-bit words are split into four BYTES -
    integers <= 255 are exact in bf16, the one-hot rows are 0/1, and each
    output sums exactly one product."""
    rows, cols = lanes
    oh = (
        a[:, None]
        == jax.lax.broadcasted_iota(jnp.int32, (rows, winrows), 1)
    ).astype(jnp.float32)
    out = jnp.zeros(lanes, jnp.int32)
    for b in range(4):
        byte = ((win2d >> (8 * b)) & 0xFF).astype(jnp.float32)
        got = jnp.dot(oh, byte, preferred_element_type=jnp.float32).astype(
            jnp.int32
        )
        out = out | (got << (8 * b))
    return out


def _monotone_gather(win2d, idx, lanes, winrows):
    """out[i,j] = win2d.flat[idx[i,j]] for flat-monotone idx (a prefix-sum
    rank + offset): each output row spans <= cols indices, touching <= 2
    window rows - so two row-selects + per-row rolls + one in-row gather."""
    rows, cols = lanes
    start = idx[:, 0]  # (rows,) monotone
    a = start // cols
    c = start % cols
    A = _row_select(win2d, a, lanes, winrows)
    B = _row_select(win2d, jnp.minimum(a + 1, winrows - 1), lanes, winrows)
    j = jax.lax.broadcasted_iota(jnp.int32, lanes, 1)
    roll = (j + c[:, None]) % cols
    Ar = jnp.take_along_axis(A, roll, axis=1)
    Br = jnp.take_along_axis(B, roll, axis=1)
    W = jnp.where(c[:, None] + j < cols, Ar, Br)  # W[i,j] = flat[start_i+j]
    o = jnp.clip(idx - start[:, None], 0, cols - 1)
    return jnp.take_along_axis(W, o, axis=1)


def _dfs_kernel(
    S: int,
    lanes: tuple,
    thresholds,
    min_idle: int,
    max_steps: int,
    winrows: int,
    # refs
    roots_state_ref,  # ANY (5, Rrows, 128) i32 (u32 bits)
    roots_count_ref,  # ANY (Rrows, 128) i32
    scal_ref,  # SMEM (3,): R (real root count), d0, gen_mx
    tab_ref,  # VMEM (K, 128): in-row threshold table ((1,128) dummy when
    # the shape is depth-independent - kernels cannot capture constants)
    nodes_ref, leaves_ref, maxd_ref,  # VMEM lanes, outputs
    ctl_ref,  # SMEM (2,): steps, unfinished
    wstate, wcount, sems,  # scratch: (5, winrows, 128), (winrows, 128), DMA
) -> None:
    rows, cols = lanes
    nlanes = rows * cols
    R = scal_ref[0]
    d0 = scal_ref[1]
    gen_mx = scal_ref[2]

    def refill(sp, next_root, st0, ch0, cn0, dp0):
        starved = sp < 0
        cum = _mm_cumsum(starved, lanes)
        avail = R - next_root
        claim = starved & (cum <= avail)
        aligned = (next_root // ALIGN) * ALIGN
        rowstart = aligned // cols  # divisible by ALIGN/cols = 8
        cps = [
            pltpu.make_async_copy(
                roots_state_ref.at[i, pl.ds(rowstart, winrows)],
                wstate.at[i],
                sems.at[i],
            )
            for i in range(5)
        ]
        cpc = pltpu.make_async_copy(
            roots_count_ref.at[pl.ds(rowstart, winrows)], wcount, sems.at[5]
        )
        for cp in cps:
            cp.start()
        cpc.start()
        for cp in cps:
            cp.wait()
        cpc.wait()
        idx = jnp.clip(cum - 1, 0, nlanes - 1) + (next_root - aligned)
        rst = [
            _monotone_gather(
                wstate[i], idx, lanes, winrows
            ).astype(jnp.uint32)
            for i in range(5)
        ]
        rcn = _monotone_gather(wcount[...], idx, lanes, winrows)
        sp, st0, ch0, cn0, dp0 = apply_claim(
            claim, rst, rcn, d0, sp, st0, ch0, cn0, dp0
        )
        next_root = next_root + jnp.minimum(
            jnp.sum(starved.astype(jnp.int32)), avail
        )
        return sp, next_root, st0, ch0, cn0, dp0

    run = make_traversal(
        S, lanes, thresholds, gen_mx, min_idle, max_steps, refill, R,
        inrow_table=tab_ref[...] if thresholds is None else None,
    )
    sp, next_root, nodes, leaves, maxd, steps = run()
    nodes_ref[...] = nodes
    leaves_ref[...] = leaves
    maxd_ref[...] = maxd
    ctl_ref[0] = steps
    ctl_ref[1] = (jnp.any(sp >= 0) | (next_root < R)).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=(
        "stack_size", "thresholds", "max_steps", "lanes",
        "min_idle_div", "interpret", "vmem_limit_bytes",
    ),
)
def _uts_dfs_pallas(
    roots_state,  # (5, Rrows, 128) i32 (u32 bits), padded + aligned
    roots_count,  # (Rrows, 128) i32
    scal,  # (3,) i32 - [R (real root count), d0, gen_mx]
    tab,  # (K, 128) i32 in-row threshold table ((1, 128) dummy for FIXED)
    stack_size: int,
    thresholds,  # static ints (FIXED fast path) or None (runtime table)
    max_steps: int,
    lanes: tuple,
    min_idle_div: int = 8,
    interpret: bool = False,
    vmem_limit_bytes: int = 100 * 2**20,
):
    S = stack_size
    rows, cols = lanes
    nlanes = rows * cols
    min_idle = max(64, nlanes // min_idle_div)
    winrows = nlanes // cols + ALIGN // cols  # window covers slack + claims
    i32 = jnp.int32
    kernel = pl.pallas_call(
        functools.partial(
            _dfs_kernel, S, lanes, thresholds, min_idle,
            max_steps, winrows,
        ),
        out_shape=(
            jax.ShapeDtypeStruct(lanes, i32),  # nodes
            jax.ShapeDtypeStruct(lanes, i32),  # leaves
            jax.ShapeDtypeStruct(lanes, i32),  # maxd
            jax.ShapeDtypeStruct((2,), i32),   # steps, unfinished
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=tuple(
            [pl.BlockSpec(memory_space=pltpu.VMEM)] * 3
            + [pl.BlockSpec(memory_space=pltpu.SMEM)]
        ),
        scratch_shapes=[
            pltpu.VMEM((5, winrows, cols), i32),
            pltpu.VMEM((winrows, cols), i32),
            pltpu.SemaphoreType.DMA((6,)),
        ],
        interpret=interpret,  # bool: the fast XLA-backed interpreter
        # (InterpretParams would select the slow Mosaic one - only
        # remote-DMA/semaphore kernels need that; see megakernel.py)
        # Lane state + refill windows + a (K,128) threshold table overflow
        # the compiler's default 16 MiB scoped-vmem budget at (64,128)
        # lanes; real VMEM is 128 MiB on v5e.
        compiler_params=(
            None
            if interpret
            else pltpu.CompilerParams(vmem_limit_bytes=vmem_limit_bytes)
        ),
    )
    nodes, leaves, maxd, ctl = kernel(roots_state, roots_count, scal, tab)
    return (
        # Per-lane planes, not totals: totals are summed on the host in
        # int64 so trees beyond 2^31 total nodes (T1XXL's 4.23B) count
        # correctly while per-lane counters stay comfortably in int32.
        nodes,
        leaves,
        maxd,
        ctl[0],
        ctl[1] != 0,
    )


def uts_pallas(
    params: UTSParams,
    target_roots: int = 16 * LANES[0] * LANES[1],
    max_steps: Optional[int] = None,
    device=None,
    lanes: Tuple[int, int] = LANES,
    min_idle_div: int = 8,
    interpret: Optional[bool] = None,
    depth_bound: Optional[int] = None,
    vmem_limit_bytes: int = 100 * 2**20,
    stack_pad: Optional[int] = None,
    timing_reps: Optional[int] = None,
    table_cols: Optional[int] = None,
) -> dict:
    """uts_vec with the whole traversal fused into one Pallas kernel; same
    exact counts, same host seeding, same result dict.

    All GEO shapes run fused: FIXED on the depth-independent threshold
    fast path; LINEAR/CYCLIC (canonical T5/T2) and EXPDEC via the same
    exact per-depth threshold tables as uts_vec, realized on-core as
    same-shape ``take_along_axis`` in-row lookups (the one gather form
    Mosaic supports); the table's depth cap must fit a 128-lane row.
    EXPDEC's cap comes from ``depth_bound`` (default 8*gen_mx) and the
    run fails loudly if the tree actually reaches it. The scoped-vmem
    budget defaults to 100 MiB (sized for v5e's 128 MiB physical VMEM);
    pass a smaller ``vmem_limit_bytes`` on TPU generations with less
    (mirrors Megakernel.vmem_limit_bytes)."""
    if lanes[1] != 128:
        raise ValueError("uts_pallas lanes must be (rows, 128)")
    import time

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    t_seed = time.perf_counter()
    host_nodes, host_leaves, host_maxd, d0, roots_state, roots_count = (
        _host_seed(params, target_roots)
    )
    seed_seconds = time.perf_counter() - t_seed
    result = {
        "host_seed_nodes": host_nodes,
        "roots": 0 if roots_count is None else int(roots_count.shape[0]),
        "seed_seconds": seed_seconds,
    }
    if roots_count is None:
        result.update(
            nodes=host_nodes, leaves=host_leaves, max_depth=host_maxd, steps=0
        )
        return result
    if max_steps is None:
        max_steps = (1 << 31) - 1
    rows, cols = lanes
    nlanes = rows * cols
    R = int(roots_count.shape[0])
    # Pad so any aligned window [align_down(next_root), +nlanes+ALIGN) is in
    # bounds (next_root <= R), then lay out as (Rrows, 128) for row-block
    # DMA. PAD_QUANTUM (a multiple of ALIGN) keeps trees with different
    # root counts on one padded shape, sharing one compiled kernel (R is
    # a runtime scalar; only the padded shape is static).
    rpad = -(-(R + nlanes + ALIGN) // PAD_QUANTUM) * PAD_QUANTUM
    pstate = np.zeros((5, rpad), np.int32)
    pstate[:, :R] = roots_state.astype(np.int32)
    pcount = np.zeros(rpad, np.int32)
    pcount[:R] = roots_count
    # Shape -> (thresholds, stack height, depth cap) exactly as uts_vec.
    derived = depth_cap(params)
    if derived is None:  # EXPDEC: caller-chosen bound, validated below
        cap = depth_bound if depth_bound is not None else 8 * params.gen_mx
        bounded = True
    elif depth_bound is not None and depth_bound < derived:
        cap = depth_bound
        bounded = True
    else:
        cap = derived
        bounded = False
    if params.shape == FIXED and not bounded:
        thr = tuple(int(t) for t in child_thresholds(params.b0))
        stack_size = max(1, params.gen_mx - d0)
        tabnp = np.zeros((1, cols), np.int32)  # unused dummy input
    else:
        # Runtime-table path: the padded in-row table is a kernel INPUT,
        # so all depth-varying trees with one padded shape + stack height
        # share a single compiled kernel (see padded_threshold_table).
        thr = None
        stack_size = max(1, (cap - d0) if bounded else (cap - 1 - d0))
        # max_rows = cols - 1: the in-row gather clips depth to column
        # cols - 1 and needs that column to stay -1 padding, so the row
        # quantization must not round past it (restores depth caps up to
        # cols - 2 = 126 that the plain 16-row round-up would reject).
        # table_cols (like stack_pad) opts into a shared width class so
        # different trees reuse one compiled engine.
        tabnp = inrow_threshold_table(
            padded_threshold_table(
                params, cap, max_rows=cols - 1, min_cols=table_cols
            ),
            cols,
        )
    if stack_pad is not None:
        # Opt-in compile sharing across tree shapes (taller stacks cost
        # select/store work per step; the perf path keeps tight heights).
        stack_size = max(stack_size, int(stack_pad))
    args = (
        jnp.asarray(pstate.reshape(5, rpad // cols, cols)),
        jnp.asarray(pcount.reshape(rpad // cols, cols)),
        jnp.asarray(np.array([R, d0, params.gen_mx], np.int32)),
        jnp.asarray(tabnp),
    )
    kw = dict(
        stack_size=stack_size,
        thresholds=thr,
        max_steps=max_steps,
        lanes=tuple(lanes),
        min_idle_div=min_idle_div,
        interpret=interpret,  # bool: the fast XLA-backed interpreter
        # (InterpretParams would select the slow Mosaic one - only
        # remote-DMA/semaphore kernels need that; see megakernel.py)
        vmem_limit_bytes=vmem_limit_bytes,
    )
    if device is not None:
        args = tuple(jax.device_put(a, device) for a in args)
    # Rate of record = best of a few executions of the SAME compiled
    # kernel on the SAME staged args (uts_vec._timed_best; a single timed
    # execution right after staging measured 4-6x slow on the
    # tunnel-attached chip, which historically read as phantom
    # "throttled windows").
    (nodes, leaves, maxd, steps, unfinished), dev_nodes, dt = _timed_best(
        lambda: _uts_dfs_pallas(*args, **kw),
        resolve_timing_reps(timing_reps, not interpret),
    )
    if bool(unfinished):
        raise RuntimeError(f"uts_pallas ran out of steps ({max_steps})")
    if bounded and int(np.asarray(maxd).max()) >= cap:
        raise RuntimeError(
            f"tree reached the depth bound ({cap}): counts beyond it are "
            "truncated - rerun with a larger depth_bound"
        )
    result.update(
        nodes=host_nodes + dev_nodes,
        leaves=host_leaves + int(np.asarray(leaves).sum(dtype=np.int64)),
        max_depth=max(host_maxd, int(np.asarray(maxd).max())),
        steps=int(steps),
        device_nodes=dev_nodes,
        device_seconds=dt,
        nodes_per_sec=dev_nodes / dt if dt > 0 else float("inf"),
        lane_efficiency=dev_nodes / (int(steps) * nlanes) if steps else 0.0,
    )
    return result


if __name__ == "__main__":  # pragma: no cover
    import sys

    from ..models.uts import T1, T1L, T3

    name = sys.argv[1] if len(sys.argv) > 1 else "T3"
    params = {"T1": T1, "T1L": T1L, "T3": T3}[name]
    print(uts_pallas(params))
