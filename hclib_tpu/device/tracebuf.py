"""Device-side flight recorder: a fixed-width trace ring in device memory.

The persistent megakernel is the north-star component of this repo, and
until now it was a black box: when a round stalls, a lane starves, or a
perf number collapses (the r05 1.2-vs-64 GCUPS gap), the only evidence
was end-of-run aggregate counters (``info['tiers']``, ``fault_stats``).
This module gives every round loop an **opt-in trace ring**: an SMEM
int32 output row the kernel appends fixed-width records to from *inside*
its scheduling rounds - round entry/exit, dispatch-tier fires (with lane
occupancy), prefetch issue/drain, steal-credit traffic, abort/fault
observation.

Design rules (the ``DeviceFaultPlan`` pattern):

- **Compiled in only when enabled.** A ``None`` ring emits nothing: the
  ``NullTracer``'s methods are no-ops, so call sites stay unconditional
  and a disabled build is bit-identical to one that predates tracing
  (asserted in tests/test_tracebuf.py). There is no "check a flag at
  runtime" cost - the flag is resolved at trace time.
- **Overflow counted, not crashed.** The write cursor is monotonic and
  records land at ``cursor % capacity``: a full ring keeps the *last*
  ``capacity`` records (the rounds before a stall are what debugging
  wants) and the decoder reports ``dropped = max(0, written - capacity)``.
- **No device clock.** TPU scalar cores expose no useful wall clock to
  kernels; records carry the ROUND index as their timebase. The host
  brackets the kernel launch with ``time.monotonic_ns()`` (the same
  clock ``runtime/instrument.py`` stamps host events with - the
  clockprobe bracketing trick) and tools/timeline.py interpolates round
  -> wall time inside that epoch, which is what lets device rounds and
  host spans land on ONE Perfetto timeline.

Record layout: 4 int32 words ``[tag, t, a, b]`` where ``t`` is the round
index and ``a``/``b`` are per-tag payloads (see the TR_* table). The ring
row is ``HDR`` header words followed by ``capacity * TR_WORDS`` record
words; header word 0 is the monotonic write cursor and word 1 a
scheduler-entry-relative round cursor (the single-core megakernel has no
exchange round of its own, so its tracer mints one per scheduling
iteration).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Union

import numpy as np

__all__ = [
    "TraceRing",
    "Tracer",
    "NullTracer",
    "decode_ring",
    "lane_partial_age",
    "trace_info",
    "trace_to_jsonable",
    "records_of",
    "TR_WORDS",
    "HDR",
    "TR_ROUND_BEGIN",
    "TR_ROUND_END",
    "TR_FIRE_SCALAR",
    "TR_FIRE_BATCH",
    "TR_PREFETCH_ISSUE",
    "TR_PREFETCH_DRAIN",
    "TR_SPILL",
    "TR_CREDIT",
    "TR_XFER",
    "TR_ABORT",
    "TR_FAULT",
    "TR_INJECT",
    "TR_QUIESCE",
    "TR_CKPT",
    "TR_SCALE",
    "TR_TENANT",
    "TR_FIRE_AGE",
    "TR_FIRE_BUCKET",
    "TR_EGRESS",
    "TR_LATENCY",
    "TR_SPLICE",
    "bucket_occupancy",
    "SC_HOLD",
    "SC_OUT",
    "SC_IN",
    "SC_EVACUATE",
    "SC_CHECKPOINT",
    "SC_FINISH",
    "SC_DEADLINE_OUT",
    "SC_STRAND_HOLD",
    "SC_SLO_OUT",
    "SC_NAMES",
    "CK_SAVE",
    "CK_LOAD",
    "CK_FALLBACK",
    "CK_QUARANTINE",
    "CK_POISON",
    "CK_NAMES",
    "host_trace_info",
    "TAG_NAMES",
]

# Header words (HDR total; the rest reserved/zero).
TH_COUNT = 0  # records ever written (monotonic; slot = count % capacity)
TH_ROUND = 1  # entry-relative round cursor (single-core megakernel only)
HDR = 8

TR_WORDS = 4  # [tag, t, a, b]

# Record tags. Payload conventions (a, b):
TR_ROUND_BEGIN = 1     # a = ready backlog, b = pending
TR_ROUND_END = 2       # a = executed since entry, b = pending
TR_FIRE_SCALAR = 3     # a = kernel-table F_FN, b = descriptor row
TR_FIRE_BATCH = 4      # a = (lane_fn << 16) | take, b = prefetched count
TR_PREFETCH_ISSUE = 5  # a = lane F_FN, b = descriptors announced
TR_PREFETCH_DRAIN = 6  # a = lane F_FN, b = in-flight descriptors retired
TR_SPILL = 7           # a = lane F_FN, b = entries spilled to the ring
TR_CREDIT = 8          # a = (hop << 8) | peer, b = delta code (CR_*)
TR_XFER = 9            # a = partner/hop, b = rows sent
TR_ABORT = 10          # a = round the folded abort word was observed
TR_FAULT = 11          # a = fault code (FLT_*), b = detail (peer/mask)
TR_INJECT = 12         # a = rows installed from the injection ring
TR_QUIESCE = 13        # a = executed-since-entry (or round) at observation
TR_CKPT = 14           # a = pending rows exported, b = ready backlog
TR_SCALE = 15          # a = (from_ndev << 8) | to_ndev, b = SC_* kind
                       # (host-emitted by runtime/autoscaler.py; rides
                       # the same record ABI so timeline.py renders
                       # scale events beside device rounds)
TR_TENANT = 16         # a = (tenant_lane << 16) | rows installed this
                       # poll, b = rows dropped expired (the counted
                       # TenantExpired records) - emitted by the WRR
                       # tenant inject poll, device/inject.py
TR_FIRE_AGE = 17       # a = (lane_fn << 16) | take, b = starved age at
                       # fire - the FIRE REASON record: this batch round
                       # jumped the ring-drain-first policy because the
                       # lane's starved-round age reached lane_max_age
                       # (megakernel.py firing site). Every TR_FIRE_AGE
                       # is paired with the TR_FIRE_BATCH of the same
                       # round; a ring-drained fire emits only the
                       # latter, so the reason split is exact.
TR_FIRE_BUCKET = 18    # a = (bucket << 16) | take, b = lane F_FN - the
                       # priority-bucket tier's fire record (ISSUE 15,
                       # priority_buckets builds only): which bucket
                       # ring this round's batch retired, at what
                       # occupancy. Paired with the round's
                       # TR_FIRE_BATCH (same take); bucket_occupancy()
                       # folds these into the per-bucket gauge.
TR_EGRESS = 19         # a = submit token of the retired row, b = park
                       # depth after the event - the completion-mailbox
                       # BACKPRESSURE record (ISSUE 16, egress builds
                       # only): emitted when retirement finds the
                       # mailbox full and PARKS the row instead of
                       # publishing (counted in ectl[EC_PARKED], never
                       # dropped, never an OVF abort). A publish emits
                       # nothing: the write-cursor echo already counts
                       # it, and the hot path stays record-free.
TR_LATENCY = 20        # a = (tenant << 16) | latency bucket, b = raw
                       # (retire - admit) delta in scheduler rounds -
                       # the per-retirement LATENCY record (telemetry
                       # builds only, device/telemetry.py): emitted at
                       # the egress fold that also bumps the on-device
                       # histogram, so the Perfetto track and the
                       # scraped histogram are two views of one event.
TR_SPLICE = 21         # a = (applied << 16) | dropped delta observed
                       # this pump visit, b = spare blocks in use
                       # (V_FREE) after it - the dynamic-graph SPLICE
                       # progress record (ISSUE 20, device/dyngraph.py
                       # serving pump; host-emitted off the device
                       # counters, the TR_SCALE ring discipline, so
                       # update-storm progress renders beside the
                       # rounds that absorbed it).

# TR_SCALE kind codes (b word) - mirror autoscaler.ScaleEvent.kind.
SC_HOLD = 0
SC_OUT = 1
SC_IN = 2
SC_EVACUATE = 3
SC_CHECKPOINT = 4
SC_FINISH = 5
SC_DEADLINE_OUT = 6   # tenant deadline-pressure scale-out (no gates:
                      # it must beat the watchdog's strike ladder)
SC_STRAND_HOLD = 7    # scale-in refused: it would strand a tenant's
                      # in-flight quota / ring residue
SC_SLO_OUT = 8        # SLO burn-rate scale-out (runtime/slo.py): the
                      # latency histogram's multi-window burn rate
                      # crossed HCLIB_TPU_SLO_BURN. Like deadline_out
                      # it bypasses hysteresis AND cooldown - an SLO
                      # on fire must not wait out a cooldown window.

# TR_CKPT store subcodes (the durable BundleStore, runtime/checkpoint
# .py): host-emitted records ride the TR_CKPT tag with a NEGATIVE a
# word - ``a = -(1 + CK_code)`` - so they can never collide with the
# device export records, whose a word is a pending-row count (>= 0);
# the b word is the store generation the event acted on.
CK_SAVE = 0        # a generation published (staged, fsync'd, renamed)
CK_LOAD = 1        # a generation validated and loaded
CK_FALLBACK = 2    # load_latest fell back past >= 1 bad generation
CK_QUARANTINE = 3  # a torn/corrupt/mismatched generation set aside
CK_POISON = 4      # no generation validates: the store is unrecoverable

# The ONE name table for SC_* codes: runtime/autoscaler.py derives its
# kind->code map from it and tools/timeline.py labels TR_SCALE spans
# with it, so a new kind is one edit here, not three drifting copies.
SC_NAMES: Dict[int, str] = {
    SC_HOLD: "hold",
    SC_OUT: "scale out",
    SC_IN: "scale in",
    SC_EVACUATE: "evacuate",
    SC_CHECKPOINT: "checkpoint",
    SC_FINISH: "finish",
    SC_DEADLINE_OUT: "deadline out",
    SC_STRAND_HOLD: "strand hold",
    SC_SLO_OUT: "slo out",
}

# The ONE name table for CK_* codes - runtime/checkpoint.py's
# BundleStore emits them and tools/timeline.py labels the store spans
# from this table, the SC_NAMES discipline exactly.
CK_NAMES: Dict[int, str] = {
    CK_SAVE: "store save",
    CK_LOAD: "store load",
    CK_FALLBACK: "store fallback",
    CK_QUARANTINE: "store quarantine",
    CK_POISON: "store poison",
}

TAG_NAMES: Dict[int, str] = {
    TR_ROUND_BEGIN: "round_begin",
    TR_ROUND_END: "round_end",
    TR_FIRE_SCALAR: "fire_scalar",
    TR_FIRE_BATCH: "fire_batch",
    TR_PREFETCH_ISSUE: "prefetch_issue",
    TR_PREFETCH_DRAIN: "prefetch_drain",
    TR_SPILL: "spill",
    TR_CREDIT: "credit",
    TR_XFER: "xfer",
    TR_ABORT: "abort",
    TR_FAULT: "fault",
    TR_INJECT: "inject",
    TR_QUIESCE: "quiesce",
    TR_CKPT: "ckpt_export",
    TR_SCALE: "scale",
    TR_TENANT: "tenant",
    TR_FIRE_AGE: "fire_age",
    TR_FIRE_BUCKET: "fire_bucket",
    TR_EGRESS: "egress_park",
    TR_LATENCY: "latency",
    TR_SPLICE: "splice",
}

# TR_CREDIT delta codes (b word).
CR_DROPPED = 1      # granter dropped the credit it owed
CR_DUPED = 2        # granter signalled twice
CR_REGENERATED = 3  # starved waiter skipped an owed wait (regeneration)

# TR_FAULT codes (a word).
FLT_DEAD_QUARANTINE = 1  # b = peer quarantined by heartbeat timeout
FLT_WEDGE = 2            # b = starved-channel encoding ((hop<<8)|granter)+1
FLT_DELAY = 3            # b = hop whose export quota was zeroed

# Name tables for the payload codes above - the SAME one-table-edit
# discipline as SC_NAMES: tools/timeline.py labels TR_CREDIT/TR_FAULT
# payloads from these, so a new code is one edit here.
CR_NAMES: Dict[int, str] = {
    CR_DROPPED: "dropped",
    CR_DUPED: "duplicated",
    CR_REGENERATED: "regenerated",
}
FLT_NAMES: Dict[int, str] = {
    FLT_DEAD_QUARANTINE: "dead-chip quarantine",
    FLT_WEDGE: "wedge",
    FLT_DELAY: "delay",
}


class TraceRing:
    """Host-side spec of a device trace ring (capacity in RECORDS).

    Capacity budgets SMEM: the ring is an SMEM output of ``HDR +
    capacity * TR_WORDS`` int32 words, and SMEM windows pad scalars
    ~32 B/word (the same accounting that caps task tables near ~800
    rows, device/workloads.py) - the 2048-record default costs about as
    much as a 512-row task table, so size DOWN next to SMEM-heavy
    kernels."""

    def __init__(self, capacity: int = 2048) -> None:
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError(f"trace capacity must be >= 1, got {capacity}")
        self.capacity = capacity

    @property
    def words(self) -> int:
        return HDR + self.capacity * TR_WORDS

    def out_shape(self):
        import jax
        import jax.numpy as jnp

        return jax.ShapeDtypeStruct((self.words,), jnp.int32)

    @staticmethod
    def of(trace: Union[None, int, "TraceRing"]) -> Optional["TraceRing"]:
        """Normalize a ``trace=`` argument (None / record count / ring)."""
        if trace is None:
            return None
        if isinstance(trace, TraceRing):
            return trace
        if isinstance(trace, bool):
            return TraceRing() if trace else None
        return TraceRing(int(trace))


def _i32(x):
    import jax.numpy as jnp

    return jnp.int32(x) if isinstance(x, (int, np.integer)) else x


class Tracer:
    """Device-side writer over one ring ref (an SMEM int32 output row).

    Every method is a handful of scalar SMEM ops; none branch. Emission
    under a fault/abort condition belongs inside the caller's ``pl.when``
    like any other conditional SMEM write.
    """

    enabled = True

    def __init__(self, ref, capacity: int) -> None:
        self._ref = ref
        self._cap = int(capacity)

    def reset(self) -> None:
        """Zero the header (per kernel entry / rep, from stage())."""
        for w in range(HDR):
            self._ref[w] = 0

    def emit(self, tag: int, t, a=0, b=0) -> None:
        n = self._ref[TH_COUNT]
        base = HDR + (n % self._cap) * TR_WORDS
        import jax.numpy as jnp

        self._ref[base + 0] = jnp.int32(tag)
        self._ref[base + 1] = _i32(t)
        self._ref[base + 2] = _i32(a)
        self._ref[base + 3] = _i32(b)
        self._ref[TH_COUNT] = n + 1

    def tick(self):
        """Mint the next entry-relative round index (single-core sched)."""
        r = self._ref[TH_ROUND]
        self._ref[TH_ROUND] = r + 1
        return r

    def now(self):
        """The current round cursor, without advancing it."""
        return self._ref[TH_ROUND]


class NullTracer:
    """The disabled recorder: no refs, no writes, no compiled code."""

    enabled = False

    def reset(self) -> None:
        return None

    def emit(self, tag: int, t, a=0, b=0) -> None:
        return None

    def tick(self):
        return 0

    def now(self):
        return 0


# ------------------------------------------------------------------ decode

def decode_ring(row, capacity: Optional[int] = None) -> Dict[str, Any]:
    """Decode one ring row into ``{written, dropped, records}``.

    ``records`` is an (n, 4) int64 array of [tag, t, a, b] in emission
    order; when the ring wrapped it holds the LAST ``capacity`` records
    and ``dropped`` counts the overwritten prefix."""
    row = np.asarray(row).astype(np.int64).ravel()
    if capacity is None:
        capacity = (len(row) - HDR) // TR_WORDS
    written = int(row[TH_COUNT])
    body = row[HDR : HDR + capacity * TR_WORDS].reshape(capacity, TR_WORDS)
    if written <= capacity:
        records = body[:written].copy()
    else:
        start = written % capacity
        records = np.roll(body, -start, axis=0).copy()
    return {
        "written": written,
        "dropped": max(0, written - capacity),
        "capacity": int(capacity),
        "records": records,
    }


def trace_info(
    rows: Sequence, t0_ns: int, t1_ns: int,
    capacity: Optional[int] = None,
) -> Dict[str, Any]:
    """The uniform ``info['trace']`` shape every traced runner returns:
    one decoded ring per device plus the host-wall-clock epoch that
    bracketed the kernel launch (``time.monotonic_ns()``, the clock host
    EventLog records share - what lets tools/timeline.py place device
    rounds on the host timeline)."""
    return {
        "epoch": {"t0_ns": int(t0_ns), "t1_ns": int(t1_ns)},
        "rings": [decode_ring(r, capacity) for r in rows],
    }


def host_trace_info(
    records: Sequence[Sequence[int]], t0_ns: int, t1_ns: int,
) -> Dict[str, Any]:
    """A trace_info-shaped dict built from HOST-emitted records (rows of
    [tag, t, a, b] - e.g. the autoscaler's TR_SCALE events, with ``t``
    the control-slice index). It rides the same epoch-bracket contract
    as a device ring, so ``tools/timeline.py --perfetto`` merges host
    control-loop events onto the same timeline as device rounds."""
    arr = np.asarray(list(records), dtype=np.int64).reshape(-1, TR_WORDS)
    return {
        "epoch": {"t0_ns": int(t0_ns), "t1_ns": int(t1_ns)},
        "rings": [{
            "written": int(arr.shape[0]),
            "dropped": 0,
            "capacity": max(1, int(arr.shape[0])),
            "records": arr,
        }],
    }


def records_of(trace: Dict[str, Any], tag: int, ring: int = 0) -> np.ndarray:
    """Records of one tag from ``info['trace']`` (rows: [tag, t, a, b])."""
    recs = np.asarray(trace["rings"][ring]["records"])
    if recs.size == 0:
        return recs.reshape(0, TR_WORDS)
    return recs[recs[:, 0] == tag]


def trace_to_jsonable(trace: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-safe copy of a trace_info dict (record arrays -> lists), so
    run infos can be saved next to perf logs and fed back to
    ``tools/timeline.py --trace``."""
    return {
        "epoch": dict(trace["epoch"]),
        "rings": [
            {
                "written": r["written"],
                "dropped": r["dropped"],
                "capacity": r["capacity"],
                "records": np.asarray(r["records"]).tolist(),
            }
            for r in trace["rings"]
        ],
    }


def trace_from_jsonable(obj: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of trace_to_jsonable (tools/timeline.py --trace loader)."""
    return {
        "epoch": dict(obj["epoch"]),
        "rings": [
            {
                "written": int(r["written"]),
                "dropped": int(r["dropped"]),
                "capacity": int(r["capacity"]),
                "records": np.asarray(
                    r["records"], dtype=np.int64
                ).reshape(-1, TR_WORDS),
            }
            for r in obj["rings"]
        ],
    }


def lane_partial_age(
    trace: Dict[str, Any], widths: Dict[int, int], ring: int = 0,
    max_gap: int = 8,
) -> Dict[int, int]:
    """Partial-batch starvation detector (the ROADMAP lane-firing-policy
    watch item), computed off TR_FIRE_BATCH occupancy records: for each
    batch lane, the longest streak of CONSECUTIVE partial fires
    (``take < width``), measured in rounds spanned (last.t - first.t + 1
    of the streak). A healthy static tile set fires full batches with at
    most one partial tail (age <= 1); a dynamic spawner that keeps the
    ready ring hot under the ring-drain-first policy starves the lanes
    into long runs of width-1 fires - exactly what this gauge surfaces
    (exported as ``lane_partial_age`` by ``MetricsRegistry.add_run_info``
    via ``info['tiers']``). ``widths`` maps lane F_FN -> batch width
    (``Megakernel`` passes its routed specs').

    ``max_gap`` bounds what "consecutive" means in rounds: a starved
    lane still fires every few rounds (each momentary ring drain fires
    it), so a silence longer than ``max_gap`` rounds means the lane was
    EMPTY - no entry was waiting - and two partial tails separated by a
    long idle stretch must read as two short streaks, not one huge
    starvation age."""
    recs = records_of(trace, TR_FIRE_BATCH, ring)
    out: Dict[int, int] = {int(f): 0 for f in widths}
    streak_start: Dict[int, Optional[int]] = {int(f): None for f in widths}
    last_t: Dict[int, int] = {}
    for tag, t, a, _b in recs:
        fid = int(a) >> 16
        take = int(a) & 0xFFFF
        if fid not in out:
            continue
        if take < widths[fid]:
            if (
                streak_start[fid] is None
                or int(t) - last_t[fid] > max_gap
            ):
                streak_start[fid] = int(t)
            last_t[fid] = int(t)
            out[fid] = max(out[fid], last_t[fid] - streak_start[fid] + 1)
        else:
            streak_start[fid] = None
    return out


def bucket_occupancy(
    trace: Dict[str, Any], widths: Dict[int, int], buckets: int,
    ring: int = 0,
) -> Dict[int, float]:
    """Per-bucket occupancy off the TR_FIRE_BUCKET records (the priority
    tier's structural gauge, ISSUE 15): for each bucket id, retired
    descriptors over the slots its fired rounds offered - the same
    tasks/offered ratio ``batch_occupancy`` reports per kind, split by
    bucket ring. A healthy ordered workload shows the low buckets firing
    near-full (the frontier lives there) and the high buckets sparse;
    a flat profile means the priority function isn't separating the
    work. ``widths`` maps lane F_FN -> batch width (the b word names the
    firing lane); buckets without a single fire report 0.0."""
    recs = records_of(trace, TR_FIRE_BUCKET, ring)
    takes = {b: 0 for b in range(int(buckets))}
    offered = {b: 0 for b in range(int(buckets))}
    for _tag, _t, a, fid in recs:
        b = int(a) >> 16
        if b not in takes:
            continue
        takes[b] += int(a) & 0xFFFF
        offered[b] += int(widths.get(int(fid), 0))
    return {
        b: (takes[b] / offered[b] if offered[b] else 0.0)
        for b in takes
    }


def summarize(trace: Dict[str, Any]) -> Dict[str, Any]:
    """Flat numeric summary of a trace (MetricsRegistry food): per-tag
    record counts plus written/dropped totals across rings."""
    out: Dict[str, Any] = {
        "rings": len(trace["rings"]),
        "written": sum(r["written"] for r in trace["rings"]),
        "dropped": sum(r["dropped"] for r in trace["rings"]),
    }
    for tag, name in TAG_NAMES.items():
        n = 0
        for r in trace["rings"]:
            recs = np.asarray(r["records"])
            if recs.size:
                n += int((recs[:, 0] == tag).sum())
        out[name] = n
    return out
