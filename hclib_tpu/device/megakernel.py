"""The persistent Pallas megakernel: a resident scheduler loop on a TPU core.

This is the TPU-first re-design of the reference's worker loop
(core_work_loop/find_and_run_task, src/hclib-runtime.c:646-724):

- worker pthread        -> one long-running ``pallas_call`` on the core
- Chase-Lev deque       -> SMEM ready ring (head/tail counters in SMEM)
- function-pointer call -> ``lax.switch`` over a static kernel table
  (TPU has no function pointers; tasks name kernels by table index)
- promise waiter walk   -> successor dep-counter decrement + ready push
- fiber swap            -> none: tasks are descriptors, not stacks; blocking
  is expressed as dependency edges, so "waiting" tasks simply aren't ready
- pthread join/done flag-> loop exits when the pending counter reaches zero

Control state (task table, ready ring, counters, scalar values) lives in
SMEM, where the scalar unit can do random access; bulk tensor data stays in
HBM/VMEM and is touched by tile kernels via DMA + MXU/VPU ops. Kernels may
spawn new tasks dynamically (fib/UTS-style recursion) through
``KernelContext.spawn``.
"""

from __future__ import annotations

import functools
import types
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..runtime.env import env_bool, env_int, env_raw
from .descriptor import (
    DESC_WORDS,
    F_A0,
    F_CSR_N,
    F_CSR_OFF,
    F_DEP,
    F_FN,
    F_HOME,
    F_HROW,
    F_OUT,
    F_SUCC0,
    F_SUCC1,
    NO_TASK,
    TaskGraphBuilder,
)
from .tracebuf import (
    NullTracer,
    TR_CKPT,
    TR_FIRE_AGE,
    TR_FIRE_BATCH,
    TR_FIRE_BUCKET,
    TR_FIRE_SCALAR,
    TR_PREFETCH_DRAIN,
    TR_PREFETCH_ISSUE,
    TR_QUIESCE,
    TR_ROUND_BEGIN,
    TR_ROUND_END,
    TR_SPILL,
    TraceRing,
    Tracer,
    trace_info,
)

__all__ = [
    "KernelContext", "BatchContext", "BatchSpec", "Megakernel", "VBLOCK",
    "decode_overflow", "interpret_mode", "fault_mix",
]


def fault_mix(seed: int, site: int, r, k: int, g):
    """Deterministic per-mille hash of (seed, site, round, hop, device) for
    in-kernel fault predicates (the scalar-core analogue of the host
    FaultPlan's blake2b decision table). ``r`` and ``g`` may be traced
    int32; ``seed``/``site``/``k`` are static. Every device of a lockstep
    mesh evaluates the identical value, so seeded injection, its detection,
    and its recovery all agree on the schedule - the property that makes a
    chaos run reproducible byte-for-byte from the seed."""
    x = (
        r * jnp.int32(-1640531527)          # 0x9E3779B9: round stride
        + g * jnp.int32(69069)
        + jnp.int32((k * 40503 + site * 2654435761 + seed * 2246822519)
                    & 0x7FFFFFFF)
    )
    x = x ^ (x >> 13)
    x = x * jnp.int32(1274126177)
    x = x ^ (x >> 16)
    return (x & jnp.int32(0x7FFFFFFF)) % 1000


def interpret_mode():
    """InterpretParams for interpret-mode kernel builds - the single
    construction point for every pallas_call in the package.

    Always the strict defaults. The fast variants were tried and are a
    trap on this jax build: ``out_of_bounds_reads="uninitialized"``
    measured ~20% faster on multi-device kernels but sporadically
    deadlocks the interpreter's io_callback buffer machinery on 1-vCPU
    hosts (device threads park in device_put - reproduced in three
    different tests), and ``dma_execution_mode="eager"`` does the same
    under shard_map. Keep the defaults until the interpreter's threading
    is fixed upstream; the race-detector tests construct their own
    params (detect_races=True) on top of the same defaults."""
    return pltpu.InterpretParams()


def decode_overflow(mask: int) -> str:
    """Human-readable exhaustion sources from a C_OVERFLOW bitmask."""
    names = [
        (OVF_ROWS, "task-table rows"),
        (OVF_VALUES, "value slots"),
        (OVF_ENGINE, "vector-tier lane stacks/step budget"),
        (OVF_OUTBOX, "AM outbox"),
        (OVF_WAITS, "wait table"),
        (OVF_LOCKQ, "lock FIFO"),
        (OVF_PROMISE, "promise-wait spin budget"),
    ]
    hit = [n for bit, n in names if mask & bit]
    return " + ".join(hit) if hit else f"unknown (mask {mask})"

# Value slots are allocated in fixed blocks of this many words so freed
# blocks are interchangeable (alloc_values' k is static per call site, so a
# shared free stack must hand out uniform sizes). Allocations larger than
# VBLOCK fall back to exact-size bump allocation without recycling.
VBLOCK = 4

# C_OVERFLOW is a BITMASK of exhaustion sources so a failed run names
# what ran out instead of guessing (OVF_* below; legacy paths that write
# a plain 1 read as OVF_ROWS).
OVF_ROWS = 1     # task-table rows (spawn/install)
OVF_VALUES = 2   # value slots (alloc_values/free_values)
OVF_ENGINE = 4   # vector-tier per-lane stacks / step budget
OVF_OUTBOX = 8   # resident AM outbox
OVF_WAITS = 16   # resident wait table
OVF_LOCKQ = 32   # resident lock FIFO
OVF_PROMISE = 64  # on-device promise wait spun out its bounded budget

# Batched-dispatch tier statistics (the 8-word tstats output a batch-routed
# megakernel appends after its data outputs; surfaced as info['tiers'] /
# Megakernel.stats_dict()). All counters reset at every kernel entry, so with
# reps > 1 they describe the LAST rep - per-graph numbers, which is what
# occupancy tracking wants.
TS_BATCH_ROUNDS = 0   # batch rounds fired
TS_BATCH_TASKS = 1    # descriptors dispatched through batch bodies
TS_SCALAR_ROUNDS = 2  # descriptors dispatched through lax.switch
TS_ROUTED = 3         # ring pops diverted into a per-kind lane
TS_PREFETCH = 4       # descriptors whose operands came from a prefetch
TS_FULL_ROUNDS = 5    # batch rounds at full width
TS_SPILLED = 6        # lane entries spilled back to the ring at sched exit
TS_OFFERED = 7        # batch slots offered (sum of widths over fired rounds)
TS_AGE_FIRES = 8      # batch rounds fired by the age trigger (jumped the
                      # ring-drain-first policy; zero when lane_max_age off)
TS_MAX_AGE = 9        # max starved-round age any lane reached (rounds a
                      # lane held entries without firing; written only
                      # when lane_max_age is on - the device-side gauge
                      # the age-trigger acceptance bounds)
TS_BUCKET_FIRES = 10  # batch rounds fired from a NONZERO priority bucket
                      # (priority_buckets builds only; zero otherwise) -
                      # how much of the dispatch actually used the
                      # ordered-retirement structure
TS_INVERSIONS = 11    # bucket-order inversions: age-guard fires that
                      # jumped a LOWER non-empty bucket (the only legal
                      # way a higher bucket fires first; bounded noise
                      # is healthy, a large count means the age knob is
                      # fighting the priority order)
TS_WORDS = 12

# Priority-bucket dispatch tier (ISSUE 15): ``priority_buckets=B`` layers
# B bucket rings over every per-kind batch lane - pop lowest-nonempty-
# bucket-first at ring-drain time. The bucket id is a pure function of
# the descriptor's OWN arg words (BatchSpec.priority reads them at
# routing time), so a bucket id always rides the descriptor: residue
# spilled to the ready ring, stolen rows, and checkpoint/reshard exports
# re-bucket on the next routing pop by construction - no extra transport
# word, no re-bucketing pass. BK_MAX bounds the static set (SMEM lane
# scratch scales linearly with B).
BK_MAX = 8

# Per-lane scheduler state words (SMEM (nbatch, LS_WORDS) scratch): the
# lane's FIFO cursors plus the cross-round prefetch handshake.
LS_HEAD = 0     # pop cursor (monotonic; ring-indexed mod capacity)
LS_TAIL = 1     # push cursor
LS_PF_BASE = 2  # head-at-issue + 1 of the outstanding prefetch (0 = none)
LS_PF_N = 3     # descriptors the outstanding prefetch covers
LS_PF_BUF = 4   # operand-buffer half the prefetch was written into
LS_AGE = 5      # consecutive rounds the lane held entries without firing
                # (the age-trigger clock; written only when lane_max_age
                # is on - see the firing-policy site in sched())
LS_WORDS = 8

# Quiesce control words (the checkpoint/restore subsystem,
# runtime/checkpoint.py). ``qctl`` is an 8-word int32 row in HBM that the
# scheduler RE-READS by DMA inside its round loop when the megakernel was
# built with ``checkpoint=True`` - the checkpoint twin of the abort word
# (device/inject.py ctl[3], device/resident.py's abort input): a host with
# in-place device-buffer write access stops a resident kernel mid-run by
# writing the word; through this driver the word is uploaded at entry.
# On observing (flag set AND at least ``after`` tasks executed since
# entry), workers stop popping at the next round boundary, per-kind lanes
# spill back to the ready ring (the fuel-exit path), and the kernel
# returns with its live scheduler state in the aliased outputs instead of
# discarding it.
QC_FLAG = 0    # nonzero = quiesce requested
QC_AFTER = 1   # honor the flag only once this many tasks ran this entry
# ``qstat`` (8-word SMEM output, appended; present only when
# checkpoint=True) reports the observation back to the host:
QS_QUIESCED = 0  # 1 = the round loop observed the quiesce word
QS_AT = 1        # tasks executed since entry at observation
QS_POLLS = 2     # scheduling rounds ticked (the quiesce_stride counter)

# counts[] slots
C_HEAD = 0
C_TAIL = 1
C_ALLOC = 2
C_PENDING = 3
C_VALLOC = 4
C_EXECUTED = 5
C_OVERFLOW = 6
# Slot 7 is time-shared: during a kernel entry it is C_VBASE (first value
# slot above the host-preset range, set by stage()); AFTER a multi-device
# steal loop finishes, the runners (device/sharded.py, device/ici_steal.py)
# overwrite it with their round count for the host to read.
C_ROUNDS = 7
C_VBASE = 7


class KernelContext:
    """Facilities exposed to device task kernels (the device analogue of the
    worker-state + spawn API the reference hands to tasks)."""

    def __init__(self, idx, tasks, succ, ready, counts, ivalues, data,
                 scratch, capacity, free, num_values, vfree,
                 uses_row_values=False, tracks_home=False):
        self.idx = idx  # this task's descriptor index
        self._tasks = tasks
        self._succ = succ
        self._ready = ready
        self._counts = counts
        self.ivalues = ivalues
        self.data = data  # name -> ref (HBM/VMEM tensor buffers)
        self.scratch = scratch  # name -> scratch ref (VMEM buffers, DMA sems)
        self._capacity = capacity
        self._num_values = num_values
        # Free-stack of recycled descriptor rows: free[0] is the count,
        # free[1..] the stack (completed rows are reclaimed, so a bounded
        # table runs unbounded dynamic graphs whose *live* set fits).
        self._free = free
        # Free-stack of recycled VBLOCK-word value blocks, same layout.
        self._vfree = vfree
        self._uses_row_values = uses_row_values
        # Whether this kernel composition can host migrated (homed) rows:
        # only then do spawn/take_continuation maintain the F_HOME words
        # (ResidentKernel sets Megakernel.tracks_home; plain megakernels
        # skip the dead scalar writes - the cost unit on this tier).
        self._tracks_home = tracks_home

    # -- descriptor access --

    def arg(self, i: int):
        return self._tasks[self.idx, F_A0 + i]

    def set_arg(self, idx, i: int, v) -> None:
        """Write argument word i of descriptor ``idx`` (e.g. to point a
        just-spawned join task at values whose location depends on its own
        row, which is only known after the spawn)."""
        self._tasks[idx, F_A0 + i] = v

    @property
    def out_slot(self):
        return self._tasks[self.idx, F_OUT]

    def value(self, slot):
        return self.ivalues[slot]

    def set_value(self, slot, v) -> None:
        self.ivalues[slot] = v

    def set_out(self, v) -> None:
        self.ivalues[self.out_slot] = v

    # -- on-device promises (the serving-loop wait surface) --

    def satisfy(self, slot, v=1) -> None:
        """Satisfy the promise flag at value slot ``slot``: one scalar
        SMEM write of a NONZERO word (``v``) - the SURVEY north star's
        "promise satisfaction becomes on-device flag writes". The
        matching ``wait_value`` observes it; the wait-graph analysis
        (hclib_tpu.analysis.waits) proves at construction that every
        waiter has a satisfier that can run first."""
        self.ivalues[slot] = v

    def wait_value(self, slot, spin_cap: int = 4096):
        """Block this task in place until the promise flag at value slot
        ``slot`` is nonzero (bounded spin; returns the observed value).

        This is an IN-BODY wait - unlike dependency edges (a task with
        deps simply isn't ready; the scheduler never blocks), a spinning
        wait occupies the core, so on a single scheduler it can only
        succeed if the satisfier already ran. That is exactly why kinds
        using it are GATED at construction: ``Megakernel(verify=True)``
        runs the wait-graph deadlock analysis over every kind's recorded
        wait/satisfy/spawn ops and refuses cycles (analysis/waits.py,
        rule ``wait-cycle``) - the safety floor under the completion-
        promise serving loop. ``spin_cap`` bounds the spin (static);
        exhaustion sets ``OVF_PROMISE`` so the host raises a diagnostic
        instead of the kernel wedging the core."""

        def cond(c):
            i, seen = c
            return (i < jnp.int32(spin_cap)) & jnp.logical_not(seen)

        def body(c):
            i, _ = c
            return (i + 1, self.ivalues[slot] != 0)

        _, seen = jax.lax.while_loop(
            cond, body, (jnp.int32(0), self.ivalues[slot] != 0)
        )
        self._counts[C_OVERFLOW] = jnp.where(
            seen, self._counts[C_OVERFLOW],
            self._counts[C_OVERFLOW] | OVF_PROMISE,
        )
        return self.ivalues[slot]

    # -- dynamic task creation --

    def alloc_values(self, k: int):
        """Reserve k consecutive scalar value slots; returns the base slot.

        k <= VBLOCK allocations consume one VBLOCK-word block, preferring a
        recycled block from the free stack (see ``free_values``) over the
        bump allocator - so graphs whose *live* value set fits run
        unbounded, like descriptor rows. k > VBLOCK falls back to exact-size
        bump allocation and is never recycled. Exhaustion sets the overflow
        flag and clamps so writes stay in bounds - the host raises after
        the kernel returns.

        Re-entrant callers (the sharded steal round loop): the value-block
        free stack is scratch, reset on every kernel entry, so blocks freed
        in an earlier round are NOT reusable later - the bump cursor holds
        its high-water mark and exhaustion is reported as overflow, never
        corruption. (Descriptor rows don't have this limit: stage()
        rebuilds their free stack from completion tombstones.) Long-lived
        recycling under re-entry wants row-owned blocks (``row_values``),
        which recycle with the rows."""
        if self._uses_row_values:
            # Trace-time guard: the bump region starts exactly at the
            # row-block base (C_VBASE == initial C_VALLOC), so any bump
            # allocation would silently alias row 0's block.
            raise ValueError(
                "alloc_values cannot be mixed with row_values "
                "(uses_row_values=True): the bump region overlaps the "
                "row-owned blocks"
            )
        # Branch-free (unconditional SMEM read-modify-writes + selects):
        # scalar-core conditionals cost more than the handful of extra SMEM
        # ops they would save, and this runs on every dynamic spawn.
        if k > VBLOCK:
            base = self._counts[C_VALLOC]
            ok = base + k <= self._num_values
            self._counts[C_VALLOC] = jnp.where(ok, base + k, base)
            self._counts[C_OVERFLOW] = jnp.where(
                ok, self._counts[C_OVERFLOW],
                self._counts[C_OVERFLOW] | OVF_VALUES,
            )
            return jnp.where(ok, base, jnp.maximum(self._num_values - k, 0))
        nfree = self._vfree[0]
        use_free = nfree > 0
        b_free = self._vfree[jnp.maximum(nfree, 1)]
        b_new = self._counts[C_VALLOC]
        ok = use_free | (b_new + VBLOCK <= self._num_values)
        self._vfree[0] = nfree - use_free.astype(jnp.int32)
        self._counts[C_VALLOC] = jnp.where(
            jnp.logical_not(use_free) & ok, b_new + VBLOCK, b_new
        )
        self._counts[C_OVERFLOW] = jnp.where(
            ok, self._counts[C_OVERFLOW],
            self._counts[C_OVERFLOW] | OVF_VALUES,
        )
        return jnp.where(
            use_free,
            b_free,
            jnp.where(
                ok, b_new, jnp.maximum(self._num_values - VBLOCK, 0)
            ),
        )

    def row_values(self, idx):
        """Base of the VBLOCK-word value block *owned by descriptor row*
        ``idx`` - the zero-overhead alternative to alloc/free_values for
        spawn/join patterns: the block's lifetime IS the row's lifetime
        (rows recycle on completion, so the block recycles with them, no
        allocator on the hot path). A join task derives its block from its
        own row (``ctx.row_values(ctx.idx)``); its spawner points children's
        out slots into it. Requires ``num_values >= host-preset slots +
        VBLOCK * capacity`` (sized by the host; see Megakernel docs) and
        must not be mixed with bump-side ``alloc_values`` in the same
        megakernel (the bump region overlaps the row blocks)."""
        return self._counts[C_VBASE] + idx * VBLOCK

    def free_values(self, base) -> None:
        """Return the VBLOCK-word block at ``base`` (from a k <= VBLOCK
        ``alloc_values``) to the free stack. Call from the kernel that
        consumes the block's values - after this, the slots may be handed to
        any later allocation (the analogue of the reference freeing a task's
        promise cells once its continuation has read them). Never free
        host-preset slots or k > VBLOCK allocations.

        A full stack means more frees than blocks exist (double-free or a
        host-preset base): the push is clamped inside the stack and
        C_OVERFLOW is set so the host raises instead of silently corrupting
        SMEM past the scratch window."""
        vcap = self._num_values // VBLOCK  # stack slots available
        nf = self._vfree[0] + 1
        ok = nf <= vcap
        nf_c = jnp.minimum(nf, vcap)
        self._vfree[0] = nf_c
        # On overflow this rewrites the top element with itself (one block
        # leaks; no corruption).
        self._vfree[nf_c] = jnp.where(ok, base, self._vfree[nf_c])
        self._counts[C_OVERFLOW] = jnp.where(
            ok, self._counts[C_OVERFLOW],
            self._counts[C_OVERFLOW] | OVF_VALUES,
        )

    def push_ready(self, t) -> None:
        tail = self._counts[C_TAIL]
        self._ready[tail % self._capacity] = t
        self._counts[C_TAIL] = tail + 1

    def add_executed(self, n) -> None:
        """Credit ``n`` extra executed tasks (the vector tier reports its
        expanded node count here so 'executed' means tasks across both
        tiers, and fuel accounting sees vector work)."""
        self._counts[C_EXECUTED] = self._counts[C_EXECUTED] + n

    def flag_overflow(self, cond) -> None:
        """Raise the overflow flag where ``cond`` (host raises after the
        kernel returns)."""
        self._counts[C_OVERFLOW] = jnp.where(
            cond, self._counts[C_OVERFLOW] | OVF_ENGINE,
            self._counts[C_OVERFLOW],
        )

    def take_continuation(self, new_idx) -> None:
        """Transfer this task's successors to ``new_idx`` - the descriptor
        equivalent of the reference turning a blocked stack into a
        continuation task (_help_finish_ctx, src/hclib-runtime.c:1032-1065):
        the spawned task becomes the continuation that fires our successors."""
        t = self._tasks
        t[new_idx, F_SUCC0] = t[self.idx, F_SUCC0]
        t[new_idx, F_SUCC1] = t[self.idx, F_SUCC1]
        t[new_idx, F_CSR_OFF] = t[self.idx, F_CSR_OFF]
        t[new_idx, F_CSR_N] = t[self.idx, F_CSR_N]
        t[self.idx, F_SUCC0] = jnp.int32(NO_TASK)
        t[self.idx, F_SUCC1] = jnp.int32(NO_TASK)
        t[self.idx, F_CSR_N] = 0
        if self._tracks_home:
            # A migrated copy's continuation inherits the home-link as
            # well: whoever ends the chain forwards the result to the home
            # proxy (device/resident.py's remote-completion protocol).
            t[new_idx, F_HOME] = t[self.idx, F_HOME]
            t[new_idx, F_HROW] = t[self.idx, F_HROW]
            t[self.idx, F_HOME] = jnp.int32(NO_TASK)

    def spawn(
        self,
        fn: int,
        args: Sequence = (),
        dep_count=0,
        succ0=NO_TASK,
        succ1=NO_TASK,
        out=0,
        nargs: Optional[int] = None,
    ):
        """Allocate + enqueue a new task descriptor; returns its index.

        On table overflow the task is dropped and counts[C_OVERFLOW] is set
        (the reference asserts on deque overflow, src/hclib-runtime.c:520-524;
        here the host checks the flag after the kernel returns).

        ``nargs`` (static) bounds how many arg words the new task will ever
        read (default: all 6 are zeroed). Scalar SMEM writes are the unit
        of cost on this tier (~1 cycle each), so a spawn-heavy kernel that
        declares its arity skips up to 6 dead writes per spawn - recycled
        rows may hold stale words beyond nargs, which a conforming kernel
        never reads (the same contract C lets the reference's task structs
        rely on, inc/hclib-task.h:32-44).
        """
        if nargs is None:
            nargs = 6
        if len(args) > nargs:
            raise ValueError(f"{len(args)} args exceed declared nargs={nargs}")
        nfree = self._free[0]
        use_free = nfree > 0
        a_free = self._free[jnp.maximum(nfree, 1)]
        a_new = self._counts[C_ALLOC]
        ok = use_free | (a_new < self._capacity)
        a_clamped = jnp.where(
            use_free, a_free, jnp.minimum(a_new, self._capacity - 1)
        )

        @pl.when(use_free)
        def _():
            self._free[0] = nfree - 1

        @pl.when(jnp.logical_not(use_free) & (a_new < self._capacity))
        def _():
            self._counts[C_ALLOC] = a_new + 1

        @pl.when(ok)
        def _():
            self._counts[C_PENDING] = self._counts[C_PENDING] + 1
            self._tasks[a_clamped, F_FN] = jnp.int32(fn)
            self._tasks[a_clamped, F_DEP] = jnp.int32(dep_count)
            self._tasks[a_clamped, F_SUCC0] = jnp.int32(succ0)
            self._tasks[a_clamped, F_SUCC1] = jnp.int32(succ1)
            # F_CSR_OFF is only ever read under F_CSR_N > 0, so a stale
            # offset in a recycled row is dead - no write needed.
            self._tasks[a_clamped, F_CSR_N] = 0
            for i in range(nargs):
                self._tasks[a_clamped, F_A0 + i] = (
                    jnp.int32(args[i]) if i < len(args) else 0
                )
            self._tasks[a_clamped, F_OUT] = jnp.int32(out)
            if self._tracks_home:
                # Recycled rows may carry a stale home-link from a
                # previously migrated occupant; fresh spawns are local
                # tasks. (F_VMASK needs no clear: it is only set on wire
                # copies, and the import path zeroes it after
                # rehydration.)
                self._tasks[a_clamped, F_HOME] = jnp.int32(NO_TASK)

        @pl.when(ok & (jnp.int32(dep_count) == 0))
        def _():
            self.push_ready(a_clamped)

        @pl.when(jnp.logical_not(ok))
        def _():
            self._counts[C_OVERFLOW] = self._counts[C_OVERFLOW] | OVF_ROWS

        return a_clamped


class BatchSpec:
    """Describes the batched-dispatch form of one kernel-table entry.

    A kind routed through a BatchSpec is never dispatched through the
    ``lax.switch`` table: the scheduler diverts its ready descriptors into a
    per-kind SMEM lane and, each batch round, pops up to ``width`` of them
    and invokes ``body(ctx: BatchContext)`` ONCE for the whole group - one
    tiled kernel body instead of ``width`` sequential switch dispatches.
    Ready descriptors of one kind are mutually independent by construction
    (neither's completion has run, so neither can be the other's
    predecessor), which is what makes same-kind group execution safe for
    arbitrary DAGs; the body remains responsible for its slots writing
    disjoint data.

    Lane pop order: non-prefetch specs pop the NEWEST queued descriptors
    each round (LIFO, the scalar tier's owner-side discipline) - recursive
    spawn-heavy families stay depth-first (bounded live set) and the
    oldest entries stay cold for the multi-device steal exchanges.
    ``prefetch=True`` switches the lane to FIFO pops, which the prefetch
    pipeline requires (see below); the static tile DAGs that use prefetch
    are order-insensitive.

    ``priority`` opts the kind into the priority-bucket tier (armed only
    when the megakernel is built with ``priority_buckets=B``): a callable
    ``priority(arg) -> traced int32`` where ``arg(i)`` reads the popped
    descriptor's arg word ``i`` - the bucket id is a pure function of the
    descriptor's own words, clipped into ``[0, B)`` by the scheduler.
    Routing diverts the descriptor into its kind's bucket ring; at
    ring-drain time the LOWEST non-empty bucket fires first, so ordered-
    retirement workloads (delta-stepping relaxation, best-first search)
    retire cheap/urgent work before speculative work. Priorities are a
    performance hint ONLY: results must be schedule-independent (the
    ``si_claim`` certification gate), and with ``priority_buckets``
    off/unset the callable is never consulted - the build is
    byte-identical to one without it.

    ``prefetch=True`` opts into the cross-round double-buffer protocol:
    the tier tells the body how many descriptors of the NEXT prospective
    batch to prefetch (``ctx.prefetch_count``) and, the round after, how
    many of its own slots were already prefetched (``ctx.prefetched``, into
    operand-buffer half ``ctx.buf``). A lane entry's inputs are fully
    written before it is pushed (its predecessors completed in earlier
    rounds and batch bodies drain their stores before completion runs), so
    prefetching a queued descriptor's operands during the current batch's
    compute is always safe. A body that opts in MUST issue exactly the
    starts the tier announces and MUST provide ``drain(ctx)`` to wait the
    in-flight prefetch of ``ctx.prefetched`` descriptors - the scheduler
    calls it before spilling unrun lane entries at exit so no DMA outlives
    its consumer.
    """

    def __init__(self, body, width: int = 8, prefetch: bool = False,
                 drain=None, priority=None,
                 verify_suppress: Sequence[str] = ()) -> None:
        if width < 1:
            raise ValueError(f"batch width must be >= 1, got {width}")
        if prefetch and drain is None:
            raise ValueError(
                "prefetch=True requires a drain(ctx) callback: the "
                "scheduler must be able to retire in-flight prefetch DMAs "
                "when it exits with lane entries unrun"
            )
        if priority is not None and not callable(priority):
            raise ValueError(
                "priority must be a callable priority(arg) -> bucket "
                "(arg(i) reads the descriptor's arg word i)"
            )
        self.body = body
        self.width = int(width)
        self.prefetch = bool(prefetch)
        self.drain = drain
        self.priority = priority
        # Per-rule opt-outs for the build-time verifier (hclib_tpu.
        # analysis): a spec whose body DELIBERATELY violates a checked
        # contract (e.g. intentionally-shared value slots) annotates the
        # rule here - the suppression rides the spec, next to the code
        # it excuses, and the finding still appears (marked suppressed)
        # in hclint reports.
        self.verify_suppress = tuple(verify_suppress)


class BatchContext:
    """Facilities exposed to batched-dispatch bodies: per-slot descriptor
    access for the current (and prospective next) batch, plus the underlying
    KernelContext facilities (``data``/``scratch``/value slots/overflow).

    Slot liveness is a prefix: slots ``[0, count)`` are live, and a live
    slot's descriptor row is ``idx(s)``. ``count`` is traced (1..width);
    ``width`` is static - bodies unroll ``range(width)`` under
    ``pl.when(s < count)``.
    """

    def __init__(self, kctx, lanes, li, head, count, width,
                 prefetched, buf, prefetch_count, capacity,
                 ctx_hook=None):
        self.k = kctx
        self._lanes = lanes
        self._li = li
        self._head = head
        self.count = count
        self.width = width
        # Prefetch protocol (zeros unless the spec opted in):
        self.prefetched = prefetched      # slots already loaded last round
        self.buf = buf                    # 0/1 operand half holding them
        self.prefetch_count = prefetch_count  # next-batch slots to issue
        self._capacity = capacity
        # The embedding runner's per-task context hook (attaches ctx.pgas
        # on the resident/pgas runners): applied to every slot_ctx so a
        # batch body's per-slot contexts carry the same facilities the
        # scalar dispatch path would have handed the task.
        self._ctx_hook = ctx_hook

    # -- current batch --

    def _row(self, pos):
        """Lane entry at FIFO position ``pos``, clamped into the descriptor
        table: dead-slot reads (callers guard semantics with ``live``) must
        still be IN-BOUNDS SMEM accesses, and uninitialized lane words must
        never index past the task table."""
        row = self._lanes[
            self._li, jnp.maximum(pos, 0) % self._capacity
        ]
        return jnp.clip(row, 0, self._capacity - 1)

    def idx(self, s):
        """Descriptor row of slot ``s`` (meaningful for s < count; clamped
        but arbitrary otherwise)."""
        return self._row(self._head + jnp.minimum(s, self.count - 1))

    def live(self, s):
        return jnp.int32(s) < self.count

    def arg(self, s, i: int):
        return self.k._tasks[self.idx(s), F_A0 + i]

    def out_slot(self, s):
        return self.k._tasks[self.idx(s), F_OUT]

    def set_out(self, s, v) -> None:
        """Write slot ``s``'s output value (callers guard liveness)."""
        self.k.ivalues[self.out_slot(s)] = v

    def slot_ctx(self, s):
        """A KernelContext focused on slot ``s``'s descriptor row - for
        batch bodies whose per-slot work is scalar-shaped (dynamic spawns,
        continuation transfer) rather than one fused tile op. The returned
        context shares every underlying ref with this batch, so
        ``spawn``/``take_continuation``/``set_arg``/``row_values`` behave
        exactly as they would under scalar dispatch of the same row; a
        body that unrolls ``range(width)`` under ``pl.when(live(s))`` and
        runs the scalar kernel per live slot computes bit-identical
        results while skipping the per-descriptor ring pop + lax.switch
        overhead (the batched spelling of spawn-heavy families like fib)."""
        k = self.k
        ctx = KernelContext(
            self.idx(s), k._tasks, k._succ, k._ready, k._counts, k.ivalues,
            k.data, k.scratch, k._capacity, k._free, k._num_values,
            k._vfree, k._uses_row_values, k._tracks_home,
        )
        if self._ctx_hook is not None:
            self._ctx_hook(ctx)
        return ctx

    # -- prospective next batch (prefetch targets) --

    def next_idx(self, s):
        """Descriptor row of slot ``s`` of the NEXT batch (meaningful for
        s < prefetch_count): lane pops are FIFO, so the entries behind the
        current batch are exactly what the next batch round will pop."""
        return self._row(
            self._head + self.count
            + jnp.minimum(s, self.prefetch_count - 1)
        )

    def next_arg(self, s, i: int):
        return self.k._tasks[self.next_idx(s), F_A0 + i]

    # -- KernelContext delegation --

    @property
    def data(self):
        return self.k.data

    @property
    def scratch(self):
        return self.k.scratch

    def value(self, slot):
        return self.k.value(slot)

    def set_value(self, slot, v) -> None:
        self.k.set_value(slot, v)

    def satisfy(self, slot, v=1) -> None:
        self.k.satisfy(slot, v)

    def wait_value(self, slot, spin_cap: int = 4096):
        return self.k.wait_value(slot, spin_cap)

    def add_executed(self, n) -> None:
        self.k.add_executed(n)

    def flag_overflow(self, cond) -> None:
        self.k.flag_overflow(cond)


def _is_vector_spec(fn) -> bool:
    from .vector_engine import VectorTaskSpec

    return isinstance(fn, VectorTaskSpec)


def _is_batch_spec(fn) -> bool:
    return isinstance(fn, BatchSpec)


def _batch_stub(ctx: "KernelContext") -> None:
    """Switch-table placeholder for a batch-routed kind. Unreachable by
    construction: the scalar pop path diverts these F_FNs into their lane
    before dispatch, so the branch only exists to keep the table dense."""
    return None


def _wrap_vector_spec(spec, interpret: bool):
    """Bridge a VectorTaskSpec into the scalar kernel table: popping a task
    of this F_FN dispatches its whole subtree across VPU lanes (the batch-
    dispatch tier, device/vector_engine.py). The seed task's 6 arg words
    feed ``spec.seed``; the ``out_acc`` accumulator lands in the task's
    F_OUT value slot; expanded-node count is credited to C_EXECUTED so
    'executed' counts tasks across both tiers."""
    from .vector_engine import make_subtree_runner

    runner = make_subtree_runner(spec, use_pltpu_roll=not interpret)

    def body(ctx: "KernelContext") -> None:
        args = tuple(ctx.arg(i) for i in range(6))
        seed_frame, seed_count = spec.seed(args)
        nodes, accs, over = runner(seed_frame, seed_count)
        if spec.root_contrib is not None:
            # The vector steps only ever expand *children*; a seed that is
            # itself a leaf contributes here (its execution is already
            # counted by the scalar tier's complete()).
            rc = spec.root_contrib(args)
            root_leaf = jnp.int32(seed_count) == 0
            accs = {
                name: accs[name] + jnp.where(root_leaf, rc.get(name, 0), 0)
                for name in accs
            }
        if spec.out_acc is not None:
            ctx.set_out(accs[spec.out_acc])
        ctx.add_executed(nodes)
        ctx.flag_overflow(over)

    # The verifier's classification pass must not abstractly interpret
    # the subtree runner (it embeds whole-engine sweeps); the marker
    # routes this kind straight to the 'vector' class.
    body._hclib_vector_wrapped = True
    return body


class Megakernel:
    """Builds and runs the single-core scheduler kernel over a task DAG.

    ``kernels`` is an ordered list of ``(name, fn)`` where ``fn(ctx)`` emits
    the device code for that kernel-table entry; a task's F_FN word indexes
    this table. ``data_specs`` declares named tensor buffers (passed to
    ``run`` and updated in place); ``scratch_specs`` declares named VMEM /
    semaphore scratch allocations available to kernels via ``ctx.scratch``.
    """

    def __init__(
        self,
        kernels: Sequence[Tuple[str, Callable[[KernelContext], None]]],
        data_specs: Optional[Dict[str, jax.ShapeDtypeStruct]] = None,
        scratch_specs: Optional[Dict[str, Any]] = None,
        capacity: int = 4096,
        num_values: int = 4096,
        succ_capacity: int = 4096,
        interpret: Optional[bool] = None,
        uses_row_values: bool = False,
        vmem_limit_bytes: Optional[int] = None,
        route: Optional[Dict[str, Any]] = None,
        auto_route: Optional[Dict[str, Any]] = None,
        trace: Optional[Any] = None,
        checkpoint: Optional[bool] = None,
        quiesce_stride: Optional[int] = None,
        lane_max_age: Optional[int] = None,
        priority_buckets: Optional[int] = None,
        verify: Optional[bool] = None,
        verify_suppress: Sequence[str] = (),
    ) -> None:
        if interpret is None:
            interpret = jax.default_backend() == "cpu"
        # Device flight recorder (device/tracebuf.py): ``trace`` is None
        # (off: zero compiled cost, no extra outputs - bit-identical to a
        # build that predates tracing), a record capacity, or a TraceRing.
        # When on, run() appends one SMEM ring output the scheduler writes
        # round/dispatch/prefetch records into, decoded as info['trace'].
        # HCLIB_TPU_TRACE=1 (default capacity) or =N turns it on
        # process-wide without touching call sites. Env-derived tracing is
        # marked so runners that cannot trace (ShardedMegakernel) degrade
        # to untraced instead of failing a run the env owner never wrote.
        self.trace_from_env = False
        if trace is None:
            env = env_raw("HCLIB_TPU_TRACE", "")
            if env and env != "0":
                try:
                    n = int(env)
                except ValueError:
                    n = 1
                # n <= 0 stays off (a negative typo in a process-wide env
                # must not abort runs that never asked for tracing).
                if n > 0:
                    trace = True if n == 1 else n
                    self.trace_from_env = True
        self.trace = TraceRing.of(trace)
        # Checkpoint/restore (runtime/checkpoint.py): ``checkpoint=True``
        # compiles the quiesce protocol into the scheduler - a qctl HBM
        # input re-read inside the round loop plus a qstat output (QC_*/
        # QS_* above). DeviceFaultPlan discipline: False compiles none of
        # it (no extra refs, no per-round DMA - bit-identical to a build
        # that predates checkpointing). HCLIB_TPU_CHECKPOINT=1 turns it on
        # process-wide; env-derived enablement is marked so runners that
        # cannot export state (ShardedMegakernel) degrade instead of
        # failing a run the env owner never wrote.
        self.checkpoint_from_env = False
        if checkpoint is None:
            checkpoint = env_bool("HCLIB_TPU_CHECKPOINT")
            self.checkpoint_from_env = checkpoint
        self.checkpoint = bool(checkpoint)
        # Quiesce poll stride (checkpoint builds only): the scheduler
        # re-reads the qctl word from HBM every scheduling round by
        # default, which is what the checkpoint-overhead guard prices
        # (~1.2x enabled-idle). ``quiesce_stride=N`` polls every Nth
        # round instead - the DMA cost amortizes N-fold and a quiesce
        # request lands at most N-1 rounds later than it would have (the
        # bounded-latency trade ROADMAP's open item asked to expose).
        # HCLIB_TPU_QUIESCE_STRIDE sets it process-wide; a malformed or
        # nonpositive value degrades to 1 (poll every round), never off.
        if quiesce_stride is None:
            quiesce_stride = env_int(
                "HCLIB_TPU_QUIESCE_STRIDE", None, malformed=1
            )
        self.quiesce_stride = max(1, int(quiesce_stride or 1))
        # Lane firing-policy age trigger (the ROADMAP lane-policy fix):
        # ``lane_max_age=N`` lets a batch lane that has held entries for N
        # consecutive scheduling rounds without firing JUMP the
        # ring-drain-first policy and fire its (possibly partial) batch -
        # see the firing-policy site in _make_core's sched(). 0/None = off:
        # no age words are written and the round loop is the pre-knob
        # ring-drain-first policy, byte-for-byte. HCLIB_TPU_LANE_MAX_AGE
        # sets it process-wide; malformed or negative values RAISE (the
        # PR 8 env convention - a typo must not silently change the
        # firing policy).
        if lane_max_age is None:
            lane_max_age = env_int("HCLIB_TPU_LANE_MAX_AGE", None)
        lane_max_age = int(lane_max_age or 0)
        if lane_max_age < 0:
            raise ValueError(
                f"lane_max_age must be >= 0 (0 = off), got {lane_max_age}"
            )
        self.lane_max_age = lane_max_age
        # Priority-bucket dispatch tier (ISSUE 15): ``priority_buckets=B``
        # layers B bucket rings over every per-kind batch lane and makes
        # ring-drain firing pop the LOWEST non-empty bucket first (see
        # the firing-policy site in sched()). The bucket id is computed
        # at routing time by the kind's BatchSpec.priority callable - a
        # pure function of the popped descriptor's arg words, so residue
        # re-buckets on resume/reshard by construction. 0/None = off: no
        # bucket rings, priorities never consulted - byte-identical to a
        # build whose specs carry no priority at all (asserted).
        # HCLIB_TPU_PRIORITY_BUCKETS sets it process-wide; malformed or
        # out-of-range values RAISE (the PR 8 env convention).
        if priority_buckets is None:
            priority_buckets = env_int("HCLIB_TPU_PRIORITY_BUCKETS", None)
        priority_buckets = int(priority_buckets or 0)
        if priority_buckets and not 2 <= priority_buckets <= BK_MAX:
            raise ValueError(
                f"priority_buckets must be 0 (off) or 2..{BK_MAX} "
                f"(the static bucket-ring set), got {priority_buckets}"
            )
        self.priority_buckets = priority_buckets
        # Dispatch-tier routing: ``route`` maps a kernel NAME to the spec
        # of a non-scalar dispatch tier for that task family. Two tiers:
        #
        # - VectorTaskSpec (the subtree tier, device/vector_engine.py): a
        #   recursive + reduction-shaped family whose tasks are dispatched
        #   as whole subtrees across the VPU lanes - one descriptor pop
        #   expands thousands of frame-tasks.
        # - BatchSpec (the batched same-kind tier): ready descriptors of
        #   this kind are diverted into a per-kind SMEM lane; each batch
        #   round pops up to ``width`` of them and runs ONE tiled body over
        #   the group (with optional cross-round operand prefetch) instead
        #   of ``width`` sequential ``lax.switch`` dispatches.
        #
        # Either way the routed entry is a drop-in at the DAG level: its
        # result lands where the scalar kernel's would and its successors
        # fire on completion, so irregular DAGs mix routed and scalar
        # tasks freely. A spec must compute the same values as the scalar
        # kernel it replaces. ``auto_route`` is the legacy vector-tier-only
        # spelling, kept as an alias.
        self.route = dict(route or {})
        if auto_route:
            self.route.update(auto_route)
        self.auto_route = self.route  # legacy alias
        unknown = set(self.route) - {name for name, _ in kernels}
        if unknown:
            raise ValueError(
                f"route/auto_route names unknown kernels: {sorted(unknown)}"
            )
        not_specs = [
            n for n, s in self.route.items()
            if not (_is_vector_spec(s) or _is_batch_spec(s))
        ]
        if not_specs:
            raise ValueError(
                f"route values must be VectorTaskSpecs or BatchSpecs; "
                f"{sorted(not_specs)} are not"
            )
        self.kernel_names = [name for name, _ in kernels]
        self.fn_id = {name: i for i, name in enumerate(self.kernel_names)}
        # Batch-routed kinds never reach the switch table (the scheduler
        # pops them into lanes): their branch is a no-op stub, so their
        # batched body is the only trace (a scalar twin would force both
        # bodies' scratch into every build).
        self.batch_specs = sorted(
            (
                (self.fn_id[name], spec)
                for name, spec in self.route.items()
                if _is_batch_spec(spec)
            ),
            key=lambda kv: kv[0],
        )
        batched_ids = {fid for fid, _ in self.batch_specs}
        routed = [
            (name, self.route.get(name, fn)) for name, fn in kernels
        ]
        self.kernel_fns = [
            _wrap_vector_spec(fn, interpret) if _is_vector_spec(fn)
            else (_batch_stub if i in batched_ids else fn)
            for i, (_, fn) in enumerate(routed)
        ]
        self.data_specs = dict(data_specs or {})
        self.scratch_specs = dict(scratch_specs or {})
        self.capacity = capacity
        self.num_values = num_values
        self.succ_capacity = succ_capacity
        # Declare when any kernel calls ctx.row_values: run() then verifies
        # every row's block fits below num_values (the region starts at the
        # runtime value_alloc, which out-slots and presets can push up).
        self.uses_row_values = uses_row_values
        self.interpret = interpret
        # Kernels whose scratch exceeds the compiler's default 16 MiB
        # scoped-vmem budget (e.g. 1024x1024 f32 tile pipelines) raise it
        # here; real VMEM is 128 MiB on v5e.
        self.vmem_limit_bytes = vmem_limit_bytes
        # Set by ResidentKernel when homed migration is configured: the
        # scheduler then maintains descriptor home-link words on spawn and
        # continuation transfer (dead writes otherwise - skipped).
        self.tracks_home = False
        self._jitted: Dict[int, Any] = {}  # fuel -> compiled call
        # Last shared_build stats ({hit, build_s, cache_lookup_s}) for
        # this instance's most recent program build - surfaced as
        # info['program_cache'] (and the tiers timing gauges) so every
        # run reports what its program cost to obtain.
        self._pc_stats: Optional[Dict[str, Any]] = None
        # Last run()'s info dict (incl. the batched-tier counters), for
        # stats_dict() consumers that don't thread the return value.
        self._last_info: Optional[Dict[str, Any]] = None
        # Packs counts + ivalues (+ tier stats) into one array so the host
        # needs a single device->host fetch (transfers are ~67ms each
        # through the axon tunnel; on a directly-attached TPU VM this
        # matters far less).
        self._packer = jax.jit(lambda *a: jnp.concatenate(a))
        # Build-time static verifier (hclib_tpu.analysis - the hclint
        # station): pure host analysis over the objects assembled above,
        # so it cannot change the compiled program in ANY mode - it can
        # only raise here with a witness. verify=None resolves through
        # HCLIB_TPU_VERIFY, defaulting ON under pytest and off
        # elsewhere; error findings raise AnalysisError unless listed in
        # ``verify_suppress`` (see analysis.findings for the syntax).
        self.verify_suppress = tuple(verify_suppress)
        # Schedule-independence claim (analysis/model.py): builders whose
        # exactness story IS schedule-independence (frontier traversals,
        # forasync tile loops) stamp their claim here; describe() and
        # hclint surface the certificate (or the refusal) lazily.
        self.si_claim = None
        if verify is None:
            from ..analysis.findings import verify_default

            verify = verify_default()
        self.verify = bool(verify)
        self.analysis = None
        if self.verify:
            from ..analysis import verify_megakernel

            self.analysis = verify_megakernel(
                self, suppress=self.verify_suppress
            )

    @property
    def lane_scratch_rows(self) -> int:
        """Rows of the batched-tier lane/lstate SMEM scratch: one ring
        per routed kind, times ``priority_buckets`` bucket rings per
        kind when the priority tier is armed. Every embedder that
        allocates the scratch (this class's _build_raw, the sharded/
        resident/ici/pgas runners) sizes it from here so the bucket
        layout cannot drift per runner."""
        return len(self.batch_specs) * (self.priority_buckets or 1)

    def describe(self) -> Dict[str, Any]:
        """Whole-program description of this megakernel's kernel table:
        per-kind dispatch tier and migratability classification (the
        reshard-class analysis), plus the build knobs - what hclint
        prints and what checkpoint bundles carry for upfront reshard
        diagnostics. Classification runs on demand (one recording-shim
        pass, memoized) even when verification is off."""
        from ..analysis import classify_megakernel

        classes = classify_megakernel(self)
        batched = {fid: spec for fid, spec in self.batch_specs}
        kinds = {}
        for i, name in enumerate(self.kernel_names):
            spec = batched.get(i)
            kinds[name] = {
                "id": i,
                "dispatch": (
                    "batch" if spec is not None
                    else ("vector" if classes.get(name) == "vector"
                          else "scalar")
                ),
                "classification": classes.get(name, "unknown"),
                **(
                    {"width": spec.width, "prefetch": spec.prefetch,
                     "priority": spec.priority is not None}
                    if spec is not None else {}
                ),
            }
        cert = None
        if self.si_claim is not None:
            from ..analysis.model import certify_claim

            cert = certify_claim(self, raise_on_error=False)
        return {
            "kinds": kinds,
            "capacity": self.capacity,
            "num_values": self.num_values,
            "checkpoint": self.checkpoint,
            "priority_buckets": self.priority_buckets,
            "verify": self.verify,
            # The schedule-independence certificate (analysis/model.py),
            # beside the reshard classification: None when the builder
            # made no claim; a dict with status "certified" (K permuted
            # pop orders, identical fixpoint) or "refused" (with the two
            # divergent schedules) otherwise.
            "schedule_independence": cert,
            "findings": (
                self.analysis.to_jsonable() if self.analysis else []
            ),
        }

    # -- the kernel body --

    def _make_core(
        self,
        succ,
        tasks,
        ready,
        counts,
        ivalues,
        data,
        scratch,
        free,
        vfree,
        tasks_in,
        ready_in,
        counts_in,
        ivalues_in,
        stage_all_values: bool,
        ctx_hook: Optional[Callable[["KernelContext"], None]] = None,
        complete_hook=None,
        value_limit: Optional[int] = None,
        lanes=None,
        lstate=None,
        tstats=None,
        tracer=None,
        quiesce_hook=None,
        fire_hook=None,
        round_hook=None,
    ):
        """Builds the scheduler core closures over a concrete set of refs:
        ``stage()`` (copy host state into the mutable windows), and
        ``sched(fuel)`` (pop/dispatch/complete until the ready ring drains
        or ``fuel`` tasks have run since this call). Used by this class's
        own kernel body and by kernels that embed the scheduler next to
        other phases (the in-kernel ICI steal runner, device/ici_steal.py;
        the one-sided PGAS runner, device/pgas_kernel.py - whose
        ``ctx_hook`` attaches its put/am/wait-until ops to each task's
        KernelContext before dispatch; the unified resident runner,
        device/resident.py - whose ``complete_hook(idx)`` runs at the top
        of every completion to forward migrated tasks' results home, and
        whose ``value_limit`` caps dynamic value allocation below the
        region it reserves for migration result slots).

        ``quiesce_hook(executed_since_entry)`` - when given - is evaluated
        once per scheduling round and returns a traced bool; a True makes
        sched() stop popping at that round boundary and exit through the
        normal fuel-exhaustion path (lanes spill to the ring, prefetches
        drain), leaving the live scheduler state in the output windows.
        The hook owns observation bookkeeping (qstat, TR_QUIESCE). None
        compiles nothing - the checkpoint-off path is byte-identical.

        ``fire_hook(idx)`` / ``round_hook()`` are the telemetry seams
        (ISSUE 19, device/telemetry.py): round_hook() runs once per
        scheduling round right after the trace tick (it owns the
        cumulative round counter and the live gauges), fire_hook(idx)
        runs at every dispatch site - scalar pop and each batch slot -
        BEFORE the task body/complete, so the fire-round stamp is
        visible to the egress fold inside complete_hook. None compiles
        nothing - the telemetry-off path is byte-identical.
        """
        capacity = self.capacity
        num_values = value_limit if value_limit is not None else self.num_values
        # Batched same-kind dispatch tier: requires the per-kind lane
        # scratch. Every runner that embeds this core (Megakernel's own
        # build, the sharded steal loop, resident/ici/pgas) allocates and
        # passes it; the lane discipline is steal-round-RE-ENTRANT - sched()
        # unconditionally spills unrun lane entries back to the ready ring
        # at every exit (the fuel/quiesce path below), so between sched
        # calls the ring is the ONLY live structure and the steal/export/
        # checkpoint sides never see a lane-resident descriptor. A direct
        # embedder that forgot the scratch would dispatch batch-routed
        # kinds into their no-op switch stub and silently drop work, so
        # refuse at trace time instead.
        if self.batch_specs and lanes is None:
            routed = sorted(
                self.kernel_names[fid] for fid, _ in self.batch_specs
            )
            raise ValueError(
                f"batch-routed kernels ({routed}) "
                "need the batched dispatch tier's lane scratch "
                "(lanes/lstate/tstats): pass it through _make_core like "
                "Megakernel._build and the multi-device runners "
                "(sharded/resident/ici/pgas) do, or drop the BatchSpec "
                "routes for this embedding"
            )
        use_batch = lanes is not None and len(self.batch_specs) > 0
        nbatch = len(self.batch_specs) if use_batch else 0
        # Priority-bucket tier: each kind's lane becomes ``nbk`` bucket
        # rings (rows ``li*nbk .. li*nbk+nbk-1`` of the lanes/lstate
        # scratch; bucket 0 pops first at drain time). nbk == 1 is the
        # bucket-free tier - every row mapping below degenerates to the
        # pre-knob lane indexing, so the off path compiles byte-for-byte
        # identically.
        nbk = self.priority_buckets if (
            use_batch and self.priority_buckets
        ) else 1
        nrows = nbatch * nbk
        # Static (row, fid, spec) enumeration of every lane-state row,
        # in row order - the spill/stage iteration set. (Drain PRIORITY
        # is not encoded here: the firing policy below derives the
        # lowest-nonempty-bucket choice dynamically via kind_lowb/
        # best_b so each kind keeps one batch-body instantiation.)
        lane_rows = [
            (li * nbk + bk, fid, spec)
            for li, (fid, spec) in enumerate(self.batch_specs)
            for bk in range(nbk)
        ]
        # Flight recorder: a NullTracer's methods are no-ops, so every
        # emit site below compiles to nothing when tracing is off (the
        # DeviceFaultPlan zero-cost-when-disabled pattern).
        tr = tracer if tracer is not None else NullTracer()

        # On TPU, SMEM output windows do NOT start with the aliased input's
        # contents (unlike interpret mode) - stage the initial scheduler
        # state into the mutable output windows explicitly. Only live rows
        # are copied: host-built descriptors ([0, alloc)), the initial ready
        # ring ([0, tail)), and host-preset value slots ([0, value_alloc)) -
        # scalar SMEM stores are expensive enough that staging the whole
        # capacity would dominate small dynamic graphs.
        def stage() -> None:
            free[0] = 0
            vfree[0] = 0
            # Trace header resets per entry/rep, so reps > 1 leaves the
            # LAST rep's records - the same per-graph semantics tstats has.
            tr.reset()
            if use_batch:
                # Lanes/prefetch state are per-entry scratch (sched() spills
                # unrun entries back to the ready ring before returning, so
                # nothing lives in a lane across entries); tstats is the
                # tier's output window - zeroed here so reps report the
                # last rep's per-graph counters.
                for li in range(nrows):
                    for w in range(LS_WORDS):
                        lstate[li, w] = 0
                for w in range(TS_WORDS):
                    tstats[w] = 0
            for i in range(8):
                counts[i] = counts_in[i]
            # Row-owned value blocks sit directly above the host range.
            counts[C_VBASE] = counts_in[C_VALLOC]

            def copy_task(i, _):
                for w in range(DESC_WORDS):
                    tasks[i, w] = tasks_in[i, w]
                # Rebuild the row free stack from completion tombstones so
                # rows freed in earlier entries (sharded steal rounds) are
                # reusable - the stack itself is scratch and resets here.
                tomb = tasks_in[i, F_DEP] == -1
                nf = free[0] + tomb.astype(jnp.int32)
                free[jnp.where(tomb, nf, 0)] = jnp.where(tomb, i, free[0])
                free[0] = nf
                return 0

            jax.lax.fori_loop(0, counts_in[C_ALLOC], copy_task, 0)

            def copy_ready(i, _):
                ready[i] = ready_in[i]
                return 0

            # C_TAIL is the all-time push counter; once it passes capacity
            # the whole ring may be live (entries wrap), and raw C_TAIL as
            # a bound would walk out of the ring. A NEGATIVE head (lane
            # spills insert at the cold end, walking head below zero) also
            # wraps the live window - positions [capacity+head, capacity)
            # hold live entries a [0, tail) copy would drop.
            jax.lax.fori_loop(
                0,
                jnp.where(
                    counts_in[C_HEAD] < 0,
                    capacity,
                    jnp.minimum(counts_in[C_TAIL], capacity),
                ),
                copy_ready,
                0,
            )

            def copy_vals(i, _):
                ivalues[i] = ivalues_in[i]
                return 0

            # stage_all_values=True (re-entrant callers like the sharded
            # steal loop, where slots above value_alloc carry live results
            # between kernel entries) copies every slot. Single-shot run()
            # copies host slots only ([0, value_alloc), widened over any
            # nonzero presets): slots above are device-owned temporaries
            # nobody reads back, and staging all num_values slots cost ~3
            # scalar copies per task on fib-sized graphs once row-owned
            # blocks grew the buffer.
            jax.lax.fori_loop(
                0,
                self.num_values if stage_all_values else counts_in[C_VALLOC],
                copy_vals,
                0,
            )

        def push_ready(t) -> None:
            tail = counts[C_TAIL]
            ready[tail % capacity] = t
            counts[C_TAIL] = tail + 1

        def complete(idx) -> None:
            """Decrement successors' dep counters; push newly-ready tasks
            (device analogue of hclib_promise_put waking the waiter list,
            src/hclib-promise.c:203-245)."""
            if complete_hook is not None:
                complete_hook(idx)

            def dec(s) -> None:
                @pl.when(s != NO_TASK)
                def _():
                    d = tasks[s, F_DEP] - 1
                    tasks[s, F_DEP] = d

                    @pl.when(d == 0)
                    def _():
                        push_ready(s)

            dec(tasks[idx, F_SUCC0])
            dec(tasks[idx, F_SUCC1])
            n = tasks[idx, F_CSR_N]
            off = tasks[idx, F_CSR_OFF]

            def body(i, _):
                dec(succ[off + i])
                return 0

            jax.lax.fori_loop(0, n, body, 0)
            counts[C_PENDING] = counts[C_PENDING] - 1
            counts[C_EXECUTED] = counts[C_EXECUTED] + 1
            # Reclaim the completed row: nothing references it anymore
            # (predecessors completed earlier; successor lists only point
            # forward), so it can back future spawns - a bounded table runs
            # unbounded dynamic graphs whose live set fits (the reference
            # frees tasks after execution, src/hclib-runtime.c:448-478).
            # The F_DEP=-1 tombstone lets stage() rediscover freed rows on
            # re-entry (the free stack itself is scratch): spawn overwrites
            # it on reuse, and completed rows are never re-examined
            # otherwise.
            tasks[idx, F_DEP] = -1
            nf = free[0] + 1
            free[0] = nf
            free[nf] = idx

        def step(idx) -> None:
            ctx = KernelContext(
                idx, tasks, succ, ready, counts, ivalues, data, scratch,
                capacity, free, num_values, vfree,
                self.uses_row_values, self.tracks_home,
            )
            if ctx_hook is not None:
                ctx_hook(ctx)
            branches = [functools.partial(fn, ctx) for fn in self.kernel_fns]
            jax.lax.switch(tasks[idx, F_FN], branches)
            complete(idx)

        def _lane_push(li, t) -> None:
            tail = lstate[li, LS_TAIL]
            lanes[li, tail % capacity] = t
            lstate[li, LS_TAIL] = tail + 1

        def _make_bctx(li, spec, head, take, pre, buf, nxt):
            kctx = KernelContext(
                lanes[li, head % capacity], tasks, succ, ready, counts,
                ivalues, data, scratch, capacity, free, num_values, vfree,
                self.uses_row_values, self.tracks_home,
            )
            if ctx_hook is not None:
                ctx_hook(kctx)
            return BatchContext(
                kctx, lanes, li, head, take, spec.width, pre, buf, nxt,
                capacity, ctx_hook=ctx_hook,
            )

        def sched(fuel) -> None:
            """Pop/dispatch/complete until the ready ring (and the per-kind
            lanes, when the batched tier is on) drain, `fuel` tasks have run
            since this call, or everything empties with work still pending
            (a dependency cycle, a lost wakeup, or - sharded - tasks parked
            on another device's queue; the caller rebalances or inspects).

            With batch-routed kinds, each round dispatches EITHER one batch
            (up to ``width`` same-kind descriptors through one tiled body)
            or one scalar descriptor; a batch round may overshoot ``fuel``
            by width-1 tasks."""

            def batch_round(li, fid, spec, e0, rt) -> None:
                """Fire one batch off lane-state row ``li`` (a (kind,
                bucket) ring under the priority tier; the kind's only
                ring otherwise)."""
                B = spec.width
                head = lstate[li, LS_HEAD]
                tail = lstate[li, LS_TAIL]
                avail = tail - head
                take = jnp.minimum(avail, B)
                # Pop side of the lane. Prefetch specs pop FIFO (oldest
                # first): the cross-round operand pipeline targets "the
                # entries behind the current batch", which is only stable
                # when pops and pushes use opposite ends. Bucket rings
                # (nbk > 1) pop FIFO too - stable oldest-first within a
                # bucket is the order the schedule-independence
                # certification's bucketed schedule models, and the
                # depth-first rationale below doesn't apply (the bucket
                # structure, not the pop end, bounds the live set).
                # Remaining non-prefetch specs pop LIFO (the NEWEST
                # `take` as one contiguous block): that is the scalar
                # tier's owner-side discipline - newest-first keeps
                # recursive families depth-first (live set ~ width *
                # depth, not a breadth frontier; a FIFO fib lane
                # measured ~40% of the WHOLE tree live) and leaves the
                # oldest entries cold in the lane, which is exactly what
                # the multi-device steal exchanges expect to find
                # spilled at the ring's cold end.
                fifo = spec.prefetch or nbk > 1
                base = head if fifo else tail - take
                # Cross-round prefetch handshake: an outstanding prefetch
                # is ours iff it was issued for exactly this head (a spill
                # or lane restage invalidates by clearing LS_PF_BASE).
                pf_ok = lstate[li, LS_PF_BASE] == head + 1
                pre = jnp.where(
                    pf_ok, jnp.minimum(lstate[li, LS_PF_N], take), 0
                )
                buf = lstate[li, LS_PF_BUF]
                if spec.prefetch and nbk == 1:
                    # Announce next-batch prefetch only when the lane keeps
                    # entries AND fuel admits another round - the round
                    # that consumes (or drains) the prefetch is then
                    # guaranteed to run before sched() exits.
                    may = ((avail - take) > 0) & (
                        counts[C_EXECUTED] - e0 + take < fuel
                    )
                    nxt = jnp.where(may, jnp.minimum(avail - take, B), 0)
                else:
                    # Priority-bucketed builds never announce: the NEXT
                    # firing ring is chosen at fire time (lowest
                    # non-empty bucket then), so "the entries behind
                    # this batch" are not the next batch, and the VMEM
                    # operand halves are shared across a kind's bucket
                    # rings - a cross-round prefetch from ring A would
                    # be overwritten (and its semaphores consumed) by
                    # ring B's on-demand loads. Ordered retirement
                    # trades the prefetch away; the asymptotic EXPAND
                    # reduction is the workload's whole point.
                    nxt = jnp.int32(0)
                # Flight-recorder: one record per batch round, lane id and
                # occupancy packed ((fid << 16) | take), prefetched count
                # in b - the triple tests/test_tracebuf.py reconciles
                # against tstats (rounds / tasks / prefetch hits) exactly.
                tr.emit(
                    TR_FIRE_BATCH, rt, (jnp.int32(fid) << 16) | take, pre
                )
                if spec.prefetch:
                    @pl.when(nxt > 0)
                    def _():
                        tr.emit(TR_PREFETCH_ISSUE, rt, fid, nxt)
                bctx = _make_bctx(li, spec, base, take, pre, buf, nxt)
                spec.body(bctx)
                for s in range(B):
                    @pl.when(jnp.int32(s) < take)
                    def _(s=s):
                        if fire_hook is not None:
                            fire_hook(lanes[li, (base + s) % capacity])
                        complete(lanes[li, (base + s) % capacity])
                if fifo:
                    lstate[li, LS_HEAD] = head + take
                    if spec.prefetch:
                        lstate[li, LS_PF_BASE] = jnp.where(
                            nxt > 0, head + take + 1, 0
                        )
                        lstate[li, LS_PF_N] = nxt
                        # The half a prefetch targets is always 1 - buf;
                        # the next round consumes (or on-demand-fills)
                        # that half, so the parity alternates every
                        # round.
                        lstate[li, LS_PF_BUF] = 1 - buf
                else:
                    # LIFO pop: the block came off the tail; head (and the
                    # dormant prefetch words) stay put.
                    lstate[li, LS_TAIL] = base
                tstats[TS_BATCH_ROUNDS] = tstats[TS_BATCH_ROUNDS] + 1
                tstats[TS_BATCH_TASKS] = tstats[TS_BATCH_TASKS] + take
                tstats[TS_OFFERED] = tstats[TS_OFFERED] + B
                tstats[TS_PREFETCH] = tstats[TS_PREFETCH] + pre
                tstats[TS_FULL_ROUNDS] = tstats[TS_FULL_ROUNDS] + (
                    take == B
                ).astype(jnp.int32)

            def cond(carry):
                # `fuel` budgets *this call*: compare against tasks executed
                # since entry, not the all-time counter (which persists
                # across steal rounds re-entering the scheduler).
                pending, executed, e0, stuck = carry
                return (
                    (pending > 0)
                    & (executed - e0 < fuel)
                    & jnp.logical_not(stuck)
                )

            def body(carry):
                _, _, e0, _ = carry
                head = counts[C_HEAD]
                tail = counts[C_TAIL]
                ring_work = head < tail
                # Entry-relative round index: the trace timebase of every
                # record this iteration emits (no device wall clock; the
                # host epoch brackets the launch and timeline.py
                # interpolates).
                rt = tr.tick()
                if round_hook is not None:
                    round_hook()
                # Quiesce poll (checkpoint builds only): a True stops this
                # round's pop - the round boundary the export contract
                # promises - and exits the loop below.
                if quiesce_hook is not None:
                    qz = quiesce_hook(counts[C_EXECUTED] - e0)
                else:
                    qz = jnp.bool_(False)
                if not use_batch:
                    @pl.when(ring_work & jnp.logical_not(qz))
                    def _():
                        # LIFO on the owner side (newest first, depth-first,
                        # small live sets); the head side is the
                        # steal/export side (device/sharded.py,
                        # device/ici_steal.py) - the Chase-Lev split of the
                        # reference deque (src/hclib-deque.c).
                        idx = ready[(tail - 1) % capacity]
                        counts[C_TAIL] = tail - 1
                        tr.emit(TR_FIRE_SCALAR, rt, tasks[idx, F_FN], idx)
                        if fire_hook is not None:
                            fire_hook(idx)
                        step(idx)

                    return (
                        counts[C_PENDING],
                        counts[C_EXECUTED],
                        e0,
                        jnp.logical_not(ring_work) | qz,
                    )
                avails = [
                    lstate[li, LS_TAIL] - lstate[li, LS_HEAD]
                    for li in range(nrows)
                ]
                lane_work = functools.reduce(
                    jnp.logical_or, [a > 0 for a in avails]
                )
                # Lane firing policy: lanes fire only once the ring drains.
                # Ring pops cost ~10 SMEM ops each and keep routing more
                # same-kind descriptors into the lanes, so waiting them out
                # maximizes batch occupancy AND leaves entries queued behind
                # each batch - which is what engages the cross-round
                # prefetch. Ready kinds that are all batch-routed reach
                # their lane within a handful of rounds, so the added
                # latency is noise against one kernel body. One dispatch
                # per round; among eligible lanes the lowest F_FN wins.
                # KNOWN TRADE (the ROADMAP lane-policy watch item, FIXED
                # here by ISSUE 10): a dynamic spawner that keeps the ring
                # hot - a chained producer, or a graph frontier whose
                # every batch deposits a fan-out of same-kind children on
                # the ring - starves the lanes: under pure ring-drain-
                # first a lane fires only at full drains, so entries sit
                # for the whole routing run (latency unbounded; partial
                # fires pile up once drains become momentary). The
                # DETECTOR is the ``lane_partial_age`` gauge (trace a run
                # and read info['tiers']; tracebuf.lane_partial_age off
                # the TR_FIRE_BATCH records, exported by
                # MetricsRegistry.add_run_info). The FIX is the age
                # trigger below: ``Megakernel(lane_max_age=N)`` /
                # HCLIB_TPU_LANE_MAX_AGE arms a per-lane starved-round
                # clock (LS_AGE: rounds the lane held entries without
                # firing); at age >= N the lane JUMPS ring-drain-first
                # and fires whatever it holds - a full batch when >= width
                # entries accumulated during routing (the frontier case:
                # occupancy AND latency improve), a partial one otherwise
                # (bounded latency is the point). Each jump emits a
                # TR_FIRE_AGE reason record beside the round's
                # TR_FIRE_BATCH and counts in tstats[TS_AGE_FIRES];
                # tstats[TS_MAX_AGE] carries the worst age any lane
                # reached. N=0/off compiles none of this - the pre-knob
                # ring-drain-first policy, byte-for-byte. Knob trail for
                # a starving workload: (1) set lane_max_age (>= the lane
                # width keeps age-fires full under a steady spawner);
                # (2) widen the spawner's fan-out so each drain deposits
                # >= width same-kind entries; (3) shrink the BatchSpec
                # width toward the workload's actual same-kind
                # concurrency.
                # (``fired`` starts at the quiesce flag: an observed
                # quiesce suppresses both the batch fire and the scalar
                # pop, so the exit below sees an untouched round.)
                max_age = self.lane_max_age
                fired = qz
                lane_fires = [jnp.bool_(False)] * nrows
                # Two eligibility passes: STARVED rows (age >= N) first,
                # then the ordinary drained-ring scan - so a starved row
                # beats the drain priority and the age bound holds with
                # several routed kinds/buckets (simultaneously starved
                # rows fire on consecutive rounds, so the worst observed
                # age is N + nrows - 1, not unbounded). Under the
                # priority tier the SAME guard is what keeps high
                # buckets live: drain pops retire the LOWEST non-empty
                # bucket first (globally - a kind is drain-eligible only
                # when its lowest non-empty bucket ties the mesh-wide
                # minimum), so a high bucket behind a continuously
                # refilled low bucket would starve without it; its
                # age-guard fire is the one legal bucket-order
                # inversion, counted in tstats[TS_INVERSIONS].
                #
                # The bucket CHOICE within a kind is a traced row index
                # (a where-fold over the kind's nbk cursor pairs), NOT a
                # per-bucket unroll: batch bodies are the largest code
                # objects in the program (a frontier body carries
                # width x EBLOCK relax loops), so each kind must keep
                # exactly ONE instantiation per phase - the pre-bucket
                # program size - with only the handful of scalar
                # selection ops scaling in nbk.
                if nbk > 1:
                    # Per kind: lowest non-empty bucket (nbk = empty),
                    # then the global minimum across kinds.
                    kind_lowb = []
                    kind_work = []
                    for li in range(nbatch):
                        has = [
                            avails[li * nbk + b] > 0 for b in range(nbk)
                        ]
                        lb = jnp.int32(nbk)
                        for b in reversed(range(nbk)):
                            lb = jnp.where(has[b], jnp.int32(b), lb)
                        kind_lowb.append(lb)
                        kind_work.append(
                            functools.reduce(jnp.logical_or, has)
                        )
                    best_b = functools.reduce(jnp.minimum, kind_lowb)
                phases = (["starved"] if max_age else []) + ["drain"]
                for phase in phases:
                    for li, (fid, spec) in enumerate(self.batch_specs):
                        base = li * nbk
                        if nbk == 1:
                            row = base
                            bk_sel = jnp.int32(0)
                            if phase == "starved":
                                eligible = (avails[base] > 0) & (
                                    lstate[base, LS_AGE]
                                    >= jnp.int32(max_age)
                                )
                            else:
                                eligible = (
                                    avails[base] > 0
                                ) & jnp.logical_not(ring_work)
                        elif phase == "starved":
                            # Lowest-bucket starved ring of this kind
                            # (deterministic; any starved ring fires
                            # within nrows rounds either way).
                            sflags = [
                                (avails[base + b] > 0)
                                & (lstate[base + b, LS_AGE]
                                   >= jnp.int32(max_age))
                                for b in range(nbk)
                            ]
                            bk_sel = jnp.int32(nbk - 1)
                            for b in reversed(range(nbk)):
                                bk_sel = jnp.where(
                                    sflags[b], jnp.int32(b), bk_sel
                                )
                            eligible = functools.reduce(
                                jnp.logical_or, sflags
                            )
                            row = base + bk_sel
                        else:
                            # Drain: this kind offers its lowest
                            # non-empty bucket, and fires only when
                            # that bucket ties the global minimum
                            # (lowest-nonempty-bucket-first across
                            # kinds; ties break to the lower F_FN via
                            # the fired latch below).
                            bk_sel = jnp.minimum(
                                kind_lowb[li], jnp.int32(nbk - 1)
                            )
                            eligible = (
                                kind_work[li]
                                & (kind_lowb[li] == best_b)
                                & jnp.logical_not(ring_work)
                            )
                            row = base + bk_sel
                        fire_now = eligible & jnp.logical_not(fired)
                        avail_sel = (
                            lstate[row, LS_TAIL] - lstate[row, LS_HEAD]
                        )
                        take = jnp.minimum(avail_sel, spec.width)
                        if phase == "starved":
                            # Reason record + counter for a fire that
                            # jumped the ring (emitted before batch_round
                            # so LS_AGE still holds the pre-fire age;
                            # take mirrors batch_round's min(avail,
                            # width) exactly). A starved fire with the
                            # ring already empty is an ordinary drain
                            # fire - no jump, no record.
                            @pl.when(fire_now & ring_work)
                            def _(row=row, fid=fid, take=take):
                                tr.emit(
                                    TR_FIRE_AGE, rt,
                                    (jnp.int32(fid) << 16) | take,
                                    lstate[row, LS_AGE],
                                )
                                tstats[TS_AGE_FIRES] = (
                                    tstats[TS_AGE_FIRES] + 1
                                )
                            if nbk > 1:
                                # Bucket-order inversion: this age-guard
                                # fire retires bucket ``bk_sel`` while a
                                # LOWER bucket still holds entries - the
                                # only path a higher bucket beats a
                                # lower one (drain pops are bucket-
                                # ordered by construction).
                                lower = functools.reduce(
                                    jnp.logical_or,
                                    [
                                        (jnp.int32(r2 % nbk) < bk_sel)
                                        & (avails[r2] > 0)
                                        for r2 in range(nrows)
                                    ],
                                )

                                @pl.when(fire_now & lower)
                                def _():
                                    tstats[TS_INVERSIONS] = (
                                        tstats[TS_INVERSIONS] + 1
                                    )
                        if nbk > 1:
                            # Bucketed fire record: which bucket ring
                            # retired, at what occupancy - the
                            # per-bucket occupancy gauge decodes from
                            # these (tracebuf.bucket_occupancy).
                            @pl.when(fire_now)
                            def _(bk_sel=bk_sel, fid=fid, take=take):
                                tr.emit(
                                    TR_FIRE_BUCKET, rt,
                                    (bk_sel << 16) | take,
                                    fid,
                                )

                            @pl.when(fire_now & (bk_sel > 0))
                            def _():
                                tstats[TS_BUCKET_FIRES] = (
                                    tstats[TS_BUCKET_FIRES] + 1
                                )

                        @pl.when(fire_now)
                        def _(row=row, fid=fid, spec=spec, e0=e0):
                            batch_round(row, fid, spec, e0, rt)

                        if nbk == 1:
                            lane_fires[base] = lane_fires[base] | fire_now
                        else:
                            for r in range(base, base + nbk):
                                lane_fires[r] = lane_fires[r] | (
                                    fire_now & (row == jnp.int32(r))
                                )
                        fired = fired | eligible

                @pl.when(jnp.logical_not(fired) & ring_work)
                def _():
                    idx = ready[(tail - 1) % capacity]
                    counts[C_TAIL] = tail - 1
                    # Pop-time partitioning: batch-routed kinds divert into
                    # their lane (one compare per routed kind) no matter
                    # who pushed them - stage, spawn, install_descriptor,
                    # and completion all funnel through the ring, so the
                    # ring stays the single persistent structure and the
                    # lanes never survive a kernel exit.
                    fn = tasks[idx, F_FN]
                    routed = jnp.bool_(False)
                    for li, (fid, spec) in enumerate(self.batch_specs):
                        hit = fn == jnp.int32(fid)

                        @pl.when(hit)
                        def _(li=li, idx=idx, spec=spec):
                            if nbk > 1 and spec.priority is not None:
                                # Priority tier: the bucket id is a pure
                                # function of the descriptor's own arg
                                # words (clipped into the static set), so
                                # spilled/stolen/resharded residue
                                # re-buckets right here on its next
                                # routing pop - the bucket rides the
                                # descriptor, not the ring row.
                                bk = jnp.clip(
                                    spec.priority(
                                        lambda i: tasks[idx, F_A0 + i]
                                    ),
                                    0, nbk - 1,
                                ).astype(jnp.int32)
                                _lane_push(jnp.int32(li * nbk) + bk, idx)
                            else:
                                _lane_push(li * nbk, idx)

                        routed = routed | hit

                    @pl.when(jnp.logical_not(routed))
                    def _():
                        tr.emit(TR_FIRE_SCALAR, rt, fn, idx)
                        step(idx)
                        tstats[TS_SCALAR_ROUNDS] = (
                            tstats[TS_SCALAR_ROUNDS] + 1
                        )

                    @pl.when(routed)
                    def _():
                        tstats[TS_ROUTED] = tstats[TS_ROUTED] + 1

                if max_age:
                    # Advance the starved-round clocks AFTER dispatch: a
                    # row that holds entries now (including one a scalar
                    # pop just routed into) and did not fire this round
                    # ages by one; a fire or an empty row resets. The
                    # worst age any row reaches rides out in tstats -
                    # the bounded-age gauge the acceptance pins (under
                    # the priority tier the clock is per bucket ring, so
                    # the guard bounds HIGH-bucket latency too).
                    for li in range(nrows):
                        has_now = (
                            lstate[li, LS_TAIL] - lstate[li, LS_HEAD]
                        ) > 0
                        age = jnp.where(
                            lane_fires[li] | jnp.logical_not(has_now),
                            0,
                            lstate[li, LS_AGE] + 1,
                        )
                        lstate[li, LS_AGE] = age
                        tstats[TS_MAX_AGE] = jnp.maximum(
                            tstats[TS_MAX_AGE], age
                        )

                return (
                    counts[C_PENDING],
                    counts[C_EXECUTED],
                    e0,
                    jnp.logical_not(ring_work | lane_work) | qz,
                )

            e0 = counts[C_EXECUTED]
            tr.emit(
                TR_ROUND_BEGIN, tr.tick(),
                counts[C_TAIL] - counts[C_HEAD], counts[C_PENDING],
            )
            jax.lax.while_loop(
                cond,
                body,
                (counts[C_PENDING], counts[C_EXECUTED], e0, jnp.bool_(False)),
            )
            if use_batch:
                # Exit with unrun lane entries (fuel exhaustion, quiesce):
                # retire any in-flight prefetch, then spill the entries
                # back to the ready ring - the ring is the only structure
                # whose contents survive this call (outputs/readback,
                # restage, steal/export scans, checkpoint export, host
                # stall diagnosis). Entries spill to the HEAD side (the
                # cold, steal-facing end of the Chase-Lev split): a lane
                # holds the OLDEST ready descriptors of its kind (routing
                # pops drained them off the ring before execution), so
                # under the multi-device runners they are exactly the
                # cold work a thief's head-side scan window must see -
                # spilling to the tail would hide every lane-resident
                # candidate behind the hot end and starve the steal
                # exchange (observed: a batch-routed forest never
                # spread). C_HEAD may go negative; every reader indexes
                # the ring mod capacity, and stage() widens its copy to
                # the whole ring when the window wraps below zero.
                rt_x = tr.now()
                for li, fid, spec in lane_rows:
                    h = lstate[li, LS_HEAD]
                    t = lstate[li, LS_TAIL]
                    if spec.prefetch:
                        pf_ok = lstate[li, LS_PF_BASE] == h + 1
                        pre = jnp.where(pf_ok, lstate[li, LS_PF_N], 0)

                        @pl.when(pre > 0)
                        def _(li=li, spec=spec, h=h, pre=pre, fid=fid):
                            tr.emit(TR_PREFETCH_DRAIN, rt_x, fid, pre)
                            spec.drain(_make_bctx(
                                li, spec, h, pre, pre,
                                lstate[li, LS_PF_BUF], jnp.int32(0),
                            ))

                    head0 = counts[C_HEAD]

                    def spill(s, _, li=li, h=h, head0=head0):
                        ready[(head0 - 1 - s) % capacity] = lanes[
                            li, (h + s) % capacity
                        ]
                        return 0

                    jax.lax.fori_loop(0, t - h, spill, 0)
                    counts[C_HEAD] = head0 - (t - h)

                    @pl.when(t > h)
                    def _(fid=fid, h=h, t=t):
                        tr.emit(TR_SPILL, rt_x, fid, t - h)

                    lstate[li, LS_HEAD] = t
                    lstate[li, LS_PF_BASE] = 0
                    tstats[TS_SPILLED] = tstats[TS_SPILLED] + (t - h)
            tr.emit(
                TR_ROUND_END, tr.tick(),
                counts[C_EXECUTED] - e0, counts[C_PENDING],
            )

        def install_descriptor(read_word):
            """Adopt one externally-produced descriptor row (a stolen row
            arriving over ICI, an injected stream row): allocate a row
            through the same path spawns use (freed rows first, then the
            bump cursor), copy the ABI words via ``read_word(w)``, count it
            pending, and push it ready only when its dep counter is zero -
            a dependent row waits for its predecessors like any other.
            Returns the installed row index (meaningful only when no
            overflow was flagged) so callers can apply post-install fixups
            (device/resident.py rewrites migrated rows' out slots)."""
            nf = free[0]
            use_free = nf > 0
            row_free = free[jnp.maximum(nf, 1)]
            a = counts[C_ALLOC]
            ok = use_free | (a < capacity)
            row = jnp.where(use_free, row_free, jnp.minimum(a, capacity - 1))

            @pl.when(use_free)
            def _():
                free[0] = nf - 1

            @pl.when(jnp.logical_not(use_free) & (a < capacity))
            def _():
                counts[C_ALLOC] = a + 1

            @pl.when(ok)
            def _():
                for w in range(DESC_WORDS):
                    tasks[row, w] = read_word(w)
                counts[C_PENDING] = counts[C_PENDING] + 1

                @pl.when(tasks[row, F_DEP] == 0)
                def _():
                    push_ready(row)

            @pl.when(jnp.logical_not(ok))
            def _():
                counts[C_OVERFLOW] = counts[C_OVERFLOW] | OVF_ROWS

            return row

        def headroom():
            """Task-table slots available to adopt EXTERNAL rows right
            now: tombstone-recycled rows on the free stack plus the unbump
            tail of the table. The inject-ring poll hook for traffic
            shaping (device/inject.py tenant lanes): a poll that consumes
            at most ``headroom()`` rows can never trip OVF_ROWS - rows it
            leaves on the ring are *backpressure* the host observes
            through the consumed-cursor echo, instead of an overflow that
            aborts the stream. (Spawning kernels still flag OVF_ROWS as
            before; the hook only shapes externally-injected load.)"""
            return free[0] + (capacity - counts[C_ALLOC])

        return types.SimpleNamespace(
            stage=stage, sched=sched, push_ready=push_ready,
            complete=complete, install_descriptor=install_descriptor,
            headroom=headroom,
        )

    def _kernel(
        self, fuel: int, reps: int, stage_all_values: bool, trace, ckpt,
        qstride, *refs
    ) -> None:
        # ``trace``/``ckpt``/``qstride`` are the TraceRing / checkpoint
        # flag / quiesce poll stride captured when _build_raw fixed the
        # output tree - NOT self.trace: pallas kernels trace lazily
        # (first call), so reading mutable instance state here could
        # disagree with the already-built out_shape and shift every ref
        # slice.
        ndata = len(self.data_specs)
        nbatch = len(self.batch_specs)
        ntrace = 1 if trace is not None else 0
        n_in = 5 + ndata + (1 if ckpt else 0)  # qctl rides last
        n_out = 4 + ndata + (1 if nbatch else 0) + (1 if ckpt else 0) + ntrace
        in_refs = refs[:n_in]
        out_refs = refs[n_in : n_in + n_out]
        tail = list(refs[n_in + n_out :])
        scratch_refs = tail[: len(self.scratch_specs)]
        tail = tail[len(self.scratch_specs) :]
        free = tail.pop(0)  # internal free-stack: [0]=count, [1..]=rows
        vfree = tail.pop(0)  # value-block free-stack, same layout
        lanes = tail.pop(0) if nbatch else None  # per-kind ready lanes
        lstate = tail.pop(0) if nbatch else None  # lane cursors + prefetch
        qbuf = tail.pop(0) if ckpt else None  # quiesce-word staging
        qsem = tail.pop(0) if ckpt else None  # its DMA semaphore
        assert not tail, f"{len(tail)} unconsumed scratch refs"
        tasks_in, succ, ready_in, counts_in, ivalues_in = in_refs[:5]
        qctl = in_refs[5 + ndata] if ckpt else None
        tasks, ready, counts, ivalues = out_refs[:4]
        data = dict(zip(self.data_specs.keys(), out_refs[4 : 4 + ndata]))
        tstats = out_refs[4 + ndata] if nbatch else None
        qstat = (
            out_refs[4 + ndata + (1 if nbatch else 0)] if ckpt else None
        )
        tracer = (
            Tracer(out_refs[n_out - 1], trace.capacity)
            if ntrace
            else None
        )
        scratch = dict(zip(self.scratch_specs.keys(), scratch_refs))
        tr = tracer if tracer is not None else NullTracer()

        quiesce_hook = None
        if ckpt:
            for w in range(8):
                qstat[w] = 0

            def quiesce_hook(executed_since):
                # Acquire-read the quiesce word from HBM - the same
                # re-read-every-round discipline as the abort words, so a
                # host with in-place buffer write access (pinned-host
                # production) lands a quiesce mid-entry; this driver
                # uploads qctl at entry, which bounds latency at one
                # round past the QC_AFTER threshold. ``quiesce_stride``
                # > 1 skips the DMA on all but every Nth round (round 0
                # always polls, so qbuf is never read uninitialized); a
                # stale qbuf between polls is safe because the host only
                # ever raises the flag monotonically within an entry -
                # observation latency grows by at most stride-1 rounds.
                if qstride > 1:
                    cnt = qstat[QS_POLLS]
                    qstat[QS_POLLS] = cnt + 1

                    @pl.when(cnt % qstride == 0)
                    def _():
                        cp = pltpu.make_async_copy(qctl, qbuf, qsem.at[0])
                        cp.start()
                        cp.wait()
                else:
                    qstat[QS_POLLS] = qstat[QS_POLLS] + 1
                    cp = pltpu.make_async_copy(qctl, qbuf, qsem.at[0])
                    cp.start()
                    cp.wait()
                q = (qbuf[QC_FLAG] != 0) & (executed_since >= qbuf[QC_AFTER])

                @pl.when(q & (qstat[QS_QUIESCED] == 0))
                def _():
                    qstat[QS_QUIESCED] = 1
                    qstat[QS_AT] = executed_since
                    tr.emit(TR_QUIESCE, tr.now(), executed_since)

                return q

        core = self._make_core(
            succ, tasks, ready, counts, ivalues, data, scratch, free, vfree,
            tasks_in, ready_in, counts_in, ivalues_in, stage_all_values,
            lanes=lanes, lstate=lstate, tstats=tstats, tracer=tracer,
            quiesce_hook=quiesce_hook,
        )

        def one_rep(r, total_executed) -> jnp.int32:
            core.stage()
            core.sched(fuel)
            return total_executed + counts[C_EXECUTED]

        # reps > 1 re-runs the staged graph as a steady-state throughput
        # harness (the resident scheduler never exits between graphs); the
        # final state is that of the last rep, with C_EXECUTED accumulated
        # across reps.
        total = jax.lax.fori_loop(0, reps, one_rep, jnp.int32(0))
        counts[C_EXECUTED] = total
        if ckpt:
            # State-export record: one TR_CKPT at exit when this entry
            # quiesced (pending rows exported, ready backlog) - the device
            # half of the checkpoint bracket tools/timeline.py renders.
            @pl.when(qstat[QS_QUIESCED] != 0)
            def _():
                tr.emit(
                    TR_CKPT, tr.now(), counts[C_PENDING],
                    counts[C_TAIL] - counts[C_HEAD],
                )

    # -- host entry --

    @staticmethod
    def widen_value_alloc(counts_row, ivalues_row) -> None:
        """Widen counts_row[C_VALLOC] over the highest nonzero preset in
        ivalues_row (in place): presets are host slots, so staging must
        cover them and the device bump/row-block regions must sit above.
        Deliberate ZERO presets above value_alloc can't be detected here -
        declare them with TaskGraphBuilder.reserve_values instead."""
        nz = np.flatnonzero(np.asarray(ivalues_row))
        if len(nz):
            counts_row[C_VALLOC] = max(
                counts_row[C_VALLOC], int(nz[-1]) + 1
            )

    def check_row_values(self, value_alloc: int) -> None:
        """For uses_row_values kernels: every row's block ([value_alloc,
        value_alloc + VBLOCK*capacity)) must fit in the value buffer, else
        row_values writes would clamp and silently corrupt the top slots."""
        if not self.uses_row_values:
            return
        need = value_alloc + VBLOCK * self.capacity
        if need > self.num_values:
            raise ValueError(
                f"row-owned value blocks need num_values >= value_alloc"
                f"({value_alloc}) + VBLOCK*capacity({VBLOCK * self.capacity})"
                f" = {need}, got {self.num_values}; shrink out slots/presets "
                "or grow num_values"
            )

    def _build_raw(
        self, fuel: int, reps: int = 1, stage_all_values: bool = False
    ):
        """The bare pallas_call (for embedding under shard_map; re-entrant
        callers must pass stage_all_values=True so value slots above
        value_alloc survive between entries)."""
        ndata = len(self.data_specs)
        nbatch = len(self.batch_specs)
        ckpt = self.checkpoint
        smem = functools.partial(pl.BlockSpec, memory_space=pltpu.SMEM)
        anyspace = functools.partial(pl.BlockSpec, memory_space=pl.ANY)
        in_specs = (
            [smem(), smem(), smem(), smem(), smem()]
            + [anyspace() for _ in range(ndata)]
            # The quiesce ctl rides last in ANY (HBM): the scheduler
            # re-reads it by DMA every round (checkpoint builds only).
            + ([anyspace()] if ckpt else [])
        )
        out_specs = tuple(
            [smem(), smem(), smem(), smem()]
            + [anyspace() for _ in range(ndata)]
            # Batched-tier counters ride out as one extra SMEM word row
            # APPENDED after the data outputs, so every existing consumer's
            # positional indexing is untouched.
            + ([smem()] if nbatch else [])
            # Quiesce status (QS_* words), same appended discipline.
            + ([smem()] if ckpt else [])
            # The flight-recorder ring rides last, same appended-output
            # discipline (absent entirely when tracing is off).
            + ([smem()] if self.trace is not None else [])
        )
        data_shapes = [
            jax.ShapeDtypeStruct(s.shape, s.dtype) for s in self.data_specs.values()
        ]
        out_shape = tuple(
            [
                jax.ShapeDtypeStruct((self.capacity, DESC_WORDS), jnp.int32),
                jax.ShapeDtypeStruct((self.capacity,), jnp.int32),
                jax.ShapeDtypeStruct((8,), jnp.int32),
                jax.ShapeDtypeStruct((self.num_values,), jnp.int32),
            ]
            + data_shapes
            + ([jax.ShapeDtypeStruct((TS_WORDS,), jnp.int32)] if nbatch else [])
            + ([jax.ShapeDtypeStruct((8,), jnp.int32)] if ckpt else [])
            + ([self.trace.out_shape()] if self.trace is not None else [])
        )
        # inputs: tasks(0) succ(1) ready(2) counts(3) ivalues(4) data(5..)
        # outputs: tasks(0) ready(1) counts(2) ivalues(3) data(4..) [tstats]
        aliases = {0: 0, 2: 1, 3: 2, 4: 3}
        for i in range(ndata):
            aliases[5 + i] = 4 + i
        return pl.pallas_call(
            functools.partial(
                self._kernel, fuel, reps, stage_all_values, self.trace,
                ckpt, self.quiesce_stride,
            ),
            out_shape=out_shape,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=list(self.scratch_specs.values())
            + [
                pltpu.SMEM((self.capacity + 1,), jnp.int32),
                pltpu.SMEM((self.num_values // VBLOCK + 1,), jnp.int32),
            ]
            + (
                [
                    pltpu.SMEM(
                        (self.lane_scratch_rows, self.capacity), jnp.int32
                    ),
                    pltpu.SMEM(
                        (self.lane_scratch_rows, LS_WORDS), jnp.int32
                    ),
                ]
                if nbatch
                else []
            )
            + (
                [
                    pltpu.SMEM((8,), jnp.int32),  # qbuf (quiesce staging)
                    pltpu.SemaphoreType.DMA((1,)),  # qsem
                ]
                if ckpt
                else []
            ),
            input_output_aliases=aliases,
            # Plain bool on purpose: True selects the fast XLA-backed
            # pallas interpreter. interpret_mode()'s InterpretParams
            # would select the far slower thread-per-device Mosaic
            # interpreter, which only kernels simulating remote DMA +
            # semaphores need (device/resident.py and friends).
            interpret=self.interpret,
            compiler_params=(
                pltpu.CompilerParams(
                    vmem_limit_bytes=self.vmem_limit_bytes
                )
                if self.vmem_limit_bytes and not self.interpret
                else None
            ),
        )

    def _build(self, fuel: int, reps: int = 1):
        from ..runtime.progcache import shared_build

        fn, self._pc_stats = shared_build(
            self, ("megakernel-build", fuel, reps),
            lambda: jax.jit(self._build_raw(fuel, reps)),
        )
        return fn

    def decode_tier_stats(self, tstats) -> Dict[str, Any]:
        """Decode the raw TS_WORDS counter row into the per-tier stats dict
        (``info['tiers']``). Occupancy is batch tasks over the slots the
        fired rounds offered (TS_OFFERED accumulates each firing lane's own
        width, so the ratio stays exact with mixed-width routes) - the
        number perf tracking watches: low occupancy means the DAG isn't
        exposing same-kind parallelism (or the firing policy is
        dispatching partial batches too eagerly)."""
        t = np.asarray(tstats)
        rounds = int(t[TS_BATCH_ROUNDS])
        tasks = int(t[TS_BATCH_TASKS])
        offered = int(t[TS_OFFERED])
        width = max(spec.width for _, spec in self.batch_specs)
        return {
            "batch_rounds": rounds,
            "batch_tasks": tasks,
            "batch_occupancy": tasks / offered if offered else 0.0,
            "batch_width": width,
            "full_rounds": int(t[TS_FULL_ROUNDS]),
            "scalar_tasks": int(t[TS_SCALAR_ROUNDS]),
            "routed": int(t[TS_ROUTED]),
            "prefetch_hits": int(t[TS_PREFETCH]),
            "spilled": int(t[TS_SPILLED]),
            # Age-trigger firing policy (lane_max_age; zeros when off):
            # rounds that jumped ring-drain-first, and the worst
            # starved-round age any lane reached - the device-side gauge
            # the bounded-age acceptance pins (lane_partial_age, the
            # trace-derived partial-fire streak, rides separately on
            # traced runs).
            "age_fires": int(t[TS_AGE_FIRES]),
            "max_starved_age": int(t[TS_MAX_AGE]),
            # Priority-bucket tier (priority_buckets; zeros when off):
            # rounds fired from a nonzero bucket ring, and age-guard
            # fires that jumped a lower non-empty bucket - the only
            # legal bucket-order inversion (per-bucket occupancy rides
            # separately on traced runs, off the TR_FIRE_BUCKET
            # records).
            "bucket_fires": int(t[TS_BUCKET_FIRES]),
            "bucket_inversions": int(t[TS_INVERSIONS]),
        }

    def stats_dict(self) -> Dict[str, Any]:
        """Stats snapshot of the most recent ``run()`` (per-tier dispatch
        counters included when batch-routed); {} before any run. The
        benches and tools/perf_regression.py read this so tier occupancy
        never floats free of a harness."""
        return dict(self._last_info or {})

    @staticmethod
    def quiesce_words(quiesce) -> np.ndarray:
        """Normalize a ``quiesce=`` argument into the 8-word qctl row:
        None/False = off (zeros - a caller plumbing a boolean flag must
        get 'no quiesce', not 'quiesce now'), True = quiesce at the first
        round boundary, an int k = quiesce once >= k tasks have executed
        this entry (the deterministic checkpoint-at-round-k spelling;
        batch rounds may overshoot by width-1 like fuel does)."""
        q = np.zeros(8, np.int32)
        if quiesce is None or quiesce is False:
            return q
        q[QC_FLAG] = 1
        if quiesce is not True:
            q[QC_AFTER] = int(quiesce)
        return q

    def run(
        self,
        builder: TaskGraphBuilder,
        data: Optional[Dict[str, Any]] = None,
        ivalues: Optional[np.ndarray] = None,
        fuel: int = 1 << 22,
        quiesce=None,
    ):
        """Execute the task graph to completion; returns
        (ivalues, data_dict, info_dict).

        Value-slot readback contract: only slots below the staged
        ``value_alloc`` (host presets + declared out slots, widened over any
        nonzero entries of ``ivalues``) round-trip host -> kernel -> host.
        Slots above it are device temporaries (row-owned blocks, bump
        allocations): their returned contents are whatever the last kernel
        entry left there and must not be relied on. A deliberate ZERO preset
        above the out-slot range is invisible to the widening scan - declare
        it with ``TaskGraphBuilder.reserve_values`` so staging covers it.

        ``quiesce`` (checkpoint builds only; see ``quiesce_words``) makes
        the scheduler stop popping at a round boundary and return its live
        state: the run comes back with ``info['quiesced']=True`` and
        ``info['state']`` (the resumable scheduler snapshot - feed it to
        ``resume()`` or ``runtime.checkpoint.snapshot_megakernel``)
        instead of raising StallError on the pending remainder."""
        tasks, succ, ring, counts = builder.finalize(
            capacity=self.capacity, succ_capacity=self.succ_capacity
        )
        if ivalues is None:
            ivalues = np.zeros(self.num_values, dtype=np.int32)
        else:
            counts = counts.copy()
            self.widen_value_alloc(counts, ivalues)
        self.check_row_values(int(counts[C_VALLOC]))
        data = dict(data or {})
        if set(data.keys()) != set(self.data_specs.keys()):
            raise ValueError(
                f"data buffers {sorted(data)} != declared {sorted(self.data_specs)}"
            )
        return self._execute(
            tasks, succ, ring, counts, ivalues, data, fuel, quiesce,
            stage_all_values=False,
        )

    def resume(self, state: Dict[str, Any], fuel: int = 1 << 22,
               quiesce=None):
        """Re-enter mid-graph from a quiesced run's exported state (the
        ``info['state']`` dict of a quiesced ``run()``/``resume()``, or a
        restored CheckpointBundle's) and continue to completion - the
        restart half of the checkpoint protocol. Stages ALL value slots
        (live row-owned blocks / bump allocations survive the re-entry,
        the sharded steal loop's re-entrant discipline) and rebuilds the
        row free stack from completion tombstones. Chains: a resumed run
        may itself be quiesced again."""
        data = dict(state.get("data") or {})
        if set(data.keys()) != set(self.data_specs.keys()):
            raise ValueError(
                f"state data buffers {sorted(data)} != declared "
                f"{sorted(self.data_specs)}"
            )
        return self._execute(
            state["tasks"], state["succ"], state["ready"], state["counts"],
            state["ivalues"], data, fuel, quiesce, stage_all_values=True,
        )

    def _execute(
        self, tasks, succ, ring, counts, ivalues, data, fuel, quiesce,
        stage_all_values: bool,
    ):
        if quiesce is False:  # falsy boolean plumbing = off, everywhere
            quiesce = None
        if quiesce is not None and not self.checkpoint:
            raise ValueError(
                "quiesce= needs Megakernel(checkpoint=True): the quiesce "
                "word is compiled into the round loop only then"
            )
        key = (fuel, bool(stage_all_values))
        first_build = key not in self._jitted
        if first_build:
            # Process-wide program cache (runtime/progcache.py): a
            # content-identical program built by ANY instance this
            # process is reused here - the returned callable is the
            # same jitted object, so its first call skips trace/lower
            # entirely. The per-instance dict stays as the L1 (repeat
            # runs on one instance never pay fingerprinting).
            from ..runtime.progcache import shared_build

            self._jitted[key], self._pc_stats = shared_build(
                self, ("megakernel-exec",) + key,
                lambda: jax.jit(
                    self._build_raw(fuel, stage_all_values=stage_all_values)
                ),
            )
        jitted = self._jitted[key]
        import contextlib

        # Interpret mode runs as plain JAX ops; pin them to the host CPU
        # backend so tests stay local (the axon TPU platform ignores
        # JAX_PLATFORMS, so this must be an explicit device choice).
        cm = (
            jax.default_device(jax.devices("cpu")[0])
            if self.interpret
            else contextlib.nullcontext()
        )
        import time as _time

        args = [
            jnp.asarray(tasks),
            jnp.asarray(succ),
            jnp.asarray(ring),
            jnp.asarray(counts),
            jnp.asarray(ivalues),
            *[jnp.asarray(data[k]) for k in self.data_specs.keys()],
        ]
        if self.checkpoint:
            args.append(jnp.asarray(self.quiesce_words(quiesce)))
        # Epoch bracket for the flight recorder (the clockprobe trick):
        # monotonic_ns before launch and after readback are the host wall
        # clock the trace's round-indexed records interpolate into - the
        # same clock runtime/instrument.py stamps host events with, so
        # device rounds and host spans share one Perfetto timeline.
        t0_ns = _time.monotonic_ns()
        with cm:
            outs = jitted(*args)
        ndata = len(self.data_specs)
        tasks_out, ready_out, counts_out, ivalues_out = outs[:4]
        data_out = dict(zip(self.data_specs.keys(), outs[4 : 4 + ndata]))
        packs = [counts_out, ivalues_out]
        off_out = 4 + ndata
        if self.batch_specs:
            packs.append(outs[off_out])
            off_out += 1
        if self.checkpoint:
            packs.append(outs[off_out])
            off_out += 1
        if self.trace is not None:
            packs.append(outs[off_out])
        packed = np.asarray(self._packer(*packs))
        t1_ns = _time.monotonic_ns()
        if first_build and self._pc_stats is not None:
            if not self._pc_stats["hit"]:
                # jax.jit is lazy: the trace/lower/compile this cache
                # exists to skip is paid inside the first entry, so a
                # MISS folds that first wall (compile + one execution)
                # into build_s; a hit's first entry rides the already-
                # traced callable and keeps build_s = 0.
                self._pc_stats["build_s"] += (t1_ns - t0_ns) / 1e9
        counts_np = packed[:8]
        ivalues_np = packed[8 : 8 + self.num_values]
        info = {
            "executed": int(counts_np[C_EXECUTED]),
            "pending": int(counts_np[C_PENDING]),
            "allocated": int(counts_np[C_ALLOC]),
            "value_alloc": int(counts_np[C_VALLOC]),
            "overflow": bool(counts_np[C_OVERFLOW]),
        }
        if self._pc_stats is not None:
            # How this run's program was obtained (the build that
            # produced the executable, not this entry): cache hit flag
            # plus build_s vs cache_lookup_s - the trade the program
            # cache exists to win. Mirrored into the tier gauges below
            # so MetricsRegistry.add_run_info exports it beside
            # lane_occupancy.
            info["program_cache"] = dict(self._pc_stats)
        off = 8 + self.num_values
        if self.batch_specs:
            info["tiers"] = self.decode_tier_stats(
                packed[off : off + TS_WORDS]
            )
            if self._pc_stats is not None:
                # Host-side build-cost gauges ride the tier dict (the
                # add_run_info export path). Cross-arm tier equality
                # tests compare device counters only - these two keys
                # are wall-clock noise by nature.
                info["tiers"]["build_s"] = self._pc_stats["build_s"]
                info["tiers"]["cache_lookup_s"] = (
                    self._pc_stats["cache_lookup_s"]
                )
            off += TS_WORDS
        quiesced = False
        if self.checkpoint:
            qstat = packed[off : off + 8]
            off += 8
            quiesced = bool(qstat[QS_QUIESCED])
            info["quiesced"] = quiesced
            if quiesced:
                info["quiesce"] = {"executed_at": int(qstat[QS_AT])}
        if self.trace is not None:
            info["trace"] = trace_info(
                [packed[off : off + self.trace.words]], t0_ns, t1_ns,
                self.trace.capacity,
            )
            if self.batch_specs and "tiers" in info:
                # Partial-batch starvation gauge (the lane-policy watch
                # item): longest consecutive-partial-fire streak per
                # lane, in rounds, off the TR_FIRE_BATCH records; the
                # max rides info['tiers'] so MetricsRegistry.add_run_info
                # exports it beside lane_occupancy.
                from .tracebuf import lane_partial_age

                ages = lane_partial_age(
                    info["trace"],
                    {fid: spec.width for fid, spec in self.batch_specs},
                )
                info["tiers"]["lane_partial_ages"] = ages
                info["tiers"]["lane_partial_age"] = max(
                    ages.values(), default=0
                )
                if self.priority_buckets:
                    # Per-bucket occupancy gauge (the priority tier's
                    # structural health read): retired descriptors over
                    # offered slots per bucket ring, off TR_FIRE_BUCKET.
                    from .tracebuf import bucket_occupancy

                    info["tiers"]["bucket_occupancy"] = bucket_occupancy(
                        info["trace"],
                        {fid: spec.width for fid, spec in
                         self.batch_specs},
                        self.priority_buckets,
                    )
        if quiesced:
            # The exported scheduler snapshot: everything resume() (and
            # CheckpointBundle) needs to relaunch mid-graph. succ is
            # input-only (never mutated on device), so the input array IS
            # its live value.
            info["state"] = {
                "tasks": np.asarray(tasks_out),
                "succ": np.asarray(succ),
                "ready": np.asarray(ready_out),
                "counts": counts_np.copy(),
                "ivalues": ivalues_np.copy(),
                "data": {k: np.asarray(v) for k, v in data_out.items()},
            }
        self._last_info = info
        if info["overflow"]:
            raise RuntimeError(
                f"megakernel overflow: "
                f"{decode_overflow(int(counts_np[C_OVERFLOW]))} exhausted "
                f"(capacity={self.capacity}, num_values={self.num_values}); "
                "raise the limits, coarsen tasks, or audit frees"
            )
        if info["pending"] != 0 and not quiesced:
            from ..runtime.resilience import StallError

            raise StallError(
                f"megakernel stalled with {info['pending']} pending tasks "
                f"after {info['executed']} executed (dependency cycle or fuel "
                f"{fuel} exhausted)",
                stats=info,
            )
        return ivalues_np, data_out, info
