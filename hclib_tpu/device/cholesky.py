"""Tiled Cholesky inside the megakernel: MXU tile tasks on a DDF DAG.

Same dependency structure as the host model (models/cholesky.py; reference
test/cholesky/cholesky.cpp), with the four tile kernels designed for the TPU
compute units rather than translated from LAPACK:

- POTRF (VPU + MXU): ``factor_and_inv`` - the serial masked rank-1 sweep
  runs only on 128x128 diagonal base blocks (row j equals column j by
  symmetry, so both outer-product factors come from cheap masked
  reductions); larger tiles recurse by 2x2 blocking with panels, trailing
  updates, and the inverse assembled as MXU block algebra, and inv(L) of a
  base block comes from Newton-Schulz iterations (exact for triangular
  matrices after ceil(log2 T) steps).
- TRSM (MXU): with inv(L_kk) available, the triangular solve is one
  dot_general: A_ik <- A_ik inv(L_kk)^T.
- SYRK/GEMM (MXU): A_ij -= L_ik L_jk^T as dot_general contractions on the
  second axis of both operands (no explicit transpose).

All tiles are DMA'd HBM->VMEM per task; f32 with
preferred_element_type=f32 on every MXU op.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.tiles import dma_copy as _dma, factor_and_inv, mm_nt as _mm_nt
from .descriptor import TaskGraphBuilder
from .megakernel import KernelContext, Megakernel

__all__ = ["device_cholesky", "build_cholesky_graph", "make_cholesky_megakernel"]

T = 128  # default tile edge (MXU-native); 256 amortizes scheduling

POTRF = 0
TRSM = 1
SYRK = 2
GEMM = 3


def _load_all(pairs, sems) -> None:
    """Start every (src, dst) copy, then wait - loads ride the DMA engines
    concurrently instead of serializing start/wait per tile."""
    cps = [
        pltpu.make_async_copy(src, dst, sems.at[i])
        for i, (src, dst) in enumerate(pairs)
    ]
    for cp in cps:
        cp.start()
    for cp in cps:
        cp.wait()


def _potrf_kernel(ctx: KernelContext, ts: int = T) -> None:
    k = ctx.arg(0)
    tiles, linv = ctx.data["tiles"], ctx.data["linv"]
    va, vb = ctx.scratch["va"], ctx.scratch["vb"]
    sem = ctx.scratch["sems"]
    _dma(tiles.at[k, k], va, sem.at[0])
    l, inv = factor_and_inv(va[:], ts)
    va[:] = l
    vb[:] = inv
    _load_all([(va, tiles.at[k, k]), (vb, linv.at[k])], sem)


def _trsm_kernel(ctx: KernelContext, ts: int = T) -> None:
    i, k = ctx.arg(0), ctx.arg(1)
    tiles, linv = ctx.data["tiles"], ctx.data["linv"]
    va, vb = ctx.scratch["va"], ctx.scratch["vb"]
    sem = ctx.scratch["sems"]
    _load_all([(tiles.at[i, k], va), (linv.at[k], vb)], sem)
    va[:] = _mm_nt(va[:], vb[:])  # A_ik inv(L_kk)^T
    _dma(va, tiles.at[i, k], sem.at[0])


def _syrk_kernel(ctx: KernelContext, ts: int = T) -> None:
    i, k = ctx.arg(0), ctx.arg(1)
    tiles = ctx.data["tiles"]
    va, vb = ctx.scratch["va"], ctx.scratch["vb"]
    sem = ctx.scratch["sems"]
    _load_all([(tiles.at[i, i], va), (tiles.at[i, k], vb)], sem)
    va[:] = va[:] - _mm_nt(vb[:], vb[:])
    _dma(va, tiles.at[i, i], sem.at[0])


def _gemm_kernel(ctx: KernelContext, ts: int = T) -> None:
    i, j, k = ctx.arg(0), ctx.arg(1), ctx.arg(2)
    tiles = ctx.data["tiles"]
    va, vb, vc = ctx.scratch["va"], ctx.scratch["vb"], ctx.scratch["vc"]
    sem = ctx.scratch["sems"]
    _load_all(
        [(tiles.at[i, j], va), (tiles.at[i, k], vb), (tiles.at[j, k], vc)],
        sem,
    )
    va[:] = va[:] - _mm_nt(vb[:], vc[:])
    _dma(va, tiles.at[i, j], sem.at[0])


def build_cholesky_graph(nt: int) -> TaskGraphBuilder:
    """Static DAG, same structure as models/cholesky.py."""
    b = TaskGraphBuilder()
    U = {}  # (i, j) -> last task updating that tile
    P = {}
    S = {}

    def dep(*ids):
        return [t for t in ids if t is not None]

    for k in range(nt):
        P[k] = b.add(POTRF, args=[k], deps=dep(U.get((k, k))))
        for i in range(k + 1, nt):
            S[(i, k)] = b.add(TRSM, args=[i, k], deps=dep(U.get((i, k)), P[k]))
        for i in range(k + 1, nt):
            U[(i, i)] = b.add(SYRK, args=[i, k], deps=dep(U.get((i, i)), S[(i, k)]))
            for j in range(k + 1, i):
                U[(i, j)] = b.add(
                    GEMM, args=[i, j, k],
                    deps=dep(U.get((i, j)), S[(i, k)], S[(j, k)]),
                )
    return b


def make_cholesky_megakernel(
    nt: int, interpret: Optional[bool] = None, tile: int = T
) -> Megakernel:
    import functools as _ft

    tile_spec = jax.ShapeDtypeStruct((nt, nt, tile, tile), jnp.float32)
    linv_spec = jax.ShapeDtypeStruct((nt, tile, tile), jnp.float32)
    ntasks = nt + nt * (nt - 1) // 2 + nt * (nt - 1) * (nt + 1) // 6
    capacity = max(64, ntasks)
    return Megakernel(
        kernels=[
            ("potrf", _ft.partial(_potrf_kernel, ts=tile)),
            ("trsm", _ft.partial(_trsm_kernel, ts=tile)),
            ("syrk", _ft.partial(_syrk_kernel, ts=tile)),
            ("gemm", _ft.partial(_gemm_kernel, ts=tile)),
        ],
        data_specs={"tiles": tile_spec, "linv": linv_spec},
        scratch_specs={
            "va": pltpu.VMEM((tile, tile), jnp.float32),
            "vb": pltpu.VMEM((tile, tile), jnp.float32),
            "vc": pltpu.VMEM((tile, tile), jnp.float32),
            "sems": pltpu.SemaphoreType.DMA((3,)),
        },
        capacity=capacity,
        num_values=8,
        succ_capacity=max(64, 4 * ntasks),
        interpret=interpret,
    )


def _to_tiles(a: np.ndarray, nt: int, ts: int = T) -> np.ndarray:
    return (
        a.reshape(nt, ts, nt, ts).swapaxes(1, 2).astype(np.float32).copy()
    )


def _from_tiles(tiles: np.ndarray, nt: int, ts: int = T) -> np.ndarray:
    return np.asarray(tiles).swapaxes(1, 2).reshape(nt * ts, nt * ts)


def device_cholesky(
    a: np.ndarray,
    interpret: Optional[bool] = None,
    mk: Optional[Megakernel] = None,
    tile: int = T,
) -> Tuple[np.ndarray, dict]:
    """Factor SPD ``a`` ((nt*tile)^2) on-device; returns (L, info)."""
    n = a.shape[0]
    if n % tile != 0:
        raise ValueError(f"matrix size must be a multiple of {tile}")
    nt = n // tile
    if mk is None:
        mk = make_cholesky_megakernel(nt, interpret, tile=tile)
    b = build_cholesky_graph(nt)
    tiles = _to_tiles(a, nt, tile)
    linv = np.zeros((nt, tile, tile), dtype=np.float32)
    t0 = time.perf_counter()
    _, data, info = mk.run(b, data={"tiles": tiles, "linv": linv})
    dt = time.perf_counter() - t0
    L = np.tril(_from_tiles(data["tiles"], nt, tile))
    info = dict(info)
    info["seconds"] = dt
    info["gflops"] = (n**3 / 3.0) / dt / 1e9
    return L, info
