"""Tiled Cholesky inside the megakernel: MXU tile tasks on a DDF DAG.

Same dependency structure as the host model (models/cholesky.py; reference
test/cholesky/cholesky.cpp), with the tile kernels designed for the TPU
compute units rather than translated from LAPACK:

- POTRF (VPU + MXU): ``factor_and_inv`` - serial math confined to 8x8
  diagonal micro-blocks; panels, trailing updates, and the inverse are MXU
  block algebra (ops/tiles.py). Writes L_kk (f32) and inv(L_kk) PRE-SPLIT
  to bf16 hi/lo.
- TRSM (MXU): with inv(L_kk) available, the triangular solve is one
  3-pass matmul: A_ik <- A_ik inv(L_kk)^T. The default graph runs it as a
  COLUMN STREAM (one task per step k): inv's split stays resident while
  the A_ik tiles double-buffer through, and each result is stored twice -
  f32 (the factor output) and bf16 hi/lo (the ``lsp`` operand cache).
- UPDROW (MXU, row-fused trailing update): one task per (row i, step k)
  performs A_ij -= L_ik L_jk^T for all j in (k, i] (the SYRK j = i case
  included). Both L operands stream from ``lsp`` ALREADY SPLIT, so the
  hot loop is exactly the three MXU passes plus one subtract - no VPU
  split work (splitting both operands per iteration measured ~15% of the
  stream's wall clock). L_ik stays resident for the row; (A_ij, L_jk)
  pairs double-buffer so the next pair's DMA rides under the current
  GEMM.

Why 3 passes: f32 data, MXU matmuls at ~f32 accuracy via the bf16 hi/lo
split (ops/tiles.mm_nt_split). This sets the physics of the benchmark: a
3-pass f32-accurate GEMM can never exceed 1/3 of the chip's bf16 matmul
clock, so the meaningful utilization number is (achieved f32-effective
FLOP/s) / (probe/3) - bench.py prints both.
"""

from __future__ import annotations

import functools as _ft
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.tiles import (
    dma_copy as _dma,
    factor_and_inv,
    mm_nt_rsplit as _mm_nt_rsplit,
    mm_nt_split as _mm_nt_split,
    split_bf16 as _split,
)
from .descriptor import TaskGraphBuilder
from .megakernel import KernelContext, Megakernel

__all__ = ["device_cholesky", "build_cholesky_graph", "make_cholesky_megakernel"]

T = 128  # default tile edge (MXU-native); 256+ amortizes scheduling

POTRF = 0
TRSM = 1
UPDROW = 2
TRSMCOL = 3


def _load_all(pairs, sems) -> None:
    """Start every (src, dst) copy, then wait - loads ride the DMA engines
    concurrently instead of serializing start/wait per tile."""
    cps = [
        pltpu.make_async_copy(src, dst, sems.at[i])
        for i, (src, dst) in enumerate(pairs)
    ]
    for cp in cps:
        cp.start()
    for cp in cps:
        cp.wait()


def _potrf_kernel(ctx: KernelContext, ts: int = T, fbase: int = 128) -> None:
    k = ctx.arg(0)
    tiles, linvsp = ctx.data["tiles"], ctx.data["linvsp"]
    va = ctx.scratch["va"]
    rvh, rvl = ctx.scratch["rvh"], ctx.scratch["rvl"]
    sem = ctx.scratch["sems"]
    _dma(tiles.at[k, k], va, sem.at[0])
    l, inv = factor_and_inv(va[:], ts, base=fbase)
    va[:] = l
    ih, il = _split(inv)
    rvh[:] = ih
    rvl[:] = il
    _load_all(
        [(va, tiles.at[k, k]), (rvh, linvsp.at[k, 0]), (rvl, linvsp.at[k, 1])],
        sem,
    )


def _trsm_kernel(ctx: KernelContext, ts: int = T) -> None:
    """Tile-at-a-time TRSM (the unfused graph's form): one 3-pass matmul
    against the resident inverse split, stored f32 + split."""
    i, k = ctx.arg(0), ctx.arg(1)
    tiles, linvsp, lsp = ctx.data["tiles"], ctx.data["linvsp"], ctx.data["lsp"]
    f32a, f32b = ctx.scratch["f32a"], ctx.scratch["f32b"]
    bfh, bfl = ctx.scratch["bfh"], ctx.scratch["bfl"]
    rvh, rvl = ctx.scratch["rvh"], ctx.scratch["rvl"]
    sem = ctx.scratch["sems"]
    _load_all(
        [(tiles.at[i, k], f32a.at[0]), (linvsp.at[k, 0], rvh),
         (linvsp.at[k, 1], rvl)],
        sem,
    )
    s = _mm_nt_rsplit(f32a[0], rvh[:], rvl[:])  # A_ik inv(L_kk)^T
    f32b[0] = s
    sh, sl = _split(s)
    bfh[0] = sh
    bfl[0] = sl
    _load_all(
        [(f32b.at[0], tiles.at[i, k]), (bfh.at[0], lsp.at[i, k, 0]),
         (bfl.at[0], lsp.at[i, k, 1])],
        sem,
    )


def _trsmcol_kernel(ctx: KernelContext, ts: int = T, nt: int = 0) -> None:
    """Column-fused TRSM stream (one task per step k): inv(L_kk)'s split
    stays resident; the A_ik tiles (i = k+1 .. nt-1) double-buffer
    through, each result stored back f32 AND bf16 hi/lo (the ``lsp``
    operand cache the trailing updates stream from). On a single core the
    DAG's TRSM tiles run back-to-back anyway; fusing them removes
    per-tile dispatch and lets every load/store ride under a neighbor's
    matmul."""
    k = ctx.arg(0)
    tiles, linvsp, lsp = ctx.data["tiles"], ctx.data["linvsp"], ctx.data["lsp"]
    f32a, f32b = ctx.scratch["f32a"], ctx.scratch["f32b"]
    bfh, bfl = ctx.scratch["bfh"], ctx.scratch["bfl"]
    rvh, rvl = ctx.scratch["rvh"], ctx.scratch["rvl"]
    sem = ctx.scratch["sems"]
    sl = ctx.scratch["sload"]  # (2, 3) load sems (only [:, 0] used here)
    ss = ctx.scratch["sstore"]  # (2, 3): per-slot {f32, hi, lo} store sems
    _load_all([(linvsp.at[k, 0], rvh), (linvsp.at[k, 1], rvl)], sem)
    nj = nt - 1 - k  # i walks k+1 .. nt-1

    def start_load(slot, i) -> None:
        pltpu.make_async_copy(
            tiles.at[i, k], f32a.at[slot], sl.at[slot, 0]
        ).start()

    def start_stores(slot, i) -> None:
        pltpu.make_async_copy(
            f32b.at[slot], tiles.at[i, k], ss.at[slot, 0]
        ).start()
        pltpu.make_async_copy(
            bfh.at[slot], lsp.at[i, k, 0], ss.at[slot, 1]
        ).start()
        pltpu.make_async_copy(
            bfl.at[slot], lsp.at[i, k, 1], ss.at[slot, 2]
        ).start()

    def wait_stores(slot, i) -> None:
        pltpu.make_async_copy(
            f32b.at[slot], tiles.at[i, k], ss.at[slot, 0]
        ).wait()
        pltpu.make_async_copy(
            bfh.at[slot], lsp.at[i, k, 0], ss.at[slot, 1]
        ).wait()
        pltpu.make_async_copy(
            bfl.at[slot], lsp.at[i, k, 1], ss.at[slot, 2]
        ).wait()

    start_load(0, k + 1)

    def body(t, _):
        i = k + 1 + t
        cur = t % 2
        nxt = 1 - cur

        @pl.when(t + 1 < nj)
        def _():
            # f32a[nxt] was an INPUT at t-1 (read synchronously by that
            # iteration's matmul), so prefetching over it is safe.
            start_load(nxt, i + 1)

        pltpu.make_async_copy(tiles.at[i, k], f32a.at[cur], sl.at[cur, 0]).wait()
        s = _mm_nt_rsplit(f32a[cur], rvh[:], rvl[:])
        # Slot cur's OUTPUT buffers last stored at t-2 (dst row i-2);
        # those transfers must land before this compute overwrites them.
        @pl.when(t >= 2)
        def _():
            wait_stores(cur, i - 2)

        f32b[cur] = s
        sh, slo = _split(s)
        bfh[cur] = sh
        bfl[cur] = slo
        start_stores(cur, i)
        return 0

    jax.lax.fori_loop(0, nj, body, 0)
    last = (nj - 1) % 2

    @pl.when(nj >= 2)
    def _():
        wait_stores(1 - last, k + nj - 1)

    wait_stores(last, k + nj)


def _updrow_stream(ctx, i, k, lh, ll) -> None:
    """The row-fused trailing-update stream for row ``i`` at step ``k``
    with the resident L_ik split already loaded (``lh``/``ll`` values):
    A_ij -= L_ik L_jk^T for j in (k, i].

    The (A_ij, L_jk-split) streams double-buffer through two slots -
    iteration t starts the DMAs for t+1 before computing t, and
    store-backs ride their own semaphores so a slot is only reused once
    its previous store completed. The SYRK j = i case needs no special
    path: lsp[j, k] at j = i IS the resident L_ik (same bits). Every
    started DMA is waited exactly once (the epilogue drains the last two
    stores), so the scalar kernel and the batched body can both run this
    back to back. ``ctx`` may be a KernelContext or a BatchContext (only
    ``data``/``scratch`` are touched)."""
    tiles, lsp = ctx.data["tiles"], ctx.data["lsp"]
    f32a = ctx.scratch["f32a"]
    bfh, bfl = ctx.scratch["bfh"], ctx.scratch["bfl"]
    sl = ctx.scratch["sload"]  # (2, 3): per-slot {A, L-hi, L-lo}
    ss = ctx.scratch["sstore"]  # (2, 3): [slot, 0] = A store-back
    nj = i - k  # j walks k+1 .. i

    def start_loads(slot, j) -> None:
        pltpu.make_async_copy(tiles.at[i, j], f32a.at[slot], sl.at[slot, 0]).start()
        pltpu.make_async_copy(lsp.at[j, k, 0], bfh.at[slot], sl.at[slot, 1]).start()
        pltpu.make_async_copy(lsp.at[j, k, 1], bfl.at[slot], sl.at[slot, 2]).start()

    start_loads(0, k + 1)

    def body(t, _):
        j = k + 1 + t
        cur = t % 2
        nxt = 1 - cur

        @pl.when(t + 1 < nj)
        def _():
            # Slot nxt last stored at t-1 (dst tiles[i, j-1]); that store
            # must land before the prefetch overwrites the buffer.
            @pl.when(t >= 1)
            def _():
                pltpu.make_async_copy(
                    f32a.at[nxt], tiles.at[i, j - 1], ss.at[nxt, 0]
                ).wait()

            start_loads(nxt, j + 1)

        pltpu.make_async_copy(tiles.at[i, j], f32a.at[cur], sl.at[cur, 0]).wait()
        pltpu.make_async_copy(lsp.at[j, k, 0], bfh.at[cur], sl.at[cur, 1]).wait()
        pltpu.make_async_copy(lsp.at[j, k, 1], bfl.at[cur], sl.at[cur, 2]).wait()
        f32a[cur] = f32a[cur] - _mm_nt_split(lh, ll, bfh[cur], bfl[cur])
        pltpu.make_async_copy(f32a.at[cur], tiles.at[i, j], ss.at[cur, 0]).start()
        return 0

    jax.lax.fori_loop(0, nj, body, 0)
    # Drain the last two stores: slot `last` stored tiles[i, i] (j = i at
    # t = nj-1), slot `1-last` stored tiles[i, i-1] (t = nj-2).
    last = (nj - 1) % 2

    @pl.when(nj >= 2)
    def _():
        pltpu.make_async_copy(
            f32a.at[1 - last], tiles.at[i, i - 1], ss.at[1 - last, 0]
        ).wait()

    pltpu.make_async_copy(f32a.at[last], tiles.at[i, i], ss.at[last, 0]).wait()


def _updrow_kernel(ctx: KernelContext, ts: int = T) -> None:
    """Scalar-dispatch trailing update: load L_ik's split resident, then
    run the shared row stream."""
    i, k = ctx.arg(0), ctx.arg(1)
    lsp = ctx.data["lsp"]
    rvh, rvl = ctx.scratch["rvh"], ctx.scratch["rvl"]
    sem = ctx.scratch["sems"]
    _load_all([(lsp.at[i, k, 0], rvh), (lsp.at[i, k, 1], rvl)], sem)
    _updrow_stream(ctx, i, k, rvh[:], rvl[:])


UPD_B = 4  # row tasks per batched trailing-update round


def _updrow_batch_kernel(ctx, ts: int = T) -> None:
    """Batched trailing updates: up to ``ctx.width`` ready row tasks (all
    rows of one step k, in practice - a TRSMCOL completion readies them
    together) through one body. The per-row GEMM stream is byte-identical
    to the scalar kernel's; what the batch buys is the resident-operand
    pipeline: slot b+1's L_ik split streams into the other half of a
    double-buffered pair DURING slot b's row stream, so the MXU never
    stalls on the per-task resident load, and the per-task ``lax.switch``
    dispatch disappears."""
    lsp = ctx.data["lsp"]
    brvh, brvl = ctx.scratch["brvh"], ctx.scratch["brvl"]  # (2, ts, ts)
    bsem = ctx.scratch["bsem"]  # (2, 2): per-half {hi, lo}

    def res_copies(half, b):
        i, k = ctx.arg(b, 0), ctx.arg(b, 1)
        return (
            pltpu.make_async_copy(lsp.at[i, k, 0], brvh.at[half], bsem.at[half, 0]),
            pltpu.make_async_copy(lsp.at[i, k, 1], brvl.at[half], bsem.at[half, 1]),
        )

    for cp in res_copies(0, 0):  # slot 0 is always live (take >= 1)
        cp.start()
    for b in range(ctx.width):
        half = b % 2

        @pl.when(ctx.live(b))
        def _(b=b, half=half):
            if b + 1 < ctx.width:
                @pl.when(ctx.live(b + 1))
                def _():
                    for cp in res_copies(1 - half, b + 1):
                        cp.start()

            for cp in res_copies(half, b):
                cp.wait()
            i, k = ctx.arg(b, 0), ctx.arg(b, 1)
            _updrow_stream(ctx, i, k, brvh[half], brvl[half])


def build_cholesky_graph(nt: int, fused_trsm: bool = True) -> TaskGraphBuilder:
    """Static DAG: POTRF / TRSM tile tasks + row-fused trailing updates.

    Dependency shape (R = UPDROW row task, C = TRSMCOL column stream):
      POTRF(k)  <- R(k, k-1)              (its diagonal tile's last writer)
      C(k)      <- POTRF(k), R(i, k-1) for all i > k   (fused default:
                   the stream reads every tile (i, k), whose last writers
                   are the step-(k-1) row updates)
      R(i, k)   <- C(k)                   (the L operands; C(k) carries
                                           R(i, k-1) transitively)
    or, with ``fused_trsm=False`` (tile-level TRSM, the reference's
    granularity, test/cholesky/cholesky.cpp):
      TRSM(i,k) <- POTRF(k), R(i, k-1)
      R(i, k)   <- TRSM(j,k) for k<j<=i

    The fused graph keeps the full cross-row parallelism of the trailing
    updates (the FLOPs); it serializes only the column solves, which a
    single core runs back-to-back in either form.
    """
    b = TaskGraphBuilder()
    P = {}
    S = {}
    R = {}  # (i, k) -> row-update task for row i at step k

    def dep(*ids):
        return [t for t in ids if t is not None]

    for k in range(nt):
        P[k] = b.add(POTRF, args=[k], deps=dep(R.get((k, k - 1))))
        if fused_trsm:
            if k + 1 < nt:
                prev = [R[(i, k - 1)] for i in range(k + 1, nt)] if k else []
                col = b.add(TRSMCOL, args=[k], deps=[P[k]] + prev)
                for i in range(k + 1, nt):
                    R[(i, k)] = b.add(UPDROW, args=[i, k], deps=[col])
        else:
            for i in range(k + 1, nt):
                S[(i, k)] = b.add(
                    TRSM, args=[i, k], deps=dep(P[k], R.get((i, k - 1)))
                )
            for i in range(k + 1, nt):
                R[(i, k)] = b.add(
                    UPDROW,
                    args=[i, k],
                    deps=[S[(j, k)] for j in range(k + 1, i + 1)],
                )
    return b


def make_cholesky_megakernel(
    nt: int,
    interpret: Optional[bool] = None,
    tile: int = T,
    factor_base: Optional[int] = None,
    fused_only: bool = False,
    batch_updrow: bool = True,
    checkpoint: Optional[bool] = None,
) -> Megakernel:
    """``batch_updrow`` routes the trailing-update row tasks through the
    megakernel's batched same-kind dispatch tier (UPD_B rows per round,
    resident L-split pipelined across slots); results are bit-identical
    to the scalar dispatch, which ``batch_updrow=False`` restores."""
    if factor_base is None:
        # In-kernel A/B at n=8192 (fast windows, interleaved): base 128
        # = 7.36 ms vs base 256 = 7.92-8.02 ms, every trial - the deeper
        # recursion's extra block algebra is cheaper than factor_tile +
        # Newton-Schulz on 256-wide planes. (A plain-jit microbench had
        # suggested the opposite; it was clock-window noise.)
        factor_base = min(tile, 128)
    tile_spec = jax.ShapeDtypeStruct((nt, nt, tile, tile), jnp.float32)
    linvsp_spec = jax.ShapeDtypeStruct((nt, 2, tile, tile), jnp.bfloat16)
    lsp_spec = jax.ShapeDtypeStruct((nt, nt, 2, tile, tile), jnp.bfloat16)
    # POTRF + TRSM tile tasks (or column streams) + one row-update task
    # per (row, step): capacity covers the larger (unfused) form unless
    # ``fused_only`` - SMEM windows pad task-table scalars to ~32 B/word,
    # so large-nt kernels (nt >= 32) only fit the 1 MB SMEM budget with
    # the fused graph's smaller table.
    if fused_only:
        ntasks = nt + (nt - 1) + nt * (nt - 1) // 2
    else:
        ntasks = nt + 2 * (nt * (nt - 1) // 2)
    capacity = max(64, ntasks)
    scratch = {
        "va": pltpu.VMEM((tile, tile), jnp.float32),
        "f32a": pltpu.VMEM((2, tile, tile), jnp.float32),
        "f32b": pltpu.VMEM((2, tile, tile), jnp.float32),
        "bfh": pltpu.VMEM((2, tile, tile), jnp.bfloat16),
        "bfl": pltpu.VMEM((2, tile, tile), jnp.bfloat16),
        "rvh": pltpu.VMEM((tile, tile), jnp.bfloat16),
        "rvl": pltpu.VMEM((tile, tile), jnp.bfloat16),
        "sems": pltpu.SemaphoreType.DMA((3,)),
        "sload": pltpu.SemaphoreType.DMA((2, 3)),
        "sstore": pltpu.SemaphoreType.DMA((2, 3)),
    }
    route = {}
    if batch_updrow:
        from .megakernel import BatchSpec

        scratch["brvh"] = pltpu.VMEM((2, tile, tile), jnp.bfloat16)
        scratch["brvl"] = pltpu.VMEM((2, tile, tile), jnp.bfloat16)
        scratch["bsem"] = pltpu.SemaphoreType.DMA((2, 2))
        route["updrow"] = BatchSpec(
            _ft.partial(_updrow_batch_kernel, ts=tile), width=UPD_B
        )
    return Megakernel(
        kernels=[
            ("potrf", _ft.partial(_potrf_kernel, ts=tile, fbase=factor_base)),
            ("trsm", _ft.partial(_trsm_kernel, ts=tile)),
            ("updrow", _ft.partial(_updrow_kernel, ts=tile)),
            ("trsmcol", _ft.partial(_trsmcol_kernel, ts=tile, nt=nt)),
        ],
        route=route,
        data_specs={
            "tiles": tile_spec, "linvsp": linvsp_spec, "lsp": lsp_spec,
        },
        scratch_specs=scratch,
        capacity=capacity,
        num_values=8,
        succ_capacity=max(
            64,
            4 * ntasks + (nt * nt if fused_only else nt * nt * nt // 2),
        ),
        interpret=interpret,
        checkpoint=checkpoint,
        # 8 f32-equivalent tile buffers + compiler stack temporaries
        # (factor_and_inv block values, bf16 split operands) + the batched
        # tier's resident double-buffer pair: past the 16 MiB scoped
        # default once tile >= 512.
        vmem_limit_bytes=max(
            (26 if batch_updrow else 24) * tile * tile * 4,
            16 * 1024 * 1024,
        ),
    )


def _to_tiles(a: np.ndarray, nt: int, ts: int = T) -> np.ndarray:
    return (
        a.reshape(nt, ts, nt, ts).swapaxes(1, 2).astype(np.float32).copy()
    )


def _from_tiles(tiles: np.ndarray, nt: int, ts: int = T) -> np.ndarray:
    return np.asarray(tiles).swapaxes(1, 2).reshape(nt * ts, nt * ts)


def cholesky_buffers(a: np.ndarray, nt: int, tile: int = T) -> dict:
    """The three data buffers a Cholesky run needs: f32 tiles plus the
    bf16 split caches (inverse + subdiagonal L operands)."""
    return {
        "tiles": _to_tiles(a, nt, tile),
        "linvsp": jnp.zeros((nt, 2, tile, tile), jnp.bfloat16),
        "lsp": jnp.zeros((nt, nt, 2, tile, tile), jnp.bfloat16),
    }


def device_cholesky(
    a: np.ndarray,
    interpret: Optional[bool] = None,
    mk: Optional[Megakernel] = None,
    tile: int = T,
    fused_trsm: bool = True,
    batch_updrow: bool = True,
) -> Tuple[np.ndarray, dict]:
    """Factor SPD ``a`` ((nt*tile)^2) on-device; returns (L, info)."""
    n = a.shape[0]
    if n % tile != 0:
        raise ValueError(f"matrix size must be a multiple of {tile}")
    nt = n // tile
    if mk is None:
        mk = make_cholesky_megakernel(
            nt, interpret, tile=tile, batch_updrow=batch_updrow
        )
    b = build_cholesky_graph(nt, fused_trsm=fused_trsm)
    t0 = time.perf_counter()
    _, data, info = mk.run(b, data=cholesky_buffers(a, nt, tile))
    dt = time.perf_counter() - t0
    L = np.tril(_from_tiles(data["tiles"], nt, tile))
    info = dict(info)
    info["seconds"] = dt
    info["gflops"] = (n**3 / 3.0) / dt / 1e9
    return L, info
