"""Tiled Cholesky inside the megakernel: MXU tile tasks on a DDF DAG.

Same dependency structure as the host model (models/cholesky.py; reference
test/cholesky/cholesky.cpp), with the four tile kernels designed for the TPU
compute units rather than translated from LAPACK:

- POTRF (VPU + MXU): ``factor_and_inv`` - the serial masked rank-1 sweep
  runs only on 128x128 diagonal base blocks (row j equals column j by
  symmetry, so both outer-product factors come from cheap masked
  reductions); larger tiles recurse by 2x2 blocking with panels, trailing
  updates, and the inverse assembled as MXU block algebra, and inv(L) of a
  base block comes from Newton-Schulz iterations (exact for triangular
  matrices after ceil(log2 T) steps).
- TRSM (MXU): with inv(L_kk) available, the triangular solve is one
  dot_general: A_ik <- A_ik inv(L_kk)^T.
- UPDROW (MXU, row-fused trailing update): one task per (row i, step k)
  performs A_ij -= L_ik L_jk^T for all j in (k, i] (the SYRK j = i case
  included), loading L_ik once and double-buffering the (A_ij, L_jk) tile
  streams so the next pair's DMA rides under the current GEMM - the
  HBM-bandwidth half of the workload overlaps the MXU half instead of
  serializing 4 transfers around every matmul. Tile-level tasks (the
  reference's granularity, test/cholesky/cholesky.cpp) spend ~half their
  wall on un-overlapped DMA; row fusion is the TPU-first regrouping: the
  DAG keeps real parallelism across rows while each task gets a
  long-enough tile stream to pipeline.

f32 data, MXU matmuls at ~f32 accuracy via the 3-pass bf16 hi/lo split
(ops/tiles.mm_nt).
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.tiles import dma_copy as _dma, factor_and_inv, mm_nt as _mm_nt
from .descriptor import TaskGraphBuilder
from .megakernel import KernelContext, Megakernel

__all__ = ["device_cholesky", "build_cholesky_graph", "make_cholesky_megakernel"]

T = 128  # default tile edge (MXU-native); 256 amortizes scheduling

POTRF = 0
TRSM = 1
UPDROW = 2


def _load_all(pairs, sems) -> None:
    """Start every (src, dst) copy, then wait - loads ride the DMA engines
    concurrently instead of serializing start/wait per tile."""
    cps = [
        pltpu.make_async_copy(src, dst, sems.at[i])
        for i, (src, dst) in enumerate(pairs)
    ]
    for cp in cps:
        cp.start()
    for cp in cps:
        cp.wait()


def _potrf_kernel(ctx: KernelContext, ts: int = T) -> None:
    k = ctx.arg(0)
    tiles, linv = ctx.data["tiles"], ctx.data["linv"]
    va, vb = ctx.scratch["va"], ctx.scratch["vb"]
    sem = ctx.scratch["sems"]
    _dma(tiles.at[k, k], va, sem.at[0])
    l, inv = factor_and_inv(va[:], ts)
    va[:] = l
    vb[:] = inv
    _load_all([(va, tiles.at[k, k]), (vb, linv.at[k])], sem)


def _trsm_kernel(ctx: KernelContext, ts: int = T) -> None:
    i, k = ctx.arg(0), ctx.arg(1)
    tiles, linv = ctx.data["tiles"], ctx.data["linv"]
    va, vb = ctx.scratch["va"], ctx.scratch["vb"]
    sem = ctx.scratch["sems"]
    _load_all([(tiles.at[i, k], va), (linv.at[k], vb)], sem)
    va[:] = _mm_nt(va[:], vb[:])  # A_ik inv(L_kk)^T
    _dma(va, tiles.at[i, k], sem.at[0])


def _updrow_kernel(ctx: KernelContext, ts: int = T) -> None:
    """Row-fused trailing update: A_ij -= L_ik L_jk^T for j in (k, i].

    L_ik stays resident in VMEM for the whole row; the (A_ij, L_jk) pairs
    stream through two double-buffered slots - iteration t starts the DMAs
    for t+1 before computing t, and store-backs ride their own semaphores
    so a slot is only reused once its previous store completed. Every
    started DMA is waited exactly once (the epilogue drains the last two
    stores)."""
    i, k = ctx.arg(0), ctx.arg(1)
    tiles = ctx.data["tiles"]
    vl = ctx.scratch["vl"]
    ab, lb = ctx.scratch["ab"], ctx.scratch["lb"]
    sl = ctx.scratch["sload"]  # (2, 2): [slot, {A, L}]
    ss = ctx.scratch["sstore"]  # (2,): per-slot store sems
    sem = ctx.scratch["sems"]
    _dma(tiles.at[i, k], vl, sem.at[0])  # L_ik, resident for the row
    nj = i - k  # j walks k+1 .. i

    def start_loads(slot, j) -> None:
        pltpu.make_async_copy(tiles.at[i, j], ab.at[slot], sl.at[slot, 0]).start()
        # j == i loads tiles[i, k] = L_ik again: harmless, keeps the DMA
        # count per iteration uniform (the compute selects vl for SYRK).
        pltpu.make_async_copy(tiles.at[j, k], lb.at[slot], sl.at[slot, 1]).start()

    start_loads(0, k + 1)

    def body(t, _):
        j = k + 1 + t
        cur = t % 2
        nxt = 1 - cur

        @pl.when(t + 1 < nj)
        def _():
            # Slot nxt last stored at t-1 (dst tiles[i, j-1]); that store
            # must land before the prefetch overwrites the buffer.
            @pl.when(t >= 1)
            def _():
                pltpu.make_async_copy(
                    ab.at[nxt], tiles.at[i, j - 1], ss.at[nxt]
                ).wait()

            start_loads(nxt, j + 1)

        pltpu.make_async_copy(tiles.at[i, j], ab.at[cur], sl.at[cur, 0]).wait()
        pltpu.make_async_copy(tiles.at[j, k], lb.at[cur], sl.at[cur, 1]).wait()
        rhs = jnp.where(j == i, vl[:], lb[cur])
        ab[cur] = ab[cur] - _mm_nt(vl[:], rhs)
        pltpu.make_async_copy(ab.at[cur], tiles.at[i, j], ss.at[cur]).start()
        return 0

    jax.lax.fori_loop(0, nj, body, 0)
    # Drain the last two stores. The wait descriptors name the transfers
    # these semaphores actually signal: slot `last` stored tiles[i, i]
    # (j = i at t = nj-1), slot `1-last` stored tiles[i, i-1] (t = nj-2).
    last = (nj - 1) % 2

    @pl.when(nj >= 2)
    def _():
        pltpu.make_async_copy(
            ab.at[1 - last], tiles.at[i, i - 1], ss.at[1 - last]
        ).wait()

    pltpu.make_async_copy(ab.at[last], tiles.at[i, i], ss.at[last]).wait()


def build_cholesky_graph(nt: int) -> TaskGraphBuilder:
    """Static DAG: POTRF / TRSM tile tasks + row-fused trailing updates.

    Dependency shape (R = UPDROW row task):
      POTRF(k)  <- R(k, k-1)             (its diagonal tile's last writer)
      TRSM(i,k) <- POTRF(k), R(i, k-1)   (tile (i,k)'s last writer)
      R(i, k)   <- TRSM(j,k) for k<j<=i  (the L_jk operands; TRSM(i,k)
                                          transitively carries R(i,k-1),
                                          the last writer of row i's tiles)
    """
    b = TaskGraphBuilder()
    P = {}
    S = {}
    R = {}  # (i, k) -> row-update task for row i at step k

    def dep(*ids):
        return [t for t in ids if t is not None]

    for k in range(nt):
        P[k] = b.add(POTRF, args=[k], deps=dep(R.get((k, k - 1))))
        for i in range(k + 1, nt):
            S[(i, k)] = b.add(
                TRSM, args=[i, k], deps=dep(P[k], R.get((i, k - 1)))
            )
        for i in range(k + 1, nt):
            R[(i, k)] = b.add(
                UPDROW,
                args=[i, k],
                deps=[S[(j, k)] for j in range(k + 1, i + 1)],
            )
    return b


def make_cholesky_megakernel(
    nt: int, interpret: Optional[bool] = None, tile: int = T
) -> Megakernel:
    import functools as _ft

    tile_spec = jax.ShapeDtypeStruct((nt, nt, tile, tile), jnp.float32)
    linv_spec = jax.ShapeDtypeStruct((nt, tile, tile), jnp.float32)
    # POTRF + TRSM tile tasks + one row-update task per (row, step).
    ntasks = nt + 2 * (nt * (nt - 1) // 2)
    capacity = max(64, ntasks)
    return Megakernel(
        kernels=[
            ("potrf", _ft.partial(_potrf_kernel, ts=tile)),
            ("trsm", _ft.partial(_trsm_kernel, ts=tile)),
            ("updrow", _ft.partial(_updrow_kernel, ts=tile)),
        ],
        data_specs={"tiles": tile_spec, "linv": linv_spec},
        scratch_specs={
            "va": pltpu.VMEM((tile, tile), jnp.float32),
            "vb": pltpu.VMEM((tile, tile), jnp.float32),
            "vl": pltpu.VMEM((tile, tile), jnp.float32),
            "ab": pltpu.VMEM((2, tile, tile), jnp.float32),
            "lb": pltpu.VMEM((2, tile, tile), jnp.float32),
            "sems": pltpu.SemaphoreType.DMA((3,)),
            "sload": pltpu.SemaphoreType.DMA((2, 2)),
            "sstore": pltpu.SemaphoreType.DMA((2,)),
        },
        capacity=capacity,
        num_values=8,
        succ_capacity=max(64, 4 * ntasks + nt * nt * nt // 2),
        interpret=interpret,
        # 7 tile buffers + compiler stack temporaries (factor_and_inv block
        # values, bf16 split operands): past the 16 MiB scoped default once
        # tile >= 768.
        vmem_limit_bytes=max(16 * tile * tile * 4, 16 * 1024 * 1024),
    )


def _to_tiles(a: np.ndarray, nt: int, ts: int = T) -> np.ndarray:
    return (
        a.reshape(nt, ts, nt, ts).swapaxes(1, 2).astype(np.float32).copy()
    )


def _from_tiles(tiles: np.ndarray, nt: int, ts: int = T) -> np.ndarray:
    return np.asarray(tiles).swapaxes(1, 2).reshape(nt * ts, nt * ts)


def device_cholesky(
    a: np.ndarray,
    interpret: Optional[bool] = None,
    mk: Optional[Megakernel] = None,
    tile: int = T,
) -> Tuple[np.ndarray, dict]:
    """Factor SPD ``a`` ((nt*tile)^2) on-device; returns (L, info)."""
    n = a.shape[0]
    if n % tile != 0:
        raise ValueError(f"matrix size must be a multiple of {tile}")
    nt = n // tile
    if mk is None:
        mk = make_cholesky_megakernel(nt, interpret, tile=tile)
    b = build_cholesky_graph(nt)
    tiles = _to_tiles(a, nt, tile)
    linv = np.zeros((nt, tile, tile), dtype=np.float32)
    t0 = time.perf_counter()
    _, data, info = mk.run(b, data={"tiles": tiles, "linv": linv})
    dt = time.perf_counter() - t0
    L = np.tril(_from_tiles(data["tiles"], nt, tile))
    info = dict(info)
    info["seconds"] = dt
    info["gflops"] = (n**3 / 3.0) / dt / 1e9
    return L, info
