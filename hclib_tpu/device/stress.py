"""Benchmark-scale multi-device acceptance workloads (VERDICT r4 #1).

The reference's core claim is load balancing under real stress (UTS as the
canonical test, test/uts/sample_trees.sh:36-37; the steal paths,
src/hclib-locality-graph.c:843-888). The round-4 dryrun proved the
multi-device *protocols* at smoke scale (~1.3k tasks); these workloads run
them at benchmark scale on the virtual CPU mesh, with exact totals, and
report wall time + per-device load for the perf harness.

Two tiers, matched to what the two interpreters can bear on a 1-vCPU host:

- ``forest_steal`` - >= 1e5 dynamically-spawned tasks through the
  bulk-synchronous sharded runner (device/sharded.py) on the FAST
  XLA-backed interpreter: a maximally-skewed forest of fib roots (every
  root seeded on device 0). Roots are successor-free descriptors, so they
  migrate over the hypercube diffusion; each stolen root then explodes
  into its dependency-rich subtree (spawns, joins, continuation passing)
  on the thief. This is the UTS shape: cheap-to-move seeds, expensive
  subtrees, discovered imbalance.
- ``unified_load`` - the unified resident kernel (device/resident.py:
  dependency-BEARING migration via the home-link proxy protocol, remote
  fetch-adds, put/wait-until channels, all in one kernel per device) under
  a load sized for the Mosaic interpreter (which simulates the remote DMAs
  and runs ~3 orders slower than hardware; the suite's protocol tests stay
  smoke-sized for this reason). Scale here means tens of times the
  dryrun's phase load, with every total exact.

Both return an ``info`` dict timeline.py's device report renders directly.
"""

from __future__ import annotations

import time
from typing import Dict

import numpy as np

__all__ = ["forest_steal", "unified_load",
           "FOREST_STEAL_BENCH", "FOREST_STEAL_QUICK"]

# The benchmark-of-record forest-steal configuration, shared by the
# scalar and batched arms in tools/perf_regression.py --multichip AND
# bench.py's multichip headline: the mesh-batch-dispatch guard compares
# the two arms' tasks/s, which is only meaningful while they run the
# SAME workload - tune these here, not at a call site.
FOREST_STEAL_BENCH = dict(ndev=8, roots=160, n=12, capacity=4096)
FOREST_STEAL_QUICK = dict(ndev=8, roots=24, n=9, capacity=1024)


def forest_steal(
    ndev: int = 8,
    roots: int = 160,
    n: int = 12,
    quantum: int = 256,
    window: int = 16,
    capacity: int = 4096,
    batch_width: int = 0,
) -> Dict:
    """Maximally-skewed fib forest through the sharded steal runner.

    ``roots`` fib(``n``) seeds all on device 0; exact checks: the executed
    count equals roots * (FIB nodes + SUM joins) and the out slots sum to
    roots * fib(n) across the mesh (a migrated root writes its slot on the
    thief's value buffer). Defaults: 160 x fib(12) = 111,520 tasks.

    ``batch_width`` > 0 routes the FIB kind through the batched same-kind
    dispatch tier (ISSUE 7): every device's scheduler fires same-kind fib
    batches between steal rounds, lanes spill to the ring's cold end at
    every kernel exit so the steal exchange sees the same candidates the
    scalar mesh would, and the returned info carries per-device
    ``tiers`` (occupancy / batch rounds / spills) beside the totals -
    which stay exact and identical to the scalar arm."""
    from ..models.fib import fib_seq, task_count
    from ..parallel.mesh import cpu_mesh
    from .descriptor import TaskGraphBuilder
    from .megakernel import VBLOCK
    from .sharded import ShardedMegakernel
    from .workloads import FIB, make_fib_megakernel

    mk = make_fib_megakernel(
        capacity=capacity, interpret=True,
        num_values=VBLOCK * capacity + max(64, roots),
        batch_width=batch_width or None,
    )
    smk = ShardedMegakernel(mk, cpu_mesh(ndev, axis_name="q"),
                            migratable_fns=[FIB])

    def build():
        builders = [TaskGraphBuilder() for _ in range(ndev)]
        for r in range(roots):
            builders[0].add(FIB, args=[n], out=r)
        for b in builders:
            # Symmetric heap: a migrated root writes its out slot on the
            # THIEF's value buffer, so every device must hold the root
            # slot range below its row-block region.
            b.reserve_values(roots)
        return builders

    iv, _, info = smk.run(build(), steal=True, quantum=quantum,
                          window=window)  # compile + warm
    t0 = time.perf_counter()
    iv, _, info = smk.run(build(), steal=True, quantum=quantum,
                          window=window)
    dt = time.perf_counter() - t0

    per_call = task_count(n)
    per_call += (per_call - 1) // 2  # SUM joins
    expect_tasks = roots * per_call
    assert info["executed"] == expect_tasks, (info["executed"], expect_tasks)
    got = int(np.asarray(iv)[:, :roots].sum(dtype=np.int64))
    assert got == roots * fib_seq(n), (got, roots * fib_seq(n))
    assert info["pending"] == 0
    per_dev = np.asarray(info["per_device_counts"])[:, 5]
    tier_label = f" [batch w={batch_width}]" if batch_width else ""
    info = dict(info)
    info.update(
        name=f"forest_steal {roots}x fib({n}) on {ndev} devices"
        + tier_label,
        seconds=dt,
        tasks=expect_tasks,
        tasks_per_sec=expect_tasks / dt,
        rounds=info.get("steal_rounds"),
        devices_used=int((per_dev > 0).sum()),
        imbalance=float(per_dev.max() * ndev / max(per_dev.sum(), 1)),
        per_device_counts=np.asarray(info["per_device_counts"]).tolist(),
    )
    if batch_width:
        # The mesh-batch acceptance: every device that executed work must
        # have fired batch rounds (the tier engaged mesh-wide, not just on
        # the seed device), and the tier totals must reconcile with the
        # executed count.
        tiers = info["tiers"]
        batched = sum(t["batch_tasks"] for t in tiers)
        scalar = sum(t["scalar_tasks"] for t in tiers)
        assert batched + scalar == expect_tasks, (batched, scalar)
        for d in range(ndev):
            if per_dev[d] > 0:
                assert tiers[d]["batch_rounds"] > 0, (d, tiers[d])
        occ = [t["batch_occupancy"] for t in tiers if t["batch_rounds"]]
        info.update(
            batch_tasks=batched,
            min_occupancy=min(occ),
            mean_occupancy=sum(occ) / len(occ),
            spilled=sum(t["spilled"] for t in tiers),
        )
    return info


def unified_load(
    ndev: int = 8,
    n: int = 10,
    fadds: int = 32,
    capacity: int = 1024,
    quantum: int = 32,
    window: int = 8,
    batch_width: int = 0,
) -> Dict:
    """Dependency-bearing migration + PGAS under load, one resident kernel
    per device: a skewed fib(``n``) tree (every task carrying successor
    links; stolen tasks leave home proxies, results return as remote
    completions) plus ``fadds`` remote fetch-adds hammering device 0's
    counter slot from every device. Totals exact: the fib value lands in
    the home slot, the counter equals the sum of all increments, and
    executed matches the tree + AM task count.

    ``batch_width`` > 0 routes the FIB kind through the batched same-kind
    dispatch tier inside the RESIDENT kernel (ISSUE 7): lanes spill to the
    ready ring at every sched() exit, so the homed steal export, the AM
    drains, and the termination fold only ever see ring rows; the info
    carries per-device ``tiers`` and totals stay exact."""
    from ..models.fib import fib_seq, task_count
    from ..parallel.mesh import cpu_mesh
    from .descriptor import TaskGraphBuilder
    from .megakernel import Megakernel, VBLOCK
    from .resident import ResidentKernel
    from .workloads import _fib_kernel, _sum_kernel, batch_of

    FIB5, SUM5, FADD5 = 0, 1, 2

    def fadd_k(ctx):
        ctx.pgas.fadd(0, 2, ctx.arg(0))

    mk = Megakernel(
        kernels=[("fib", _fib_kernel), ("sum", _sum_kernel),
                 ("fadd", fadd_k)],
        capacity=capacity,
        num_values=VBLOCK * capacity + 16 + capacity,
        succ_capacity=64,
        interpret=True,
        uses_row_values=True,
        route=(
            {"fib": batch_of(_fib_kernel, width=batch_width)}
            if batch_width else None
        ),
    )
    rk = ResidentKernel(
        mk, cpu_mesh(ndev, axis_name="q"),
        migratable_fns={FIB5: (), SUM5: (0, 1)},
        window=window, am_window=8,
    )
    def build(nn: int, nf: int):
        builders = [TaskGraphBuilder() for _ in range(ndev)]
        builders[0].add(FIB5, args=[nn], out=3)
        total = 0
        for i in range(nf):
            builders[i % ndev].add(FADD5, args=[i + 1])
            total += i + 1
        for b in builders:
            b.reserve_values(8)
        return builders, total

    # Warm-up on a tiny graph: same jit signature, so the timed run below
    # measures the protocol under load, not the Mosaic compile.
    wb, _ = build(2, ndev)
    rk.run(wb, quantum=quantum)
    builders, total_inc = build(n, fadds)
    t0 = time.perf_counter()
    iv, _, info = rk.run(builders, quantum=quantum)
    dt = time.perf_counter() - t0

    assert info["pending"] == 0
    assert int(np.asarray(iv)[:, 3].sum()) == fib_seq(n)
    assert int(np.asarray(iv)[0, 2]) == total_inc  # every AM landed, once
    expect = task_count(n)
    expect += (expect - 1) // 2
    expect += fadds
    assert info["executed"] == expect, (info["executed"], expect)
    per_dev = np.asarray(info["per_device_counts"])[:, 5]
    if batch_width:
        tiers = info["tiers"]
        batched = sum(t["batch_tasks"] for t in tiers)
        scalar = sum(t["scalar_tasks"] for t in tiers)
        assert batched + scalar == expect, (batched, scalar, expect)
        assert batched > 0, tiers
    info = dict(info)
    info.update(
        name=f"unified_load fib({n}) + {fadds} remote fetch-adds "
        f"on {ndev} devices"
        + (f" [batch w={batch_width}]" if batch_width else ""),
        seconds=dt,
        tasks=expect,
        tasks_per_sec=expect / dt,
        devices_used=int((per_dev > 0).sum()),
        imbalance=float(per_dev.max() * ndev / max(per_dev.sum(), 1)),
        per_device_counts=np.asarray(info["per_device_counts"]).tolist(),
    )
    return info
