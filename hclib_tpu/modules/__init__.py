"""Backend modules (the reference's modules/ layer, re-designed for TPU).

The reference extends its core runtime through dlopen'd modules that register
locale types, memory handlers, and communication backends (modules/{system,
cuda,mpi,openshmem,sos,openshmem-am,upcxx}, ~2.9 kLoC). This package rebuilds
that layer for the JAX single-controller model:

- ``common``  - pending-op completion-polling harness shared by all comm
  backends (reference: modules/common/hclib-module-common.h).
- ``system``  - host locale types + malloc-family memory handlers
  (reference: modules/system/).
- ``tpu``     - the accelerator module: TPU locales, device memory handlers,
  stream-ordered async offload (reference: modules/cuda/).
- ``comm``    - two-sided messaging + collectives between ranks
  (reference: modules/mpi/).
- ``oneside`` - symmetric heap, one-sided put/get, atomics, wait-sets,
  distributed locks, per-worker comm contexts (reference:
  modules/openshmem/ + modules/sos/).
- ``am``      - active messages: run a function on a remote rank
  (reference: modules/openshmem-am/).
- ``pgas``    - global pointers, shared arrays, dependency-chained asyncs
  (reference: modules/upcxx/).

Key re-interpretation: the reference's PE (an MPI/SHMEM process) becomes a
*rank* bound to a mesh device under JAX's single-controller model. One Python
process drives every device; "remote" data movement is a device-to-device
transfer over ICI (multi-host: DCN via jax.distributed, same addressing).
See ``world.py``.
"""

from .world import World, current_world, set_world  # noqa: F401
from .common import PendingList, PendingOp  # noqa: F401
from .system import SystemModule, get_closest_cpu_locale  # noqa: F401
from .tpu import TpuModule, get_closest_tpu_locale  # noqa: F401
from .comm import CommModule  # noqa: F401
from .oneside import DistLock, OneSidedModule, SymArray, symm_array  # noqa: F401
from .am import async_remote  # noqa: F401
from .pgas import GlobalRef, SharedArray, async_after, remote_finish  # noqa: F401
