"""Active messages: run a function on a remote rank.

Reference (modules/openshmem-am/): ``async_remote(lambda, pe)`` serializes
{fn-ptr, lambda bytes, optional user data} into an am_packet, ships it with
shmemx_am_request, and a registered handler on the target PE unpacks and
spawns it (inc/hclib_openshmem-am.h:22-64; handler src/hclib_openshmem-am.cpp:
64-123). It assumes identical binaries so raw fn pointers are valid cross-PE.

TPU-native redesign: an active message is a *task-descriptor injection into
the destination rank's queue* - under the single controller that queue is the
rank's locale deque (serviced by whichever worker's path covers it); on the
device path the same concept is a descriptor written into a remote core's HBM
ring (device/sharded.py). The payload round-trips through pickle so the
serialization contract is honest - anything shipped must survive a byte copy,
the multi-host (DCN) requirement - and the fn is resolved by qualified name
when possible (the identical-binary assumption made explicit).
"""

from __future__ import annotations

import importlib
import pickle
import threading
from typing import Any, Callable, Optional, Tuple

from ..runtime.promise import Future, Promise
from ..runtime.scheduler import current_runtime
from .world import World, current_world

__all__ = ["async_remote", "pack_am", "unpack_am"]


class _ByRef:
    """In-process function table for non-picklable payload fns."""

    _lock = threading.Lock()
    _table: dict = {}
    _next = 0

    @classmethod
    def intern(cls, fn: Callable[..., Any]) -> int:
        with cls._lock:
            cls._next += 1
            cls._table[cls._next] = fn
            return cls._next

    @classmethod
    def resolve(cls, ref: int) -> Callable[..., Any]:
        with cls._lock:
            return cls._table.pop(ref)


def pack_am(fn: Callable[..., Any], args: Tuple[Any, ...]) -> bytes:
    """Serialize the message (am_packet construction,
    modules/openshmem-am/inc/hclib_openshmem-am.h:22-49). Module-level
    functions ship by qualified name; closures/lambdas ship by value."""
    mod = getattr(fn, "__module__", None)
    qual = getattr(fn, "__qualname__", "")
    if mod and qual and "<" not in qual:
        try:
            if getattr(importlib.import_module(mod), qual, None) is fn:
                return pickle.dumps(("name", (mod, qual), args))
        except Exception:
            pass
    try:
        return pickle.dumps(("value", fn, args))
    except Exception:
        # Closures/lambdas aren't byte-copyable with stdlib pickle. Under the
        # single controller every rank shares the address space, so ship a
        # reference - the same assumption the reference makes shipping raw fn
        # pointers between identical binaries. Cross-host (DCN) AMs must use
        # module-level functions.
        ref = _ByRef.intern(fn)
        return pickle.dumps(("ref", ref, args))


def unpack_am(packet: bytes) -> Tuple[Callable[..., Any], Tuple[Any, ...]]:
    """Handler-side unpack (modules/openshmem-am/src/hclib_openshmem-am.cpp:
    64-123)."""
    kind, ref, args = pickle.loads(packet)
    if kind == "name":
        mod, qual = ref
        return getattr(importlib.import_module(mod), qual), args
    if kind == "ref":
        return _ByRef.resolve(ref), args
    return ref, args


def async_remote(
    fn: Callable[..., Any],
    rank: int,
    *args: Any,
    world: Optional[World] = None,
) -> Future:
    """Run ``fn(*args)`` at ``rank``; returns a future with the result.

    The reference's AM has no reply path (fire-and-forget); returning a
    future is the natural upgrade - completion signaling is one promise-put,
    which the reference expresses separately via shmem flag writes.
    """
    w = world if world is not None else current_world()
    w._check(rank)
    packet = pack_am(fn, args)
    p = Promise()

    def handler() -> None:
        try:
            f, a = unpack_am(packet)
            p.put(f(*a))
        except BaseException as e:
            p.poison(e)

    # Injection: spawn at the destination rank's locale; escaping, because a
    # remote task's lifetime belongs to the target, not the sender's finish
    # scope (the reference's AMs are likewise untracked by the sender).
    current_runtime().spawn(handler, locale=w.locale_for(rank), escaping=True)
    return p.future
