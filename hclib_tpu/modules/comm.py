"""Two-sided messaging + collectives between ranks (the mpi module's role).

Reference (modules/mpi/src/hclib_mpi.cpp): registers an Interconnect locale
marked special "COMM" (:55-93); blocking Send/Recv are ``finish { async_nb_at
(nic) }`` (:107-128); Isend/Irecv return futures through the pending-op list
with MPI_Test polling (:130-210); collectives are blocking tasks at the NIC
locale (:220-286).

TPU-native redesign: ranks live in one controller process (world.py), so the
transport is a tagged in-process mailbox table, with the *data path* going
device-to-device (ICI) whenever both endpoints are device-bound - a send
commits its payload to the destination rank's device before the message is
visible, exactly the part MPI would do over the wire. Collectives on
device-bound payloads execute as one fused XLA op over the per-rank arrays
(single-controller collapses the N-process rendezvous); multi-host DCN rides
jax.distributed, under which jax.devices() spans hosts and device_put crosses
DCN with the same addressing.

All ops are issued at the COMM locale, so comm/compute overlap works the way
the reference's does: any worker whose pop/steal path covers the COMM locale
services messaging while others compute.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..runtime.locality import Locale
from ..runtime.module import Module
from ..runtime.promise import Future, Promise
from ..runtime.scheduler import async_, finish
from .common import PendingList, PendingOp
from .world import World, current_world

__all__ = [
    "CommModule",
    "comm_rank_count",
    "comm_locale",
    "send",
    "recv",
    "isend",
    "irecv",
    "wait_all",
    "barrier",
    "broadcast",
    "reduce",
    "allreduce",
    "gather",
    "allgather",
    "scatter",
    "alltoall",
]

ANY_SOURCE = -1


class _Mailboxes:
    """Tag-matched message queues, one table per world."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (dst, src, tag) -> list of payloads, FIFO per key (MPI ordering).
        self._queues: Dict[Tuple[int, int, int], List[Any]] = {}

    def deposit(self, dst: int, src: int, tag: int, payload: Any) -> None:
        with self._lock:
            self._queues.setdefault((dst, src, tag), []).append(payload)

    def try_take(self, dst: int, src: int, tag: int) -> Tuple[bool, Any, int]:
        """Returns (found, payload, actual_src); src may be ANY_SOURCE."""
        with self._lock:
            if src == ANY_SOURCE:
                for (d, s, t), q in self._queues.items():
                    if d == dst and t == tag and q:
                        return True, q.pop(0), s
                return False, None, -1
            q = self._queues.get((dst, src, tag))
            if q:
                return True, q.pop(0), src
            return False, None, -1


class CommModule(Module):
    """Owns the COMM locale, mailbox table, and pending-op poller.

    The reference requires exactly one Interconnect locale and marks it
    special "COMM" (modules/mpi/src/hclib_mpi.cpp:55-93); here any graph
    works - an ``ici`` locale is used when present, else the central locale.
    """

    name = "comm"

    def __init__(self, world: Optional[World] = None) -> None:
        self._world = world
        self.locale: Optional[Locale] = None
        self.mail = _Mailboxes()
        self.pending = PendingList()

    def pre_init(self, runtime) -> None:
        ici = runtime.graph.locales_of_type("ici")
        self.locale = ici[0] if ici else runtime.graph.central_locale()
        self.locale.mark_special("COMM")
        self.pending.locale = self.locale

    def world(self) -> World:
        return self._world if self._world is not None else current_world()


def _active() -> CommModule:
    from ..runtime.module import registered_modules

    for m in registered_modules():
        if isinstance(m, CommModule):
            return m
    raise RuntimeError("no CommModule registered")


def comm_rank_count() -> int:
    return _active().world().size


def comm_locale() -> Locale:
    loc = _active().locale
    assert loc is not None, "CommModule used before runtime pre-init"
    return loc


def _commit_to_rank(payload: Any, rank: int) -> Any:
    """Data path: commit the payload to the destination rank's device
    (the ICI/DCN hop; host-only ranks keep a host copy)."""
    dev = _active().world().device_for(rank)
    if dev is not None and (isinstance(payload, np.ndarray) or _is_jax(payload)):
        import jax

        return jax.device_put(payload, dev)
    return payload


def _batch_commit(values: Sequence[Any], rank: int) -> List[Any]:
    """Commit many HOST-resident values to one rank's device as ONE
    stacked transfer (instead of one device_put per element - a w-element
    gather from host is one hop, not w): the returned entries are views of
    the stacked device array. Device-resident, mixed-shape, or non-array
    payloads fall back to the per-element path (a gather of values already
    spread over w devices is w hops whichever way it is expressed)."""
    w = _active().world()
    dev = w.device_for(rank)
    host_arrays = all(isinstance(v, np.ndarray) for v in values)
    if dev is not None and host_arrays and len(values) > 1:
        import jax

        shapes = {(v.shape, v.dtype) for v in values}
        if len(shapes) == 1:
            # Stack on the HOST, then one device_put: truly a single
            # hop (jnp.stack would first commit to the default device).
            stacked = jax.device_put(np.stack(list(values)), dev)
            return list(stacked)
    return [_commit_to_rank(v, rank) for v in values]


def _is_jax(x: Any) -> bool:
    try:
        import jax

        return isinstance(x, jax.Array)
    except Exception:
        return False


# ------------------------------------------------------------- point-to-point


def isend(payload: Any, dst: int, tag: int = 0, src: Optional[int] = None) -> Future:
    """Nonblocking send; the future is satisfied once the payload is
    committed at the destination (MPI_Isend shape,
    modules/mpi/src/hclib_mpi.cpp:151-180)."""
    mod = _active()
    mod.world()._check(dst)
    p = Promise()
    s = -1 if src is None else src

    def issue() -> None:
        placed = _commit_to_rank(payload, dst)
        leaves = [placed] if _is_jax(placed) else []

        def done(op: PendingOp) -> Tuple[bool, Any]:
            if all(l.is_ready() for l in leaves):
                mod.mail.deposit(dst, s, tag, placed)
                return True, None
            return False, None

        mod.pending.append(PendingOp(done, promise=p))

    async_(issue, at=mod.locale, non_blocking=True, escaping=True)
    return p.future


def irecv(src: int = ANY_SOURCE, tag: int = 0, *, rank: int = 0) -> Future:
    """Nonblocking receive; future satisfied with the payload
    (MPI_Irecv -> pending-op poll, modules/mpi/src/hclib_mpi.cpp:130-149)."""
    mod = _active()
    p = Promise()

    def match(op: PendingOp) -> Tuple[bool, Any]:
        found, payload, _ = mod.mail.try_take(rank, src, tag)
        if found:
            return True, payload
        return False, None

    def issue() -> None:
        mod.pending.append(PendingOp(match, promise=p))

    async_(issue, at=mod.locale, non_blocking=True, escaping=True)
    return p.future


def send(payload: Any, dst: int, tag: int = 0, src: Optional[int] = None) -> None:
    """Blocking send = finish { nonblocking op at COMM locale }
    (modules/mpi/src/hclib_mpi.cpp:107-117)."""
    isend(payload, dst, tag, src).wait()


def recv(src: int = ANY_SOURCE, tag: int = 0, *, rank: int = 0) -> Any:
    return irecv(src, tag, rank=rank).wait()


def wait_all(futures: Sequence[Future]) -> List[Any]:
    """MPI_Waitall = wait each future (modules/mpi/src/hclib_mpi.cpp:143-149)."""
    return [f.wait() for f in futures]


# --------------------------------------------------------------- collectives
#
# Single-controller collapses the N-process rendezvous: a collective is one
# task at the COMM locale transforming the per-rank value list. Device-bound
# payloads batch into a single stacked XLA op (the on-TPU execution of these
# patterns inside jitted step functions is parallel/collectives.py - psum &
# friends over a mesh axis; this host-level API is the task-runtime face).


def _collective(fn: Callable[[], Any]) -> Any:
    mod = _active()
    out: List[Any] = [None]

    def body() -> None:
        out[0] = fn()

    with finish():
        async_(body, at=mod.locale, non_blocking=True)
    return out[0]


def barrier() -> None:
    """MPI_Barrier (modules/mpi/src/hclib_mpi.cpp:220-227): a task at the
    COMM locale that drains after all previously issued comm ops."""
    _collective(lambda: None)


def broadcast(value: Any, root: int = 0) -> List[Any]:
    """Returns one copy per rank, committed to each rank's device
    (MPI_Bcast, modules/mpi/src/hclib_mpi.cpp:229-244)."""
    w = _active().world()

    def run() -> List[Any]:
        return [_commit_to_rank(value, r) for r in range(w.size)]

    return _collective(run)


def reduce(values: Sequence[Any], op: Callable = np.add, root: int = 0) -> Any:
    """Reduce per-rank values to the root rank (MPI_Reduce)."""
    w = _active().world()
    if len(values) != w.size:
        raise ValueError(f"need one value per rank ({w.size}), got {len(values)}")

    def run() -> Any:
        acc = _stack_reduce(values, op)
        return _commit_to_rank(acc, root)

    return _collective(run)


def allreduce(values: Sequence[Any], op: Callable = np.add) -> List[Any]:
    """MPI_Allreduce (modules/mpi/src/hclib_mpi.cpp:246-262)."""
    w = _active().world()
    if len(values) != w.size:
        raise ValueError(f"need one value per rank ({w.size}), got {len(values)}")

    def run() -> List[Any]:
        acc = _stack_reduce(values, op)
        return [_commit_to_rank(acc, r) for r in range(w.size)]

    return _collective(run)


def _stack_reduce(values: Sequence[Any], op: Callable) -> Any:
    if any(_is_jax(v) for v in values):
        import jax
        import jax.numpy as jnp

        # Operands may be committed to different devices; gather them onto
        # one (the ICI hop) before the fused reduce.
        dev = None
        for v in values:
            if _is_jax(v):
                dev = list(v.devices())[0]
                break
        stacked = jnp.stack([jax.device_put(jnp.asarray(v), dev) for v in values])
        if op is np.add:
            return jnp.sum(stacked, axis=0)
        if op is np.maximum:
            return jnp.max(stacked, axis=0)
        if op is np.minimum:
            return jnp.min(stacked, axis=0)
        acc = stacked[0]
        for i in range(1, stacked.shape[0]):
            acc = op(acc, stacked[i])
        return acc
    acc = values[0]
    for v in values[1:]:
        acc = op(acc, v)
    return acc


def gather(values: Sequence[Any], root: int = 0) -> List[Any]:
    """MPI_Gather: one value per rank lands on root (one stacked transfer,
    not one per element)."""
    w = _active().world()
    if len(values) != w.size:
        raise ValueError(f"need one value per rank ({w.size}), got {len(values)}")
    return _collective(lambda: _batch_commit(values, root))


def allgather(values: Sequence[Any]) -> List[List[Any]]:
    """MPI_Allgather: every rank gets the full list (one stacked transfer
    per destination rank)."""
    w = _active().world()
    if len(values) != w.size:
        raise ValueError(f"need one value per rank ({w.size}), got {len(values)}")

    def run() -> List[List[Any]]:
        return [_batch_commit(values, r) for r in range(w.size)]

    return _collective(run)


def scatter(values: Sequence[Any], root: int = 0) -> List[Any]:
    w = _active().world()
    if len(values) != w.size:
        raise ValueError(f"need one value per rank ({w.size}), got {len(values)}")
    return _collective(lambda: [_commit_to_rank(v, r) for r, v in enumerate(values)])


def alltoall(matrix: Sequence[Sequence[Any]]) -> List[List[Any]]:
    """matrix[src][dst] -> out[dst][src], each destination's column
    committed as one stacked transfer (w hops total, not w^2)."""
    w = _active().world()
    if len(matrix) != w.size or any(len(row) != w.size for row in matrix):
        raise ValueError(f"need a {w.size}x{w.size} matrix")

    def run() -> List[List[Any]]:
        return [
            _batch_commit([matrix[s][d] for s in range(w.size)], d)
            for d in range(w.size)
        ]

    return _collective(run)
