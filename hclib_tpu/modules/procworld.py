"""Cross-process ranks over the jax.distributed coordination service.

The reference's comm modules span real OS processes launched by mpirun
(modules/mpi/src/hclib_mpi.cpp:107-286 two-sided + collectives;
modules/openshmem symmetric heap put/get; modules/openshmem-am active
messages, hclib_openshmem-am.cpp:64-123). The in-process ``World``
(modules/world.py) gives rank semantics inside one controller; this module
is the *multi-controller* counterpart: every rank is a separate process
wired by ``jax.distributed.initialize``, and the transport is the JAX
coordination service (key-value store + named barriers) that the
multi-controller runtime already establishes over DCN.

Design mapping (reference -> here):

- MPI_Send/Recv            -> ordered KV messages (per (src, dst, tag)
                              sequence numbers; receiver deletes after take)
- MPI_Allreduce/Barrier    -> epoch-keyed contributions + local reduce;
                              coordination-service named barriers
- SHMEM symmetric heap     -> same-named numpy arrays allocated collectively
                              in every process; put/get are *op records*
                              addressed to the owner
- SHMEM progress engine    -> a daemon progress thread per process polling
                              its op directory and applying puts / serving
                              gets / running AM handlers in arrival order -
                              the reference's NIC-locale poller
                              (modules/common/hclib-module-common.h:10-115)
                              as a thread instead of a pinned worker
- shmem_quiet / fence      -> a no-op op with a reply key: when the owner's
                              progress thread reaches it, every earlier op
                              from this rank has been applied (ops apply in
                              global sequence order)
- async_remote (AM)        -> op records naming a registered handler
                              (handlers must be registered in every process,
                              mirroring the reference's identical-binary
                              assumption)

The KV store is a control-plane transport: fine for task descriptors,
small tensors, and coordination; bulk tensors should ride XLA collectives
over a global mesh (parallel/multihost.py) - the same split the reference
makes between AM packets and bulk MPI datatypes.
"""

from __future__ import annotations

import io
import json
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

__all__ = ["ProcWorld"]


def _pack(meta: dict, arr: Optional[np.ndarray]) -> bytes:
    """4-byte length + JSON metadata + optional .npy payload."""
    m = json.dumps(meta).encode()
    buf = io.BytesIO()
    if arr is not None:
        np.save(buf, arr, allow_pickle=False)
    return struct.pack("<I", len(m)) + m + buf.getvalue()


def _unpack(b: bytes) -> Tuple[dict, Optional[np.ndarray]]:
    (mlen,) = struct.unpack("<I", b[:4])
    meta = json.loads(b[4 : 4 + mlen].decode())
    rest = b[4 + mlen :]
    arr = np.load(io.BytesIO(rest), allow_pickle=False) if rest else None
    return meta, arr


class ProcWorld:
    """Rank-per-process communication world (requires an initialized
    jax.distributed runtime; see parallel/multihost.init_multihost).

    All collective entry points (``barrier``, ``allreduce``, ``alloc``)
    follow SPMD discipline: every process calls them in the same order.
    """

    def __init__(
        self,
        namespace: str = "hcpw",
        poll_interval_s: float = 0.002,
        timeout_s: float = 60.0,
    ) -> None:
        import jax
        from jax._src import distributed

        if not jax.distributed.is_initialized():
            raise RuntimeError(
                "ProcWorld needs jax.distributed initialized "
                "(parallel.multihost.init_multihost)"
            )
        self._c = distributed.global_state.client
        self.rank = jax.process_index()
        self.size = jax.process_count()
        self._ns = namespace
        self._timeout_ms = int(timeout_s * 1000)
        self._poll_s = poll_interval_s
        # Guards the sequence/reply counters: AM handlers run on the
        # progress thread and receive this world, so send/get/fence may be
        # called concurrently with the application thread.
        self._seq_lock = threading.Lock()
        self._send_seq: Dict[Tuple[int, int], int] = {}
        self._recv_seq: Dict[Tuple[int, int], int] = {}
        self._barrier_n = 0
        self._ar_epoch = 0
        self._reply_n = 0
        self._heap: Dict[str, np.ndarray] = {}
        self._heap_lock = threading.Lock()
        self._handlers: Dict[str, Callable] = {}
        self._applied = 0  # ops applied by the progress thread, in order
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._progress_loop, daemon=True,
            name=f"procworld-progress-{self.rank}",
        )
        self._thread.start()

    # ---- two-sided messaging (hclib_mpi.cpp:107-128) ----

    def send(self, dst: int, arr, tag: int = 0) -> None:
        """Ordered per (src, dst, tag); non-blocking (KV deposit)."""
        arr = np.asarray(arr)
        with self._seq_lock:
            seq = self._send_seq.get((dst, tag), 0)
            self._send_seq[(dst, tag)] = seq + 1
        key = f"{self._ns}/msg/{self.rank}/{dst}/{tag}/{seq}"
        self._c.key_value_set_bytes(key, _pack({}, arr))

    def recv(self, src: int, tag: int = 0) -> np.ndarray:
        """Blocks for the next in-order message from (src, tag)."""
        with self._seq_lock:
            seq = self._recv_seq.get((src, tag), 0)
            self._recv_seq[(src, tag)] = seq + 1
        key = f"{self._ns}/msg/{src}/{self.rank}/{tag}/{seq}"
        b = self._c.blocking_key_value_get_bytes(key, self._timeout_ms)
        self._c.key_value_delete(key)
        _, arr = _unpack(b)
        return arr

    # ---- collectives (hclib_mpi.cpp:220-286) ----

    def barrier(self) -> None:
        self._barrier_n += 1
        self._c.wait_at_barrier(
            f"{self._ns}/b/{self._barrier_n}", self._timeout_ms
        )

    def allreduce(self, arr, op: str = "sum") -> np.ndarray:
        """Contribution exchange through the KV store + local reduce (the
        data path for bulk arrays is XLA collectives over a global mesh;
        this is the control-plane reduce for scalars/small tensors)."""
        arr = np.asarray(arr)
        self._ar_epoch += 1
        e = self._ar_epoch
        mine = f"{self._ns}/ar/{e}/{self.rank}"
        self._c.key_value_set_bytes(mine, _pack({}, arr))
        parts = []
        for r in range(self.size):
            b = self._c.blocking_key_value_get_bytes(
                f"{self._ns}/ar/{e}/{r}", self._timeout_ms
            )
            parts.append(_unpack(b)[1])
        self.barrier()  # everyone has read: contributions deletable
        self._c.key_value_delete(mine)
        fn = {
            "sum": np.sum, "max": np.max, "min": np.min, "prod": np.prod,
        }[op]
        return fn(np.stack(parts), axis=0)

    # ---- symmetric heap + one-sided ops (modules/openshmem) ----

    def alloc(self, name: str, shape, dtype=np.int32) -> np.ndarray:
        """Collective: allocate the same-named array in every process (the
        symmetric-heap contract; SPMD call order required)."""
        with self._heap_lock:
            if name in self._heap:
                raise ValueError(f"heap array {name!r} exists")
            a = np.zeros(shape, dtype)
            self._heap[name] = a
        self.barrier()
        return a

    def heap(self, name: str) -> np.ndarray:
        return self._heap[name]

    def _post_op(self, dst: int, meta: dict, arr=None) -> None:
        if dst == self.rank:
            self._apply(meta, arr)  # loopback: apply inline
            return
        # Global per-target sequencing: increment-then-set; the target's
        # progress thread applies strictly in sequence order, so a visible
        # gap (incremented but not yet set) just parks the queue briefly.
        seq = self._c.key_value_increment(f"{self._ns}/opseq/{dst}", 1) - 1
        self._c.key_value_set_bytes(
            f"{self._ns}/op/{dst}/{seq}", _pack(meta, arr)
        )

    def put(self, dst: int, name: str, arr, offset: int = 0) -> None:
        """One-sided write into rank ``dst``'s heap array (applied by its
        progress thread; order vs other ops from this rank preserved).
        Completion at the target is observable via fence()/barrier()."""
        self._post_op(
            dst, {"op": "put", "name": name, "off": int(offset)},
            np.asarray(arr),
        )

    def get(self, src: int, name: str, offset: int = 0,
            size: Optional[int] = None) -> np.ndarray:
        """One-sided read of rank ``src``'s heap array (served by its
        progress thread; sequenced after this rank's earlier ops to src)."""
        with self._seq_lock:
            self._reply_n += 1
            rk = f"{self._ns}/re/{self.rank}/{self._reply_n}"
        self._post_op(
            src,
            {"op": "get", "name": name, "off": int(offset),
             "size": -1 if size is None else int(size), "reply": rk},
        )
        b = self._c.blocking_key_value_get_bytes(rk, self._timeout_ms)
        self._c.key_value_delete(rk)
        return _unpack(b)[1]

    def fence(self, dst: int) -> None:
        """Returns once every op this rank posted to ``dst`` has been
        applied (shmem_quiet for one target: a no-op op with a reply)."""
        if dst == self.rank:
            return
        with self._seq_lock:
            self._reply_n += 1
            rk = f"{self._ns}/re/{self.rank}/{self._reply_n}"
        self._post_op(dst, {"op": "fence", "reply": rk})
        self._c.blocking_key_value_get_bytes(rk, self._timeout_ms)
        self._c.key_value_delete(rk)

    def quiet(self) -> None:
        """shmem_quiet: fence every target this rank has posted ops to."""
        for r in range(self.size):
            self.fence(r)

    # ---- active messages (hclib_openshmem-am.cpp:64-123) ----

    def register_handler(self, name: str, fn: Callable) -> None:
        """AM handlers are named (not function pointers): every process
        registers the same names - the portable form of the reference's
        identical-binary fn-pointer assumption."""
        self._handlers[name] = fn

    def am(self, dst: int, handler: str, arr=None, **kwargs) -> None:
        """Run the named handler on rank ``dst``'s progress thread with
        (world, payload_array, **kwargs)."""
        self._post_op(
            dst, {"op": "am", "h": handler, "kw": kwargs},
            None if arr is None else np.asarray(arr),
        )

    # ---- progress engine ----

    def _apply(self, meta: dict, arr) -> None:
        op = meta["op"]
        if op == "put":
            with self._heap_lock:
                a = self._heap[meta["name"]]
                flat = a.reshape(-1)
                v = arr.astype(a.dtype, copy=False).reshape(-1)
                flat[meta["off"] : meta["off"] + v.size] = v
        elif op == "get":
            with self._heap_lock:
                a = self._heap[meta["name"]].reshape(-1)
                off = meta["off"]
                end = a.size if meta["size"] < 0 else off + meta["size"]
                out = a[off:end].copy()
            self._c.key_value_set_bytes(meta["reply"], _pack({}, out))
        elif op == "fence":
            self._c.key_value_set_bytes(meta["reply"], _pack({}, None))
        elif op == "am":
            self._handlers[meta["h"]](self, arr, **meta.get("kw", {}))
        else:  # pragma: no cover
            raise ValueError(f"unknown op {op!r}")

    def _progress_loop(self) -> None:
        me = self.rank
        while not self._stop.is_set():
            key = f"{self._ns}/op/{me}/{self._applied}"
            try:
                b = self._c.key_value_try_get_bytes(key)
            except Exception as e:
                # Absent keys surface as NOT_FOUND JaxRuntimeErrors; any
                # OTHER failure means the coordination service / client is
                # gone - stop the engine loudly instead of spinning while
                # every pending fence/get runs out its timeout silently.
                if "NOT_FOUND" in str(e):
                    b = None
                else:  # pragma: no cover - requires killing the service
                    import traceback

                    print(
                        f"procworld rank {me}: progress engine died:",
                        flush=True,
                    )
                    traceback.print_exc()
                    return
            if b is None:
                time.sleep(self._poll_s)
                continue
            meta, arr = _unpack(b)
            self._c.key_value_delete(key)
            self._applied += 1
            try:
                self._apply(meta, arr)
            except Exception:  # pragma: no cover - keep the engine alive
                import traceback

                traceback.print_exc()

    def close(self) -> None:
        """Stop the progress engine (pending remote ops stay queued in the
        coordination service; call quiet() first for a clean drain)."""
        self._stop.set()
        self._thread.join(timeout=5)
