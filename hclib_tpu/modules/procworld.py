"""Cross-process ranks over the jax.distributed coordination service.

The reference's comm modules span real OS processes launched by mpirun
(modules/mpi/src/hclib_mpi.cpp:107-286 two-sided + collectives;
modules/openshmem symmetric heap put/get; modules/openshmem-am active
messages, hclib_openshmem-am.cpp:64-123). The in-process ``World``
(modules/world.py) gives rank semantics inside one controller; this module
is the *multi-controller* counterpart: every rank is a separate process
wired by ``jax.distributed.initialize``, and the transport is the JAX
coordination service (key-value store + named barriers) that the
multi-controller runtime already establishes over DCN.

Design mapping (reference -> here):

- MPI_Send/Recv            -> ordered KV messages (per (src, dst, tag)
                              sequence numbers; receiver deletes after take)
- MPI_Isend/Irecv          -> future-returning ops polled by the COMM-locale
                              pending-op poller (``ProcWorldModule``), the
                              reference's hclib_mpi.cpp:130-210 shape
- MPI_Allreduce/Barrier    -> recursive-doubling exchange through the KV
                              store (O(n log n) messages); coordination-
                              service named barriers
- SHMEM symmetric heap     -> same-named numpy arrays allocated collectively
                              in every process; put/get are *op records*
                              addressed to the owner
- SHMEM progress engine    -> a daemon progress thread per process polling
                              its op directory and applying puts / serving
                              gets / running AM handlers in arrival order -
                              the reference's NIC-locale poller
                              (modules/common/hclib-module-common.h:10-115)
                              as a thread instead of a pinned worker
- shmem_quiet / fence      -> a no-op op with a reply key: when the owner's
                              progress thread reaches it, every earlier op
                              from this rank has been applied (ops apply in
                              global sequence order)
- async_remote (AM)        -> op records naming a registered handler
                              (handlers must be registered in every process,
                              mirroring the reference's identical-binary
                              assumption)

Failure model: coordination-service RPCs are classified by gRPC status code
(the leading token of the error string - jaxlib exposes no code attribute).
NOT_FOUND means "key absent"; UNAVAILABLE/ABORTED/etc. are transient and the
progress engine retries them with backoff for up to ``timeout_s`` before
declaring the engine dead. A dying engine best-effort *poisons* the reply
key of every op still queued at this rank and publishes a tombstone, so
peers blocked on a reply fail fast with ``ProcWorldError`` instead of
running out their own timeouts (the reference simply aborts the job;
multi-controller JAX deserves a diagnosable failure).

The KV store is a control-plane transport: fine for task descriptors,
small tensors, and coordination; bulk tensors ride XLA collectives over a
global mesh (``allreduce`` dispatches to ``parallel/multihost.py`` above a
size threshold) - the same split the reference makes between AM packets and
bulk MPI datatypes.
"""

from __future__ import annotations

import io
import json
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

# A backend that cannot run multiprocess computations fails LOCALLY at
# dispatch, identically on every rank of a committed collective - the one
# failure class where a joint fallback is safe (see allreduce). Anything
# raised mid-collective stays fatal.
from ..jaxcompat import (
    is_multiprocess_capability_error as _bulk_capability_error,
)
from ..runtime.module import Module

__all__ = ["ProcWorld", "ProcWorldError", "ProcWorldModule"]


def _pack(meta: dict, arr: Optional[np.ndarray]) -> bytes:
    """4-byte length + JSON metadata + optional .npy payload."""
    m = json.dumps(meta).encode()
    buf = io.BytesIO()
    if arr is not None:
        np.save(buf, arr, allow_pickle=False)
    return struct.pack("<I", len(m)) + m + buf.getvalue()


def _unpack(b: bytes) -> Tuple[dict, Optional[np.ndarray]]:
    (mlen,) = struct.unpack("<I", b[:4])
    meta = json.loads(b[4 : 4 + mlen].decode())
    rest = b[4 + mlen :]
    arr = np.load(io.BytesIO(rest), allow_pickle=False) if rest else None
    return meta, arr


class ProcWorldError(RuntimeError):
    """A peer's (or this rank's) progress engine died, or an op was
    poisoned during engine shutdown."""


# gRPC status names, as they lead JaxRuntimeError strings ("NOT_FOUND: ...").
_GRPC_STATUSES = {
    "OK", "CANCELLED", "UNKNOWN", "INVALID_ARGUMENT", "DEADLINE_EXCEEDED",
    "NOT_FOUND", "ALREADY_EXISTS", "PERMISSION_DENIED", "RESOURCE_EXHAUSTED",
    "FAILED_PRECONDITION", "ABORTED", "OUT_OF_RANGE", "UNIMPLEMENTED",
    "INTERNAL", "UNAVAILABLE", "DATA_LOSS", "UNAUTHENTICATED",
}
# Worth retrying: the service may be mid-(re)start, a stream may have been
# torn down, or the RPC raced a barrier epoch. Everything else is a
# programming error or a hard disconnect.
_TRANSIENT = {"UNAVAILABLE", "ABORTED", "CANCELLED", "UNKNOWN", "INTERNAL",
              "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED"}


def _status(e: BaseException) -> str:
    """gRPC status code of a coordination-service error (by leading token,
    not substring - 'NOT_FOUND' can legitimately appear inside unrelated
    messages)."""
    head = str(e).split(":", 1)[0].strip()
    return head if head in _GRPC_STATUSES else "UNKNOWN"


class _ClientCompat:
    """Adapter for older ``DistributedRuntimeClient`` builds (jaxlib
    0.4.x) that lack ``key_value_try_get_bytes``: emulated with a
    non-blocking parent-directory listing (one RPC; a blocking-get
    emulation measured orders slower under progress-loop polling). Every
    other method proxies through unchanged. (The op queue itself needs no
    atomic increment on any build - per-source sequencing, see
    ``_post_op``.)

    Known limit: a directory listing transfers its VALUES, so probing a
    deep per-source op backlog re-downloads queued payloads - O(backlog)
    bytes per idle probe on these legacy builds. A hint-key protocol was
    tried and reverted: these clients' ``key_value_set`` is INSERT-only
    (ALREADY_EXISTS on overwrite), so no cheap mutable counter exists.
    The progress loop drains each source to its first miss, which keeps
    probes per APPLIED op at one; only sustained deep backlogs on 0.4.x
    pay the listing cost."""

    __slots__ = ("_c",)

    def __init__(self, c) -> None:
        self._c = c

    def __getattr__(self, name):
        return getattr(self._c, name)

    def key_value_try_get_bytes(self, key):
        parent = key.rsplit("/", 1)[0] + "/"
        for k, v in self._c.key_value_dir_get_bytes(parent):
            if k == key:
                return v
        raise RuntimeError(f"NOT_FOUND: {key} (dir-scan emulation)")


def _adapt_client(c):
    return c if hasattr(c, "key_value_try_get_bytes") else _ClientCompat(c)




class ProcWorld:
    """Rank-per-process communication world (requires an initialized
    jax.distributed runtime; see parallel/multihost.init_multihost).

    All collective entry points (``barrier``, ``allreduce``, ``alloc``)
    follow SPMD discipline: every process calls them in the same order.
    """

    #: payload bytes above which allreduce rides XLA collectives over the
    #: global device mesh instead of the KV control plane (see allreduce).
    BULK_THRESHOLD = 1 << 16

    def __init__(
        self,
        namespace: str = "hcpw",
        poll_interval_s: float = 0.002,
        timeout_s: float = 60.0,
        retry_s: Optional[float] = None,
        fault_plan=None,
        _client=None,
        _rank: Optional[int] = None,
        _size: Optional[int] = None,
    ) -> None:
        if _client is not None:
            # Test seam: a fake coordination client (threads as ranks) so
            # engine failure paths are unit-testable in one process - the
            # reference's comm modules have no such seam and are untestable
            # without a cluster (SURVEY §4 'do better').
            self._c = _client
            self.rank = int(_rank or 0)
            self.size = int(_size or 1)
            self._native_runtime = False
        else:
            import jax
            from jax._src import distributed

            from ..jaxcompat import distributed_is_initialized

            if not distributed_is_initialized():
                raise RuntimeError(
                    "ProcWorld needs jax.distributed initialized "
                    "(parallel.multihost.init_multihost)"
                )
            self._c = _adapt_client(distributed.global_state.client)
            self.rank = jax.process_index()
            self.size = jax.process_count()
            self._native_runtime = True
        self._ns = namespace
        self._timeout_ms = int(timeout_s * 1000)
        self._timeout_s = timeout_s
        self._retry_s = timeout_s if retry_s is None else retry_s
        self._poll_s = poll_interval_s
        # Guards the sequence/reply counters: AM handlers run on the
        # progress thread and receive this world, so send/get/fence may be
        # called concurrently with the application thread.
        self._seq_lock = threading.Lock()
        self._send_seq: Dict[Tuple[int, int], int] = {}
        self._recv_seq: Dict[Tuple[int, int], int] = {}
        self._barrier_n = 0
        self._ar_epoch = 0
        self._reply_n = 0
        self._heap: Dict[str, np.ndarray] = {}
        self._heap_lock = threading.Lock()
        self._handlers: Dict[str, Callable] = {}
        self._applied = 0  # total ops applied by the progress thread
        # Per-source op cursors: the op queue is sequenced per (src, dst)
        # stream (see _post_op), so the consumer tracks one dense cursor
        # per source and the producer needs no service-side increment.
        self._op_seq: Dict[int, int] = {}
        self._applied_src = [0] * self.size
        self._bulk_broken: Optional[str] = None  # see _bulk_usable
        # Chaos (runtime/resilience.FaultPlan): may kill this rank's
        # progress engine on cue, exercising tombstones + reply poisoning.
        self._fault_plan = fault_plan
        self._stop = threading.Event()
        self._dead: Optional[BaseException] = None
        self.last_allreduce_path: Optional[str] = None
        self._thread = threading.Thread(
            target=self._progress_loop, daemon=True,
            name=f"procworld-progress-{self.rank}",
        )
        self._thread.start()

    # ---- health ----

    @property
    def dead(self) -> Optional[BaseException]:
        """The error that killed this rank's progress engine, if any."""
        return self._dead

    def _check_alive(self) -> None:
        if self._dead is not None:
            raise ProcWorldError(
                f"rank {self.rank}: progress engine is dead"
            ) from self._dead

    def _tomb_key(self, rank: int) -> str:
        return f"{self._ns}/dead/{rank}"

    def _peer_dead(self, rank: int) -> Optional[str]:
        """Tombstone text if ``rank``'s progress engine died, else None
        (also None when the service is unreachable: the caller's own wait
        loop decides what a dead service means for it)."""
        try:
            b = self._c.key_value_try_get_bytes(self._tomb_key(rank))
        except Exception:
            return None
        return b.decode(errors="replace") if b is not None else None

    def _raise_if_peer_dead(self, rank: int, context: str = "") -> None:
        """The ONE tombstone protocol for every wait loop (recv/_await_key,
        barrier, allreduce, module futures): raise ProcWorldError when this
        rank's own engine died, or when ``rank`` published a tombstone -
        never leave a waiter to run out its full timeout against a peer
        that is already known dead."""
        self._check_alive()
        if rank == self.rank:
            return
        tomb = self._peer_dead(rank)
        if tomb is not None:
            raise ProcWorldError(
                f"rank {rank}'s progress engine died{context}: {tomb}"
            )

    # ---- reply-key plumbing ----

    def _new_reply_key(self) -> str:
        with self._seq_lock:
            self._reply_n += 1
            return f"{self._ns}/re/{self.rank}/{self._reply_n}"

    def _try_take(self, key: str):
        """Non-blocking take of any protocol key: (found, payload array);
        deletes the key on take. Transient service errors read as
        not-found (the caller's poll loop retries); a poisoned payload
        (deposited by a dying peer) raises ProcWorldError."""
        try:
            b = self._c.key_value_try_get_bytes(key)
        except Exception as e:
            st = _status(e)
            if st == "NOT_FOUND" or st in _TRANSIENT:
                return False, None
            raise
        if b is None:
            return False, None
        self._c.key_value_delete(key)
        meta, arr = _unpack(b)
        if "poisoned" in meta:
            raise ProcWorldError(
                f"op poisoned by dying peer: {meta['poisoned']}"
            )
        return True, arr

    # The module poller and the blocking waits share one take protocol.
    _try_reply = _try_take

    def _await_key(self, key: str, target: int) -> Optional[np.ndarray]:
        """Block for a protocol key, failing fast if the target rank's
        engine (or our own) published a tombstone instead of ever
        depositing it, or if a dying peer poisoned it."""
        deadline = time.monotonic() + self._timeout_s
        chunk_ms = min(2000, self._timeout_ms)
        while True:
            self._check_alive()
            try:
                # Try the key FIRST: a reply the peer deposited before
                # dying is valid (only unapplied ops get poisoned) and
                # must win over its tombstone. The tombstone is consulted
                # when a chunk comes back empty/transient, so a dead peer
                # still surfaces within one chunk.
                b = self._c.blocking_key_value_get_bytes(key, chunk_ms)
            except Exception as e:
                st = _status(e)
                if st not in _TRANSIENT:
                    raise
                try:
                    self._raise_if_peer_dead(
                        target, context=f"; op {key} will never complete"
                    )
                except ProcWorldError as pe:
                    raise pe from e
                if time.monotonic() >= deadline:
                    raise
                continue
            self._c.key_value_delete(key)
            meta, arr = _unpack(b)
            if "poisoned" in meta:
                raise ProcWorldError(
                    f"op poisoned by dying peer: {meta['poisoned']}"
                )
            return arr

    _await_reply = _await_key

    # ---- two-sided messaging (hclib_mpi.cpp:107-128) ----

    def _next_send_key(self, dst: int, tag: int) -> str:
        """Claim the next (dst, tag) sequence slot. Message order is
        defined by this claim (program order), not by deposit time - which
        lets isend defer the deposit to the COMM-locale poller."""
        with self._seq_lock:
            seq = self._send_seq.get((dst, tag), 0)
            self._send_seq[(dst, tag)] = seq + 1
        return f"{self._ns}/msg/{self.rank}/{dst}/{tag}/{seq}"

    def _deposit(self, key: str, arr: np.ndarray) -> None:
        self._c.key_value_set_bytes(key, _pack({}, arr))

    def send(self, dst: int, arr, tag: int = 0) -> None:
        """Ordered per (src, dst, tag); non-blocking (KV deposit)."""
        self._check_alive()
        self._deposit(self._next_send_key(dst, tag), np.asarray(arr))

    def _claim_recv(self, src: int, tag: int) -> Tuple[str, int]:
        with self._seq_lock:
            seq = self._recv_seq.get((src, tag), 0)
            self._recv_seq[(src, tag)] = seq + 1
        return f"{self._ns}/msg/{src}/{self.rank}/{tag}/{seq}", seq

    def _unclaim_recv(self, src: int, tag: int, seq: int) -> None:
        """Roll back a failed receive's sequence claim so a retry waits for
        the SAME message instead of permanently skewing the (src, tag)
        stream (only possible when no later claim happened meanwhile)."""
        with self._seq_lock:
            if self._recv_seq.get((src, tag)) == seq + 1:
                self._recv_seq[(src, tag)] = seq

    # Non-blocking in-order receive attempt shares the take protocol too.
    _try_take_msg = _try_take

    def recv(self, src: int, tag: int = 0) -> np.ndarray:
        """Blocks for the next in-order message from (src, tag); fails
        fast (ProcWorldError) if the sender's engine tombstones or the
        message was poisoned by a dying sender."""
        self._check_alive()
        key, seq = self._claim_recv(src, tag)
        try:
            return self._await_key(key, src)
        except ProcWorldError:
            raise  # poisoned (consumed) or peer dead: the claim stands
        except Exception:
            # Timeout/service error, message NOT consumed: roll back so a
            # retry waits for the SAME message instead of skewing the
            # (src, tag) stream by one forever.
            self._unclaim_recv(src, tag, seq)
            raise

    # ---- collectives (hclib_mpi.cpp:220-286) ----

    def barrier(self) -> None:
        self._check_alive()
        # Under _seq_lock: AM handlers may invoke world ops from the
        # progress thread, and a torn increment would desynchronize
        # barrier ids across ranks (a wedge, not an error).
        with self._seq_lock:
            self._barrier_n += 1
            bn = self._barrier_n
        try:
            self._c.wait_at_barrier(f"{self._ns}/b/{bn}", self._timeout_ms)
        except Exception as e:
            # A barrier has no single target: on failure, scan every peer
            # for a tombstone so the error NAMES the dead rank instead of
            # reading as an anonymous DEADLINE_EXCEEDED.
            for r in range(self.size):
                if r == self.rank:
                    continue
                try:
                    self._raise_if_peer_dead(r, context=f" (barrier {bn})")
                except ProcWorldError as pe:
                    raise pe from e
            raise

    _REDUCE_FNS = {
        "sum": lambda a, b: a + b,
        "max": np.maximum,
        "min": np.minimum,
        "prod": lambda a, b: a * b,
    }

    def allreduce(self, arr, op: str = "sum") -> np.ndarray:
        """Recursive-doubling allreduce through the KV store: log2(n)
        rounds of pairwise exchange, O(n log n) total messages (the round-2
        design read all n contributions on every rank - O(n^2) reads).

        Payloads larger than ``BULK_THRESHOLD`` bytes ride the global
        device mesh (XLA collectives over ICI/DCN, parallel/multihost.py)
        when one is active - the reference's split between control-plane
        AM packets and bulk MPI datatypes. The bulk-vs-KV choice is made
        *collectively* (a 1-byte KV vote each epoch): a rank whose local
        bulk probe fails must not silently fall back while its peers enter
        the device collective - that wedges the job and desynchronizes
        epochs forever."""
        self._check_alive()
        arr = np.asarray(arr)
        fn = self._REDUCE_FNS[op]
        with self._seq_lock:  # see barrier(): epoch ids must not tear
            self._ar_epoch += 1
            e = self._ar_epoch
        if self._native_runtime and arr.nbytes >= self.BULK_THRESHOLD:
            want = np.uint8(1 if self._bulk_usable(op) else 0)
            agreed = self._kv_allreduce(e, want, np.minimum,
                                        round_base=100)
            if int(agreed) == 1:
                # All ranks committed to the device collective; a failure
                # inside it is fatal (raise), never a silent solo fallback
                # - EXCEPT a deterministic local capability error: a
                # backend that cannot run multiprocess computations at all
                # (CPU pre-gloo jaxlib) rejects the dispatch on EVERY rank
                # before any cross-rank rendezvous, so a collective
                # fallback to the KV path is consistent, and later epochs
                # vote KV outright (_bulk_broken).
                from ..parallel.multihost import bulk_allreduce

                try:
                    out = bulk_allreduce(arr, op)
                except Exception as exc:
                    if not _bulk_capability_error(exc):
                        raise
                    self._bulk_broken = f"{type(exc).__name__}: {exc}"
                    self.last_allreduce_path = "kv-fallback"
                    return self._kv_allreduce(e, arr, fn, round_base=0)
                self.last_allreduce_path = "bulk"
                return out
        self.last_allreduce_path = "kv"
        return self._kv_allreduce(e, arr, fn, round_base=0)

    def _bulk_usable(self, op: str) -> bool:
        """Local probe: can this rank run the device-collective path?"""
        if op not in ("sum", "max", "min"):
            return False
        if self._bulk_broken is not None:
            return False  # backend proved incapable; degrade permanently
        try:
            import jax

            return jax.process_count() == self.size
        except Exception:
            return False

    def _kv_allreduce(self, e: int, arr, fn, round_base: int) -> np.ndarray:
        acc = arr
        # Non-power-of-two: fold extras into the power-of-two core first
        # (the classic recursive-doubling pre/post step).
        n = self.size
        pof2 = 1
        while pof2 * 2 <= n:
            pof2 *= 2
        rem = n - pof2
        me = self.rank
        in_core = True
        if me < 2 * rem:
            if me % 2 == 1:  # odd extras send to even partner, then idle
                self._ar_send(e, me - 1, round_base, acc)
                in_core = False
            else:
                acc = fn(acc, self._ar_recv(e, me + 1, round_base))
        if in_core:
            core = me // 2 if me < 2 * rem else me - rem
            mask, round_i = 1, round_base + 1
            while mask < pof2:
                peer_core = core ^ mask
                peer = peer_core * 2 if peer_core < rem else peer_core + rem
                self._ar_send(e, peer, round_i, acc)
                acc = fn(acc, self._ar_recv(e, peer, round_i))
                mask *= 2
                round_i += 1
            if me < 2 * rem:  # send final result back to the odd partner
                self._ar_send(e, me + 1, round_base + 99, acc)
        else:
            acc = self._ar_recv(e, me - 1, round_base + 99)
        return acc

    def _ar_send(self, epoch: int, dst: int, rnd: int, arr) -> None:
        key = f"{self._ns}/ar/{epoch}/{rnd}/{self.rank}/{dst}"
        self._c.key_value_set_bytes(key, _pack({}, np.asarray(arr)))

    def _ar_recv(self, epoch: int, src: int, rnd: int) -> np.ndarray:
        # Chunked wait with tombstone detection (_await_key): an allreduce
        # whose partner died surfaces as a prompt ProcWorldError naming the
        # dead rank, not a raw DEADLINE_EXCEEDED after the full timeout.
        key = f"{self._ns}/ar/{epoch}/{rnd}/{src}/{self.rank}"
        return self._await_key(key, src)

    # ---- symmetric heap + one-sided ops (modules/openshmem) ----

    def alloc(self, name: str, shape, dtype=np.int32) -> np.ndarray:
        """Collective: allocate the same-named array in every process (the
        symmetric-heap contract; SPMD call order required)."""
        with self._heap_lock:
            if name in self._heap:
                raise ValueError(f"heap array {name!r} exists")
            a = np.zeros(shape, dtype)
            self._heap[name] = a
        self.barrier()
        return a

    def heap(self, name: str) -> np.ndarray:
        return self._heap[name]

    def _post_op(self, dst: int, meta: dict, arr=None) -> None:
        self._check_alive()
        if dst == self.rank:
            self._apply(meta, arr)  # loopback: apply inline
            return
        # Per-source sequencing: each (src -> dst) op stream carries its
        # own dense local counter, so posting needs no atomic-increment
        # primitive (absent on older jaxlib clients). Per-source FIFO is
        # the guarantee that matters; the old global counter's
        # cross-source arbitration was race-decided anyway, and
        # fences/barriers provide real cross-rank ordering.
        with self._seq_lock:
            seq = self._op_seq.get(dst, 0)
            self._op_seq[dst] = seq + 1
        self._c.key_value_set_bytes(
            f"{self._ns}/op/{dst}/{self.rank}/{seq}", _pack(meta, arr)
        )

    def put(self, dst: int, name: str, arr, offset: int = 0) -> None:
        """One-sided write into rank ``dst``'s heap array (applied by its
        progress thread; order vs other ops from this rank preserved).
        Completion at the target is observable via fence()/barrier()."""
        self._post_op(
            dst, {"op": "put", "name": name, "off": int(offset)},
            np.asarray(arr),
        )

    def _post_get(self, src: int, name: str, offset: int,
                  size: Optional[int]) -> str:
        rk = self._new_reply_key()
        self._post_op(
            src,
            {"op": "get", "name": name, "off": int(offset),
             "size": -1 if size is None else int(size), "reply": rk},
        )
        return rk

    def get(self, src: int, name: str, offset: int = 0,
            size: Optional[int] = None) -> np.ndarray:
        """One-sided read of rank ``src``'s heap array (served by its
        progress thread; sequenced after this rank's earlier ops to src)."""
        if src == self.rank:
            with self._heap_lock:
                a = self._heap[name].reshape(-1)
                end = a.size if size is None else offset + size
                return a[offset:end].copy()
        return self._await_reply(self._post_get(src, name, offset, size), src)

    def _post_fence(self, dst: int) -> Optional[str]:
        if dst == self.rank:
            return None
        rk = self._new_reply_key()
        self._post_op(dst, {"op": "fence", "reply": rk})
        return rk

    def fence(self, dst: int) -> None:
        """Returns once every op this rank posted to ``dst`` has been
        applied (shmem_quiet for one target: a no-op op with a reply)."""
        rk = self._post_fence(dst)
        if rk is not None:
            self._await_reply(rk, dst)

    def quiet(self) -> None:
        """shmem_quiet: fence every target this rank has posted ops to."""
        for r in range(self.size):
            self.fence(r)

    # ---- active messages (hclib_openshmem-am.cpp:64-123) ----

    def register_handler(self, name: str, fn: Callable) -> None:
        """AM handlers are named (not function pointers): every process
        registers the same names - the portable form of the reference's
        identical-binary fn-pointer assumption."""
        self._handlers[name] = fn

    def am(self, dst: int, handler: str, arr=None, **kwargs) -> None:
        """Run the named handler on rank ``dst``'s progress thread with
        (world, payload_array, **kwargs)."""
        self._post_op(
            dst, {"op": "am", "h": handler, "kw": kwargs},
            None if arr is None else np.asarray(arr),
        )

    # ---- progress engine ----

    def _apply(self, meta: dict, arr) -> None:
        op = meta["op"]
        if op == "put":
            with self._heap_lock:
                a = self._heap[meta["name"]]
                flat = a.reshape(-1)
                v = arr.astype(a.dtype, copy=False).reshape(-1)
                flat[meta["off"] : meta["off"] + v.size] = v
        elif op == "get":
            with self._heap_lock:
                a = self._heap[meta["name"]].reshape(-1)
                off = meta["off"]
                end = a.size if meta["size"] < 0 else off + meta["size"]
                out = a[off:end].copy()
            self._c.key_value_set_bytes(meta["reply"], _pack({}, out))
        elif op == "fence":
            self._c.key_value_set_bytes(meta["reply"], _pack({}, None))
        elif op == "am":
            h = meta["h"]
            # A fast peer can post an AM before this rank reaches its
            # register_handler call (registration is local, not collective):
            # wait briefly for the name instead of dropping the op. Ordered
            # application makes this a short stall of the queue, not a skip.
            deadline = time.monotonic() + min(2.0, self._timeout_s)
            while (h not in self._handlers and not self._stop.is_set()
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            fn = self._handlers.get(h)
            if fn is None:
                raise ValueError(
                    f"AM handler {h!r} never registered; op dropped "
                    f"(register handlers before communicating)"
                )
            fn(self, arr, **meta.get("kw", {}))
        else:  # pragma: no cover
            raise ValueError(f"unknown op {op!r}")

    def _progress_loop(self) -> None:
        me = self.rank
        backoff = 0.005
        retry_deadline = None  # armed on the first consecutive transient
        fp = self._fault_plan
        while not self._stop.is_set():
            if fp is not None and fp.on_procworld_poll(me, self._applied):
                from ..runtime.resilience import InjectedFault

                self._die(InjectedFault(
                    f"chaos: rank {me} progress engine killed by FaultPlan"
                ))
                return
            progressed = False
            transient = False
            for src in range(self.size):
                # Drain this source to its first miss before moving on:
                # one probe per APPLIED op (a probe-per-source-per-op
                # sweep would multiply RPC cost by the world size).
                while not self._stop.is_set():
                    key = (
                        f"{self._ns}/op/{me}/{src}/"
                        f"{self._applied_src[src]}"
                    )
                    try:
                        b = self._c.key_value_try_get_bytes(key)
                    except Exception as e:
                        st = _status(e)
                        if st == "NOT_FOUND":
                            b = None
                        elif st in _TRANSIENT:
                            # The service may be mid-restart
                            # (multi-controller startup on some PJRT
                            # platforms churns the channel): back off and
                            # retry for up to retry_s before giving up.
                            now = time.monotonic()
                            if retry_deadline is None:
                                retry_deadline = now + self._retry_s
                            if now < retry_deadline:
                                self._stop.wait(backoff)
                                backoff = min(backoff * 2, 0.25)
                                transient = True
                                break
                            self._die(e)
                            return
                        else:
                            self._die(e)
                            return
                    retry_deadline = None
                    backoff = 0.005
                    if b is None:
                        break
                    meta, arr = _unpack(b)
                    self._c.key_value_delete(key)
                    self._applied_src[src] += 1
                    self._applied += 1
                    progressed = True
                    try:
                        self._apply(meta, arr)
                    except Exception:  # pragma: no cover - engine lives
                        import traceback

                        traceback.print_exc()
                if self._stop.is_set():
                    return
                if transient:
                    break
            if not progressed and not transient:
                time.sleep(self._poll_s)

    def _die(self, err: BaseException) -> None:
        """Fatal engine failure: publish a tombstone and poison the reply
        key of every op still queued here, so peers fail fast instead of
        running out their fence/get timeouts. All best-effort - the
        service itself may be the thing that died."""
        self._dead = err
        import traceback

        print(f"procworld rank {self.rank}: progress engine died "
              f"({_status(err)}):", flush=True)
        traceback.print_exception(type(err), err, err.__traceback__)
        try:
            self._c.key_value_set_bytes(
                self._tomb_key(self.rank),
                f"{_status(err)}: {err}".encode()[:512],
            )
        except Exception:
            pass
        poison = _pack({"poisoned": f"rank {self.rank}: {_status(err)}"},
                       None)
        for src in range(self.size):
            # Per-source queues are dense (set-only, posted in order), so
            # the first miss ends a source's scan; a producer racing its
            # next set loses only that op's poisoning - its caller still
            # fails fast on the tombstone.
            seq = self._applied_src[src]
            while True:
                try:
                    b = self._c.key_value_try_get_bytes(
                        f"{self._ns}/op/{self.rank}/{src}/{seq}"
                    )
                except Exception as e:
                    if _status(e) != "NOT_FOUND":
                        return  # service gone: nothing more we can do
                    b = None
                if b is None:
                    break
                seq += 1
                try:
                    meta, _ = _unpack(b)
                    if "reply" in meta:
                        self._c.key_value_set_bytes(meta["reply"], poison)
                except Exception:
                    return

    def close(self) -> None:
        """Stop the progress engine (pending remote ops stay queued in the
        coordination service; call quiet() first for a clean drain)."""
        self._stop.set()
        self._thread.join(timeout=5)


class ProcWorldModule(Module):
    """ProcWorld as a runtime module: ops are *tasks at the COMM locale
    returning futures*, completion-polled by the shared pending-op
    harness - the reference's comm-module integration pattern
    (modules/mpi/src/hclib_mpi.cpp:130-210 Isend/Irecv + MPI_Test polling;
    modules/common/hclib-module-common.h:10-115).

    ``isend``/``irecv``/``iget``/``ifence`` return hclib futures that
    ``async_await`` tasks can depend on; the poller runs at the COMM locale
    so any worker whose pop/steal path covers it services cross-process
    completion while the rest compute.
    """

    name = "procworld"

    def __init__(self, world: Optional[ProcWorld] = None, **world_kwargs):
        self._world = world
        self._owns_world = world is None
        self._world_kwargs = world_kwargs
        self.locale = None
        self.pending = None

    # -- Module lifecycle (runtime/module.py) --

    def pre_init(self, runtime) -> None:
        from .common import PendingList

        ici = runtime.graph.locales_of_type("ici")
        self.locale = ici[0] if ici else runtime.graph.central_locale()
        self.locale.mark_special("COMM")
        self.pending = PendingList(locale=self.locale)

    def post_init(self, runtime) -> None:
        if self._world is None:
            self._world = ProcWorld(**self._world_kwargs)

    def finalize(self, runtime) -> None:
        """Drain + close only a world this module created; an injected one
        stays open for its owner (the reference's module-finalize hooks
        likewise only tear down state the module initialized)."""
        if not self._owns_world or self._world is None:
            return
        if self._world.dead is None:
            try:
                self._world.quiet()
            except ProcWorldError:
                pass
        self._world.close()

    @property
    def world(self) -> ProcWorld:
        if self._world is None:
            raise RuntimeError("ProcWorldModule not post-initialized")
        return self._world

    # -- future-returning ops --

    def _pend(self, test):
        from ..runtime.promise import Promise
        from .common import PendingOp

        return self.pending.append(PendingOp(test, Promise()))

    def _guarded(self, test, target: int, on_fail=None):
        """Wrap a pending-op test with the same failure model the blocking
        API has: raise ProcWorldError (poisoning the future) on the op
        timeout, on a peer tombstone, or on local engine death - a module
        future must fail fast, not pend forever past a dead peer."""
        w = self.world
        deadline = time.monotonic() + w._timeout_s
        state = {"tomb_at": 0.0}

        def run(op):
            try:
                done, val = test(op)
            except ProcWorldError:
                raise  # op consumed/poisoned: rollback would double-take
            except Exception as e:
                if _status(e) in _TRANSIENT:
                    return False, None  # service blip: retry next sweep
                if on_fail is not None:
                    on_fail()
                raise
            if done:
                return True, val
            now = time.monotonic()
            err = None
            if now >= state["tomb_at"]:
                # Tombstone polls are KV RPCs: throttle to 2/s. Same
                # protocol as the blocking waits (_raise_if_peer_dead):
                # local engine death and peer tombstones both fail fast.
                state["tomb_at"] = now + 0.5
                try:
                    w._raise_if_peer_dead(
                        target, context="; op will never complete"
                    )
                except ProcWorldError as pe:
                    err = pe
            elif w.dead is not None:
                err = ProcWorldError(
                    f"rank {w.rank}: local progress engine died"
                )
            if err is None and now >= deadline:
                err = ProcWorldError(
                    f"op to rank {target} timed out after {w._timeout_s}s"
                )
            if err is not None:
                if on_fail is not None:
                    on_fail()
                raise err
            return False, None

        return run

    def isend(self, dst: int, arr, tag: int = 0):
        """Future completing when the message is committed to the KV store
        (local completion, like MPI_Isend's buffer-free guarantee). The
        sequence slot is claimed here (program order); the deposit itself
        runs on the COMM-locale poller, so the calling worker never blocks
        on the coordination-service RPC."""
        w = self.world
        w._check_alive()
        arr = np.asarray(arr)
        key = w._next_send_key(dst, tag)

        def test(op):
            # Transient failures are retried by _guarded, but the deposit
            # is not idempotent: if the first set committed server-side and
            # only the RPC response was lost, the retry sees
            # ALREADY_EXISTS. The slot is ours by construction (claimed
            # under _seq_lock above), so that means delivered - success.
            try:
                w._deposit(key, arr)
            except Exception as e:
                if _status(e) == "ALREADY_EXISTS":
                    return True, None
                raise
            return True, None

        def on_fail():
            # The sequence slot is claimed and later sends may hold higher
            # slots, so it can't be unclaimed - deposit a poison marker
            # instead, turning the peer's recv of this slot into a prompt
            # ProcWorldError rather than a stream wedged at seq k forever.
            try:
                w._c.key_value_set_bytes(
                    key, _pack({"poisoned": f"rank {w.rank} isend failed"},
                               None),
                )
            except Exception:
                pass

        return self._pend(self._guarded(test, dst, on_fail=on_fail))

    def irecv(self, src: int, tag: int = 0):
        """Future carrying the next in-order message from (src, tag); fails
        (poisoned future) on timeout or peer death, rolling back the
        sequence claim so a retry waits for the same message."""
        w = self.world
        key, seq = w._claim_recv(src, tag)

        def test(op):
            return w._try_take_msg(key)

        return self._pend(self._guarded(
            test, src, on_fail=lambda: w._unclaim_recv(src, tag, seq)
        ))

    def iput(self, dst: int, name: str, arr, offset: int = 0):
        """Future completing at local completion of the put. The op is
        posted eagerly (op-queue sequencing happens at post time, so a
        following ifence/fence is guaranteed to cover this put)."""
        w = self.world
        w.put(dst, name, arr, offset)

        def test(op):
            return True, None

        return self._pend(test)

    def iget(self, src: int, name: str, offset: int = 0,
             size: Optional[int] = None):
        """Future carrying the remote heap slice - the poller polls the
        reply key instead of blocking a worker on it."""
        w = self.world
        if src == w.rank:
            def test_local(op):
                return True, w.get(src, name, offset, size)

            return self._pend(test_local)
        rk = w._post_get(src, name, offset, size)

        def test(op):
            return w._try_reply(rk)

        return self._pend(self._guarded(test, src))

    def ifence(self, dst: int):
        """Future completing once every op this rank posted to ``dst`` has
        been applied."""
        w = self.world
        rk = w._post_fence(dst)
        if rk is None:
            def test_local(op):
                return True, None

            return self._pend(test_local)

        def test(op):
            return w._try_reply(rk)

        return self._pend(self._guarded(test, dst))

    def wait_all(self, *futures):
        """MPI_Waitall (hclib_mpi.cpp:143-149): wait each future."""
        return [f.wait() for f in futures]
