"""Shared async-completion-polling harness for comm/device backends.

Reference design (modules/common/hclib-module-common.h:10-115): each backend
keeps a lock-free list of pending operations; ``append_to_pending`` pushes an
op and, if the list was empty, spawns a poller task at the module's locale.
The poller tests every op via a callback, fulfills the op's promise (or spawns
its task) on completion, then yields at the locale and sweeps again until the
list drains.

Here the poller is an *escaping* task (it must not prolong unrelated finish
scopes - user code is gated on the op promises, not on the poller), and a
backend may alternatively register the sweep as a runtime idle function
(the reference's per-locale idle tasks, src/hclib-locality-graph.c:807-827).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional

from ..runtime.locality import Locale
from ..runtime.promise import Promise
from ..runtime.scheduler import current_runtime, yield_

__all__ = ["PendingOp", "PendingList"]


class PendingOp:
    """One in-flight operation: ``test()`` returns (done, result)."""

    __slots__ = ("test", "promise", "data")

    def __init__(
        self,
        test: Callable[["PendingOp"], Any],
        promise: Optional[Promise] = None,
        data: Any = None,
    ) -> None:
        self.test = test
        self.promise = promise
        self.data = data


class PendingList:
    """Pending-op list + self-terminating poller task.

    ``append`` returns the op's promise's future when one exists, so callers
    can write ``PendingList.append(op).wait()``.
    """

    def __init__(self, locale: Optional[Locale] = None, use_idle_fn: bool = False) -> None:
        self.locale = locale
        self._lock = threading.Lock()
        self._ops: List[PendingOp] = []
        self._poller_live = False
        self._use_idle_fn = use_idle_fn
        self._idle_registered = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._ops)

    def append(self, op: PendingOp):
        """Add an op; ensure a poller is draining the list
        (append_to_pending, modules/common/hclib-module-common.h:92-115)."""
        rt = current_runtime()
        with self._lock:
            self._ops.append(op)
            spawn_poller = not self._poller_live and not self._use_idle_fn
            if spawn_poller:
                self._poller_live = True
            if self._use_idle_fn and not self._idle_registered:
                self._idle_registered = True
                rt.register_idle_fn(lambda wid: self.sweep())
        if spawn_poller:
            # Escaping: the poller's lifetime is governed by the ops, not by
            # whatever finish scope happened to issue the first op.
            rt.spawn(self._poll_loop, locale=self.locale, escaping=True)
        return op.promise.future if op.promise is not None else None

    def sweep(self) -> bool:
        """Test every pending op once; returns True if any completed."""
        with self._lock:
            ops = list(self._ops)
        completed = []
        for op in ops:
            try:
                done, result = op.test(op)
            except BaseException as e:
                done, result = True, e
                if op.promise is not None:
                    with self._lock:
                        self._ops.remove(op)
                    completed.append(op)
                    op.promise.poison(e)
                    continue
            if done:
                with self._lock:
                    self._ops.remove(op)
                completed.append(op)
                if op.promise is not None:
                    op.promise.put(result)
        return bool(completed)

    def _poll_loop(self) -> None:
        """Poller body (poll_on_pending, modules/common/
        hclib-module-common.h:10-90): sweep, yield at the locale, repeat;
        exit when the list drains (re-spawned by the next append)."""
        while True:
            progressed = self.sweep()
            with self._lock:
                if not self._ops:
                    self._poller_live = False
                    return
            ran = yield_(at=self.locale)
            if not progressed and not ran:
                # Nothing moved: back off briefly instead of burning a worker
                # (the reference busy-yields; host Python should not).
                time.sleep(0.0002)
