"""TPU module: device locales, memory handlers, stream-ordered async offload.

This is the accelerator module - the role modules/cuda/ plays in the
reference, re-designed for JAX:

- Reference GPU locale metadata = device id + 64 round-robin streams
  (modules/cuda/src/hclib_cuda.cpp:44-62,141-154). JAX dispatch is already
  asynchronous, so a *stream* here is a sequencing token: ops issued on the
  same stream are chained (each waits on the predecessor's completion future)
  while different streams overlap. Each tpu locale gets a round-robin pool.
- Reference memory handlers: cudaMalloc/cudaFree/cudaMemset + a MUST_USE copy
  whose cudaMemcpyKind is chosen from the src/dst locale types
  (modules/cuda/src/hclib_cuda.cpp:103-139,169-174). Here: device buffers are
  jax.Arrays committed to the locale's device; copy direction resolves to
  jax.device_put / np.asarray(device->host) / device-to-device device_put
  (the ICI path between chips).
- Reference kernel launch ``forasync_cuda`` = async at the GPU locale ->
  launch on a stream -> cudaEvent completion poll -> future
  (modules/cuda/inc/hclib_cuda.h:9-74). Here ``async_device`` runs a jitted
  function on the locale's device; completion is polled via
  jax.Array.is_ready() through the shared pending-op harness - the worker
  never blocks in the dispatch task.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np

from ..runtime.locality import Locale
from ..runtime.module import MUST_USE, Module, register_mem_fns
from ..runtime.promise import Future, Promise
from ..runtime.scheduler import async_, current_runtime, current_worker
from .common import PendingList, PendingOp

__all__ = [
    "TpuModule",
    "get_closest_tpu_locale",
    "async_device",
    "forasync_device",
    "device_stream",
    "abort_on_cancel",
    "NUM_STREAMS",
]

NUM_STREAMS = 64  # per-locale pool size, matching the reference's stream pool
_DEVICE_TYPES = ("tpu", "hbm")


class _Stream:
    """Sequencing token: ops on one stream serialize, streams overlap."""

    __slots__ = ("locale", "index", "_tail", "_lock")

    def __init__(self, locale: Locale, index: int) -> None:
        self.locale = locale
        self.index = index
        self._tail: Optional[Future] = None
        self._lock = threading.Lock()

    def chain(self) -> Tuple[Optional[Future], Promise]:
        """Returns (predecessor future, this op's completion promise)."""
        p = Promise()
        with self._lock:
            prev, self._tail = self._tail, p.future
        return prev, p


def _streams_for(locale: Locale) -> list:
    pool = locale.metadata.get("streams")
    if pool is None:
        pool = [_Stream(locale, i) for i in range(NUM_STREAMS)]
        locale.metadata["streams"] = pool
        locale.metadata["next_stream"] = 0
    return pool


def device_stream(locale: Locale) -> _Stream:
    """Round-robin stream from the locale's pool
    (get_stream, modules/cuda/src/hclib_cuda.cpp:141-154)."""
    pool = _streams_for(locale)
    i = locale.metadata["next_stream"]
    locale.metadata["next_stream"] = (i + 1) % len(pool)
    return pool[i]


def _device_of(locale: Locale):
    dev = locale.metadata.get("device")
    if dev is None:
        raise ValueError(f"locale {locale.name!r} has no bound jax device")
    return dev


def _tpu_alloc(spec: Any, locale: Locale, *, dtype=None) -> Any:
    import jax
    import jax.numpy as jnp

    if isinstance(spec, (int, np.integer)):
        arr = jnp.zeros(int(spec), dtype=jnp.uint8 if dtype is None else dtype)
    elif isinstance(spec, tuple) and len(spec) == 2 and not isinstance(spec[0], int):
        shape, dt = spec
        arr = jnp.zeros(shape, dtype=dt)
    else:
        arr = jnp.zeros(spec, dtype=jnp.float32 if dtype is None else dtype)
    return jax.device_put(arr, _device_of(locale))


def _tpu_free(buf: Any, locale: Locale) -> None:
    try:
        buf.delete()
    except Exception:
        pass


def _tpu_memset(buf: Any, value: int, locale: Locale) -> Any:
    import jax
    import jax.numpy as jnp

    flat = jnp.full(buf.shape, value, dtype=buf.dtype)
    return jax.device_put(flat, _device_of(locale))


def _is_device_type(t: str) -> bool:
    return t in _DEVICE_TYPES


def _tpu_copy(
    dst: Any,
    dst_locale: Locale,
    src: Any,
    src_locale: Locale,
    nelems: Optional[int] = None,
) -> Any:
    """Direction chosen from locale types, the reference's cudaMemcpyKind
    selection (modules/cuda/src/hclib_cuda.cpp:103-139). Device copies are
    functional: the handler returns the new dst value (host numpy dsts are
    mutated in place for parity with the system module)."""
    import jax

    s_dev = _is_device_type(src_locale.type)
    d_dev = _is_device_type(dst_locale.type)
    if d_dev:
        # host->device or device->device (ICI when the devices differ).
        # Host sources not registered in the pinned-buffer tree
        # (runtime/memtree.py, the reference's hclib-tree.c role) get a
        # defensive staging copy first: the caller may mutate or free the
        # buffer while JAX's async dispatch still reads it. Pinned buffers
        # are promised stable and transfer zero-copy.
        if isinstance(src, np.ndarray) and not s_dev:
            from ..runtime import memtree

            try:
                pinned = memtree.lookup(src) is not None
            except ValueError:  # non-contiguous: never pinnable
                pinned = False
            if not pinned:
                src = np.ascontiguousarray(src).copy()
        out = jax.device_put(src, _device_of(dst_locale))
        if nelems is not None:
            out = out.reshape(-1)[:nelems]
        return out
    if s_dev:
        host = np.asarray(src)  # device->host
        if isinstance(dst, np.ndarray):
            if nelems is None:
                np.copyto(dst.reshape(-1), host.reshape(-1))
            else:
                dst.reshape(-1)[:nelems] = host.reshape(-1)[:nelems]
            return dst
        return host
    raise ValueError("tpu copy handler invoked with no device-side locale")


class TpuModule(Module):
    """Binds jax devices to ``tpu`` locales and registers device memory
    handlers (MUST_USE, so mixed host/device copies resolve to this module -
    the reference registers its GPU copy MUST_USE for the same reason)."""

    name = "tpu"

    def __init__(self, devices: Optional[Sequence] = None) -> None:
        self._devices = devices
        self.pending = PendingList()

    def pre_init(self, runtime) -> None:
        import jax

        devices = list(self._devices) if self._devices else jax.devices()
        tpu_locales = runtime.graph.locales_of_type("tpu")
        for i, loc in enumerate(tpu_locales):
            if "device" not in loc.metadata:
                loc.metadata["device"] = devices[i % len(devices)]
        self.pending.locale = tpu_locales[0] if tpu_locales else None

    def post_init(self, runtime) -> None:
        for t in _DEVICE_TYPES:
            register_mem_fns(
                t,
                alloc=_tpu_alloc,
                free=_tpu_free,
                memset=_tpu_memset,
                copy=_tpu_copy,
                priority=MUST_USE,
            )


def _active_module() -> TpuModule:
    from ..runtime.module import registered_modules

    for m in registered_modules():
        if isinstance(m, TpuModule):
            return m
    raise RuntimeError("no TpuModule registered")


def abort_on_cancel(stream, scope=None):
    """Tie a running device stream's kill switch to host cancellation:
    when a ``CancelScope`` cancels (``scope=None``: any scope - e.g.
    root-finish cancellation, the watchdog's last rung, a deadline),
    ``stream.abort()`` fires, the stream's in-kernel abort word lands in
    its round loop, and the running quantum stops within a bounded number
    of inner iterations instead of draining. ``stream`` is anything with
    ``abort(reason)`` (StreamingMegakernel; any adapter for the mesh
    runners' ``run(abort=...)`` word).

    Returns an unregister callable; use as a context manager::

        with abort_on_cancel(sm, scope=fin.scope):
            sm.run_stream(b)
    """
    from ..runtime.resilience import bind_abort_to_scope

    unregister = bind_abort_to_scope(stream.abort, scope)

    class _Unreg:
        def __call__(self) -> None:
            unregister()

        def __enter__(self) -> "_Unreg":
            return self

        def __exit__(self, *exc) -> bool:
            self()
            return False

    return _Unreg()


def get_closest_tpu_locale() -> Locale:
    """Closest tpu locale to the calling worker
    (hclib::get_closest_gpu_locale, modules/cuda/inc/hclib_cuda.h)."""
    rt = current_runtime()
    loc = rt.graph.closest_of_type(max(current_worker(), 0), "tpu")
    if loc is None:
        raise RuntimeError("locality graph has no tpu locale (use mesh_locality_graph)")
    return loc


def async_device(
    fn: Callable[..., Any],
    *args: Any,
    locale: Optional[Locale] = None,
    stream: Optional[_Stream] = None,
) -> Future:
    """Dispatch ``fn(*args)`` on the locale's device; returns a future
    satisfied with the result once the device computation lands
    (forasync_cuda shape: async at locale -> launch on stream -> completion
    poll -> future; modules/cuda/inc/hclib_cuda.h:9-74)."""
    import jax

    loc = locale if locale is not None else get_closest_tpu_locale()
    st = stream if stream is not None else device_stream(loc)
    prev, done = st.chain()
    mod = _active_module()

    def dispatch() -> None:
        dev = _device_of(loc)
        placed = [
            jax.device_put(a, dev) if isinstance(a, (np.ndarray, jax.Array)) else a
            for a in args
        ]
        out = fn(*placed)

        def ready(op: PendingOp) -> Tuple[bool, Any]:
            leaves = jax.tree_util.tree_leaves(op.data)
            if all(l.is_ready() for l in leaves if hasattr(l, "is_ready")):
                return True, op.data
            return False, None

        mod.pending.append(PendingOp(ready, promise=done, data=out))

    async_(
        dispatch,
        at=loc,
        await_=(prev,) if prev is not None else (),
        non_blocking=True,
    )
    return done.future


def forasync_device(
    fn: Callable[..., Any],
    n: int,
    *args: Any,
    locale: Optional[Locale] = None,
) -> Future:
    """Data-parallel device loop: one fused dispatch of ``vmap(fn)`` over
    ``iota(n)`` - the reference launches a CUDA grid over the iteration space
    (driver_kernel, modules/cuda/inc/hclib_cuda.h:76-127); on TPU the grid is
    a vectorized program the XLA compiler tiles onto the VPU/MXU."""
    import jax
    import jax.numpy as jnp

    idx = jnp.arange(n)
    return async_device(
        lambda i, *rest: jax.vmap(lambda j: fn(j, *rest))(i), idx, *args, locale=locale
    )
