"""System module: host-memory locale types and handlers.

Reference (modules/system/src/hclib_system.cpp:50-82): pre-init registers the
CPU locale types (L1, L2, L3, sysmem); post-init registers malloc/free/
memset/memcpy handlers for each so ``allocate_at``/``async_copy`` work on CPU
locales; exposes ``get_closest_cpu_locale``.

Host buffers are numpy arrays. ``alloc`` accepts either a byte count (the
reference's malloc shape) or a (shape, dtype) pair, returning an array the
caller mutates in place.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..runtime.locality import Locale
from ..runtime.module import MAY_USE, Module, register_mem_fns
from ..runtime.scheduler import current_runtime, current_worker

__all__ = ["SystemModule", "get_closest_cpu_locale", "CPU_LOCALE_TYPES"]

CPU_LOCALE_TYPES = ("L1", "L2", "L3", "sysmem", "host")


def _host_alloc(spec: Any, locale: Locale, *, dtype=None) -> np.ndarray:
    if isinstance(spec, (int, np.integer)):
        return np.empty(int(spec), dtype=np.uint8 if dtype is None else dtype)
    if isinstance(spec, tuple) and len(spec) == 2 and not isinstance(spec[0], int):
        shape, dt = spec
        return np.empty(shape, dtype=dt)
    return np.empty(spec, dtype=np.float32 if dtype is None else dtype)


def _host_free(buf: Any, locale: Locale) -> None:
    return None  # numpy frees on GC; parity op so free_at() resolves


def _host_memset(buf: np.ndarray, value: int, locale: Locale) -> np.ndarray:
    buf.view(np.uint8).fill(value)
    return buf


def _host_copy(
    dst: np.ndarray,
    dst_locale: Locale,
    src: Any,
    src_locale: Locale,
    nelems: Optional[int] = None,
) -> np.ndarray:
    s = np.asarray(src)
    if nelems is None:
        np.copyto(dst.reshape(-1), s.reshape(-1))
    else:
        dst.reshape(-1)[:nelems] = s.reshape(-1)[:nelems]
    return dst


class SystemModule(Module):
    """Registers host locale types' memory handlers at post-init
    (reference: modules/system/src/hclib_system.cpp:57-82)."""

    name = "system"

    def post_init(self, runtime) -> None:
        for t in CPU_LOCALE_TYPES:
            register_mem_fns(
                t,
                alloc=_host_alloc,
                free=_host_free,
                memset=_host_memset,
                copy=_host_copy,
                priority=MAY_USE,
            )


def get_closest_cpu_locale() -> Locale:
    """Closest host-memory locale to the calling worker
    (hclib::get_closest_cpu_locale)."""
    rt = current_runtime()
    w = max(current_worker(), 0)
    for t in CPU_LOCALE_TYPES:
        loc = rt.graph.closest_of_type(w, t)
        if loc is not None:
            return loc
    return rt.graph.closest_locale(w)
