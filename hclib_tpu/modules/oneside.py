"""One-sided communication: symmetric heap, put/get, atomics, wait-sets,
distributed locks, per-worker comm contexts.

This module covers the roles of the reference's OpenSHMEM modules:

- modules/openshmem/ - ~30 one-sided ops (put/get/AMO/collectives/locks)
  wrapped as tasks at the NIC locale; **wait-sets**: shmem_int_wait_until
  [_any] / async_when[_any] enqueue {var, cmp, value} sets onto a list polled
  by a self-re-spawning task at the NIC locale
  (modules/openshmem/src/hclib_openshmem.cpp:755-920); distributed locks
  chained through promises per lock address (:124-134, 383-439).
- modules/sos/ - per-worker communication *contexts* so puts/gets issue on
  the calling worker's own channel instead of funneling through one NIC
  worker (modules/sos/src/hclib_sos.cpp:156-255); quiet/barrier flush them.

TPU-native redesign: the symmetric heap is a table of per-rank buffers -
device-committed when the rank is device-bound (HBM; remote access = ICI
transfer, the role SHMEM's RDMA plays), host numpy otherwise. Signal-driven
tasks (wait_until/async_when) poll through the shared pending-op harness,
which is exactly the reference's poll_on_waits loop; inside the device
megakernel the same feature is the DDF flag-wait in the scheduler loop
(device/megakernel.py). Atomics serialize through a per-variable host lock -
the single-controller equivalent of the NIC's atomic engine.
"""

from __future__ import annotations

import operator
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..runtime.locality import Locale
from ..runtime.module import Module, add_per_worker_state, get_per_worker_state
from ..runtime.promise import Future, Promise
from ..runtime.scheduler import async_, current_runtime, current_worker
from .common import PendingList, PendingOp
from .world import World, current_world

__all__ = [
    "OneSidedModule",
    "SymArray",
    "symm_array",
    "put",
    "get",
    "iput",
    "iget",
    "fetch_add",
    "compare_swap",
    "wait_until",
    "wait_until_any",
    "async_when",
    "async_when_any",
    "DistLock",
    "quiet",
    "my_context",
]

_CMP = {
    "eq": operator.eq,
    "ne": operator.ne,
    "gt": operator.gt,
    "ge": operator.ge,
    "lt": operator.lt,
    "le": operator.le,
}


class OneSidedModule(Module):
    name = "oneside"

    def __init__(self, world: Optional[World] = None) -> None:
        self._world = world
        self.locale: Optional[Locale] = None
        self.pending = PendingList()
        # Wait-sets are polled from the runtime idle loop as well as the
        # poller task, so a fully busy machine still observes flag writes
        # (reference: poll_on_waits re-spawns itself at the NIC locale).
        self._ctx_slot: Optional[int] = None

    def pre_init(self, runtime) -> None:
        ici = runtime.graph.locales_of_type("ici")
        self.locale = ici[0] if ici else runtime.graph.central_locale()
        self.locale.mark_special("COMM")
        self.pending.locale = self.locale
        # Per-worker comm contexts (modules/sos/src/hclib_sos.cpp:156-255).
        self._ctx_slot = add_per_worker_state(lambda wid: _CommContext(wid))

    def world(self) -> World:
        return self._world if self._world is not None else current_world()


class _CommContext:
    """Per-worker channel: tracks this worker's outstanding one-sided ops so
    ``quiet()`` flushes only the caller's traffic (the sos contexts' point -
    comm concurrency without funneling through one worker)."""

    __slots__ = ("worker_id", "_lock", "outstanding")

    def __init__(self, worker_id: int) -> None:
        self.worker_id = worker_id
        self._lock = threading.Lock()
        self.outstanding: List[Future] = []

    def track(self, fut: Future) -> Future:
        with self._lock:
            self.outstanding = [f for f in self.outstanding if not f.satisfied()]
            self.outstanding.append(fut)
        return fut

    def drain(self) -> None:
        with self._lock:
            pending, self.outstanding = self.outstanding, []
        for f in pending:
            f.wait()


def _active() -> OneSidedModule:
    from ..runtime.module import registered_modules

    for m in registered_modules():
        if isinstance(m, OneSidedModule):
            return m
    raise RuntimeError("no OneSidedModule registered")


def my_context() -> _CommContext:
    """The calling worker's comm context (shmemx_ctx_t analogue,
    modules/sos/src/hclib_sos.cpp:425-435)."""
    mod = _active()
    rt = current_runtime()
    wid = max(current_worker(), 0)
    return get_per_worker_state(rt, wid, mod._ctx_slot)


def quiet() -> None:
    """Flush the calling worker's outstanding one-sided ops
    (shmem_quiet on the worker's context, modules/sos/src/hclib_sos.cpp:299-314)."""
    my_context().drain()


# ------------------------------------------------------------ symmetric heap


class SymArray:
    """A symmetric allocation: one buffer per rank, same shape/dtype.

    Device-bound ranks hold committed jax arrays (HBM); host ranks hold
    numpy. Mutation is serialized per (rank, array) through a lock - the
    atomicity domain SHMEM gives AMOs on symmetric variables.
    """

    def __init__(self, world: World, shape, dtype, fill: Any = 0) -> None:
        self.world = world
        self.shape = tuple(np.atleast_1d(np.asarray(shape)).tolist()) if not isinstance(
            shape, tuple
        ) else shape
        self.dtype = np.dtype(dtype)
        self._locks = [threading.Lock() for _ in range(world.size)]
        self._bufs: List[Any] = []
        for r in range(world.size):
            host = np.full(self.shape, fill, dtype=self.dtype)
            self._bufs.append(self._commit(host, r))

    def _commit(self, host: np.ndarray, rank: int) -> Any:
        dev = self.world.device_for(rank)
        if dev is None:
            return host
        import jax

        return jax.device_put(host, dev)

    def _read_host(self, rank: int) -> np.ndarray:
        return np.asarray(self._bufs[rank])

    def read(self, rank: int, index: Any = None) -> Any:
        with self._locks[rank]:
            h = self._read_host(rank)
        return h if index is None else h[index]

    def write(self, rank: int, value: Any, index: Any = None) -> None:
        with self._locks[rank]:
            h = self._read_host(rank).copy()
            if index is None:
                h[...] = value
            else:
                h[index] = value
            self._bufs[rank] = self._commit(h, rank)

    def rmw(self, rank: int, fn: Callable[[np.ndarray], Tuple[np.ndarray, Any]]) -> Any:
        """Atomic read-modify-write on rank's buffer; fn returns (new, ret)."""
        with self._locks[rank]:
            h = self._read_host(rank).copy()
            new, ret = fn(h)
            self._bufs[rank] = self._commit(new, rank)
        return ret

    def buffer(self, rank: int) -> Any:
        """The rank's current buffer (device array for device ranks)."""
        return self._bufs[rank]


def symm_array(shape, dtype=np.int32, fill: Any = 0, world: Optional[World] = None) -> SymArray:
    """shmem_malloc analogue: symmetric across all ranks."""
    w = world if world is not None else _active().world()
    return SymArray(w, shape if isinstance(shape, tuple) else (int(shape),), dtype, fill)


# ------------------------------------------------------------------- put/get


def iput(arr: SymArray, rank: int, value: Any, index: Any = None) -> Future:
    """Nonblocking put to ``rank``'s copy; future satisfied when committed
    (shmem_putmem shape, modules/openshmem/src/hclib_openshmem.cpp:136-200)."""
    mod = _active()
    p = Promise()

    def issue() -> None:
        try:
            arr.write(rank, value, index)
            p.put(None)
        except BaseException as e:
            p.poison(e)

    async_(issue, at=mod.locale, non_blocking=True, escaping=True)
    return my_context().track(p.future)


def iget(arr: SymArray, rank: int, index: Any = None) -> Future:
    mod = _active()
    p = Promise()

    def issue() -> None:
        try:
            p.put(arr.read(rank, index))
        except BaseException as e:
            p.poison(e)

    async_(issue, at=mod.locale, non_blocking=True, escaping=True)
    return my_context().track(p.future)


def put(arr: SymArray, rank: int, value: Any, index: Any = None) -> None:
    iput(arr, rank, value, index).wait()


def get(arr: SymArray, rank: int, index: Any = None) -> Any:
    return iget(arr, rank, index).wait()


# ------------------------------------------------------------------- atomics


def fetch_add(arr: SymArray, rank: int, delta: Any, index: Any = 0) -> Any:
    """shmem_int_fadd (modules/openshmem/src/hclib_openshmem.cpp AMO family):
    returns the pre-add value."""

    def fn(h: np.ndarray) -> Tuple[np.ndarray, Any]:
        old = h[index].copy() if h.ndim else h.copy()
        if h.ndim:
            h[index] += delta
        else:
            h += delta
        return h, old

    return arr.rmw(rank, fn)


def compare_swap(arr: SymArray, rank: int, expected: Any, desired: Any, index: Any = 0) -> Any:
    """shmem_int_cswap: returns the observed value."""

    def fn(h: np.ndarray) -> Tuple[np.ndarray, Any]:
        old = h[index].copy()
        if old == expected:
            h[index] = desired
        return h, old

    return arr.rmw(rank, fn)


# ----------------------------------------------------------------- wait-sets


def _make_wait_test(
    sets: Sequence[Tuple[SymArray, int, str, Any, Any]]
) -> Callable[[PendingOp], Tuple[bool, Any]]:
    """A wait-set entry is (arr, rank, cmp, value, index); satisfied when any
    entry's comparison holds. Mirrors the reference's {var, cmp, value}[]
    wait-sets (modules/openshmem/inc/hclib_openshmem-internal.h:109-167)."""

    def test(op: PendingOp) -> Tuple[bool, Any]:
        for i, (arr, rank, cmp, value, index) in enumerate(sets):
            if _CMP[cmp](arr.read(rank, index), value):
                return True, i
        return False, None

    return test


def async_when(
    arr: SymArray, cmp: str, value: Any, *, rank: int = 0, index: Any = 0
) -> Future:
    """Future satisfied when ``arr[rank][index] cmp value`` holds
    (shmem_int_async_when, modules/openshmem/src/hclib_openshmem.cpp:895-920)."""
    return async_when_any([(arr, rank, cmp, value, index)])


def async_when_any(sets: Sequence[Tuple[SymArray, int, str, Any, Any]]) -> Future:
    """Future satisfied with the index of the first matching entry."""
    mod = _active()
    p = Promise()
    mod.pending.append(PendingOp(_make_wait_test(sets), promise=p))
    return p.future


def wait_until(arr: SymArray, cmp: str, value: Any, *, rank: int = 0, index: Any = 0) -> None:
    """Blocking wait (shmem_int_wait_until): parks the calling context; the
    polling happens at the COMM locale, not on this worker."""
    async_when(arr, cmp, value, rank=rank, index=index).wait()


def wait_until_any(sets: Sequence[Tuple[SymArray, int, str, Any, Any]]) -> int:
    return async_when_any(sets).wait()


# ---------------------------------------------------------------------- locks


class DistLock:
    """Distributed lock chained through promises.

    Reference (modules/openshmem/src/hclib_openshmem.cpp:124-134, 383-439):
    each lock address maps to a chain - an acquirer atomically swaps itself
    in as the tail and waits on the previous holder's release promise; unlock
    satisfies it. FIFO, no spinning.
    """

    _registry_lock = threading.Lock()
    _registry: Dict[str, "DistLock"] = {}

    def __init__(self, name: str = "") -> None:
        self.name = name
        self._lock = threading.Lock()
        self._tail: Optional[Promise] = None
        self._holder_release: Optional[Promise] = None

    @classmethod
    def named(cls, name: str) -> "DistLock":
        """Locks are identified by address in the reference; by name here."""
        with cls._registry_lock:
            lk = cls._registry.get(name)
            if lk is None:
                lk = cls._registry[name] = DistLock(name)
            return lk

    def lock(self) -> None:
        my_release = Promise()
        with self._lock:
            prev, self._tail = self._tail, my_release
        if prev is not None:
            prev.future.wait()
        self._holder_release = my_release

    def unlock(self) -> None:
        rel = self._holder_release
        if rel is None:
            raise RuntimeError("unlock without holding the lock")
        self._holder_release = None
        with self._lock:
            if self._tail is rel:
                self._tail = None  # no waiters: reset the chain
        rel.put(None)

    def __enter__(self) -> "DistLock":
        self.lock()
        return self

    def __exit__(self, *exc) -> bool:
        self.unlock()
        return False
