"""PGAS layer: global pointers, shared arrays, dependency-chained asyncs.

Reference (modules/upcxx/): wraps UPC++ v1 - ``global_ptr`` (a {rank, addr}
pair any rank can dereference), cyclically distributed ``shared_array``,
``async_after`` chaining remote asyncs onto hclib futures, and
``remote_finish`` awaiting all outstanding remote ops
(inc/hclib_upcxx.h:59-164, 218-230; src/hclib_upcxx.cpp:73-126).

Here a GlobalRef addresses an element slice of a symmetric allocation
(oneside.SymArray) on a specific rank; shared arrays distribute elements
cyclically across ranks the way UPC++ shared_array does. Device-bound ranks
keep their shard in HBM; dereferencing a remote element is the same ICI/DCN
transfer as a one-sided get.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

import numpy as np

from ..runtime.promise import Future
from ..runtime.scheduler import async_future
from .am import async_remote
from .oneside import SymArray, iget, iput
from .world import World, current_world

__all__ = ["GlobalRef", "SharedArray", "async_after", "remote_finish"]


class GlobalRef:
    """{rank, array, index}: a dereferenceable global pointer
    (upcxx::global_ptr, modules/upcxx/inc/hclib_upcxx.h:59-101)."""

    __slots__ = ("array", "rank", "index")

    def __init__(self, array: SymArray, rank: int, index: Any = None) -> None:
        array.world._check(rank)
        self.array = array
        self.rank = rank
        self.index = index

    def get(self) -> Any:
        return iget(self.array, self.rank, self.index).wait()

    def put(self, value: Any) -> None:
        iput(self.array, self.rank, value, self.index).wait()

    def iget(self) -> Future:
        return iget(self.array, self.rank, self.index)

    def iput(self, value: Any) -> Future:
        return iput(self.array, self.rank, value, self.index)

    def __add__(self, offset: int) -> "GlobalRef":
        base = 0 if self.index is None else self.index
        return GlobalRef(self.array, self.rank, base + offset)


class SharedArray:
    """Cyclic distribution of n elements over the world's ranks
    (upcxx::shared_array, modules/upcxx/inc/hclib_upcxx.h:120-164):
    element i lives on rank i % size, local slot i // size."""

    def __init__(
        self,
        n: int,
        dtype=np.int64,
        fill: Any = 0,
        world: Optional[World] = None,
    ) -> None:
        self.world = world if world is not None else current_world()
        self.n = int(n)
        per_rank = (self.n + self.world.size - 1) // self.world.size
        self._backing = SymArray(self.world, (max(per_rank, 1),), dtype, fill)

    def ref(self, i: int) -> GlobalRef:
        if not (0 <= i < self.n):
            raise IndexError(f"index {i} out of range [0, {self.n})")
        return GlobalRef(self._backing, i % self.world.size, i // self.world.size)

    def __getitem__(self, i: int) -> Any:
        return self.ref(i).get()

    def __setitem__(self, i: int, value: Any) -> None:
        self.ref(i).put(value)


def async_after(fut: Future, fn: Callable[..., Any], *args: Any) -> Future:
    """Chain ``fn`` after ``fut`` (upcxx async_after,
    modules/upcxx/inc/hclib_upcxx.h:218-230): runs once the dependency is
    satisfied, returns the result future - pure DDF composition."""
    return async_future(fn, *args, await_=(fut,))


class remote_finish:
    """``with remote_finish():`` waits for every remote op issued in the
    block (upcxx remote_finish + async_wait,
    modules/upcxx/src/hclib_upcxx.cpp:73-126). Ops register via ``track``;
    ``async_remote``/GlobalRef futures passed to ``track`` are awaited at
    block exit."""

    _tls = threading.local()

    def __init__(self) -> None:
        self._futs: List[Future] = []

    @classmethod
    def current(cls) -> Optional["remote_finish"]:
        return getattr(cls._tls, "active", None)

    def track(self, fut: Future) -> Future:
        self._futs.append(fut)
        return fut

    def remote(self, fn: Callable[..., Any], rank: int, *args: Any) -> Future:
        """async_remote tracked by this scope."""
        return self.track(async_remote(fn, rank, *args))

    def __enter__(self) -> "remote_finish":
        self._prev = remote_finish.current()
        remote_finish._tls.active = self
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        remote_finish._tls.active = self._prev
        for f in self._futs:
            f.wait()
        return False
