"""Ranks: the TPU-native reinterpretation of the reference's PEs.

The reference's comm modules address *processing elements* - MPI/SHMEM
processes launched by mpirun, each owning its memory (modules/openshmem/src/
hclib_openshmem.cpp:218-231 maps PEs to locales). JAX is single-controller:
one Python process drives every device, across hosts when jax.distributed is
initialized. So a *rank* here is a logical endpoint bound to (a) a mesh
device when one is available - data lives in that device's HBM and "remote"
access is a device-to-device ICI/DCN transfer - and (b) a locale in the
runtime's locality graph, so tasks can be placed "at rank r" and serviced by
the workers whose paths cover that locale.

``World`` is the shared rank table used by the comm/oneside/am/pgas modules.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

from ..runtime.locality import Locale
from ..runtime.scheduler import current_runtime

__all__ = ["World", "current_world", "set_world"]


class World:
    def __init__(
        self,
        n_ranks: int,
        devices: Optional[Sequence] = None,
        locales: Optional[Sequence[Locale]] = None,
    ) -> None:
        if n_ranks <= 0:
            raise ValueError("world needs at least one rank")
        self.size = n_ranks
        self.devices: List = list(devices) if devices else []
        if self.devices and len(self.devices) < n_ranks:
            raise ValueError(f"world of {n_ranks} ranks given {len(self.devices)} devices")
        self.locales: List[Optional[Locale]] = (
            list(locales) if locales else [None] * n_ranks
        )
        if len(self.locales) < n_ranks:
            raise ValueError("need one locale (or None) per rank")

    def device_for(self, rank: int):
        self._check(rank)
        return self.devices[rank] if self.devices else None

    def locale_for(self, rank: int) -> Optional[Locale]:
        self._check(rank)
        return self.locales[rank]

    def _check(self, rank: int) -> None:
        if not (0 <= rank < self.size):
            raise ValueError(f"rank {rank} out of range [0, {self.size})")

    @staticmethod
    def from_runtime(runtime=None, devices: Optional[Sequence] = None) -> "World":
        """Derive a world from the active runtime's locality graph: one rank
        per ``tpu`` locale when the graph has them (mesh graphs,
        parallel/mesh.py), else one rank per worker bound to its closest
        locale (the default star graph)."""
        rt = runtime if runtime is not None else current_runtime()
        tpu_locales = rt.graph.locales_of_type("tpu")
        if tpu_locales:
            devs = devices or [l.metadata.get("device") for l in tpu_locales]
            if any(d is None for d in devs):
                devs = None
            return World(len(tpu_locales), devs, tpu_locales)
        locales = [rt.graph.closest_locale(w) for w in range(rt.nworkers)]
        return World(rt.nworkers, devices, locales)


_lock = threading.Lock()
_world: Optional[World] = None


def set_world(world: Optional[World]) -> Optional[World]:
    global _world
    with _lock:
        prev, _world = _world, world
    return prev


def current_world() -> World:
    """The active world; lazily derived from the runtime if unset."""
    global _world
    with _lock:
        if _world is None:
            _world = World.from_runtime()
        return _world
