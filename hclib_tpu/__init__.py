"""hclib_tpu: a TPU-native task-parallel framework.

A from-scratch rebuild of the capabilities of HClib (habanero-rice/hclib) on
the JAX/XLA/Pallas stack: finish/async structured parallelism, data-driven
futures/promises, locality-aware parallel loops, and work stealing - with the
execution core re-imagined as a persistent Pallas "megakernel" in which each
TPU core runs a resident scheduler loop over device-memory task queues.

Layers:
- ``hclib_tpu.runtime``  - host runtime (semantics, work-stealing workers)
- ``hclib_tpu.device``   - task-descriptor ABI + Pallas megakernel scheduler
- ``hclib_tpu.parallel`` - device mesh, sharding, collectives, multi-chip
- ``hclib_tpu.ops``      - Pallas/MXU tile kernels used by device tasks
- ``hclib_tpu.models``   - benchmark workloads (fib, UTS, Cholesky, SW, ...)
- ``hclib_tpu.native``   - C++ native host runtime (fast CPU path)
"""

from .runtime import (  # noqa: F401
    FLAT,
    RECURSIVE,
    Autoscaler,
    AutoscalerPolicy,
    BundleFault,
    BundleStore,
    CancelScope,
    CancelledError,
    CheckpointBundle,
    CheckpointError,
    DeviceFaultPlan,
    FaultPlan,
    Finish,
    Future,
    InjectedFault,
    Locale,
    LocalityGraph,
    MaxReducer,
    MeshPlacement,
    MetricsRegistry,
    Module,
    Observation,
    OrReducer,
    Promise,
    PromiseError,
    Reducer,
    RetryPolicy,
    Runtime,
    ScaleEvent,
    StallError,
    SumReducer,
    Task,
    WSDeque,
    allocate_at,
    async_,
    async_copy,
    async_future,
    checkpoint_on_preempt,
    current_finish,
    current_runtime,
    current_worker,
    default_store,
    end_finish,
    end_finish_nonblocking,
    finish,
    forasync,
    forasync_future,
    free_at,
    generate_default_graph,
    restore_megakernel,
    restore_resident,
    restore_stream,
    snapshot_megakernel,
    snapshot_resident,
    snapshot_stream,
    launch,
    load_locality_file,
    memset_at,
    num_workers,
    register_dist_func,
    register_module,
    resolve_placement,
    run_on_main,
    start_finish,
    steal_hop_order,
    unregister_all_modules,
    yield_,
)

__version__ = "0.1.0"
