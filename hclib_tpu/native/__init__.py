"""ctypes bindings for the native host runtime (built on demand).

The C++ core (src/) is the fast host-side work-stealing engine: Chase-Lev
deques with C++11 atomics, pthread workers, help-first finish joins, and
native implementations of the benchmark workloads (fib, UTS with an in-house
FIPS-180-1 SHA-1, arrayadd). It provides the compiled CPU baseline the
device megakernel is measured against, and the host-side queue engine for
feeding device work.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

def _csr(paths):
    """Flatten per-worker locale paths to CSR (offsets, data) int arrays."""
    off = [0]
    data = []
    for p in paths:
        data.extend(int(x) for x in p)
        off.append(len(data))
    return (ctypes.c_int * len(off))(*off), (ctypes.c_int * max(1, len(data)))(*data)


_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libhclib_native.so")
_lib = None


class NativeBuildError(RuntimeError):
    pass


# Callback signatures crossing the ctypes boundary (tasks and loop bodies).
TASK_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
LOOP1_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_long)
LOOP2_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_long, ctypes.c_long)


def _build() -> None:
    try:
        subprocess.run(
            ["make", "-s"], cwd=_DIR, check=True, capture_output=True, text=True
        )
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        raise NativeBuildError(f"native runtime build failed: {detail}") from e


def load() -> ctypes.CDLL:
    """Build (if needed) and load the native library."""
    global _lib
    if _lib is not None:
        return _lib
    src_newer = not os.path.exists(_LIB_PATH) or any(
        os.path.getmtime(os.path.join(_DIR, "src", f)) > os.path.getmtime(_LIB_PATH)
        for f in os.listdir(os.path.join(_DIR, "src"))
    )
    if src_newer:
        _build()
    lib = ctypes.CDLL(_LIB_PATH)
    lib.hcn_create.restype = ctypes.c_void_p
    lib.hcn_create.argtypes = [ctypes.c_int]
    lib.hcn_destroy.argtypes = [ctypes.c_void_p]
    lib.hcn_nworkers.restype = ctypes.c_int
    lib.hcn_nworkers.argtypes = [ctypes.c_void_p]
    lib.hcn_pinned_cpu.restype = ctypes.c_int
    lib.hcn_pinned_cpu.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.hcn_typed_promise_demo.restype = ctypes.c_longlong
    lib.hcn_typed_promise_demo.argtypes = [ctypes.c_void_p]
    lib.hcn_executed.restype = ctypes.c_ulonglong
    lib.hcn_executed.argtypes = [ctypes.c_void_p]
    lib.hcn_steals.restype = ctypes.c_ulonglong
    lib.hcn_steals.argtypes = [ctypes.c_void_p]
    lib.hcn_fib.restype = ctypes.c_longlong
    lib.hcn_fib.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.hcn_fib_ddt.restype = ctypes.c_longlong
    lib.hcn_fib_ddt.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.hcn_smithwaterman.restype = ctypes.c_int
    lib.hcn_smithwaterman.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    pint = ctypes.POINTER(ctypes.c_int)
    lib.hcn_create_graph.restype = ctypes.c_void_p
    lib.hcn_create_graph.argtypes = [ctypes.c_int, ctypes.c_int, pint, pint, pint, pint]
    lib.hcn_nlocales.restype = ctypes.c_int
    lib.hcn_nlocales.argtypes = [ctypes.c_void_p]
    lib.hcn_backlog.restype = ctypes.c_long
    lib.hcn_backlog.argtypes = [ctypes.c_void_p]
    lib.hcn_steal_matrix.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_ulonglong),
    ]
    lib.hcn_format_stats.restype = ctypes.c_int
    lib.hcn_format_stats.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.hcn_finish_new.restype = ctypes.c_void_p
    lib.hcn_finish_new.argtypes = [ctypes.c_void_p]
    lib.hcn_finish_end.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.hcn_finish_end_nonblocking.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.hcn_finish_free.argtypes = [ctypes.c_void_p]
    lib.hcn_async.argtypes = [
        ctypes.c_void_p, TASK_FN, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int, ctypes.POINTER(ctypes.c_void_p), ctypes.c_int, ctypes.c_int,
    ]
    lib.hcn_yield.restype = ctypes.c_int
    lib.hcn_yield.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.hcn_promise_new.restype = ctypes.c_void_p
    lib.hcn_promise_new.argtypes = []
    lib.hcn_promise_free.argtypes = [ctypes.c_void_p]
    lib.hcn_promise_put.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.hcn_promise_get.restype = ctypes.c_void_p
    lib.hcn_promise_get.argtypes = [ctypes.c_void_p]
    lib.hcn_promise_satisfied.restype = ctypes.c_int
    lib.hcn_promise_satisfied.argtypes = [ctypes.c_void_p]
    lib.hcn_promise_wait.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.hcn_forasync1d.argtypes = [
        ctypes.c_void_p, LOOP1_FN, ctypes.c_void_p,
        ctypes.c_long, ctypes.c_long, ctypes.c_int,
    ]
    lib.hcn_forasync2d.argtypes = [
        ctypes.c_void_p, LOOP2_FN, ctypes.c_void_p,
        ctypes.c_long, ctypes.c_long, ctypes.c_long, ctypes.c_long,
    ]
    lib.hcn_uts.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_double,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_ulonglong),
        ctypes.POINTER(ctypes.c_ulonglong),
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.hcn_arrayadd.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_long,
        ctypes.c_long,
    ]
    _lib = lib
    return lib


class NativePromise:
    """Handle to a native single-assignment promise. Values are machine
    words (ints); the Python layer uses it for completion signalling and
    small payloads."""

    def __init__(self, rt: "NativeRuntime") -> None:
        self._rt = rt
        self._p = rt._lib.hcn_promise_new()

    def put(self, value: int = 0) -> None:
        self._rt._lib.hcn_promise_put(self._rt._handle, self._p, ctypes.c_void_p(value))

    def get(self) -> int:
        return int(self._rt._lib.hcn_promise_get(self._p) or 0)

    @property
    def satisfied(self) -> bool:
        return bool(self._rt._lib.hcn_promise_satisfied(self._p))

    def wait(self) -> int:
        self._rt._lib.hcn_promise_wait(self._rt._handle, self._p)
        return self.get()

    def free(self) -> None:
        if self._p is not None:
            self._rt._lib.hcn_promise_free(self._p)
            self._p = None


class NativeFinish:
    """Finish scope over the native runtime (blocking on exit)."""

    def __init__(self, rt: "NativeRuntime") -> None:
        self._rt = rt
        self._f = rt._lib.hcn_finish_new(rt._handle)

    def __enter__(self) -> "NativeFinish":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end()

    def end(self) -> None:
        if self._f is not None:
            self._rt._lib.hcn_finish_end(self._rt._handle, self._f)
            self._rt._lib.hcn_finish_free(self._f)
            self._f = None

    def end_nonblocking(self) -> NativePromise:
        """Detach: returned promise is satisfied when the scope drains
        (hclib_end_finish_nonblocking, src/hclib-runtime.c:1279-1313)."""
        p = NativePromise(self._rt)
        self._rt._lib.hcn_finish_end_nonblocking(self._rt._handle, self._f, p._p)
        self._f = None  # detached; the runtime frees the scope on drain
        return p


class NativeRuntime:
    """RAII wrapper over the native scheduler."""

    def __init__(self, nworkers: Optional[int] = None, graph=None) -> None:
        self._lib = load()
        self._live: dict = {}  # id -> ctypes callback, kept alive until executed
        if graph is not None:
            nworkers = graph.nworkers
            pop_off, pop_data = _csr([graph.pop_paths[w] for w in range(nworkers)])
            st_off, st_data = _csr([graph.steal_paths[w] for w in range(nworkers)])
            self._rt = self._lib.hcn_create_graph(
                nworkers, len(graph.locales), pop_off, pop_data, st_off, st_data
            )
        else:
            if nworkers is None:
                nworkers = os.cpu_count() or 1
            self._rt = self._lib.hcn_create(nworkers)
        self.nworkers = nworkers

    def close(self) -> None:
        if self._rt is not None:
            self._lib.hcn_destroy(self._rt)
            self._rt = None

    def __enter__(self) -> "NativeRuntime":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def _handle(self):
        if self._rt is None:
            raise RuntimeError("NativeRuntime used after close()")
        return self._rt

    @property
    def executed(self) -> int:
        return int(self._lib.hcn_executed(self._handle))

    @property
    def steals(self) -> int:
        return int(self._lib.hcn_steals(self._handle))

    def pinned_cpus(self) -> list:
        """Per-worker pinned CPU ids (-1 = unpinned). Pinning is opt-in
        via HCLIB_TPU_AFFINITY / HCLIB_AFFINITY = "strided" | "chunked"
        at runtime creation (reference: HCLIB_AFFINITY hwloc cpusets,
        src/hclib-runtime.c:731-900)."""
        h = self._handle
        return [
            int(self._lib.hcn_pinned_cpu(h, w)) for w in range(self.nworkers)
        ]

    # -- tasking API ------------------------------------------------------

    def promise(self) -> NativePromise:
        return NativePromise(self)

    def finish(self) -> NativeFinish:
        return NativeFinish(self)

    def async_(
        self,
        fn,
        finish: Optional[NativeFinish] = None,
        locale: int = 0,
        deps=(),
        non_blocking: bool = False,
    ) -> None:
        """Spawn a Python callable as a native task (worker threads call
        back through ctypes, which re-acquires the GIL per task).

        ``non_blocking`` is advisory parity metadata (reference async_nb):
        this engine's work-shift model may inline any ready task, so the
        flag does not change scheduling. Submissions from threads other
        than runtime workers are routed through an injection queue; blocking
        calls from such threads require nworkers >= 2 to make progress."""

        cb_box = []

        def tramp(_env):
            try:
                fn()
            finally:
                self._live.pop(id(cb_box[0]), None)

        cb = TASK_FN(tramp)
        cb_box.append(cb)
        self._live[id(cb)] = cb
        dep_arr = (
            (ctypes.c_void_p * len(deps))(*[p._p for p in deps]) if deps else None
        )
        self._lib.hcn_async(
            self._handle,
            cb,
            None,
            finish._f if finish is not None else None,
            locale,
            dep_arr,
            len(deps),
            int(non_blocking),
        )

    def yield_(self, locale: int = -1) -> bool:
        return bool(self._lib.hcn_yield(self._handle, locale))

    def forasync1d(self, fn, n: int, tile: int = 0, recursive: bool = False) -> None:
        cb = LOOP1_FN(lambda _env, i: fn(i))
        self._lib.hcn_forasync1d(
            self._handle, cb, None, n, tile, 1 if recursive else 0
        )

    def forasync2d(self, fn, n0: int, n1: int, tile0: int = 0, tile1: int = 0) -> None:
        cb = LOOP2_FN(lambda _env, i, j: fn(i, j))
        self._lib.hcn_forasync2d(self._handle, cb, None, n0, n1, tile0, tile1)

    # -- introspection ----------------------------------------------------

    @property
    def nlocales(self) -> int:
        return int(self._lib.hcn_nlocales(self._handle))

    @property
    def backlog(self) -> int:
        return int(self._lib.hcn_backlog(self._handle))

    def steal_matrix(self):
        n = self.nworkers
        buf = (ctypes.c_ulonglong * (n * n))()
        self._lib.hcn_steal_matrix(self._handle, buf)
        return [[int(buf[w * n + v]) for v in range(n)] for w in range(n)]

    def format_stats(self) -> str:
        n = self._lib.hcn_format_stats(self._handle, None, 0)
        buf = ctypes.create_string_buffer(n + 1)
        self._lib.hcn_format_stats(self._handle, buf, n + 1)
        return buf.value.decode()

    # -- native workloads -------------------------------------------------

    def fib(self, n: int) -> int:
        return int(self._lib.hcn_fib(self._handle, n))

    def fib_ddt(self, n: int) -> int:
        return int(self._lib.hcn_fib_ddt(self._handle, n))

    def smithwaterman(self, nx: int, ny: int, ts: int, seed: int = 1) -> int:
        return int(self._lib.hcn_smithwaterman(self._handle, nx, ny, ts, seed))

    def uts(self, shape: int, gen_mx: int, b0: float, seed: int) -> Tuple[int, int, int]:
        nodes = ctypes.c_ulonglong()
        leaves = ctypes.c_ulonglong()
        depth = ctypes.c_int()
        self._lib.hcn_uts(
            self._handle, shape, gen_mx, b0, seed,
            ctypes.byref(nodes), ctypes.byref(leaves), ctypes.byref(depth),
        )
        return int(nodes.value), int(leaves.value), int(depth.value)

    def arrayadd(self, a, b, c, tile: int = 4096) -> None:
        import numpy as np

        for name, arr in (("a", a), ("b", b), ("c", c)):
            if not isinstance(arr, np.ndarray) or arr.dtype != np.float64:
                raise TypeError(f"{name} must be a float64 ndarray")
            if not arr.flags["C_CONTIGUOUS"]:
                raise ValueError(f"{name} must be C-contiguous")
        n = len(a)
        if len(b) != n or len(c) != n:
            raise ValueError("a, b, c must have equal length")
        if tile <= 0:
            raise ValueError("tile must be positive")
        pd = ctypes.POINTER(ctypes.c_double)
        self._lib.hcn_arrayadd(
            self._handle,
            a.ctypes.data_as(pd),
            b.ctypes.data_as(pd),
            c.ctypes.data_as(pd),
            n,
            tile,
        )
