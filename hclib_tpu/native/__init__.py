"""ctypes bindings for the native host runtime (built on demand).

The C++ core (src/) is the fast host-side work-stealing engine: Chase-Lev
deques with C++11 atomics, pthread workers, help-first finish joins, and
native implementations of the benchmark workloads (fib, UTS with an in-house
FIPS-180-1 SHA-1, arrayadd). It provides the compiled CPU baseline the
device megakernel is measured against, and the host-side queue engine for
feeding device work.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libhclib_native.so")
_lib = None


class NativeBuildError(RuntimeError):
    pass


def _build() -> None:
    try:
        subprocess.run(
            ["make", "-s"], cwd=_DIR, check=True, capture_output=True, text=True
        )
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        raise NativeBuildError(f"native runtime build failed: {detail}") from e


def load() -> ctypes.CDLL:
    """Build (if needed) and load the native library."""
    global _lib
    if _lib is not None:
        return _lib
    src_newer = not os.path.exists(_LIB_PATH) or any(
        os.path.getmtime(os.path.join(_DIR, "src", f)) > os.path.getmtime(_LIB_PATH)
        for f in os.listdir(os.path.join(_DIR, "src"))
    )
    if src_newer:
        _build()
    lib = ctypes.CDLL(_LIB_PATH)
    lib.hcn_create.restype = ctypes.c_void_p
    lib.hcn_create.argtypes = [ctypes.c_int]
    lib.hcn_destroy.argtypes = [ctypes.c_void_p]
    lib.hcn_nworkers.restype = ctypes.c_int
    lib.hcn_nworkers.argtypes = [ctypes.c_void_p]
    lib.hcn_executed.restype = ctypes.c_ulonglong
    lib.hcn_executed.argtypes = [ctypes.c_void_p]
    lib.hcn_steals.restype = ctypes.c_ulonglong
    lib.hcn_steals.argtypes = [ctypes.c_void_p]
    lib.hcn_fib.restype = ctypes.c_longlong
    lib.hcn_fib.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.hcn_uts.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_double,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_ulonglong),
        ctypes.POINTER(ctypes.c_ulonglong),
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.hcn_arrayadd.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_long,
        ctypes.c_long,
    ]
    _lib = lib
    return lib


class NativeRuntime:
    """RAII wrapper over the native scheduler."""

    def __init__(self, nworkers: Optional[int] = None) -> None:
        self._lib = load()
        if nworkers is None:
            nworkers = os.cpu_count() or 1
        self._rt = self._lib.hcn_create(nworkers)
        self.nworkers = nworkers

    def close(self) -> None:
        if self._rt is not None:
            self._lib.hcn_destroy(self._rt)
            self._rt = None

    def __enter__(self) -> "NativeRuntime":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def _handle(self):
        if self._rt is None:
            raise RuntimeError("NativeRuntime used after close()")
        return self._rt

    @property
    def executed(self) -> int:
        return int(self._lib.hcn_executed(self._handle))

    @property
    def steals(self) -> int:
        return int(self._lib.hcn_steals(self._handle))

    def fib(self, n: int) -> int:
        return int(self._lib.hcn_fib(self._handle, n))

    def uts(self, shape: int, gen_mx: int, b0: float, seed: int) -> Tuple[int, int, int]:
        nodes = ctypes.c_ulonglong()
        leaves = ctypes.c_ulonglong()
        depth = ctypes.c_int()
        self._lib.hcn_uts(
            self._handle, shape, gen_mx, b0, seed,
            ctypes.byref(nodes), ctypes.byref(leaves), ctypes.byref(depth),
        )
        return int(nodes.value), int(leaves.value), int(depth.value)

    def arrayadd(self, a, b, c, tile: int = 4096) -> None:
        import numpy as np

        for name, arr in (("a", a), ("b", b), ("c", c)):
            if not isinstance(arr, np.ndarray) or arr.dtype != np.float64:
                raise TypeError(f"{name} must be a float64 ndarray")
            if not arr.flags["C_CONTIGUOUS"]:
                raise ValueError(f"{name} must be C-contiguous")
        n = len(a)
        if len(b) != n or len(c) != n:
            raise ValueError("a, b, c must have equal length")
        if tile <= 0:
            raise ValueError("tile must be positive")
        pd = ctypes.POINTER(ctypes.c_double)
        self._lib.hcn_arrayadd(
            self._handle,
            a.ctypes.data_as(pd),
            b.ctypes.data_as(pd),
            c.ctypes.data_as(pd),
            n,
            tile,
        )
