// extern "C" API surface (ctypes boundary) + native benchmark workloads.
//
// The C ABI mirrors the reference's C API split (inc/hclib.h): runtime
// lifecycle, async spawn with promise dependencies, finish scopes, promise
// put/get/wait, forasync loops, yield, and stats introspection. Workloads
// (fib, fib-ddt, UTS, arrayadd, Smith-Waterman wavefront) are the native
// counterparts of the reference's test/ benchmark programs.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>

#include "cppapi.hpp"
#include "runtime.hpp"
#include "sha1.hpp"

using hcn::FinishScope;
using hcn::GraphSpec;
using hcn::NPromise;
using hcn::NTask;
using hcn::Runtime;

extern "C" {

// ---------------------------------------------------------------- lifecycle

void* hcn_create(int nworkers) { return new Runtime(nworkers); }

// Locality-aware constructor: paths in CSR form (see GraphSpec).
void* hcn_create_graph(int nworkers, int nlocales, const int* pop_off,
                       const int* pop_data, const int* steal_off,
                       const int* steal_data) {
  GraphSpec g;
  g.nlocales = nlocales;
  g.pop_off.assign(pop_off, pop_off + nworkers + 1);
  g.pop_data.assign(pop_data, pop_data + pop_off[nworkers]);
  g.steal_off.assign(steal_off, steal_off + nworkers + 1);
  g.steal_data.assign(steal_data, steal_data + steal_off[nworkers]);
  return new Runtime(nworkers, std::move(g));
}

void hcn_destroy(void* rt) { delete static_cast<Runtime*>(rt); }
int hcn_nworkers(void* rt) { return static_cast<Runtime*>(rt)->nworkers(); }
int hcn_pinned_cpu(void* rt, int w) {
  return static_cast<Runtime*>(rt)->pinned_cpu(w);
}
int hcn_nlocales(void* rt) { return static_cast<Runtime*>(rt)->nlocales(); }
unsigned long long hcn_executed(void* rt) {
  return static_cast<Runtime*>(rt)->total_executed();
}
unsigned long long hcn_steals(void* rt) {
  return static_cast<Runtime*>(rt)->total_steals();
}
long hcn_backlog(void* rt) {
  return static_cast<long>(static_cast<Runtime*>(rt)->backlog());
}

// Per-worker steal matrix: out[w * nworkers + v] = tasks w stole from v.
void hcn_steal_matrix(void* rtp, unsigned long long* out) {
  Runtime* rt = static_cast<Runtime*>(rtp);
  int n = rt->nworkers();
  for (int w = 0; w < n; ++w) {
    const auto& s = rt->worker_stats(w);
    for (int v = 0; v < n; ++v) out[w * n + v] = s.stolen_from[v];
  }
}

int hcn_format_stats(void* rtp, char* buf, int len) {
  std::string s = static_cast<Runtime*>(rtp)->format_stats();
  int n = static_cast<int>(s.size());
  if (buf != nullptr && len > 0) {
    int c = n < len - 1 ? n : len - 1;
    std::memcpy(buf, s.data(), c);
    buf[c] = '\0';
  }
  return n;
}

// ------------------------------------------------------------ task spawning

void hcn_run_root(void* rt, void (*fn)(void*), void* env) {
  static_cast<Runtime*>(rt)->run_root(fn, env);
}

// Finish scope handles for foreign callers. Counter starts at 1 (owner token).
void* hcn_finish_new(void* rtp) {
  FinishScope* f = new FinishScope;
  f->rt = static_cast<Runtime*>(rtp);
  f->parent = f->rt->current_finish();
  return f;
}

void hcn_finish_end(void* rtp, void* f) {
  static_cast<Runtime*>(rtp)->end_finish(static_cast<FinishScope*>(f));
}

// Nonblocking end: promise `dep` is satisfied when the scope drains.
void hcn_finish_end_nonblocking(void* rtp, void* f, void* dep) {
  static_cast<Runtime*>(rtp)->end_finish_nonblocking(
      static_cast<FinishScope*>(f), static_cast<NPromise*>(dep));
}

void hcn_finish_free(void* f) { delete static_cast<FinishScope*>(f); }

// Spawn fn(env) under `finish` (nullable) at `locale`, blocked on `deps`.
void hcn_async(void* rtp, void (*fn)(void*), void* env, void* finish,
               int locale, void** deps, int ndeps, int non_blocking) {
  Runtime* rt = static_cast<Runtime*>(rtp);
  NTask* t = hcn::task_alloc();
  t->fn = fn;
  t->env = env;
  t->finish = static_cast<FinishScope*>(finish);
  t->locale = locale;
  t->non_blocking = non_blocking != 0;
  for (int i = 0; i < ndeps; ++i) {
    t->add_dep(static_cast<NPromise*>(deps[i]));
  }
  rt->spawn(t);
}

int hcn_yield(void* rtp, int locale) {
  return static_cast<Runtime*>(rtp)->yield(locale) ? 1 : 0;
}

// --------------------------------------------------------------- promises

void* hcn_promise_new(void) { return new NPromise; }
void hcn_promise_free(void* p) { delete static_cast<NPromise*>(p); }
void hcn_promise_put(void* rtp, void* p, void* value) {
  static_cast<Runtime*>(rtp)->promise_put(static_cast<NPromise*>(p), value);
}
void* hcn_promise_get(void* p) { return static_cast<NPromise*>(p)->get(); }
int hcn_promise_satisfied(void* p) {
  return static_cast<NPromise*>(p)->satisfied() ? 1 : 0;
}
void hcn_promise_wait(void* rtp, void* p) {
  static_cast<Runtime*>(rtp)->future_wait(static_cast<NPromise*>(p));
}

// --------------------------------------------------------------- forasync
// Blocking loop parallelism over an index callback; mode 0 = flat tiles,
// 1 = recursive splitting (src/hclib.c:158-416).

namespace {
struct LoopRoot {
  Runtime* rt;
  void (*fn)(void*, long);
  void* env;
  long n, tile;
  int mode;
};

void forasync1d_root(void* pv) {
  LoopRoot* e = static_cast<LoopRoot*>(pv);
  // Capture by value: spawned tiles run after this root task returns.
  auto fn = e->fn;
  auto env = e->env;
  auto body = [fn, env](long i) { fn(env, i); };
  hcn::forasync1d(e->n, body, e->tile,
                  e->mode == 0 ? hcn::ForasyncMode::kFlat
                               : hcn::ForasyncMode::kRecursive);
  delete e;
}

struct Loop2Root {
  Runtime* rt;
  void (*fn)(void*, long, long);
  void* env;
  long n0, n1, tile0, tile1;
};

void forasync2d_root(void* pv) {
  Loop2Root* e = static_cast<Loop2Root*>(pv);
  auto fn = e->fn;
  auto env = e->env;
  auto body = [fn, env](long i, long j) { fn(env, i, j); };
  hcn::forasync2d(e->n0, e->n1, body, e->tile0, e->tile1);
  delete e;
}
}  // namespace

void hcn_forasync1d(void* rtp, void (*fn)(void*, long), void* env, long n,
                    long tile, int mode) {
  Runtime* rt = static_cast<Runtime*>(rtp);
  rt->run_root(forasync1d_root, new LoopRoot{rt, fn, env, n, tile, mode});
}

void hcn_forasync2d(void* rtp, void (*fn)(void*, long, long), void* env,
                    long n0, long n1, long tile0, long tile1) {
  Runtime* rt = static_cast<Runtime*>(rtp);
  rt->run_root(forasync2d_root,
               new Loop2Root{rt, fn, env, n0, n1, tile0, tile1});
}

// ------------------------------------------------------------------ fib

namespace {
void fib_rec(int n, long long* out) {
  if (n < 2) {
    *out = n;
    return;
  }
  long long a = 0, b = 0;
  // Both children spawn as tasks (one task per fib node), matching the
  // device megakernel's fib graph so tasks/sec is comparable across
  // engines.
  hcn::finish([&] {
    hcn::async([n, &a] { fib_rec(n - 1, &a); });
    hcn::async([n, &b] { fib_rec(n - 2, &b); });
  });
  *out = a + b;
}

struct FibRoot {
  int n;
  long long* out;
};
void fib_root(void* pv) {
  FibRoot* e = static_cast<FibRoot*>(pv);
  fib_rec(e->n, e->out);
  delete e;
}
}  // namespace

long long hcn_fib(void* rtp, int n) {
  long long result = 0;
  static_cast<Runtime*>(rtp)->run_root(fib_root, new FibRoot{n, &result});
  return result;
}

// -------------------------------------------------------------- fib-ddt
// Promise-based fib (reference workload test/misc/fib-ddt): every node puts
// its value into a promise; join tasks await both child promises. Exercises
// the DDF waiter-list machinery end to end.

namespace {
void fib_ddt_node(int n, NPromise* res) {
  if (n < 2) {
    Runtime::current()->promise_put(res, (void*)(intptr_t)n);
    return;
  }
  NPromise* l = new NPromise;
  NPromise* r = new NPromise;
  hcn::async([n, l] { fib_ddt_node(n - 1, l); });
  hcn::async([n, r] { fib_ddt_node(n - 2, r); });
  hcn::async_await(
      [l, r, res] {
        intptr_t a = (intptr_t)l->get();
        intptr_t b = (intptr_t)r->get();
        Runtime::current()->promise_put(res, (void*)(a + b));
        delete l;
        delete r;
      },
      {l, r});
}

struct FibDdtRoot {
  int n;
  long long* out;
};
void fib_ddt_root(void* pv) {
  FibDdtRoot* e = static_cast<FibDdtRoot*>(pv);
  NPromise res;
  fib_ddt_node(e->n, &res);
  // The root finish drains every spawned task (including the final put)
  // before run_root returns, so read after the implicit end-finish via a
  // future-wait here (help-first inline execution).
  Runtime::current()->future_wait(&res);
  *e->out = (long long)(intptr_t)res.get();
  delete e;
}
}  // namespace

long long hcn_fib_ddt(void* rtp, int n) {
  long long result = 0;
  static_cast<Runtime*>(rtp)->run_root(fib_ddt_root, new FibDdtRoot{n, &result});
  return result;
}

// Exercises the typed C++ promise/future layer (promise_t<T>/future_t<T>,
// reference inc/hclib_promise.h:41-124): an int promise chained through
// async_await into a double future; returns 1000*int + (int)double.
namespace {
struct TypedDemo {
  long long* out;
};
void typed_demo_root(void* env) {
  auto* d = static_cast<TypedDemo*>(env);
  long long* out = d->out;
  delete d;
  auto* pi = new hcn::promise_t<int>;
  hcn::future_t<int> fi = pi->get_future();
  hcn::promise_t<double>* pd = nullptr;
  hcn::finish([out, pi, fi, &pd] {
    auto fd = hcn::async_future_t([] { return 2.5; });
    // async_future_t allocated a promise_t<double>; keep the concrete
    // type so the delete below is well-formed (NPromise has no virtual
    // destructor by design - it is a POD-ish machine word cell).
    pd = static_cast<hcn::promise_t<double>*>(fd.raw());
    hcn::async_await(
        [out, fi, fd]() mutable {
          *out = 1000LL * fi.get() + (long long)fd.wait();
        },
        {fi.raw()});
    hcn::async([pi] { pi->put(42); });
  });
  // Caller-owns convention (async_future comment above): reclaim both
  // promises once the finish scope guarantees no task still reads them.
  delete pi;
  delete pd;
}
}  // namespace

long long hcn_typed_promise_demo(void* rtp) {
  long long result = 0;
  static_cast<Runtime*>(rtp)->run_root(typed_demo_root, new TypedDemo{&result});
  return result;
}

// ------------------------------------------------------------------ UTS
// Tree spec re-implemented from the published UTS algorithm (see
// hclib_tpu/models/uts.py for the parameter citations).

namespace {
struct UtsCounters {
  std::atomic<uint64_t> nodes{0};
  std::atomic<uint64_t> leaves{0};
  std::atomic<int> max_depth{0};
};

struct UtsParams {
  int shape;  // 0=LINEAR 1=EXPDEC 2=CYCLIC 3=FIXED
  int gen_mx;
  double b0;
};

int uts_num_children(const UtsParams& p, const uint8_t state[20], int depth) {
  double b_i = p.b0;
  if (depth > 0) {
    switch (p.shape) {
      case 0:
        b_i = p.b0 * (1.0 - double(depth) / double(p.gen_mx));
        break;
      case 1:
        b_i = p.b0 * std::pow(double(depth),
                              -std::log(p.b0) / std::log(double(p.gen_mx)));
        break;
      case 2:
        if (depth > 5 * p.gen_mx)
          b_i = 0.0;
        else
          b_i = std::pow(p.b0, std::sin(2.0 * M_PI * depth / p.gen_mx));
        break;
      case 3:
        b_i = depth < p.gen_mx ? p.b0 : 0.0;
        break;
    }
  }
  if (b_i <= 0.0) return 0;
  uint32_t r = (uint32_t(state[16]) << 24) | (uint32_t(state[17]) << 16) |
               (uint32_t(state[18]) << 8) | uint32_t(state[19]);
  r &= 0x7FFFFFFF;
  double u = double(r) / 2147483648.0;
  double pgeo = 1.0 / (1.0 + b_i);
  int n = int(std::floor(std::log(1.0 - u) / std::log(1.0 - pgeo)));
  return n > 100 ? 100 : n;  // MAXNUMCHILDREN cap
}

struct UtsNode {
  const UtsParams* params;
  UtsCounters* counters;
  uint8_t state[20];
  int depth;
};

void uts_visit(UtsNode node) {
  node.counters->nodes.fetch_add(1, std::memory_order_relaxed);
  int md = node.counters->max_depth.load(std::memory_order_relaxed);
  while (node.depth > md &&
         !node.counters->max_depth.compare_exchange_weak(md, node.depth)) {
  }
  int nc = uts_num_children(*node.params, node.state, node.depth);
  if (nc == 0) {
    node.counters->leaves.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  for (int i = 0; i < nc; ++i) {
    UtsNode c;
    c.params = node.params;
    c.counters = node.counters;
    c.depth = node.depth + 1;
    uint8_t msg[24];
    std::memcpy(msg, node.state, 20);
    msg[20] = (i >> 24) & 0xff;
    msg[21] = (i >> 16) & 0xff;
    msg[22] = (i >> 8) & 0xff;
    msg[23] = i & 0xff;
    hcn::sha1_single_block(msg, 24, c.state);
    hcn::async([c] { uts_visit(c); });
  }
}

struct UtsRoot {
  UtsNode node;
};
void uts_root(void* pv) {
  UtsRoot* e = static_cast<UtsRoot*>(pv);
  uts_visit(e->node);
  delete e;
}
}  // namespace

void hcn_uts(void* rtp, int shape, int gen_mx, double b0, int seed,
             unsigned long long* nodes, unsigned long long* leaves,
             int* max_depth) {
  Runtime* rt = static_cast<Runtime*>(rtp);
  UtsParams params{shape, gen_mx, b0};
  UtsCounters counters;
  UtsRoot* root = new UtsRoot;
  root->node.params = &params;
  root->node.counters = &counters;
  root->node.depth = 0;
  uint8_t msg[20] = {0};
  msg[16] = (seed >> 24) & 0xff;
  msg[17] = (seed >> 16) & 0xff;
  msg[18] = (seed >> 8) & 0xff;
  msg[19] = seed & 0xff;
  hcn::sha1_single_block(msg, 20, root->node.state);
  rt->run_root(uts_root, root);
  *nodes = counters.nodes.load();
  *leaves = counters.leaves.load();
  *max_depth = counters.max_depth.load();
}

// -------------------------------------------------------------- arrayadd

namespace {
struct AddEnv {
  const double* a;
  const double* b;
  double* c;
  long n, tile;
};

void arrayadd_root(void* pv) {
  AddEnv* e = static_cast<AddEnv*>(pv);
  const double* a = e->a;
  const double* b = e->b;
  double* c = e->c;
  hcn::forasync1d(
      e->n, [a, b, c](long i) { c[i] = a[i] + b[i]; }, e->tile);
  delete e;
}
}  // namespace

void hcn_arrayadd(void* rtp, const double* a, const double* b, double* c,
                  long n, long tile) {
  if (tile <= 0) tile = n > 0 ? n : 1;
  static_cast<Runtime*>(rtp)->run_root(arrayadd_root,
                                       new AddEnv{a, b, c, n, tile});
}

// ------------------------------------------- Smith-Waterman tile wavefront
// 2D DDF dependency grid: tile (i,j) awaits the promises of (i-1,j) and
// (i,j-1) (the diagonal is transitively ordered), then fills its DP block
// (reference workload: test/smithwaterman/smith_waterman.cpp:77-180).
// Sequences are generated from a splitmix64 stream; affine-free scoring
// (match +1 / mismatch -1 / gap -1), local alignment (floor at 0).

namespace {
struct SwGrid {
  int nx, ny, ts;
  std::vector<int32_t> h;     // (nx*ts+1) x (ny*ts+1) DP matrix
  std::vector<uint8_t> seq_a;  // length nx*ts
  std::vector<uint8_t> seq_b;  // length ny*ts
  std::vector<NPromise> tile_done;  // nx*ny
  std::atomic<int32_t> best{0};
  int stride() const { return ny * ts + 1; }
};

uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

void sw_tile(SwGrid* g, int ti, int tj) {
  const int ts = g->ts, stride = g->stride();
  int32_t local_best = 0;
  for (int i = ti * ts + 1; i <= (ti + 1) * ts; ++i) {
    for (int j = tj * ts + 1; j <= (tj + 1) * ts; ++j) {
      int s = g->seq_a[i - 1] == g->seq_b[j - 1] ? 1 : -1;
      int32_t diag = g->h[(i - 1) * stride + (j - 1)] + s;
      int32_t up = g->h[(i - 1) * stride + j] - 1;
      int32_t left = g->h[i * stride + (j - 1)] - 1;
      int32_t v = diag > up ? diag : up;
      v = v > left ? v : left;
      v = v > 0 ? v : 0;
      g->h[i * stride + j] = v;
      if (v > local_best) local_best = v;
    }
  }
  int32_t cur = g->best.load(std::memory_order_relaxed);
  while (local_best > cur &&
         !g->best.compare_exchange_weak(cur, local_best)) {
  }
  Runtime::current()->promise_put(&g->tile_done[ti * g->ny + tj], nullptr);
}

struct SwRoot {
  SwGrid* g;
};

void sw_root(void* pv) {
  SwGrid* g = static_cast<SwRoot*>(pv)->g;
  for (int i = 0; i < g->nx; ++i) {
    for (int j = 0; j < g->ny; ++j) {
      NPromise* up = i > 0 ? &g->tile_done[(i - 1) * g->ny + j] : nullptr;
      NPromise* left = j > 0 ? &g->tile_done[i * g->ny + (j - 1)] : nullptr;
      if (up != nullptr && left != nullptr) {
        hcn::async_await([g, i, j] { sw_tile(g, i, j); }, {up, left});
      } else if (up != nullptr) {
        hcn::async_await([g, i, j] { sw_tile(g, i, j); }, {up});
      } else if (left != nullptr) {
        hcn::async_await([g, i, j] { sw_tile(g, i, j); }, {left});
      } else {
        hcn::async([g, i, j] { sw_tile(g, i, j); });
      }
    }
  }
  delete static_cast<SwRoot*>(pv);
}
}  // namespace

int hcn_smithwaterman(void* rtp, int nx, int ny, int ts, int seed) {
  SwGrid g;
  g.nx = nx;
  g.ny = ny;
  g.ts = ts;
  g.h.assign(size_t(nx * ts + 1) * (ny * ts + 1), 0);
  g.seq_a.resize(size_t(nx) * ts);
  g.seq_b.resize(size_t(ny) * ts);
  g.tile_done = std::vector<NPromise>(size_t(nx) * ny);
  uint64_t s = uint64_t(seed) * 2654435761ULL + 1;
  for (auto& c : g.seq_a) c = uint8_t(splitmix64(s) & 3);
  for (auto& c : g.seq_b) c = uint8_t(splitmix64(s) & 3);
  static_cast<Runtime*>(rtp)->run_root(sw_root, new SwRoot{&g});
  return int(g.best.load());
}

}  // extern "C"
