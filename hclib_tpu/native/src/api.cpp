// extern "C" API surface (ctypes boundary) + native benchmark workloads.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>

#include "runtime.hpp"
#include "sha1.hpp"

using hcn::Finish;
using hcn::Runtime;
using hcn::Task;

extern "C" {

void* hcn_create(int nworkers) { return new Runtime(nworkers); }
void hcn_destroy(void* rt) { delete static_cast<Runtime*>(rt); }
int hcn_nworkers(void* rt) { return static_cast<Runtime*>(rt)->nworkers(); }
unsigned long long hcn_executed(void* rt) {
  return static_cast<Runtime*>(rt)->total_executed();
}
unsigned long long hcn_steals(void* rt) {
  return static_cast<Runtime*>(rt)->total_steals();
}

// Generic task API for foreign (e.g. Python-callback) tasks.
void hcn_run_root(void* rt, void (*fn)(void*), void* env) {
  static_cast<Runtime*>(rt)->run_root(fn, env);
}

// ------------------------------------------------------------------ fib

namespace {
struct FibEnv {
  Runtime* rt;
  int n;
  long long* out;
};

void fib_task(void* p) {
  FibEnv* e = static_cast<FibEnv*>(p);
  if (e->n < 2) {
    *e->out = e->n;
    delete e;
    return;
  }
  long long a = 0, b = 0;
  Finish f;
  f.check_in();
  e->rt->spawn({fib_task, new FibEnv{e->rt, e->n - 1, &a}, &f.counter});
  f.check_in();
  e->rt->spawn({fib_task, new FibEnv{e->rt, e->n - 2, &b}, &f.counter});
  e->rt->help_until_zero(&f.counter);
  *e->out = a + b;
  delete e;
}
}  // namespace

long long hcn_fib(void* rtp, int n) {
  Runtime* rt = static_cast<Runtime*>(rtp);
  long long result = 0;
  FibEnv* root = new FibEnv{rt, n, &result};
  rt->run_root(fib_task, root);
  return result;
}

// ------------------------------------------------------------------ UTS
// Tree spec re-implemented from the published UTS algorithm (see
// hclib_tpu/models/uts.py for the parameter citations).

namespace {
struct UtsCounters {
  std::atomic<uint64_t> nodes{0};
  std::atomic<uint64_t> leaves{0};
  std::atomic<int> max_depth{0};
};

struct UtsParams {
  int shape;  // 0=LINEAR 1=EXPDEC 2=CYCLIC 3=FIXED
  int gen_mx;
  double b0;
};

struct UtsEnv {
  Runtime* rt;
  const UtsParams* params;
  UtsCounters* counters;
  uint8_t state[20];
  int depth;
  Finish* finish;  // tree-wide finish
};

int uts_num_children(const UtsParams& p, const uint8_t state[20], int depth) {
  double b_i = p.b0;
  if (depth > 0) {
    switch (p.shape) {
      case 0:
        b_i = p.b0 * (1.0 - double(depth) / double(p.gen_mx));
        break;
      case 1:
        b_i = p.b0 * std::pow(double(depth),
                              -std::log(p.b0) / std::log(double(p.gen_mx)));
        break;
      case 2:
        if (depth > 5 * p.gen_mx)
          b_i = 0.0;
        else
          b_i = std::pow(p.b0, std::sin(2.0 * M_PI * depth / p.gen_mx));
        break;
      case 3:
        b_i = depth < p.gen_mx ? p.b0 : 0.0;
        break;
    }
  }
  if (b_i <= 0.0) return 0;
  uint32_t r = (uint32_t(state[16]) << 24) | (uint32_t(state[17]) << 16) |
               (uint32_t(state[18]) << 8) | uint32_t(state[19]);
  r &= 0x7FFFFFFF;
  double u = double(r) / 2147483648.0;
  double pgeo = 1.0 / (1.0 + b_i);
  int n = int(std::floor(std::log(1.0 - u) / std::log(1.0 - pgeo)));
  return n > 100 ? 100 : n;  // MAXNUMCHILDREN cap
}

void uts_task(void* pv) {
  UtsEnv* e = static_cast<UtsEnv*>(pv);
  e->counters->nodes.fetch_add(1, std::memory_order_relaxed);
  int md = e->counters->max_depth.load(std::memory_order_relaxed);
  while (e->depth > md &&
         !e->counters->max_depth.compare_exchange_weak(md, e->depth)) {
  }
  int nc = uts_num_children(*e->params, e->state, e->depth);
  if (nc == 0) {
    e->counters->leaves.fetch_add(1, std::memory_order_relaxed);
  }
  for (int i = 0; i < nc; ++i) {
    UtsEnv* c = new UtsEnv;
    c->rt = e->rt;
    c->params = e->params;
    c->counters = e->counters;
    c->depth = e->depth + 1;
    c->finish = e->finish;
    uint8_t msg[24];
    std::memcpy(msg, e->state, 20);
    msg[20] = (i >> 24) & 0xff;
    msg[21] = (i >> 16) & 0xff;
    msg[22] = (i >> 8) & 0xff;
    msg[23] = i & 0xff;
    hcn::sha1_single_block(msg, 24, c->state);
    e->finish->check_in();
    e->rt->spawn({uts_task, c, &e->finish->counter});
  }
  delete e;
}
}  // namespace

void hcn_uts(void* rtp, int shape, int gen_mx, double b0, int seed,
             unsigned long long* nodes, unsigned long long* leaves,
             int* max_depth) {
  Runtime* rt = static_cast<Runtime*>(rtp);
  UtsParams params{shape, gen_mx, b0};
  UtsCounters counters;
  Finish finish;
  UtsEnv* root = new UtsEnv;
  root->rt = rt;
  root->params = &params;
  root->counters = &counters;
  root->depth = 0;
  root->finish = &finish;
  uint8_t msg[20] = {0};
  msg[16] = (seed >> 24) & 0xff;
  msg[17] = (seed >> 16) & 0xff;
  msg[18] = (seed >> 8) & 0xff;
  msg[19] = seed & 0xff;
  hcn::sha1_single_block(msg, 20, root->state);
  finish.check_in();
  rt->spawn({uts_task, root, &finish.counter});
  rt->help_until_zero(&finish.counter);
  *nodes = counters.nodes.load();
  *leaves = counters.leaves.load();
  *max_depth = counters.max_depth.load();
}

// -------------------------------------------------------------- arrayadd

namespace {
struct AddEnv {
  const double* a;
  const double* b;
  double* c;
  long lo, hi;
};

void add_task(void* pv) {
  AddEnv* e = static_cast<AddEnv*>(pv);
  for (long i = e->lo; i < e->hi; ++i) e->c[i] = e->a[i] + e->b[i];
  delete e;
}
}  // namespace

void hcn_arrayadd(void* rtp, const double* a, const double* b, double* c,
                  long n, long tile) {
  Runtime* rt = static_cast<Runtime*>(rtp);
  if (tile <= 0) tile = n > 0 ? n : 1;
  Finish f;
  for (long lo = 0; lo < n; lo += tile) {
    long hi = lo + tile < n ? lo + tile : n;
    f.check_in();
    rt->spawn({add_task, new AddEnv{a, b, c, lo, hi}, &f.counter});
  }
  rt->help_until_zero(&f.counter);
}

}  // extern "C"
