// Native host runtime: work-stealing scheduler core.
//
// A fresh C++17 implementation of the reference's scheduling model
// (finish/async over per-worker Chase-Lev deques, help-first joins -
// src/hclib-runtime.c, src/hclib-deque.c), designed for the role it plays in
// this framework: the fast *host-side* execution engine that feeds/drains
// TPU device queues and provides the measured CPU baseline. Differences from
// the reference are deliberate:
//  - no stackful fibers: a blocked finish help-first executes other tasks on
//    the same stack (work-shift). All framework workloads are fork-join, so
//    bounded stack growth is guaranteed by the spawn tree depth.
//  - deques are bounded lock-free Chase-Lev rings with C++11 atomics
//    (acquire/release instead of x86-TSO assumptions + __sync builtins).
//  - tasks are {function pointer, void* env} pairs; closures are arena-free
//    (caller owns env lifetime until execution).

#pragma once

#include <atomic>
#include <cstdint>
#include <thread>
#include <vector>

namespace hcn {

struct Task {
  void (*fn)(void*) = nullptr;
  void* env = nullptr;
  std::atomic<int64_t>* finish_counter = nullptr;
};

// Chase-Lev work-stealing deque (bounded ring). Owner pushes/pops at the
// bottom; thieves CAS the top.
class Deque {
 public:
  static constexpr size_t kCapacity = 1 << 16;

  bool push(const Task& t) {
    int64_t b = bottom_.load(std::memory_order_relaxed);
    int64_t tp = top_.load(std::memory_order_acquire);
    if (b - tp >= static_cast<int64_t>(kCapacity)) return false;  // full
    buf_[b & kMask] = t;
    std::atomic_thread_fence(std::memory_order_release);
    bottom_.store(b + 1, std::memory_order_relaxed);
    return true;
  }

  bool pop(Task* out) {
    int64_t b = bottom_.load(std::memory_order_relaxed) - 1;
    bottom_.store(b, std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    int64_t tp = top_.load(std::memory_order_relaxed);
    if (tp > b) {  // empty
      bottom_.store(b + 1, std::memory_order_relaxed);
      return false;
    }
    *out = buf_[b & kMask];
    if (tp == b) {  // last element: race with thieves
      if (!top_.compare_exchange_strong(tp, tp + 1,
                                        std::memory_order_seq_cst,
                                        std::memory_order_relaxed)) {
        bottom_.store(b + 1, std::memory_order_relaxed);
        return false;
      }
      bottom_.store(b + 1, std::memory_order_relaxed);
    }
    return true;
  }

  bool steal(Task* out) {
    int64_t tp = top_.load(std::memory_order_acquire);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    int64_t b = bottom_.load(std::memory_order_acquire);
    if (tp >= b) return false;  // empty
    Task t = buf_[tp & kMask];
    if (!top_.compare_exchange_strong(tp, tp + 1, std::memory_order_seq_cst,
                                      std::memory_order_relaxed)) {
      return false;  // lost the race
    }
    *out = t;
    return true;
  }

  size_t size() const {
    int64_t b = bottom_.load(std::memory_order_relaxed);
    int64_t tp = top_.load(std::memory_order_relaxed);
    return b > tp ? static_cast<size_t>(b - tp) : 0;
  }

 private:
  static constexpr size_t kMask = kCapacity - 1;
  alignas(64) std::atomic<int64_t> top_{0};
  alignas(64) std::atomic<int64_t> bottom_{0};
  std::vector<Task> buf_{kCapacity};
};

struct WorkerStats {
  uint64_t executed = 0;
  uint64_t steals = 0;
  char pad[48];
};

class Runtime {
 public:
  explicit Runtime(int nworkers);
  ~Runtime();

  int nworkers() const { return nworkers_; }

  // Spawn a task under the given finish counter (counter is pre-incremented
  // by the caller via Finish::check_in).
  void spawn(Task t);

  // Help-first drain: execute tasks until *counter reaches zero
  // (help_finish, src/hclib-runtime.c:1067-1119 - minus the fiber swap).
  void help_until_zero(std::atomic<int64_t>* counter);

  // Run fn(env) as the root task on the calling thread and drain everything.
  void run_root(void (*fn)(void*), void* env);

  uint64_t total_executed() const;
  uint64_t total_steals() const;

 private:
  friend struct WorkerMain;
  void worker_loop(int wid);
  bool find_task(int wid, Task* out);
  void execute(const Task& t);

  int nworkers_;
  std::vector<Deque> deques_;
  std::vector<WorkerStats> stats_;
  std::vector<std::thread> threads_;
  std::atomic<bool> shutdown_{false};
  std::atomic<int64_t> root_counter_{0};
};

// Finish scope: atomic counter of outstanding children. Spawners check_in
// before spawn; the runtime decrements when the task completes (execute()),
// so there is deliberately no public check_out.
struct Finish {
  std::atomic<int64_t> counter{0};
  void check_in() { counter.fetch_add(1, std::memory_order_relaxed); }
};

}  // namespace hcn
