// Native host runtime: locality-aware work-stealing scheduler with
// data-driven tasks (promises/futures) and finish scopes.
//
// A fresh C++17 implementation of the reference's scheduling model
// (finish/async over per-(locale,worker) Chase-Lev deques, help-first joins,
// DDF promise waiter lists - src/hclib-runtime.c, src/hclib-deque.c,
// src/hclib-promise.c, src/hclib-locality-graph.c), designed for the role it
// plays in this framework: the fast *host-side* execution engine that feeds/
// drains TPU device queues and provides the measured CPU baseline. Deliberate
// differences from the reference:
//  - no stackful fibers: a blocked end-finish / future-wait help-first
//    executes other ready tasks on the same stack (work-shift), and
//    dependency-blocked tasks are *descriptors* parked on promise waiter
//    lists rather than suspended stacks. This is the same continuation model
//    as the device megakernel (re-enqueueable descriptors), so host and
//    device share one semantics.
//  - deques are bounded lock-free Chase-Lev rings with C++11 atomics
//    (acquire/release instead of x86-TSO assumptions + __sync builtins).
//    On overflow the task runs inline (the reference aborts,
//    src/hclib-runtime.c:520-524).
//  - tasks are heap descriptors {fn, env, finish, deps[], locale}; the deque
//    stores pointers.

#pragma once

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace hcn {

class Runtime;
struct FinishScope;
struct NPromise;

// Per-thread 128-byte chunk pool for task descriptors and small lambda
// environments: spawn/execute would otherwise pay two malloc/free pairs per
// task, which halves fine-grained task throughput (fib). Chunks recycle on
// the freeing thread's list (stolen tasks migrate chunks between threads,
// which is fine - overflow falls back to operator delete).
constexpr size_t kPoolChunk = 128;
void* pool_alloc();
void pool_free(void* p);

// Task descriptor (reference: inc/hclib-task.h:32-44). `deps` mirrors
// waiting_on[4] + waiting_on_extra; `dep_index` is the one-at-a-time
// registration cursor (src/hclib-promise.c:171-195).
struct NTask {
  static constexpr int kInlineDeps = 4;

  void (*fn)(void*) = nullptr;
  void* env = nullptr;
  FinishScope* finish = nullptr;
  NPromise* deps[kInlineDeps] = {nullptr, nullptr, nullptr, nullptr};
  std::vector<NPromise*>* extra_deps = nullptr;  // overflow beyond 4
  uint32_t ndeps = 0;
  uint32_t dep_index = 0;  // next unregistered dependency
  int locale = 0;
  // Advisory parity field (reference inc/hclib-task.h `non_blocking`): the
  // reference uses it to allow inline execution on any context; this engine's
  // work-shift model may inline any ready task, so the flag is metadata only.
  bool non_blocking = false;
  NTask* next_waiter = nullptr;  // promise waiter-list link

  NPromise* dep_at(uint32_t i) const {
    return i < kInlineDeps ? deps[i] : (*extra_deps)[i - kInlineDeps];
  }

  void add_dep(NPromise* p) {
    if (ndeps < kInlineDeps) {
      deps[ndeps] = p;
    } else {
      if (extra_deps == nullptr) extra_deps = new std::vector<NPromise*>;
      extra_deps->push_back(p);
    }
    ++ndeps;
  }
};

// All NTasks are pool chunks (see pool_alloc above).
NTask* task_alloc();
void task_free(NTask* t);

// Single-assignment data-driven future (reference: inc/hclib-promise.h:76-90,
// src/hclib-promise.c). `waiters` is a lock-free Treiber list of parked task
// descriptors, closed with a sentinel by `put`.
struct NPromise {
  // Sentinel for "list closed, promise satisfied".
  static NTask* closed_sentinel() {
    return reinterpret_cast<NTask*>(uintptr_t(1));
  }

  std::atomic<void*> datum{nullptr};
  std::atomic<bool> satisfied_{false};
  std::atomic<NTask*> waiters{nullptr};

  bool satisfied() const { return satisfied_.load(std::memory_order_acquire); }
  void* get() const { return datum.load(std::memory_order_acquire); }

  // CAS-push `t` onto the waiter list. Returns false if the promise was
  // already satisfied (list closed) - the caller keeps walking its deps.
  bool register_waiter(NTask* t) {
    NTask* head = waiters.load(std::memory_order_acquire);
    for (;;) {
      if (head == closed_sentinel()) return false;
      t->next_waiter = head;
      if (waiters.compare_exchange_weak(head, t, std::memory_order_release,
                                        std::memory_order_acquire)) {
        return true;
      }
    }
  }
};

// Finish scope (reference: src/inc/hclib-finish.h:6-10). Counter starts at 1
// for the owning task (src/hclib-runtime.c:1219-1247); on reaching 0 the
// optional `finish_dep` promise is satisfied, waking the continuation.
struct FinishScope {
  std::atomic<int64_t> counter{1};
  FinishScope* parent = nullptr;
  NPromise* finish_dep = nullptr;
  Runtime* rt = nullptr;
  // Set by end_finish_nonblocking: the scope outlives its creator, so the
  // final check_out deletes it after satisfying finish_dep.
  bool self_delete = false;

  void check_in() { counter.fetch_add(1, std::memory_order_relaxed); }
  void check_out();  // defined in runtime.cpp (needs Runtime::put)
};

// Chase-Lev work-stealing deque of task pointers (bounded ring). Owner
// pushes/pops at the bottom; thieves CAS the top.
class Deque {
 public:
  static constexpr size_t kCapacity = 1 << 15;

  bool push(NTask* t) {
    int64_t b = bottom_.load(std::memory_order_relaxed);
    int64_t tp = top_.load(std::memory_order_acquire);
    if (b - tp >= static_cast<int64_t>(kCapacity)) return false;  // full
    buf_[b & kMask] = t;
    std::atomic_thread_fence(std::memory_order_release);
    bottom_.store(b + 1, std::memory_order_relaxed);
    return true;
  }

  bool pop(NTask** out) {
    int64_t b = bottom_.load(std::memory_order_relaxed) - 1;
    bottom_.store(b, std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    int64_t tp = top_.load(std::memory_order_relaxed);
    if (tp > b) {  // empty
      bottom_.store(b + 1, std::memory_order_relaxed);
      return false;
    }
    *out = buf_[b & kMask];
    if (tp == b) {  // last element: race with thieves
      if (!top_.compare_exchange_strong(tp, tp + 1, std::memory_order_seq_cst,
                                        std::memory_order_relaxed)) {
        bottom_.store(b + 1, std::memory_order_relaxed);
        return false;
      }
      bottom_.store(b + 1, std::memory_order_relaxed);
    }
    return true;
  }

  bool steal(NTask** out) {
    int64_t tp = top_.load(std::memory_order_acquire);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    int64_t b = bottom_.load(std::memory_order_acquire);
    if (tp >= b) return false;  // empty
    NTask* t = buf_[tp & kMask];
    if (!top_.compare_exchange_strong(tp, tp + 1, std::memory_order_seq_cst,
                                      std::memory_order_relaxed)) {
      return false;  // lost the race
    }
    *out = t;
    return true;
  }

  size_t size() const {
    int64_t b = bottom_.load(std::memory_order_relaxed);
    int64_t tp = top_.load(std::memory_order_relaxed);
    return b > tp ? static_cast<size_t>(b - tp) : 0;
  }

 private:
  static constexpr size_t kMask = kCapacity - 1;
  alignas(64) std::atomic<int64_t> top_{0};
  alignas(64) std::atomic<int64_t> bottom_{0};
  std::vector<NTask*> buf_{kCapacity};
};

// Flattened locality description (mirrors the Python LocalityGraph; see
// runtime/locality.py and reference inc/hclib-locality-graph.h). Paths are
// CSR-style: worker w's pop path is pop_data[pop_off[w] .. pop_off[w+1]).
struct GraphSpec {
  int nlocales = 1;
  std::vector<int> pop_off, pop_data;      // own-deque drain order
  std::vector<int> steal_off, steal_data;  // victim-scan order

  static GraphSpec flat(int nworkers) {
    GraphSpec g;
    g.nlocales = 1;
    for (int w = 0; w <= nworkers; ++w) {
      g.pop_off.push_back(w);
      g.steal_off.push_back(w);
    }
    g.pop_data.assign(nworkers, 0);
    g.steal_data.assign(nworkers, 0);
    return g;
  }
};

// Per-worker counters (HCLIB_STATS analog, src/hclib-runtime.c:83-104),
// including the per-victim steal matrix.
struct WorkerStats {
  uint64_t executed = 0;
  uint64_t spawned = 0;
  uint64_t scheduled = 0;
  uint64_t steals = 0;
  uint64_t end_finishes = 0;
  uint64_t future_waits = 0;
  uint64_t yields = 0;
  std::vector<uint64_t> stolen_from;  // [victim worker] -> count
  char pad[64];
};

class Runtime {
 public:
  explicit Runtime(int nworkers, GraphSpec graph = GraphSpec{});
  ~Runtime();

  int nworkers() const { return nworkers_; }
  int nlocales() const { return graph_.nlocales; }
  // CPU this worker was pinned to, or -1 (no affinity requested / pin
  // failed / unsupported platform). Well-defined once the constructor
  // returns. Reference: HCLIB_AFFINITY hwloc cpusets,
  // src/hclib-runtime.c:731-900.
  int pinned_cpu(int w) const {
    return (w >= 0 && w < nworkers_)
               ? pinned_[w].load(std::memory_order_acquire)
               : -1;
  }

  // Thread-local context (reference: pthread_setspecific ws_key,
  // src/hclib-runtime.c:151-193).
  static Runtime* current();
  static int current_worker();
  FinishScope* current_finish();
  void set_current_finish(FinishScope* f);

  // -- task creation ------------------------------------------------------
  // Spawn under `t->finish` (check_in is done here). If the task has
  // unsatisfied deps it parks on a promise waiter list; otherwise it is
  // enqueued at its locale's deque for the calling worker.
  void spawn(NTask* t);
  // Make an eligible task runnable (promise put path; no check_in).
  void schedule(NTask* t);

  // -- blocking operations (work-shift: execute other tasks inline) -------
  void end_finish(FinishScope* f);
  // Nonblocking end: attach `dep` as the finish continuation promise
  // (hclib_end_finish_nonblocking, src/hclib-runtime.c:1279-1313).
  void end_finish_nonblocking(FinishScope* f, NPromise* dep);
  void future_wait(NPromise* p);
  // Run up to one pending task inline and return (work-shift yield).
  bool yield(int locale = -1);

  // Run fn(env) as the root task on the calling thread under a fresh root
  // finish, and drain it (hclib_launch shape, src/hclib-runtime.c:1460-1478).
  void run_root(void (*fn)(void*), void* env);

  // Satisfy a promise: store datum, close the waiter list, re-run the
  // registration walk for each parked task (src/hclib-promise.c:203-245).
  void promise_put(NPromise* p, void* value);

  // -- introspection ------------------------------------------------------
  uint64_t total_executed() const;
  uint64_t total_steals() const;
  size_t backlog() const;
  std::string format_stats() const;
  const WorkerStats& worker_stats(int w) const { return stats_[w]; }

  // Legacy simple-counter helpers (used by native workloads): drain tasks
  // until *counter reaches `target`.
  void help_until(std::atomic<int64_t>* counter, int64_t target);

 private:
  friend struct FinishScope;
  void worker_loop(int wid);
  bool find_task(int wid, NTask** out);
  void execute(NTask* t);
  void enqueue(NTask* t, int wid);
  // Resume the dependency-registration walk; returns true if the task is
  // eligible to run (all deps satisfied), false if it parked on a promise.
  bool register_deps(NTask* t);
  Deque& deque_at(int locale, int worker) {
    return deques_[size_t(locale) * nworkers_ + worker];
  }
  const Deque& deque_at(int locale, int worker) const {
    return deques_[size_t(locale) * nworkers_ + worker];
  }

  int nworkers_;
  std::unique_ptr<std::atomic<int>[]> pinned_;
  std::vector<char> orig_mask_;  // caller-thread mask, restored at teardown
  bool restore_mask_ = false;
  GraphSpec graph_;
  std::vector<Deque> deques_;  // [locale][worker]
  std::vector<WorkerStats> stats_;
  std::vector<int> last_steal_idx_;  // per-worker steal-path rotation
  std::vector<std::thread> threads_;
  std::atomic<bool> shutdown_{false};
  // Injection queue for tasks submitted from threads that are not runtime
  // workers (foreign Python threads): owner-side Chase-Lev pushes are
  // single-producer, so foreign submissions go through this mutex-guarded
  // queue, drained by workers in find_task.
  std::mutex inject_mu_;
  std::vector<NTask*> inject_;
  std::atomic<size_t> inject_count_{0};
};

}  // namespace hcn
