// C++ lambda API over the native runtime core - the analog of the
// reference's header-template layer (inc/hclib-async.h lambda trampolines,
// inc/hclib-forasync.h loop parallelism, inc/hclib_promise.h typed wrappers,
// inc/hclib_cpp.h launch). Lambdas are heap-copied and dispatched through a
// call-and-delete trampoline exactly as the reference's lambda_wrapper
// (inc/hclib-async.h:64-149) - just with C++17 instead of C++11 idioms.

#pragma once

#include <algorithm>
#include <cstring>
#include <new>
#include <type_traits>
#include <utility>
#include <vector>

#include "runtime.hpp"

namespace hcn {

namespace detail {

template <typename F>
void call_lambda(void* env) {
  F* f = static_cast<F*>(env);
  (*f)();
  delete f;
}

// Small lambda environments live in pool chunks (see pool_alloc): one
// recycled allocation instead of a malloc/free pair per task.
template <typename F>
void call_lambda_pooled(void* env) {
  F* f = static_cast<F*>(env);
  (*f)();
  f->~F();
  pool_free(env);
}

template <typename F>
NTask* make_task(F&& body) {
  using Fn = std::decay_t<F>;
  NTask* t = task_alloc();
  if constexpr (sizeof(Fn) <= kPoolChunk) {
    t->fn = &call_lambda_pooled<Fn>;
    t->env = new (pool_alloc()) Fn(std::forward<F>(body));
  } else {
    t->fn = &call_lambda<Fn>;
    t->env = new Fn(std::forward<F>(body));
  }
  return t;
}

}  // namespace detail

// -- async variants (inc/hclib-async.h:162-547) ---------------------------

template <typename F>
void async(F&& body) {
  Runtime* rt = Runtime::current();
  NTask* t = detail::make_task(std::forward<F>(body));
  t->finish = rt->current_finish();
  rt->spawn(t);
}

template <typename F>
void async_at(F&& body, int locale) {
  Runtime* rt = Runtime::current();
  NTask* t = detail::make_task(std::forward<F>(body));
  t->finish = rt->current_finish();
  t->locale = locale;
  rt->spawn(t);
}

template <typename F>
void async_await(F&& body, std::initializer_list<NPromise*> deps) {
  Runtime* rt = Runtime::current();
  NTask* t = detail::make_task(std::forward<F>(body));
  t->finish = rt->current_finish();
  for (NPromise* p : deps) t->add_dep(p);
  rt->spawn(t);
}

// Wrap `body` in a promise-putting trampoline (hclib_async_future,
// src/hclib.c:59-81). The caller owns the returned promise.
template <typename F>
NPromise* async_future(F&& body) {
  NPromise* p = new NPromise;
  async([p, b = std::decay_t<F>(std::forward<F>(body))]() mutable {
    Runtime::current()->promise_put(p, b());
  });
  return p;
}

// -- typed promises/futures (inc/hclib_promise.h:41-124,
//    inc/hclib_future.h:9-77) ---------------------------------------------
// The reference's design: typed views POD-cast over the untyped machine-
// word promise, zero storage of their own. T must be trivially copyable
// and fit in a void* (ints, pointers, enums, float); wider payloads go
// through a pointer, exactly as in the reference.

template <typename T>
class future_t {
  static_assert(sizeof(T) <= sizeof(void*),
                "future_t<T>: T must fit the promise word (pass a pointer)");
  static_assert(std::is_trivially_copyable<T>::value,
                "future_t<T>: T must be trivially copyable");

 public:
  explicit future_t(NPromise* p) : p_(p) {}
  bool satisfied() const { return p_->satisfied(); }
  T wait() {
    Runtime::current()->future_wait(p_);
    return get();
  }
  T get() const {
    void* w = p_->get();
    T v;
    std::memcpy(&v, &w, sizeof(T));
    return v;
  }
  NPromise* raw() const { return p_; }

 private:
  NPromise* p_;
};

template <typename T>
class promise_t : public NPromise {
  static_assert(sizeof(T) <= sizeof(void*),
                "promise_t<T>: T must fit the promise word (pass a pointer)");
  static_assert(std::is_trivially_copyable<T>::value,
                "promise_t<T>: T must be trivially copyable");

 public:
  void put(T v) {
    void* w = nullptr;
    std::memcpy(&w, &v, sizeof(T));
    Runtime::current()->promise_put(this, w);
  }
  future_t<T> get_future() { return future_t<T>(this); }
};

template <>
class promise_t<void> : public NPromise {
 public:
  void put() { Runtime::current()->promise_put(this, nullptr); }
};

// Typed async_future: runs `body`, puts its result (hclib::async_future
// returning future_t<T>, inc/hclib-async.h:424-547). Void-returning
// bodies are not supported here - use async + promise_t<void> directly.
template <typename F, typename T = std::invoke_result_t<std::decay_t<F>>>
future_t<T> async_future_t(F&& body) {
  static_assert(!std::is_void<T>::value,
                "async_future_t: void body - use async + promise_t<void>");
  auto* p = new promise_t<T>;
  async([p, b = std::decay_t<F>(std::forward<F>(body))]() mutable {
    p->put(b());
  });
  return p->get_future();
}

// -- finish (inc/hclib-async.h:550-563) -----------------------------------

template <typename F>
void finish(F&& body) {
  Runtime* rt = Runtime::current();
  FinishScope f;
  f.rt = rt;
  f.parent = rt->current_finish();
  FinishScope* prev = rt->current_finish();
  rt->set_current_finish(&f);
  body();
  rt->set_current_finish(prev);
  rt->end_finish(&f);
}

// -- forasync (src/hclib.c:158-416, inc/hclib-forasync.h) -----------------
// FLAT: one task per tile. RECURSIVE: binary splitting until <= tile.

enum class ForasyncMode { kFlat, kRecursive };

template <typename F>
void forasync1d_flat(long n, long tile, F&& body) {
  if (tile <= 0) tile = std::max<long>(1, n / Runtime::current()->nworkers());
  for (long lo = 0; lo < n; lo += tile) {
    long hi = std::min(lo + tile, n);
    async([lo, hi, body]() {
      for (long i = lo; i < hi; ++i) body(i);
    });
  }
}

template <typename F>
void forasync1d_rec(long lo, long hi, long tile, const F& body) {
  if (hi - lo <= tile) {
    for (long i = lo; i < hi; ++i) body(i);
    return;
  }
  long mid = lo + (hi - lo) / 2;
  async([lo, mid, tile, body]() { forasync1d_rec(lo, mid, tile, body); });
  forasync1d_rec(mid, hi, tile, body);
}

template <typename F>
void forasync1d(long n, F&& body, long tile = 0,
                ForasyncMode mode = ForasyncMode::kFlat) {
  if (tile <= 0) tile = std::max<long>(1, n / Runtime::current()->nworkers());
  if (mode == ForasyncMode::kFlat) {
    forasync1d_flat(n, tile, std::forward<F>(body));
  } else {
    async([n, tile, b = std::decay_t<F>(std::forward<F>(body))]() {
      forasync1d_rec(0, n, tile, b);
    });
  }
}

template <typename F>
void forasync2d(long n0, long n1, F&& body, long tile0 = 0, long tile1 = 0) {
  if (tile0 <= 0) tile0 = std::max<long>(1, n0 / Runtime::current()->nworkers());
  if (tile1 <= 0) tile1 = n1;
  for (long lo0 = 0; lo0 < n0; lo0 += tile0) {
    long hi0 = std::min(lo0 + tile0, n0);
    for (long lo1 = 0; lo1 < n1; lo1 += tile1) {
      long hi1 = std::min(lo1 + tile1, n1);
      async([lo0, hi0, lo1, hi1, body]() {
        for (long i = lo0; i < hi0; ++i)
          for (long j = lo1; j < hi1; ++j) body(i, j);
      });
    }
  }
}

template <typename F>
void forasync3d(long n0, long n1, long n2, F&& body, long tile0 = 0) {
  if (tile0 <= 0) tile0 = std::max<long>(1, n0 / Runtime::current()->nworkers());
  for (long lo0 = 0; lo0 < n0; lo0 += tile0) {
    long hi0 = std::min(lo0 + tile0, n0);
    async([hi0, lo0, n1, n2, body]() {
      for (long i = lo0; i < hi0; ++i)
        for (long j = 0; j < n1; ++j)
          for (long k = 0; k < n2; ++k) body(i, j, k);
    });
  }
}

// -- launch (inc/hclib_cpp.h:29-47) ---------------------------------------

template <typename F>
void launch(Runtime* rt, F&& body) {
  using Fn = std::decay_t<F>;
  Fn* env = new Fn(std::forward<F>(body));
  rt->run_root(&detail::call_lambda<Fn>, env);
}

}  // namespace hcn
