#include "runtime.hpp"

#include <chrono>

namespace hcn {

namespace {
thread_local Runtime* g_runtime = nullptr;
thread_local int g_worker = -1;
}  // namespace

Runtime::Runtime(int nworkers)
    : nworkers_(nworkers < 1 ? 1 : nworkers),
      deques_(nworkers_),
      stats_(nworkers_) {
  g_runtime = this;
  g_worker = 0;
  threads_.reserve(nworkers_ - 1);
  for (int w = 1; w < nworkers_; ++w) {
    threads_.emplace_back([this, w] {
      g_runtime = this;
      g_worker = w;
      worker_loop(w);
    });
  }
}

Runtime::~Runtime() {
  shutdown_.store(true, std::memory_order_release);
  for (auto& t : threads_) t.join();
  g_runtime = nullptr;
  g_worker = -1;
}

void Runtime::spawn(Task t) {
  int w = g_worker >= 0 ? g_worker : 0;
  if (!deques_[w].push(t)) {
    // Deque full: run inline (the reference aborts,
    // src/hclib-runtime.c:520-524; degrading to inline execution keeps
    // deep spawn trees correct at some parallelism cost).
    execute(t);
  }
}

bool Runtime::find_task(int wid, Task* out) {
  if (deques_[wid].pop(out)) return true;
  for (int i = 1; i <= nworkers_; ++i) {
    int v = (wid + i) % nworkers_;
    if (v == wid) continue;
    if (deques_[v].steal(out)) {
      ++stats_[wid].steals;
      return true;
    }
  }
  return false;
}

void Runtime::execute(const Task& t) {
  t.fn(t.env);
  if (t.finish_counter)
    t.finish_counter->fetch_sub(1, std::memory_order_release);
  int w = g_worker >= 0 ? g_worker : 0;
  ++stats_[w].executed;
}

void Runtime::worker_loop(int wid) {
  Task t;
  int idle_spins = 0;
  while (!shutdown_.load(std::memory_order_acquire)) {
    if (find_task(wid, &t)) {
      execute(t);
      idle_spins = 0;
    } else if (++idle_spins > 1024) {
      std::this_thread::yield();
    }
  }
}

void Runtime::help_until_zero(std::atomic<int64_t>* counter) {
  int wid = g_worker >= 0 ? g_worker : 0;
  Task t;
  while (counter->load(std::memory_order_acquire) != 0) {
    if (find_task(wid, &t)) {
      execute(t);
    } else {
      std::this_thread::yield();
    }
  }
}

void Runtime::run_root(void (*fn)(void*), void* env) {
  root_counter_.store(1, std::memory_order_relaxed);
  Task t{fn, env, &root_counter_};
  execute(t);
  help_until_zero(&root_counter_);
}

uint64_t Runtime::total_executed() const {
  uint64_t n = 0;
  for (auto& s : stats_) n += s.executed;
  return n;
}

uint64_t Runtime::total_steals() const {
  uint64_t n = 0;
  for (auto& s : stats_) n += s.steals;
  return n;
}

}  // namespace hcn
