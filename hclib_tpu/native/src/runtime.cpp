#include "runtime.hpp"

#include <cstdlib>
#include <cstring>
#include <new>
#include <sstream>

#ifdef __linux__
#include <pthread.h>
#include <sched.h>
#include <unistd.h>
#endif

namespace hcn {

namespace {
thread_local Runtime* g_runtime = nullptr;
thread_local int g_worker = -1;
thread_local FinishScope* g_finish = nullptr;
thread_local std::vector<void*> g_pool;
constexpr size_t kPoolMax = 8192;
}  // namespace

void* pool_alloc() {
  if (!g_pool.empty()) {
    void* p = g_pool.back();
    g_pool.pop_back();
    return p;
  }
  return ::operator new(kPoolChunk);
}

void pool_free(void* p) {
  if (g_pool.size() < kPoolMax) {
    g_pool.push_back(p);
  } else {
    ::operator delete(p);
  }
}

static_assert(sizeof(NTask) <= kPoolChunk, "NTask must fit a pool chunk");

NTask* task_alloc() { return new (pool_alloc()) NTask; }

void task_free(NTask* t) {
  delete t->extra_deps;
  t->~NTask();
  pool_free(t);
}

Runtime* Runtime::current() { return g_runtime; }
int Runtime::current_worker() { return g_worker >= 0 ? g_worker : 0; }
FinishScope* Runtime::current_finish() { return g_finish; }
void Runtime::set_current_finish(FinishScope* f) { g_finish = f; }

void FinishScope::check_out() {
  if (counter.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    NPromise* dep = finish_dep;
    Runtime* r = rt;
    if (self_delete) delete this;  // detached scope (end_finish_nonblocking)
    if (dep != nullptr) r->promise_put(dep, nullptr);
  }
}

// Worker->CPU pinning (reference: HCLIB_AFFINITY strided/chunked over
// hwloc cpusets, src/hclib-runtime.c:731-900). Opt-in via
// HCLIB_TPU_AFFINITY (or HCLIB_AFFINITY) = "strided" | "chunked"; any
// other value is rejected with a warning. Candidate CPUs come from the
// process's ALLOWED set (sched_getaffinity), so cgroup/taskset-restricted
// environments pin correctly.
struct AffinityPlan {
  bool active = false;
  std::vector<int> cpu;  // per-worker target
};

static AffinityPlan affinity_plan(int nworkers) {
  AffinityPlan plan;
#ifdef __linux__
  const char* mode = std::getenv("HCLIB_TPU_AFFINITY");
  if (mode == nullptr) mode = std::getenv("HCLIB_AFFINITY");
  if (mode == nullptr || *mode == '\0') return plan;
  std::string m(mode);
  if (m != "strided" && m != "chunked") {
    std::fprintf(
        stderr,
        "hclib_tpu native: ignoring unknown affinity mode '%s' "
        "(use strided|chunked)\n",
        mode);
    return plan;
  }
  cpu_set_t allowed;
  if (sched_getaffinity(0, sizeof(allowed), &allowed) != 0) return plan;
  std::vector<int> cpus;
  for (int c = 0; c < CPU_SETSIZE; ++c)
    if (CPU_ISSET(c, &allowed)) cpus.push_back(c);
  if (cpus.empty()) return plan;
  plan.active = true;
  plan.cpu.resize(nworkers);
  int n = int(cpus.size());
  for (int w = 0; w < nworkers; ++w)
    plan.cpu[w] = (m == "chunked") ? cpus[size_t((long(w) * n) / nworkers)]
                                   : cpus[w % n];  // strided (ref default)
#else
  (void)nworkers;
#endif
  return plan;
}

static int pin_self(int cpu) {
#ifdef __linux__
  cpu_set_t set;
  CPU_ZERO(&set);
  CPU_SET(cpu, &set);
  if (pthread_setaffinity_np(pthread_self(), sizeof(set), &set) != 0)
    return -1;
  return cpu;
#else
  (void)cpu;
  return -1;
#endif
}

constexpr int kPinPending = -2;

Runtime::Runtime(int nworkers, GraphSpec graph)
    : nworkers_(nworkers < 1 ? 1 : nworkers),
      graph_(std::move(graph)),
      last_steal_idx_(nworkers_, 0) {
  if (graph_.pop_off.empty()) graph_ = GraphSpec::flat(nworkers_);
  deques_ = std::vector<Deque>(size_t(graph_.nlocales) * nworkers_);
  stats_ = std::vector<WorkerStats>(nworkers_);
  for (auto& s : stats_) s.stolen_from.assign(nworkers_, 0);
  AffinityPlan plan = affinity_plan(nworkers_);
  pinned_.reset(new std::atomic<int>[nworkers_]);
  for (int w = 0; w < nworkers_; ++w)
    pinned_[w].store(plan.active ? kPinPending : -1,
                     std::memory_order_relaxed);
#ifdef __linux__
  if (plan.active) {
    // The calling thread becomes worker 0 and gets pinned below; remember
    // its mask so destruction undoes the side effect on the host program.
    orig_mask_.resize(sizeof(cpu_set_t));
    if (pthread_getaffinity_np(
            pthread_self(), sizeof(cpu_set_t),
            reinterpret_cast<cpu_set_t*>(orig_mask_.data())) == 0)
      restore_mask_ = true;
  }
#endif
  g_runtime = this;
  g_worker = 0;
  threads_.reserve(nworkers_ - 1);
  // Spawn BEFORE pinning worker 0: children inherit the caller's original
  // mask and then apply their own targets.
  for (int w = 1; w < nworkers_; ++w) {
    int target = plan.active ? plan.cpu[w] : -1;
    threads_.emplace_back([this, w, target] {
      g_runtime = this;
      g_worker = w;
      pinned_[w].store(target >= 0 ? pin_self(target) : -1,
                       std::memory_order_release);
      worker_loop(w);
    });
  }
  if (plan.active) {
    pinned_[0].store(pin_self(plan.cpu[0]), std::memory_order_release);
    // Rendezvous: pinned_cpu() is well-defined the moment the constructor
    // returns (workers record their result first thing).
    for (int w = 1; w < nworkers_; ++w)
      while (pinned_[w].load(std::memory_order_acquire) == kPinPending)
        std::this_thread::yield();
  }
}

Runtime::~Runtime() {
  shutdown_.store(true, std::memory_order_release);
  for (auto& t : threads_) t.join();
#ifdef __linux__
  if (restore_mask_)
    pthread_setaffinity_np(
        pthread_self(), sizeof(cpu_set_t),
        reinterpret_cast<const cpu_set_t*>(orig_mask_.data()));
#endif
  g_runtime = nullptr;
  g_worker = -1;
  g_finish = nullptr;
}

// One-at-a-time dependency registration walk
// (register_on_all_promise_dependencies, src/hclib-promise.c:171-195): park
// on the *first* unsatisfied promise; its put() resumes the walk.
bool Runtime::register_deps(NTask* t) {
  while (t->dep_index < t->ndeps) {
    NPromise* p = t->dep_at(t->dep_index);
    t->dep_index += 1;
    if (p != nullptr && p->register_waiter(t)) return false;  // parked
  }
  return true;
}

void Runtime::spawn(NTask* t) {
  int w = current_worker();
  ++stats_[w].spawned;
  if (t->finish != nullptr) t->finish->check_in();
  if (register_deps(t)) {
    enqueue(t, w);
  }
}

void Runtime::schedule(NTask* t) { enqueue(t, current_worker()); }

void Runtime::enqueue(NTask* t, int wid) {
  int locale = t->locale;
  if (locale < 0 || locale >= graph_.nlocales) locale = 0;
  // Owner-side Chase-Lev pushes are single-producer: submissions from
  // foreign threads (not runtime workers) go through the injection queue.
  if (g_runtime != this || g_worker < 0) {
    std::lock_guard<std::mutex> lock(inject_mu_);
    inject_.push_back(t);
    inject_count_.fetch_add(1, std::memory_order_release);
    return;
  }
  ++stats_[wid].scheduled;
  if (!deque_at(locale, wid).push(t)) {
    // Deque full: run inline (the reference aborts,
    // src/hclib-runtime.c:520-524; degrading keeps deep trees correct).
    execute(t);
  }
}

void Runtime::promise_put(NPromise* p, void* value) {
  p->datum.store(value, std::memory_order_release);
  NTask* head = p->waiters.exchange(NPromise::closed_sentinel(),
                                    std::memory_order_acq_rel);
  // Publish `satisfied` only after the last touch of *p: a future_wait
  // spinning on it may free the promise the moment this becomes true.
  p->satisfied_.store(true, std::memory_order_release);
  while (head != nullptr && head != NPromise::closed_sentinel()) {
    NTask* next = head->next_waiter;
    head->next_waiter = nullptr;
    if (register_deps(head)) schedule(head);
    head = next;
  }
}

// Pop path over own deques, then steal path over victims' deques, rotating
// the starting locale at the last successful steal and scanning victims
// nearest-first (locale_pop_task / locale_steal_task,
// src/hclib-locality-graph.c:774-805, :843-888).
bool Runtime::find_task(int wid, NTask** out) {
  for (int i = graph_.pop_off[wid]; i < graph_.pop_off[wid + 1]; ++i) {
    if (deque_at(graph_.pop_data[i], wid).pop(out)) return true;
  }
  if (inject_count_.load(std::memory_order_acquire) > 0) {
    std::lock_guard<std::mutex> lock(inject_mu_);
    if (!inject_.empty()) {
      *out = inject_.back();
      inject_.pop_back();
      inject_count_.fetch_sub(1, std::memory_order_release);
      return true;
    }
  }
  int lo = graph_.steal_off[wid], hi = graph_.steal_off[wid + 1];
  int n = hi - lo;
  if (n <= 0) return false;
  int start = last_steal_idx_[wid] % n;
  for (int k = 0; k < n; ++k) {
    int locale = graph_.steal_data[lo + (start + k) % n];
    // Scan every worker's deque at this locale, own deque included: a
    // steal-path locale may be outside this worker's pop path (e.g. a task
    // pushed at a remote locale by this worker), and the reference's
    // locale_steal_task likewise scans all deques of the locale
    // (src/hclib-locality-graph.c:843-888).
    for (int d = 0; d < nworkers_; ++d) {
      int v = (wid + d) % nworkers_;
      if (deque_at(locale, v).steal(out)) {
        if (v != wid) {
          ++stats_[wid].steals;
          ++stats_[wid].stolen_from[v];
        }
        last_steal_idx_[wid] = (start + k) % n;
        return true;
      }
    }
  }
  return false;
}

void Runtime::execute(NTask* t) {
  int w = current_worker();
  FinishScope* prev = g_finish;
  g_finish = t->finish;
  t->fn(t->env);
  g_finish = prev;
  if (t->finish != nullptr) t->finish->check_out();
  ++stats_[w].executed;
  task_free(t);
}

void Runtime::worker_loop(int wid) {
  NTask* t = nullptr;
  int idle_spins = 0;
  while (!shutdown_.load(std::memory_order_acquire)) {
    if (find_task(wid, &t)) {
      execute(t);
      idle_spins = 0;
    } else if (++idle_spins > 1024) {
      std::this_thread::yield();
    }
  }
}

void Runtime::help_until(std::atomic<int64_t>* counter, int64_t target) {
  // Foreign threads (not runtime workers) may not run find_task - the
  // owner-side deque pop is single-consumer. They spin; the workers drain.
  // (Requires nworkers >= 2 for foreign-thread blocking to make progress.)
  if (g_runtime != this || g_worker < 0) {
    while (counter->load(std::memory_order_acquire) != target) {
      std::this_thread::yield();
    }
    return;
  }
  int wid = current_worker();
  NTask* t = nullptr;
  while (counter->load(std::memory_order_acquire) != target) {
    if (find_task(wid, &t)) {
      execute(t);
    } else {
      std::this_thread::yield();
    }
  }
}

// Help-first drain (help_finish, src/hclib-runtime.c:1067-1119, minus the
// fiber swap): run ready tasks on this stack until only the owner's token
// remains, then drop it.
void Runtime::end_finish(FinishScope* f) {
  ++stats_[current_worker()].end_finishes;
  help_until(&f->counter, 1);
  f->counter.store(0, std::memory_order_release);
  if (f->finish_dep != nullptr) promise_put(f->finish_dep, nullptr);
}

void Runtime::end_finish_nonblocking(FinishScope* f, NPromise* dep) {
  f->finish_dep = dep;
  f->self_delete = true;  // detached: the final check_out frees the scope
  f->check_out();         // drop the owner's token; last child (or this) puts
}

void Runtime::future_wait(NPromise* p) {
  if (p->satisfied()) return;
  if (g_runtime != this || g_worker < 0) {  // foreign thread: spin only
    while (!p->satisfied()) std::this_thread::yield();
    return;
  }
  ++stats_[current_worker()].future_waits;
  int wid = current_worker();
  NTask* t = nullptr;
  while (!p->satisfied()) {
    if (find_task(wid, &t)) {
      execute(t);
    } else {
      std::this_thread::yield();
    }
  }
}

bool Runtime::yield(int locale) {
  if (g_runtime != this || g_worker < 0) return false;  // foreign thread
  int wid = current_worker();
  ++stats_[wid].yields;
  NTask* t = nullptr;
  if (locale >= 0 && locale < graph_.nlocales) {
    bool found = deque_at(locale, wid).pop(&t);
    for (int d = 1; d <= nworkers_ && !found; ++d) {
      int v = (wid + d) % nworkers_;
      if (v != wid) found = deque_at(locale, v).steal(&t);
    }
    if (!found) t = nullptr;
  } else if (!find_task(wid, &t)) {
    t = nullptr;
  }
  if (t == nullptr) return false;
  execute(t);
  return true;
}

void Runtime::run_root(void (*fn)(void*), void* env) {
  FinishScope root;
  root.rt = this;
  root.parent = nullptr;
  NTask* t = task_alloc();
  t->fn = fn;
  t->env = env;
  t->finish = &root;
  root.check_in();  // the root task itself
  execute(t);
  end_finish(&root);
}

uint64_t Runtime::total_executed() const {
  uint64_t n = 0;
  for (auto& s : stats_) n += s.executed;
  return n;
}

uint64_t Runtime::total_steals() const {
  uint64_t n = 0;
  for (auto& s : stats_) n += s.steals;
  return n;
}

size_t Runtime::backlog() const {
  size_t n = 0;
  for (auto& d : deques_) n += d.size();
  return n;
}

// Text dump in the spirit of hclib_print_runtime_stats
// (src/hclib-runtime.c:1370-1410): per-worker counters + steal matrix.
std::string Runtime::format_stats() const {
  std::ostringstream os;
  for (int w = 0; w < nworkers_; ++w) {
    const WorkerStats& s = stats_[w];
    os << "worker " << w << ": executed=" << s.executed
       << " spawned=" << s.spawned << " scheduled=" << s.scheduled
       << " steals=" << s.steals << " end_finishes=" << s.end_finishes
       << " future_waits=" << s.future_waits << " yields=" << s.yields
       << "\n";
    if (s.steals > 0) {
      os << "  stolen from:";
      for (int v = 0; v < nworkers_; ++v) {
        if (s.stolen_from[v] > 0) os << " w" << v << ":" << s.stolen_from[v];
      }
      os << "\n";
    }
  }
  return os.str();
}

}  // namespace hcn
