// Minimal SHA-1 (FIPS 180-1) for fixed-size small messages - implemented
// from the published specification for the UTS splittable RNG (the tree spec
// hashes 20-byte states || 4-byte spawn ids; messages are always < 56 bytes,
// so single-block processing suffices).

#pragma once

#include <cstdint>
#include <cstring>

namespace hcn {

inline void sha1_single_block(const uint8_t* msg, size_t len, uint8_t out[20]) {
  // len must be < 56 (fits one 64-byte block with padding + length).
  uint8_t block[64] = {0};
  std::memcpy(block, msg, len);
  block[len] = 0x80;
  uint64_t bits = static_cast<uint64_t>(len) * 8;
  for (int i = 0; i < 8; ++i) block[63 - i] = (bits >> (8 * i)) & 0xff;

  uint32_t w[80];
  for (int i = 0; i < 16; ++i) {
    w[i] = (uint32_t(block[4 * i]) << 24) | (uint32_t(block[4 * i + 1]) << 16) |
           (uint32_t(block[4 * i + 2]) << 8) | uint32_t(block[4 * i + 3]);
  }
  auto rol = [](uint32_t x, int s) { return (x << s) | (x >> (32 - s)); };
  for (int i = 16; i < 80; ++i)
    w[i] = rol(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1);

  uint32_t a = 0x67452301, b = 0xEFCDAB89, c = 0x98BADCFE, d = 0x10325476,
           e = 0xC3D2E1F0;
  for (int i = 0; i < 80; ++i) {
    uint32_t f, k;
    if (i < 20) {
      f = (b & c) | ((~b) & d);
      k = 0x5A827999;
    } else if (i < 40) {
      f = b ^ c ^ d;
      k = 0x6ED9EBA1;
    } else if (i < 60) {
      f = (b & c) | (b & d) | (c & d);
      k = 0x8F1BBCDC;
    } else {
      f = b ^ c ^ d;
      k = 0xCA62C1D6;
    }
    uint32_t tmp = rol(a, 5) + f + e + k + w[i];
    e = d;
    d = c;
    c = rol(b, 30);
    b = a;
    a = tmp;
  }
  uint32_t h[5] = {0x67452301 + a, 0xEFCDAB89 + b, 0x98BADCFE + c,
                   0x10325476 + d, 0xC3D2E1F0 + e};
  for (int i = 0; i < 5; ++i) {
    out[4 * i] = (h[i] >> 24) & 0xff;
    out[4 * i + 1] = (h[i] >> 16) & 0xff;
    out[4 * i + 2] = (h[i] >> 8) & 0xff;
    out[4 * i + 3] = h[i] & 0xff;
  }
}

}  // namespace hcn
