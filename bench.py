"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline: UTS tree-search throughput (nodes/sec) of the vectorized DFS
engine on the canonical T1L tree (BASELINE.json's north-star workload),
compared against this repo's C++ native work-stealing runtime on the local
CPU (the measured baseline BASELINE.md calls for; the reference publishes no
reusable numbers). On a machine without a TPU the headline falls back to T1
on the CPU backend and says so in the metric label.

Secondary numbers (fib megakernel tasks/sec vs Python-host and native
baselines, Cholesky GFLOP/s) go to stderr so the stdout contract stays a
single JSON line.

**Clock-window discipline** (runtime/clockprobe.py): the tunnel-attached
TPU oscillates between fast and throttled clock windows (2-3x spread over
minutes). Every TPU trial here is bracketed by a fixed MXU probe; the
number of record is the MEDIAN over fast-window trials (best and the full
distribution go to stderr and perf-logs/clock_*.jsonl), so a regression is
distinguishable from weather by reading the probe columns.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# --------------------------------------------------------- wall budget
# BENCH_r05 died at the driver's timeout (rc=124) inside a SECONDARY
# section, after the headline had already been measured - and the whole
# round parsed as null because the JSON line only printed at the end.
# Two rules now: (1) the headline runs FIRST and its JSON line flushes
# the moment it exists; (2) every section start is gated on the time
# remaining, so the bench self-truncates instead of being killed mid-
# number. HCLIB_TPU_BENCH_BUDGET_S overrides the default wall budget.

# Armed by main(): other consumers of these bench functions (notably
# tools/perf_regression.py --device, whose whole-suite wall time easily
# exceeds one bench budget) must not have their trials truncated by a
# clock that started at module import.
_T0 = None


def _budget_s() -> float:
    from hclib_tpu.runtime.env import env_float

    return env_float("HCLIB_TPU_BENCH_BUDGET_S", 780.0)


def _remaining() -> float:
    if _T0 is None:
        return float("inf")
    return _budget_s() - (time.monotonic() - _T0)


def section(name: str, est_s: float, fn):
    """Run one bench section if ~est_s seconds fit in the remaining wall
    budget; a failure or a skip never breaks the stdout contract (all
    section output goes to stderr)."""
    left = _remaining()
    if left < est_s:
        log(f"SKIP {name}: {left:.0f}s of budget left, ~{est_s:.0f}s needed")
        return None
    try:
        return fn()
    except Exception as e:
        log(f"{name} failed: {e}")
        return None


_PROBE = None


def _probe():
    """Shared clock probe (one compile per bench process)."""
    global _PROBE
    if _PROBE is None:
        from hclib_tpu.runtime.clockprobe import ClockProbe

        _PROBE = ClockProbe()
    return _PROBE


def _chol_ceiling_pct(gflops: float) -> float:
    """Achieved f32-effective GFLOP/s as a percentage of the 3-pass f32
    ceiling (probe/3): every f32-accurate GEMM costs 3 bf16 MXU passes, so
    this is the one ceiling formula both the section log and the end-of-run
    summary must agree on."""
    return 100.0 * gflops / (_probe().best * 1000.0 / 3.0)


def windowed(
    name: str,
    fn,
    trials: int,
    spread_seconds: float = 8.0,
    min_fast: int = 3,
    max_trials: int = 0,
):
    """Run ``fn`` (-> value, higher better) ``trials`` times, each
    bracketed by clock-probe samples; returns the WindowedTrials stats
    dict (median/best over fast windows) and logs the distribution.

    Trustworthy-number policy (VERDICT r4 #4): if fewer than ``min_fast``
    trials landed in fast clock windows, keep running spread trials (up to
    ``max_trials``, default 3x ``trials``) until enough do - a median
    backed by <3 fast windows is weather, not measurement. The cap keeps a
    fully-throttled chip from stalling the bench; the stats label then
    says how many fast windows actually back the number."""
    from hclib_tpu.runtime.clockprobe import WindowedTrials

    wt = WindowedTrials(name, probe=_probe())
    max_trials = max_trials or 3 * trials

    def n_fast() -> int:
        return wt.count_fast()

    t = 0
    while t < trials or (n_fast() < min_fast and t < max_trials):
        if t and _remaining() < 0:
            log(f"  {name}: wall budget exhausted after {t} trials")
            break
        if t:
            time.sleep(spread_seconds)
        rec = wt.run(fn)
        log(
            f"  {name} trial {t}: {rec['value']:.4g} "
            f"(probe {rec['probe_pre_tflops']:.0f}/"
            f"{rec['probe_post_tflops']:.0f} TF)"
        )
        t += 1
    s = wt.stats()
    log(
        f"{name}: median {s['median']:.4g} / best {s['best']:.4g} "
        f"({s['n_fast']}/{s['n_trials']} fast windows, spread "
        f"{s['spread']}x, probe best {s['probe_best_tflops']:.0f} TF)"
    )
    return s


# Reps gaps under this are transfer/clock jitter, not measurement: any
# slope computed from them is nonsense (observed: 7e12 tasks/s from a
# near-zero denominator). The -1.0 sentinel is what WindowedTrials
# excludes from statistics - ONE policy for every slope bench here.
_SHEAR_GAP_S = 5e-3


def _slope_or_sheared(gap_seconds: float, units: float) -> float:
    """units/sec over a reps gap, or the sheared-trial sentinel."""
    if gap_seconds < _SHEAR_GAP_S:
        return -1.0
    return units / gap_seconds


def _slope_harness(mk, builder, expect_value, fuel, reps_pair, label):
    """Shared steady-state harness: re-run the staged graph R times inside
    one kernel launch for two R values; per-task cost is the slope between
    them - this cancels launch + host<->device transfer overhead, which on
    this tunnel setup is ~0.1-0.8 s and would otherwise swamp the
    measurement. The warm-up call's value slot 0 is asserted against
    ``expect_value``; the D2H read of the counts word is the only reliable
    sync through the tunnel (block_until_ready returns early on remote
    arrays). Returns a zero-arg trial callable (-> tasks/sec) for the
    windowed runner."""
    import jax
    import jax.numpy as jnp

    from hclib_tpu.device.megakernel import C_EXECUTED

    tasks, succ, ring, counts = builder.finalize(
        capacity=mk.capacity, succ_capacity=mk.succ_capacity
    )

    def fresh():
        return [
            jax.device_put(jnp.asarray(x))
            for x in (tasks, succ, ring, counts,
                      np.zeros(mk.num_values, np.int32))
        ]

    jits = {}
    for reps in reps_pair:
        jits[reps] = mk._build(fuel, reps=reps)
        outs = jits[reps](*fresh())  # compile + warm
        assert int(np.asarray(outs[3])[0]) == expect_value, f"{label} wrong"

    def one_trial():
        points = []
        for reps in reps_pair:
            t0 = time.perf_counter()
            outs = jits[reps](*fresh())
            n = int(np.asarray(outs[2])[C_EXECUTED])  # d2h = true sync
            dt = time.perf_counter() - t0
            points.append((dt, n))
        (d1, n1), (d2, n2) = points
        return _slope_or_sheared(d2 - d1, n2 - n1)

    return one_trial


def _graph_slope_trial(jits, fresh, reps_pair, units_per_graph):
    """Two-reps slope over a pre-staged megakernel graph -> units/sec.

    The shared machinery of the Cholesky and SW-wave benches (the
    fib benches use _slope_harness, which also owns graph STAGING): run
    the compiled reps-variants on fresh device buffers, sync via a D2H
    read of the counts word (the only reliable sync through the tunnel),
    and return units_per_graph over the per-graph slope, with the shared
    shear guard (_slope_or_sheared)."""
    from hclib_tpu.device.megakernel import C_EXECUTED

    r1, r2 = reps_pair

    def one_trial():
        t = {}
        for r in reps_pair:
            args = fresh()
            np.asarray(args[3])  # H2D done
            t0 = time.perf_counter()
            outs = jits[r](*args)
            _ = int(np.asarray(outs[2])[C_EXECUTED])
            t[r] = time.perf_counter() - t0
        return _slope_or_sheared(
            t[r2] - t[r1], units_per_graph * (r2 - r1)
        )

    return one_trial


def _slope_rate(mk, builder, expect_value, fuel, reps_pair, label):
    """One-shot form of _slope_harness (CPU/interpret paths)."""
    one_trial = _slope_harness(
        mk, builder, expect_value, fuel, reps_pair, label
    )
    rate = one_trial()
    return rate, 1.0 / rate


def bench_device_vfib():
    """Steady-state batch-dispatch (vector tier) throughput: the fib(30)
    graph (2,692,537 tasks - the whole recursion tree, lane-level work
    stealing balancing the lanes) under the shared slope harness."""
    import jax

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.workloads import VFIB, make_vfib_megakernel

    interpret = jax.default_backend() != "tpu"
    # 100 reps between the two points ~= 270M tasks ~= 100-190 ms of
    # kernel time: the slope must stay well above the ~100 ms tunnel
    # transfer jitter or it measures weather (a (2,12) pair produced
    # 7e12 "tasks/s" from an 11 ms gap).
    n, reps_pair = (30, (10, 110)) if not interpret else (10, (1, 2))
    expect = {30: 832040, 10: 55}[n]
    mk = make_vfib_megakernel(max_n=n + 2, interpret=interpret)
    b = TaskGraphBuilder()
    b.add(VFIB, args=[n], out=0)
    if interpret:
        rate, slope = _slope_rate(
            mk, b, expect, 1 << 30, reps_pair, f"device vfib({n})"
        )
        log(f"device fib batch-dispatch steady-state: {slope*1e9:.2f} "
            f"ns/task -> {rate/1e6:,.1f}M tasks/s (interpret)")
        return rate
    one_trial = _slope_harness(
        mk, b, expect, 1 << 30, reps_pair, f"device vfib({n})"
    )
    s = windowed("fib batch-dispatch tier", one_trial, trials=3)
    log(f"device fib batch-dispatch steady-state: "
        f"{1e9/s['median']:.2f} ns/task -> {s['median']/1e6:,.1f}M tasks/s "
        f"median (best {s['best']/1e6:,.1f}M)")
    return s["median"]


def bench_device_fib():
    """Steady-state scalar-tier megakernel throughput: the fib(12) task
    graph (697 dynamic tasks: spawns, joins, continuation passing) under
    the shared slope harness (the resident scheduler never exits between
    reps)."""
    import jax

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.workloads import FIB, make_fib_megakernel

    interpret = jax.default_backend() != "tpu"
    reps_pair = (100, 2000) if not interpret else (1, 3)
    mk = make_fib_megakernel(768, interpret=interpret)
    b = TaskGraphBuilder()
    b.add(FIB, args=[12], out=0)  # 697 tasks, fits the SMEM table
    if interpret:
        rate, slope = _slope_rate(
            mk, b, 144, 1 << 22, reps_pair, "device fib"
        )
        log(f"device fib steady-state: {slope*1e9:.0f} ns/task -> "
            f"{rate:,.0f} tasks/s (interpret)")
        return rate
    one_trial = _slope_harness(mk, b, 144, 1 << 22, reps_pair, "device fib")
    s = windowed("fib scalar tier", one_trial, trials=3)
    log(f"device fib steady-state: {1e9/s['median']:.0f} ns/task -> "
        f"{s['median']:,.0f} tasks/s median (best {s['best']:,.0f})")
    return s["median"]


def bench_host_fib(n: int = 20):
    from hclib_tpu.models import fib

    r = fib.run(n, variant="finish")
    log(f"host fib({n}): {r['tasks']} tasks in {r['seconds']*1000:.0f} ms "
        f"-> {r['tasks_per_sec']:,.0f} tasks/s")
    return r["tasks_per_sec"]


def bench_native_fib(n: int = 27):
    """The strongest CPU baseline: this repo's C++ work-stealing runtime."""
    try:
        from hclib_tpu.native import NativeRuntime

        with NativeRuntime() as rt:
            t0 = time.perf_counter()
            v = rt.fib(n)
            dt = time.perf_counter() - t0
            tasks = rt.executed
        rate = tasks / dt
        log(f"native C++ fib({n}) = {v}: {tasks} tasks in {dt*1000:.0f} ms "
            f"-> {rate:,.0f} tasks/s ({rt.nworkers} workers)")
        return rate
    except Exception as e:
        log(f"native baseline unavailable: {e}")
        return None


def bench_device_sw():
    """Secondary: batched Smith-Waterman GCUPS via the fused Pallas sweep
    (device/sw_pallas.py). Per-call tunnel overhead (~80 ms) dwarfs the
    compute, so the rate is the slope between two query lengths."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return None
    from hclib_tpu.device.sw_pallas import _sw_pallas

    rng = np.random.default_rng(1)
    B, m = 1024, 1024
    bt = jax.device_put(jnp.asarray(rng.integers(0, 4, (m, B)), jnp.int32))
    ats = {}
    for n in (256, 2048):
        ats[n] = jax.device_put(
            jnp.asarray(rng.integers(0, 4, (n, B)), jnp.int32)
        )
        np.asarray(_sw_pallas(ats[n], bt, block_b=256, interpret=False))

    def one_trial():
        # Both lengths timed back-to-back inside ONE trial so a clock-
        # window edge between them can't flip the slope negative. Each
        # leg dispatches K calls and syncs ONCE (one D2H read at the
        # end): single-call legs are ~4-35 ms of compute against ~100 ms
        # of tunnel transfer jitter, which dominated the 2-point slope
        # and made the quoted rate weather, not measurement.
        K = 8
        t = {}
        for n in (256, 2048):
            out = None
            t0 = time.perf_counter()
            for _ in range(K):
                out = _sw_pallas(ats[n], bt, block_b=256, interpret=False)
            np.asarray(out)  # D2H = the only reliable tunnel sync
            t[n] = (time.perf_counter() - t0) / K
        return B * m * (2048 - 256) / (t[2048] - t[256]) / 1e9

    s = windowed("SW pallas GCUPS", one_trial, trials=3)
    log(f"device SW [pallas]: B={B} m={m}, {s['median']:.0f} GCUPS median "
        f"(best {s['best']:.0f})")
    return s["median"]


def bench_device_sw_wave(trials: int = 3, spread_seconds: float = 8.0):
    """Secondary: GCUPS of the wave-batched SW tile-DAG engine
    (device/smithwaterman.py device_sw_wave - wave chunks chained by REAL
    dependencies through the megakernel scheduler, unlike the fused
    sw_pallas sweep which has no task graph). Scoring mode (with_h=False)
    so the measured rate is the DP itself, not H-matrix writeback. Slope
    harness over reps cancels the tunnel round-trip."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return None
    from hclib_tpu.device.smithwaterman import (
        T as SWT,
        build_sw_wave_graph,
        make_sw_wave_megakernel,
        sw_wave_buffers,
    )
    from hclib_tpu.models.smithwaterman import random_seq

    n = m = 8192
    nt = n // SWT
    mk = make_sw_wave_megakernel(nt, nt, interpret=False, with_h=False)
    builder = build_sw_wave_graph(nt, nt)
    a, b_ = random_seq(n, 5), random_seq(m, 6)
    tasks, succ, ring, counts = builder.finalize(
        capacity=mk.capacity, succ_capacity=mk.succ_capacity
    )
    bufs = sw_wave_buffers(a, b_)
    host = (
        tasks, succ, ring, counts, np.zeros(mk.num_values, np.int32),
        bufs["aseq"], bufs["bseq"], bufs["bot"], bufs["right"],
    )

    def fresh():
        return [jax.device_put(jnp.asarray(x)) for x in host]

    reps_pair = (2, 12)
    jits = {r: mk._build(1 << 22, reps=r) for r in reps_pair}
    score = None
    outs = None
    for r in reps_pair:
        outs = jits[r](*fresh())  # compile + warm
        score = int(np.asarray(outs[3])[0])  # best alignment score
    # Correctness gate: the wave DAG's best score must match the
    # independent batched-scan XLA engine on the same pair (a different
    # algorithmic formulation of the same DP, no megakernel involved).
    from hclib_tpu.device.sw_vec import sw_score_one

    ref = sw_score_one(np.asarray(a), np.asarray(b_))
    assert score == ref, (score, ref)
    log(f"device SW [wave-DAG]: score {score} matches the scan engine")
    # Batched-dispatch tier counters (guarded by tools/perf_regression.py
    # so the occupancy the speedup rests on never floats free).
    global LAST_SW_WAVE_TIERS
    LAST_SW_WAVE_TIERS = tiers = mk.decode_tier_stats(
        np.asarray(outs[4 + len(mk.data_specs)])
    )
    log(
        f"device SW [wave-DAG]: batch occupancy "
        f"{tiers['batch_occupancy']:.2f} ({tiers['batch_rounds']} rounds x "
        f"width {tiers['batch_width']}, {tiers['prefetch_hits']} prefetch "
        f"hits, {tiers['full_rounds']} full rounds)"
    )

    one_trial = _graph_slope_trial(jits, fresh, reps_pair, n * m / 1e9)
    s = windowed("SW wave-DAG GCUPS", one_trial, trials, spread_seconds)
    log(
        f"device SW [wave-DAG]: {n}x{m} grid, {builder.num_tasks} chunk "
        f"tasks, {s['median']:.1f} GCUPS median (best {s['best']:.1f})"
    )
    return s["median"]


def bench_device_cholesky(
    trials: int = 4,
    spread_seconds: float = 12.0,
    n: int = 8192,
    residual_bound: float = 1e-6,
):
    """In-kernel tiled-Cholesky throughput: a DDF DAG of 512x512 MXU
    tiles (column-fused TRSM streams + row-fused trailing updates over
    PRE-SPLIT bf16 operands, double-buffered DMA) - hundreds of
    heterogeneous tasks sustained by the resident scheduler, not a toy
    graph. One fresh factorization is residual-checked on-device first
    (||LL^T - A||_max / ||A||_max < ``residual_bound``, measured with a
    HIGHEST-precision matmul - the default bf16 matmul's own error would
    drown the signal); throughput then comes from the steady-state slope
    harness (re-run the staged graph R times inside one kernel launch;
    per-graph cost = slope between two R values, cancelling the ~0.8 s
    tunnel round-trip). Trials are clock-probe bracketed; the number of
    record is the median over fast windows.

    Two sizes ship (fused-graph task counts): n=8192 (151 tasks;
    residual gated < 1e-6, the reference-parity bar) and n=16384 (559
    tasks; the f32 accumulation error over 2x the update steps lands
    ~1.5e-6, gated < 2e-6 and reported - the POTRF/TRSM serial fraction
    amortizes, so this is the peak-utilization row)."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return None
    from hclib_tpu.device.cholesky import (
        build_cholesky_graph,
        cholesky_buffers,
        device_cholesky,
        make_cholesky_megakernel,
    )
    from hclib_tpu.models.cholesky import make_spd

    # 512 tiles flip the GEMMs compute-bound (arithmetic intensity ts/8
    # flops/byte); 1024 tiles measured slower (POTRF block algebra grows
    # faster than the DMA savings).
    tile = 512
    nt = n // tile
    # fused-only capacity: at nt=32 the unfused task table would overflow
    # the 1 MB SMEM budget (~32 B per descriptor word in SMEM windows).
    mk = make_cholesky_megakernel(
        nt, interpret=False, tile=tile, fused_only=True
    )
    a = make_spd(n).astype(np.float32)

    # Correctness gate on the REAL size (reference keeps a checked result,
    # test/cholesky/run.sh): factor once fresh, residual on-device.
    L, _ = device_cholesky(a, interpret=False, mk=mk, tile=tile)
    La = jax.device_put(jnp.asarray(L))
    Aa = jax.device_put(jnp.asarray(a))
    m = jnp.matmul(La, La.T, precision=jax.lax.Precision.HIGHEST)
    rel = float(jnp.max(jnp.abs(m - Aa)) / jnp.max(jnp.abs(Aa)))
    assert rel < residual_bound, (
        f"cholesky n={n} residual {rel:.2e} >= {residual_bound:g}"
    )
    log(f"device cholesky n={n}: residual {rel:.2e} (< {residual_bound:g})")
    del L, La, Aa, m

    b = build_cholesky_graph(nt)
    tasks, succ, ring, counts = b.finalize(
        capacity=mk.capacity, succ_capacity=mk.succ_capacity
    )
    bufs = cholesky_buffers(a, nt, tile)
    host = (
        tasks, succ, ring, counts, np.zeros(8, np.int32),
        bufs["tiles"], bufs["linvsp"], bufs["lsp"],
    )

    def fresh():
        # input_output_aliases donate the inputs; every call needs fresh
        # device buffers.
        return [jax.device_put(jnp.asarray(x)) for x in host]

    reps_pair = (5, 45) if n <= 8192 else (2, 12)
    jits = {r: mk._build(1 << 22, reps=r) for r in reps_pair}
    ntasks = 0
    for r in reps_pair:
        outs = jits[r](*fresh())  # compile + warm
        ntasks = int(np.asarray(outs[2])[5]) // r

    one_trial = _graph_slope_trial(jits, fresh, reps_pair, n**3 / 3.0 / 1e9)
    s = windowed(
        f"cholesky n={n} ({ntasks} tasks)", one_trial, trials,
        spread_seconds,
    )
    # Physics context for the number: every f32-accurate GEMM costs 3 bf16
    # MXU passes, so the achievable ceiling is probe/3 - report achieved
    # utilization against THAT, plus the bf16-equivalent MXU rate, so
    # "fraction of the probed clock" is judged against the right bound.
    probe_tf = _probe().best
    log(
        f"device cholesky: {s['median']/1e3:.1f} TF f32-effective = "
        f"{_chol_ceiling_pct(s['median']):.0f}% of the 3-pass f32 ceiling "
        f"(probe {probe_tf:.0f} TF / 3 passes); bf16-equivalent MXU rate "
        f"{3.0 * s['median']/1e3:.1f} TF = "
        f"{100.0 * 3.0 * s['median'] / (probe_tf * 1000.0):.0f}% of probe"
    )
    return s["median"]


def emit_trace_artifacts(log_dir: str = "perf-logs"):
    """--trace artifact emission: one traced megakernel run + one
    instrumented host run, folded into a MetricsRegistry snapshot
    (JSON + Prometheus text) and a merged Perfetto file under
    ``log_dir`` - the machine-readable observability bundle of a bench
    round (budget-gated like every other section)."""
    import hclib_tpu as hc
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.tracebuf import trace_to_jsonable
    from hclib_tpu.device.workloads import FIB, make_fib_megakernel
    from hclib_tpu.runtime.metrics import MetricsRegistry

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    try:
        import timeline
    finally:
        sys.path.pop(0)

    os.makedirs(log_dir, exist_ok=True)
    ts = int(time.time())

    # Device: the fib megakernel with the flight recorder on (interpret
    # off-TPU; the recorder rides inside the kernel either way).
    mk = make_fib_megakernel(768, trace=1024)
    b = TaskGraphBuilder()
    b.add(FIB, args=[12], out=0)
    iv, _, dev_info = mk.run(b)
    assert int(iv[0]) == 144

    # Host: an instrumented + metrics-enabled runtime.
    rt = hc.Runtime(nworkers=2, instrument=True, metrics=True)

    def body():
        with hc.finish():
            for _ in range(200):
                hc.async_(lambda: None)

    rt.run(body)
    dump = rt.event_log.dump(log_dir)

    reg = rt.metrics or MetricsRegistry()
    reg.add_run_info("device_fib", dev_info)
    snap = reg.snapshot()
    mpath = os.path.join(log_dir, f"trace_{ts}.metrics.json")
    with open(mpath, "w") as f:
        f.write(reg.to_json(snap))
    with open(os.path.join(log_dir, f"trace_{ts}.prom"), "w") as f:
        f.write(reg.to_prometheus(snap))
    tpath = os.path.join(log_dir, f"trace_{ts}.trace.json")
    with open(tpath, "w") as f:
        json.dump(trace_to_jsonable(dev_info["trace"]), f)
    ppath = os.path.join(log_dir, f"trace_{ts}.perfetto.json")
    doc = timeline.export_perfetto(
        ppath, dump_path=dump, traces=[dev_info["trace"]]
    )
    log(
        f"trace artifacts: {len(doc['traceEvents'])} perfetto events -> "
        f"{ppath}; metrics -> {mpath}; device trace -> {tpath}; "
        f"host dump -> {dump}"
    )
    return ppath


T1_NODES = 4130071
T1L_NODES = 102181082

# Last bench_device_sw_wave run's batched-tier counters (occupancy,
# prefetch hits), for tools/perf_regression.py.
LAST_SW_WAVE_TIERS: dict = {}


def bench_native_uts():
    """CPU baseline for the headline: C++ runtime on UTS T1 (same node rate
    as T1L, 50x faster to run)."""
    from hclib_tpu.models.uts import T1
    from hclib_tpu.native import NativeRuntime

    with NativeRuntime() as rt:
        t0 = time.perf_counter()
        nodes, leaves, depth = rt.uts(T1.shape, T1.gen_mx, T1.b0, T1.root_seed)
        dt = time.perf_counter() - t0
    assert nodes == T1_NODES, nodes
    rate = nodes / dt
    log(f"native C++ UTS T1: {nodes} nodes in {dt:.2f}s -> {rate:,.0f} nodes/s "
        f"({rt.nworkers} workers)")
    return rate


def bench_device_uts():
    """Headline: vectorized-DFS UTS on the canonical T1L tree
    (102,181,082 nodes; BASELINE.json's north-star workload). Returns
    (rate, tree_label, statistic_tag).

    Engine: the fully-fused Pallas kernel (uts_pallas.py, whole traversal
    resident on-core) - ~5x the split-XLA engine; falls back to uts_vec if
    the fused kernel fails to compile (it leans on newer Mosaic features:
    same-shape gathers, dynamic-offset DMA)."""
    import importlib

    import jax

    from hclib_tpu.models.uts import T1, T1L

    on_tpu = jax.default_backend() == "tpu"
    params, expected, tree = (T1L, T1L_NODES, "T1L") if on_tpu else (T1, T1_NODES, "T1")
    device = None if on_tpu else jax.devices("cpu")[0]
    # Empirically best single-chip config (v5e): 8192 lanes as (64,128)
    # planes, ~240k subtree roots (deep enough that the shared root queue
    # bounds imbalance by one small subtree), refill threshold nlanes/32.
    # The tunnel-attached TPU oscillates between fast and throttled windows
    # (3x run-to-run spread). This is the HEADLINE metric the driver
    # records once per round, so spend 7 spread trials on it: the median
    # over fast-labeled windows converges on the true fast rate even if
    # several trials land throttled.
    lanes, roots, div, trials = ((64, 128), 256 * 1024, 32, 7) if on_tpu else (
        (8, 128), 8192, 8, 2)
    # Engines resolved lazily inside the try so an import failure (e.g. a
    # jax build without the Mosaic features uts_pallas leans on) falls
    # through to the next engine instead of crashing the bench.
    engines = (
        ("pallas", "hclib_tpu.device.uts_pallas", "uts_pallas"),
        ("xla", "hclib_tpu.device.uts_vec", "uts_vec"),
    )
    for name, module, fn in engines:
        try:
            engine = getattr(importlib.import_module(module), fn)
            holder = {}

            def one_trial(engine=engine):
                r = engine(params, target_roots=roots, device=device,
                           lanes=lanes, min_idle_div=div)
                assert r["nodes"] == expected, r["nodes"]
                holder["r"] = r
                return r["nodes_per_sec"]

            if on_tpu:
                s = windowed(f"UTS {tree} [{name}]", one_trial, trials)
                # Number of record: median over fast windows. If NO trial
                # landed in a fast window even after windowed()'s retry
                # policy (the chip can throttle for the whole bench), the
                # all-trials median is biased far low (throttled UTS
                # trials measure 4-6x under fast ones) - report
                # best-observed instead, and TAG the emitted JSON with the
                # statistic used so downstream consumers can't conflate
                # the two (the window label and full distribution are in
                # perf-logs either way).
                rate = s["median"] if s["n_fast"] else s["best"]
                stat = (
                    f"median-fast-{s['n_fast']}of{s['n_trials']}"
                    if s["n_fast"] else "best-fallback-all-throttled"
                )
            else:
                rate = max(one_trial() for _ in range(trials))
                stat = f"best-of-{trials}"
            r = holder["r"]
            log(f"device UTS {tree} [{name}]: {r['nodes']} nodes, "
                f"{rate/1e6:.1f}M nodes/s (lane eff "
                f"{100.0 * r['lane_efficiency']:.0f}%, statistic {stat})")
            return rate, tree, stat
        except AssertionError:
            raise
        except Exception as e:
            log(f"UTS engine {name} failed ({str(e)[:160]}); trying next")
    raise RuntimeError("no UTS engine ran")


def bench_checkpoint():
    """Checkpoint/restore cost of record (ISSUE 5): quiesce latency,
    bundle size, and save/restore wall time for the seeded UTS traversal
    and the Cholesky factor, written to perf-logs/<ts>.checkpoint.json.
    Runs on the current backend (interpret on CPU-only hosts) - the
    numbers that matter operationally are the QUIESCE latency (how long a
    preemption notice stalls before the state is exportable) and the
    BUNDLE size (what a preemption window must flush to disk)."""
    import tempfile

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.workloads import make_uts_megakernel
    from hclib_tpu.runtime.checkpoint import (
        restore_megakernel, snapshot_megakernel,
    )

    out = {}

    def uts_builder():
        b = TaskGraphBuilder()
        b.add(0, args=[1, 0])  # UTS_NODE root
        return b

    def one(name, make_mk, builder, data_of):
        mk_plain = make_mk(False)
        full = mk_plain.run(builder(), data=data_of())[2]
        mk = make_mk(True)
        mk.run(builder(), data=data_of())  # warm the checkpoint build
        at = max(1, full["executed"] // 2)
        t0 = time.perf_counter()
        _, _, info_q = mk.run(builder(), data=data_of(), quiesce=at)
        quiesce_s = time.perf_counter() - t0
        bundle = snapshot_megakernel(mk, info_q)
        d = tempfile.mkdtemp(prefix=f"hclib-bench-ckpt-{name}-")
        stats = bundle.save(d)
        t0 = time.perf_counter()
        _, _, info_r = restore_megakernel(d, make_mk(True))
        restore_s = time.perf_counter() - t0
        assert info_r["executed"] == full["executed"], (name, info_r)
        row = {
            "executed": full["executed"],
            "checkpoint_at": info_q["quiesce"]["executed_at"],
            "quiesce_entry_s": round(quiesce_s, 4),
            "bundle_bytes": stats["bundle_bytes"],
            "save_s": stats["save_s"],
            "restore_s": round(restore_s, 4),
        }
        out[name] = row
        log(f"checkpoint [{name}]: quiesced at "
            f"{row['checkpoint_at']}/{row['executed']} tasks in "
            f"{row['quiesce_entry_s'] * 1e3:.1f} ms, bundle "
            f"{row['bundle_bytes'] / 1024:.0f} KiB "
            f"(save {row['save_s'] * 1e3:.1f} ms, restore+drain "
            f"{row['restore_s'] * 1e3:.1f} ms)")

    one(
        "uts",
        lambda ck: make_uts_megakernel(checkpoint=ck),
        uts_builder,
        lambda: None,
    )

    from hclib_tpu.device.cholesky import (
        build_cholesky_graph, cholesky_buffers, make_cholesky_megakernel,
    )
    from hclib_tpu.models.cholesky import make_spd

    nt = 4
    a = make_spd(nt * 128).astype(np.float32)
    one(
        "cholesky",
        lambda ck: make_cholesky_megakernel(nt, checkpoint=ck),
        lambda: build_cholesky_graph(nt),
        lambda: cholesky_buffers(a, nt),
    )

    # Durable-store arms (ISSUE 17). Schema under out["store"]:
    #   publish_fsync_s / publish_nofsync_s - median save() wall time
    #     (stage + hash + atomic rename [+ fsync]) for the UTS bundle;
    #   cold_load_clean_s - load_latest() on a healthy 3-gen store;
    #   cold_load_healing_s - load_latest() with the 2 NEWEST gens
    #     corrupt (2 quarantine moves + sha walk before the valid gen);
    #   bundle_bytes - the payload all arms move.
    # Every arm logs its own line as it lands, so a timeout kill
    # (rc=124) still leaves the completed numbers in the transcript.
    import shutil

    from hclib_tpu.runtime.checkpoint import BundleStore

    mk = make_uts_megakernel(checkpoint=True)
    _, _, info_q = mk.run(uts_builder(), quiesce=8)
    bundle = snapshot_megakernel(mk, info_q)
    store_row = {}

    def publish(fsync, trials=5):
        times = []
        for _ in range(trials):
            d = tempfile.mkdtemp(prefix="hclib-bench-store-")
            st = BundleStore(d, keep=3, fsync=fsync)
            t0 = time.perf_counter()
            st.save(bundle)
            times.append(time.perf_counter() - t0)
            shutil.rmtree(d, ignore_errors=True)
        return round(sorted(times)[len(times) // 2], 4)

    store_row["publish_fsync_s"] = publish(True)
    store_row["publish_nofsync_s"] = publish(False)
    log(f"store publish: {store_row['publish_fsync_s'] * 1e3:.1f} ms "
        f"fsync'd / {store_row['publish_nofsync_s'] * 1e3:.1f} ms fast "
        f"(atomic-rename generational save)")

    def cold_load(corrupt_newest):
        d = tempfile.mkdtemp(prefix="hclib-bench-store-")
        st = BundleStore(d, keep=3, fsync=False)
        for _ in range(3):
            st.save(bundle)
        for g in st.generations()[-corrupt_newest:] if corrupt_newest else []:
            npz = os.path.join(st.path_of(g), "state.npz")
            blob = open(npz, "rb").read()
            with open(npz, "wb") as f:
                f.write(blob[:-4] + b"\xff" * 4)
        reader = BundleStore(d, fsync=False)
        t0 = time.perf_counter()
        got = reader.load_latest()
        dt = time.perf_counter() - t0
        assert len(reader.faults) == corrupt_newest
        assert got.diff(bundle)["equal"]
        shutil.rmtree(d, ignore_errors=True)
        return round(dt, 4)

    store_row["cold_load_clean_s"] = cold_load(0)
    store_row["cold_load_healing_s"] = cold_load(2)
    stats = bundle.save(tempfile.mkdtemp(prefix="hclib-bench-store-"))
    store_row["bundle_bytes"] = stats["bundle_bytes"]
    out["store"] = store_row
    log(f"store cold load_latest: "
        f"{store_row['cold_load_clean_s'] * 1e3:.1f} ms clean / "
        f"{store_row['cold_load_healing_s'] * 1e3:.1f} ms healing past "
        f"2 quarantined generations "
        f"({store_row['bundle_bytes'] / 1024:.0f} KiB bundle)")

    logdir = os.path.join(os.path.dirname(__file__), "perf-logs")
    os.makedirs(logdir, exist_ok=True)
    path = os.path.join(logdir, f"{int(time.time())}.checkpoint.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    log(f"checkpoint bench written: {path}")
    return out


def bench_autoscale():
    """Elastic-autoscaling cost of record (ISSUE 6): resize latency
    (quiesced state -> resumable state across a reshard) and tasks/s
    sustained THROUGH scale events, for an autoscaled UTS mesh that
    scales 2 -> 4 under backlog and back in on the idle tail. Written to
    perf-logs/<ts>.autoscale.json. Needs the Mosaic interpret mode on
    CPU hosts (the resident mesh simulates remote DMA); logged as a skip
    otherwise."""
    import jax

    from hclib_tpu.jaxcompat import has_mosaic_interpret

    if jax.default_backend() != "tpu" and not has_mosaic_interpret():
        log("autoscale bench: no TPU and no Mosaic interpret mode; skip")
        return None
    import hclib_tpu as hc
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.device.workloads import UTS_NODE, make_uts_megakernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    def make_kernel(ndev):
        mk = make_uts_megakernel(max_depth=7, interpret=True,
                                 checkpoint=True)
        return ResidentKernel(
            mk, cpu_mesh(ndev, axis_name="q"),
            migratable_fns=[UTS_NODE], window=4, homed=False,
        )

    builders = [TaskGraphBuilder() for _ in range(2)]
    for d in range(2):
        for r in range(8):
            builders[d].add(UTS_NODE, args=[d * 8 + r + 1, 0])
    reg = hc.MetricsRegistry()
    asc = hc.Autoscaler(
        make_kernel,
        hc.AutoscalerPolicy(min_devices=1, max_devices=4,
                            scale_out_backlog=4.0, scale_in_backlog=1.0,
                            hysteresis=1, cooldown=1),
        slice_rounds=8, metrics=reg,
    )
    t0 = time.perf_counter()
    iv, _, info = asc.run(builders, quantum=8)
    wall = time.perf_counter() - t0
    resizes = [e for e in info["scale_events"]
               if e["from_ndev"] != e["to_ndev"]]
    out = {
        "executed": info["executed"],
        "wall_s": round(wall, 4),
        "tasks_per_sec": round(info["executed"] / max(wall, 1e-9)),
        "slices": len(info["scale_events"]),
        "resizes": [
            {
                "kind": e["kind"], "from": e["from_ndev"],
                "to": e["to_ndev"],
                "resize_latency_s": e["resize_latency_s"],
            }
            for e in resizes
        ],
        "ndev_final": info["ndev_final"],
    }
    logdir = os.path.join(os.path.dirname(__file__), "perf-logs")
    os.makedirs(logdir, exist_ok=True)
    path = os.path.join(logdir, f"{int(time.time())}.autoscale.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    lat = [r["resize_latency_s"] for r in out["resizes"]
           if r["resize_latency_s"] is not None]
    if lat:
        log(f"autoscale: {info['executed']} tasks through "
            f"{len(resizes)} resize(s) at {out['tasks_per_sec']:,} "
            f"tasks/s; resize latency {max(lat) * 1e3:.1f} ms max")
    else:
        log(f"autoscale: {info['executed']} tasks at "
            f"{out['tasks_per_sec']:,} tasks/s, no resizes fired")
    log(f"autoscale bench written: {path}")
    return out


def _bench_tenants_mesh(weights: dict, per_tenant: int) -> dict:
    """The MESH arm (ISSUE 13): the same 3-lane roster spanning a
    4-device front door (MeshTenantTable routing + the numpy WRR
    reference model - the executable spec of the in-kernel poll;
    interpret mode serializes the DMAs, so the model is the honest
    host-side price), riding ONE live reshard cut 4 -> 2 mid-stream
    (the scale event). Reports aggregate tasks/s and per-tenant
    p50/p99 admission-to-complete latency ACROSS the event, plus the
    cut's own latency - the serving-latency seed direction 1 inherits."""
    import numpy as np

    from hclib_tpu.device.descriptor import RING_ROW
    from hclib_tpu.device.tenants import (
        MeshTenantTable, TenantSpec, wrr_poll_reference,
    )

    # Region sized so each tenant's rows fit one lane region even at
    # the 2-device trough (the lifetime budget resets at the cut).
    region = -(-per_tenant // (2 * 8)) * 8 + 16
    specs = [TenantSpec(t, weight=w, queue_capacity=4 * per_tenant)
             for t, w in weights.items()]
    table = MeshTenantTable(specs, 4, region)
    rings = np.zeros((4, len(specs) * region, RING_ROW), np.int32)

    def drive(tbl, rg, polls, start):
        tctl = tbl.pump(rg)
        for r in range(start, start + polls):
            for d in range(tbl.ndev):
                wrr_poll_reference(rg[d], tctl[d], region, r, 1 << 20)
        tbl.absorb(tctl)

    def raw_latencies(tbl):
        out = {tid: [] for tid in weights}
        for i, tid in enumerate(weights):
            for t in tbl.tables:
                out[tid].extend(t._lanes[i].latencies)
        return out

    t0 = time.perf_counter()
    total = 0
    for tid in weights:
        for _ in range(per_tenant):
            assert table.submit(tid, 0, args=[1])
            total += 1
    rnd = 0
    drive(table, rings, 4, rnd)
    rnd += 4
    lat_pre = raw_latencies(table)
    done_pre = {t: s["completed"] for t, s in table.stats().items()}
    t_cut = time.perf_counter()
    table, _ = table.reshard(rings, 2)
    resize_s = time.perf_counter() - t_cut
    rings = np.zeros((2, len(specs) * region, RING_ROW), np.int32)
    for r in range(1024):
        drive(table, rings, 2, rnd)
        rnd += 2
        if table.drained():
            break
    wall = time.perf_counter() - t0
    assert table.drained(), "mesh tenant bench wedged"
    snap = table.stats()
    assert sum(s["completed"] for s in snap.values()) == total
    lat_post = raw_latencies(table)
    detail = {}
    for tid in weights:
        xs = sorted(lat_pre[tid] + lat_post[tid])
        pct = (lambda p, xs=xs:
               xs[min(len(xs) - 1, int(p * len(xs)))] if xs else 0.0)
        detail[tid] = {
            "weight": weights[tid],
            "completed": int(snap[tid]["completed"]),
            "completed_before_cut": int(done_pre[tid]),
            "p50_latency_s": round(pct(0.50), 6),
            "p99_latency_s": round(pct(0.99), 6),
        }
    return {
        "ndev": "4->2",
        "tasks": total,
        "tasks_per_sec": round(total / max(wall, 1e-9), 1),
        "wall_s": round(wall, 4),
        "resize_latency_s": round(resize_s, 6),
        "wrr_rounds": rnd,
        "per_tenant": detail,
    }


def bench_tenants(quick: bool = False) -> None:
    """Multi-tenant ingress cost of record (ISSUE 8 + the ISSUE 13 mesh
    arm): a 3-lane weighted front door (4:2:1) over the interpret-mode
    streaming kernel, plus the same roster spanning a 4-device mesh
    front door across a live reshard cut. The headline JSON - aggregate
    admitted tasks/s through the WRR poll, single-device AND mesh -
    prints (and flushes) FIRST, rc=124-proofed like every other
    headline; per-tenant tasks/s and p50/p99 admission-to-complete
    latency go to stderr and perf-logs/<ts>.tenants.json."""
    import jax

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.inject import StreamingMegakernel
    from hclib_tpu.device.megakernel import Megakernel
    from hclib_tpu.device.tenants import TenantSpec

    per_tenant = 40 if quick else 150
    weights = {"gold": 4, "silver": 2, "bronze": 1}

    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    mk = Megakernel(
        kernels=[("bump", bump)], capacity=3 * per_tenant + 64,
        num_values=8, succ_capacity=8, interpret=True,
    )
    sm = StreamingMegakernel(
        mk, ring_capacity=3 * max(per_tenant, 64),
        tenants=[TenantSpec(t, weight=w) for t, w in weights.items()],
    )
    # The mesh arm runs first (host-model, milliseconds) so its
    # aggregate lands in the rc=124-proofed headline line.
    mesh = _bench_tenants_mesh(weights, per_tenant)
    total = 0
    for tid in weights:
        for i in range(per_tenant):
            assert sm.submit(tid, 0, args=[1])
            total += 1
    sm.close()
    b = TaskGraphBuilder()
    b.add(0, args=[0])
    t0 = time.perf_counter()
    iv, info = sm.run_stream(b)
    wall = time.perf_counter() - t0
    assert int(iv[0]) == total
    rate = total / max(wall, 1e-9)
    headline = {
        "bench": "tenant_ingress",
        "backend": jax.default_backend(),
        "tenants": len(weights),
        "tasks": total,
        "tasks_per_sec": round(rate, 1),
        "wall_s": round(wall, 4),
        "mesh_tasks_per_sec": mesh["tasks_per_sec"],
        "mesh_resize_latency_s": mesh["resize_latency_s"],
    }
    print(json.dumps(headline), flush=True)  # headline FIRST, always
    detail = {}
    for tid in weights:
        ten = info["tenants"][tid]
        lat = sm.tenants.latency_stats(tid)
        detail[tid] = {
            "weight": weights[tid],
            "completed": ten["completed"],
            "tasks_per_sec": round(ten["completed"] / max(wall, 1e-9), 1),
            "p50_latency_s": round(lat.get("p50_s", 0.0), 4),
            "p99_latency_s": round(lat.get("p99_s", 0.0), 4),
        }
        log(f"tenant [{tid}] w={weights[tid]}: "
            f"{detail[tid]['completed']} tasks "
            f"({detail[tid]['tasks_per_sec']:,} tasks/s), "
            f"admission-to-complete p50 "
            f"{detail[tid]['p50_latency_s'] * 1e3:.1f} ms / p99 "
            f"{detail[tid]['p99_latency_s'] * 1e3:.1f} ms")
    for tid, row in mesh["per_tenant"].items():
        log(f"mesh tenant [{tid}] w={row['weight']}: "
            f"{row['completed']} tasks across the 4->2 cut "
            f"({row['completed_before_cut']} pre-cut), "
            f"admission-to-complete p50 "
            f"{row['p50_latency_s'] * 1e3:.2f} ms / p99 "
            f"{row['p99_latency_s'] * 1e3:.2f} ms")
    log(f"mesh arm: {mesh['tasks']} tasks at "
        f"{mesh['tasks_per_sec']:,} tasks/s across a 4->2 reshard "
        f"({mesh['resize_latency_s'] * 1e3:.2f} ms cut)")
    logdir = os.path.join(os.path.dirname(__file__), "perf-logs")
    os.makedirs(logdir, exist_ok=True)
    path = os.path.join(logdir, f"{int(time.time())}.tenants.json")
    with open(path, "w") as f:
        json.dump({**headline, "per_tenant": detail, "mesh": mesh},
                  f, indent=1)
    log(f"tenant ingress bench written: {path}")


def _bench_serve_stream(per_tenant: int) -> dict:
    """The DEVICE arm of the serving bench: 3 lanes through the real
    interpret-mode streaming kernel with the completion mailbox ON -
    every request rides submit() -> egress mailbox -> Future.result(),
    so the rate prices the whole request/response loop (admission, WRR
    install, in-kernel retirement publish, host drain, ledger resolve),
    not just ingress. The telemetry plane (ISSUE 19) rides the same
    run: the on-device histogram's p50/p99 (rounds -> seconds via the
    entry epoch bracket) report beside the host-stamped quantiles - the
    agreement the acceptance holds to one log2 bucket."""
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.egress import EgressSpec
    from hclib_tpu.device.inject import StreamingMegakernel
    from hclib_tpu.device.megakernel import Megakernel
    from hclib_tpu.device.telemetry import TelemetryBlock
    from hclib_tpu.device.tenants import TenantSpec, TenantTable

    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    names = ("gold", "silver", "bronze")
    region = max(64, per_tenant)
    table = TenantTable(
        [TenantSpec(t, weight=w) for t, w in
         zip(names, (4, 2, 1))],
        region, egress=EgressSpec(depth=64),
    )
    mk = Megakernel(
        kernels=[("bump", bump)], capacity=3 * per_tenant + 64,
        num_values=8, succ_capacity=8, interpret=True,
    )
    sm = StreamingMegakernel(mk, ring_capacity=3 * region,
                             tenants=table, telemetry=True)
    futs = []
    t0 = time.perf_counter()
    for tid in names:
        for i in range(per_tenant):
            adm = sm.submit(tid, 0, args=[1])
            assert adm, adm
            futs.append(adm.future)
    sm.close()
    b = TaskGraphBuilder()
    b.add(0, args=[0])
    sm.run_stream(b)
    lats = sorted(f.latency_s() for f in futs)
    wall = time.perf_counter() - t0
    assert all(f.state == "RESULT" for f in futs)
    cons = table.futures.conservation()
    assert cons["ok"] and cons["resolved"] == len(futs), cons
    pct = (lambda p: lats[min(len(lats) - 1, int(p * len(lats)))])
    out = {
        "requests": len(futs),
        "req_per_sec": round(len(futs) / max(wall, 1e-9), 1),
        "wall_s": round(wall, 4),
        "p50_latency_s": round(pct(0.50), 6),
        "p99_latency_s": round(pct(0.99), 6),
    }
    snap = sm.telemetry_snapshot()
    if snap is not None:
        blk = TelemetryBlock(snap["tele"], snap.get("ns_per_round"))
        out["hist_requests"] = blk.total()
        out["hist_rounds"] = snap["rounds"]
        for q, key in ((0.50, "hist_p50"), (0.99, "hist_p99")):
            r = blk.quantile(q)
            if r is not None:
                out[f"{key}_rounds"] = r
            s = blk.quantile_s(q)
            if s is not None:
                out[f"{key}_latency_s"] = round(s, 6)
    return out


def bench_serve(quick: bool = False) -> None:
    """Request/response serving loop cost of record (ISSUE 16): a
    3-tenant weighted roster (4:2:1) submitting through the futures
    face of a 4-device mesh front door with per-device completion
    mailboxes (WRR reference model + HostMailbox - the executable spec
    of the in-kernel poll/publish), riding ONE live reshard cut 4 -> 2
    with futures in flight (preempt -> reattach on the shared ledger).
    The headline JSON - aggregate requests/s plus p50/p99
    submit-to-result latency ACROSS the scale event - prints (and
    flushes) FIRST, rc=124-proofed like every other headline; the
    device arm (real interpret-mode stream with the mailbox on) and
    per-tenant lines go to stderr budget-gated.

    perf-logs/<ts>.serve.json schema::

        {"bench": "serve", "backend": str, "tenants": 3,
         "requests": int,            # total accepted submits
         "req_per_sec": float,       # aggregate, across the cut
         "wall_s": float,
         "p50_latency_s": float,     # submit-to-RESULT, ACROSS the cut
         "p99_latency_s": float,     #   (reattached futures keep their
         "resize_latency_s": float,  #    original submit timestamp)
         "reattached": int,          # futures that rode the cut
         "ndev": "4->2",
         "per_tenant": {tenant: {"weight": int, "requests": int,
                                 "p50_latency_s": float,
                                 "p99_latency_s": float}},
         "conservation": {...},      # FutureTable.conservation()
         "stream": {...} | null}     # device arm (same latency keys)
    """
    import jax

    from hclib_tpu.device.descriptor import RING_ROW, TEN_TOKEN
    from hclib_tpu.device.egress import EgressSpec, HostMailbox
    from hclib_tpu.device.tenants import (
        MeshTenantTable, TenantSpec, wrr_poll_reference,
    )

    per_tenant = 40 if quick else 200
    weights = {"gold": 4, "silver": 2, "bronze": 1}
    region = -(-per_tenant // (2 * 8)) * 8 + 16
    spec = EgressSpec(depth=32)
    specs = [TenantSpec(t, weight=w, queue_capacity=4 * per_tenant)
             for t, w in weights.items()]
    table = MeshTenantTable(specs, 4, region, egress=spec)
    futures = table.futures
    rings = np.zeros((4, len(specs) * region, RING_ROW), np.int32)
    # Client view: token -> (tenant, submit time, latest Future). The
    # submit stamp is OURS so a reattached future's latency still spans
    # the cut (the ledger restamps t_submit at reattach).
    client = {}

    def drive(tbl, rg, polls, start):
        boxes = [HostMailbox(spec, park_cap=len(specs) * region)
                 for _ in range(tbl.ndev)]
        tctl = tbl.pump(rg)
        for r in range(start, start + polls):
            for d in range(tbl.ndev):
                rows = wrr_poll_reference(
                    rg[d], tctl[d], region, r, 1 << 20
                )
                boxes[d].publish([
                    (int(row[TEN_TOKEN]), 0, 0, 0, 1) for row in rows
                ])
        tbl.absorb(tctl)
        for box in boxes:
            box.drain(futures=futures)

    def submit_half(tbl):
        n = 0
        for tid in weights:
            for _ in range(per_tenant // 2):
                adm = tbl.submit(tid, 0, args=[1])
                assert adm, adm
                client[adm.future.token] = (
                    tid, time.monotonic(), adm.future
                )
                n += 1
        return n

    t0 = time.perf_counter()
    total = submit_half(table)
    rnd = 0
    drive(table, rings, 4, rnd)
    rnd += 4
    # THE scale event: export preempts in-flight futures; the resized
    # mesh shares the SAME ledger, so every resume token reattaches.
    t_cut = time.perf_counter()
    state = table.export_state(rings)
    preempted = [(tok, f.resume_token)
                 for tok, (_, _, f) in client.items()
                 if f.state == "PREEMPTED"]
    table = table.resized(2)
    table.resume_from(state)
    for tok, rt in preempted:
        tid, ts, _ = client[tok]
        client[tok] = (tid, ts, table.reattach(rt))
    resize_s = time.perf_counter() - t_cut
    rings = np.zeros((2, len(specs) * region, RING_ROW), np.int32)
    total += submit_half(table)
    for r in range(1024):
        drive(table, rings, 2, rnd)
        rnd += 2
        if table.drained():
            break
    wall = time.perf_counter() - t0
    assert table.drained(), "serve bench wedged"
    cons = futures.conservation()
    assert cons["ok"] and cons["resolved"] == total, cons
    by_tenant = {t: [] for t in weights}
    for tok, (tid, ts, f) in client.items():
        assert f.state == "RESULT", (tok, f.state)
        by_tenant[tid].append(f.t_done - ts)
    lats = sorted(x for xs in by_tenant.values() for x in xs)
    pct = (lambda p, xs: xs[min(len(xs) - 1, int(p * len(xs)))])
    headline = {
        "bench": "serve",
        "backend": jax.default_backend(),
        "tenants": len(weights),
        "requests": total,
        "req_per_sec": round(total / max(wall, 1e-9), 1),
        "wall_s": round(wall, 4),
        "p50_latency_s": round(pct(0.50, lats), 6),
        "p99_latency_s": round(pct(0.99, lats), 6),
        "resize_latency_s": round(resize_s, 6),
        "reattached": len(preempted),
        "ndev": "4->2",
    }
    print(json.dumps(headline), flush=True)  # headline FIRST, always
    detail = {}
    for tid, xs in by_tenant.items():
        xs.sort()
        detail[tid] = {
            "weight": weights[tid],
            "requests": len(xs),
            "p50_latency_s": round(pct(0.50, xs), 6),
            "p99_latency_s": round(pct(0.99, xs), 6),
        }
        log(f"serve tenant [{tid}] w={weights[tid]}: {len(xs)} "
            f"requests across the 4->2 cut, submit-to-result p50 "
            f"{detail[tid]['p50_latency_s'] * 1e3:.2f} ms / p99 "
            f"{detail[tid]['p99_latency_s'] * 1e3:.2f} ms")
    log(f"serve mesh arm: {total} requests at "
        f"{headline['req_per_sec']:,} req/s across a 4->2 reshard "
        f"({resize_s * 1e3:.2f} ms cut, {len(preempted)} futures "
        f"reattached)")
    stream = section(
        "serve device arm", 120,
        lambda: _bench_serve_stream(20 if quick else 50),
    )
    if stream:
        log(f"serve device arm (interpret stream, mailbox on): "
            f"{stream['requests']} requests at "
            f"{stream['req_per_sec']:,} req/s, submit-to-result p50 "
            f"{stream['p50_latency_s'] * 1e3:.1f} ms / p99 "
            f"{stream['p99_latency_s'] * 1e3:.1f} ms")
        if "hist_p99_latency_s" in stream:
            log(f"serve device histograms (on-device, "
                f"{stream['hist_rounds']} rounds): p50 "
                f"{stream['hist_p50_latency_s'] * 1e3:.1f} ms / p99 "
                f"{stream['hist_p99_latency_s'] * 1e3:.1f} ms from "
                f"{stream['hist_requests']} tracked retirements")
    logdir = os.path.join(os.path.dirname(__file__), "perf-logs")
    os.makedirs(logdir, exist_ok=True)
    path = os.path.join(logdir, f"{int(time.time())}.serve.json")
    with open(path, "w") as f:
        json.dump({**headline, "per_tenant": detail,
                   "conservation": cons, "stream": stream},
                  f, indent=1)
    log(f"serve bench written: {path}")


def bench_forasync(quick: bool = False) -> None:
    """forasync device tier cost of record (ISSUE 9): the 2D Jacobi-style
    stencil and the map-style batched-apply loop through the tile tier
    (batch lanes + double-buffered operand prefetch). The headline JSON -
    combined tiles/s across both loops - prints (and flushes) FIRST,
    rc=124-proofed like every other headline; per-tile-size occupancy /
    prefetch lines go to stderr budget-gated, and the full detail lands
    in perf-logs/<ts>.forasync.json."""
    import jax
    import numpy as np

    from hclib_tpu.device.forasync_tier import run_forasync_device
    from hclib_tpu.device.workloads import (
        map_data, map_loop, map_reference, stencil_data, stencil_loop,
        stencil_reference,
    )

    H, W = (16, 512) if quick else (64, 1024)
    T = 16 if quick else 64
    tk_s, bounds_s, tile_s = stencil_loop(H, W)
    gin, gout = stencil_data(H, W)
    ref_s = stencil_reference(gin)
    tk_m, bounds_m, tile_m = map_loop(T)
    vin, vout = map_data(T)
    ref_m = map_reference(vin)

    def arm(tk, bounds, tile, data, ref, out_name, width):
        from hclib_tpu.device.forasync_tier import make_forasync_megakernel

        # One megakernel reused across warm + timed runs: the timed arm
        # measures the steady-state tile rate, not the XLA compile.
        mk = make_forasync_megakernel(tk, width=width, interpret=True)
        d, info = run_forasync_device(
            tk, bounds, tile, dict(data), width=width, mk=mk
        )  # warm the jit
        t0 = time.perf_counter()
        d, info = run_forasync_device(
            tk, bounds, tile, dict(data), width=width, mk=mk
        )
        wall = time.perf_counter() - t0
        assert np.array_equal(np.asarray(d[out_name]), ref), "wrong result"
        return info, wall

    info_s, wall_s = arm(
        tk_s, bounds_s, tile_s, {"gin": gin, "gout": gout}, ref_s,
        "gout", 8,
    )
    info_m, wall_m = arm(
        tk_m, bounds_m, tile_m, {"vin": vin, "vout": vout}, ref_m,
        "vout", 8,
    )
    tiles_s = info_s["executed"]
    tiles_m = info_m["executed"]
    rate_s = tiles_s / max(wall_s, 1e-9)
    rate_m = tiles_m / max(wall_m, 1e-9)
    headline = {
        "bench": "forasync_tile_tier",
        "backend": jax.default_backend(),
        "tasks": tiles_s + tiles_m,
        "tasks_per_sec": round(
            (tiles_s + tiles_m) / max(wall_s + wall_m, 1e-9), 1
        ),
        "stencil_tasks_per_sec": round(rate_s, 1),
        "map_tasks_per_sec": round(rate_m, 1),
        "stencil_occupancy": round(
            info_s["tiers"]["batch_occupancy"], 3
        ),
        "map_occupancy": round(info_m["tiers"]["batch_occupancy"], 3),
    }
    print(json.dumps(headline), flush=True)  # headline FIRST, always
    log(f"forasync stencil: {tiles_s} tiles ({H}x{W}/8x128) at "
        f"{rate_s:,.0f} tiles/s, occupancy "
        f"{info_s['tiers']['batch_occupancy']:.2f}, "
        f"{info_s['tiers']['prefetch_hits']} prefetch hits")
    log(f"forasync map: {tiles_m} tiles at {rate_m:,.0f} tiles/s, "
        f"occupancy {info_m['tiers']['batch_occupancy']:.2f}, "
        f"{info_m['tiers']['prefetch_hits']} prefetch hits")

    # Per-tile-size sweep (stderr, budget-gated): occupancy + prefetch
    # behavior as the batch width changes - the knob a workload tunes.
    detail = {"widths": {}}

    def sweep():
        for width in (2, 4, 8):
            d, info = run_forasync_device(
                tk_m, bounds_m, tile_m, {"vin": vin, "vout": vout.copy()},
                width=width,
            )
            t = info["tiers"]
            detail["widths"][width] = {
                "occupancy": round(t["batch_occupancy"], 3),
                "batch_rounds": t["batch_rounds"],
                "prefetch_hits": t["prefetch_hits"],
            }
            log(f"forasync width={width}: occupancy "
                f"{t['batch_occupancy']:.2f}, {t['batch_rounds']} rounds, "
                f"{t['prefetch_hits']} prefetch hits")

    section("forasync width sweep", 60, sweep)
    logdir = os.path.join(os.path.dirname(__file__), "perf-logs")
    os.makedirs(logdir, exist_ok=True)
    path = os.path.join(logdir, f"{int(time.time())}.forasync.json")
    with open(path, "w") as f:
        json.dump({**headline, **detail}, f, indent=1)
    log(f"forasync bench written: {path}")


def bench_graph(quick: bool = False) -> None:
    """Graph-analytics frontier tier cost of record (ISSUE 10): BFS,
    delta-stepping-style SSSP, and push PageRank over a seeded
    R-MAT-style graph through the batch-lane frontier tier (edge-slab
    prefetch + the age-triggered firing policy). The headline JSON -
    combined traversed-edges/s (TEPS) - prints (and flushes) FIRST,
    rc=124-proofed like every other headline; per-kernel TEPS /
    occupancy / lane_partial_age lines go to stderr budget-gated, and
    the full detail lands in perf-logs/<ts>.graph.json.

    perf-logs schema (<ts>.graph.json): the headline fields (metric/
    value/unit, per-kernel ``*_teps``, ``sssp_delta_teps`` +
    ``sssp_delta_expand_ratio`` - the ISSUE 15 ordered-work dividend,
    executed EXPANDs of the bucketed arm over the unordered arm's)
    merged with ``kernels.<kind>`` rows: edges / relaxations / tasks /
    elapsed_s / occupancy / age_fires / max_starved_age /
    bucket_fires / bucket_inversions (the last two zero on unbucketed
    arms), plus ``traced_bfs`` gauges."""
    import jax
    import numpy as np

    from hclib_tpu.device.frontier import (
        Graph, host_bfs, host_pagerank_push, host_sssp,
        make_frontier_megakernel, run_frontier, _KINDS,
    )
    from hclib_tpu.device.workloads import rmat_edges

    scale = 6 if quick else 9
    n, src, dst, w = rmat_edges(scale, efactor=8, seed=7)
    g = Graph(n, src, dst, w)
    width = 8
    # PageRank mass/threshold sized so the push's FIFO-lane breadth (the
    # live descriptor set is the mass frontier, not a DFS spine) fits
    # the table; interpret-mode capacity may exceed the ~800-row SMEM
    # guidance real hardware wants.
    m0, reps = 1 << 12, 64
    capacity = 1024 if quick else 4096

    def arm(kind):
        fk = _KINDS[kind](reps=reps) if kind == "pagerank" else _KINDS[kind]()
        mk = make_frontier_megakernel(
            fk, g, width=width, capacity=capacity, interpret=True,
        )
        kw = dict(m0=m0, reps=reps, capacity=capacity, interpret=True, mk=mk)
        res, info = run_frontier(kind, g, 0, **kw)  # warm the jit
        t0 = time.perf_counter()
        res, info = run_frontier(kind, g, 0, **kw)
        wall = time.perf_counter() - t0
        ref = {
            "bfs": lambda: host_bfs(g, 0),
            "sssp": lambda: host_sssp(g, 0),
            "pagerank": lambda: host_pagerank_push(g, m0=m0, reps=reps)[0],
        }[kind]()
        assert np.array_equal(np.asarray(res, np.int64), ref), (
            f"{kind}: device result diverged from the host reference"
        )
        return info, wall

    arms = {}
    edges_total = 0.0
    wall_total = 0.0
    for kind in ("bfs", "sssp", "pagerank"):
        info, wall = arm(kind)
        arms[kind] = (info, wall)
        edges_total += info["edges"]
        wall_total += wall

    # Delta-stepping arm (ISSUE 15): the SAME seeded SSSP through the
    # priority-bucket tier - the headline addition is the executed-
    # EXPAND ratio vs the unordered arm just measured (ordered
    # retirement = asymptotically less work; distances asserted
    # bit-identical) plus its own TEPS.
    def delta_arm():
        fk = _KINDS["sssp"]()
        mk = make_frontier_megakernel(
            fk, g, width=width, capacity=capacity, interpret=True,
            priority_buckets=8,
        )
        kw = dict(capacity=capacity, interpret=True, mk=mk)
        run_frontier("sssp", g, 0, **kw)  # warm the jit
        t0 = time.perf_counter()
        res, info = run_frontier("sssp", g, 0, **kw)
        wall = time.perf_counter() - t0
        assert np.array_equal(np.asarray(res), host_sssp(g, 0)), (
            "sssp-delta: bucketed distances diverged from Dijkstra"
        )
        return info, wall

    dinfo, dwall = delta_arm()
    expand_ratio = dinfo["executed"] / max(arms["sssp"][0]["executed"], 1)
    headline = {
        "metric": f"graph frontier traversal throughput (BFS+SSSP+"
        f"PageRank, R-MAT scale {scale}, {g.m} edges, batched "
        f"frontier width {width})",
        "value": round(edges_total / max(wall_total, 1e-9)),
        "unit": "TEPS",
        "bfs_teps": round(arms["bfs"][0]["edges"] / max(arms["bfs"][1], 1e-9)),
        "sssp_teps": round(
            arms["sssp"][0]["edges"] / max(arms["sssp"][1], 1e-9)
        ),
        "pagerank_teps": round(
            arms["pagerank"][0]["edges"] / max(arms["pagerank"][1], 1e-9)
        ),
        # Priority tier (delta-stepping SSSP, priority_buckets=8):
        # the work-count dividend is the schedule-proof number
        # (interpret walls are weather; the EXPAND ratio is exact).
        "sssp_delta_teps": round(dinfo["edges"] / max(dwall, 1e-9)),
        "sssp_delta_expand_ratio": round(expand_ratio, 4),
        "backend": jax.default_backend(),
    }
    print(json.dumps(headline), flush=True)  # headline FIRST, always
    detail = {"kernels": {}}
    arms["sssp_delta"] = (dinfo, dwall)
    for kind, (info, wall) in arms.items():
        t = info.get("tiers", {})
        detail["kernels"][kind] = {
            "edges": info["edges"],
            "relaxations": info["relaxations"],
            "tasks": info["executed"],
            "elapsed_s": wall,
            "occupancy": round(t.get("batch_occupancy", 0.0), 3),
            "age_fires": t.get("age_fires", 0),
            "max_starved_age": t.get("max_starved_age", 0),
            # Priority-tier counters (zeros on unbucketed arms).
            "bucket_fires": t.get("bucket_fires", 0),
            "bucket_inversions": t.get("bucket_inversions", 0),
        }
        log(f"graph {kind}: {info['edges']} edges in {wall:.3f}s "
            f"({info['edges'] / max(wall, 1e-9):,.0f} TEPS), occupancy "
            f"{t.get('batch_occupancy', 0.0):.2f}, {t.get('age_fires', 0)} "
            f"age fires (max starved age {t.get('max_starved_age', 0)})")
    log(f"graph sssp-delta: {dinfo['executed']} EXPANDs vs "
        f"{arms['sssp'][0]['executed']} unordered "
        f"({expand_ratio:.2f}x), {dinfo['edges']} edges in {dwall:.3f}s")

    # Traced BFS round (stderr, budget-gated): the lane_partial_age
    # gauge - bounded by the age-triggered firing policy - plus per-lane
    # occupancy off the flight recorder.
    def traced():
        _, info = run_frontier(
            "bfs", g, 0, width=width, capacity=capacity, interpret=True,
            trace=4096,
        )
        t = info["tiers"]
        detail["traced_bfs"] = {
            "lane_partial_age": t.get("lane_partial_age", 0),
            "age_fires": t.get("age_fires", 0),
            "max_starved_age": t.get("max_starved_age", 0),
            "occupancy": round(t.get("batch_occupancy", 0.0), 3),
        }
        log(f"graph traced bfs: lane_partial_age "
            f"{t.get('lane_partial_age', 0)}, max starved age "
            f"{t.get('max_starved_age', 0)} (bounded by the "
            "age-triggered firing policy)")

    section("graph traced round", 90, traced)
    logdir = os.path.join(os.path.dirname(__file__), "perf-logs")
    os.makedirs(logdir, exist_ok=True)
    path = os.path.join(logdir, f"{int(time.time())}.graph.json")
    with open(path, "w") as f:
        json.dump({**headline, **detail}, f, indent=1)
    log(f"graph bench written: {path}")


def bench_dyngraph(quick: bool = False) -> None:
    """Dynamic-graph service cost of record (ISSUE 20): a concurrent
    UPDATE storm + QUERY stream against the mutable blocked-CSR
    adjacency, raced with the BFS/SSSP traversals on the batched
    frontier tier - the incremental fixpoint asserted bit-identical to
    the from-scratch host reference ON THE MUTATED GRAPH. The headline
    JSON - updates applied per second, with the concurrent traversal's
    query TEPS riding along - prints (and flushes) FIRST, rc=124-proofed
    like every other headline; per-kind splice/query lines go to stderr
    budget-gated and the full detail lands in
    perf-logs/<ts>.dyngraph.json.

    perf-logs schema (<ts>.dyngraph.json): the headline fields (metric/
    value/unit, ``updates_per_sec`` / ``query_teps`` /
    ``queries_per_sec``) merged with ``kernels.<kind>`` rows: edges /
    relaxations / tasks / updates_applied / dropped / spare_in_use /
    queries / elapsed_s."""
    import jax
    import numpy as np

    from hclib_tpu.device.dyngraph import (
        DynGraph, host_dyngraph, make_dyngraph_megakernel, run_dyngraph,
    )
    from hclib_tpu.device.workloads import rmat_edges

    scale = 5 if quick else 7
    n, src_e, dst_e, w_e = rmat_edges(scale, efactor=8, seed=7)
    width = 8
    capacity = 512 if quick else 1024
    rng = np.random.default_rng(11)
    n_ups = 8 if quick else 24
    ups = [
        (int(u), int(v), int(w))
        for u, v, w in zip(
            rng.integers(0, n, n_ups),
            rng.integers(0, n, n_ups),
            rng.integers(1, 8, n_ups),
        )
    ]
    queries = [int(q) for q in rng.integers(0, n, 4)]

    def arm(kind):
        # Fresh graph per arm: the update stream registers on it and
        # the spare rows mutate in-run.
        g = DynGraph(
            n, src_e, dst_e, w_e, spare_blocks=2,
            upd_cap=max(16, n_ups),
        )
        mk = make_dyngraph_megakernel(
            kind, g, width=width, capacity=capacity, interpret=True,
        )
        kw = dict(
            updates=ups, queries=queries, capacity=capacity,
            interpret=True, mk=mk,
        )
        run_dyngraph(kind, g, 0, **kw)  # warm the jit (mutates nothing
        g = DynGraph(                   # host-side; rebuild regardless)
            n, src_e, dst_e, w_e, spare_blocks=2,
            upd_cap=max(16, n_ups),
        )
        t0 = time.perf_counter()
        res, info = run_dyngraph(kind, g, 0, **dict(kw, mk=mk))
        wall = time.perf_counter() - t0
        assert np.array_equal(
            np.asarray(res, np.int64),
            np.asarray(host_dyngraph(kind, g), np.int64),
        ), f"{kind}: incremental fixpoint diverged from the mutated-graph"
        return info, wall

    arms = {}
    ups_total = edges_total = wall_total = 0.0
    q_total = 0
    for kind in ("bfs", "sssp"):
        info, wall = arm(kind)
        arms[kind] = (info, wall)
        ups_total += info["updates_applied"]
        edges_total += info["edges"]
        q_total += info["queries"]
        wall_total += wall

    headline = {
        "metric": f"dynamic-graph update+query service throughput "
        f"(BFS+SSSP, R-MAT scale {scale}, {len(src_e)} static edges, "
        f"{n_ups} updates, {len(queries)} queries, batched width "
        f"{width})",
        "value": round(ups_total / max(wall_total, 1e-9)),
        "unit": "updates/sec",
        "updates_per_sec": round(ups_total / max(wall_total, 1e-9)),
        "query_teps": round(edges_total / max(wall_total, 1e-9)),
        "queries_per_sec": round(q_total / max(wall_total, 1e-9)),
        "backend": jax.default_backend(),
    }
    print(json.dumps(headline), flush=True)  # headline FIRST, always
    detail = {"kernels": {}}
    for kind, (info, wall) in arms.items():
        detail["kernels"][kind] = {
            "edges": info["edges"],
            "relaxations": info["relaxations"],
            "tasks": info["executed"],
            "updates_applied": info["updates_applied"],
            "dropped": info["dropped"],
            "spare_in_use": info["spare_in_use"],
            "queries": info["queries"],
            "elapsed_s": wall,
        }
        log(f"dyngraph {kind}: {info['updates_applied']} splices "
            f"({info['dropped']} dropped, {info['spare_in_use']} spare "
            f"blocks), {info['queries']} queries, {info['edges']} edges "
            f"in {wall:.3f}s, bit-identical to the mutated-graph "
            "reference")

    logdir = os.path.join(os.path.dirname(__file__), "perf-logs")
    os.makedirs(logdir, exist_ok=True)
    path = os.path.join(logdir, f"{int(time.time())}.dyngraph.json")
    with open(path, "w") as f:
        json.dump({**headline, **detail}, f, indent=1)
    log(f"dyngraph bench written: {path}")


def bench_bnb(quick: bool = False) -> None:
    """Branch-and-bound cost of record (ISSUE 15): best-first 0/1
    knapsack on the priority-bucket tier vs the unordered batched arm,
    same seeded instance, optimum asserted equal to the independent
    host DP in both. The headline JSON - best-first expanded nodes/s
    plus the expanded-node ratio (priority IS the speedup here) -
    prints (and flushes) FIRST, rc=124-proofed like every other
    headline; per-arm node/prune lines go to stderr budget-gated and
    the full detail lands in perf-logs/<ts>.bnb.json.

    perf-logs schema (<ts>.bnb.json): the headline fields (metric/
    value/unit, ``expand_ratio`` = best-first executed nodes over
    unordered, ``optimum``) merged with ``arms.<name>`` rows:
    executed / pruned / leaves / elapsed_s / occupancy /
    bucket_fires / bucket_inversions."""
    import jax

    from hclib_tpu.device.bnb import (
        host_knapsack_opt, make_bnb_megakernel, make_knapsack, run_bnb,
    )

    n_items = 12 if quick else 16
    kp = make_knapsack(n_items, seed=5)
    opt = host_knapsack_opt(kp)
    width = 4
    arms = {}
    for name, buckets in (("unordered", 0), ("best_first", 8)):
        mk = make_bnb_megakernel(
            kp, width=width, priority_buckets=buckets, interpret=True,
            capacity=2048,
        )
        run_bnb(kp, mk=mk, interpret=True)  # warm the jit
        t0 = time.perf_counter()
        best, info = run_bnb(kp, mk=mk, interpret=True)
        wall = time.perf_counter() - t0
        assert best == opt, (
            f"bnb {name}: incumbent {best} != DP optimum {opt}"
        )
        arms[name] = (info, wall)
    bi, bw = arms["best_first"]
    ui, _uw = arms["unordered"]
    ratio = bi["executed"] / max(ui["executed"], 1)
    headline = {
        "metric": f"branch-and-bound best-first search ({n_items}-item "
        f"knapsack, priority buckets over the batch lanes)",
        "value": round(bi["executed"] / max(bw, 1e-9)),
        "unit": "nodes/sec",
        "optimum": opt,
        "expand_ratio": round(ratio, 4),
        "pruned_best_first": bi["pruned"],
        "pruned_unordered": ui["pruned"],
        "backend": jax.default_backend(),
    }
    print(json.dumps(headline), flush=True)  # headline FIRST, always
    detail = {"arms": {}}
    for name, (info, wall) in arms.items():
        t = info.get("tiers", {})
        detail["arms"][name] = {
            "executed": info["executed"],
            "pruned": info["pruned"],
            "leaves": info["leaves"],
            "elapsed_s": wall,
            "occupancy": round(t.get("batch_occupancy", 0.0), 3),
            "bucket_fires": t.get("bucket_fires", 0),
            "bucket_inversions": t.get("bucket_inversions", 0),
        }
        log(f"bnb {name}: {info['executed']} nodes ({info['pruned']} "
            f"pruned, {info['leaves']} leaves) in {wall:.3f}s")
    log(f"bnb best-first expanded {ratio:.2f}x the unordered node "
        f"count (optimum {opt} proven by both)")
    logdir = os.path.join(os.path.dirname(__file__), "perf-logs")
    os.makedirs(logdir, exist_ok=True)
    path = os.path.join(logdir, f"{int(time.time())}.bnb.json")
    with open(path, "w") as f:
        json.dump({**headline, **detail}, f, indent=1)
    log(f"bnb bench written: {path}")


def bench_multichip(quick: bool = False) -> None:
    """8-device forest-steal through the sharded steal runner, BATCHED
    arm first (ISSUE 7): the batched tasks/s headline JSON prints (and
    flushes) before anything else can eat the driver budget - the same
    rc=124-proofing the single-device path got in PR 3 - then per-device
    occupancy/prefetch lines and the scalar-mesh comparison go to stderr,
    budget-gated."""
    from hclib_tpu.device import stress

    kw = stress.FOREST_STEAL_QUICK if quick else stress.FOREST_STEAL_BENCH
    try:
        binfo = stress.forest_steal(batch_width=8, **kw)
        print(
            json.dumps(
                {
                    "metric": f"forest-steal mesh throughput (batched "
                    f"dispatch, {kw['ndev']} devices, "
                    f"{kw['roots']}x fib({kw['n']}))",
                    "value": round(binfo["tasks_per_sec"]),
                    "unit": "tasks/sec",
                    "tasks": binfo["tasks"],
                    "mean_occupancy": round(binfo["mean_occupancy"], 3),
                    "devices_used": binfo["devices_used"],
                }
            ),
            flush=True,
        )
    except Exception as e:
        log(f"multichip batched bench failed: {e}")
        print(
            json.dumps(
                {
                    "metric": "multichip bench headline unavailable "
                    f"({str(e)[:160]})",
                    "value": 0,
                    "unit": "none",
                }
            ),
            flush=True,
        )
        return
    for d, t in enumerate(binfo["tiers"]):
        log(
            f"device {d}: occupancy {t['batch_occupancy']:.2f} "
            f"({t['batch_rounds']} batch rounds, {t['batch_tasks']} "
            f"batched + {t['scalar_tasks']} scalar tasks, "
            f"{t['prefetch_hits']} prefetch hits, {t['spilled']} lane "
            f"spills)"
        )
    out = {"batched": {k: v for k, v in binfo.items() if k != "trace"}}
    sinfo = section(
        "scalar-mesh baseline", 180,
        lambda: stress.forest_steal(**kw),
    )
    if sinfo:
        mult = binfo["tasks_per_sec"] / sinfo["tasks_per_sec"]
        log(
            f"mesh batch dispatch vs scalar mesh: {mult:.2f}x "
            f"({binfo['tasks_per_sec']:,.0f} vs "
            f"{sinfo['tasks_per_sec']:,.0f} tasks/s; interpret-mode "
            "wall time is weather/ordering-prone - the guard of record "
            "is tools/perf_regression.py --multichip, which runs the "
            "scalar arm first)"
        )
        out["scalar"] = dict(sinfo)
        out["batch_vs_scalar"] = mult
    os.makedirs("perf-logs", exist_ok=True)
    path = os.path.join("perf-logs", f"{int(time.time())}.multichip.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1, default=float)
    log(f"multichip log written: {path}")


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description="hclib_tpu benchmark driver")
    ap.add_argument(
        "--trace", action="store_true",
        help="also emit per-section metrics JSON + a Perfetto trace "
        "under perf-logs/ (budget-gated like the other sections)",
    )
    ap.add_argument(
        "--checkpoint", action="store_true",
        help="also measure checkpoint/restore cost (quiesce latency + "
        "bundle size for UTS and Cholesky) plus the durable-store arms "
        "(save-publish latency fsync'd/fast, cold load_latest clean and "
        "healing past 2 quarantined generations) into perf-logs/ "
        "(budget-gated like the other sections)",
    )
    ap.add_argument(
        "--autoscale", action="store_true",
        help="also measure elastic-autoscaling cost (resize latency + "
        "tasks/s through a scale event) into perf-logs/ "
        "(budget-gated like the other sections)",
    )
    ap.add_argument(
        "--tenants", action="store_true",
        help="multi-tenant ingress mode: 3 weighted lanes through the "
        "streaming front door; the aggregate tasks/s headline prints "
        "FIRST (stdout JSON), per-tenant rates + p50/p99 admission-to-"
        "complete latency to stderr and perf-logs/<ts>.tenants.json; "
        "replaces the single-device suite for this run",
    )
    ap.add_argument(
        "--serve", action="store_true",
        help="request/response serving mode: 3 weighted tenants "
        "submitting through the futures face of a 4-device mesh front "
        "door with completion mailboxes, across ONE live 4->2 reshard "
        "with futures reattached; the req/s + p50/p99 submit-to-result "
        "latency headline prints FIRST (stdout JSON), the device arm "
        "and per-tenant lines to stderr and perf-logs/<ts>.serve.json; "
        "replaces the single-device suite for this run",
    )
    ap.add_argument(
        "--forasync", action="store_true",
        help="forasync device-tier mode: stencil + map-loop tiles/s "
        "through the batch-lane tile tier; the combined tasks/s headline "
        "prints FIRST (stdout JSON), per-tile-size occupancy/prefetch "
        "lines to stderr and perf-logs/<ts>.forasync.json; replaces the "
        "single-device suite for this run",
    )
    ap.add_argument(
        "--graph", nargs="?", const="static", default=None,
        metavar="ARM",
        help="graph-analytics mode: BFS/SSSP/PageRank traversed-edges/s "
        "(TEPS) through the batched frontier tier on a seeded R-MAT "
        "graph; the combined TEPS headline prints FIRST (stdout JSON), "
        "per-kernel TEPS/occupancy/lane_partial_age to stderr and "
        "perf-logs/<ts>.graph.json; replaces the single-device suite "
        "for this run. '--graph dyngraph' runs the dynamic-graph arm "
        "instead: a concurrent update storm + queries against the "
        "mutable adjacency, updates/s + query TEPS headline, detail to "
        "perf-logs/<ts>.dyngraph.json",
    )
    ap.add_argument(
        "--bnb", action="store_true",
        help="branch-and-bound mode: best-first knapsack search on the "
        "priority-bucket tier; the expanded-nodes/s headline (plus the "
        "expanded-node ratio vs the unordered arm) prints FIRST "
        "(stdout JSON), per-arm node/prune lines to stderr and "
        "perf-logs/<ts>.bnb.json; replaces the single-device suite for "
        "this run",
    )
    ap.add_argument(
        "--multichip", action="store_true",
        help="8-device mesh mode: the batched forest-steal tasks/s "
        "headline prints FIRST (stdout JSON), then per-device "
        "occupancy/prefetch lines and the scalar-mesh comparison "
        "(stderr); replaces the single-device suite for this run",
    )
    ap.add_argument(
        "--quick", action="store_true",
        help="tiny inputs (CI smoke; affects --multichip and --tenants)",
    )
    args = ap.parse_args(argv)
    global _T0
    _T0 = time.monotonic()  # arm the wall budget for THIS driver run
    if args.tenants:
        bench_tenants(quick=args.quick)
        return
    if args.serve:
        bench_serve(quick=args.quick)
        return
    if args.forasync:
        bench_forasync(quick=args.quick)
        return
    if args.graph == "dyngraph":
        bench_dyngraph(quick=args.quick)
        return
    if args.graph:
        bench_graph(quick=args.quick)
        return
    if args.bnb:
        bench_bnb(quick=args.quick)
        return
    if args.multichip:
        # Must land before jax initializes: the mesh workloads need the
        # CPU backend with 8 virtual devices.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        bench_multichip(quick=args.quick)
        return
    # ---- headline FIRST: the stdout JSON line exists (and is flushed)
    # before any secondary section can eat the driver budget. Every
    # fallback rung is itself guarded: stdout MUST end up with one
    # JSON-parsable line no matter what fails (BENCH_r05 parsed null).
    host_rate = device_fib_rate = None
    try:
        native_uts_rate = bench_native_uts()
        device_uts_rate, tree, uts_stat = bench_device_uts()
        print(
            json.dumps(
                {
                    "metric": f"UTS {tree} tree-search throughput "
                    f"(vectorized DFS, "
                    f"{'1 TPU core' if tree == 'T1L' else 'cpu backend'})",
                    "value": round(device_uts_rate),
                    "unit": "nodes/sec",
                    "vs_baseline": round(
                        device_uts_rate / native_uts_rate, 2
                    ),
                    "statistic": uts_stat,
                }
            ),
            flush=True,
        )
    except Exception as e:
        log(f"uts bench failed: {e}; falling back to fib headline")
        try:
            host_rate = bench_host_fib()
            device_fib_rate = bench_device_fib()
            print(
                json.dumps(
                    {
                        "metric": "megakernel dynamic-task throughput (fib)",
                        "value": round(device_fib_rate),
                        "unit": "tasks/sec",
                        "vs_baseline": round(device_fib_rate / host_rate, 2),
                    }
                ),
                flush=True,
            )
        except Exception as e2:
            log(f"fib fallback failed too: {e2}")
            print(
                json.dumps(
                    {
                        "metric": "bench headline unavailable "
                        f"(uts: {str(e)[:120]}; fib: {str(e2)[:120]})",
                        "value": 0,
                        "unit": "none",
                    }
                ),
                flush=True,
            )

    # ---- secondaries (stderr only), budget-gated, priority order: the
    # dispatch-tier numbers under acceptance tracking come first.
    sw_wave = section("sw wave-DAG", 90, bench_device_sw_wave)
    chol8k = section("cholesky n=8192", 150, bench_device_cholesky)
    if host_rate is None:  # not already measured by the fallback headline
        host_rate = section("host fib", 30, bench_host_fib)
    native_fib_rate = section("native fib", 45, bench_native_fib)
    if device_fib_rate is None:
        device_fib_rate = section(
            "device fib scalar tier", 60, bench_device_fib
        )
    if host_rate and device_fib_rate:
        line = (
            f"fib megakernel (scalar tier) vs python host: "
            f"{device_fib_rate / host_rate:.1f}x"
        )
        if native_fib_rate:
            line += (
                f"; vs native C++: {device_fib_rate / native_fib_rate:.2f}x"
            )
        log(line)
    vfib_rate = section("device fib batch tier", 90, bench_device_vfib)
    if host_rate and vfib_rate:
        line = (
            f"fib megakernel (batch-dispatch tier) vs python host: "
            f"{vfib_rate / host_rate:.0f}x"
        )
        if native_fib_rate:
            line += f"; vs native C++: {vfib_rate / native_fib_rate:.1f}x"
        log(line)
    section("sw pallas (fused ceiling)", 90, bench_device_sw)
    # The peak-utilization size (POTRF/TRSM amortized over 8x the GEMM
    # work); its residual bound reflects f32 accumulation over twice the
    # update steps - reported, not hidden.
    section(
        "cholesky n=16384", 200,
        lambda: bench_device_cholesky(trials=3, n=16384, residual_bound=2e-6),
    )
    if args.trace:
        section("trace artifacts", 60, emit_trace_artifacts)
    if args.checkpoint:
        section("checkpoint/restore", 120, bench_checkpoint)
    if args.autoscale:
        section("elastic autoscale", 120, bench_autoscale)
    if sw_wave:
        log(f"wave-DAG SW final: {sw_wave:.1f} GCUPS median (r05 baseline "
            f"1.2; acceptance floor 12)")
    if chol8k is not None:
        log(f"cholesky n=8192 final: {_chol_ceiling_pct(chol8k):.0f}% "
            f"of the 3-pass ceiling (r05 baseline 80%)")


if __name__ == "__main__":
    main()
