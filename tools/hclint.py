#!/usr/bin/env python
"""hclint: run the build-time program verifier over the repo's builders.

The library half (``hclib_tpu.analysis``) runs automatically at
``Megakernel`` construction when ``verify=True`` / ``HCLIB_TPU_VERIFY``
(default-on under pytest) and RAISES on violations. This CLI is the
audit spelling for CI and humans: it constructs every curated in-repo
program builder (workloads, stress configurations, the kernels the
benches and tutorials build), runs the full analysis suite over each -
word-layout consistency, batch-slot race detection, prefetch-protocol
conformance, tile store-window disjointness over concrete tile spaces,
and the reshard/migratability classification audit - and prints every
finding with its witness. Exit 1 when any unsuppressed error/warn
finding exists (info notes and spec-annotated suppressions don't gate).

Everything is host-only composition: kernels are CONSTRUCTED, never
built or run - no Pallas lowering, no Mosaic, a few seconds total.

Usage: ``python tools/hclint.py [--json] [--verbose]``
CI runs this beside tools/lint.py, before the test suite.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Tuple

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The CLI drives verification EXPLICITLY (collecting findings instead of
# raising at construction), so force the construction-time hook off for
# the builders below no matter what the environment says.
os.environ["HCLIB_TPU_VERIFY"] = "0"


def _programs() -> List[Tuple[str, "callable"]]:
    """(label, thunk) per curated builder; each thunk returns either a
    Megakernel or a finished AnalysisReport."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl

    from hclib_tpu.analysis import (
        AnalysisReport, check_migratable, check_tile_windows,
        verify_megakernel,
    )
    from hclib_tpu.device.cholesky import make_cholesky_megakernel
    from hclib_tpu.device.forasync_tier import Slab, TileKernel, \
        make_forasync_megakernel
    from hclib_tpu.device.frontier import (
        Graph, bfs_kernel, make_frontier_megakernel, pagerank_kernel,
        sssp_kernel,
    )
    from hclib_tpu.device.smithwaterman import (
        make_sw_batched_megakernel, make_sw_megakernel,
        make_sw_wave_megakernel,
    )
    from hclib_tpu.device.workloads import (
        FIB, make_fib_megakernel, make_uts_megakernel,
        make_vfib_megakernel,
    )

    progs: List[Tuple[str, "callable"]] = []
    progs.append(("fib(scalar)", lambda: make_fib_megakernel(
        256, interpret=True)))
    progs.append(("fib(batch=4)", lambda: make_fib_megakernel(
        256, interpret=True, batch_width=4)))
    progs.append(("uts", lambda: make_uts_megakernel(interpret=True)))
    progs.append(("vfib", lambda: make_vfib_megakernel(interpret=True)))
    progs.append(("cholesky(nt=4)", lambda: make_cholesky_megakernel(
        4, interpret=True)))
    progs.append(("sw", lambda: make_sw_megakernel(4, 4, interpret=True)))
    progs.append(("sw-wave", lambda: make_sw_wave_megakernel(
        4, 4, interpret=True)))
    progs.append(("sw-batched", lambda: make_sw_batched_megakernel(
        4, 4, interpret=True, width=4)))

    rng = np.random.default_rng(7)
    n, m = 32, 96
    g = Graph(
        n, rng.integers(0, n, m), rng.integers(0, n, m),
        rng.integers(1, 9, m),
    )
    for kf in (bfs_kernel, sssp_kernel, pagerank_kernel):
        progs.append((
            f"frontier:{kf().name}",
            lambda kf=kf: make_frontier_megakernel(
                kf(), g, width=4, interpret=True
            ),
        ))

    # The forasync tutorial's 2D Jacobi tile loop, with the whole-loop
    # store-window proof over its concrete tile space.
    N, TS = 32, 8

    def jacobi() -> AnalysisReport:
        specs = {
            "grid": jax.ShapeDtypeStruct((N, N), jnp.int32),
            "out": jax.ShapeDtypeStruct((N, N), jnp.int32),
        }
        tk = TileKernel(
            loads=[Slab(
                "win", "grid",
                lambda a: (pl.ds(a[1], TS), pl.ds(a[2], TS)), (TS, TS),
            )],
            stores=[Slab(
                "wout", "out",
                lambda a: (pl.ds(a[1], TS), pl.ds(a[2], TS)), (TS, TS),
            )],
            compute=lambda ins: {"wout": ins["win"] * 2 + 1},
            data_specs=specs,
        )
        mk = make_forasync_megakernel(tk, width=4, interpret=True)
        rep = verify_megakernel(mk, raise_on_error=False)
        check_tile_windows(tk, [N, N], [TS, TS], report=rep)
        return rep

    progs.append(("forasync:jacobi2d", jacobi))

    # The mesh stress configuration's migratability claim (stress.
    # forest_steal: fib on the sharded exchange) - audited, with the
    # workload's own suppression annotation honored.
    def forest_claim() -> AnalysisReport:
        mk = make_fib_megakernel(256, interpret=True, batch_width=4)
        return check_migratable(
            mk, [FIB], "stress.forest_steal",
            suppress=mk.verify_suppress,
        )

    progs.append(("stress:forest_steal", forest_claim))
    return progs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings")
    ap.add_argument("--verbose", action="store_true",
                    help="print clean programs and info findings too")
    args = ap.parse_args(argv)

    from hclib_tpu.analysis import (
        check_layout, classify_megakernel, verify_megakernel,
    )
    from hclib_tpu.analysis.findings import AnalysisReport

    out = {}
    bad = 0

    lay = check_layout(force=True)
    out["layout"] = {"findings": lay.to_jsonable(), "kind_classes": {}}
    bad += len(lay.actionable())

    for label, thunk in _programs():
        try:
            obj = thunk()
        except Exception as e:  # noqa: BLE001 - report, keep auditing
            out[label] = {"findings": [{
                "rule": "builder-error", "severity": "error",
                "kernel": None, "message": f"{type(e).__name__}: {e}",
                "witness": {}, "suppressed": False,
            }], "kind_classes": {}}
            bad += 1
            continue
        if isinstance(obj, AnalysisReport):
            rep = obj
        else:
            rep = verify_megakernel(
                obj, suppress=getattr(obj, "verify_suppress", ()),
                raise_on_error=False,
            )
            rep.kind_classes = classify_megakernel(obj)
        out[label] = {
            "findings": rep.to_jsonable(),
            "kind_classes": dict(rep.kind_classes),
        }
        if rep.kind_classes and not args.json and args.verbose:
            cls = ", ".join(
                f"{k}={v}" for k, v in sorted(rep.kind_classes.items())
            )
            print(f"{label}: {cls}")
        bad += len(rep.actionable())
        for f in rep.findings:
            if args.json:
                continue
            if f.severity == "info" and not args.verbose:
                continue
            print(f"{label}: {f}")

    if args.json:
        print(json.dumps(out, indent=2))
    if bad:
        print(f"hclint: {bad} actionable finding(s)", file=sys.stderr)
        return 1
    if not args.json:
        n = len(out) - 1
        print(f"hclint: {n} program(s) + layout table clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
