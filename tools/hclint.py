#!/usr/bin/env python
"""hclint: run the build-time program verifier + whole-program
concurrency model checker over the repo's builders.

The library half (``hclib_tpu.analysis``) runs automatically at
``Megakernel`` construction when ``verify=True`` / ``HCLIB_TPU_VERIFY``
(default-on under pytest) and RAISES on violations. This CLI is the
audit spelling for CI and humans: it constructs every curated in-repo
program builder (workloads, stress configurations, the kernels the
benches and tutorials build), runs the full analysis suite over each -
word-layout consistency, batch-slot race detection, prefetch-protocol
conformance, tile store-window disjointness over concrete tile spaces,
the reshard/migratability classification audit, and (v2, ISSUE 14) the
whole-program model checker: wait-graph deadlock detection over every
kind's spawn/wait/satisfy ops, bounded-interleaving exploration of the
inject-poll / steal-credit / quiesce protocols (every schedule of a
small seeded configuration, checked for termination, conservation, and
the quiesce freeze - wall-budgeted by ``HCLIB_TPU_MODEL_BUDGET_S`` and
depth-bounded by ``HCLIB_TPU_MODEL_DEPTH``), and schedule-independence
certification for the kernels that claim it (frontier BFS/SSSP/
PageRank, forasync tiles - K permuted pop orders to the fixpoint).
Every finding prints with its concrete witness (the colliding windows,
the wait cycle's kind chain, the interleaving prefix, the two divergent
schedules). Exit 1 when any unsuppressed error/warn finding exists
(info notes and spec-annotated suppressions don't gate).

Everything is host-only composition: kernels are CONSTRUCTED, never
built or run - no Pallas lowering, no Mosaic, a few seconds total.

Usage: ``python tools/hclint.py [--json] [--json-out FILE] [--verbose]
[--no-explore]``; ``--json-out`` writes the machine-readable findings
(rule, kernel, witness, severity per program) for the CI artifact so
regressions diff across PRs. CI runs this beside tools/lint.py, before
the test suite.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Tuple

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The CLI drives verification EXPLICITLY (collecting findings instead of
# raising at construction), so force the construction-time hook off for
# the builders below no matter what the environment says.
os.environ["HCLIB_TPU_VERIFY"] = "0"


def _programs() -> List[Tuple[str, "callable"]]:
    """(label, thunk) per curated builder; each thunk returns either a
    Megakernel or a finished AnalysisReport."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl

    from hclib_tpu.analysis import (
        AnalysisReport, check_migratable, check_tile_windows,
        verify_megakernel,
    )
    from hclib_tpu.device.cholesky import make_cholesky_megakernel
    from hclib_tpu.device.forasync_tier import Slab, TileKernel, \
        make_forasync_megakernel
    from hclib_tpu.device.frontier import (
        Graph, bfs_kernel, make_frontier_megakernel, pagerank_kernel,
        sssp_kernel,
    )
    from hclib_tpu.device.smithwaterman import (
        make_sw_batched_megakernel, make_sw_megakernel,
        make_sw_wave_megakernel,
    )
    from hclib_tpu.device.workloads import (
        FIB, make_fib_megakernel, make_uts_megakernel,
        make_vfib_megakernel,
    )

    progs: List[Tuple[str, "callable"]] = []
    progs.append(("fib(scalar)", lambda: make_fib_megakernel(
        256, interpret=True)))
    progs.append(("fib(batch=4)", lambda: make_fib_megakernel(
        256, interpret=True, batch_width=4)))
    progs.append(("uts", lambda: make_uts_megakernel(interpret=True)))
    progs.append(("vfib", lambda: make_vfib_megakernel(interpret=True)))
    progs.append(("cholesky(nt=4)", lambda: make_cholesky_megakernel(
        4, interpret=True)))
    progs.append(("sw", lambda: make_sw_megakernel(4, 4, interpret=True)))
    progs.append(("sw-wave", lambda: make_sw_wave_megakernel(
        4, 4, interpret=True)))
    progs.append(("sw-batched", lambda: make_sw_batched_megakernel(
        4, 4, interpret=True, width=4)))

    rng = np.random.default_rng(7)
    n, m = 32, 96
    g = Graph(
        n, rng.integers(0, n, m), rng.integers(0, n, m),
        rng.integers(1, 9, m),
    )
    for kf in (bfs_kernel, sssp_kernel, pagerank_kernel):
        progs.append((
            f"frontier:{kf().name}",
            lambda kf=kf: make_frontier_megakernel(
                kf(), g, width=4, interpret=True
            ),
        ))

    # The ISSUE 15 priority-bucketed builders: delta-stepping SSSP and
    # bounded-frontier PageRank (bucket rings over the frontier lane;
    # the 5-tuple si_claim certifies the bucketed pop order), plus the
    # branch-and-bound search (best-first = the speedup; optimum
    # certified order-free).
    for kf in (sssp_kernel, pagerank_kernel):
        progs.append((
            f"priority:{kf().name}",
            lambda kf=kf: make_frontier_megakernel(
                kf(), g, width=4, interpret=True, priority_buckets=4,
            ),
        ))

    def bnb_builder():
        from hclib_tpu.device.bnb import make_bnb_megakernel, make_knapsack

        return make_bnb_megakernel(
            make_knapsack(10, seed=5), width=4, priority_buckets=4,
            interpret=True,
        )

    progs.append(("priority:bnb", bnb_builder))

    # The forasync tutorial's 2D Jacobi tile loop, with the whole-loop
    # store-window proof over its concrete tile space.
    N, TS = 32, 8

    def jacobi() -> AnalysisReport:
        from hclib_tpu.analysis import certify_tile_schedule

        specs = {
            "grid": jax.ShapeDtypeStruct((N, N), jnp.int32),
            "out": jax.ShapeDtypeStruct((N, N), jnp.int32),
        }
        tk = TileKernel(
            loads=[Slab(
                "win", "grid",
                lambda a: (pl.ds(a[1], TS), pl.ds(a[2], TS)), (TS, TS),
            )],
            stores=[Slab(
                "wout", "out",
                lambda a: (pl.ds(a[1], TS), pl.ds(a[2], TS)), (TS, TS),
            )],
            compute=lambda ins: {"wout": ins["win"] * 2 + 1},
            data_specs=specs,
        )
        mk = make_forasync_megakernel(tk, width=4, interpret=True)
        rep = verify_megakernel(mk, raise_on_error=False)
        check_tile_windows(tk, [N, N], [TS, TS], report=rep)
        # The schedule-independence certificate over the concrete tile
        # space (refusals would land in rep as findings).
        rep.certificates = {tk.name: certify_tile_schedule(
            tk, [N, N], [TS, TS], report=rep, raise_on_error=False,
        )}
        return rep

    progs.append(("forasync:jacobi2d", jacobi))

    # The mesh stress configuration's migratability claim (stress.
    # forest_steal: fib on the sharded exchange) - audited, with the
    # workload's own suppression annotation honored.
    def forest_claim() -> AnalysisReport:
        mk = make_fib_megakernel(256, interpret=True, batch_width=4)
        return check_migratable(
            mk, [FIB], "stress.forest_steal",
            suppress=mk.verify_suppress,
        )

    progs.append(("stress:forest_steal", forest_claim))

    # Tenant front-door roster (the PR 8/13 ingress configuration the
    # CI smokes run): its WRR poll explored over EVERY schedule via the
    # roster-seeded protocol model (TenantTable.protocol_model wraps
    # wrr_poll_reference - the same executable spec the fairness tests
    # pin), plus the inner megakernel's standard verification.
    def tenant_front_door() -> AnalysisReport:
        from hclib_tpu.analysis import check_protocols
        from hclib_tpu.device.tenants import TenantSpec, TenantTable

        tb = TenantTable(
            [TenantSpec("gold", weight=2), TenantSpec("std"),
             TenantSpec("best-effort")],
            16, clock=lambda: 0.0,
        )
        return check_protocols(configs=[
            ("tenants:wrr(2:1:1)", tb.protocol_model()),
        ])

    progs.append(("tenants:front_door", tenant_front_door))
    return progs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--json-out", metavar="FILE",
                    help="also write the machine-readable findings to "
                         "FILE (the CI artifact - diffable across PRs)")
    ap.add_argument("--no-explore", action="store_true",
                    help="skip the bounded-interleaving protocol "
                         "exploration (the model-checker half)")
    ap.add_argument("--verbose", action="store_true",
                    help="print clean programs and info findings too")
    args = ap.parse_args(argv)

    from hclib_tpu.analysis import (
        check_layout, check_protocols, classify_megakernel,
        verify_megakernel,
    )
    from hclib_tpu.analysis.findings import AnalysisReport

    out = {}
    bad = 0

    lay = check_layout(force=True)
    out["layout"] = {"findings": lay.to_jsonable(), "kind_classes": {}}
    bad += len(lay.actionable())

    def emit(label, rep, certs=None):
        nonlocal bad
        out[label] = {
            "findings": rep.to_jsonable(),
            "kind_classes": dict(rep.kind_classes),
            "certificates": dict(certs or {}),
        }
        if rep.kind_classes and not args.json and args.verbose:
            cls = ", ".join(
                f"{k}={v}" for k, v in sorted(rep.kind_classes.items())
            )
            print(f"{label}: {cls}")
        if certs and not args.json and args.verbose:
            for k, c in sorted(certs.items()):
                print(f"{label}: schedule-independence[{k}]: "
                      f"{c.get('status')}")
        bad += len(rep.actionable())
        for f in rep.findings:
            if args.json:
                continue
            if f.severity == "info" and not args.verbose:
                continue
            print(f"{label}: {f}")

    for label, thunk in _programs():
        try:
            obj = thunk()
        except Exception as e:  # noqa: BLE001 - report, keep auditing
            out[label] = {"findings": [{
                "rule": "builder-error", "severity": "error",
                "kernel": None, "message": f"{type(e).__name__}: {e}",
                "witness": {}, "suppressed": False,
            }], "kind_classes": {}, "certificates": {}}
            bad += 1
            continue
        certs = {}
        if isinstance(obj, AnalysisReport):
            rep = obj
            certs = dict(getattr(obj, "certificates", {}) or {})
        else:
            rep = verify_megakernel(
                obj, suppress=getattr(obj, "verify_suppress", ()),
                raise_on_error=False,
            )
            rep.kind_classes = classify_megakernel(obj)
            if getattr(obj, "si_claim", None) is not None:
                from hclib_tpu.analysis import certify_claim

                cert = certify_claim(
                    obj, raise_on_error=False, report=rep,
                )
                if cert is not None:
                    certs[cert.get("kind", cert.get("kernel", "?"))] = (
                        cert
                    )
        emit(label, rep, certs)

    # The bounded-interleaving model checker over the curated protocol
    # configurations (inject WRR + quiesce freeze + credit exchange):
    # every schedule of each small seeded config, wall-budgeted
    # (HCLIB_TPU_MODEL_BUDGET_S) and depth-bounded
    # (HCLIB_TPU_MODEL_DEPTH) - CI's hard budget is the step timeout.
    if not args.no_explore:
        prot = check_protocols()
        prot.kind_classes = {}
        emit("protocols", prot)

    doc = json.dumps(out, indent=2)
    if args.json:
        print(doc)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(doc + "\n")
    if bad:
        print(f"hclint: {bad} actionable finding(s)", file=sys.stderr)
        return 1
    if not args.json:
        n = len(out) - 1
        print(f"hclint: {n} program(s) + layout table clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
