"""Stdlib-only Prometheus scrape endpoint over a MetricsRegistry.

ISSUE 19's export leg: ``serve(registry)`` binds an ``http.server`` on
localhost and answers ``GET /metrics`` with the registry's Prometheus
text exposition - flat gauges plus the native latency-histogram family
(``hclib_latency_bucket{tenant=...,le=...}``) when a scraped
``TelemetryBlock`` has been recorded (``MetricsRegistry.
record_latency``). Pair it with ``MetricsRegistry.watch(...)`` so the
request path only formats the record table; a scrape never touches a
live stream.

No dependencies beyond the standard library - the same constraint as
the rest of tools/. The server thread is a daemon; ``server.shutdown()``
stops it cleanly (tests and the CI smoke step do).

Usage (library)::

    from hclib_tpu.runtime.metrics import MetricsRegistry
    from tools.metrics_serve import serve

    reg = MetricsRegistry()
    reg.watch("stream", sm.telemetry_snapshot_metrics)  # or any source
    server, thread = serve(reg, port=9108)
    ...
    server.shutdown()

Usage (CLI)::

    python tools/metrics_serve.py --self-test   # serve + scrape + exit
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Tuple

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

__all__ = ["serve"]


def _make_handler(registry):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server ABI)
            if self.path.split("?", 1)[0] != "/metrics":
                self.send_error(404, "try /metrics")
                return
            try:
                body = registry.to_prometheus().encode()
            except Exception as e:  # a half-dead registry still answers
                self.send_error(500, f"exposition failed: {e}")
                return
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            pass  # scrapes are periodic; stderr noise helps nobody

    return Handler


def serve(
    registry, port: int = 0, host: str = "127.0.0.1"
) -> Tuple[HTTPServer, threading.Thread]:
    """Start the endpoint on a daemon thread; returns (server, thread).
    ``port=0`` binds an ephemeral port - read it back from
    ``server.server_address[1]``. Stop with ``server.shutdown()``."""
    server = HTTPServer((host, int(port)), _make_handler(registry))
    thread = threading.Thread(
        target=server.serve_forever,
        name="hclib-metrics-serve",
        daemon=True,
    )
    thread.start()
    return server, thread


def _self_test(port: int) -> int:
    """Serve a registry with one record + a synthetic latency block,
    scrape it once over real HTTP, and verify the exposition shape."""
    import urllib.request

    import numpy as np

    from hclib_tpu.device.telemetry import LAT_BUCKETS, TelemetryBlock
    from hclib_tpu.runtime.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.record("selftest", {"alive": 1})
    tele = np.zeros((2, LAT_BUCKETS), np.int64)
    tele[1, 3] = 5
    reg.record_latency(TelemetryBlock(tele, ns_per_round=1000.0))
    server, _ = serve(reg, port=port)
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            text = resp.read().decode()
    finally:
        server.shutdown()
    for needle in (
        "hclib_tpu_selftest_alive 1.0",
        'hclib_latency_bucket{tenant="0",le="16"} 5',
        'hclib_latency_count{tenant="0"} 5',
    ):
        if needle not in text:
            print(f"self-test FAILED: missing {needle!r}")
            return 1
    print("self-test ok:", len(text.splitlines()), "exposition lines")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--port", type=int, default=0,
        help="listen port (0 = ephemeral)",
    )
    p.add_argument(
        "--self-test", action="store_true",
        help="serve a synthetic registry, scrape once, exit",
    )
    args = p.parse_args(argv)
    if args.self_test:
        return _self_test(args.port)
    # Standalone mode serves an empty registry (useful only to check
    # wiring); real deployments call serve() with their registry.
    from hclib_tpu.runtime.metrics import MetricsRegistry

    server, thread = serve(MetricsRegistry(), port=args.port)
    print(
        f"serving /metrics on "
        f"http://127.0.0.1:{server.server_address[1]} (ctrl-c stops)"
    )
    try:
        thread.join()
    except KeyboardInterrupt:
        server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
