#!/usr/bin/env python
"""Seeded chaos sweep over the resilience subsystem (ISSUE 1, CI tooling).

Runs every failure-injection scenario the runtime claims to survive -
injected task faults under retry, worker death mid-UTS, runtime deadlines,
poison-task quarantine, and a procworld peer crash - across one or more
seeds, and exits nonzero if any scenario fails OR hangs.

Hang enforcement is the tool's own: ``faulthandler.dump_traceback_later``
arms a process-wide timer that dumps every thread's stack and hard-exits
(status 1) if the sweep overruns ``--timeout-s``, so a regression that
re-introduces an unbounded wait fails CI loudly instead of wedging it.
Each launch additionally runs under its own ``deadline_s`` (the feature
under test bounding the test).

``--mesh`` adds the seeded DEVICE chaos scenarios (ISSUE 2): a dead chip
on an 8-device interpret mesh whose queue re-homes to the survivors, and
a dropped ICI steal credit healed by timeout + regeneration. They need
the Mosaic TPU interpret mode (jax >= 0.5); on older builds they report
as skipped, not failed.

``--preempt`` adds the seeded PREEMPTION scenarios (ISSUE 5): checkpoint
a UTS megakernel mid-traversal and restore it bit-exactly from the
on-disk bundle; fire_preempt (the SIGTERM/watchdog path) quiescing a
live injection stream whose snapshot then drains exactly; and a
resident-mesh checkpoint restored onto a SMALLER mesh (N->M re-homing,
totals conserved - Mosaic-gated like the other mesh scenarios).

``--storm`` adds the seeded PREEMPT-STORM scenarios (ISSUE 6): repeated
fire_preempt cuts on a live injection stream (every cut resumed, grand
total exact), >= 3 chained checkpoints on one UTS traversal with
byte-identical bundles across storms (CheckpointBundle.diff), and the
autoscaled resident mesh riding scale-out, a dead-chip EVACUATION
mid-stream, and scale-in with totals bit-identical to an uninterrupted
fault-free run (the autoscale half is Mosaic-gated like the other mesh
scenarios).

``--tenants`` adds the seeded MULTI-TENANT INGRESS scenarios (ISSUE 8):
a greedy tenant pushing far past its quota while its siblings complete
their exact totals with WRR fairness in exact weight proportion; a
poison tenant throttled then quarantined while the others' task algebra
stays exact; a deadline storm whose per-tenant
``accepted == completed + expired`` identity reconciles exactly across
every expiry point (admission / host queue / on-ring lazy drop); and
fire_preempt landing mid-stream with three tenants live, per-tenant
accepted/completed/residue conserved across the checkpoint/resume cut.
All four run on the interpret-mode streaming kernel (no Mosaic needed).

``--serve`` adds the seeded SERVING-LOOP scenarios (ISSUE 16): a
depth-4 completion mailbox under a poller consuming one result per
step (sustained backpressure parks rows - counted, never dropped - and
every future still resolves RESULT with its exact payload); fire_preempt
landing on the live egress-enabled stream with futures in flight (every
future lands RESULT or PREEMPTED with a valid resume token, the resumed
stream re-adopts and every reattached future resolves); and a mesh
deadline storm resharded LIVE 4 -> 2 -> 4 with futures riding every cut,
closing ``submitted == resolved + expired + poisoned`` EXACTLY, globally
and per tenant. All three run interpret-mode/host-model (no Mosaic).

``--durability`` adds the seeded DURABLE-STORE scenarios (ISSUE 17): the
crash-point matrix over the generational ``BundleStore`` - torn npz,
flipped bit, lost manifest, preempt mid-save, preempt mid-restore, and a
fully-damaged store - proving bit-identical resume from the newest valid
generation with typed quarantines (and the poison diagnostic when none
survives); plus the serving loop restored THROUGH a fallback (newest
generation damaged on disk) with futures reattached and the ledger
closing exactly, and the reshard wait re-homing algebra (counts and
per-channel need sums conserved 4 -> 2 -> 4; satisfier-in-residue
refused whole-program). Both host-model (no Mosaic).

``--slo`` adds the seeded SLO-BURN scenario (ISSUE 19): a request
stream whose latency tail degrades mid-run; the streaming burn-rate
estimator (fed cumulative on-device latency histograms, the
TelemetryPoller shape) crosses the policy threshold and fires a typed
``slo_out`` scale-out BEFORE the deadline-budget watchdog rung (no
deadline has expired - the same observation with the burn signal
zeroed holds), riding TR_SCALE, the metrics registry, and the Perfetto
exporter. Host-model (no Mosaic).

Usage:
    python tools/chaos_soak.py                    # fast smoke (tier-1)
    python tools/chaos_soak.py --scale soak --seeds 8   # standalone soak
    python tools/chaos_soak.py --mesh --seeds 1   # device-mesh chaos (CI)
    python tools/chaos_soak.py --preempt-only --seeds 1  # checkpoint (CI)
    python tools/chaos_soak.py --storm-only --seeds 1  # preempt storms (CI)
    python tools/chaos_soak.py --serve-only --seeds 2  # serving loop (CI)

One JSON line per scenario; a machine-readable summary line last (seed
base/count, faults injected, recoveries, failures, wall time) so CI and
BENCH tooling can diff soak runs across PRs.
"""

from __future__ import annotations

import argparse
import faulthandler
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Before jax initializes: the mesh scenarios want 8 virtual CPU devices
# (same configuration tests/conftest.py pins).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import hclib_tpu as hc  # noqa: E402
from hclib_tpu.models import fib, uts  # noqa: E402
from hclib_tpu.modules.procworld import (  # noqa: E402
    ProcWorld,
    ProcWorldError,
)


class _FakeKV:
    """Minimal coordination-service stand-in (threads as ranks) so the
    procworld crash scenario runs in one process with no cluster - the
    same seam tests/test_procworld_unit.py uses."""

    def __init__(self) -> None:
        self._kv = {}
        self._ctr = {}
        self._cv = threading.Condition()

    def key_value_set_bytes(self, key, val):
        with self._cv:
            self._kv[key] = bytes(val)
            self._cv.notify_all()

    def key_value_try_get_bytes(self, key):
        with self._cv:
            if key in self._kv:
                return self._kv[key]
        raise RuntimeError(f"NOT_FOUND: key {key} not found")

    def blocking_key_value_get_bytes(self, key, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._cv:
            while key not in self._kv:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise RuntimeError(
                        f"DEADLINE_EXCEEDED: GetKeyValue() timed out "
                        f"with key: {key}"
                    )
                self._cv.wait(left)
            return self._kv[key]

    def key_value_delete(self, key):
        with self._cv:
            self._kv.pop(key, None)

    def key_value_increment(self, key, n):
        with self._cv:
            self._ctr[key] = self._ctr.get(key, 0) + n
            return self._ctr[key]

    def wait_at_barrier(self, bid, timeout_ms, *a, **k):
        raise RuntimeError("UNIMPLEMENTED: no barriers in the soak fake")


# ------------------------------------------------------------- scenarios

def scenario_fib_retry(seed: int, scale: str) -> dict:
    """Injected task faults healed by runtime-default retry."""
    n = 12 if scale == "smoke" else 18
    plan = hc.FaultPlan(
        seed=seed, task_failure_rate=0.15, max_task_failures=50
    )
    out = fib.run(
        n, "finish", nworkers=2,
        fault_plan=plan,
        default_retry=hc.RetryPolicy(max_attempts=8, backoff_s=0.0005,
                                     jitter=0, seed=seed),
        deadline_s=60.0,
    )
    faults = len(plan.trace_key())
    assert faults > 0, "plan injected nothing; scenario is vacuous"
    want = fib.fib_seq(n)
    assert out["value"] == want, (out["value"], want)
    # Retry is the only recovery path here and quarantine is off, so a
    # fault that did NOT recover would have failed the launch (or the
    # exact-value assert above): completing exactly means every injected
    # fault was healed.
    return {"value": out["value"], "faults": faults, "recoveries": faults}


def scenario_uts_kill_worker(seed: int, scale: str) -> dict:
    """Worker thread death mid-UTS; identity re-binds, traversal exact.
    The kill fires on worker 1's first scheduling poll; on a loaded
    1-vCPU host the short tree can drain before that thread is ever
    scheduled, so the kill is raced over a few attempts - every attempt
    must stay exact, and the kill must land within the attempt budget."""
    params = uts.T3
    plan = hc.FaultPlan(
        seed=seed, kill_worker=1, kill_worker_after=1,
        steal_delay_rate=0.05, steal_delay_s=0.001,
    )
    expect = uts.count_seq(params)[0]
    attempts = 0
    for attempts in range(1, 6):
        nodes, leaves, depth = uts.count_parallel(
            params, nworkers=4, grain=1,
            fault_plan=plan, deadline_s=120.0,
        )
        assert nodes == expect, f"UTS corrupted: {nodes} != {expect}"
        if ("kill_worker", 1) in plan.trace_key():
            break
    assert ("kill_worker", 1) in plan.trace_key(), "worker never died"
    return {"nodes": expect, "attempts": attempts,
            "trace": len(plan.trace_key())}


def scenario_deadline(seed: int, scale: str) -> dict:
    """A wedged program surfaces as StallError in bounded time."""
    t0 = time.monotonic()
    try:
        hc.launch(
            lambda: hc.Promise().future.wait(), nworkers=2, deadline_s=0.5
        )
    except hc.StallError:
        dt = time.monotonic() - t0
        assert dt < 10.0, f"deadline enforcement took {dt:.1f}s"
        return {"bounded_s": round(dt, 3)}
    raise AssertionError("wedged launch returned without StallError")


def scenario_quarantine(seed: int, scale: str) -> dict:
    """Poison tasks quarantine; the rest of the batch completes."""
    n = 64 if scale == "smoke" else 512
    done = []
    lock = threading.Lock()
    poison = {i for i in range(n) if i % 13 == seed % 13}

    def body(i):
        if i in poison:
            raise ValueError(f"poison item {i}")
        with lock:
            done.append(i)

    rt = hc.Runtime(
        nworkers=4,
        default_retry=hc.RetryPolicy(max_attempts=2, backoff_s=0,
                                     jitter=0, quarantine=True),
    )
    rt.run(lambda: hc.forasync(body, [n], tile=1), deadline_s=60.0)
    res = rt.stats_dict()["resilience"]
    assert len(done) == n - len(poison), (len(done), n, len(poison))
    assert res["quarantined"] == len(poison), res
    return {"completed": len(done), "quarantined": res["quarantined"]}


def scenario_procworld_crash(seed: int, scale: str) -> dict:
    """Peer progress-engine crash: the blocked waiter gets a structured
    ProcWorldError (tombstone/poison), never its full timeout."""
    kv = _FakeKV()
    plan = hc.FaultPlan(seed=seed, peer_crash_rank=1, peer_crash_after=0)
    a = ProcWorld(_client=kv, _rank=0, _size=2, timeout_s=20.0)
    b = ProcWorld(_client=kv, _rank=1, _size=2, timeout_s=20.0,
                  fault_plan=plan)
    try:
        import numpy as np

        with b._heap_lock:
            b._heap["x"] = np.zeros(2, np.int32)
        t0 = time.monotonic()
        try:
            a.get(1, "x")
        except ProcWorldError:
            dt = time.monotonic() - t0
            assert dt < 15.0, f"peer-death detection took {dt:.1f}s"
            return {"detected_s": round(dt, 3)}
        raise AssertionError("get() against crashed peer succeeded")
    finally:
        a.close()
        b.close()


# --------------------------------------------- device-mesh chaos (ISSUE 2)

def _mesh_prereq():
    from hclib_tpu.jaxcompat import has_mosaic_interpret

    if not has_mosaic_interpret():
        return "no Mosaic TPU interpret mode (needs jax >= 0.5)"
    import jax

    if len(jax.devices("cpu")) < 8:
        return "needs 8 virtual cpu devices"
    return None


def _mesh_rk(ndev, plan, capacity=256):
    import numpy as _np  # noqa: F401  (jax pulls it anyway)

    from hclib_tpu.device.megakernel import Megakernel
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    mk = Megakernel(
        kernels=[("bump", bump)], capacity=capacity, num_values=1024,
        succ_capacity=8, interpret=True,
    )
    return ResidentKernel(
        mk, cpu_mesh(ndev, axis_name="q"), migratable_fns=[0], window=4,
        fault_plan=plan,
    )


def scenario_mesh_dead_chip(seed: int, scale: str) -> dict:
    """Seeded dead chip on an 8-device interpret mesh: the survivors must
    drain the whole workload (queue re-homed, totals conserved)."""
    skip = _mesh_prereq()
    if skip:
        return {"skipped": skip}
    from hclib_tpu.device.descriptor import TaskGraphBuilder

    ndev, per = 8, 4
    dead = seed % ndev
    plan = hc.DeviceFaultPlan(
        seed=seed, dead_device=dead, dead_round=2, heartbeat_timeout=2,
    )
    rk = _mesh_rk(ndev, plan)
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    v = 0
    for d in range(ndev):
        for _ in range(per):
            v += 1
            builders[d].add(0, args=[v])
    iv, _, info = rk.run(builders, quantum=2, max_rounds=4096)
    assert info["pending"] == 0 and info["executed"] == ndev * per
    assert int(iv[:, 0].sum()) == v * (v + 1) // 2
    fs = info["fault_stats"]
    assert fs[dead]["rehomed_rows"] > 0
    quarantiners = sum(
        1 for d, f in enumerate(fs) if d != dead and dead in f["quarantined"]
    )
    assert quarantiners > 0
    return {"faults": 1, "recoveries": 1, "dead": dead,
            "rehomed": fs[dead]["rehomed_rows"],
            "quarantiners": quarantiners, "rounds": info["rounds"]}


def scenario_mesh_dropped_credit(seed: int, scale: str) -> dict:
    """Seeded dropped ICI steal credit: timeout + regeneration heal the
    channel; totals stay exact."""
    skip = _mesh_prereq()
    if skip:
        return {"skipped": skip}
    from hclib_tpu.device.descriptor import TaskGraphBuilder

    ntasks = 40
    plan = hc.DeviceFaultPlan(
        seed=seed, drop_credit_at=[(1, 0, 1)], credit_timeout=2,
    )
    rk = _mesh_rk(2, plan, capacity=128)
    builders = [TaskGraphBuilder(), TaskGraphBuilder()]
    for i in range(ntasks):
        builders[0].add(0, args=[i + 1])
    iv, _, info = rk.run(builders, quantum=2, max_rounds=4096)
    assert info["pending"] == 0 and info["executed"] == ntasks
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    fs = info["fault_stats"]
    dropped = sum(f["credits_dropped"] for f in fs)
    regen = sum(f["credits_regenerated"] for f in fs)
    assert dropped == 1 and regen == 1, fs
    return {"faults": dropped, "recoveries": regen,
            "rounds": info["rounds"]}


# --------------------------------------- preemption checkpoint (ISSUE 5)

def scenario_preempt_checkpoint(seed: int, scale: str) -> dict:
    """Seeded preemption mid-UTS-traversal: quiesce at a round boundary,
    bundle to disk (npz + checksummed manifest), restore on a FRESH
    megakernel, and the final totals are bit-identical to the
    uninterrupted run - the fault is the preemption, the recovery is the
    checkpoint/restore round trip."""
    import tempfile

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.workloads import (
        UTS_NODE, device_uts_mk, make_uts_megakernel,
    )
    from hclib_tpu.runtime.checkpoint import (
        restore_megakernel, snapshot_megakernel,
    )

    kw = dict(seed=19 + seed, interpret=True,
              max_depth=7 if scale == "smoke" else 9)
    nodes, _ = device_uts_mk(**kw)
    mk = make_uts_megakernel(checkpoint=True, **kw)
    b = TaskGraphBuilder()
    b.add(UTS_NODE, args=[1, 0])
    t0 = time.monotonic()
    _, _, info_q = mk.run(b, quiesce=max(1, nodes // 3))
    quiesce_s = time.monotonic() - t0
    assert info_q["quiesced"] and info_q["pending"] > 0, info_q
    d = tempfile.mkdtemp(prefix="hclib-ckpt-")
    stats = snapshot_megakernel(mk, info_q).save(d)
    iv, _, info_r = restore_megakernel(
        d, make_uts_megakernel(checkpoint=True, **kw)
    )
    assert int(iv[0]) == nodes, (int(iv[0]), nodes)
    assert info_r["executed"] == nodes and info_r["pending"] == 0
    return {"faults": 1, "recoveries": 1, "nodes": nodes,
            "checkpoint_at": info_q["quiesce"]["executed_at"],
            "bundle_bytes": stats["bundle_bytes"],
            "quiesce_s": round(quiesce_s, 3)}


def scenario_preempt_stream(seed: int, scale: str) -> dict:
    """fire_preempt (the SIGTERM/watchdog path) lands mid-stream: the
    bound hook quiesces it, the snapshot restores on a fresh stream, and
    the drain is exact - totals conserved across the preemption."""
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.inject import StreamingMegakernel
    from hclib_tpu.device.megakernel import Megakernel
    from hclib_tpu.runtime import resilience
    from hclib_tpu.runtime.checkpoint import checkpoint_on_preempt

    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    def make_sm():
        return StreamingMegakernel(
            Megakernel(kernels=[("bump", bump)], capacity=512,
                       num_values=64, succ_capacity=8, interpret=True,
                       checkpoint=True),
            ring_capacity=512,
        )

    resilience.reset_preempt()
    n = 60 if scale == "smoke" else 300
    sm = make_sm()
    b = TaskGraphBuilder()
    for i in range(10):
        b.add(0, args=[i + 1])
    for i in range(10, n):
        sm.inject(0, args=[i + 1])

    def preempter():
        time.sleep(0.05 + 0.01 * (seed % 3))
        resilience.fire_preempt(f"soak preemption seed {seed}")

    t = threading.Thread(target=preempter)
    t.start()
    try:
        with checkpoint_on_preempt(sm, after_executed=5):
            iv, info = sm.run_stream(b, quantum=8, deadline_s=120.0)
    finally:
        t.join()
        resilience.reset_preempt()
    assert info.get("quiesced"), "preemption never quiesced the stream"
    sm2 = make_sm()
    sm2.close()
    iv2, info2 = sm2.run_stream(resume_state=info["state"],
                                deadline_s=120.0)
    want = n * (n + 1) // 2
    assert int(iv2[0]) == want, (int(iv2[0]), want)
    return {"faults": 1, "recoveries": 1, "injected": n,
            "executed_at_cut": info["executed"]}


def scenario_preempt_mesh_reshard(seed: int, scale: str) -> dict:
    """Resident-mesh preemption with ELASTIC resume: quiesce a 4-chip
    interpret mesh mid-traversal, restore the bundle onto 2 chips (queues
    re-homed host-side, PR 2 conservation semantics), totals exact."""
    skip = _mesh_prereq()
    if skip:
        return {"skipped": skip}
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.device.workloads import UTS_NODE, make_uts_megakernel
    from hclib_tpu.parallel.mesh import cpu_mesh
    from hclib_tpu.runtime.checkpoint import (
        restore_resident, snapshot_resident,
    )
    import numpy as np

    def make_rk(ndev):
        mk = make_uts_megakernel(seed=19 + seed, max_depth=6,
                                 interpret=True, checkpoint=True)
        return ResidentKernel(
            mk, cpu_mesh(ndev, axis_name="q"),
            migratable_fns=[UTS_NODE], window=4, homed=False,
        )

    def builders(ndev):
        bs = [TaskGraphBuilder() for _ in range(ndev)]
        for d in range(ndev):
            bs[d].add(UTS_NODE, args=[d + 1, 0])
        return bs

    iv_f, _, info_f = make_rk(4).run(builders(4), quantum=8,
                                     max_rounds=4096)
    total = int(np.asarray(iv_f)[:, 0].sum())
    rk = make_rk(4)
    _, _, info_q = rk.run(builders(4), quantum=8, max_rounds=4096,
                          quiesce=2)
    assert info_q["quiesced"], info_q
    iv_r, _, info_r = restore_resident(
        snapshot_resident(rk, info_q), make_rk(2), quantum=8,
        max_rounds=4096,
    )
    assert info_r["pending"] == 0
    assert int(np.asarray(iv_r)[:, 0].sum()) == total
    return {"faults": 1, "recoveries": 1, "total": total,
            "executed": info_r["executed"],
            "pending_at_cut": info_q["pending"]}


# ------------------------------------- preempt storms + autoscale (ISSUE 6)

def scenario_storm_stream(seed: int, scale: str) -> dict:
    """Seeded PREEMPT STORM on a live injection stream: repeated
    fire_preempt cuts (the SIGTERM path) interleaved with resumes - every
    cut exports the ring residue + cursor, every resume drains exactly,
    and the grand total is bit-identical to an uninterrupted stream."""
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.inject import StreamingMegakernel
    from hclib_tpu.device.megakernel import Megakernel
    from hclib_tpu.runtime import resilience
    from hclib_tpu.runtime.checkpoint import checkpoint_on_preempt

    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    def make_sm():
        return StreamingMegakernel(
            Megakernel(kernels=[("bump", bump)], capacity=512,
                       num_values=64, succ_capacity=8, interpret=True,
                       checkpoint=True),
            ring_capacity=512,
        )

    n = 60 if scale == "smoke" else 240
    cuts = 3
    resilience.reset_preempt()
    sm = make_sm()
    b = TaskGraphBuilder()
    for i in range(8):
        b.add(0, args=[i + 1])
    for i in range(8, n):
        sm.inject(0, args=[i + 1])
    state = None
    quiesced = 0
    try:
        for cut in range(cuts):
            # Each cut: the preemption notice lands WHILE the stream
            # runs (a resume clears any pre-entry quiesce request by
            # design - same-object resumes behave like fresh streams),
            # so fire it from a delayed thread like a real SIGTERM.
            delay = 0.1 + 0.02 * ((seed + cut) % 4)
            t = threading.Thread(
                target=lambda d=delay, c=cut: (
                    time.sleep(d),
                    resilience.fire_preempt(f"storm cut {c}"),
                ),
            )
            with checkpoint_on_preempt(sm, after_executed=2):
                t.start()
                if state is None:
                    iv, info = sm.run_stream(b, quantum=4,
                                             deadline_s=120.0)
                else:
                    iv, info = sm.run_stream(resume_state=state,
                                             quantum=4, deadline_s=120.0)
                t.join()
            resilience.reset_preempt()
            assert info.get("quiesced"), f"cut {cut} never landed"
            quiesced += 1
            state = info["state"]
        sm.close()
        iv, info = sm.run_stream(resume_state=state, quantum=64,
                                 deadline_s=120.0)
    finally:
        resilience.reset_preempt()
    want = n * (n + 1) // 2
    assert int(iv[0]) == want, (int(iv[0]), want)
    assert info["pending"] == 0
    st = sm.stats_dict()
    assert st["quiesces"] == quiesced, st
    return {"faults": quiesced, "recoveries": quiesced, "injected": n,
            "cuts": quiesced, "total": want}


def scenario_storm_megakernel_chain(seed: int, scale: str) -> dict:
    """Chained checkpoint storm on the scalar tier: >= 3 quiesce cuts on
    one UTS traversal (one through the on-disk bundle), final count
    bit-identical; two independent storms produce byte-identical mid-cut
    bundles (CheckpointBundle.diff - determinism of the cut itself)."""
    import tempfile

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.workloads import (
        UTS_NODE, device_uts_mk, make_uts_megakernel,
    )
    from hclib_tpu.runtime.checkpoint import (
        CheckpointBundle, restore_megakernel, snapshot_megakernel,
    )

    kw = dict(seed=19 + seed, interpret=True,
              max_depth=7 if scale == "smoke" else 9)
    nodes, _ = device_uts_mk(**kw)

    def storm(mk):
        b = TaskGraphBuilder()
        b.add(UTS_NODE, args=[1, 0])
        # Absolute cut positions; quiesce= counts executed-since-ENTRY,
        # so each resume's threshold is relative to the previous cut.
        cuts = [max(1, nodes // 4), max(2, nodes // 2),
                max(3, (3 * nodes) // 4)]
        _, _, info = mk.run(b, quiesce=cuts[0])
        assert info["quiesced"], info
        bundles = [snapshot_megakernel(mk, info)]
        for at in cuts[1:]:
            rel = max(1, at - info["executed"])
            _, _, info = mk.resume(info["state"], quiesce=rel)
            assert info["quiesced"], info
            bundles.append(snapshot_megakernel(mk, info))
        return info, bundles

    mk = make_uts_megakernel(checkpoint=True, **kw)
    info, bundles = storm(mk)
    # Cut 3 goes through disk onto a FRESH kernel.
    d = tempfile.mkdtemp(prefix="hclib-storm-")
    bundles[-1].save(d)
    iv, _, done = restore_megakernel(
        d, make_uts_megakernel(checkpoint=True, **kw)
    )
    assert int(iv[0]) == nodes and done["pending"] == 0, (int(iv[0]), nodes)
    # Determinism of the storm itself: a second identical storm's
    # bundles are byte-identical (diff reports equal).
    _, bundles2 = storm(make_uts_megakernel(checkpoint=True, **kw))
    for b1, b2 in zip(bundles, bundles2):
        dd = b1.diff(b2)
        assert dd["equal"], dd
    # And a re-loaded bundle equals what was saved.
    assert CheckpointBundle.load(d).diff(bundles[-1])["equal"]

    # Cholesky under the same storm (batch tier + through-disk bf16):
    # two chained cuts + a disk restore, L bit-identical to the
    # uninterrupted factor.
    import numpy as np

    from hclib_tpu.device.cholesky import (
        build_cholesky_graph, cholesky_buffers, make_cholesky_megakernel,
    )
    from hclib_tpu.models.cholesky import make_spd

    nt = 2
    a = make_spd(256).astype(np.float32)
    _, data_full, info_full = make_cholesky_megakernel(
        nt, interpret=True
    ).run(build_cholesky_graph(nt), data=cholesky_buffers(a, nt))
    L_full = np.asarray(data_full["tiles"])
    mkc = make_cholesky_megakernel(nt, interpret=True, checkpoint=True)
    _, _, qc = mkc.run(
        build_cholesky_graph(nt), data=cholesky_buffers(a, nt), quiesce=2,
    )
    chol_cuts = 1
    if qc["quiesced"] and qc["pending"] > 0:
        _, _, q2 = mkc.resume(qc["state"], quiesce=2)
        if q2["quiesced"]:
            chol_cuts += 1
            qc = q2
        dc = tempfile.mkdtemp(prefix="hclib-storm-chol-")
        snapshot_megakernel(mkc, qc).save(dc)
        _, data_r, info_r = restore_megakernel(
            dc, make_cholesky_megakernel(nt, interpret=True,
                                         checkpoint=True)
        )
        assert info_r["executed"] == info_full["executed"]
        assert np.array_equal(np.asarray(data_r["tiles"]), L_full)
    return {"faults": len(bundles) + chol_cuts,
            "recoveries": len(bundles) + chol_cuts,
            "nodes": nodes, "cuts": len(bundles),
            "cholesky_cuts": chol_cuts}


def scenario_storm_autoscale(seed: int, scale: str) -> dict:
    """The full elastic story under a seeded storm: an autoscaled UTS
    mesh scales OUT under backlog, a dead chip mid-stream is detected,
    quarantined, and EVACUATED by reshard, the idle tail scales IN - and
    the final totals are bit-identical to an uninterrupted fault-free
    run (zero task loss through >= 3 scale events)."""
    skip = _mesh_prereq()
    if skip:
        return {"skipped": skip}
    import numpy as np

    import hclib_tpu as hc
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.device.workloads import UTS_NODE, make_uts_megakernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    depth = 6 if scale == "smoke" else 7

    def make_kernel(ndev, faulty=True):
        plan = None
        if ndev == 4 and faulty:
            # The storm's chip death: device 3 dies early in every
            # 4-device slice; survivors quarantine it by heartbeat.
            plan = hc.DeviceFaultPlan(
                seed=seed, dead_device=3, dead_round=2,
                heartbeat_timeout=2,
            )
        mk = make_uts_megakernel(seed=19 + seed, max_depth=depth,
                                 interpret=True, checkpoint=True)
        return ResidentKernel(
            mk, cpu_mesh(ndev, axis_name="q"),
            migratable_fns=[UTS_NODE], window=4, homed=False,
            fault_plan=plan,
        )

    def builders(ndev):
        bs = [TaskGraphBuilder() for _ in range(ndev)]
        for d in range(ndev):
            for r in range(8):
                bs[d].add(UTS_NODE, args=[d * 8 + r + 1, 0])
        return bs

    # Uninterrupted, fault-free reference on the starting mesh size.
    iv_f, _, info_f = make_kernel(2, faulty=False).run(
        builders(2), quantum=8, max_rounds=1 << 14
    )
    total = int(np.asarray(iv_f)[:, 0].sum())

    reg = hc.MetricsRegistry()
    asc = hc.Autoscaler(
        make_kernel,
        hc.AutoscalerPolicy(min_devices=1, max_devices=4,
                            scale_out_backlog=4.0, scale_in_backlog=1.0,
                            hysteresis=1, cooldown=1),
        slice_rounds=8, metrics=reg,
    )
    iv, _, info = asc.run(builders(2), quantum=8)
    assert info["pending"] == 0, info
    assert int(np.asarray(iv)[:, 0].sum()) == total, (
        int(np.asarray(iv)[:, 0].sum()), total
    )
    assert info["executed"] == info_f["executed"]
    kinds = [e["kind"] for e in info["scale_events"]]
    assert len(info["scale_events"]) >= 3, kinds
    assert "evacuate" in kinds, kinds
    resizes = [e for e in info["scale_events"]
               if e["from_ndev"] != e["to_ndev"]]
    snap = reg.snapshot()["metrics"]
    assert snap.get("autoscale.evacuate.count", 0) >= 1, snap
    return {"faults": 1, "recoveries": 1, "total": total,
            "events": kinds, "resizes": len(resizes),
            "ndev_final": info["ndev_final"]}


# ------------------------------------- multi-tenant ingress (ISSUE 8)

def _tenant_sm(specs, ring=768, checkpoint=False):
    from hclib_tpu.device.inject import StreamingMegakernel
    from hclib_tpu.device.megakernel import Megakernel

    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    return StreamingMegakernel(
        Megakernel(kernels=[("bump", bump)], capacity=512,
                   num_values=64, succ_capacity=8, interpret=True,
                   checkpoint=checkpoint),
        ring_capacity=ring, tenants=specs,
    )


def scenario_tenant_greedy_quota(seed: int, scale: str) -> dict:
    """A greedy tenant pushes 4x past its quota: the quota pushes back
    (typed backlog rejections, never a wedge), both sibling lanes
    complete their exact totals, and the WRR reference model proves
    install fairness stays in exact weight proportion."""
    import numpy as np

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.tenants import (
        TenantSpec, TenantTable, build_row, wrr_poll_reference,
    )

    rng = np.random.default_rng(1000 + seed)
    n1, n2 = int(rng.integers(15, 30)), int(rng.integers(15, 30))
    specs = lambda: [  # noqa: E731
        TenantSpec("victim1", weight=2),
        TenantSpec("victim2", weight=1),
        TenantSpec("greedy", weight=1, max_in_flight=4,
                   queue_capacity=8),
    ]
    sm = _tenant_sm(specs())
    expect, admitted, rejected = 0, 0, 0
    for k in range(n1):
        assert sm.submit("victim1", 0, args=[k + 1])
        expect += k + 1
    for k in range(n2):
        assert sm.submit("victim2", 0, args=[100])
        expect += 100
    for _ in range(4 * (n1 + n2)):
        adm = sm.submit("greedy", 0, args=[1])
        if adm:
            admitted += 1
        else:
            rejected += 1
            assert adm.reason == "backlog", adm.reason
    expect += admitted
    sm.close()
    iv, info = sm.run_stream(TaskGraphBuilder(), deadline_s=120.0)
    assert int(iv[0]) == expect, (int(iv[0]), expect)
    ten = info["tenants"]
    assert ten["victim1"]["completed"] == n1
    assert ten["victim2"]["completed"] == n2
    assert rejected > 0, "quota never pushed back"
    # Fairness bound (reference model, saturated lanes): installs per
    # whole WRR cycle are EXACTLY weight-proportional. Quotas off here -
    # fairness is the WRR weights' property; the quota's pushback was
    # asserted above on the live stream.
    table = TenantTable(
        [TenantSpec("victim1", weight=2), TenantSpec("victim2"),
         TenantSpec("greedy")],
        64, clock=lambda: 0.0,
    )
    ring = np.zeros((3 * 64, 256), np.int32)
    for lane in range(3):
        for i in range(32):
            table.admit(lane, build_row(0, [i]))
    tctl = table.pump(ring)
    for r in range(8):
        wrr_poll_reference(ring, tctl, 64, r, 1 << 20)
    table.absorb(tctl)
    done = {t: s["completed"] for t, s in table.stats().items()}
    assert done["victim1"] == 2 * done["victim2"] == 2 * done["greedy"]
    return {"faults": rejected, "recoveries": 1, "greedy_admitted":
            admitted, "greedy_rejected": rejected,
            "victim_tasks": n1 + n2}


def scenario_tenant_poison_quarantine(seed: int, scale: str) -> dict:
    """A poison tenant (validator explodes on seeded rows) climbs
    throttle -> quarantine; the other tenants complete exactly - no
    poison row ever executes, quarantine never wedges the drain."""
    import numpy as np

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.tenants import TenantSpec

    rng = np.random.default_rng(2000 + seed)
    n_ok = int(rng.integers(20, 40))

    def poison(row):
        raise RuntimeError(f"poison row (seed {seed})")

    sm = _tenant_sm([
        TenantSpec("poison", validator=poison, poison_throttle=1,
                   poison_quarantine=2),
        TenantSpec("steady", weight=2),
        TenantSpec("bursty"),
    ])
    for _ in range(6):
        sm.submit("poison", 0, args=[999_999])
    expect, nb = 0, 0
    for k in range(n_ok):
        assert sm.submit("steady", 0, args=[k + 1])
        expect += k + 1
        if rng.random() < 0.5:
            assert sm.submit("bursty", 0, args=[10])
            expect += 10
            nb += 1
    sm.close()
    iv, info = sm.run_stream(TaskGraphBuilder(), deadline_s=120.0)
    assert int(iv[0]) == expect, (int(iv[0]), expect)
    ten = info["tenants"]
    assert ten["steady"]["completed"] == n_ok
    assert ten["bursty"]["completed"] == nb
    assert ten["poison"]["completed"] == 0
    assert ten["poison"]["quarantined"] == 1
    return {"faults": ten["poison"]["poisoned"], "recoveries": 1,
            "steady": n_ok, "bursty": nb}


def scenario_tenant_deadline_storm(seed: int, scale: str) -> dict:
    """Deadline storm under a deterministic clock: seeded mix of live
    and doomed submissions across 3 lanes; every expiry point exercised
    and the per-tenant accepted == completed + expired identity
    reconciles exactly."""
    import numpy as np

    from hclib_tpu.device.tenants import (
        TenantSpec, TenantTable, build_row, wrr_poll_reference,
    )

    rng = np.random.default_rng(3000 + seed)
    t_now = [100.0]
    clock = lambda: t_now[0]  # noqa: E731
    table = TenantTable(
        [TenantSpec("a", weight=2, max_in_flight=8, queue_capacity=512),
         TenantSpec("b", queue_capacity=512),
         TenantSpec("c", deadline_s=0.5, queue_capacity=512)],
        64, clock=clock,
    )
    ring = np.zeros((3 * 64, 256), np.int32)
    n = 60 if scale == "smoke" else 240
    rejected_expired = 0
    for i in range(n):
        lane = int(rng.integers(0, 3))
        doomed = rng.random() < 0.4
        dl = clock() + (0.01 if doomed else 60.0)
        if rng.random() < 0.1:
            dl = clock() - 1.0  # already expired at admission
        adm = table.admit(lane, build_row(0, [i]), deadline_at=dl)
        if not adm:
            assert adm.reason == "expired"
            rejected_expired += 1
        # Seeded clock jitter + a pump/poll slice every few admits.
        t_now[0] += float(rng.random() * 0.02)
        if i % 8 == 7:
            tctl = table.pump(ring)
            for r in range(2):
                wrr_poll_reference(ring, tctl, 64, i + r, 1 << 20)
            table.absorb(tctl)
            t_now[0] += float(rng.random() * 0.05)
    # Drain: advance past every live deadline's horizon is NOT done -
    # live rows must complete, doomed rows must expire.
    for r in range(256):
        tctl = table.pump(ring)
        wrr_poll_reference(ring, tctl, 64, r, 1 << 20)
        table.absorb(tctl)
        if table.drained():
            break
    assert table.drained(), "deadline storm wedged the drain"
    total_exp = total_done = 0
    for tid, s in table.stats().items():
        assert s["accepted"] == s["completed"] + s["expired"], (tid, s)
        total_exp += s["expired"]
        total_done += s["completed"]
    assert total_exp > 0 and total_done > 0
    return {"faults": total_exp + rejected_expired, "recoveries": 1,
            "admitted": total_done + total_exp,
            "expired": total_exp, "completed": total_done,
            "rejected_at_admission": rejected_expired}


def scenario_tenant_preempt_stream(seed: int, scale: str) -> dict:
    """fire_preempt lands mid-stream with THREE tenants live: the bound
    hook quiesces, per-tenant residue rides the snapshot tenant-tagged,
    and the resumed drain conserves per-tenant accepted/completed
    counts exactly (grand total exact by value algebra)."""
    import numpy as np

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.tenants import per_tenant_ring_counts
    from hclib_tpu.runtime import resilience
    from hclib_tpu.runtime.checkpoint import checkpoint_on_preempt

    rng = np.random.default_rng(4000 + seed)
    subs = {t: int(rng.integers(20, 40))
            for t in ("alpha", "beta", "gamma")}
    resilience.reset_preempt()
    sm = _tenant_sm(list(subs), checkpoint=True)
    expect = 0
    for i, (tid, cnt) in enumerate(subs.items()):
        for _ in range(cnt):
            assert sm.submit(tid, 0, args=[i + 1])
            expect += i + 1

    def preempter():
        time.sleep(0.05 + 0.01 * (seed % 3))
        resilience.fire_preempt(f"tenant soak preemption seed {seed}")

    t = threading.Thread(target=preempter)
    t.start()
    try:
        with checkpoint_on_preempt(sm, after_executed=5):
            iv, info = sm.run_stream(
                TaskGraphBuilder(), quantum=8, deadline_s=120.0,
            )
    finally:
        t.join()
        resilience.reset_preempt()
    assert info.get("quiesced"), "preemption never quiesced the stream"
    st = info["state"]
    residue = per_tenant_ring_counts(st["ring_rows"])
    installed_at_cut = {
        i: int(st["tctl"][i, 5]) for i in range(3)  # TC_INSTALLED
    }
    for i, cnt in enumerate(subs.values()):
        assert installed_at_cut[i] + residue.get(i, 0) == cnt
    sm2 = _tenant_sm(list(subs), checkpoint=True)
    sm2.close()
    iv2, info2 = sm2.run_stream(resume_state=st, deadline_s=120.0)
    assert int(iv2[0]) == expect, (int(iv2[0]), expect)
    ten = info2["tenants"]
    for tid, cnt in subs.items():
        assert ten[tid]["accepted"] == cnt
        assert ten[tid]["completed"] == cnt
    return {"faults": 1, "recoveries": 1,
            "executed_at_cut": info["executed"],
            "residue_rows": int(sum(residue.values())),
            **{f"tasks_{t}": c for t, c in subs.items()}}


# ------------------------- mesh-wide tenancy + tenant storms (ISSUE 13)

def _mesh_conservation(table) -> None:
    """The per-cut identity: submitted == completed + expired + dropped
    (+ still-queued backlog) reconciles EXACTLY per tenant, at every
    mesh size."""
    for tid, s in table.stats().items():
        assert s["accepted"] == (
            s["completed"] + s["expired"] + s["dropped"] + s["backlog"]
        ), (tid, s)


def _mesh_drive(table, rings, polls=2, start=0, clock=None, dt=0.0):
    from hclib_tpu.device.tenants import wrr_poll_reference

    tctl = table.pump(rings)
    for r in range(start, start + polls):
        for d in range(table.ndev):
            wrr_poll_reference(
                rings[d], tctl[d], table.region_rows, r, 1 << 20
            )
    table.absorb(tctl)
    if clock is not None and dt:
        clock[0] += dt


def scenario_tenant_mesh_storm_reshard(seed: int, scale: str) -> dict:
    """THE ACCEPTANCE STORM (ISSUE 13): greedy tenant + deadline storm +
    poison quarantine hitting a mesh-wide front door across THREE live
    reshard cuts (4 -> 2 -> 4 -> 2), per-tenant
    submitted == completed + expired + dropped reconciled exactly at
    every mesh size (one cut routed through CheckpointBundle.reshard's
    tctl/tstats pass-through), and WRR fairness probed after every cut
    in exact weight proportion - the single-device bounds. Runs on the
    numpy WRR reference model (the executable spec of the in-kernel
    poll), so no Mosaic is needed."""
    import numpy as np

    from hclib_tpu.device.descriptor import RING_ROW
    from hclib_tpu.device.tenants import MeshTenantTable, TenantSpec

    rng = np.random.default_rng(5000 + seed)
    t_now = [100.0]
    clock = lambda: t_now[0]  # noqa: E731
    # Region sized so a phase's storm + probe + carry + re-dealt
    # residue fits one lane region even at the 2-device trough (the
    # lifetime budget is per table incarnation: it resets at each cut).
    region = 32

    def boom(row):
        raise RuntimeError(f"poison row (seed {seed})")

    def specs():
        return [
            TenantSpec("steady", weight=2, queue_capacity=512),
            TenantSpec("greedy", weight=1, max_in_flight=4,
                       queue_capacity=6),
            TenantSpec("stormy", weight=1, queue_capacity=512,
                       deadline_budget=1_000_000),
            TenantSpec("poison", weight=1, validator=boom,
                       poison_throttle=2, poison_quarantine=4,
                       queue_capacity=512),
        ]

    def fresh_rings(ndev):
        return np.zeros((ndev, 4 * region, RING_ROW), np.int32)

    sizes = [4, 2, 4, 2]
    table = MeshTenantTable(specs(), sizes[0], region, clock=clock)
    rings = fresh_rings(sizes[0])
    greedy_rejects = 0
    expired_doomed = 0
    poisoned_subs = 6
    fairness_probes = []
    cuts = 0
    rnd = 0
    for phase, ndev in enumerate(sizes):
        # Storm traffic: steady flow, a greedy burst far past its
        # quota, a deadline storm (seeded doomed fraction), and - in
        # phase 0 only - the poison tenant walking into quarantine.
        for k in range(12):
            assert table.submit("steady", 0, args=[k + 1]), "steady"
        for _ in range(40):
            adm = table.submit("greedy", 0, args=[1])
            if not adm:
                greedy_rejects += 1
                assert adm.reason in ("backlog", "ring"), adm.reason
        for i in range(16):
            doomed = rng.random() < 0.4
            if doomed:
                expired_doomed += 1
            adm = table.submit(
                "stormy", 0, args=[i],
                deadline_s=(0.01 if doomed else 1e6),
            )
            assert adm, adm.reason
        if phase == 0:
            for _ in range(poisoned_subs):
                table.submit("poison", 0, args=[999])
        _mesh_drive(table, rings, polls=2, start=rnd, clock=t_now,
                    dt=0.05)
        rnd += 2
        _mesh_drive(table, rings, polls=2, start=rnd, clock=t_now,
                    dt=0.05)
        rnd += 2
        _mesh_conservation(table)
        # Drain this phase's storm (doomed rows expire, live rows
        # complete) so the fairness probe below measures CLEAN lanes -
        # expired rows legitimately consume WRR slots without
        # installing, which is throughput shaping, not unfairness.
        for r in range(128):
            _mesh_drive(table, rings, polls=2, start=rnd, clock=t_now,
                        dt=0.02)
            rnd += 2
            if table.drained():
                break
        assert table.drained(), f"phase {phase} storm wedged the drain"
        _mesh_conservation(table)
        # WRR fairness probe at THIS size (the single-device bounds):
        # with both lanes saturated, installs per whole WRR cycle are
        # exactly weight-proportional (steady w=2 : stormy w=1).
        before = {t: table.stats()[t]["completed"]
                  for t in ("steady", "stormy")}
        for d in range(table.ndev):
            for k in range(8):
                assert table.submit("steady", 0, args=[1], device=d)
            for k in range(4):
                assert table.submit("stormy", 0, args=[1],
                                    deadline_s=1e6, device=d)
        _mesh_drive(table, rings, polls=4, start=rnd, clock=t_now)
        rnd += 4
        after = {t: table.stats()[t]["completed"]
                 for t in ("steady", "stormy")}
        ds = after["steady"] - before["steady"]
        dm = after["stormy"] - before["stormy"]
        assert ds == 2 * dm > 0, (phase, ds, dm)
        fairness_probes.append((ds, dm))
        if phase == len(sizes) - 1:
            break
        # Carry residue INTO the cut: a fresh batch pinned on device 0
        # (so one weight-bounded poll cannot drain it), only partially
        # consumed - the reshard must re-deal live tenant-tagged rows
        # (the conservation identity must reconcile across the cut
        # with work genuinely in flight).
        for k in range(6):
            assert table.submit("steady", 0, args=[k + 1], device=0)
        for k in range(4):
            assert table.submit("stormy", 0, args=[k], deadline_s=1e6,
                                device=0)
        _mesh_drive(table, rings, polls=1, start=rnd)
        rnd += 1
        assert not table.drained(), "carry batch already drained"
        _mesh_conservation(table)
        # LIVE RESHARD CUT to the next size. Cut 1 rides the
        # CheckpointBundle path end-to-end (ring_rows re-deal + the
        # aggregate tctl/tstats pass-through); the others use the
        # table's own export/resume.
        ndev_next = sizes[phase + 1]
        if phase == 1:
            from hclib_tpu.device.descriptor import (
                DESC_WORDS, F_HOME, NO_TASK,
            )
            from hclib_tpu.runtime.checkpoint import CheckpointBundle

            st = table.export_state(rings)
            cap = 8
            tasks = np.zeros((table.ndev, cap, DESC_WORDS), np.int32)
            tasks[:, :, 2:4] = NO_TASK
            tasks[:, :, F_HOME] = NO_TASK
            counts = np.zeros((table.ndev, 8), np.int32)
            counts[:, 4] = 2
            b = CheckpointBundle("resident", {"ndev": table.ndev}, {
                "tasks": tasks,
                "succ": np.full((table.ndev, 8), -1, np.int32),
                "ready": np.zeros((table.ndev, cap), np.int32),
                "counts": counts,
                "ivalues": np.zeros((table.ndev, 16), np.int32),
                "ring_rows": st["ring_rows"], "ictl": st["ictl"],
                "tctl": st["tctl"], "tstats": st["tstats"],
            })
            out = b.reshard(ndev_next)
            assert np.array_equal(out.arrays["tctl"], st["tctl"])
            assert np.array_equal(out.arrays["tstats"], st["tstats"])
            nxt = table.resized(ndev_next)
            nxt.resume_from({
                "ring_rows": out.arrays["ring_rows"],
                "ictl": out.arrays["ictl"],
                "tctl": out.arrays["tctl"],
                "tstats": out.arrays["tstats"],
                "tenant_ids": st["tenant_ids"],
            })
            table = nxt
        else:
            table, _ = table.reshard(rings, ndev_next)
        rings = fresh_rings(ndev_next)
        cuts += 1
        _mesh_conservation(table)
    # Drain to empty: doomed rows expire, live rows complete.
    for r in range(256):
        _mesh_drive(table, rings, polls=2, start=rnd + r, clock=t_now,
                    dt=0.02)
        if table.drained():
            break
    assert table.drained(), "tenant mesh storm wedged the drain"
    _mesh_conservation(table)
    snap = table.stats()
    assert snap["poison"]["quarantined"] == 1, snap["poison"]
    assert snap["poison"]["completed"] == 0, snap["poison"]
    assert snap["stormy"]["expired"] > 0, snap["stormy"]
    assert greedy_rejects > 0, "greedy quota never pushed back"
    assert all(s["backlog"] == 0 for s in snap.values()), snap
    return {
        "faults": greedy_rejects + snap["stormy"]["expired"]
        + snap["poison"]["poisoned"],
        "recoveries": cuts, "cuts": cuts,
        "greedy_rejected": greedy_rejects,
        "stormy_expired": int(snap["stormy"]["expired"]),
        "fairness": fairness_probes,
    }


def scenario_tenant_mesh_autoscale_pressure(seed: int, scale: str) -> dict:
    """Tenant/deadline-aware autoscaling (ISSUE 13 policy half): a
    tenant burning its deadline budget triggers a typed ``deadline_out``
    scale-out BEFORE the watchdog rung (budget exhaustion -> lane
    cancel) - during cooldown, with zero streak - and scale-in is
    refused with a typed ``strand_hold`` while any tenant has in-flight
    ring residue, then fires once drained."""
    import numpy as np

    import hclib_tpu as hc
    from hclib_tpu.device.descriptor import RING_ROW
    from hclib_tpu.device.tenants import MeshTenantTable, TenantSpec

    t_now = [100.0]
    clock = lambda: t_now[0]  # noqa: E731
    region = 16
    budget = 40
    table = MeshTenantTable(
        [TenantSpec("latency", weight=2, deadline_budget=budget,
                    queue_capacity=512),
         TenantSpec("bulk", queue_capacity=512)],
        2, region, clock=clock,
    )
    rings = np.zeros((2, 2 * region, RING_ROW), np.int32)
    policy = hc.AutoscalerPolicy(
        min_devices=1, max_devices=8, scale_out_backlog=1e9,
        scale_in_backlog=4.0, hysteresis=2, cooldown=3,
        tenant_pressure=0.25,
    )
    # Prime the cooldown gate (prove the pressure path bypasses it).
    policy._cooling = 3
    ndev, events, rnd = 2, [], 0

    def observe(backlog_rows):
        return hc.Observation(
            ndev, [backlog_rows] * ndev, executed_delta=8, slice_s=1.0,
            tenants=table.pressure(),
        )

    # Slice 0: baseline (no drain yet - deltas need a previous slice).
    events.append(policy.decide(observe(8))[1])
    # Slice 1: the deadline storm - a burst of doomed rows expires
    # within one slice, draining >= 25% of the budget.
    for i in range(16):
        assert table.submit("latency", 0, args=[i], deadline_s=0.01)
    t_now[0] += 1.0  # every deadline lapses before the pump
    tctl = table.pump(rings)
    table.absorb(tctl)
    target, kind, reason = policy.decide(observe(8))
    events.append(kind)
    assert kind == "deadline_out", (kind, reason, table.pressure())
    assert target == 2 * ndev
    snap = table.stats()["latency"]
    # BEFORE the watchdog rung: the budget is not exhausted, the lane
    # is NOT cancelled - the controller beat the strike ladder.
    assert snap["expired"] < budget, snap
    assert table.submit("latency", 0, args=[0], deadline_s=1e6), (
        "lane already cancelled: scale-out lost the race"
    )
    ndev = target
    # The typed event rides TR_SCALE + the metrics registry.
    reg = hc.MetricsRegistry()
    asc = hc.Autoscaler(lambda n: None, policy, metrics=reg)
    asc._event(hc.ScaleEvent("deadline_out", 1, 2, 4, reason))
    from hclib_tpu.device.tracebuf import TR_SCALE, records_of

    recs = records_of(asc.trace_info(), TR_SCALE)
    assert len(recs) == 1 and int(recs[0][2]) == (2 << 8) | 4
    assert reg.snapshot()["metrics"]["autoscale.deadline_out.count"] == 1
    # Strand refusal: idle backlog + in-flight ring residue (published,
    # unconsumed - the submit above) -> typed strand_hold, repeatedly.
    tctl = table.pump(rings)  # publish; nothing consumed yet
    table.absorb(tctl)
    assert table.stats()["latency"]["in_flight"] > 0
    policy._cooling = 0
    kinds = [policy.decide(observe(0))[1] for _ in range(3)]
    events += kinds
    assert kinds[0] == "hold"  # streak 1/2
    assert kinds[1] == "strand_hold" and kinds[2] == "strand_hold", kinds
    asc._event(hc.ScaleEvent("strand_hold", 2, ndev, ndev, "refused"))
    # Drain the residue: the very next slice scales in.
    from hclib_tpu.device.tenants import wrr_poll_reference

    tctl = table.pump(rings)
    for d in range(2):
        wrr_poll_reference(rings[d], tctl[d], region, rnd, 1 << 20)
    table.absorb(tctl)
    assert table.stats()["latency"]["in_flight"] == 0
    target, kind, reason = policy.decide(observe(0))
    events.append(kind)
    assert kind == "scale_in" and target == ndev // 2, (kind, reason)
    return {"faults": int(table.stats()["latency"]["expired"]),
            "recoveries": 1, "events": events}


# ------------------- request/response serving loop (ISSUE 16)

def scenario_serve_slow_poller(seed: int, scale: str) -> dict:
    """SERVE: a depth-4 completion mailbox fed by bursty retirement
    while the poller consumes ONE result per step - sustained
    backpressure parks rows (counted, never dropped) and every
    submitted future still resolves RESULT with its exact payload:
    zero loss under a poller an order of magnitude too slow."""
    import numpy as np

    from hclib_tpu.device.descriptor import RING_ROW, TEN_TOKEN
    from hclib_tpu.device.egress import EgressSpec, HostMailbox
    from hclib_tpu.device.tenants import (
        TenantSpec, TenantTable, wrr_poll_reference,
    )

    rng = np.random.default_rng(6000 + seed)
    n = 48 if scale == "smoke" else 192
    region = 64
    spec = EgressSpec(depth=4)
    table = TenantTable(
        [TenantSpec("gold", weight=2), TenantSpec("std")],
        region, clock=lambda: 100.0, egress=spec,
    )
    # Host-model park capacity covers the whole storm: the DEVICE
    # bounds park occupancy with its install credit gate; this
    # reference drive retires whole poll batches at once, so the
    # ring must hold everything the slow poller leaves behind.
    box = HostMailbox(spec, park_cap=n)
    ring = np.zeros((2 * region, RING_ROW), np.int32)
    futs, values, submitted, drained = [], {}, 0, 0
    for i in range(n):
        adm = table.submit(int(rng.integers(0, 2)), 0, args=[i])
        assert adm and adm.future.token > 0, adm
        futs.append(adm.future)
        values[adm.future.token] = 3 * i + 1
        submitted += 1
    rnd = 0
    while drained < submitted:
        tctl = table.pump(ring)
        rows = wrr_poll_reference(ring, tctl, region, rnd, 1 << 20)
        table.absorb(tctl)
        rnd += 1
        box.publish([
            (int(r[TEN_TOKEN]), 0, 0, 0, values[int(r[TEN_TOKEN])])
            for r in rows
        ])
        # The slow poller: one result per step, no matter the burst.
        drained += len(box.drain(futures=table.futures, limit=1))
        assert rnd < 16 * n, "slow poller wedged the serve loop"
    assert box.park_events() > 0, "mailbox never backpressured"
    assert box.occupancy() == 0 and box.parked() == 0
    for f in futs:
        assert f.result(timeout=1.0) == values[f.token]
        assert f.state == "RESULT"
    cons = table.futures.conservation()
    assert cons["ok"] and cons["resolved"] == submitted, cons
    return {"faults": int(box.park_events()), "recoveries": 1,
            "submitted": submitted, "park_events":
            int(box.park_events()), "steps": rnd}


def scenario_serve_fire_preempt(seed: int, scale: str) -> dict:
    """SERVE: fire_preempt lands with futures in flight on the live
    egress-enabled stream - the cut lands every future in RESULT or
    PREEMPTED (valid resume token, never a silent hang); the resumed
    stream re-adopts the tokens and every reattached future resolves.
    Conservation closes exactly on both ledgers."""
    import numpy as np

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.egress import EgressSpec
    from hclib_tpu.device.tenants import TenantSpec, TenantTable
    from hclib_tpu.runtime import resilience
    from hclib_tpu.runtime.checkpoint import checkpoint_on_preempt

    rng = np.random.default_rng(7000 + seed)
    subs = {t: int(rng.integers(12, 24))
            for t in ("alpha", "beta", "gamma")}

    def table():
        return TenantTable(
            [TenantSpec(t) for t in subs], 256,
            egress=EgressSpec(depth=16),
        )

    resilience.reset_preempt()
    t1 = table()
    sm = _tenant_sm(t1, checkpoint=True)
    futs, expect = [], 0
    for i, (tid, cnt) in enumerate(subs.items()):
        for _ in range(cnt):
            adm = sm.submit(tid, 0, args=[i + 1])
            assert adm and adm.future is not None
            futs.append(adm.future)
            expect += i + 1

    def preempter():
        time.sleep(0.05 + 0.01 * (seed % 3))
        resilience.fire_preempt(f"serve soak preemption seed {seed}")

    t = threading.Thread(target=preempter)
    t.start()
    try:
        with checkpoint_on_preempt(sm, after_executed=5):
            iv, info = sm.run_stream(
                TaskGraphBuilder(), quantum=8, deadline_s=120.0,
            )
    finally:
        t.join()
        resilience.reset_preempt()
    assert info.get("quiesced"), "preemption never quiesced the stream"
    st = info["state"]
    assert "etok" in st, "egress tokens missing from the snapshot"
    states = {f.state for f in futs}
    assert states <= {"RESULT", "PREEMPTED"}, states
    tokens = []
    for f in futs:
        if f.state == "PREEMPTED":
            tok = f.resume_token
            assert tok and tok[0] == "hclib-egress-resume", tok
            tokens.append(tok)
    c1 = t1.futures.conservation()
    assert c1["ok"] and c1["preempted"] == len(tokens), c1
    # Resume on a fresh equivalent stream; reattach AFTER resume_from
    # has re-adopted the snapshot's tokens.
    t2 = table()
    sm2 = _tenant_sm(t2, checkpoint=True)
    sm2.close()
    iv2, info2 = sm2.run_stream(resume_state=st, deadline_s=120.0)
    assert int(iv2[0]) == expect, (int(iv2[0]), expect)
    for tok in tokens:
        f = sm2.tenants.reattach(tok)
        assert f.result(timeout=2.0) is not None
        assert f.state == "RESULT", f.state
    c2 = t2.futures.conservation()
    assert c2["ok"] and c2["pending"] == 0, c2
    return {"faults": 1, "recoveries": 1,
            "executed_at_cut": info["executed"],
            "preempted_futures": len(tokens),
            "resolved_before_cut": int(c1["resolved"]),
            **{f"tasks_{t}": c for t, c in subs.items()}}


def scenario_serve_mesh_deadline_storm(seed: int, scale: str) -> dict:
    """SERVE: the soak conservation arm - a 4-device mesh front door
    under a seeded deadline storm, resharded LIVE 4 -> 2 -> 4 with
    futures in flight (preempt -> reattach on the shared ledger at
    every cut). At the end every future is terminal and
    submitted == resolved + expired + poisoned EXACTLY, globally and
    per tenant."""
    import numpy as np

    from hclib_tpu.device.descriptor import RING_ROW, TEN_TOKEN
    from hclib_tpu.device.egress import EgressSpec, HostMailbox
    from hclib_tpu.device.tenants import (
        MeshTenantTable, TenantSpec, wrr_poll_reference,
    )

    rng = np.random.default_rng(8000 + seed)
    region = 16
    clk = [100.0]
    spec = EgressSpec(depth=4)
    table = MeshTenantTable(
        [TenantSpec("gold", weight=2), TenantSpec("std"),
         TenantSpec("batch", queue_capacity=512)],
        4, region, clock=lambda: clk[0], egress=spec,
    )
    futures = table.futures
    assert futures is not None
    per_batch = 10 if scale == "smoke" else 40
    # Client view: token -> latest Future (reattach swaps in the new
    # one); tenant name rides alongside for the per-tenant identity.
    client = {}

    def drive(table, rings, polls=2, start=0, dt=0.05):
        boxes = [HostMailbox(spec, park_cap=8 * region)
                 for _ in range(table.ndev)]
        tctl = table.pump(rings)
        for r in range(start, start + polls):
            for d in range(table.ndev):
                rows = wrr_poll_reference(
                    rings[d], tctl[d], table.region_rows, r, 1 << 20
                )
                boxes[d].publish([
                    (int(row[TEN_TOKEN]), 0, 0, 0, 7) for row in rows
                ])
        table.absorb(tctl)
        for box in boxes:
            box.drain(futures=futures)
        clk[0] += dt

    def rings_for(ndev):
        return np.zeros((ndev, 3 * region, RING_ROW), np.int32)

    submitted = 0
    sizes = [4, 2, 4]
    rings = rings_for(4)
    names = ("gold", "std", "batch")
    for phase, ndev in enumerate(sizes):
        for i in range(per_batch):
            tid = names[int(rng.integers(0, 3))]
            doomed = rng.random() < 0.35
            adm = table.submit(
                tid, 0, args=[i],
                deadline_s=(0.01 if doomed else 600.0),
            )
            if adm:
                submitted += 1
                client[adm.future.token] = (tid, adm.future)
            clk[0] += float(rng.random() * 0.02)
        drive(table, rings, polls=2, start=4 * phase)
        if phase == len(sizes) - 1:
            break
        # The live cut: export preempts in-flight futures; the resized
        # mesh shares the SAME ledger, so resume tokens reattach.
        state = table.export_state(rings)
        tokens = [(tok, tid, f.resume_token)
                  for tok, (tid, f) in client.items()
                  if f.state == "PREEMPTED"]
        nxt = table.resized(sizes[phase + 1])
        assert nxt.futures is futures, "ledger forked across the cut"
        nxt.resume_from(state)
        for tok, tid, rt in tokens:
            client[tok] = (tid, nxt.reattach(rt))
        table, rings = nxt, rings_for(nxt.ndev)
    for r in range(40, 40 + 64):
        drive(table, rings, polls=1, start=r)
        if table.drained():
            break
    assert table.drained(), "deadline storm wedged the mesh drain"
    cons = futures.conservation()
    assert cons["ok"] and cons["pending"] == 0, cons
    assert submitted == (
        cons["resolved"] + cons["expired"] + cons["poisoned"]
    ), (submitted, cons)
    assert cons["expired"] > 0 and cons["resolved"] > 0, cons
    assert cons["reattached"] > 0, "no future rode a cut"
    per = {t: {"RESULT": 0, "EXPIRED": 0, "POISONED": 0}
           for t in names}
    for tok, (tid, f) in client.items():
        assert f.state in per[tid], (tid, f.state)
        per[tid][f.state] += 1
    for tid, s in per.items():
        lane = table.stats()[tid]
        assert s["RESULT"] + s["EXPIRED"] + s["POISONED"] == (
            lane["accepted"]
        ), (tid, s, lane)
    return {"faults": int(cons["expired"]), "recoveries": 2,
            "submitted": submitted, "resolved": int(cons["resolved"]),
            "expired": int(cons["expired"]),
            "reattached": int(cons["reattached"]),
            "per_tenant": {t: s for t, s in per.items()}}


def _durability_bundle(seed: int, ndev: int = 4, cap: int = 16,
                       live: int = 3, parked=(), channels=("left", "right"),
                       host_residue=None, max_waits: int = 4):
    """Schema-complete synthetic resident bundle (the durability matrix
    exercises the STORE and the reshard algebra, not the kernel):
    ``live`` ready link-free rows per device, optional wait-parked rows
    (``parked``: (device, channel, need) triples), seeded ivalues so
    two bundles of different seeds are bit-distinguishable."""
    import numpy as np

    from hclib_tpu.device.descriptor import (
        DESC_WORDS, F_DEP, F_FN, F_HOME, F_SUCC0, F_SUCC1, NO_TASK,
    )
    from hclib_tpu.device.megakernel import C_ALLOC, C_PENDING, C_VALLOC
    from hclib_tpu.runtime.checkpoint import CheckpointBundle

    rng = np.random.default_rng(seed)
    tasks = np.zeros((ndev, cap, DESC_WORDS), np.int32)
    tasks[:, :, F_SUCC0] = NO_TASK
    tasks[:, :, F_SUCC1] = NO_TASK
    tasks[:, :, F_HOME] = -1
    ready = np.full((ndev, cap), NO_TASK, np.int32)
    counts = np.zeros((ndev, 8), np.int32)
    waits = np.zeros((ndev, max_waits + 1, 3), np.int32)
    for d in range(ndev):
        for i in range(live):
            tasks[d, i, F_FN] = 1
            ready[d, i] = i
        npk = 0
        for (pd, ch, need) in parked:
            if pd != d:
                continue
            slot = live + npk
            tasks[d, slot, F_FN] = 2
            tasks[d, slot, F_DEP] = 1
            w = int(waits[d, 0, 0])
            waits[d, 1 + w] = (ch, need, slot)
            waits[d, 0, 0] = w + 1
            npk += 1
        counts[d, 1] = live  # ready-ring tail
        counts[d, C_ALLOC] = live + npk
        counts[d, C_PENDING] = live + npk
        counts[d, C_VALLOC] = 2
    meta = {
        "kernel_names": ["seed", "waiter"], "capacity": cap,
        "num_values": 4, "succ_capacity": 4, "data_specs": [],
        "ndev": ndev, "channels": list(channels),
    }
    if host_residue:
        meta["host_residue"] = dict(host_residue)
    return CheckpointBundle("resident", meta, {
        "tasks": tasks,
        "succ": np.full((ndev, 4), NO_TASK, np.int32),
        "ready": ready, "counts": counts,
        "ivalues": rng.integers(0, 1 << 20, (ndev, 4)).astype(np.int32),
        "waits": waits,
    })


def scenario_durability_crashpoints(seed: int, scale: str) -> dict:
    """DURABILITY: the seeded crash-point matrix over the BundleStore -
    clean generational publishes reload bit-identically; a torn npz, a
    flipped bit, and a lost manifest (FaultPlan disk sites) each
    quarantine that generation with the right typed reason and fall
    back bit-identically to the newest valid one; preempt-mid-save
    leaves the store at its previous state (a staged save is never
    visible); preempt-mid-restore retries idempotently; and an
    unrecoverable store raises the poison diagnostic (naming every
    fault) instead of hanging. Metrics counters and TR_CKPT trace
    records are asserted alongside."""
    import shutil
    import tempfile

    from hclib_tpu.device import tracebuf as tb
    from hclib_tpu.runtime.checkpoint import BundleStore, CheckpointError
    from hclib_tpu.runtime.metrics import MetricsRegistry
    from hclib_tpu.runtime.resilience import FaultPlan, InjectedFault

    rounds = 3 if scale == "smoke" else 8
    faults = recoveries = 0
    root = tempfile.mkdtemp(prefix="hclib-durability-")
    try:
        metrics = MetricsRegistry()
        # Clean generational publishes, retention, bit-identical reload.
        store = BundleStore(root, keep=3, fsync=False, metrics=metrics)
        bundles = []
        for i in range(rounds):
            b = _durability_bundle(1000 * seed + i)
            store.save(b)
            bundles.append(b)
        gens = store.generations()
        assert len(gens) == min(rounds, 3) and gens[-1] == rounds, gens
        got = BundleStore(root, keep=3, fsync=False).load_latest()
        assert got.diff(bundles[-1])["equal"], "clean reload diverged"

        # Every disk damage class at a seeded crash point: the damaged
        # generation publishes, the next restore quarantines it (typed)
        # and falls back bit-identically to the previous generation.
        for kind, plan_kw, reason in (
            ("torn", {"disk_torn_at": (0,)}, "corrupt"),
            ("flip", {"disk_flip_at": (0,)}, "corrupt"),
            ("manifest", {"disk_manifest_at": (0,)}, "torn"),
        ):
            plan = FaultPlan(seed=seed, **plan_kw)
            writer = BundleStore(root, keep=4, fsync=False,
                                 metrics=metrics, fault_plan=plan)
            gen = writer.save(_durability_bundle(9000 * seed + len(kind)))
            faults += 1
            healer = BundleStore(root, keep=4, fsync=False,
                                 metrics=metrics)
            back = healer.load_latest()
            assert back.diff(bundles[-1])["equal"], (
                kind, "fallback not bit-identical")
            assert [f.generation for f in healer.faults] == [gen], (
                kind, healer.faults)
            assert healer.faults[0].reason == reason, (
                kind, healer.faults[0])
            assert all(
                r[0] == tb.TR_CKPT and (-int(r[2]) - 1) in tb.CK_NAMES
                for r in healer.events
            ), healer.events
            recoveries += 1

        # Preempt mid-save: the InjectedFault lands BEFORE the rename,
        # so the staged generation is invisible and the store unmoved.
        before = BundleStore(root, fsync=False).generations()
        plan = FaultPlan(seed=seed, preempt_save_at=0)
        writer = BundleStore(root, keep=4, fsync=False, fault_plan=plan)
        try:
            writer.save(_durability_bundle(31 * seed + 7))
            raise AssertionError("preempt-mid-save never fired")
        except InjectedFault:
            faults += 1
        after = BundleStore(root, keep=4, fsync=False)
        assert after.generations() == before, "a torn save became visible"
        assert after.load_latest().diff(bundles[-1])["equal"]
        recoveries += 1

        # Preempt mid-restore: the retry is idempotent (same survivor).
        plan = FaultPlan(seed=seed, preempt_restore_at=0)
        reader = BundleStore(root, keep=4, fsync=False, fault_plan=plan)
        try:
            reader.load_latest()
            raise AssertionError("preempt-mid-restore never fired")
        except InjectedFault:
            faults += 1
        assert reader.load_latest().diff(bundles[-1])["equal"]
        recoveries += 1

        # Unrecoverable: every generation damaged -> the poison
        # diagnostic names each fault; the caller's degradation ladder
        # gets a signal instead of a hang.
        dead = BundleStore(root, keep=4, fsync=False, metrics=metrics)
        for g in dead.generations():
            npz = os.path.join(dead.path_of(g), "state.npz")
            with open(npz, "r+b") as f:
                f.truncate(max(1, os.path.getsize(npz) // 2))
            faults += 1
        try:
            dead.load_latest()
            raise AssertionError("unrecoverable store did not raise")
        except CheckpointError as e:
            assert "unrecoverable" in str(e) and "poison" in str(e), e
        recoveries += 1

        m = metrics.snapshot()["metrics"]
        assert m.get("checkpoint.save.count", 0) >= rounds + 3, m
        assert m.get("checkpoint.quarantined.count", 0) >= 3, m
        assert m.get("checkpoint.fallback.count", 0) >= 3, m
        assert m.get("checkpoint.poison.count", 0) >= 1, m
        return {"faults": faults, "recoveries": recoveries,
                "generations": rounds,
                "quarantined": int(m["checkpoint.quarantined.count"])}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def scenario_durability_serve_fallback(seed: int, scale: str) -> dict:
    """DURABILITY: fallback restore under the serving loop - the
    deadline-storm mesh (4 devices, 3 tenants, futures in flight) cuts
    at 4 -> 2, the exported state is published TWICE to a BundleStore,
    the newest generation is then bit-flipped on disk, and the resume
    path restores through ``load_latest`` - which quarantines the
    damaged generation and falls back to the older, bit-identical one.
    Futures reattach onto the restored table, the 2 -> 4 resize rides
    the live path, and the serving ledger closes EXACTLY:
    submitted == resolved + expired + poisoned. Alongside, the reshard
    wait re-homing algebra: a bundle with pending host-declared waits
    reshards 4 -> 2 -> 4 with wait counts and per-channel need sums
    conserved, and a satisfier-in-residue bundle is refused with the
    whole-program diagnostic."""
    import shutil
    import tempfile

    import numpy as np

    from hclib_tpu.device.descriptor import RING_ROW, TEN_TOKEN
    from hclib_tpu.device.egress import EgressSpec, HostMailbox
    from hclib_tpu.device.tenants import (
        MeshTenantTable, TenantSpec, wrr_poll_reference,
    )
    from hclib_tpu.runtime.checkpoint import (
        BundleStore, CheckpointBundle, CheckpointError,
    )

    rng = np.random.default_rng(8600 + seed)
    region = 16
    clk = [100.0]
    spec = EgressSpec(depth=4)
    table = MeshTenantTable(
        [TenantSpec("gold", weight=2), TenantSpec("std"),
         TenantSpec("batch", queue_capacity=512)],
        4, region, clock=lambda: clk[0], egress=spec,
    )
    futures = table.futures
    assert futures is not None
    per_batch = 10 if scale == "smoke" else 30
    client = {}

    def drive(table, rings, polls=2, start=0):
        boxes = [HostMailbox(spec, park_cap=8 * region)
                 for _ in range(table.ndev)]
        tctl = table.pump(rings)
        for r in range(start, start + polls):
            for d in range(table.ndev):
                rows = wrr_poll_reference(
                    rings[d], tctl[d], table.region_rows, r, 1 << 20
                )
                boxes[d].publish([
                    (int(row[TEN_TOKEN]), 0, 0, 0, 7) for row in rows
                ])
        table.absorb(tctl)
        for box in boxes:
            box.drain(futures=futures)
        clk[0] += 0.05

    def rings_for(ndev):
        return np.zeros((ndev, 3 * region, RING_ROW), np.int32)

    submitted = 0
    sizes = [4, 2, 4]
    rings = rings_for(4)
    names = ("gold", "std", "batch")
    root = tempfile.mkdtemp(prefix="hclib-serve-fallback-")
    try:
        for phase, ndev in enumerate(sizes):
            for i in range(per_batch):
                tid = names[int(rng.integers(0, 3))]
                doomed = rng.random() < 0.3
                adm = table.submit(
                    tid, 0, args=[i],
                    deadline_s=(0.01 if doomed else 600.0),
                )
                if adm:
                    submitted += 1
                    client[adm.future.token] = (tid, adm.future)
                clk[0] += float(rng.random() * 0.02)
            drive(table, rings, polls=2, start=4 * phase)
            if phase == len(sizes) - 1:
                break
            # A pre-cut burst with generous deadlines: futures that are
            # GUARANTEED live at the export, so every cut exercises the
            # preempt -> reattach path regardless of the seed's storm.
            for j, tid in enumerate(names):
                adm = table.submit(tid, 0, args=[1000 + j],
                                   deadline_s=600.0)
                if adm:
                    submitted += 1
                    client[adm.future.token] = (tid, adm.future)
            state = table.export_state(rings)
            tokens = [(tok, tid, f.resume_token)
                      for tok, (tid, f) in client.items()
                      if f.state == "PREEMPTED"]
            if phase == 0:
                # The durable cut: publish the exported state TWICE,
                # damage the newest generation on disk, and restore
                # through the self-healing walk - the fallback must be
                # bit-identical to what was exported.
                bundle = CheckpointBundle(
                    "resident", {"schema": "mesh-serve-export"}, state,
                )
                store = BundleStore(root, keep=3, fsync=False)
                store.save(bundle)
                gen2 = store.save(bundle)
                npz = os.path.join(store.path_of(gen2), "state.npz")
                with open(npz, "r+b") as f:
                    f.seek(12)
                    byte = f.read(1)
                    f.seek(12)
                    f.write(bytes([byte[0] ^ 0x40]))
                healer = BundleStore(root, keep=3, fsync=False)
                back = healer.load_latest()
                assert [f.generation for f in healer.faults] == [gen2], (
                    healer.faults)
                assert back.diff(bundle)["equal"], (
                    "fallback generation not bit-identical")
                state = {k: back.arrays[k] for k in state}
            nxt = table.resized(sizes[phase + 1])
            assert nxt.futures is futures, "ledger forked across the cut"
            nxt.resume_from(state)
            for tok, tid, rt in tokens:
                client[tok] = (tid, nxt.reattach(rt))
            table, rings = nxt, rings_for(nxt.ndev)
        for r in range(40, 40 + 64):
            drive(table, rings, polls=1, start=r)
            if table.drained():
                break
        assert table.drained(), "fallback restore wedged the mesh drain"
        cons = futures.conservation()
        assert cons["ok"] and cons["pending"] == 0, cons
        assert submitted == (
            cons["resolved"] + cons["expired"] + cons["poisoned"]
        ), (submitted, cons)
        assert cons["reattached"] > 0, "no future rode the fallback cut"

        # Reshard wait re-homing algebra (the checkpoint tentpole):
        # counts and per-channel need sums conserved 4 -> 2 -> 4; a
        # satisfier-in-residue bundle refused whole-program.
        wb = _durability_bundle(
            77 * seed + 5,
            parked=[(0, 0, 3), (1, 1, 2), (2, 0, 1), (3, 1, 4)],
        )
        w0 = int(np.asarray(wb.arrays["waits"])[:, 0, 0].sum())
        down = wb.reshard(2)
        up = down.reshard(4)
        for b2 in (down, up):
            arr = np.asarray(b2.arrays["waits"])
            assert int(arr[:, 0, 0].sum()) == w0, (w0, arr[:, 0, 0])
        from hclib_tpu.device.megakernel import C_PENDING

        assert int(up.arrays["counts"][:, C_PENDING].sum()) == int(
            wb.arrays["counts"][:, C_PENDING].sum()
        )
        rb = _durability_bundle(
            78 * seed, parked=[(0, 0, 3)],
            host_residue={"left": 2},
        )
        try:
            rb.reshard(2)
            raise AssertionError("residue refusal never fired")
        except CheckpointError as e:
            assert "host residue" in str(e) and "left" in str(e), e
        return {"faults": int(cons["expired"]) + 1, "recoveries": 3,
                "submitted": submitted,
                "resolved": int(cons["resolved"]),
                "reattached": int(cons["reattached"]),
                "rehomed_waits": w0}
    finally:
        shutil.rmtree(root, ignore_errors=True)


# ------------------------------- SLO burn-rate autoscaling (ISSUE 19)

def scenario_slo_burn_scaleout(seed: int, scale: str) -> dict:
    """SLO: the seeded burn-rate storm (ISSUE 19) - a healthy request
    stream degrades its tail mid-run; the streaming estimator (fed
    cumulative on-device latency histograms, the TelemetryPoller
    shape) reports latency_pressure over the policy threshold and the
    policy fires a typed ``slo_out`` scale-out BEFORE the
    deadline-budget watchdog rung (no deadline has expired - with the
    burn signal zeroed the same observation HOLDS), during cooldown.
    The typed event rides TR_SCALE, the metrics registry, and the
    Perfetto exporter. A no-objective estimator replaying the same
    degraded stream stays at zero pressure (the off path)."""
    import numpy as np

    import hclib_tpu as hc
    from hclib_tpu.device.telemetry import LAT_BUCKETS, bucket_of
    from hclib_tpu.runtime.slo import SloEstimator

    rng = np.random.default_rng(9100 + seed)
    objective = 64  # rounds: whole buckets at/above this edge are bad
    windows = (5.0, 30.0)
    est = SloEstimator(objective_rounds=objective, quantile=0.99,
                       windows_s=windows)
    counts = np.zeros(LAT_BUCKETS, np.int64)
    snapshots = []
    per_tick = 16 if scale == "smoke" else 64
    t, bad_total = 0.0, 0

    def tick(lo, hi):
        nonlocal t
        for d in rng.integers(lo, hi, size=per_tick):
            counts[bucket_of(int(d))] += 1
        t += 1.0
        snapshots.append((t, counts.copy()))
        est.observe(counts.copy(), t)

    # Healthy phase: every request lands well under the objective.
    for _ in range(6):
        tick(4, 32)
    healthy_pressure = est.latency_pressure(t)
    assert healthy_pressure < 2.0, healthy_pressure
    # Degradation: the tail walks past the objective bucket edge.
    for _ in range(6):
        tick(128, 2048)
        bad_total += per_tick
    pressure = est.latency_pressure(t)
    assert pressure >= 2.0, (pressure, est.stats())
    p99 = est.quantiles((0.99,))[0.99]
    assert p99 >= 128, p99

    policy = hc.AutoscalerPolicy(
        min_devices=1, max_devices=8, scale_out_backlog=1e9,
        scale_in_backlog=4.0, hysteresis=2, cooldown=3,
        tenant_pressure=0.25, slo_burn=2.0,
    )
    # Prime the cooldown gate (prove the burn path bypasses it).
    policy._cooling = 3

    def observe(p):
        return hc.Observation(2, [8, 8], executed_delta=8, slice_s=1.0,
                              latency_pressure=p)

    # BEFORE the watchdog rung: nothing expired, no deadline budget
    # drained - the SAME observation with the burn signal zeroed holds.
    assert policy.decide(observe(0.0))[1] == "hold"
    target, kind, reason = policy.decide(observe(pressure))
    assert kind == "slo_out", (kind, reason)
    assert target == 4 and "burn" in reason, (target, reason)

    # The typed event rides TR_SCALE + metrics + Perfetto.
    reg = hc.MetricsRegistry()
    asc = hc.Autoscaler(lambda n: None, policy, metrics=reg)
    asc._event(hc.ScaleEvent("slo_out", 1, 2, target, reason))
    from hclib_tpu.device.tracebuf import TR_SCALE, records_of

    recs = records_of(asc.trace_info(), TR_SCALE)
    assert len(recs) == 1 and int(recs[0][2]) == (2 << 8) | target
    assert reg.snapshot()["metrics"]["autoscale.slo_out.count"] == 1
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__))))
    import timeline

    doc = timeline.export_perfetto("", traces=[asc.trace_info()])
    names = [e.get("name", "") for e in doc["traceEvents"]]
    assert any(n.startswith(f"slo out 2→{target}") for n in names), names

    # Off path: no objective -> zero pressure on the SAME stream.
    quiet = SloEstimator(objective_rounds=None, quantile=0.99,
                         windows_s=windows)
    for ts, c in snapshots:
        quiet.observe(c, ts)
    assert quiet.latency_pressure(t) == 0.0
    return {"faults": bad_total, "recoveries": 1,
            "pressure": round(float(pressure), 3),
            "healthy_pressure": round(float(healthy_pressure), 4),
            "p99_rounds": float(p99), "target": target}


# --------------------------------------- dynamic graph service (ISSUE 20)


def scenario_dyngraph_storm_reshard(seed: int, scale: str) -> dict:
    """Dyngraph: an UPDATE storm cut LIVE mid-run, twice - two replicas
    of the same registered stream quiesce at different points (divergent
    applied subsets, labels, spare cursors), the stacked 2-replica
    bundle reshards 2 -> 4 -> 1 through the canonical merge (union
    flags broadcast, edge-count conservation, labels min-folded), and
    the live replica resumes to a fixpoint bit-identical to the
    from-scratch host run ON THE MUTATED GRAPH - the fault is the
    mid-storm preemption, the recoveries are the reshard folds and the
    exact drain."""
    import numpy as np

    from hclib_tpu.device.dyngraph import (
        DynGraph, _bind_updates, _seed_builders, fk_data, host_dyngraph,
        make_dyngraph_megakernel,
    )
    from hclib_tpu.device.frontier import INF, VT_BASE
    from hclib_tpu.runtime.checkpoint import (
        CheckpointBundle, snapshot_megakernel,
    )

    rng = np.random.default_rng(29 + seed)
    n, m = (16, 48) if scale == "smoke" else (32, 128)
    n_ups = 4 if scale == "smoke" else 8
    g = DynGraph(n, rng.integers(0, n, m), rng.integers(0, n, m),
                 rng.integers(1, 8, m), spare_blocks=2,
                 upd_cap=max(8, n_ups))
    for u, v, w in zip(rng.integers(0, n, n_ups),
                       rng.integers(0, n, n_ups),
                       rng.integers(1, 8, n_ups)):
        g.add_update(int(u), int(v), int(w))
    mk = make_dyngraph_megakernel(
        "sssp", g, width=0, interpret=True, checkpoint=True,
    )
    _bind_updates(mk, g)

    def cut(quiesce):
        builders, _ = _seed_builders(
            g, "sssp", 0, 1 << 14, 64, [1], mk.num_values, 1,
            lambda i, tot: 0,
        )
        iv = g.preset_values(mk.num_values, INF)
        iv[g.st_base] = 0
        _, _, info_q = mk.run(
            builders[0], data=dict(fk_data(g, mk)), ivalues=iv,
            quiesce=quiesce,
        )
        assert info_q["quiesced"] and info_q["pending"] > 0, info_q
        return info_q

    qa, qb = cut(1), cut(3)  # divergent cuts of the same stream
    ba, bb = snapshot_megakernel(mk, qa), snapshot_megakernel(mk, qb)
    arrays = {k: np.stack([np.asarray(ba.arrays[k]),
                           np.asarray(bb.arrays[k])])
              for k in ba.arrays}
    mesh = CheckpointBundle("resident", {**ba.meta, "ndev": 2}, arrays)

    flag_base, st = g.flag_base, g.st_base
    ivs = arrays["ivalues"].astype(np.int64)
    union = ivs[:, flag_base:flag_base + g.upd_cap].max(axis=0)
    recoveries = 0
    for ndev_new in (4, 1):
        out = mesh.reshard(ndev_new)
        oiv = np.asarray(out.arrays["ivalues"]).astype(np.int64)
        assert oiv.shape[0] == ndev_new
        for d in range(ndev_new):
            # Union flags + the canonical adjacency broadcast to every
            # new device; degrees conserve static + union-applied.
            assert np.array_equal(
                oiv[d, flag_base:flag_base + g.upd_cap], union)
            vt = oiv[d, VT_BASE:VT_BASE + 3 * n].reshape(n, 3)
            assert int(vt[:, 2].sum()) == (
                int(g.deg.sum()) + int(union.sum()))
            assert np.array_equal(out.arrays["data/indices"][d],
                                  out.arrays["data/indices"][0])
        assert np.array_equal(  # labels min-fold across the replicas
            oiv[0, st:st + n], ivs[:, st:st + n].min(axis=0))
        recoveries += 1

    # The live replica drains: bit-identical to the mutated-graph twin.
    iv_r, _, _ = mk.resume(qa["state"])
    res = np.asarray(iv_r, np.int64)[st:st + n].astype(np.int32)
    assert np.array_equal(res, host_dyngraph("sssp", g, 0))
    recoveries += 1
    return {"faults": 2, "recoveries": recoveries, "updates": n_ups,
            "union_applied": int(union.sum()),
            "pending_at_cut": int(qa["pending"])}


SCENARIOS = [
    ("fib_retry", scenario_fib_retry),
    ("uts_kill_worker", scenario_uts_kill_worker),
    ("deadline", scenario_deadline),
    ("quarantine", scenario_quarantine),
    ("procworld_crash", scenario_procworld_crash),
]

MESH_SCENARIOS = [
    ("mesh_dead_chip", scenario_mesh_dead_chip),
    ("mesh_dropped_credit", scenario_mesh_dropped_credit),
]

PREEMPT_SCENARIOS = [
    ("preempt_checkpoint", scenario_preempt_checkpoint),
    ("preempt_stream", scenario_preempt_stream),
    ("preempt_mesh_reshard", scenario_preempt_mesh_reshard),
]

STORM_SCENARIOS = [
    ("storm_stream", scenario_storm_stream),
    ("storm_megakernel_chain", scenario_storm_megakernel_chain),
    ("storm_autoscale", scenario_storm_autoscale),
]

TENANT_SCENARIOS = [
    ("tenant_greedy_quota", scenario_tenant_greedy_quota),
    ("tenant_poison_quarantine", scenario_tenant_poison_quarantine),
    ("tenant_deadline_storm", scenario_tenant_deadline_storm),
    ("tenant_preempt_stream", scenario_tenant_preempt_stream),
    # Mesh-wide tenancy (ISSUE 13): the reshard storm + the
    # tenant/deadline-aware policy, both host-model (no Mosaic needed).
    ("tenant_mesh_storm_reshard", scenario_tenant_mesh_storm_reshard),
    ("tenant_mesh_autoscale_pressure",
     scenario_tenant_mesh_autoscale_pressure),
]

SERVE_SCENARIOS = [
    ("serve_slow_poller", scenario_serve_slow_poller),
    ("serve_fire_preempt", scenario_serve_fire_preempt),
    ("serve_mesh_deadline_storm", scenario_serve_mesh_deadline_storm),
]

DURABILITY_SCENARIOS = [
    ("durability_crashpoints", scenario_durability_crashpoints),
    ("durability_serve_fallback", scenario_durability_serve_fallback),
]

SLO_SCENARIOS = [
    ("slo_burn_scaleout", scenario_slo_burn_scaleout),
]

DYNGRAPH_SCENARIOS = [
    ("dyngraph_storm_reshard", scenario_dyngraph_storm_reshard),
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=1,
                    help="number of seeds (starting at --seed-base)")
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument("--scale", choices=("smoke", "soak"), default="smoke")
    ap.add_argument("--mesh", action="store_true",
                    help="add the seeded device-mesh chaos scenarios "
                         "(dead chip, dropped steal credit)")
    ap.add_argument("--mesh-only", action="store_true",
                    help="run ONLY the device-mesh chaos scenarios")
    ap.add_argument("--preempt", action="store_true",
                    help="add the seeded preemption scenarios "
                         "(checkpoint mid-run, restore, totals "
                         "conserved; incl. N->M mesh reshard)")
    ap.add_argument("--preempt-only", action="store_true",
                    help="run ONLY the preemption scenarios")
    ap.add_argument("--storm", action="store_true",
                    help="add the seeded preempt-storm scenarios "
                         "(repeated cuts on a live stream, chained "
                         "megakernel checkpoints, and the autoscaled "
                         "mesh with a dead-chip evacuation mid-stream)")
    ap.add_argument("--storm-only", action="store_true",
                    help="run ONLY the preempt-storm scenarios")
    ap.add_argument("--tenants", action="store_true",
                    help="add the seeded multi-tenant ingress scenarios "
                         "(greedy tenant vs quota with WRR fairness, "
                         "poison tenant quarantined, deadline storm "
                         "reconciliation, preempt with 3 tenants live)")
    ap.add_argument("--tenants-only", action="store_true",
                    help="run ONLY the multi-tenant ingress scenarios")
    ap.add_argument("--serve", action="store_true",
                    help="add the seeded serving-loop scenarios "
                         "(slow poller vs mailbox backpressure, "
                         "fire_preempt with futures in flight, mesh "
                         "deadline storm with live 4->2->4 reshards "
                         "and exact future conservation)")
    ap.add_argument("--serve-only", action="store_true",
                    help="run ONLY the serving-loop scenarios")
    ap.add_argument("--durability", action="store_true",
                    help="add the seeded durable-store scenarios "
                         "(crash-point matrix over the BundleStore: "
                         "torn/flipped/lost members quarantined with "
                         "bit-identical fallback, preempt mid-save/"
                         "mid-restore, serving-ledger conservation "
                         "across a fallback restore, reshard wait "
                         "re-homing algebra)")
    ap.add_argument("--durability-only", action="store_true",
                    help="run ONLY the durable-store scenarios")
    ap.add_argument("--slo", action="store_true",
                    help="add the seeded SLO burn-rate scenario (tail "
                         "degradation crossing the multi-window burn "
                         "threshold fires a typed slo_out scale-out "
                         "before the deadline watchdog rung, riding "
                         "TR_SCALE/metrics/Perfetto)")
    ap.add_argument("--slo-only", action="store_true",
                    help="run ONLY the SLO burn-rate scenario")
    ap.add_argument("--dyngraph", action="store_true",
                    help="add the seeded dynamic-graph scenario (an "
                         "update storm cut live at two divergent "
                         "points, the stacked replicas resharded "
                         "2->4->1 with canonical-merge conservation, "
                         "and the live replica drained bit-identical "
                         "to the mutated-graph host twin)")
    ap.add_argument("--dyngraph-only", action="store_true",
                    help="run ONLY the dynamic-graph scenario")
    ap.add_argument("--no-skip", action="store_true",
                    help="treat skipped scenarios as failures (CI gating "
                         "jobs must fail CLOSED: an environment that "
                         "cannot run the fault paths is not a pass)")
    ap.add_argument("--timeout-s", type=float, default=300.0,
                    help="hard whole-sweep ceiling; overrun = exit 1 "
                         "with all-thread stack dumps")
    args = ap.parse_args(argv)

    # An -only flag drops the base suite; the group flags are additive
    # on top of whatever remains, so every combination runs exactly the
    # groups it names (e.g. --mesh-only --preempt = mesh + preempt).
    scenarios = (
        []
        if (args.mesh_only or args.preempt_only or args.storm_only
            or args.tenants_only or args.serve_only
            or args.durability_only or args.slo_only
            or args.dyngraph_only)
        else list(SCENARIOS)
    )
    if args.mesh or args.mesh_only:
        scenarios += MESH_SCENARIOS
    if args.preempt or args.preempt_only:
        scenarios += PREEMPT_SCENARIOS
    if args.storm or args.storm_only:
        scenarios += STORM_SCENARIOS
    if args.tenants or args.tenants_only:
        scenarios += TENANT_SCENARIOS
    if args.serve or args.serve_only:
        scenarios += SERVE_SCENARIOS
    if args.durability or args.durability_only:
        scenarios += DURABILITY_SCENARIOS
    if args.slo or args.slo_only:
        scenarios += SLO_SCENARIOS
    if args.dyngraph or args.dyngraph_only:
        scenarios += DYNGRAPH_SCENARIOS

    # The tool's own hang enforcement: dump + hard-exit on overrun.
    faulthandler.dump_traceback_later(args.timeout_s, exit=True)
    failures = skipped = faults = recoveries = 0
    t0 = time.monotonic()
    for seed in range(args.seed_base, args.seed_base + args.seeds):
        for name, fn in scenarios:
            row = {"scenario": name, "seed": seed, "scale": args.scale}
            ts = time.monotonic()
            try:
                row.update(fn(seed, args.scale))
                row["ok"] = True
                if "skipped" in row:
                    skipped += 1
                faults += int(row.get("faults", 0))
                recoveries += int(row.get("recoveries", 0))
            except Exception as e:  # scenario failed; keep sweeping
                failures += 1
                row["ok"] = False
                row["error"] = f"{type(e).__name__}: {e}"
            row["seconds"] = round(time.monotonic() - ts, 3)
            print(json.dumps(row), flush=True)
    faulthandler.cancel_dump_traceback_later()
    # The one-line machine-readable summary CI/BENCH tooling diffs.
    print(json.dumps({
        "summary": True, "failures": failures, "skipped": skipped,
        "seed_base": args.seed_base, "seeds": args.seeds,
        "scenarios": len(scenarios) * args.seeds,
        "faults_injected": faults, "recoveries": recoveries,
        "seconds": round(time.monotonic() - t0, 3),
    }), flush=True)
    return 1 if failures or (args.no_skip and skipped) else 0


if __name__ == "__main__":
    sys.exit(main())
